"""BaseModule — the abstract training interface and the canonical fit loop.

Parity: /root/reference/python/mxnet/module/base_module.py (fit :369-508,
score :216, predict :154, forward_backward :191).  The loop's hard sync
points are metric updates and epoch-end get_params, exactly like the
reference (asnumpy ⇒ WaitToRead); everything between them is async XLA
dispatch.
"""
from __future__ import annotations

import logging
import time
from typing import List, Optional

import numpy as np

from .. import metric as _metric
from .. import ndarray as nd
from ..model import BatchEndParam
from ..base import MXNetError

__all__ = ["BaseModule"]


def _as_list(obj):
    if obj is None:
        return []
    if isinstance(obj, (list, tuple)):
        return list(obj)
    return [obj]


def _check_input_names(symbol, names, typename, throw):
    """Check that input names are arguments of the symbol (reference
    base_module._check_input_names)."""
    args = symbol.list_arguments()
    for name in names:
        if name in args:
            continue
        candidates = [arg for arg in args if not arg.endswith("_weight")
                      and not arg.endswith("_bias") and not arg.endswith("_gamma")
                      and not arg.endswith("_beta")]
        msg = "\033[91mYou created Module with Module(..., %s_names=%s) but " \
              "input with name '%s' is not found in symbol.list_arguments(). " \
              "Did you mean one of:\n\t%s\033[0m" % (
                  typename, str(names), name, "\n\t".join(candidates))
        if throw:
            raise ValueError(msg)
        logging.warning(msg)


class BaseModule:
    """The base class of every module (reference base_module.py:60)."""

    def __init__(self, logger=logging):
        self.logger = logger
        self.binded = False
        self.for_training = False
        self.inputs_need_grad = False
        self.params_initialized = False
        self.optimizer_initialized = False
        self._symbol = None
        self._total_exec_bytes = 0
        self._guardian = None  # attached by fit() when MXNET_GUARDIAN=1
        self._guardian_action = "ok"  # last update()'s verdict

    # ------------------------------------------------------------------
    # high-level interface
    # ------------------------------------------------------------------
    def forward_backward(self, data_batch):
        """A convenient function that calls both ``forward`` and
        ``backward``."""
        self.forward(data_batch, is_train=True)
        self.backward()

    def score(self, eval_data, eval_metric, num_batch=None,
              batch_end_callback=None, score_end_callback=None, reset=True,
              epoch=0):
        """Run prediction on ``eval_data`` and evaluate (reference
        base_module.py:216)."""
        assert self.binded and self.params_initialized
        if reset:
            eval_data.reset()
        if not isinstance(eval_metric, _metric.EvalMetric):
            eval_metric = _metric.create(eval_metric)
        eval_metric.reset()
        actual_num_batch = 0
        for nbatch, eval_batch in enumerate(eval_data):
            if num_batch is not None and nbatch == num_batch:
                break
            self.forward(eval_batch, is_train=False)
            self.update_metric(eval_metric, eval_batch.label)
            if batch_end_callback is not None:
                batch_end_params = BatchEndParam(epoch=epoch, nbatch=nbatch,
                                                 eval_metric=eval_metric,
                                                 locals=locals())
                for callback in _as_list(batch_end_callback):
                    callback(batch_end_params)
            actual_num_batch += 1
        if score_end_callback:
            params = BatchEndParam(epoch=epoch, nbatch=actual_num_batch,
                                   eval_metric=eval_metric, locals=locals())
            for callback in _as_list(score_end_callback):
                callback(params)
        return eval_metric.get_name_value()

    def iter_predict(self, eval_data, num_batch=None, reset=True):
        """Iterate over predictions (reference base_module.py:185)."""
        assert self.binded and self.params_initialized
        if reset:
            eval_data.reset()
        for nbatch, eval_batch in enumerate(eval_data):
            if num_batch is not None and nbatch == num_batch:
                break
            self.forward(eval_batch, is_train=False)
            pad = eval_batch.pad
            outputs = [out[0:out.shape[0] - pad] for out in self.get_outputs()]
            yield (outputs, nbatch, eval_batch)

    def predict(self, eval_data, num_batch=None, merge_batches=True,
                reset=True, always_output_list=False):
        """Run prediction, collecting outputs (reference base_module.py:154)."""
        assert self.binded and self.params_initialized
        if reset:
            eval_data.reset()
        output_list = []
        for nbatch, eval_batch in enumerate(eval_data):
            if num_batch is not None and nbatch == num_batch:
                break
            self.forward(eval_batch, is_train=False)
            pad = eval_batch.pad
            outputs = [out[0:out.shape[0] - pad].copy()
                       for out in self.get_outputs()]
            output_list.append(outputs)
        if len(output_list) == 0:
            return output_list
        if merge_batches:
            num_outputs = len(output_list[0])
            for out in output_list:
                assert len(out) == num_outputs, \
                    "Cannot merge batches, as num of outputs is not the same " \
                    "in mini-batches. Maybe bucketing is used?"
            output_list2 = [
                nd.concatenate([out[i] for out in output_list])
                for i in range(num_outputs)]
            if num_outputs == 1 and not always_output_list:
                return output_list2[0]
            return output_list2
        return output_list

    def fit(self, train_data, eval_data=None, eval_metric="acc",
            epoch_end_callback=None, batch_end_callback=None, kvstore="local",
            optimizer="sgd", optimizer_params=(("learning_rate", 0.01),),
            eval_end_callback=None, eval_batch_end_callback=None,
            initializer=None, arg_params=None, aux_params=None,
            allow_missing=False, force_rebind=False, force_init=False,
            begin_epoch=0, num_epoch=None, validation_metric=None,
            monitor=None):
        """Train the module (reference base_module.py:369-508)."""
        from ..initializer import Uniform

        assert num_epoch is not None, "please specify number of epochs"
        if initializer is None:
            initializer = Uniform(0.01)

        self.bind(data_shapes=train_data.provide_data,
                  label_shapes=train_data.provide_label, for_training=True,
                  force_rebind=force_rebind)
        if monitor is not None:
            self.install_monitor(monitor)
        self.init_params(initializer=initializer, arg_params=arg_params,
                         aux_params=aux_params, allow_missing=allow_missing,
                         force_init=force_init)
        self.init_optimizer(kvstore=kvstore, optimizer=optimizer,
                            optimizer_params=optimizer_params)

        if validation_metric is None:
            validation_metric = eval_metric
        if not isinstance(eval_metric, _metric.EvalMetric):
            eval_metric = _metric.create(eval_metric)

        from .. import guardian as _guardian_mod
        from .. import profiler as _prof

        guardian = _guardian_mod.Guardian() if _guardian_mod.enabled() \
            else None
        self._guardian = guardian

        # ------------------------------------------------------ training loop
        try:
            for epoch in range(begin_epoch, num_epoch):
                tic = time.time()
                eval_metric.reset()
                from .. import telemetry as _telemetry

                data_iter = iter(train_data)
                nbatch = 0
                with _prof.Frame("Module.fit:epoch%d" % epoch, "fit"):
                    while True:
                        # the iterator cursor must be captured BEFORE the
                        # fetch (so a rollback replays the batch about to
                        # run) but the snapshot is only committed after the
                        # fetch succeeds — a cursor parked on StopIteration
                        # would make the replayed epoch end early.  Forced
                        # at each epoch start: replaying across an epoch
                        # boundary would re-apply the prior epoch's tail.
                        snap_force = guardian is not None and nbatch == 0
                        snap_due = guardian is not None and \
                            (snap_force or guardian.snapshot_due())
                        snap_iter = None
                        if snap_due:
                            try:
                                snap_iter = train_data.state_dict()
                            except (NotImplementedError, ValueError,
                                    AttributeError):
                                pass  # replay falls back to live position
                        # data-wait: time blocked on the iterator (the
                        # prefetch pipeline's starvation signal) — measured
                        # only when telemetry is on so the off path stays
                        # the plain next() call
                        if _telemetry.enabled():
                            t_fetch = time.monotonic()
                            try:
                                data_batch = next(data_iter)
                            except StopIteration:
                                break
                            mon = getattr(self, "_telemetry_monitor", None)
                            if mon is not None:
                                mon().note_data_wait(
                                    time.monotonic() - t_fetch)
                        else:
                            try:
                                data_batch = next(data_iter)
                            except StopIteration:
                                break
                        if snap_due:
                            self._guardian_snapshot(guardian, snap_iter,
                                                    epoch, nbatch,
                                                    force=snap_force)
                        if monitor is not None:
                            monitor.tic()
                        with _prof.Frame("Module.fit:step", "fit"):
                            self.forward_backward(data_batch)
                            self.update()
                        if guardian is not None and \
                                self._guardian_action == "rollback":
                            # restore the last-good snapshot and replay —
                            # with params/updater/PRNG/iterator all rolled
                            # back, the replayed steps are bit-identical to
                            # what an uncorrupted run would have produced
                            nbatch = self._guardian_rollback(guardian,
                                                             train_data,
                                                             epoch)
                            continue
                        # on an async kvstore update() leaves comms in
                        # flight; metric update + the iterator's next-batch
                        # prefetch run inside that window, and the next
                        # forward() drains it
                        self.update_metric(eval_metric, data_batch.label)
                        if monitor is not None:
                            monitor.toc_print()
                        if batch_end_callback is not None:
                            batch_end_params = BatchEndParam(
                                epoch=epoch, nbatch=nbatch,
                                eval_metric=eval_metric, locals=locals())
                            for callback in _as_list(batch_end_callback):
                                callback(batch_end_params)
                        nbatch += 1

                # one epoch of training is finished
                for name, val in eval_metric.get_name_value():
                    self.logger.info("Epoch[%d] Train-%s=%f", epoch, name, val)
                toc = time.time()
                self.logger.info("Epoch[%d] Time cost=%.3f", epoch, (toc - tic))

                # sync aux params across devices
                arg_params_, aux_params_ = self.get_params()
                self.set_params(arg_params_, aux_params_)

                if epoch_end_callback is not None:
                    for callback in _as_list(epoch_end_callback):
                        callback(epoch, self.symbol, arg_params_, aux_params_)

                # ------------------------------------------------- evaluation
                if eval_data:
                    res = self.score(eval_data, validation_metric,
                                     score_end_callback=eval_end_callback,
                                     batch_end_callback=eval_batch_end_callback,
                                     epoch=epoch)
                    for name, val in res:
                        self.logger.info("Epoch[%d] Validation-%s=%f",
                                         epoch, name, val)

                train_data.reset()
        finally:
            # an abandoned epoch (exception, early stop) must not leave a
            # prefetching iterator's worker threads parked on live queues
            close = getattr(train_data, "close", None)
            if callable(close):
                close()

    # ------------------------------------------------------------------
    # guardian: last-good retention ring + rollback-and-replay
    # ------------------------------------------------------------------
    def _guardian_snapshot(self, guardian, iter_state, epoch, nbatch,
                           force=False):
        """Offer a last-good ring snapshot before this batch runs
        (``iter_state`` was captured before the fetch, so it replays
        this very batch).  The capture closure only executes on the
        batches the guardian elects (every MXNET_GUARDIAN_SNAPSHOT_EVERY
        applied steps plus each epoch start, never while anomalies are
        live) — it copies every parameter."""

        def capture():
            from .. import random as _random

            arg_params, aux_params = self.get_params()
            snap = {"arg": {k: v.copy() for k, v in arg_params.items()},
                    "aux": {k: v.copy() for k, v in aux_params.items()},
                    "rng": _random.get_state(),
                    "epoch": epoch, "nbatch": nbatch,
                    "updater": None, "iter": iter_state}
            upd = getattr(self, "_updater", None)
            if upd is not None:
                snap["updater"] = upd.get_states()
            return snap

        guardian.offer_snapshot(capture, force=force)

    def _guardian_rollback(self, guardian, train_data, epoch):
        """Restore the newest ring snapshot from the current epoch —
        params, updater state, the framework PRNG stream, and the
        data-iterator position — so the fit loop replays from last-good.
        Returns the restored nbatch.  Raises GuardianAbort when the
        rollback budget is spent or no in-epoch snapshot was retained
        (fit forces one at each epoch start, so only a ring-size of
        zero or an unseeded resume can hit that)."""
        from .. import guardian as _guardian_mod
        from .. import random as _random

        target = guardian.rollback_target(
            lambda snap: snap.get("epoch") == epoch)
        guardian.note_rollback(
            to_step=target[0] if target is not None else None)
        if target is None:
            raise _guardian_mod.GuardianAbort(
                "guardian must roll back but the last-good ring holds no "
                "snapshot from the current epoch")
        snap = target[1]
        self.set_params(snap["arg"], snap["aux"])
        upd = getattr(self, "_updater", None)
        if upd is not None and snap["updater"] is not None:
            upd.set_states(snap["updater"])
        _random.set_state(snap["rng"])
        if snap["iter"] is not None:
            train_data.set_state(snap["iter"])
        self._guardian_action = "ok"
        self.logger.info(
            "guardian: rolled back to last-good snapshot "
            "(epoch %d, batch %d)", snap["epoch"], snap["nbatch"])
        return snap["nbatch"]

    # ------------------------------------------------------------------
    # symbol / params
    # ------------------------------------------------------------------
    @property
    def symbol(self):
        return self._symbol

    def get_params(self):
        raise NotImplementedError()

    def init_params(self, initializer=None, arg_params=None, aux_params=None,
                    allow_missing=False, force_init=False):
        raise NotImplementedError()

    def set_params(self, arg_params, aux_params, allow_missing=False,
                   force_init=True):
        self.init_params(initializer=None, arg_params=arg_params,
                         aux_params=aux_params, allow_missing=allow_missing,
                         force_init=force_init)

    def save_params(self, fname):
        arg_params, aux_params = self.get_params()
        save_dict = {("arg:%s" % k): v for k, v in arg_params.items()}
        save_dict.update({("aux:%s" % k): v for k, v in aux_params.items()})
        nd.save(fname, save_dict)

    def load_params(self, fname):
        save_dict = nd.load(fname)
        arg_params = {}
        aux_params = {}
        for k, value in save_dict.items():
            arg_type, _, name = k.partition(":")
            if arg_type == "arg":
                arg_params[name] = value
            elif arg_type == "aux":
                aux_params[name] = value
            else:
                raise ValueError("Invalid param file " + fname)
        self.set_params(arg_params, aux_params)

    # ------------------------------------------------------------------
    # computation interface (implemented by subclasses)
    # ------------------------------------------------------------------
    def forward(self, data_batch, is_train=None):
        raise NotImplementedError()

    def backward(self, out_grads=None):
        raise NotImplementedError()

    def update(self):
        raise NotImplementedError()

    def update_metric(self, eval_metric, labels):
        raise NotImplementedError()

    def get_outputs(self, merge_multi_context=True):
        raise NotImplementedError()

    def get_input_grads(self, merge_multi_context=True):
        raise NotImplementedError()

    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        raise NotImplementedError()

    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        raise NotImplementedError()

    def install_monitor(self, mon):
        raise NotImplementedError()

    # ------------------------------------------------------------------
    # properties
    # ------------------------------------------------------------------
    @property
    def data_names(self):
        raise NotImplementedError()

    @property
    def output_names(self):
        raise NotImplementedError()

    @property
    def data_shapes(self):
        raise NotImplementedError()

    @property
    def label_shapes(self):
        raise NotImplementedError()

    @property
    def output_shapes(self):
        raise NotImplementedError()

"""mx.image — pure-Python image pipeline (reference:
python/mxnet/image.py, 491 LoC, backed there by the OpenCV imperative ops
``_cvimdecode``/``_cvimresize`` from src/io/image_io.cc:269-291).

TPU-native layout decision: decode/augment run on host CPU over numpy HWC
uint8/float32 (PIL backend, image_backend.py); the device only ever sees the
batched, normalized NCHW tensor — keeping host→HBM transfers to one
contiguous buffer per batch.
"""
from __future__ import annotations

import logging
import os
import random as pyrandom
from typing import List, Optional, Sequence

import numpy as np

from . import image_backend, io as mxio, native, ndarray as nd, recordio

__all__ = [
    "imdecode", "imresize", "scale_down", "resize_short", "fixed_crop",
    "random_crop", "center_crop", "random_size_crop", "color_normalize",
    "ResizeAug", "ForceResizeAug", "RandomCropAug", "RandomSizedCropAug",
    "CenterCropAug", "RandomOrderAug", "BrightnessJitterAug",
    "ContrastJitterAug", "SaturationJitterAug", "LightingAug",
    "ColorNormalizeAug", "HorizontalFlipAug", "CastAug", "CreateAugmenter",
    "ImageIter", "ImageRecordIter",
]


def imdecode(buf, flag=1, to_rgb=True, **kwargs):
    """Decode an image buffer to an HWC uint8 NDArray (reference
    image.py imdecode → _cvimdecode)."""
    arr = image_backend.decode_image(buf, channels=3 if flag else 1)
    if not to_rgb:
        arr = arr[:, :, ::-1]
    return nd.array(arr, dtype=np.uint8)


def imresize(src, w, h, interp=1):
    arr = src.asnumpy() if isinstance(src, nd.NDArray) else np.asarray(src)
    out = image_backend.resize_image(arr, w, h, interp)
    return nd.array(out, dtype=out.dtype)


def _as_np(img):
    return img.asnumpy() if isinstance(img, nd.NDArray) else np.asarray(img)


def _like(src, arr):
    """Type-preserving wrap: NDArray in -> NDArray out (reference API
    parity); numpy in -> numpy out (the iterators' fast path — no per-image
    device round trip through the eager array layer)."""
    if isinstance(src, nd.NDArray):
        return nd.array(arr, dtype=arr.dtype)
    return arr


def scale_down(src_size, size):
    """Scale (w, h) down to fit in src_size, preserving aspect."""
    w, h = size
    sw, sh = src_size
    if sh < h:
        w, h = float(w * sh) / h, sh
    if sw < w:
        w, h = sw, float(h * sw) / w
    return int(w), int(h)

def resize_short(src, size, interp=1):
    """Resize so the shorter edge equals ``size``."""
    arr = _as_np(src)
    h, w = arr.shape[:2]
    if h > w:
        new_w, new_h = size, int(h * size / w)
    else:
        new_w, new_h = int(w * size / h), size
    out = image_backend.resize_image(arr, new_w, new_h, interp)
    return _like(src, out)


def fixed_crop(src, x0, y0, w, h, size=None, interp=1):
    arr = _as_np(src)[y0:y0 + h, x0:x0 + w]
    if size is not None and (w, h) != size:
        arr = image_backend.resize_image(arr, size[0], size[1], interp)
    return _like(src, arr)


def random_crop(src, size, interp=1):
    arr = _as_np(src)
    h, w = arr.shape[:2]
    new_w, new_h = scale_down((w, h), size)
    x0 = pyrandom.randint(0, w - new_w)
    y0 = pyrandom.randint(0, h - new_h)
    out = fixed_crop(src, x0, y0, new_w, new_h, size, interp)
    return out, (x0, y0, new_w, new_h)


def center_crop(src, size, interp=1):
    arr = _as_np(src)
    h, w = arr.shape[:2]
    new_w, new_h = scale_down((w, h), size)
    x0 = (w - new_w) // 2
    y0 = (h - new_h) // 2
    out = fixed_crop(src, x0, y0, new_w, new_h, size, interp)
    return out, (x0, y0, new_w, new_h)


def random_size_crop(src, size, min_area=0.08, ratio=(3.0 / 4.0, 4.0 / 3.0),
                     interp=1):
    """Random crop with area and aspect-ratio jitter (Inception-style)."""
    arr = _as_np(src)
    h, w = arr.shape[:2]
    area = h * w
    for _ in range(10):
        target_area = pyrandom.uniform(min_area, 1.0) * area
        log_ratio = (np.log(ratio[0]), np.log(ratio[1]))
        new_ratio = np.exp(pyrandom.uniform(*log_ratio))
        new_w = int(round(np.sqrt(target_area * new_ratio)))
        new_h = int(round(np.sqrt(target_area / new_ratio)))
        if new_w <= w and new_h <= h:
            x0 = pyrandom.randint(0, w - new_w)
            y0 = pyrandom.randint(0, h - new_h)
            return fixed_crop(src, x0, y0, new_w, new_h, size, interp), \
                (x0, y0, new_w, new_h)
    return center_crop(src, size, interp)


def color_normalize(src, mean, std=None):
    arr = _as_np(src).astype(np.float32)
    arr = arr - np.asarray(mean, np.float32)
    if std is not None:
        arr = arr / np.asarray(std, np.float32)
    return _like(src, arr.astype(np.float32))


class _NpSafeAugList(list):
    """Marker: every augmenter in this list is type-preserving (numpy in ->
    numpy out), so iterators may run the chain GIL-cheaply on raw numpy.
    User-supplied aug_list values keep the reference NDArray contract."""


# -- augmenter callables (reference image.py returns lists of closures) -----

def ResizeAug(size, interp=1):
    def aug(src):
        return [resize_short(src, size, interp)]
    return aug


def ForceResizeAug(size, interp=1):
    def aug(src):
        arr = _as_np(src)
        return [_like(src, image_backend.resize_image(
            arr.astype(np.uint8), size[0], size[1], interp))]
    return aug


def RandomCropAug(size, interp=1):
    def aug(src):
        return [random_crop(src, size, interp)[0]]
    return aug


def RandomSizedCropAug(size, min_area=0.08, ratio=(3 / 4, 4 / 3), interp=1):
    def aug(src):
        return [random_size_crop(src, size, min_area, ratio, interp)[0]]
    return aug


def CenterCropAug(size, interp=1):
    def aug(src):
        return [center_crop(src, size, interp)[0]]
    return aug


def RandomOrderAug(ts):
    def aug(src):
        srcs = [src]
        ts_shuffled = list(ts)
        pyrandom.shuffle(ts_shuffled)
        for t in ts_shuffled:
            srcs = [j for i in srcs for j in t(i)]
        return srcs
    return aug


def BrightnessJitterAug(brightness):
    def aug(src):
        alpha = 1.0 + pyrandom.uniform(-brightness, brightness)
        return [_like(src, _as_np(src).astype(np.float32) * alpha)]
    return aug


def ContrastJitterAug(contrast):
    coef = np.array([[[0.299, 0.587, 0.114]]], np.float32)

    def aug(src):
        alpha = 1.0 + pyrandom.uniform(-contrast, contrast)
        arr = _as_np(src).astype(np.float32)
        gray = (arr * coef).sum() * (3.0 / arr.size) * (1.0 - alpha)
        return [_like(src, arr * alpha + gray)]
    return aug


def SaturationJitterAug(saturation):
    coef = np.array([[[0.299, 0.587, 0.114]]], np.float32)

    def aug(src):
        alpha = 1.0 + pyrandom.uniform(-saturation, saturation)
        arr = _as_np(src).astype(np.float32)
        gray = (arr * coef).sum(axis=2, keepdims=True) * (1.0 - alpha)
        return [_like(src, arr * alpha + gray)]
    return aug


def LightingAug(alphastd, eigval, eigvec):
    """AlexNet-style PCA color noise."""
    def aug(src):
        alpha = np.random.normal(0, alphastd, size=(3,))
        rgb = np.dot(np.asarray(eigvec) * alpha, np.asarray(eigval))
        return [_like(src, _as_np(src).astype(np.float32) + rgb)]
    return aug


def ColorNormalizeAug(mean, std):
    def aug(src):
        return [color_normalize(src, mean, std)]
    return aug


def HorizontalFlipAug(p):
    def aug(src):
        if pyrandom.random() < p:
            return [_like(src, _as_np(src)[:, ::-1].copy())]
        return [src]
    return aug


def CastAug():
    def aug(src):
        return [_like(src, _as_np(src).astype(np.float32))]
    return aug


def CreateAugmenter(data_shape, resize=0, rand_crop=False, rand_resize=False,
                    rand_mirror=False, mean=None, std=None, brightness=0,
                    contrast=0, saturation=0, pca_noise=0, inter_method=2):
    """Standard augmenter chain (reference image.py CreateAugmenter)."""
    auglist = []
    if resize > 0:
        auglist.append(ResizeAug(resize, inter_method))
    crop_size = (data_shape[2], data_shape[1])
    if rand_resize:
        assert rand_crop
        auglist.append(RandomSizedCropAug(crop_size, 0.3, (3.0 / 4.0, 4.0 / 3.0),
                                          inter_method))
    elif rand_crop:
        auglist.append(RandomCropAug(crop_size, inter_method))
    else:
        auglist.append(CenterCropAug(crop_size, inter_method))
    if rand_mirror:
        auglist.append(HorizontalFlipAug(0.5))
    auglist.append(CastAug())
    if brightness or contrast or saturation:
        ts = []
        if brightness:
            ts.append(BrightnessJitterAug(brightness))
        if contrast:
            ts.append(ContrastJitterAug(contrast))
        if saturation:
            ts.append(SaturationJitterAug(saturation))
        auglist.append(RandomOrderAug(ts))
    if pca_noise > 0:
        eigval = np.array([55.46, 4.794, 1.148])
        eigvec = np.array([[-0.5675, 0.7192, 0.4009],
                           [-0.5808, -0.0045, -0.8140],
                           [-0.5836, -0.6948, 0.4203]])
        auglist.append(LightingAug(pca_noise, eigval, eigvec))
    if mean is True:
        mean = np.array([123.68, 116.28, 103.53])
    if std is True:
        std = np.array([58.395, 57.12, 57.375])
    if mean is not None:
        auglist.append(ColorNormalizeAug(mean, std))
    return auglist


class ImageIter(mxio.DataIter):
    """Image iterator with pluggable augmenters, reading RecordIO packs
    (``path_imgrec``) or an image list + root dir (``path_imglist`` /
    ``imglist``). Reference: python/mxnet/image.py ImageIter; rank sharding
    via part_index/num_parts matches the reference's kv.rank split
    (src/io/iter_image_recordio.cc InputSplit usage)."""

    def __init__(self, batch_size, data_shape, label_width=1,
                 path_imgrec=None, path_imglist=None, path_root=None,
                 shuffle=False, part_index=0, num_parts=1, aug_list=None,
                 imglist=None, data_name="data", label_name="softmax_label",
                 last_batch_handle="pad", **kwargs):
        super(ImageIter, self).__init__()
        assert path_imgrec or path_imglist or imglist is not None, \
            "must supply path_imgrec, path_imglist or imglist"
        assert len(data_shape) == 3 and data_shape[0] == 3
        self.batch_size = batch_size
        self.data_shape = tuple(data_shape)
        self.label_width = label_width
        self.data_name = data_name
        self.label_name = label_name
        self.record = None
        self.imglist = None
        self._native_reader = None
        self._native_prefetch = None
        self._rec_path = path_imgrec
        if path_imgrec:
            from .filesystem import local_path

            idx_path = kwargs.get("path_imgidx",
                                  os.path.splitext(path_imgrec)[0] + ".idx")
            lp = local_path(idx_path)
            # local: cheap existence check; remote: attempt the indexed
            # open and fall back only on not-found (auth/transport
            # errors stay LOUD, and an explicitly passed path_imgidx is
            # never silently discarded)
            if lp is None or os.path.exists(lp):
                try:
                    self.record = recordio.MXIndexedRecordIO(
                        idx_path, path_imgrec, "r")
                except (FileNotFoundError, KeyError, IsADirectoryError):
                    if "path_imgidx" in kwargs:
                        raise
                    self.record = None
            if self.record is not None:
                self.seq = list(self.record.keys)
            else:
                self.record = recordio.MXRecordIO(path_imgrec, "r")
                if shuffle or num_parts > 1:
                    # no sidecar .idx: build an in-memory offset index with
                    # one sequential scan so shuffle/sharding still work
                    # (the C++ reference shuffles chunk-wise without one)
                    self._offsets = []
                    while True:
                        pos = self.record.tell()
                        if self.record.read() is None:
                            break
                        self._offsets.append(pos)
                    self.record.reset()
                    self.seq = list(range(len(self._offsets)))
                else:
                    self.seq = None
        else:
            if path_imglist:
                entries = []
                with open(path_imglist) as fin:
                    for line in fin:
                        parts = line.strip().split("\t")
                        label = np.array(parts[1:-1], np.float32)
                        entries.append((parts[-1], label))
                self.imglist = entries
            else:
                self.imglist = [
                    (item[-1], np.array(item[:-1], np.float32).reshape(-1))
                    if not isinstance(item, str) else (item, np.zeros(1))
                    for item in imglist]
            self.path_root = path_root or "."
            self.seq = list(range(len(self.imglist)))
        if self.seq is not None and num_parts > 1:
            # rank sharding: contiguous split like dmlc InputSplit, with the
            # remainder spread over the first parts (no sample dropped)
            n, rem = divmod(len(self.seq), num_parts)
            start = part_index * n + min(part_index, rem)
            stop = start + n + (1 if part_index < rem else 0)
            self.seq = self.seq[start:stop]
        self.shuffle = shuffle
        if last_batch_handle not in ("pad", "discard"):
            raise ValueError("last_batch_handle must be 'pad' or 'discard', "
                             "got %r" % (last_batch_handle,))
        self.last_batch_handle = last_batch_handle
        if aug_list is None:
            aug_list = CreateAugmenter(data_shape)
        self.auglist = aug_list
        self.cur = 0
        self._provide_data = [mxio.DataDesc(data_name,
                                            (batch_size,) + self.data_shape)]
        label_shape = (batch_size,) if label_width == 1 else \
            (batch_size, label_width)
        self._provide_label = [mxio.DataDesc(label_name, label_shape)]
        self.reset()

    @property
    def provide_data(self):
        return self._provide_data

    @property
    def provide_label(self):
        return self._provide_label

    def reset(self):
        from .filesystem import scheme_of

        if self.shuffle and self.seq is not None:
            pyrandom.shuffle(self.seq)
        # the C++ fast path mmap/reads a local file; registered remote
        # schemes (mx.filesystem) stay on the Python handle, which
        # already resolved through the registry
        native_ok = native.have_native() and \
            scheme_of(self._rec_path or "") == ""
        if self.record is not None and self.seq is None:
            if native_ok:
                # C++ readahead thread (src/recordio.cc prefetcher) for the
                # sequential scan; Python handle untouched
                if self._native_prefetch is not None:
                    self._native_prefetch.close()
                    self._native_prefetch = None
                self._native_prefetch = native.NativePrefetchReader(
                    self._rec_path)
            else:
                self.record.reset()
        elif self.record is not None and native_ok and \
                self._native_reader is None:
            self._native_reader = native.NativeRecordReader(self._rec_path)
        self.cur = 0

    def next_sample(self):
        """One (label, encoded image bytes) pair — decode is deferred so
        subclasses can parallelize it (the reference's OMP decode threads,
        iter_image_recordio.cc:140-160)."""
        if self.seq is not None:
            if self.cur >= len(self.seq):
                raise StopIteration
            idx = self.seq[self.cur]
            self.cur += 1
            if self.record is not None:
                if getattr(self, "_offsets", None) is not None:
                    pos = self._offsets[idx]
                elif self._native_reader is not None:
                    pos = self.record.idx[idx]
                else:
                    pos = None
                if self._native_reader is not None and pos is not None:
                    s = self._native_reader.read_at(pos)
                elif pos is not None:
                    self.record.seek(pos)
                    s = self.record.read()
                else:
                    s = self.record.read_idx(idx)
                header, img = recordio.unpack(s)
                return header.label, img
            fname, label = self.imglist[idx]
            with open(os.path.join(self.path_root, fname), "rb") as fin:
                img = fin.read()
            return label, img
        if self._native_prefetch is not None:
            s = next(self._native_prefetch)
        else:
            s = self.record.read()
        if s is None:
            raise StopIteration
        header, img = recordio.unpack(s)
        return header.label, img

    def _augment_arr(self, arr):
        """Run the augmenter chain → HWC float32.  Built-in chains
        (_NpSafeAugList) run numpy-to-numpy — no per-image device array;
        user-supplied aug_lists get the reference NDArray contract."""
        a = arr
        if not isinstance(self.auglist, _NpSafeAugList) and \
                not isinstance(a, nd.NDArray):
            a = nd.array(a)
        for aug in self.auglist:
            a = aug(a)[0]
        return _as_np(a).astype(np.float32)

    def _decode_augment(self, buf):
        """Decode one sample and run the augmenter chain."""
        return self._augment_arr(image_backend.decode_image(buf))

    def _collect_raw(self):
        """Read up to batch_size raw samples; StopIteration if exhausted."""
        samples = []
        try:
            while len(samples) < self.batch_size:
                samples.append(self.next_sample())
        except StopIteration:
            if not samples:
                raise
        return samples

    def _decode_batch(self, samples):
        return [self._decode_augment(buf) for _, buf in samples]

    def next(self):
        c, h, w = self.data_shape
        batch_data = np.zeros((self.batch_size, h, w, c), np.float32)
        batch_label = np.zeros((self.batch_size, self.label_width), np.float32)
        samples = self._collect_raw()
        decoded = self._decode_batch(samples)
        i = 0
        for (label, _), data in zip(samples, decoded):
            if data.shape[:2] != (h, w):
                if not getattr(self, "_warned_shape", False):
                    logging.warning(
                        "ImageIter: dropping sample with post-augment shape "
                        "%s != %s — add a crop/ForceResize augmenter",
                        data.shape, (h, w))
                    self._warned_shape = True
                continue
            batch_data[i] = data
            lab = np.asarray(label, np.float32).reshape(-1)
            batch_label[i] = lab[:self.label_width]
            i += 1
        if i == 0 or (i < self.batch_size and
                      self.last_batch_handle == "discard"):
            raise StopIteration
        # pad the final partial batch by repeating the last sample
        for j in range(i, self.batch_size):
            batch_data[j] = batch_data[i - 1]
            batch_label[j] = batch_label[i - 1]
        data_nchw = np.transpose(batch_data, (0, 3, 1, 2))
        label_out = batch_label[:, 0] if self.label_width == 1 else batch_label
        return mxio.DataBatch(data=[nd.array(data_nchw)],
                              label=[nd.array(label_out)],
                              pad=self.batch_size - i)


class _ParallelImageIter(ImageIter):
    """ImageIter with parallel decode — JPEGs go through the native libjpeg
    thread pool (GIL-free, src/imgdecode.cc; the analogue of the reference's
    preprocess_threads OMP decode, iter_image_recordio.cc:140-160), other
    formats through PIL on Python threads.  Augmenters run on a thread pool
    either way."""

    def __init__(self, *args, preprocess_threads=4, **kwargs):
        from concurrent.futures import ThreadPoolExecutor

        super(_ParallelImageIter, self).__init__(*args, **kwargs)
        self._nthreads = max(1, preprocess_threads)
        self._pool = ThreadPoolExecutor(max_workers=self._nthreads)

    def _decode_batch(self, samples):
        bufs = [buf for _, buf in samples]
        decoded = native.decode_jpeg_batch(bufs, nthreads=self._nthreads) \
            if native.have_native() else [None] * len(bufs)

        def finish(pair):
            arr, buf = pair
            if arr is None:  # non-JPEG or native unavailable: PIL path
                return self._decode_augment(buf)
            return self._augment_arr(arr)

        return list(self._pool.map(finish, zip(decoded, bufs)))


def ImageRecordIter(path_imgrec, data_shape, batch_size, label_width=1,
                    shuffle=False, rand_crop=False, rand_mirror=False,
                    mean_r=0.0, mean_g=0.0, mean_b=0.0,
                    std_r=1.0, std_g=1.0, std_b=1.0, resize=0,
                    part_index=0, num_parts=1, preprocess_threads=4,
                    prefetch_buffer=1, data_name="data",
                    label_name="softmax_label", **kwargs):
    """RecordIO image iterator: threaded decode + augment + prefetch + rank
    sharding (reference: the C++ ImageRecordIter chain
    parser→augmenter→normalize→batch→prefetch, src/io/io.cc:9-23). Returns
    a DataIter yielding NCHW float32 batches."""
    mean = None
    std = None
    if mean_r or mean_g or mean_b:
        mean = np.array([mean_r, mean_g, mean_b], np.float32)
    if (std_r, std_g, std_b) != (1.0, 1.0, 1.0):
        std = np.array([std_r, std_g, std_b], np.float32)
    aug_list = CreateAugmenter(data_shape, resize=resize, rand_crop=rand_crop,
                               rand_mirror=rand_mirror, mean=mean, std=std)
    inner = _ParallelImageIter(
        batch_size, data_shape, label_width=label_width,
        path_imgrec=path_imgrec, shuffle=shuffle, part_index=part_index,
        num_parts=num_parts, aug_list=aug_list, data_name=data_name,
        label_name=label_name, preprocess_threads=preprocess_threads,
        **kwargs)
    if prefetch_buffer:
        return mxio.PrefetchingIter(inner)
    return inner


# ---------------------------------------------------------------------------
# Detection pipeline — box-aware augmenters + ImageDetIter/ImageDetRecordIter
# (reference: src/io/iter_image_det_recordio.cc:475-563 + the det augmenter
# src/io/image_det_aug_default.cc).  Record label layout follows the dmlc
# detection pack: [A, B, extra..., (B fields per object)*] where A is the
# header width (>=2), B the per-object width (>=5: id, xmin, ymin, xmax,
# ymax in [0,1] normalized coordinates).
# ---------------------------------------------------------------------------


def _det_parse_label(raw):
    """Flat record label -> (N, B) object array (normalized coords)."""
    raw = np.asarray(raw, np.float32).reshape(-1)
    if raw.size < 2:
        raise ValueError("detection label too short: %r" % (raw,))
    a, b = int(raw[0]), int(raw[1])
    if a < 2 or b < 5:
        raise ValueError(
            "bad detection header A=%d B=%d (need A>=2, B>=5)" % (a, b))
    body = raw[a:]
    n = body.size // b
    return body[:n * b].reshape(n, b).copy()


def _det_encode_label(objects, header_width=2):
    """(N, B) objects -> flat record label (inverse of _det_parse_label)."""
    objects = np.asarray(objects, np.float32)
    b = objects.shape[1] if objects.ndim == 2 else 5
    head = np.zeros(header_width, np.float32)
    head[0], head[1] = header_width, b
    return np.concatenate([head, objects.reshape(-1)])


def DetHorizontalFlipAug(p):
    """Mirror image AND boxes: x' = 1 - x (reference
    image_det_aug_default.cc horizontal flip)."""
    def aug(src, label):
        if pyrandom.random() < p:
            src = _as_np(src)[:, ::-1, :]
            label = label.copy()
            x1 = label[:, 1].copy()
            label[:, 1] = 1.0 - label[:, 3]
            label[:, 3] = 1.0 - x1
        return src, label
    return aug


def DetRandomCropAug(min_object_covered=0.3, aspect_ratio_range=(0.75, 1.33),
                     area_range=(0.3, 1.0), max_attempts=20):
    """Sample a crop keeping objects whose centers stay inside; coordinates
    are clipped and re-normalized to the crop (reference det crop sampler,
    image_det_aug_default.cc crop logic)."""
    def aug(src, label):
        img = _as_np(src)
        h, w = img.shape[:2]
        for _ in range(max_attempts):
            area = pyrandom.uniform(*area_range) * h * w
            ratio = pyrandom.uniform(*aspect_ratio_range)
            cw = int(round(np.sqrt(area * ratio)))
            ch = int(round(np.sqrt(area / ratio)))
            if cw > w or ch > h or cw < 1 or ch < 1:
                continue
            x0 = pyrandom.randint(0, w - cw)
            y0 = pyrandom.randint(0, h - ch)
            nx0, ny0 = x0 / w, y0 / h
            nx1, ny1 = (x0 + cw) / w, (y0 + ch) / h
            cx = (label[:, 1] + label[:, 3]) / 2
            cy = (label[:, 2] + label[:, 4]) / 2
            keep = (cx >= nx0) & (cx < nx1) & (cy >= ny0) & (cy < ny1)
            if not keep.any():
                continue
            kept = label[keep].copy()
            # clip to the crop, re-normalize
            kept[:, 1] = np.clip((kept[:, 1] - nx0) / (nx1 - nx0), 0, 1)
            kept[:, 3] = np.clip((kept[:, 3] - nx0) / (nx1 - nx0), 0, 1)
            kept[:, 2] = np.clip((kept[:, 2] - ny0) / (ny1 - ny0), 0, 1)
            kept[:, 4] = np.clip((kept[:, 4] - ny0) / (ny1 - ny0), 0, 1)
            # min_object_covered: kept boxes must retain enough area
            ow = np.maximum(kept[:, 3] - kept[:, 1], 0) * (nx1 - nx0)
            oh = np.maximum(kept[:, 4] - kept[:, 2], 0) * (ny1 - ny0)
            orig_w = np.maximum(label[keep, 3] - label[keep, 1], 1e-8)
            orig_h = np.maximum(label[keep, 4] - label[keep, 2], 1e-8)
            cov = (ow * oh) / (orig_w * orig_h)
            if (cov >= min_object_covered).all():
                return img[y0:y0 + ch, x0:x0 + cw, :], kept
        return img, label
    return aug


def DetForceResizeAug(size, interp=1):
    """Resize to exact (w, h); normalized box coords are scale-invariant."""
    def aug(src, label):
        return image_backend.resize_image(_as_np(src), size[0], size[1],
                                          interp), label
    return aug


def CreateDetAugmenter(data_shape, resize=0, rand_crop=0.0, rand_mirror=False,
                       mean=None, std=None, min_object_covered=0.3,
                       aspect_ratio_range=(0.75, 1.33),
                       area_range=(0.3, 1.0), max_attempts=20):
    """Standard detection augmenter chain (reference
    image_det_aug_default.cc defaults): [crop] -> resize -> [flip] ->
    normalize."""
    augs = []
    if rand_crop > 0:
        crop = DetRandomCropAug(min_object_covered, aspect_ratio_range,
                                area_range, max_attempts)
        p = float(rand_crop)

        def maybe_crop(src, label, _crop=crop, _p=p):
            if pyrandom.random() < _p:
                return _crop(src, label)
            return _as_np(src), label
        augs.append(maybe_crop)
    augs.append(DetForceResizeAug((data_shape[2], data_shape[1])))
    if rand_mirror:
        augs.append(DetHorizontalFlipAug(0.5))
    if mean is not None or std is not None:
        mean = np.zeros(3, np.float32) if mean is None else mean
        std = np.ones(3, np.float32) if std is None else std

        def normalize(src, label, _m=mean, _s=std):
            return (_as_np(src).astype(np.float32) - _m) / _s, label
        augs.append(normalize)
    return _NpSafeAugList(augs)


class ImageDetIter(ImageIter):
    """Detection iterator: variable-object records -> fixed (batch,
    label_pad_width, object_width) labels padded with -1 (the shape
    MultiBoxTarget consumes).  Reference:
    src/io/iter_image_det_recordio.cc:475-563."""

    def __init__(self, batch_size, data_shape, path_imgrec=None,
                 label_pad_width=8, object_width=5, aug_list=None,
                 data_name="data", label_name="label", **kwargs):
        if aug_list is None:
            aug_list = CreateDetAugmenter(data_shape)
        self.label_pad_width = label_pad_width
        self.object_width = object_width
        super(ImageDetIter, self).__init__(
            batch_size, data_shape, label_width=1, path_imgrec=path_imgrec,
            aug_list=aug_list, data_name=data_name, label_name=label_name,
            **kwargs)
        self._provide_label = [mxio.DataDesc(
            label_name, (batch_size, label_pad_width, object_width))]

    def next(self):
        c, h, w = self.data_shape
        batch_data = np.zeros((self.batch_size, h, w, c), np.float32)
        batch_label = np.full(
            (self.batch_size, self.label_pad_width, self.object_width),
            -1.0, np.float32)
        samples = self._collect_raw()
        i = 0
        for raw_label, buf in samples:
            objects = _det_parse_label(raw_label)
            img = image_backend.decode_image(buf)
            for aug in self.auglist:
                img, objects = aug(img, objects)
            img = np.asarray(img, np.float32)
            if img.shape[:2] != (h, w):
                continue
            if len(objects) > self.label_pad_width:
                raise MXNetError(
                    "record has %d objects but label_pad_width=%d — raise "
                    "label_pad_width to at least the dataset maximum"
                    % (len(objects), self.label_pad_width))
            n = len(objects)
            batch_data[i] = img
            if n:
                batch_label[i, :n] = objects[:n, :self.object_width]
            i += 1
        if i == 0 or (i < self.batch_size and
                      self.last_batch_handle == "discard"):
            raise StopIteration
        for j in range(i, self.batch_size):
            batch_data[j] = batch_data[i - 1]
            batch_label[j] = batch_label[i - 1]
        data_nchw = np.transpose(batch_data, (0, 3, 1, 2))
        return mxio.DataBatch(data=[nd.array(data_nchw)],
                              label=[nd.array(batch_label)],
                              pad=self.batch_size - i)


def ImageDetRecordIter(path_imgrec, data_shape, batch_size,
                       label_pad_width=8, object_width=5, shuffle=False,
                       rand_crop=0.0, rand_mirror=False,
                       min_object_covered=0.3, max_attempts=20,
                       area_range=(0.3, 1.0),
                       mean_r=0.0, mean_g=0.0, mean_b=0.0,
                       std_r=1.0, std_g=1.0, std_b=1.0,
                       part_index=0, num_parts=1, prefetch_buffer=1,
                       data_name="data", label_name="label", **kwargs):
    """Detection RecordIO iterator (reference ImageDetRecordIter,
    src/io/iter_image_det_recordio.cc:563 registration): consumes
    ``tools/im2rec.py``-packed detection records (vector labels), applies
    box-aware augmentation, yields (data NCHW, label (B, pad, width))."""
    mean = None
    std = None
    if mean_r or mean_g or mean_b:
        mean = np.array([mean_r, mean_g, mean_b], np.float32)
    if (std_r, std_g, std_b) != (1.0, 1.0, 1.0):
        std = np.array([std_r, std_g, std_b], np.float32)
    aug_list = CreateDetAugmenter(
        data_shape, rand_crop=rand_crop, rand_mirror=rand_mirror,
        mean=mean, std=std, min_object_covered=min_object_covered,
        area_range=area_range, max_attempts=max_attempts)
    inner = ImageDetIter(
        batch_size, data_shape, path_imgrec=path_imgrec,
        label_pad_width=label_pad_width, object_width=object_width,
        shuffle=shuffle, part_index=part_index, num_parts=num_parts,
        aug_list=aug_list, data_name=data_name, label_name=label_name,
        **kwargs)
    if prefetch_buffer:
        return mxio.PrefetchingIter(inner)
    return inner


# -- imperative decode/resize ops (reference src/io/image_io.cc:269-291:
# _cvimdecode/_cvimresize/_cvcopyMakeBorder backing mx.image) — host-side,
# eager-only: output shapes are data-dependent so they cannot trace under jit
def _register_image_ops():
    from .ops.param import Param
    from .ops.registry import register as reg_op

    @reg_op("_cvimdecode", inputs=("buf",),
            params={"flag": Param(int, default=1),
                    "to_rgb": Param(bool, default=True)},
            hint="cvimdecode")
    def _cvimdecode(opctx, attrs, buf):
        import jax.numpy as jnp

        arr = image_backend.decode_image(
            np.asarray(buf).tobytes(), channels=3 if attrs["flag"] else 1)
        if not attrs["to_rgb"]:
            arr = arr[:, :, ::-1]
        return jnp.asarray(arr)

    @reg_op("_cvimresize", inputs=("data",),
            params={"w": Param(int, required=True),
                    "h": Param(int, required=True),
                    "interp": Param(int, default=1)},
            infer_shape=lambda attrs, s: (
                s, [(attrs["h"], attrs["w"], s[0][2])] if s[0] else [None], []),
            hint="cvimresize")
    def _cvimresize(opctx, attrs, data):
        import jax.numpy as jnp

        arr = image_backend.resize_image(
            np.asarray(data).astype(np.uint8), attrs["w"], attrs["h"],
            attrs["interp"])
        return jnp.asarray(arr)

    @reg_op("_cvcopyMakeBorder", inputs=("data",),
            params={"top": Param(int, required=True),
                    "bot": Param(int, required=True),
                    "left": Param(int, required=True),
                    "right": Param(int, required=True),
                    "type": Param(int, default=0),
                    "values": Param("float-shape", default=(0.0,))},
            hint="cvcopymakeborder")
    def _cvcopyMakeBorder(opctx, attrs, data):
        import jax.numpy as jnp

        arr = np.asarray(data)
        val = attrs["values"][0] if attrs["values"] else 0.0
        out = np.pad(arr, ((attrs["top"], attrs["bot"]),
                           (attrs["left"], attrs["right"]), (0, 0)),
                     constant_values=val)
        return jnp.asarray(out)

    @reg_op("_imdecode", inputs=("mean", "str_img"),
            params={"index": Param(int, default=0),
                    "x0": Param(int, default=0), "y0": Param(int, default=0),
                    "x1": Param(int, default=0), "y1": Param(int, default=0),
                    "c": Param(int, default=3), "size": Param(int, default=0)},
            hint="imdecode_fun")
    def _imdecode_fun(opctx, attrs, mean, str_img):
        """Registered NDArray function ``_imdecode`` (reference
        src/ndarray/ndarray.cc registered fun ``_imdecode``): decode image
        ``index`` (of byte length ``size``) from a packed uint8 buffer,
        optional crop box, CHW float32 output with an optional CHW mean
        subtracted — the reference's layout contract."""
        import jax.numpy as jnp

        buf = np.asarray(str_img).tobytes()
        size = attrs["size"]
        if size > 0:
            buf = buf[attrs["index"] * size:(attrs["index"] + 1) * size]
        arr = image_backend.decode_image(buf, channels=attrs["c"])
        if arr.ndim == 2:
            arr = arr[:, :, None]
        x0, y0, x1, y1 = (attrs[k] for k in ("x0", "y0", "x1", "y1"))
        if x1 > x0 and y1 > y0:
            arr = arr[y0:y1, x0:x1]
        out = np.transpose(arr.astype(np.float32), (2, 0, 1))  # CHW
        m = np.asarray(mean, np.float32)
        # empty mean or scalar 0 means "no subtraction" (ndarray.cc:876-879)
        if m.size and (m.ndim >= 2 or m.size > 1
                       or float(m.reshape(-1)[0]) != 0.0):
            out = out - m  # CHW mean, broadcast rules
        return jnp.asarray(out)


_register_image_ops()

# refresh the generated op surfaces (codegen ran before these ops existed)
from . import symbol as _sym_mod  # noqa: E402

nd._init_ops()
_sym_mod._init_symbol_module()

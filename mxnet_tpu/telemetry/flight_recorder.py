"""Flight recorder — postmortem evidence for every process death.

The telemetry core already keeps bounded in-memory rings: the tracer's
span buffer, the EventLog ring, and the metrics registry.  This module
snapshots the last ``MXNET_TELEMETRY_FLIGHT_RING`` spans/events plus a
full metrics snapshot and writes them atomically to
``postmortem-<role><rank>-<ts>.json`` when the process is about to die:

* SIGTERM preemption drain (``kvstore.install_preemption_handler``),
* a fault-injected ``kill`` (``faults.FaultPlan.fire``, just before
  ``os._exit(137)``),
* a membership eviction (the kvstore server dumps its view of the round
  state when it removes ranks),
* an unhandled exception (``sys.excepthook`` / ``threading.excepthook``,
  installed while telemetry is enabled).

The write path deliberately does NOT go through ``filesystem.atomic_write``:
that primitive fires the fault layer, and a ``*:kill`` plan would
re-enter the kill while the postmortem is mid-write.  A plain
tmp+fsync+``os.replace`` gives the same atomicity without re-arming the
trap that is killing us.
"""
from __future__ import annotations

import json
import os
import sys
import threading
import time
from typing import Optional

from ..base import env, register_env

__all__ = ["dump", "install_excepthooks", "uninstall_excepthooks",
           "last_path"]

register_env("MXNET_TELEMETRY_FLIGHT_RING", 256, int,
             "Max spans and events kept in a flight-recorder postmortem "
             "dump (the in-memory rings may hold more).")
register_env("MXNET_TELEMETRY_POSTMORTEM_DIR", "", str,
             "Directory for flight-recorder postmortem dumps; empty "
             "falls back to MXNET_TELEMETRY_DUMP_DIR, then "
             "MXNET_TELEMETRY_DIR, then <tmpdir>/mxnet_tpu-artifacts "
             "(never the cwd).")

_lock = threading.Lock()
_in_dump = False
_last_path: Optional[str] = None


def last_path() -> Optional[str]:
    return _last_path


def _postmortem_dir() -> str:
    from . import dump_dir

    return env("MXNET_TELEMETRY_POSTMORTEM_DIR", "", str) or \
        env("MXNET_TELEMETRY_DUMP_DIR", "", str) or \
        env("MXNET_TELEMETRY_DIR", "", str) or dump_dir()


def dump(reason: str, extra: Optional[dict] = None) -> Optional[str]:
    """Write the postmortem for this process; returns its path, or None
    when telemetry is off / a dump is already in flight (re-entrancy
    guard: the crash path must never recurse into itself)."""
    global _in_dump, _last_path
    from . import enabled, events, registry
    from . import tracer
    from .distributed import proc_identity, proc_label

    if not enabled():
        return None
    with _lock:
        if _in_dump:
            return None
        _in_dump = True
    try:
        n = max(1, env("MXNET_TELEMETRY_FLIGHT_RING", 256, int))
        role, rank = proc_identity()
        payload = {
            "reason": reason,
            "role": role,
            "rank": rank,
            "pid": os.getpid(),
            "time": round(time.time(), 6),
            "spans": tracer.events()[-n:],
            "events": events(n),
            "metrics": registry().snapshot(),
        }
        if extra:
            payload["extra"] = extra
        d = _postmortem_dir()
        try:
            os.makedirs(d, exist_ok=True)
        except OSError:
            import tempfile

            d = tempfile.gettempdir()
        path = os.path.join(d, "postmortem-%s-%d.json"
                            % (proc_label(), int(time.time() * 1e3)))
        tmp = "%s.tmp.%d" % (path, os.getpid())
        with open(tmp, "w") as f:
            json.dump(payload, f, default=str)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        _last_path = path
        return path
    except Exception:
        return None
    finally:
        with _lock:
            _in_dump = False


# -- unhandled-exception hooks ----------------------------------------------

_orig_excepthook = None
_orig_threading_hook = None


def install_excepthooks():
    """Chain onto sys/threading excepthooks so an unhandled exception in
    any thread leaves a postmortem before the default reporting runs."""
    global _orig_excepthook, _orig_threading_hook
    if _orig_excepthook is not None:
        return

    _orig_excepthook = sys.excepthook
    _orig_threading_hook = threading.excepthook

    def hook(tp, val, tb):
        try:
            dump("exception:%s" % getattr(tp, "__name__", tp),
                 extra={"message": str(val)[:500]})
        except Exception:
            pass
        (_orig_excepthook or sys.__excepthook__)(tp, val, tb)

    def thook(args):
        try:
            dump("thread-exception:%s"
                 % getattr(args.exc_type, "__name__", args.exc_type),
                 extra={"thread": getattr(args.thread, "name", None),
                        "message": str(args.exc_value)[:500]})
        except Exception:
            pass
        (_orig_threading_hook or threading.__excepthook__)(args)

    sys.excepthook = hook
    threading.excepthook = thook


def uninstall_excepthooks():
    global _orig_excepthook, _orig_threading_hook
    if _orig_excepthook is not None:
        sys.excepthook = _orig_excepthook
        _orig_excepthook = None
    if _orig_threading_hook is not None:
        threading.excepthook = _orig_threading_hook
        _orig_threading_hook = None

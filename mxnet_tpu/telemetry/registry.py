"""Metrics registry — counters, gauges, histograms, and a JSONL event log.

The storage layer of ``mxnet_tpu.telemetry``: every instrumented subsystem
(comm engine, kvstore server, prefetch iterator, serving batcher, step
monitor) creates its instruments here, and one Prometheus text renderer /
one snapshot walk exports them all.  The reference framework's analogue is
the per-op stat table inside src/engine/profiler.h; production servers
(TF Serving, Triton) converged on exactly this counter/gauge/histogram
trio, which ``serving/metrics.py`` pioneered locally and now shares.

Thread-safety: each instrument carries its own lock; the registry dict is
guarded separately for get-or-create.  Nothing here imports jax — the
module is safe to load before backend init.
"""
from __future__ import annotations

import json
import threading
import time
from collections import OrderedDict, deque
from typing import Callable, Dict, List, Optional

__all__ = ["Counter", "Gauge", "Histogram", "LabeledCounter", "Registry",
           "EventLog"]


def _fmt(v):
    """Prometheus sample value: ints render bare, floats keep precision."""
    if isinstance(v, float) and not v.is_integer():
        return "%.6g" % v
    return "%d" % int(v)


class Counter:
    """Monotonic counter (float increments allowed for ms/bytes totals)."""

    __slots__ = ("name", "doc", "_lock", "_v")

    def __init__(self, name: str, doc: str = ""):
        self.name = name
        self.doc = doc
        self._lock = threading.Lock()
        self._v = 0

    def inc(self, n=1):
        with self._lock:
            self._v += n

    @property
    def value(self):
        return self._v

    def render(self) -> List[str]:
        return ["# TYPE %s counter" % self.name,
                "%s %s" % (self.name, _fmt(self._v))]


class Gauge:
    """Last-value gauge; ``fn`` makes it a live probe read at render time
    (queue depths, inflight counts) instead of a stored sample."""

    __slots__ = ("name", "doc", "_lock", "_v", "_fn")

    def __init__(self, name: str, doc: str = "",
                 fn: Optional[Callable[[], float]] = None):
        self.name = name
        self.doc = doc
        self._lock = threading.Lock()
        self._v = 0
        self._fn = fn

    def set(self, v):
        with self._lock:
            self._v = v

    def inc(self, n=1):
        with self._lock:
            self._v += n

    def dec(self, n=1):
        self.inc(-n)

    def set_max(self, v):
        """Watermark update: keep the max of the current and new value."""
        with self._lock:
            if v > self._v:
                self._v = v

    @property
    def value(self):
        if self._fn is not None:
            try:
                return self._fn()
            except Exception:
                return 0
        return self._v

    def render(self) -> List[str]:
        return ["# TYPE %s gauge" % self.name,
                "%s %s" % (self.name, _fmt(self.value))]


class Histogram:
    """Histogram over exponential buckets: upper bounds
    ``start * factor**i`` for i in [0, count), plus +Inf."""

    __slots__ = ("name", "doc", "_lock", "bounds", "_counts", "_sum", "_n")

    def __init__(self, name: str, doc: str = "", start: float = 0.5,
                 factor: float = 2.0, count: int = 16):
        self.name = name
        self.doc = doc
        self._lock = threading.Lock()
        self.bounds = [start * (factor ** i) for i in range(count)]
        self._counts = [0] * (count + 1)  # last slot: +Inf
        self._sum = 0.0
        self._n = 0

    def observe(self, v):
        i = 0
        bounds = self.bounds
        while i < len(bounds) and v > bounds[i]:
            i += 1
        with self._lock:
            self._counts[i] += 1
            self._sum += v
            self._n += 1

    @property
    def count(self):
        return self._n

    @property
    def sum(self):
        return self._sum

    def snapshot(self):
        with self._lock:
            counts = list(self._counts)
            total, n = self._sum, self._n
        cum, buckets = 0, []
        for bound, c in zip(self.bounds + [float("inf")], counts):
            cum += c
            buckets.append((bound, cum))
        return {"buckets": buckets, "sum": total, "count": n}

    def render(self) -> List[str]:
        s = self.snapshot()
        lines = ["# TYPE %s histogram" % self.name]
        for bound, cum in s["buckets"]:
            le = "+Inf" if bound == float("inf") else "%.6g" % bound
            lines.append('%s_bucket{le="%s"} %d' % (self.name, le, cum))
        lines.append("%s_sum %s" % (self.name, _fmt(s["sum"])))
        lines.append("%s_count %d" % (self.name, s["count"]))
        return lines


class LabeledCounter:
    """Counter family over one label dimension — sparse exact-value
    histograms (batch buckets, fault kinds, RPC commands)."""

    __slots__ = ("name", "doc", "label", "_lock", "_c")

    def __init__(self, name: str, label: str, doc: str = ""):
        self.name = name
        self.doc = doc
        self.label = label
        self._lock = threading.Lock()
        self._c: Dict[object, float] = {}

    def inc(self, label_value, n=1):
        with self._lock:
            self._c[label_value] = self._c.get(label_value, 0) + n

    def get(self, label_value, default=0):
        return self._c.get(label_value, default)

    def snapshot(self):
        with self._lock:
            return dict(self._c)

    @property
    def value(self):
        with self._lock:
            return sum(self._c.values())

    def render(self) -> List[str]:
        lines = ["# TYPE %s counter" % self.name]
        for k in sorted(self._c, key=str):
            lines.append('%s{%s="%s"} %s'
                         % (self.name, self.label, k, _fmt(self._c[k])))
        return lines


class Registry:
    """Named instrument collection with get-or-create semantics.

    One process-global instance backs the framework (``telemetry.registry()``);
    subsystems that need isolated counts per object (a serving server, an
    async kvstore) build their own and attach it to the global render via
    ``telemetry.register_collector``.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._instruments: "OrderedDict[str, object]" = OrderedDict()

    def _get_or_create(self, name, cls, *args, **kwargs):
        with self._lock:
            inst = self._instruments.get(name)
            if inst is None:
                inst = cls(name, *args, **kwargs)
                self._instruments[name] = inst
            elif not isinstance(inst, cls):
                raise TypeError("instrument %r already registered as %s"
                                % (name, type(inst).__name__))
            return inst

    def counter(self, name, doc="") -> Counter:
        return self._get_or_create(name, Counter, doc)

    def gauge(self, name, doc="", fn=None) -> Gauge:
        return self._get_or_create(name, Gauge, doc, fn)

    def histogram(self, name, doc="", start=0.5, factor=2.0,
                  count=16) -> Histogram:
        return self._get_or_create(name, Histogram, doc, start, factor, count)

    def labeled_counter(self, name, label, doc="") -> LabeledCounter:
        return self._get_or_create(name, LabeledCounter, label, doc)

    def get(self, name):
        return self._instruments.get(name)

    def snapshot(self) -> Dict[str, object]:
        """name -> scalar (counter/gauge) or dict (histogram/labeled)."""
        with self._lock:
            items = list(self._instruments.items())
        out = {}
        for name, inst in items:
            if isinstance(inst, (Histogram, LabeledCounter)):
                out[name] = inst.snapshot()
            else:
                out[name] = inst.value
        return out

    def render_prometheus(self) -> str:
        with self._lock:
            items = list(self._instruments.values())
        lines: List[str] = []
        for inst in items:
            lines.extend(inst.render())
        return "\n".join(lines) + ("\n" if lines else "")


class EventLog:
    """Bounded in-memory structured-event buffer, optionally mirrored to a
    JSONL file (``MXNET_TELEMETRY_DIR/events.jsonl``) for post-hoc tooling
    (tools/telemetry_dump.py)."""

    def __init__(self, path: Optional[str] = None, maxlen: int = 4096):
        self._lock = threading.Lock()
        self._buf = deque(maxlen=maxlen)
        self._path = path
        self._fh = open(path, "a", buffering=1) if path else None

    @property
    def path(self):
        return self._path

    def emit(self, kind: str, **fields):
        rec = {"ts": round(time.time(), 6), "kind": kind}
        rec.update(fields)
        with self._lock:
            self._buf.append(rec)
            if self._fh is not None:
                try:
                    self._fh.write(json.dumps(rec, default=str) + "\n")
                except ValueError:  # closed file during teardown
                    pass
        return rec

    def tail(self, n: Optional[int] = None):
        with self._lock:
            evs = list(self._buf)
        return evs if n is None else evs[-n:]

    def close(self):
        with self._lock:
            if self._fh is not None:
                try:
                    self._fh.close()
                finally:
                    self._fh = None

"""Span tracer — one merged Chrome-trace timeline across every thread.

``profiler.Frame`` spans (Module steps, comm-engine workers, the serving
batcher) normally record only while the legacy profiler is in the "run"
state.  When telemetry tracing is active this module installs itself as
the profiler's external sink, so every Frame from any thread ALSO lands in
a bounded buffer here — no profiler_set_state dance needed — and
``merged_trace()`` combines both buffers (deduplicating spans that were
recorded to each) plus per-thread ``thread_name`` metadata into a single
chrome://tracing / Perfetto-loadable JSON with one track per thread.
"""
from __future__ import annotations

import json
import threading
import time
from collections import deque
from typing import Dict, List, Optional

from .. import profiler as _prof

__all__ = ["start", "stop", "active", "events", "merged_trace",
           "dump_trace", "validate_trace", "span", "flow_event"]

_lock = threading.Lock()
_buf: Optional[deque] = None
_tnames: Dict[int, str] = {}


def _sink(ev, tname):
    """Called by profiler.Frame/record_event on the recording thread."""
    buf = _buf
    if buf is not None:
        buf.append(ev)  # deque.append is atomic under the GIL
        _tnames[ev["tid"]] = tname


def start(buffer_size: int = 65536):
    """Begin capturing spans from all threads into a bounded buffer."""
    global _buf
    with _lock:
        if _buf is None:
            _buf = deque(maxlen=max(1, int(buffer_size)))
        _prof._set_sink(_sink)


def stop():
    global _buf
    with _lock:
        _prof._set_sink(None)
        _buf = None
        _tnames.clear()


def active() -> bool:
    return _buf is not None


def events() -> List[dict]:
    buf = _buf
    return list(buf) if buf is not None else []


def flow_event(name, phase, flow_id):
    """Record one flow event ("s" start / "f" finish) binding a span on
    this thread to its counterpart across a thread or process boundary —
    how a worker's kvstore RPC span links to the server-side handler
    span in the merged fleet trace.  No-op unless tracing is active."""
    buf = _buf
    if buf is None:
        return
    ev = {"name": name, "cat": "flow", "ph": phase, "id": flow_id,
          "ts": time.perf_counter_ns() // 1000, "pid": 0,
          "tid": _prof.trace_tid()}
    if phase == "f":
        ev["bp"] = "e"  # bind to the enclosing slice's end
    buf.append(ev)
    _tnames.setdefault(ev["tid"], threading.current_thread().name)


def span(name, category="telemetry"):
    """A named span on the merged timeline — records whenever the legacy
    profiler is running OR telemetry tracing is active (profiler.Frame
    carries the sink hookup)."""
    return _prof.Frame(name, category)


def merged_trace() -> dict:
    """ONE timeline: legacy profiler events + telemetry spans, deduped
    (a Frame recorded while both were active is the same dict object in
    both buffers), with thread_name/process_name metadata so each thread
    renders as its own named track."""
    prof_events, prof_tnames = _prof._snapshot_events()
    mine = events()
    tnames = dict(prof_tnames)
    tnames.update(_tnames)
    seen = set()
    merged = []
    for ev in prof_events + mine:
        if id(ev) in seen:
            continue
        seen.add(id(ev))
        merged.append(ev)
    # role/rank-qualified track names: dumps from different processes of
    # one job carry identically-named threads (comm-worker-0 exists in
    # every worker), so the process label keeps multi-process merges
    # (tools/trace_merge.py) collision-free and readable
    from .distributed import proc_label

    label = proc_label()
    meta = [{"name": "process_name", "ph": "M", "pid": 0,
             "args": {"name": label}}]
    for tid in sorted(tnames):
        meta.append({"name": "thread_name", "ph": "M", "pid": 0, "tid": tid,
                     "args": {"name": "%s/%s" % (label, tnames[tid])}})
    return {"traceEvents": meta + merged, "displayTimeUnit": "ms"}


def dump_trace(path: str) -> str:
    payload = merged_trace()
    with open(path, "w") as f:
        json.dump(payload, f)
    return path


def validate_trace(payload: dict) -> bool:
    """Assert trace-event-schema validity (the checks chrome://tracing's
    importer actually trips on); raises ValueError on violation."""
    evs = payload.get("traceEvents")
    if not isinstance(evs, list):
        raise ValueError("traceEvents must be a list")
    for ev in evs:
        if not isinstance(ev, dict):
            raise ValueError("trace event is not an object: %r" % (ev,))
        ph = ev.get("ph")
        if not isinstance(ev.get("name"), str) or not isinstance(ph, str):
            raise ValueError("trace event needs string name+ph: %r" % (ev,))
        if ph == "M":
            continue
        for field in ("ts", "pid", "tid"):
            if not isinstance(ev.get(field), (int, float)):
                raise ValueError("event %r missing numeric %s"
                                 % (ev.get("name"), field))
        if ph == "X" and not isinstance(ev.get("dur"), (int, float)):
            raise ValueError("complete event %r missing dur" % ev["name"])
    return True

"""Cluster-wide telemetry: process identity, RPC trace contexts, and
fleet metrics aggregation.

Three small pieces turn the per-process telemetry core into a fleet
view:

* :func:`proc_identity` / :func:`proc_label` — a stable ``(role, rank)``
  for this process derived from the DMLC launch contract
  (``DMLC_ROLE`` / ``DMLC_WORKER_ID`` / ``DMLC_SERVER_ID``), used to
  label trace tracks, postmortems, and aggregated metrics.
* :func:`new_trace_ctx` — a compact trace/span context dict stamped into
  kvstore RPC envelopes so the server-side handler span carries the same
  trace id as the worker-side client span (``tools/trace_merge.py``
  renders the pair as linked flow events across process tracks).
* :class:`FleetAggregator` + :func:`start_pusher` — a stdlib-HTTP
  federation endpoint: every process pushes its Prometheus text
  (``telemetry.render_prometheus()``) to ``MXNET_TELEMETRY_AGG_ADDR``;
  the aggregator relabels each sample with ``role``/``rank`` and serves
  ONE ``/metrics`` page plus derived fleet gauges (min/median/max worker
  step time, sync-round wait skew).

Everything here is off the training hot path: contexts are built only
when telemetry is enabled, and the pusher is a daemon thread.
"""
from __future__ import annotations

import itertools
import json
import os
import re
import statistics
import threading
import time
from typing import Dict, Optional, Tuple

from ..base import env, register_env

__all__ = ["proc_identity", "proc_label", "new_trace_ctx",
           "FleetAggregator", "start_pusher", "stop_pusher", "push_once"]

register_env("MXNET_TELEMETRY_AGG_ADDR", "", str,
             "host:port of the fleet metrics aggregator this process "
             "pushes its Prometheus text to (empty: no pushing). "
             "Exported to every child by tools/launch.py --metrics-port.")
register_env("MXNET_TELEMETRY_AGG_INTERVAL", 2.0, float,
             "Seconds between metrics pushes to the aggregator.")
register_env("MXNET_TELEMETRY_ROLE", "", str,
             "Override for this process's telemetry role label; default "
             "derives from DMLC_ROLE (worker/server) or 'proc'.")


def proc_identity() -> Tuple[str, int]:
    """``(role, rank)`` for this process from the DMLC launch contract.
    Serving replicas and standalone processes (no DMLC role) report as
    ``('proc', pid)`` so concurrent dumps never collide on a name."""
    role = os.environ.get("MXNET_TELEMETRY_ROLE") or \
        os.environ.get("DMLC_ROLE")
    if not role:
        role = "worker" if os.environ.get("DMLC_WORKER_ID") else "proc"
    try:
        if role == "server":
            rank = int(os.environ.get("DMLC_SERVER_ID", "0") or 0)
        elif role == "worker":
            rank = int(os.environ.get("DMLC_WORKER_ID", "0") or 0)
        else:
            rank = os.getpid()
    except ValueError:
        rank = os.getpid()
    return role, rank


def proc_label() -> str:
    """``worker0`` / ``server1`` / ``proc<pid>`` — the process-track name
    in merged traces and the ``<role><rank>`` part of postmortem files."""
    role, rank = proc_identity()
    return "%s%d" % (role, rank)


_ctx_counter = itertools.count(1)


def new_trace_ctx(seed: Optional[str] = None) -> dict:
    """A trace/span context for one RPC: globally unique trace id (the
    originating process label + pid + a counter, or a caller-provided
    seed such as the kvstore client id), plus the origin's role/rank so
    the server can label its handler span with the caller."""
    role, rank = proc_identity()
    if seed is None:
        trace = "%s-%d-%d" % (proc_label(), os.getpid(),
                              next(_ctx_counter))
    else:
        trace = "%s-%d" % (seed, next(_ctx_counter))
    return {"trace": trace, "parent": trace, "role": role, "rank": rank}


# -- fleet metrics aggregation ----------------------------------------------

_SAMPLE_RE = re.compile(
    r"^([A-Za-z_:][A-Za-z0-9_:]*)(\{[^}]*\})?\s+(\S+)\s*$")
_MODEL_RE = re.compile(r'model="([^"]*)"')


def _relabel(text: str, role: str, rank) -> str:
    """Inject ``role``/``rank`` labels into every sample of a Prometheus
    text page (the Registry's LabeledCounter carries only one label
    dimension, so federation labels are applied at the text layer)."""
    extra = 'role="%s",rank="%s"' % (role, rank)
    out = []
    for line in text.splitlines():
        if not line or line.startswith("#"):
            out.append(line)
            continue
        m = _SAMPLE_RE.match(line)
        if m is None:
            out.append(line)
            continue
        name, labels, val = m.groups()
        merged = "{%s,%s}" % (labels[1:-1], extra) if labels \
            else "{%s}" % extra
        out.append("%s%s %s" % (name, merged, val))
    return "\n".join(out)


def _sample_value(text: str, name: str) -> Optional[float]:
    """First sample value of ``name`` (bare or labeled) in a text page."""
    for line in text.splitlines():
        if not line.startswith(name):
            continue
        m = _SAMPLE_RE.match(line)
        if m is not None and m.group(1) == name:
            try:
                return float(m.group(3))
            except ValueError:
                return None
    return None


class FleetAggregator:
    """Federates per-process metrics pages into one Prometheus endpoint.

    HTTP surface (stdlib ``http.server``, same pattern as serving's
    ``serve_http``):

    * ``POST /push?role=R&rank=N`` — a process replaces its latest page.
    * ``GET /metrics`` — every page relabeled with ``role``/``rank``
      plus derived fleet gauges: ``mxtpu_fleet_step_ms{stat=min|median|
      max}`` over the workers' ``mxtpu_step_last_ms`` and
      ``mxtpu_fleet_sync_skew_ms`` (max of the servers'
      ``mxtpu_kvsrv_round_skew_ms``).
    * ``GET /healthz`` — liveness + contributing process count.
    """

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        import http.server

        self._lock = threading.Lock()
        self._pages: Dict[Tuple[str, str], Tuple[str, float]] = {}
        agg = self

        class Handler(http.server.BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # quiet
                pass

            def _reply(self, code, body, ctype="text/plain; version=0.0.4"):
                data = body.encode()
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):
                if self.path.startswith("/metrics"):
                    self._reply(200, agg.render())
                elif self.path.startswith("/healthz"):
                    with agg._lock:
                        n = len(agg._pages)
                    self._reply(200, json.dumps(
                        {"status": "ok", "processes": n}),
                        ctype="application/json")
                else:
                    self._reply(404, "not found\n")

            def do_POST(self):
                if not self.path.startswith("/push"):
                    self._reply(404, "not found\n")
                    return
                from urllib.parse import parse_qs, urlparse

                q = parse_qs(urlparse(self.path).query)
                role = (q.get("role") or ["proc"])[0]
                rank = (q.get("rank") or ["0"])[0]
                n = int(self.headers.get("Content-Length") or 0)
                body = self.rfile.read(n).decode("utf-8", "replace")
                with agg._lock:
                    agg._pages[(role, rank)] = (body, time.time())
                self._reply(200, "ok\n")

        class Server(http.server.ThreadingHTTPServer):
            daemon_threads = True
            allow_reuse_address = True

        self._server = Server((host, port), Handler)
        self.port = self._server.server_address[1]
        self.addr = "%s:%d" % (self._server.server_address[0], self.port)
        self._thread = None

    def render(self) -> str:
        with self._lock:
            pages = dict(self._pages)
        parts = []
        step_ms = []
        skew_ms = []
        models = set()
        for (role, rank), (text, _t) in sorted(pages.items()):
            parts.append(_relabel(text, role, rank))
            models.update(_MODEL_RE.findall(text))
            if role == "worker":
                v = _sample_value(text, "mxtpu_step_last_ms")
                if v:
                    step_ms.append(v)
            if role == "server":
                v = _sample_value(text, "mxtpu_kvsrv_round_skew_ms")
                if v is not None:
                    skew_ms.append(v)
        fleet = ["# TYPE mxtpu_fleet_processes gauge",
                 "mxtpu_fleet_processes %d" % len(pages)]
        if step_ms:
            fleet.append("# TYPE mxtpu_fleet_step_ms gauge")
            for stat, v in (("min", min(step_ms)),
                            ("median", statistics.median(step_ms)),
                            ("max", max(step_ms))):
                fleet.append('mxtpu_fleet_step_ms{stat="%s"} %.6g'
                             % (stat, v))
        if skew_ms:
            fleet.append("# TYPE mxtpu_fleet_sync_skew_ms gauge")
            fleet.append("mxtpu_fleet_sync_skew_ms %.6g" % max(skew_ms))
        if models:
            # distinct model= labels across every contributed page —
            # the platform's per-model cost-attribution sanity signal
            fleet.append("# TYPE mxtpu_fleet_models gauge")
            fleet.append("mxtpu_fleet_models %d" % len(models))
        parts.append("\n".join(fleet))
        return "\n".join(p.rstrip("\n") for p in parts if p) + "\n"

    def processes(self):
        with self._lock:
            return sorted(self._pages)

    def start(self):
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._server.serve_forever, kwargs={"poll_interval":
                                                           0.1},
                name="telemetry-aggregator", daemon=True)
            self._thread.start()
        return self

    def stop(self):
        self._server.shutdown()
        self._server.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None


# -- per-process metrics pusher ---------------------------------------------

_pusher_stop: Optional[threading.Event] = None
_pusher_thread: Optional[threading.Thread] = None


def push_once(addr: Optional[str] = None, timeout: float = 2.0) -> bool:
    """POST this process's current metrics page to the aggregator once.
    Quietly returns False when the aggregator is unreachable — telemetry
    must never take the training job down with it."""
    from urllib import request as _rq

    from . import render_prometheus

    addr = addr or env("MXNET_TELEMETRY_AGG_ADDR", "", str)
    if not addr:
        return False
    role, rank = proc_identity()
    url = "http://%s/push?role=%s&rank=%s" % (addr, role, rank)
    try:
        req = _rq.Request(url, data=render_prometheus().encode(),
                          method="POST")
        with _rq.urlopen(req, timeout=timeout):
            pass
        return True
    except Exception:
        return False


def start_pusher(addr: Optional[str] = None,
                 interval: Optional[float] = None) -> bool:
    """Background daemon pushing this process's metrics page to the
    aggregator every ``MXNET_TELEMETRY_AGG_INTERVAL`` seconds (plus one
    immediate push).  Idempotent; returns whether a pusher is running."""
    global _pusher_stop, _pusher_thread
    addr = addr or env("MXNET_TELEMETRY_AGG_ADDR", "", str)
    if not addr:
        return False
    if _pusher_thread is not None and _pusher_thread.is_alive():
        return True
    if interval is None:
        interval = max(0.05, env("MXNET_TELEMETRY_AGG_INTERVAL", 2.0, float))
    stop = threading.Event()

    def loop():
        push_once(addr)
        while not stop.wait(interval):
            push_once(addr)

    _pusher_stop = stop
    _pusher_thread = threading.Thread(target=loop, name="telemetry-pusher",
                                      daemon=True)
    _pusher_thread.start()
    return True


def stop_pusher():
    global _pusher_stop, _pusher_thread
    if _pusher_stop is not None:
        _pusher_stop.set()
    if _pusher_thread is not None:
        _pusher_thread.join(timeout=2)
    _pusher_stop = None
    _pusher_thread = None

"""StepMonitor — per-step wall time, data-wait, throughput, memory
watermarks, achieved model-MFU, and a recompile detector.

The MFU path is tools/perf_probe.py's introspection hook promoted into the
framework: the fused-step executor records ``_fused_introspect = (fn,
abstract_args)`` on every compile miss, and :func:`lower_and_analyze`
lowers that exact program and reads XLA's own cost analysis — so the flop
count is the compiled program's, not a hand-derived model ("A Learned
Performance Model for TPUs", arxiv 2008.01040, argues this is the number
that matters).  Cost analysis runs once per compiled executable, never on
the per-step path.

The recompile detector fingerprints the batch signature (name, shape,
dtype of every input) feeding the step.  jax.jit retraces silently when a
shape changes — the Python-level jit cache key stays put — so the first
signature per monitor is warmup and any NEW signature after it warns once
with the offending shape diff and bumps ``mxtpu_recompiles_total``.
"""
from __future__ import annotations

import time
import warnings
from typing import Optional

from ..base import env
from ..hlo_analysis import lower_and_analyze, peak_flops

__all__ = ["StepMonitor", "RecompileWarning", "peak_flops",
           "lower_and_analyze", "fused_cost_analysis"]


class RecompileWarning(UserWarning):
    """The fused train step recompiled after warmup (shape change)."""


def fused_cost_analysis(executor):
    """Cost analysis of an executor's last-compiled fused step, or None.

    When the persistent compile cache primed the step it already carries
    XLA's cost analysis (read once from the fresh executable on a miss,
    or from the cache-entry metadata on a hit) — use that and skip the
    re-lower+re-compile entirely, which is what keeps a warm-cache cold
    start at zero compiler invocations even with telemetry on."""
    info = getattr(executor, "_fused_cost_info", None)
    if info and info.get("flops"):
        return info
    fn, abstract = getattr(executor, "_fused_introspect", (None, None))
    _, info = lower_and_analyze(fn, abstract)
    return info


def _batch_signature(data_batch):
    """Hashable fingerprint of the arrays feeding one step."""
    sig = []
    for kind, arrs in (("data", data_batch.data or []),
                       ("label", getattr(data_batch, "label", None) or [])):
        for i, a in enumerate(arrs):
            sig.append(("%s%d" % (kind, i), tuple(a.shape), str(a.dtype)))
    return tuple(sig)


def _sig_diff(old, new):
    """Human-readable shape diff between two batch signatures."""
    old_d = {name: (shape, dt) for name, shape, dt in old}
    new_d = {name: (shape, dt) for name, shape, dt in new}
    parts = []
    for name in sorted(set(old_d) | set(new_d)):
        o, n = old_d.get(name), new_d.get(name)
        if o == n:
            continue
        if o is None:
            parts.append("%s: (new)->%s %s" % (name, n[0], n[1]))
        elif n is None:
            parts.append("%s: %s %s->(gone)" % (name, o[0], o[1]))
        else:
            parts.append("%s: %s->%s" % (
                name, o[0], n[0]) + ("" if o[1] == n[1]
                                     else " [%s->%s]" % (o[1], n[1])))
    return ", ".join(parts)


class StepMonitor:
    """Per-Module training-step telemetry.  Created lazily by Module when
    ``MXNET_TELEMETRY`` is on; the telemetry-off step path never touches
    this class."""

    def __init__(self, telemetry_mod):
        self._tm = telemetry_mod
        reg = telemetry_mod.registry()
        self.c_steps = reg.counter("mxtpu_steps_total",
                                   "Training steps completed.")
        self.c_samples = reg.counter("mxtpu_samples_total",
                                     "Training samples consumed.")
        self.c_data_wait_ms = reg.counter(
            "mxtpu_data_wait_ms_total",
            "Milliseconds the train loop blocked waiting for input batches.")
        self.h_step_ms = reg.histogram("mxtpu_step_time_ms",
                                       "Per-step wall time (ms).",
                                       start=0.25, factor=2.0, count=20)
        self.c_compiles = reg.counter("mxtpu_fused_compiles_total",
                                      "Fused-step executable builds.")
        self.c_recompiles = reg.counter(
            "mxtpu_recompiles_total",
            "Post-warmup step recompiles (shape changes).")
        self.g_last_ms = reg.gauge("mxtpu_step_last_ms",
                                   "Most recent step wall time (ms).")
        self.g_mfu = reg.gauge("mxtpu_step_mfu",
                               "Achieved model FLOP utilization [0,1].")
        self.g_mem_peak = reg.gauge(
            "mxtpu_device_peak_bytes",
            "Device memory high-watermark (bytes), when the backend "
            "reports memory_stats.")
        self._t0 = None
        self._first_t0 = None
        self._last_end = None
        self._steps = 0
        self._samples = 0
        self._step_ms_total = 0.0
        self._data_wait_ms = 0.0
        self._flops_per_step = None
        self._mem_supported = True
        self._sigs = None  # recompile detector state: {sig}, last sig
        self._last_sig = None
        self._mesh_axes = None  # {axis_name: size} when training on a mesh
        telemetry_mod._set_current_monitor(self)

    def note_mesh(self, mesh):
        """Record the device-mesh layout the module trains on (surfaces in
        ``telemetry.summary()`` / BENCH records, next to the byte gauges,
        so a run's parallel layout is part of its record)."""
        if mesh is None:
            self._mesh_axes = None
            return
        self._mesh_axes = {str(name): int(mesh.shape[name])
                           for name in mesh.axis_names}
        self._tm.log_event("mesh", axes=self._mesh_axes)

    # -- per-step hooks (Module.forward_backward / update / fit) ----------
    def note_data_wait(self, seconds):
        ms = seconds * 1e3
        self._data_wait_ms += ms
        self.c_data_wait_ms.inc(ms)

    def note_batch(self, data_batch):
        """Recompile detection: fingerprint this step's input signature."""
        sig = _batch_signature(data_batch)
        if self._sigs is None:  # warmup: the first signature is expected
            self._sigs = {sig}
            self._last_sig = sig
            return
        if sig in self._sigs:
            self._last_sig = sig
            return
        diff = _sig_diff(self._last_sig, sig)
        self._sigs.add(sig)
        self._last_sig = sig
        self.c_recompiles.inc()
        self._tm.log_event("recompile", diff=diff, step=self._steps)
        warnings.warn(
            "training step input shapes changed after warmup — the fused "
            "step recompiles (%s)" % diff, RecompileWarning, stacklevel=3)

    def step_begin(self):
        self._t0 = time.perf_counter()
        if self._first_t0 is None:
            self._first_t0 = self._t0

    def step_end(self, batch_size):
        now = time.perf_counter()
        dur_ms = (now - self._t0) * 1e3 if self._t0 is not None else 0.0
        self._t0 = None
        self._last_end = now
        self._steps += 1
        self._samples += int(batch_size or 0)
        self._step_ms_total += dur_ms
        self.c_steps.inc()
        if batch_size:
            self.c_samples.inc(int(batch_size))
        self.h_step_ms.observe(dur_ms)
        self.g_last_ms.set(dur_ms)
        if self._steps % 10 == 1:
            self._sample_memory()
        self._tm.log_event("step", n=self._steps, dur_ms=round(dur_ms, 3),
                           data_wait_ms=round(self._data_wait_ms, 3))

    def note_compile(self, executor):
        """Compile-miss path: one XLA cost analysis per new executable."""
        self.c_compiles.inc()
        if not env("MXNET_TELEMETRY_MFU", 1, int):
            return
        try:
            info = fused_cost_analysis(executor)
        except Exception:
            info = None
        if info and info.get("flops"):
            self._flops_per_step = float(info["flops"])
            self._tm.log_event("compile", flops=self._flops_per_step,
                               bytes_accessed=info.get("bytes_accessed"))

    # -- derived ----------------------------------------------------------
    def _sample_memory(self):
        if not self._mem_supported:
            return
        try:
            import jax

            stats = jax.local_devices()[0].memory_stats()
        except Exception:
            stats = None
        if not stats:
            self._mem_supported = False
            return
        peak = stats.get("peak_bytes_in_use") or stats.get("bytes_in_use")
        if peak:
            self.g_mem_peak.set_max(int(peak))

    @property
    def data_wait_ms_total(self):
        return self._data_wait_ms

    @property
    def flops_per_step(self):
        return self._flops_per_step

    def avg_step_s(self) -> Optional[float]:
        """Steady-state seconds per step: wall clock over all steps (the
        same quantity perf_probe times), not just host dispatch."""
        if self._steps < 1 or self._first_t0 is None:
            return None
        wall = self._last_end - self._first_t0
        if wall <= 0:
            return None
        return wall / self._steps

    def mfu(self) -> Optional[float]:
        step_s = self.avg_step_s()
        if not step_s or not self._flops_per_step:
            return None
        v = self._flops_per_step / step_s / peak_flops()
        self.g_mfu.set(v)
        return v

    def report(self) -> dict:
        step_s = self.avg_step_s()
        rep = {
            "steps": self._steps,
            "avg_step_ms": round(step_s * 1e3, 3) if step_s else None,
            "dispatch_ms_avg": round(self._step_ms_total / self._steps, 3)
            if self._steps else None,
            "data_wait_ms_total": round(self._data_wait_ms, 3),
            "data_wait_frac": round(
                self._data_wait_ms / (step_s * 1e3 * self._steps), 4)
            if step_s else None,
            "samples_per_sec": round(self._samples / (step_s * self._steps),
                                     1) if step_s and self._samples else None,
            "flops_per_step": self._flops_per_step,
            "mfu": self.mfu(),
            "recompiles": self.c_recompiles.value,
            "device_peak_bytes": self.g_mem_peak.value or None,
        }
        if self._mesh_axes:
            rep["mesh"] = dict(self._mesh_axes)
        mfu = rep["mfu"]
        if mfu is not None:
            rep["mfu"] = round(mfu, 4)
        # cluster health: when a colocated kvstore server flagged slow
        # ranks this process's summary names them (per-rank counts)
        stragglers = self._straggler_counts()
        if stragglers:
            rep["stragglers"] = stragglers
        return rep

    @staticmethod
    def _straggler_counts():
        import mxnet_tpu.telemetry as _tm

        reg = _tm._registry  # only if the global registry already exists
        if reg is None:
            return None
        c = reg.get("mxtpu_kvsrv_stragglers_total")
        if c is None or not getattr(c, "value", 0):
            return None
        return {str(k): v for k, v in c.snapshot().items()}

"""mxnet_tpu.telemetry — unified observability core.

One shared, thread-safe home for the four instruments that grew up
separately (profiler Frame spans, serving Prometheus counters,
kv.comm_stats, perf_probe's XLA cost analysis):

* a metrics :class:`Registry` (counters / gauges / exponential-bucket
  histograms) with a Prometheus text renderer and a JSONL structured-event
  log (:func:`log_event`);
* a span tracer whose spans from ANY thread (Module step, comm-engine
  workers, kvstore-server RPC handlers, the serving batcher) merge with
  the legacy ``profiler.py`` events into ONE Chrome-trace timeline with
  per-thread tracks (:func:`dump_trace`);
* a :class:`StepMonitor` recording per-step wall time, data-wait,
  throughput, device-memory watermarks and achieved model-MFU (XLA cost
  analysis, once per compiled executable);
* a recompile detector warning — with the offending shape diff — when the
  fused step recompiles after warmup.

Cost model: everything is gated by ``MXNET_TELEMETRY``.  Off (the
default), every hook in the hot path is a single module-global bool read —
no locks, no allocations, mirroring ``faults.fire``'s plan-is-None idiom.
Activate with ``MXNET_TELEMETRY=1`` in the environment or
:func:`enable` in-process.
"""
from __future__ import annotations

import os
import threading
import weakref
from typing import Optional

from ..base import env, register_env
from . import distributed, flight_recorder, tracer
from .distributed import (FleetAggregator, proc_identity, proc_label,
                          start_pusher, stop_pusher)
from .registry import (Counter, EventLog, Gauge, Histogram, LabeledCounter,
                       Registry)
from .step_monitor import (RecompileWarning, StepMonitor, fused_cost_analysis,
                           lower_and_analyze, peak_flops)

__all__ = [
    "enabled", "enable", "disable", "dump_dir", "registry", "counter",
    "gauge",
    "histogram", "labeled_counter", "log_event", "events", "events_of",
    "event_log",
    "span", "dump_trace", "merged_trace", "validate_trace",
    "render_prometheus", "register_collector", "summary",
    "current_step_monitor", "Registry", "Counter", "Gauge", "Histogram",
    "LabeledCounter", "EventLog", "StepMonitor", "RecompileWarning",
    "peak_flops", "fused_cost_analysis", "lower_and_analyze",
    "distributed", "flight_recorder", "FleetAggregator", "proc_identity",
    "proc_label", "start_pusher", "stop_pusher",
]

register_env("MXNET_TELEMETRY", 0, int,
             "Master switch for the telemetry subsystem (metrics registry, "
             "span capture, StepMonitor, recompile detector). Off: every "
             "hook is one global bool read.")
register_env("MXNET_TELEMETRY_TRACE", 1, int,
             "With telemetry on, capture Frame spans from all threads into "
             "the merged Chrome trace even when the legacy profiler is "
             "stopped (0 keeps only the profiler-run capture path).")
register_env("MXNET_TELEMETRY_TRACE_BUFFER", 65536, int,
             "Max spans kept in the telemetry trace ring buffer.")
register_env("MXNET_TELEMETRY_DIR", "", str,
             "Directory for the JSONL structured-event log "
             "(events.jsonl); empty keeps events in memory only.")
register_env("MXNET_TELEMETRY_DUMP_DIR", "", str,
             "Directory for telemetry artifacts (exit-time trace-*.json, "
             "flight-recorder postmortems when their own dirs are unset); "
             "empty uses <tmpdir>/mxnet_tpu-artifacts — never the cwd.")
register_env("MXNET_TELEMETRY_MFU", 1, int,
             "Run XLA cost analysis once per compiled fused step to "
             "derive achieved MFU (0 skips the per-compile analysis).")
register_env("MXNET_TELEMETRY_PEAK_FLOPS", 0.0, float,
             "MFU denominator in FLOP/s; 0 uses the TPU v5e bf16 peak "
             "(197e12).")

# the single hot-path gate: plain module-global read, no locks
_ENABLED = False
_lock = threading.Lock()
_registry: Optional[Registry] = None
_event_log: Optional[EventLog] = None
_collectors = []  # weakrefs to objects exposing render_prometheus()
_current_monitor = None  # weakref to the most recent StepMonitor

span = tracer.span
merged_trace = tracer.merged_trace
validate_trace = tracer.validate_trace
dump_trace = tracer.dump_trace


def enabled() -> bool:
    return _ENABLED


def dump_dir() -> str:
    """Where telemetry artifacts (traces, postmortems without an explicit
    dir) land: ``MXNET_TELEMETRY_DUMP_DIR``, defaulting to a per-tmpdir
    artifacts directory.  Deliberately NEVER the cwd — test and bench
    runs must not litter the working tree."""
    d = env("MXNET_TELEMETRY_DUMP_DIR", "", str)
    if not d:
        import tempfile

        d = os.path.join(tempfile.gettempdir(), "mxnet_tpu-artifacts")
    return d


def registry() -> Registry:
    """The process-global metrics registry (created on first use)."""
    global _registry
    if _registry is None:
        with _lock:
            if _registry is None:
                _registry = Registry()
    return _registry


def counter(name, doc="") -> Counter:
    return registry().counter(name, doc)


def gauge(name, doc="", fn=None) -> Gauge:
    return registry().gauge(name, doc, fn)


def histogram(name, doc="", start=0.5, factor=2.0, count=16) -> Histogram:
    return registry().histogram(name, doc, start, factor, count)


def labeled_counter(name, label, doc="") -> LabeledCounter:
    return registry().labeled_counter(name, label, doc)


def event_log() -> EventLog:
    global _event_log
    if _event_log is None:
        with _lock:
            if _event_log is None:
                d = env("MXNET_TELEMETRY_DIR", "", str)
                path = None
                if d:
                    os.makedirs(d, exist_ok=True)
                    path = os.path.join(d, "events.jsonl")
                _event_log = EventLog(path)
    return _event_log


def log_event(kind, **fields):
    """Append one structured event (no-op while telemetry is off)."""
    if not _ENABLED:
        return None
    return event_log().emit(kind, **fields)


def events(n=None):
    return event_log().tail(n) if _event_log is not None else []


def events_of(kind, n=None):
    """The tail of the structured-event log filtered to one ``kind`` —
    what chaos scenarios and tests assert platform transitions against
    (e.g. ``platform_domain_health``, ``platform_brownout``)."""
    out = [e for e in events() if e.get("kind") == kind]
    return out if n is None else out[-int(n):]


_atexit_hooked = False


def _atexit_flush():
    """Process-exit flush for cluster observability: land one final
    metrics push on the fleet aggregator (short-lived workers would
    otherwise miss the last interval) and, with MXNET_TELEMETRY_DIR set,
    dump this process's trace to ``trace-<role><rank>.json`` so
    ``tools/trace_merge.py`` can stitch the fleet timeline."""
    if not _ENABLED:
        return
    distributed.push_once()
    # trace routing: an explicit MXNET_TELEMETRY_DIR keeps its contract
    # (trace_merge stitches from there); otherwise traces go to the
    # artifacts dump dir — never the cwd
    d = env("MXNET_TELEMETRY_DIR", "", str) or dump_dir()
    if tracer.active():
        try:
            os.makedirs(d, exist_ok=True)
            dump_trace(os.path.join(
                d, "trace-%s.json" % distributed.proc_label()))
        except Exception:
            pass


def enable(trace: Optional[bool] = None) -> None:
    """Turn telemetry on in-process (the env-var path calls this at
    import).  ``trace`` overrides MXNET_TELEMETRY_TRACE."""
    global _ENABLED, _atexit_hooked
    with _lock:
        _ENABLED = True
    if trace is None:
        trace = bool(env("MXNET_TELEMETRY_TRACE", 1, int))
    if trace:
        tracer.start(env("MXNET_TELEMETRY_TRACE_BUFFER", 65536, int))
    # cluster-wide pieces: metrics pusher (only when an aggregator
    # address is configured), crash flight recorder, exit-time flush
    distributed.start_pusher()
    flight_recorder.install_excepthooks()
    if not _atexit_hooked:
        import atexit

        atexit.register(_atexit_flush)
        _atexit_hooked = True


def disable() -> None:
    global _ENABLED, _event_log
    with _lock:
        _ENABLED = False
    tracer.stop()
    distributed.stop_pusher()
    flight_recorder.uninstall_excepthooks()
    if _event_log is not None:
        _event_log.close()
        _event_log = None


def _reset_for_tests() -> None:
    """Drop all global state (registry contents, collectors, monitors)."""
    import sys

    global _registry, _event_log, _current_monitor
    disable()
    with _lock:
        _registry = None
        _event_log = None
        _current_monitor = None
        del _collectors[:]
    # instrumented modules cache registry handles lazily; stale handles
    # would keep writing to the dropped registry
    for modname, attr in (("mxnet_tpu.io", "_PREFETCH_TELEM"),
                          ("mxnet_tpu.kvstore_server", "_TELEM"),
                          ("mxnet_tpu.compile_cache", "_instruments"),
                          ("mxnet_tpu.autotune", "_instruments")):
        m = sys.modules.get(modname)
        if m is not None:
            setattr(m, attr, None)


def _set_current_monitor(mon) -> None:
    global _current_monitor
    _current_monitor = weakref.ref(mon)


def current_step_monitor() -> Optional[StepMonitor]:
    ref = _current_monitor
    return ref() if ref is not None else None


def register_collector(obj) -> None:
    """Include ``obj.render_prometheus()`` in the global metrics render —
    how per-object registries (serving servers, async kvstores) surface
    their series without sharing counters across instances.  Held by
    weakref: dead collectors drop out on the next render."""
    with _lock:
        _collectors.append(weakref.ref(obj))


def render_prometheus() -> str:
    """Prometheus text exposition: global registry + live collectors."""
    parts = [registry().render_prometheus()]
    with _lock:
        refs = list(_collectors)
    alive = []
    for ref in refs:
        obj = ref()
        if obj is None:
            continue
        alive.append(ref)
        try:
            parts.append(obj.render_prometheus())
        except Exception:
            pass
    with _lock:
        _collectors[:] = alive
    return "".join(p if p.endswith("\n") or not p else p + "\n"
                   for p in parts if p)


def summary() -> dict:
    """Compact run summary for embedding (bench.py BENCH json): non-zero
    counters/gauges from the global registry plus the active StepMonitor
    report."""
    out = {}
    if _registry is not None:
        flat = {}
        for name, val in _registry.snapshot().items():
            if isinstance(val, dict):
                n = val.get("count")
                if n:
                    flat[name] = {"count": n,
                                  "sum": round(val.get("sum", 0.0), 3)}
            elif val:
                flat[name] = round(val, 3) if isinstance(val, float) else val
        if flat:
            out["counters"] = flat
    mon = current_step_monitor()
    if mon is not None:
        out["step"] = mon.report()
    if _event_log is not None and _event_log.path:
        out["events_jsonl"] = _event_log.path
    return out


# env activation at import: a process launched with MXNET_TELEMETRY=1 is
# instrumented from its very first step
if env("MXNET_TELEMETRY", 0, int):
    enable()

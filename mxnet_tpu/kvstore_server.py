"""Host-side parameter service — the ``dist_async`` control/data plane.

TPU-native stance (SURVEY.md §2.4, §5.8): synchronous data parallelism needs
no server — gradients are ``psum``'d inside the jitted step over ICI. What a
server still buys is the reference's *asynchronous* PS semantics
(/root/reference/src/kvstore/kvstore_dist_server.h:87-260: updater runs on
every push immediately, workers never wait for each other) plus the
coordination plane (barriers, optimizer shipping, cooperative stop —
kSyncMode/kStopServer commands, kvstore_dist_server.h:121-134). This module
provides both over DCN-style TCP with length-prefixed pickles replacing
ps-lite/ZeroMQ.

Bootstrap parity with python/mxnet/kvstore_server.py:11-58: importing
mxnet_tpu in a process whose ``DMLC_ROLE=server`` starts the server loop and
exits when a stop command arrives.

.. warning:: **Trust model** — same as the reference's ps-lite: the wire
   format is unauthenticated length-prefixed pickles, so any peer that can
   connect to the server port gets arbitrary code execution in the server
   process.  Deploy only on a trusted, isolated network (the training
   cluster's fabric).  The default bind address is 127.0.0.1; setting
   ``DMLC_PS_ROOT_URI`` to a non-loopback address widens exposure to that
   interface — do so only behind a network boundary you control.
"""
from __future__ import annotations

import logging
import os
import pickle
import random
import re
import socket
import socketserver
import struct
import threading
import time
import uuid
import zlib
from collections import OrderedDict
from typing import Dict, Optional

import numpy as np

# bound at module import (on the importing thread) — request-handler threads
# must NOT run `from . import ...`: under the DMLC_ROLE=server bootstrap the
# main thread is still inside the package import and holds the import lock,
# so a handler-side relative import deadlocks the whole server
from . import faults
from . import ndarray as nd
from . import optimizer as opt
from . import profiler as _prof
from . import telemetry as _telemetry
from .base import MXNetError
from .base import env as _env
from .base import register_env
from .sparse.array import row_merge
from .telemetry import tracer

__all__ = ["KVStoreServer", "start_server", "ServerClient",
           "KVStoreConnectionError", "NonFiniteGradientError",
           "_init_kvstore_server_module"]

register_env("MXNET_KVSTORE_RETRY_MAX", 10, int,
             "Max reconnect/replay attempts per kvstore client RPC.")
register_env("MXNET_KVSTORE_RETRY_DEADLINE", 0, float,
             "Overall wall-clock cap in seconds on a client RPC's "
             "reconnect/replay loop; 0 disables.  Once exceeded the RPC "
             "fails with KVStoreConnectionError instead of burning the "
             "remaining per-attempt budget (an evicted worker fails fast).")
register_env("MXNET_KVSTORE_HEARTBEAT_TIMEOUT", 60, float,
             "Seconds of heartbeat silence before a rank counts as dead — "
             "the shared default for the dead_nodes RPC and the barrier "
             "dead-peer release.")
register_env("MXNET_KVSTORE_EVICT_TIMEOUT", 0, float,
             "Elastic membership: seconds of heartbeat silence before a "
             "JOINED rank is evicted from the live set (its partial merge "
             "contributions discarded, barriers and sync rounds re-formed "
             "around the survivors).  0 disables eviction.")
register_env("MXNET_KVSTORE_RETRY_INITIAL_MS", 50, float,
             "First retry backoff in ms (doubles per attempt).")
register_env("MXNET_KVSTORE_RETRY_MAX_MS", 2000, float,
             "Backoff ceiling in ms.")
register_env("MXNET_KVSTORE_RETRY_JITTER", 0.2, float,
             "Multiplicative backoff jitter fraction (decorrelates a "
             "worker fleet hammering a restarting server).")
register_env("MXNET_KVSTORE_SNAPSHOT_PATH", "", str,
             "Durable snapshot file for the kvstore server; empty "
             "disables journaling.")
register_env("MXNET_KVSTORE_SNAPSHOT_INTERVAL", 30, float,
             "Seconds between periodic server snapshots; <= 0 snapshots "
             "only on demand and clean stop.")
register_env("MXNET_KVSTORE_DEDUP_WINDOW", 4096, int,
             "Completed idempotency records kept per client for replay "
             "matching on the pipelined transport.")
register_env("MXNET_TELEMETRY_STRAGGLER_MULT", 4.0, float,
             "Flag a rank as a straggler when its sync-round merge "
             "latency exceeds this multiple of the round median "
             "(<= 0 disables detection).")
register_env("MXNET_TELEMETRY_STRAGGLER_MIN_MS", 50.0, float,
             "Minimum absolute sync-round latency (ms) before a rank can "
             "be flagged as a straggler — suppresses noise on fast rounds.")
register_env("MXNET_KVSTORE_REJECT_NONFINITE", 1, int,
             "Server-side numeric containment: reject dense/sparse "
             "gradient pushes carrying NaN/Inf with a typed NACK instead "
             "of merging them into the shared parameter plane.  0 "
             "disables the scan.")
register_env("MXNET_KVSTORE_NACK_LIMIT", 0, int,
             "Non-finite push rejections tolerated per rank before the "
             "server flags it as poisoned and evicts it from the elastic "
             "membership (sync rounds re-form around the survivors).  0 "
             "never evicts — pushes are still NACKed.")


# -- retry/backoff knobs (docs/how_to/fault_tolerance.md) -------------------
# A worker-side RPC that hits a dead connection reconnects with exponential
# backoff + jitter and REPLAYS the request under the same idempotency token;
# the server deduplicates, so a push whose ACK was lost is applied exactly
# once (the reference's ps-lite resender, ps/internal/van.h, solved the same
# dropped-ACK double-apply).
def _retry_conf():
    return {
        "retries": int(os.environ.get("MXNET_KVSTORE_RETRY_MAX", "10")),
        "initial": float(os.environ.get("MXNET_KVSTORE_RETRY_INITIAL_MS",
                                        "50")) / 1e3,
        "cap": float(os.environ.get("MXNET_KVSTORE_RETRY_MAX_MS",
                                    "2000")) / 1e3,
        "jitter": float(os.environ.get("MXNET_KVSTORE_RETRY_JITTER", "0.2")),
        "deadline": float(os.environ.get("MXNET_KVSTORE_RETRY_DEADLINE",
                                         "0")),
    }


def _hb_timeout_default():
    """The ONE heartbeat-staleness default shared by the ``dead_nodes``
    RPC and the barrier dead-peer release, so eviction and barrier-abort
    agree on who is dead.  ``MXNET_KVSTORE_DEAD_TIMEOUT`` is honored as a
    legacy alias."""
    v = os.environ.get("MXNET_KVSTORE_HEARTBEAT_TIMEOUT")
    if v is None:
        v = os.environ.get("MXNET_KVSTORE_DEAD_TIMEOUT")
    return float(v) if v is not None else 60.0


class KVStoreConnectionError(ConnectionError):
    """A kvstore client RPC gave up: the per-attempt retry budget or the
    ``MXNET_KVSTORE_RETRY_DEADLINE`` wall-clock cap was exhausted.
    Subclasses ConnectionError, so existing transport handlers still
    catch it; callers that care (an evicted worker deciding to exit) can
    match the type."""


class NonFiniteGradientError(MXNetError):
    """The server NACKed this client's gradient push: it carried NaN/Inf
    values and was never applied to the parameter plane.  Deliberately
    NOT a ConnectionError — retrying the same payload cannot succeed;
    the worker should drop the batch (or let its guardian respond)."""


def _backoff_sleep(attempt, conf):
    """Exponential backoff with multiplicative jitter (decorrelates a
    worker fleet hammering a restarting server)."""
    base = min(conf["cap"], conf["initial"] * (2 ** attempt))
    time.sleep(base * (1.0 + conf["jitter"] * random.random()))

# wire: 1 version byte, <payload_len, n_bufs> header, n_bufs buffer
# lengths, pickled metadata, then the raw array buffers OUT OF BAND
# (pickle protocol 5 buffer_callback) — array bytes go straight from the
# caller's memory to per-buffer sendall with no pickle-side copy; the copy
# was the measured bottleneck of the dist_async plane at exactly the
# big-key sizes the range split targets (PERF.md table).  The leading
# version byte turns a mixed-version worker/server pair into a clear
# error instead of a confusing unpickling failure mid-stream.
# v2 adds the sparse plane envelopes (init_table / push_rows / pull_rows /
# table_info / set_sparse_optimizer); dense command tuples are unchanged,
# but a v1 peer would mis-handle the new commands so the byte is bumped.
_WIRE_VERSION = 2
_HDR = struct.Struct("<QI")
_LEN = struct.Struct("<Q")


def _nodelay(sock):
    """Small request/reply frames: without TCP_NODELAY the Nagle +
    delayed-ACK interaction adds ~40ms to every per-key round trip."""
    try:
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    except OSError:
        pass
    return sock


def _send_msg(sock, obj, op=None):
    if op is not None:
        faults.fire(op)
    bufs = []
    payload = pickle.dumps(obj, protocol=5, buffer_callback=bufs.append)
    try:
        raws = [b.raw() for b in bufs]
    except BufferError:
        # non-contiguous ndarray reached the wire (sliced/transposed
        # views can't expose a flat buffer): fall back to in-band
        # protocol-5 pickling, which copies into contiguous form
        payload = pickle.dumps(obj, protocol=5)
        raws = []
    head = bytes([_WIRE_VERSION]) + _HDR.pack(len(payload), len(raws))
    lens = b"".join(_LEN.pack(r.nbytes) for r in raws)
    sock.sendall(head + lens + payload)  # small metadata: one copy
    for r in raws:                       # array bytes: zero-copy sendall
        sock.sendall(r)


def _recv_exact(sock, n):
    buf = bytearray(n)
    view = memoryview(buf)
    got = 0
    while got < n:
        r = sock.recv_into(view[got:], n - got)
        if not r:
            raise ConnectionError("peer closed")
        got += r
    return buf


def _recv_msg(sock, op=None):
    if op is not None:
        faults.fire(op)
    ver = _recv_exact(sock, 1)[0]
    if ver != _WIRE_VERSION:
        raise ConnectionError(
            "kvstore wire version mismatch: peer sent %d, this process "
            "speaks %d — worker and server run different mxnet_tpu "
            "builds" % (ver, _WIRE_VERSION))
    n, nbuf = _HDR.unpack(_recv_exact(sock, _HDR.size))
    lens = []
    if nbuf:
        raw = _recv_exact(sock, _LEN.size * nbuf)
        lens = [_LEN.unpack_from(raw, i * _LEN.size)[0]
                for i in range(nbuf)]
    payload = _recv_exact(sock, n)
    bufs = [_recv_exact(sock, ln) for ln in lens]
    return pickle.loads(payload, buffers=bufs)


# -- telemetry instruments (global registry; created on first enabled use)
_TELEM = None


def _srv_metrics():
    global _TELEM
    if _TELEM is None:
        reg = _telemetry.registry()
        _TELEM = {
            "rpc_ms": reg.histogram(
                "mxtpu_kvsrv_rpc_ms",
                "Server-side RPC dispatch latency (ms).",
                start=0.05, factor=4.0, count=10),
            "rpc_total": reg.labeled_counter(
                "mxtpu_kvsrv_rpc_total", "cmd", "RPCs dispatched."),
            "dedup": reg.counter(
                "mxtpu_kvsrv_dedup_replays_total",
                "Idempotency replays answered from the dedup window."),
            "snap_ms": reg.gauge(
                "mxtpu_kvsrv_snapshot_ms",
                "Duration of the last durable snapshot (ms)."),
            "snaps": reg.counter(
                "mxtpu_kvsrv_snapshots_total", "Durable snapshots written."),
            "members": reg.gauge(
                "mxtpu_kvsrv_members",
                "Live ranks in the elastic membership table."),
            "joins": reg.counter(
                "mxtpu_kvsrv_joins_total", "Membership join RPCs admitted."),
            "leaves": reg.counter(
                "mxtpu_kvsrv_leaves_total", "Graceful membership leaves."),
            "evictions": reg.counter(
                "mxtpu_kvsrv_evictions_total",
                "Ranks evicted for heartbeat staleness (or by the evict "
                "RPC)."),
            "stragglers": reg.labeled_counter(
                "mxtpu_kvsrv_stragglers_total", "rank",
                "Sync-round contributions slower than "
                "MXNET_TELEMETRY_STRAGGLER_MULT x the round median."),
            "round_skew": reg.gauge(
                "mxtpu_kvsrv_round_skew_ms",
                "Last sync-merge round's max-minus-median contribution "
                "wait (ms) — the fleet aggregator's skew source."),
            "sparse_pushed": reg.counter(
                "mxtpu_kvsrv_sparse_rows_pushed_total",
                "Row-sparse gradient rows received via push_rows."),
            "sparse_pulled": reg.counter(
                "mxtpu_kvsrv_sparse_rows_pulled_total",
                "Embedding-table rows served via pull_rows."),
            "rejected": reg.labeled_counter(
                "mxtpu_kvsrv_rejected_pushes_total", "rank",
                "Gradient pushes NACKed for carrying non-finite values "
                "(numeric containment — never applied to the store)."),
            # per-command latency histograms (incl. the membership RPCs
            # join/leave/evict/membership and the sparse push_rows/
            # pull_rows plane) and per-rank round-wait histograms, created
            # lazily as commands/ranks appear; per-table row/byte gauges
            # likewise appear as tables are initialized
            "rpc_cmd_ms": {},
            "rank_wait_ms": {},
            "table_gauges": {},
        }
    return _TELEM


def _cmd_hist(m, cmd):
    h = m["rpc_cmd_ms"].get(cmd)
    if h is None:
        h = _telemetry.registry().histogram(
            "mxtpu_kvsrv_rpc_%s_ms" % cmd,
            "Server-side %r RPC dispatch latency (ms)." % cmd,
            start=0.05, factor=4.0, count=10)
        m["rpc_cmd_ms"][cmd] = h
    return h


def _rank_wait_hist(m, rank):
    h = m["rank_wait_ms"].get(rank)
    if h is None:
        h = _telemetry.registry().histogram(
            "mxtpu_kvsrv_round_wait_rank%s_ms" % rank,
            "Rank %s's sync-merge contribution wait behind the round's "
            "first arrival (ms)." % rank,
            start=0.5, factor=4.0, count=10)
        m["rank_wait_ms"][rank] = h
    return h


def _register_table_gauges(server, key):
    """Per-key callback gauges over a sharded table's local shard: row
    count and resident bytes.  Callback-style so the scrape always sees
    the live dict — no per-push bookkeeping on the hot path."""
    if not _telemetry.enabled():
        return
    m = _srv_metrics()
    if key in m["table_gauges"]:
        return
    safe = re.sub(r"[^A-Za-z0-9_]", "_", str(key))
    reg = _telemetry.registry()

    def _rows(server=server, key=key):
        tbl = server.tables.get(key)
        return len(tbl["rows"]) if tbl else 0

    def _bytes(server=server, key=key):
        tbl = server.tables.get(key)
        if not tbl:
            return 0
        return sum(v.nbytes for v in tbl["rows"].values()) + \
            sum(v.nbytes for v in tbl["state"].values())

    m["table_gauges"][key] = (
        reg.gauge("mxtpu_kvsrv_table_rows_%s" % safe,
                  "Rows resident in this server's shard of table %r."
                  % (key,), fn=_rows),
        reg.gauge("mxtpu_kvsrv_table_bytes_%s" % safe,
                  "Bytes resident in this server's shard of table %r "
                  "(rows + optimizer state)." % (key,), fn=_bytes),
    )


class KVStoreServer:
    """Async parameter server: per-key store + updater applied on every
    push (async mode, kvstore_dist_server.h:198-206) or after all workers'
    pushes merge (sync mode, :164-179).

    Crash tolerance (docs/how_to/fault_tolerance.md):

    * requests may arrive wrapped in an idempotency envelope
      ``("req", client_id, seq, inner)``; the server records the last
      applied (seq, reply) per client and REPLAYS the recorded reply for a
      retried seq instead of re-dispatching — a push whose ACK was lost on
      the wire is applied exactly once.
    * with ``snapshot_path`` set (or ``MXNET_KVSTORE_SNAPSHOT_PATH``), the
      full server state — store, updater (with live momentum), barrier
      generation, sync-merge rounds, dedup records — is journaled to an
      atomic CRC-checked snapshot every ``snapshot_interval`` seconds, on
      clean stop, and on the ``snapshot`` command; a restarted server
      restores it and re-admits reconnecting workers mid-barrier.

    Elastic membership (docs/how_to/fault_tolerance.md §elasticity): once
    workers ``join`` the live-rank table, barriers and sync-merge rounds
    are sized by the CURRENT membership generation instead of the static
    ``num_workers`` — a graceful ``leave`` (preemption) or a stale-
    heartbeat eviction (``MXNET_KVSTORE_EVICT_TIMEOUT``, kill -9) shrinks
    the job and renormalizes gradient averaging by the live count; a
    mid-run ``join`` grows it back.  Membership is journaled into the
    snapshots (v3) so restarts preserve the live set.
    """

    def __init__(self, host="127.0.0.1", port=0, num_workers=1,
                 sync_mode=False, snapshot_path=None, snapshot_interval=None,
                 evict_timeout=None):
        self.num_workers = num_workers
        self.sync_mode = sync_mode
        self.store: Dict[object, np.ndarray] = {}
        self.updater = None
        self._lock = threading.Lock()  # single-threaded-executor parity
        self._barrier_ranks = set()
        self._barrier_gen = 0
        self._barrier_cv = threading.Condition()
        self._merge: Dict[object, list] = {}
        # sparse parameter plane (docs/how_to/sparse.md): per-key sharded
        # embedding tables.  Each entry is {"meta": {...}, "rows":
        # {row_id: ndarray}, "state": {row_id: ndarray}} — this server
        # holds ONLY the rows with row_id % num_servers == server_index,
        # materialized lazily on first touch, with the server-placed
        # optimizer state beside them.  _sparse_merge mirrors _merge for
        # sync-mode row-sparse rounds: each round is {rank: (ids, vals)}.
        self.tables: Dict[object, dict] = {}
        self.sparse_updater = None
        self._sparse_merge: Dict[object, list] = {}
        self.applied_row_pushes = 0  # distinct (non-replayed) push_rows
        # telemetry-only shadow of _merge: per-round {rank: arrival ts}
        # for straggler detection.  A PARALLEL structure because snapshot
        # v3 pickles the _merge round dicts directly — timestamps must
        # never leak into the durable format (and are meaningless across
        # a restart's monotonic clock anyway).
        self._merge_ts: Dict[object, list] = {}
        self._stop = threading.Event()
        # elastic membership (docs/how_to/fault_tolerance.md §elasticity):
        # the live-rank set replaces the static num_workers in barriers
        # and sync-merge rounds once workers join; _mgen is a monotonic
        # generation bumped on every join/leave/evict so clients can
        # detect membership changes.  Lock ordering: membership is
        # guarded by _lock, mutated only while holding _barrier_cv first
        # (the established _barrier_cv -> _lock order), so barrier
        # release and merge-round flushing observe one consistent set.
        self._members: set = set()
        self._mgen = 0
        self._evict_timeout = float(
            evict_timeout if evict_timeout is not None
            else os.environ.get("MXNET_KVSTORE_EVICT_TIMEOUT", "0"))
        # liveness: rank -> monotonic time of last heartbeat (reference:
        # ps::Postoffice node tracking behind GetDeadNodes,
        # kvstore_dist.h:151-160)
        self._heartbeats: Dict[int, float] = {}
        # idempotency records: client_id -> {"floor", "window"} where
        # window is an OrderedDict seq -> {"done", "reply"}.  The pipelined
        # client keeps MANY requests in flight, so dedup must remember a
        # window of completed seqs (MXNET_KVSTORE_DEDUP_WINDOW), not just
        # the newest; "floor" rises as done entries are evicted, and any
        # retried seq at or below it is definitively stale.
        self._dedup: Dict[str, dict] = {}
        self._dedup_cv = threading.Condition()
        self.applied_pushes = 0  # distinct (non-replayed) push applications
        # numeric containment: non-finite pushes NACKed, total and per
        # rank (chaos scenarios assert on these without telemetry)
        self.rejected_pushes = 0
        self.rejects_by_rank: Dict[int, int] = {}
        # contribution-count histogram of flushed sync-merge rounds
        # ({3: 40, 2: 7} = 40 full rounds, 7 renormalized 2-worker rounds);
        # chaos tests read it to prove shrink/grow actually changed round
        # composition rather than stalling the job
        self.round_sizes: Dict[int, int] = {}
        self.restored = False
        self.snapshot_path = snapshot_path if snapshot_path is not None \
            else (os.environ.get("MXNET_KVSTORE_SNAPSHOT_PATH") or None)
        self._snap_interval = float(
            snapshot_interval if snapshot_interval is not None
            else os.environ.get("MXNET_KVSTORE_SNAPSHOT_INTERVAL", "30"))
        if self.snapshot_path:
            self.restored = self._restore_snapshot()
        server_self = self

        class Handler(socketserver.BaseRequestHandler):
            def setup(self):
                _nodelay(self.request)

            def handle(self):
                # pipelined connections: enveloped requests are answered
                # as ("rsp", seq, reply) so the client's reader thread can
                # match replies to in-flight tokens out of order; raw
                # (unenveloped) messages keep the legacy lockstep reply.
                # The send lock serializes writers: the main loop and any
                # parked barrier threads share this socket.
                send_lock = threading.Lock()
                sock = self.request

                def respond(wrapped, seq, reply):
                    out = ("rsp", seq, reply) if wrapped else reply
                    with send_lock:
                        _send_msg(sock, out, op="kv.server.send")

                try:
                    while True:
                        msg = _recv_msg(sock, op="kv.server.recv")
                        if isinstance(msg, tuple) and msg and \
                                msg[0] == "req":
                            # tolerate the 5-element envelope: slot 4 is
                            # the optional distributed-trace context a
                            # telemetry-enabled client stamps on
                            cid, seq, inner = msg[1], msg[2], msg[3]
                            ctx = msg[4] if len(msg) > 4 else None
                            wrapped = True
                        else:
                            cid, seq, inner, ctx = None, None, msg, None
                            wrapped = False
                        if wrapped and inner[0] == "barrier":
                            # a barrier parks for up to minutes; serve it
                            # off-thread so pipelined pushes/pulls behind
                            # it keep flowing on this connection
                            def run(cid=cid, seq=seq, inner=inner,
                                    ctx=ctx):
                                try:
                                    respond(True, seq, server_self.
                                            _serve_one(cid, seq, inner,
                                                       ctx))
                                except (ConnectionError, OSError):
                                    pass

                            threading.Thread(target=run, daemon=True).start()
                            continue
                        reply = server_self._serve_one(cid, seq, inner, ctx)
                        respond(wrapped, seq, reply)
                        if inner[0] == "stop":
                            break
                except (ConnectionError, OSError):
                    pass

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = Server((host, port), Handler)
        self.addr = self._server.server_address
        self._snap_thread = None
        if self.snapshot_path and self._snap_interval > 0:
            self._snap_thread = threading.Thread(
                target=self._snapshot_loop, name="kvsrv-snapshot",
                daemon=True)
            self._snap_thread.start()
        self._evict_thread = None
        if self._evict_timeout > 0:
            self._evict_thread = threading.Thread(
                target=self._evictor_loop, name="kvsrv-evictor", daemon=True)
            self._evict_thread.start()

    # -- idempotent request admission --------------------------------------
    def _serve_one(self, cid, seq, msg, ctx=None):
        """Dispatch one request, deduplicating retries by (cid, seq).  A
        replayed token returns the recorded reply (waiting out a still-
        running original, e.g. a barrier whose connection died while
        parked) without re-running the command.  Pipelined clients keep
        many tokens in flight, so records live in a per-client window of
        completed seqs rather than a single newest-seq slot."""
        if cid is None:
            return self._dispatch_timed(msg, ctx)
        with self._dedup_cv:
            rec = self._dedup.setdefault(
                cid, {"floor": 0, "window": OrderedDict()})
            ent = rec["window"].get(seq)
            if ent is not None:
                if _telemetry.enabled():
                    _srv_metrics()["dedup"].inc()
                while not ent["done"]:
                    self._dedup_cv.wait(0.1)
                return ent["reply"]
            if seq <= rec["floor"]:
                return ("err", "stale request token %s <= %s (client %s)"
                        % (seq, rec["floor"], cid))
            ent = {"done": False, "reply": None}
            rec["window"][seq] = ent
        reply = self._dispatch_timed(msg, ctx)
        with self._dedup_cv:
            if rec["window"].get(seq) is ent:
                ent["reply"] = reply
                ent["done"] = True
                self._evict_dedup_locked(rec)
                self._dedup_cv.notify_all()
        return reply

    @staticmethod
    def _evict_dedup_locked(rec):
        """Trim a client's dedup window to MXNET_KVSTORE_DEDUP_WINDOW done
        entries, raising the stale floor past what falls off.  A pending
        entry stops eviction — its token must stay replayable."""
        limit = max(1, int(os.environ.get("MXNET_KVSTORE_DEDUP_WINDOW",
                                          "4096")))
        win = rec["window"]
        while len(win) > limit:
            s, e = next(iter(win.items()))
            if not e["done"]:
                break
            del win[s]
            if s > rec["floor"]:
                rec["floor"] = s

    def _dispatch_safe(self, msg):
        try:
            return self._dispatch(msg)
        except Exception as e:  # keep serving; tell the client
            return ("err", "%s: %s" % (type(e).__name__, e))

    def _dispatch_timed(self, msg, ctx=None):
        """_dispatch_safe plus telemetry: RPC latency histograms (overall
        AND per command — the membership RPCs join/leave/evict/membership
        get their own series), per-command counter, and a span on the
        merged trace carrying the envelope's distributed trace context so
        the handler span shares the worker-side span's trace id.  Off
        path: one bool read, then straight dispatch."""
        if not _telemetry.enabled():
            return self._dispatch_safe(msg)
        cmd = msg[0] if isinstance(msg, tuple) and msg else "?"
        m = _srv_metrics()
        args = None
        if ctx:
            trace = ctx.get("trace")
            args = {"trace": trace,
                    "src": "%s%s" % (ctx.get("role", "?"),
                                     ctx.get("rank", "?"))}
            if trace is not None:
                # finish the flow the client started: the merged fleet
                # trace draws the arrow worker span -> this handler span
                tracer.flow_event("kv.rpc", "f", trace)
        t0 = time.perf_counter()
        with _prof.Frame("kv.rpc.%s" % cmd, "kvserver", args=args):
            reply = self._dispatch_safe(msg)
        dur_ms = (time.perf_counter() - t0) * 1e3
        m["rpc_ms"].observe(dur_ms)
        _cmd_hist(m, cmd).observe(dur_ms)
        m["rpc_total"].inc(cmd)
        return reply

    # -- message dispatch --------------------------------------------------
    def _dispatch(self, msg):
        cmd = msg[0]
        if cmd == "init":
            _, key, arr = msg
            with self._lock:
                self.store.setdefault(key, np.array(arr))
            return ("ok",)
        if cmd == "multi":
            # fused bucket of inner commands (gradient coalescing): ONE
            # envelope = ONE dedup record, so exactly-once replay covers
            # the whole bucket atomically from the client's perspective.
            # Inner commands bypass _dispatch_timed (the bucket already
            # owns the RPC span/histogram), so count them here — per-cmd
            # totals must not lose the fused pushes/pulls.
            if _telemetry.enabled():
                counts = _srv_metrics()["rpc_total"]
                for im in msg[1]:
                    counts.inc(im[0] if isinstance(im, tuple) and im
                               else "?")
            return ("ok", [self._dispatch_safe(m) for m in msg[1]])
        if cmd == "push":
            key, arr = msg[1], msg[2]
            rank = msg[3] if len(msg) > 3 else 0
            nack = self._reject_nonfinite("push", key, arr, rank)
            if nack is not None:
                return nack
            with self._lock:
                if self.sync_mode and self._members \
                        and rank not in self._members:
                    # an evicted/left rank's in-flight push: ack it (the
                    # client would retry an error) but keep it out of the
                    # survivors' merge rounds
                    return ("ok",)
                stored = self.store.get(key)
                if stored is not None and \
                        np.asarray(arr).dtype != stored.dtype:
                    # fp16 wire compression: decompress to the stored
                    # dtype before merging/updating so server-side math
                    # runs at full precision
                    arr = np.asarray(arr, dtype=stored.dtype)
                self.applied_pushes += 1
                if self.sync_mode:
                    # per-worker rounds: a fast worker's next-iteration push
                    # must not count toward the current round
                    # (kvstore_dist_server.h:164-179 merges one push per
                    # worker before the update fires)
                    rounds = self._merge.setdefault(key, [])
                    placed_at = None
                    for i, rnd in enumerate(rounds):
                        if rank not in rnd:
                            rnd[rank] = np.asarray(arr)
                            placed_at = i
                            break
                    if placed_at is None:
                        rounds.append({rank: np.asarray(arr)})
                        placed_at = len(rounds) - 1
                    if _telemetry.enabled():
                        # arrival timestamp for straggler detection,
                        # mirrored in the shadow structure (never the
                        # snapshotted round dicts)
                        tss = self._merge_ts.setdefault(key, [])
                        while len(tss) <= placed_at:
                            tss.append({})
                        tss[placed_at][rank] = time.monotonic()
                    self._flush_rounds_locked(key)
                else:
                    self._apply(key, np.asarray(arr))
            return ("ok",)
        if cmd == "pull":
            _, key = msg
            with self._lock:
                if key not in self.store:
                    return ("err", "uninitialized key %r" % (key,))
                return ("ok", self.store[key])
        if cmd == "set_optimizer":
            is_recovery = bool(msg[2]) if len(msg) > 2 else False
            optimizer = pickle.loads(msg[1])
            with self._lock:
                # a rejoining rank 0 re-ships the optimizer it launched
                # with; installing it fresh would reset live momentum
                # state mid-training — keep the installed updater
                if not (is_recovery and self.updater is not None):
                    self.updater = opt.get_updater(optimizer)
            return ("ok",)
        # -- sparse parameter plane (wire v2, docs/how_to/sparse.md) -----
        if cmd == "init_table":
            _, key, meta = msg
            meta = dict(meta)
            meta.setdefault("num_servers", 1)
            meta.setdefault("server_index", 0)
            meta.setdefault("init", ("zeros",))
            meta.setdefault("dtype", "float32")
            with self._lock:
                tbl = self.tables.get(key)
                if tbl is None:
                    self.tables[key] = {"meta": meta, "rows": {},
                                        "state": {}}
            _register_table_gauges(self, key)
            return ("ok",)
        if cmd == "push_rows":
            faults.fire("kv.server.push_rows")
            key, row_ids, values = msg[1], msg[2], msg[3]
            rank = msg[4] if len(msg) > 4 else 0
            nack = self._reject_nonfinite("push_rows", key, values, rank)
            if nack is not None:
                return nack
            with self._lock:
                if key not in self.tables:
                    return ("err", "uninitialized table %r" % (key,))
                if self.sync_mode and self._members \
                        and rank not in self._members:
                    # evicted/left rank's in-flight sparse push: ack but
                    # keep it out of the survivors' merge rounds
                    return ("ok",)
                ids = np.asarray(row_ids, dtype=np.int64).reshape(-1)
                vals = np.asarray(values)
                self.applied_row_pushes += 1
                if _telemetry.enabled():
                    _srv_metrics()["sparse_pushed"].inc(ids.shape[0])
                if self.sync_mode:
                    # per-worker rounds, mirroring the dense push path: a
                    # fast worker's next-iteration rows must not count
                    # toward the current round
                    rounds = self._sparse_merge.setdefault(key, [])
                    for rnd in rounds:
                        if rank not in rnd:
                            rnd[rank] = (ids, vals)
                            break
                    else:
                        rounds.append({rank: (ids, vals)})
                    self._flush_sparse_rounds_locked(key)
                else:
                    # sum-merge duplicate ids first: the writeback is
                    # per-row, so unmerged duplicates would last-write-win
                    # instead of adding like the dense scatter
                    ids, vals = row_merge(ids, vals)
                    self._apply_rows_locked(key, ids, vals)
            return ("ok",)
        if cmd == "pull_rows":
            faults.fire("kv.server.pull_rows")
            _, key, row_ids = msg
            with self._lock:
                if key not in self.tables:
                    return ("err", "uninitialized table %r" % (key,))
                ids = np.asarray(row_ids, dtype=np.int64).reshape(-1)
                out = self._gather_rows_locked(key, ids)
                if _telemetry.enabled():
                    _srv_metrics()["sparse_pulled"].inc(ids.shape[0])
                return ("ok", out)
        if cmd == "table_info":
            want = msg[1] if len(msg) > 1 else None
            with self._lock:
                info = {}
                for key, tbl in self.tables.items():
                    if want is not None and key != want:
                        continue
                    meta = tbl["meta"]
                    ns = int(meta.get("num_servers", 1))
                    si = int(meta.get("server_index", 0))
                    misplaced = sum(1 for r in tbl["rows"]
                                    if int(r) % ns != si)
                    info[key] = {
                        "rows": len(tbl["rows"]),
                        "state_rows": len(tbl["state"]),
                        "bytes": (sum(v.nbytes
                                      for v in tbl["rows"].values())
                                  + sum(v.nbytes
                                        for v in tbl["state"].values())),
                        "misplaced": misplaced,
                        "meta": dict(meta),
                    }
                return ("ok", info)
        if cmd == "set_sparse_optimizer":
            is_recovery = bool(msg[2]) if len(msg) > 2 else False
            updater = pickle.loads(msg[1])
            with self._lock:
                # same recovery semantics as the dense updater: a
                # rejoining rank 0 must not reset live optimizer state
                if not (is_recovery and self.sparse_updater is not None):
                    self.sparse_updater = updater
            return ("ok",)
        if cmd == "heartbeat":
            rank = int(msg[1])
            with self._lock:
                self._heartbeats[rank] = time.monotonic()
            return ("ok",)
        if cmd == "dead_nodes":
            timeout_s = (float(msg[1])
                         if len(msg) > 1 and msg[1] is not None
                         else _hb_timeout_default())
            return ("ok", self._dead_nodes(timeout_s))
        if cmd == "join":
            # elastic membership entry: admit the rank into the live set,
            # bump the generation, baseline its heartbeat (the eviction
            # clock must not start before the worker's first beat), and
            # hand back the fleet view so a mid-run joiner can align
            rank = int(msg[1])
            with self._barrier_cv:
                with self._lock:
                    fresh = rank not in self._members
                    self._members.add(rank)
                    self._heartbeats[rank] = time.monotonic()
                    if fresh:
                        self._mgen += 1
                    gen = self._mgen
                    ranks = sorted(self._members)
                self._barrier_cv.notify_all()
            if fresh:
                self._note_membership("join", rank, gen, ranks)
                logging.info("kvstore membership: rank %d joined (gen %d, "
                             "live %s)", rank, gen, ranks)
            return ("ok", {"gen": gen, "ranks": ranks,
                           "num_workers": self.num_workers})
        if cmd == "leave":
            # graceful preemption exit: drop the rank NOW so survivors'
            # barriers and merge rounds re-form without waiting for the
            # eviction timeout
            with self._barrier_cv:
                gen = self._evict_members_locked([int(msg[1])], "leave")
            return ("ok", gen)
        if cmd == "evict":
            with self._barrier_cv:
                gen = self._evict_members_locked([int(msg[1])], "evict rpc")
            return ("ok", gen)
        if cmd == "membership":
            with self._lock:
                return ("ok", {"gen": self._mgen,
                               "ranks": sorted(self._members),
                               "num_workers": self.num_workers})
        if cmd == "barrier":
            rank = int(msg[1]) if len(msg) > 1 else 0
            is_recovery = bool(msg[2]) if len(msg) > 2 else False
            timeout = float(os.environ.get("MXNET_KVSTORE_BARRIER_TIMEOUT",
                                           "600"))
            hb_timeout = _hb_timeout_default()
            deadline = time.monotonic() + timeout
            with self._barrier_cv:
                # rejoin semantics (reference kvstore_dist.h:35-38): a
                # recovered worker skips a barrier only when the job has
                # passed startup (a generation completed) AND no peers
                # are currently parked at one — if they are, it must
                # join and release them (they count num_workers arrivals
                # and would otherwise wedge until the timeout). Arrivals
                # are tracked per RANK so a worker that crashed after
                # arriving cannot double-count on rejoin.
                if (is_recovery and self._barrier_gen > 0
                        and not self._barrier_ranks):
                    return ("ok",)
                gen = self._barrier_gen
                self._barrier_ranks.add(rank)
                if self._try_release_barrier_locked():
                    return ("ok",)
                # wake periodically: a dead peer (stale heartbeat) releases
                # the barrier instead of hanging the job until the full
                # timeout — by EVICTION (elastic mode: the barrier re-forms
                # around the survivors and training continues) or by abort
                # (static mode, reference GetDeadNodes semantics: callers
                # observe the failure; a dead worker otherwise wedges the
                # server's merge-until-NumWorkers forever)
                while True:
                    released = self._barrier_cv.wait_for(
                        lambda: self._barrier_gen != gen,
                        timeout=min(1.0, max(deadline - time.monotonic(),
                                             0.01)))
                    if released:
                        return ("ok",)
                    if self._evict_timeout > 0 and self._members:
                        stale = self._stale_members(self._evict_timeout)
                        if stale:
                            self._evict_members_locked(stale,
                                                       "stale heartbeat")
                            if self._barrier_gen != gen:
                                return ("ok",)
                    else:
                        dead = self._dead_nodes(hb_timeout)
                        if dead:
                            if self._barrier_gen == gen:
                                self._barrier_ranks.discard(rank)
                            return ("err",
                                    "barrier aborted: dead workers %s"
                                    % dead)
                    if time.monotonic() >= deadline:
                        if self._barrier_gen == gen:
                            self._barrier_ranks.discard(rank)
                        return ("err",
                                "barrier timed out after %.0fs" % timeout)
        if cmd == "snapshot":
            # force a durable snapshot NOW (workers quiesce at a barrier,
            # rank 0 snapshots, and the job is then kill-safe to that point)
            path = self.snapshot()
            if path is None:
                return ("err", "server has no snapshot_path configured")
            return ("ok", path)
        if cmd == "stop":
            self._stop.set()
            try:
                self.snapshot()
            except Exception as e:
                logging.warning("kvstore snapshot on stop failed: %s", e)
            threading.Thread(target=self._server.shutdown,
                             daemon=True).start()
            return ("ok",)
        return ("err", "unknown command %r" % (cmd,))

    def _dead_nodes(self, timeout_s):
        """Ranks whose last heartbeat is older than ``timeout_s`` (only
        ranks that have ever heartbeated are tracked — a worker that never
        connected is the launcher's problem, as in the reference)."""
        now = time.monotonic()
        with self._lock:
            return sorted(r for r, t in self._heartbeats.items()
                          if now - t > timeout_s)

    # -- elastic membership ------------------------------------------------
    def _round_complete_locked(self, rnd):
        """A sync-merge round is ready when every live member contributed;
        before any member joined (legacy static launch) the round counts
        ``num_workers`` contributions instead.  Caller holds ``_lock``."""
        if self._members:
            return self._members <= set(rnd)
        return len(rnd) >= self.num_workers

    def _flush_rounds_locked(self, key):
        """Apply every leading complete merge round for ``key`` (caller
        holds ``_lock``).  When the live set has shrunk below the nominal
        worker count, the merged gradient is renormalized by
        ``num_workers / len(round)``: the worker-side optimizer scales by
        ``1/num_workers`` (gradient averaging over the launch-time fleet),
        so without the correction a shrink would silently shrink the
        effective learning rate too."""
        rounds = self._merge.get(key)
        while rounds and self._round_complete_locked(rounds[0]):
            rnd = rounds.pop(0)
            tss = self._merge_ts.get(key)
            self._note_round_latency(key, tss.pop(0) if tss else None)
            self.round_sizes[len(rnd)] = self.round_sizes.get(len(rnd), 0) + 1
            merged = np.sum(list(rnd.values()), axis=0)
            if self._members and len(rnd) != self.num_workers:
                merged = np.asarray(
                    merged * (float(self.num_workers) / len(rnd)),
                    dtype=merged.dtype)
            self._apply(key, merged)

    def _note_round_latency(self, key, tsr):
        """Straggler detection over one completed sync-merge round
        (caller holds ``_lock``): per-rank round-wait histograms, the
        round's max-minus-median skew gauge, and — when a rank's wait
        behind the first arrival exceeds ``MXNET_TELEMETRY_STRAGGLER_MULT``
        times the round median (and ``..._MIN_MS``, the noise floor) — a
        structured ``straggler`` event plus a rank-labeled counter."""
        if tsr is None or len(tsr) < 2 or not _telemetry.enabled():
            return
        import statistics

        t0 = min(tsr.values())
        lats = {r: (t - t0) * 1e3 for r, t in tsr.items()}
        med = statistics.median(lats.values())
        m = _srv_metrics()
        for r, lat in lats.items():
            _rank_wait_hist(m, r).observe(lat)
        m["round_skew"].set(max(lats.values()) - med)
        mult = _env("MXNET_TELEMETRY_STRAGGLER_MULT", 4.0, float)
        if mult <= 0:
            return
        min_ms = _env("MXNET_TELEMETRY_STRAGGLER_MIN_MS", 50.0, float)
        for r, lat in sorted(lats.items()):
            if lat >= min_ms and lat > mult * max(med, 1e-9):
                m["stragglers"].inc(str(r))
                _telemetry.log_event(
                    "straggler", key=str(key), rank=r,
                    lat_ms=round(lat, 3), median_ms=round(med, 3),
                    mult=mult, round_size=len(tsr))

    def _reject_nonfinite(self, cmd, key, values, rank):
        """Numeric containment (guardian's fleet-side half): a gradient
        push carrying NaN/Inf is answered with a typed NACK and never
        touches the store/merge rounds — one poisoned worker cannot
        corrupt the parameter plane every other rank pulls from.  Runs
        BEFORE the dedup-recorded dispatch returns, so a retried push
        replays the same NACK from the idempotency window without
        double-counting.  Returns the NACK reply tuple, or None to admit
        the push."""
        if _env("MXNET_KVSTORE_REJECT_NONFINITE", 1, int) == 0:
            return None
        a = np.asarray(values)
        if not np.issubdtype(a.dtype, np.floating) or \
                bool(np.all(np.isfinite(a))):
            return None
        with self._lock:
            self.rejected_pushes += 1
            n = self.rejects_by_rank.get(rank, 0) + 1
            self.rejects_by_rank[rank] = n
        if _telemetry.enabled():
            _srv_metrics()["rejected"].inc(str(rank))
            _telemetry.log_event("kv_nack", cmd=cmd, key=str(key),
                                 rank=rank, count=n)
        limit = _env("MXNET_KVSTORE_NACK_LIMIT", 0, int)
        if limit > 0 and n >= limit:
            self._flag_poisoned(rank, n)
        return ("nack", "nonfinite",
                "%s from rank %s to key %r carries non-finite values "
                "(rejection %d for this rank)" % (cmd, rank, key, n))

    def _flag_poisoned(self, rank, n):
        """A rank crossed MXNET_KVSTORE_NACK_LIMIT rejections: flag it
        through the straggler counter (the fleet-health dashboard's
        existing bad-rank signal) and, if it holds elastic membership,
        evict it exactly like a heartbeat-dead rank."""
        if _telemetry.enabled():
            _srv_metrics()["stragglers"].inc(str(rank))
            _telemetry.log_event("poisoned_worker", rank=rank,
                                 rejections=n)
        with self._lock:
            member = bool(self._members) and rank in self._members
        if member:
            # established lock order: _barrier_cv before _lock
            with self._barrier_cv:
                self._evict_members_locked(
                    [rank], "poisoned (%d non-finite pushes)" % n)

    def _try_release_barrier_locked(self):
        """Release the parked barrier if every required rank has arrived
        (caller holds ``_barrier_cv``).  Elastic mode: the required set is
        the live membership (extra arrivals — a rank evicted after
        parking — never block); static mode keeps the ``num_workers``
        count."""
        with self._lock:
            members = set(self._members)
        if members:
            ready = members <= self._barrier_ranks
        else:
            ready = len(self._barrier_ranks) >= self.num_workers
        if ready:
            self._barrier_ranks = set()
            self._barrier_gen += 1
            self._barrier_cv.notify_all()
        return ready

    def _stale_members(self, timeout_s):
        """Live members whose heartbeat is older than ``timeout_s``.  A
        member with no heartbeat record yet (snapshot restore, join race)
        is re-baselined to now rather than instantly evicted."""
        now = time.monotonic()
        with self._lock:
            out = []
            for r in sorted(self._members):
                t = self._heartbeats.get(r)
                if t is None:
                    self._heartbeats[r] = now
                elif now - t > timeout_s:
                    out.append(r)
            return out

    def _evict_members_locked(self, ranks, reason):
        """Remove ``ranks`` from the live membership (caller holds
        ``_barrier_cv``): bump the generation, discard their partial
        merge-round contributions, flush any rounds the shrunken set now
        completes, re-form a parked barrier around the survivors, and
        emit telemetry.  Returns the membership generation."""
        with self._lock:
            gone = [r for r in ranks if r in self._members]
            for r in gone:
                self._members.discard(r)
                self._heartbeats.pop(r, None)
            if gone:
                self._mgen += 1
                for rounds in self._merge.values():
                    for rnd in rounds:
                        for r in gone:
                            rnd.pop(r, None)
                for rounds in self._sparse_merge.values():
                    for rnd in rounds:
                        for r in gone:
                            rnd.pop(r, None)
                for tss in self._merge_ts.values():
                    for tsr in tss:
                        for r in gone:
                            tsr.pop(r, None)
                for key in list(self._merge):
                    self._flush_rounds_locked(key)
                for key in list(self._sparse_merge):
                    self._flush_sparse_rounds_locked(key)
            gen = self._mgen
            ranks_now = sorted(self._members)
        if gone:
            self._barrier_ranks -= set(gone)
            if self._barrier_ranks:
                self._try_release_barrier_locked()
            self._barrier_cv.notify_all()
            for r in gone:
                self._note_membership(
                    "leave" if reason == "leave" else "evict",
                    r, gen, ranks_now, reason=reason)
            logging.info("kvstore membership: %s — rank(s) %s removed "
                         "(gen %d, live %s)", reason, gone, gen, ranks_now)
            if reason != "leave" and _telemetry.enabled():
                # an eviction is a death the victim could not report —
                # the server's flight recorder keeps the evidence (round
                # state, membership events, recent spans)
                _telemetry.flight_recorder.dump(
                    "evict:%s" % reason,
                    extra={"evicted": gone, "gen": gen,
                           "live": ranks_now})
        return gen

    def _note_membership(self, kind, rank, gen, ranks, reason=None):
        if not _telemetry.enabled():
            return
        m = _srv_metrics()
        m["members"].set(len(ranks))
        if kind == "join":
            m["joins"].inc()
        elif kind == "leave":
            m["leaves"].inc()
        else:
            m["evictions"].inc()
        fields = {"change": kind, "rank": rank, "gen": gen,
                  "live": list(ranks)}
        if reason:
            fields["reason"] = reason
        _telemetry.log_event("kvsrv_membership", **fields)

    def _evictor_loop(self):
        """Background stale-member eviction: a straggler that stops
        heartbeating for ``MXNET_KVSTORE_EVICT_TIMEOUT`` is removed even
        when no barrier is parked (async mode, or sync workers stuck
        waiting on a merge round rather than a barrier)."""
        poll = max(0.05, min(1.0, self._evict_timeout / 4.0))
        while not self._stop.wait(poll):
            try:
                faults.fire("kv.server.evict")
                with self._barrier_cv:
                    stale = self._stale_members(self._evict_timeout)
                    if stale:
                        self._evict_members_locked(stale, "stale heartbeat")
            except Exception as e:
                logging.warning("kvstore evictor: %s", e)

    # -- sparse tables ------------------------------------------------------
    @staticmethod
    def _row_init(meta, key, row_id):
        """Deterministically materialize one absent row.  The seed is a
        function of (key, row_id) ONLY — independent of which server owns
        the row, of arrival order, and of restarts — so resharding or a
        crash-restart reproduces bit-identical virgin rows."""
        shape = tuple(meta.get("row_shape", ()))
        dtype = np.dtype(meta.get("dtype", "float32"))
        spec = tuple(meta.get("init", ("zeros",)))
        kind = spec[0]
        if kind == "zeros":
            return np.zeros(shape, dtype=dtype)
        if kind == "constant":
            return np.full(shape, spec[1], dtype=dtype)
        seed = zlib.crc32(("%r:%d" % (key, int(row_id))).encode())
        rng = np.random.RandomState(seed)
        if kind == "uniform":
            scale = float(spec[1]) if len(spec) > 1 else 0.01
            return rng.uniform(-scale, scale, size=shape).astype(dtype)
        if kind == "normal":
            std = float(spec[1]) if len(spec) > 1 else 0.01
            return (rng.standard_normal(size=shape) * std).astype(dtype)
        raise ValueError("unknown sparse init spec %r" % (spec,))

    def _gather_rows_locked(self, key, ids):
        """Stack the requested rows into one (n, *row_shape) array,
        lazily materializing absent rows (caller holds ``_lock``)."""
        tbl = self.tables[key]
        rows, meta = tbl["rows"], tbl["meta"]
        out = np.empty((ids.shape[0],) + tuple(meta.get("row_shape", ())),
                       dtype=np.dtype(meta.get("dtype", "float32")))
        for i, r in enumerate(ids):
            r = int(r)
            row = rows.get(r)
            if row is None:
                row = self._row_init(meta, key, r)
                rows[r] = row
            out[i] = row
        return out

    def _apply_rows_locked(self, key, ids, vals):
        """Apply a merged row-sparse gradient block to this shard: run the
        server-placed sparse updater over the touched rows (materializing
        rows and their optimizer state lazily), or accumulate when no
        updater is installed (caller holds ``_lock``)."""
        tbl = self.tables[key]
        meta = tbl["meta"]
        weight = self._gather_rows_locked(key, ids)
        if vals.dtype != weight.dtype:
            # fp16 wire compression parity with the dense path: server
            # math runs at the stored precision
            vals = np.asarray(vals, dtype=weight.dtype)
        upd = self.sparse_updater
        if upd is None:
            weight += vals
        else:
            sshape = upd.state_shape(tuple(meta.get("row_shape", ())))
            if sshape is None:
                upd.update_rows(weight, vals, None)
            else:
                state_rows, states = tbl["state"], None
                states = np.empty((ids.shape[0],) + tuple(sshape),
                                  dtype=weight.dtype)
                for i, r in enumerate(ids):
                    s = state_rows.get(int(r))
                    states[i] = 0 if s is None else s
                upd.update_rows(weight, vals, states)
                for i, r in enumerate(ids):
                    state_rows[int(r)] = states[i]
        rows = tbl["rows"]
        for i, r in enumerate(ids):
            rows[int(r)] = weight[i]

    def _flush_sparse_rounds_locked(self, key):
        """Sparse twin of ``_flush_rounds_locked`` (caller holds
        ``_lock``): pop every leading complete round, concatenate the
        member contributions, sum duplicate row ids, renormalize by
        ``num_workers / len(round)`` when the live set has shrunk, and
        apply the merged block."""
        rounds = self._sparse_merge.get(key)
        while rounds and self._round_complete_locked(rounds[0]):
            rnd = rounds.pop(0)
            faults.fire("sparse.merge")
            self.round_sizes[len(rnd)] = \
                self.round_sizes.get(len(rnd), 0) + 1
            ids = np.concatenate([c[0] for c in rnd.values()])
            vals = np.concatenate([c[1] for c in rnd.values()])
            ids, vals = row_merge(ids, vals)
            if self._members and len(rnd) != self.num_workers:
                vals = np.asarray(
                    vals * (float(self.num_workers) / len(rnd)),
                    dtype=vals.dtype)
            self._apply_rows_locked(key, ids, vals)

    def _apply(self, key, grad):
        """Run the updater (reference DataHandle: updater_(key, recved,
        &stored)); without one, accumulate like the reference default."""
        if key not in self.store:
            self.store[key] = np.array(grad)
            return
        if self.updater is None:
            self.store[key] = self.store[key] + grad
            return
        weight = nd.array(self.store[key])
        self.updater(key, nd.array(grad), weight)
        self.store[key] = weight.asnumpy()

    # -- durable snapshots --------------------------------------------------
    # v2: dedup records are per-client windows {"floor", "window": {seq:
    # reply}} (pipelined transport); v1 single-record snapshots are
    # converted on restore.  v3 adds the elastic membership table
    # ("members", "mgen") so a restarted server re-forms around the same
    # live set instead of forgetting who was in the job.  v4 adds the
    # sparse parameter plane: the sharded embedding tables (rows +
    # server-placed optimizer state + meta), the sparse updater, pending
    # sparse merge rounds, and the applied_row_pushes counter — a killed
    # server restarts with a bit-identical table.
    _SNAP_VERSION = 4

    def snapshot(self):
        """Write the full server state to ``snapshot_path`` atomically
        (tmp + fsync + replace, CRC32 sidecar).  Returns the path, or None
        when no snapshot path is configured.  State captured: the store,
        the updater (optimizer + live momentum), barrier generation,
        pending sync-merge rounds, and idempotency records — everything a
        restarted server needs to re-admit its workers."""
        if not self.snapshot_path:
            return None
        from .filesystem import atomic_write

        snap_t0 = time.perf_counter()
        with self._lock:
            store = dict(self.store)
            merge = {k: [dict(rnd) for rnd in rounds]
                     for k, rounds in self._merge.items()}
            updater_bytes = (pickle.dumps(self.updater,
                                          pickle.HIGHEST_PROTOCOL)
                            if self.updater is not None else None)
            applied = self.applied_pushes
            members = sorted(self._members)
            mgen = self._mgen
            tables = {k: {"meta": dict(t["meta"]),
                          "rows": dict(t["rows"]),
                          "state": dict(t["state"])}
                      for k, t in self.tables.items()}
            sparse_merge = {k: [dict(rnd) for rnd in rounds]
                            for k, rounds in self._sparse_merge.items()}
            sparse_updater_bytes = (
                pickle.dumps(self.sparse_updater, pickle.HIGHEST_PROTOCOL)
                if self.sparse_updater is not None else None)
            applied_rows = self.applied_row_pushes
        with self._dedup_cv:
            dedup = {cid: {"floor": rec["floor"],
                           "window": {s: e["reply"]
                                      for s, e in rec["window"].items()
                                      if e["done"]}}
                     for cid, rec in self._dedup.items()}
        state = {
            "version": self._SNAP_VERSION,
            "store": store,
            "merge": merge,
            "updater": updater_bytes,
            "barrier_gen": self._barrier_gen,
            "dedup": dedup,
            "applied_pushes": applied,
            "num_workers": self.num_workers,
            "sync_mode": self.sync_mode,
            "members": members,
            "mgen": mgen,
            "tables": tables,
            "sparse_merge": sparse_merge,
            "sparse_updater": sparse_updater_bytes,
            "applied_row_pushes": applied_rows,
        }
        payload = pickle.dumps(state, pickle.HIGHEST_PROTOCOL)
        atomic_write(self.snapshot_path, lambda f: f.write(payload),
                     checksum=True, op="kvsnap.write")
        if _telemetry.enabled():
            ms = (time.perf_counter() - snap_t0) * 1e3
            m = _srv_metrics()
            m["snap_ms"].set(ms)
            m["snaps"].inc()
            _telemetry.log_event("kvsrv_snapshot", ms=round(ms, 3),
                                 bytes=len(payload))
        return self.snapshot_path

    def _restore_snapshot(self):
        """Load ``snapshot_path`` if present and intact; a missing, torn,
        or CRC-mismatched snapshot is skipped (cold start) rather than
        crashing the restart loop."""
        from .filesystem import verify_crc_sidecar

        path = self.snapshot_path
        if not path or not os.path.exists(path):
            return False
        if verify_crc_sidecar(path) is False:
            logging.warning("kvstore snapshot %s fails its CRC sidecar; "
                            "starting cold", path)
            return False
        try:
            with open(path, "rb") as f:
                state = pickle.load(f)
            if state.get("version") not in (1, 2, 3, self._SNAP_VERSION):
                raise ValueError("snapshot version %r"
                                 % (state.get("version"),))
            updater = (pickle.loads(state["updater"])
                       if state.get("updater") is not None else None)
            sparse_updater = (
                pickle.loads(state["sparse_updater"])
                if state.get("sparse_updater") is not None else None)
        except Exception as e:
            logging.warning("kvstore snapshot %s is unreadable (%s); "
                            "starting cold", path, e)
            return False
        with self._lock:
            self.store = dict(state.get("store", {}))
            self._merge = {k: [dict(rnd) for rnd in rounds]
                           for k, rounds in state.get("merge", {}).items()}
            self.updater = updater
            self.applied_pushes = int(state.get("applied_pushes", 0))
            self.tables = {k: {"meta": dict(t["meta"]),
                               "rows": dict(t["rows"]),
                               "state": dict(t["state"])}
                           for k, t in state.get("tables", {}).items()}
            self._sparse_merge = {
                k: [dict(rnd) for rnd in rounds]
                for k, rounds in state.get("sparse_merge", {}).items()}
            self.sparse_updater = sparse_updater
            self.applied_row_pushes = int(
                state.get("applied_row_pushes", 0))
            self._members = set(state.get("members", []))
            self._mgen = int(state.get("mgen", 0))
            now = time.monotonic()
            for r in self._members:
                # restored members get a fresh heartbeat baseline: the
                # eviction clock restarts with the server instead of
                # reading as infinitely stale and evicting everyone
                self._heartbeats[r] = now
        with self._barrier_cv:
            self._barrier_gen = int(state.get("barrier_gen", 0))
        with self._dedup_cv:
            self._dedup = self._load_dedup(state.get("dedup", {}),
                                           state.get("version"))
        for key in self.tables:
            _register_table_gauges(self, key)
        logging.info("kvstore server restored %d keys (barrier gen %d) "
                     "from %s", len(self.store), self._barrier_gen, path)
        return True

    @staticmethod
    def _load_dedup(raw, version):
        """Rebuild live dedup records from a snapshot; v1 snapshots hold a
        single {"seq", "done", "reply"} record per client."""
        out = {}
        for cid, rec in raw.items():
            if version == 1 or "window" not in rec:
                win = OrderedDict()
                win[rec["seq"]] = {"done": True, "reply": rec["reply"]}
                out[cid] = {"floor": rec["seq"] - 1, "window": win}
                continue
            win = OrderedDict()
            for s in sorted(rec["window"]):
                win[s] = {"done": True, "reply": rec["window"][s]}
            out[cid] = {"floor": int(rec.get("floor", 0)), "window": win}
        return out

    def _snapshot_loop(self):
        while not self._stop.wait(self._snap_interval):
            try:
                self.snapshot()
            except Exception as e:
                logging.warning("periodic kvstore snapshot failed: %s", e)

    # -- lifecycle ---------------------------------------------------------
    def serve_forever(self):
        self._server.serve_forever(poll_interval=0.05)

    def start_background(self):
        t = threading.Thread(target=self.serve_forever, daemon=True)
        t.start()
        return t

    def stop(self):
        self._stop.set()
        try:
            self.snapshot()
        except Exception as e:
            logging.warning("kvstore snapshot on stop failed: %s", e)
        self._server.shutdown()
        self._server.server_close()


class ServerClient:
    """Worker-side connection to a KVStoreServer (the ps::KVWorker role).

    Pipelined crash-tolerant transport: requests are SENT as soon as they
    are submitted — many can be in flight at once — and a dedicated
    reader thread matches ``("rsp", seq, reply)`` frames back to their
    waiters by the PR-2 idempotency token, replacing the old send→recv
    lockstep (one RPC round trip per request, serialized).  Every RPC
    still carries a ``(client_id, seq)`` token; on any connection failure
    the reader reconnects with exponential backoff + jitter
    (``MXNET_KVSTORE_RETRY_*``) and REPLAYS every in-flight envelope
    under its original token, which the server deduplicates — so retried
    pushes after a dropped ACK are applied exactly once even with
    multiple requests in flight, and a server kill+restart (snapshot
    recovery) is ridden out transparently within the retry budget.

    Usable as a context manager; ``close()`` is idempotent and always
    joins the heartbeat and reader threads.
    """

    def __init__(self, host, port):
        self._addr = (host, port)
        self._cid = uuid.uuid4().hex  # idempotency namespace for this client
        self._seq = 0
        self._sock = None
        self._closed = False
        self._hb_stop = None
        self._hb_thread = None
        # _state_cv guards _seq/_inflight/_closed; _send_lock serializes
        # socket writes and reconnects.  Ordering rule: _send_lock may be
        # taken first and _state_cv inside it, never the reverse.
        self._state_cv = threading.Condition()
        self._inflight: "OrderedDict[int, dict]" = OrderedDict()
        self.max_inflight = 0
        self._send_lock = threading.Lock()
        self._connect(_retry_conf())
        self._reader = threading.Thread(target=self._reader_loop,
                                        daemon=True, name="kvclient-reader")
        self._reader.start()

    # -- transport ---------------------------------------------------------
    @staticmethod
    def _deadline_hit(t0, conf):
        """MXNET_KVSTORE_RETRY_DEADLINE: overall wall-clock cap on one
        reconnect/replay loop (0 disables).  An evicted worker whose
        server stopped talking to it fails fast with a typed error
        instead of burning the remaining per-attempt budget."""
        return conf["deadline"] > 0 and \
            time.monotonic() - t0 >= conf["deadline"]

    def _connect(self, conf):
        last = None
        t0 = time.monotonic()
        for attempt in range(conf["retries"] + 1):
            try:
                faults.fire("kv.client.connect")
                self._sock = _nodelay(
                    socket.create_connection(self._addr, timeout=120))
                return
            except OSError as e:
                last = e
                self._sock = None
                if attempt >= conf["retries"] or \
                        self._deadline_hit(t0, conf):
                    break
                _backoff_sleep(attempt, conf)
        raise KVStoreConnectionError(
            "kvstore server %s:%d unreachable after %d attempts (%.1fs): %s"
            % (self._addr[0], self._addr[1], attempt + 1,
               time.monotonic() - t0, last))

    def _kill_sock_locked(self):
        """Drop the socket (caller holds _send_lock).  shutdown() first:
        close() alone does not reliably wake a reader parked in recv."""
        sock, self._sock = self._sock, None
        if sock is not None:
            try:
                sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                sock.close()
            except OSError:
                pass

    def _submit(self, msg, retries=None, ctx=None):
        """Register an in-flight entry and send its envelope; returns the
        entry whose ``event`` fires when the reply (or failure) lands.
        Non-blocking beyond the socket write — the pipelining primitive.
        ``ctx`` (telemetry on only) rides as an optional 5th envelope
        element: the distributed trace context the server's handler span
        adopts; replays reuse it, so a retried RPC keeps its trace id."""
        with self._state_cv:
            if self._closed:
                raise ConnectionError("ServerClient is closed")
            self._seq += 1
            seq = self._seq
            env = ("req", self._cid, seq, msg) if ctx is None \
                else ("req", self._cid, seq, msg, ctx)
            ent = {"seq": seq, "env": env,
                   "event": threading.Event(), "reply": None, "exc": None,
                   "retries": retries, "replays": 0}
            self._inflight[seq] = ent
            if len(self._inflight) > self.max_inflight:
                self.max_inflight = len(self._inflight)
            self._state_cv.notify_all()  # wake the reader
        with self._send_lock:
            if self._sock is not None:
                try:
                    _send_msg(self._sock, ent["env"], op="kv.client.send")
                except (ConnectionError, OSError, EOFError):
                    # reader notices the dead socket and replays everything
                    self._kill_sock_locked()
        return ent

    def _reader_loop(self):
        """Single reader: waits for work, receives reply frames, matches
        them to in-flight entries by seq.  Any transport failure funnels
        into _recover(), which reconnects and replays all live tokens."""
        while True:
            with self._state_cv:
                while not self._inflight and not self._closed:
                    self._state_cv.wait()
                if self._closed:
                    return
                sock = self._sock
            if sock is None:
                self._recover(None)
                continue
            try:
                reply = _recv_msg(sock, op="kv.client.recv")
            except (ConnectionError, OSError, EOFError):
                self._recover(sock)
                continue
            if isinstance(reply, tuple) and len(reply) == 3 \
                    and reply[0] == "rsp":
                with self._state_cv:
                    ent = self._inflight.pop(reply[1], None)
                if ent is not None:
                    ent["reply"] = reply[2]
                    ent["event"].set()
                # an unknown seq is a duplicate response from a replay
                # race (original + replay both answered): drop it

    def _recover(self, failed):
        """Reconnect after a transport failure and replay every in-flight
        envelope in seq order under its original idempotency token.  The
        server's dedup window turns replays of already-applied requests
        into recorded-reply replays — exactly-once with >1 in flight."""
        conf = _retry_conf()
        t0 = time.monotonic()
        with self._send_lock:
            if failed is not None and self._sock is not None \
                    and self._sock is not failed:
                return  # socket already replaced
            self._kill_sock_locked()
            last = None
            for attempt in range(conf["retries"] + 1):
                with self._state_cv:
                    if self._closed or not self._inflight:
                        return
                if self._deadline_hit(t0, conf):
                    last = ("retry deadline %.1fs exceeded"
                            % conf["deadline"]) if last is None else last
                    break
                try:
                    faults.fire("kv.client.connect")
                    sock = _nodelay(
                        socket.create_connection(self._addr, timeout=120))
                except OSError as e:
                    last = e
                    if attempt < conf["retries"]:
                        _backoff_sleep(attempt, conf)
                    continue
                with self._state_cv:
                    ents = sorted(self._inflight.values(),
                                  key=lambda e: e["seq"])
                sent_all = True
                for ent in ents:
                    limit = ent["retries"] if ent["retries"] is not None \
                        else conf["retries"]
                    ent["replays"] += 1
                    if ent["replays"] > limit:
                        # e.g. stop_server(retries=1): once the server
                        # acked and exited, burning the whole budget on a
                        # dead address helps nobody
                        self._fail_entry(ent, KVStoreConnectionError(
                            "kvstore rpc %r to %s:%d failed after %d "
                            "attempts" % (ent["env"][3][0], self._addr[0],
                                          self._addr[1], limit + 1)))
                        continue
                    try:
                        _send_msg(sock, ent["env"], op="kv.client.send")
                    except (ConnectionError, OSError, EOFError) as e:
                        last = e
                        sent_all = False
                        try:
                            sock.close()
                        except OSError:
                            pass
                        break
                if not sent_all:
                    if attempt < conf["retries"]:
                        _backoff_sleep(attempt, conf)
                    continue
                self._sock = sock
                return
            # budget (or retry deadline) exhausted: fail every waiter
            with self._state_cv:
                ents = list(self._inflight.values())
            for ent in ents:
                self._fail_entry(ent, KVStoreConnectionError(
                    "kvstore rpc %r to %s:%d gave up after %d attempts "
                    "(%.1fs): %s"
                    % (ent["env"][3][0], self._addr[0], self._addr[1],
                       attempt + 1, time.monotonic() - t0, last)))

    def _fail_entry(self, ent, exc):
        with self._state_cv:
            self._inflight.pop(ent["seq"], None)
        ent["exc"] = exc
        ent["event"].set()

    def _rpc(self, *msg, **kw):
        if self._closed:
            raise ConnectionError("ServerClient is closed")
        if not _telemetry.enabled():
            # hot path: one bool read, the 4-element envelope, no spans
            ent = self._submit(msg, retries=kw.get("retries"))
            ent["event"].wait()
        else:
            # distributed tracing: stamp a trace context into the
            # envelope, open a client-side span (covering the full round
            # trip) carrying the same trace id, and start a flow the
            # server-side handler span finishes
            ctx = _telemetry.distributed.new_trace_ctx(self._cid[:8])
            with _prof.Frame("kv.client.%s" % msg[0], "kvclient",
                             args={"trace": ctx["trace"]}):
                tracer.flow_event("kv.rpc", "s", ctx["trace"])
                ent = self._submit(msg, retries=kw.get("retries"), ctx=ctx)
                ent["event"].wait()
        if ent["exc"] is not None:
            raise ent["exc"]
        reply = ent["reply"]
        if reply[0] == "nack":
            # typed rejection (numeric containment): retrying the same
            # payload cannot succeed, so surface it as its own error
            raise NonFiniteGradientError(
                "kvstore server rejected push: %s"
                % (reply[2] if len(reply) > 2 else reply[1],))
        if reply[0] != "ok":
            raise MXNetError("kvstore server error: %s" % (reply[1],))
        return reply[1] if len(reply) > 1 else None

    # -- liveness ----------------------------------------------------------
    def start_heartbeat(self, rank, interval=5.0):
        """Publish liveness for ``rank`` every ``interval`` seconds on a
        daemon thread (ps-lite node heartbeats; feeds the server's
        dead-node tracking).  Uses its OWN connection: the main RPC socket
        can sit inside a long blocking barrier() round trip, and a worker
        waiting at a barrier must not go heartbeat-silent (that would make
        the dead-peer barrier release see live stragglers as dead).  The
        loop reconnects after failures, so heartbeats resume on their own
        once a killed server restarts from its snapshot."""
        if self._hb_stop is not None:
            return
        self._hb_stop = threading.Event()
        stop = self._hb_stop
        addr = self._addr
        self.heartbeat(rank)  # immediate first beat on the main socket

        def loop():
            sock = None
            while not stop.wait(interval):
                try:
                    if sock is None:
                        sock = _nodelay(
                            socket.create_connection(addr, timeout=30))
                    _send_msg(sock, ("heartbeat", rank))
                    reply = _recv_msg(sock)
                    if reply[0] != "ok":
                        return
                except Exception:
                    # connection gone: drop it and retry next tick — a
                    # restarting server must see us come back alive
                    if sock is not None:
                        try:
                            sock.close()
                        except OSError:
                            pass
                        sock = None
            if sock is not None:
                try:
                    sock.close()
                except OSError:
                    pass

        self._hb_thread = threading.Thread(target=loop, daemon=True,
                                           name="kvclient-heartbeat")
        self._hb_thread.start()

    def heartbeat(self, rank):
        self._rpc("heartbeat", rank)

    def dead_nodes(self, timeout_s=None):
        """None asks the server for its own MXNET_KVSTORE_HEARTBEAT_TIMEOUT
        default, so callers and the barrier release agree on who is dead."""
        return self._rpc("dead_nodes", timeout_s)

    # -- elastic membership -------------------------------------------------
    def join(self, rank):
        """Enter the live membership table; returns ``{gen, ranks,
        num_workers}`` so a mid-run joiner can align with the fleet."""
        return self._rpc("join", rank)

    def leave(self, rank):
        """Graceful preemption exit: the server drops this rank from
        barriers and merge rounds immediately (one retry only — a leaving
        worker must not burn the whole backoff budget on a dead server)."""
        return self._rpc("leave", rank, retries=1)

    def evict(self, rank):
        """Administratively remove another rank from the live set."""
        return self._rpc("evict", rank)

    def membership(self):
        return self._rpc("membership")

    # -- RPC surface -------------------------------------------------------
    def init(self, key, arr):
        self._rpc("init", key, np.asarray(arr))

    def push(self, key, arr, rank=0):
        self._rpc("push", key, np.asarray(arr), rank)

    def pull(self, key):
        return self._rpc("pull", key)

    def multi(self, msgs):
        """One fused round trip over many inner commands (gradient
        coalescing): the whole bucket rides a single idempotency token,
        so crash-replay applies it exactly once.  Returns the inner
        payloads in order; the first inner error raises."""
        replies = self._rpc("multi", list(msgs))
        out = []
        for r in replies:
            if r[0] == "nack":
                raise NonFiniteGradientError(
                    "kvstore server rejected push: %s"
                    % (r[2] if len(r) > 2 else r[1],))
            if r[0] != "ok":
                raise MXNetError("kvstore server error: %s" % (r[1],))
            out.append(r[1] if len(r) > 1 else None)
        return out

    def set_optimizer(self, optimizer, is_recovery=False):
        self._rpc("set_optimizer",
                  pickle.dumps(optimizer, pickle.HIGHEST_PROTOCOL),
                  int(is_recovery))

    # -- sparse plane (wire v2) --------------------------------------------
    def init_table(self, key, meta):
        """Declare a sharded embedding table on this server: ``meta``
        carries row_shape/dtype/init/num_servers/server_index/num_rows.
        Idempotent — every worker declares every table."""
        self._rpc("init_table", key, dict(meta))

    def push_rows(self, key, row_ids, values, rank=0):
        """Push a row-sparse gradient block (ids must be this shard's)."""
        self._rpc("push_rows", key,
                  np.asarray(row_ids, dtype=np.int64), np.asarray(values),
                  rank)

    def pull_rows(self, key, row_ids):
        """Fetch rows by id; absent rows materialize deterministically."""
        return self._rpc("pull_rows", key,
                         np.asarray(row_ids, dtype=np.int64))

    def table_info(self, key=None):
        """Shard audit: per-table row/byte counts, misplaced-row count,
        and meta for this server (the kvstore_admin surface)."""
        return self._rpc("table_info", key)

    def set_sparse_optimizer(self, updater, is_recovery=False):
        self._rpc("set_sparse_optimizer",
                  pickle.dumps(updater, pickle.HIGHEST_PROTOCOL),
                  int(is_recovery))

    def barrier(self, rank=0, is_recovery=False):
        self._rpc("barrier", rank, int(is_recovery))

    def snapshot(self):
        """Force a durable server snapshot now; returns its path."""
        return self._rpc("snapshot")

    def stop_server(self):
        # a single retry only: once the server acks and exits, replaying
        # into a dead address would just burn the whole backoff budget
        self._rpc("stop", retries=1)

    # -- lifecycle ---------------------------------------------------------
    def close(self):
        """Idempotent teardown: stop + join the heartbeat and reader
        threads, close the RPC socket, fail any remaining in-flight
        waiters.  Safe to call any number of times."""
        with self._state_cv:
            if self._closed:
                return
            self._closed = True
            self._state_cv.notify_all()
        if self._hb_stop is not None:
            self._hb_stop.set()
        if self._hb_thread is not None:
            self._hb_thread.join(timeout=5)
            self._hb_thread = None
        with self._send_lock:
            self._kill_sock_locked()
        reader = getattr(self, "_reader", None)
        if reader is not None and reader is not threading.current_thread():
            reader.join(timeout=5)
        with self._state_cv:
            ents = list(self._inflight.values())
            self._inflight.clear()
        for ent in ents:
            ent["exc"] = ConnectionError("ServerClient is closed")
            ent["event"].set()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def start_server(host="127.0.0.1", port=0, num_workers=1, sync_mode=False,
                 snapshot_path=None, snapshot_interval=None,
                 evict_timeout=None):
    """Start a server in this process (background thread); returns it."""
    srv = KVStoreServer(host, port, num_workers, sync_mode,
                        snapshot_path=snapshot_path,
                        snapshot_interval=snapshot_interval,
                        evict_timeout=evict_timeout)
    srv.start_background()
    return srv


def _init_kvstore_server_module():
    """Reference bootstrap (python/mxnet/kvstore_server.py:11-58): processes
    launched with DMLC_ROLE=server run the serving loop then exit."""
    role = os.environ.get("DMLC_ROLE", "")
    if role != "server":
        return
    # Address resolution (clients derive the matching list in
    # DistAsyncKVStore): DMLC_SERVER_URIS ("h1:p1,h2:p2", the ssh
    # launcher's authoritative assignment) wins; otherwise server i
    # listens on DMLC_PS_ROOT_URI : root_port + i.  Big arrays are
    # range-split across the fleet (reference kvstore_dist.h:264-302).
    server_id = int(os.environ.get("DMLC_SERVER_ID", "0"))
    uris = os.environ.get("DMLC_SERVER_URIS")
    if uris:
        entry = uris.split(",")[server_id]
        host, _, p = entry.rpartition(":")
        port = int(p)
    else:
        host = os.environ.get("DMLC_PS_ROOT_URI", "127.0.0.1")
        port = int(os.environ.get("DMLC_PS_ROOT_PORT", "9091")) + server_id
    num_workers = int(os.environ.get("DMLC_NUM_WORKER", "1"))
    sync = os.environ.get("MXNET_KVSTORE_SYNC", "0") == "1"
    # each server of a fleet journals to its own snapshot file — the env
    # var names the shared prefix, the id keeps them from clobbering
    snap = os.environ.get("MXNET_KVSTORE_SNAPSHOT_PATH") or None
    if snap and server_id:
        snap = "%s.%d" % (snap, server_id)
    srv = KVStoreServer(host, port, num_workers, sync_mode=sync,
                        snapshot_path=snap)
    srv.serve_forever()
    raise SystemExit(0)


_init_kvstore_server_module()

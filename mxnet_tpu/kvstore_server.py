"""Host-side parameter service — the ``dist_async`` control/data plane.

TPU-native stance (SURVEY.md §2.4, §5.8): synchronous data parallelism needs
no server — gradients are ``psum``'d inside the jitted step over ICI. What a
server still buys is the reference's *asynchronous* PS semantics
(/root/reference/src/kvstore/kvstore_dist_server.h:87-260: updater runs on
every push immediately, workers never wait for each other) plus the
coordination plane (barriers, optimizer shipping, cooperative stop —
kSyncMode/kStopServer commands, kvstore_dist_server.h:121-134). This module
provides both over DCN-style TCP with length-prefixed pickles replacing
ps-lite/ZeroMQ.

Bootstrap parity with python/mxnet/kvstore_server.py:11-58: importing
mxnet_tpu in a process whose ``DMLC_ROLE=server`` starts the server loop and
exits when a stop command arrives.

.. warning:: **Trust model** — same as the reference's ps-lite: the wire
   format is unauthenticated length-prefixed pickles, so any peer that can
   connect to the server port gets arbitrary code execution in the server
   process.  Deploy only on a trusted, isolated network (the training
   cluster's fabric).  The default bind address is 127.0.0.1; setting
   ``DMLC_PS_ROOT_URI`` to a non-loopback address widens exposure to that
   interface — do so only behind a network boundary you control.
"""
from __future__ import annotations

import os
import pickle
import socket
import socketserver
import struct
import threading
import time
from typing import Dict, Optional

import numpy as np

# bound at module import (on the importing thread) — request-handler threads
# must NOT run `from . import ...`: under the DMLC_ROLE=server bootstrap the
# main thread is still inside the package import and holds the import lock,
# so a handler-side relative import deadlocks the whole server
from . import ndarray as nd
from . import optimizer as opt

__all__ = ["KVStoreServer", "start_server", "ServerClient",
           "_init_kvstore_server_module"]

# wire: 1 version byte, <payload_len, n_bufs> header, n_bufs buffer
# lengths, pickled metadata, then the raw array buffers OUT OF BAND
# (pickle protocol 5 buffer_callback) — array bytes go straight from the
# caller's memory to per-buffer sendall with no pickle-side copy; the copy
# was the measured bottleneck of the dist_async plane at exactly the
# big-key sizes the range split targets (PERF.md table).  The leading
# version byte turns a mixed-version worker/server pair into a clear
# error instead of a confusing unpickling failure mid-stream.
_WIRE_VERSION = 1
_HDR = struct.Struct("<QI")
_LEN = struct.Struct("<Q")


def _send_msg(sock, obj):
    bufs = []
    payload = pickle.dumps(obj, protocol=5, buffer_callback=bufs.append)
    try:
        raws = [b.raw() for b in bufs]
    except BufferError:
        # non-contiguous ndarray reached the wire (sliced/transposed
        # views can't expose a flat buffer): fall back to in-band
        # protocol-5 pickling, which copies into contiguous form
        payload = pickle.dumps(obj, protocol=5)
        raws = []
    head = bytes([_WIRE_VERSION]) + _HDR.pack(len(payload), len(raws))
    lens = b"".join(_LEN.pack(r.nbytes) for r in raws)
    sock.sendall(head + lens + payload)  # small metadata: one copy
    for r in raws:                       # array bytes: zero-copy sendall
        sock.sendall(r)


def _recv_exact(sock, n):
    buf = bytearray(n)
    view = memoryview(buf)
    got = 0
    while got < n:
        r = sock.recv_into(view[got:], n - got)
        if not r:
            raise ConnectionError("peer closed")
        got += r
    return buf


def _recv_msg(sock):
    ver = _recv_exact(sock, 1)[0]
    if ver != _WIRE_VERSION:
        raise ConnectionError(
            "kvstore wire version mismatch: peer sent %d, this process "
            "speaks %d — worker and server run different mxnet_tpu "
            "builds" % (ver, _WIRE_VERSION))
    n, nbuf = _HDR.unpack(_recv_exact(sock, _HDR.size))
    lens = []
    if nbuf:
        raw = _recv_exact(sock, _LEN.size * nbuf)
        lens = [_LEN.unpack_from(raw, i * _LEN.size)[0]
                for i in range(nbuf)]
    payload = _recv_exact(sock, n)
    bufs = [_recv_exact(sock, ln) for ln in lens]
    return pickle.loads(payload, buffers=bufs)


class KVStoreServer:
    """Async parameter server: per-key store + updater applied on every
    push (async mode, kvstore_dist_server.h:198-206) or after all workers'
    pushes merge (sync mode, :164-179)."""

    def __init__(self, host="127.0.0.1", port=0, num_workers=1,
                 sync_mode=False):
        self.num_workers = num_workers
        self.sync_mode = sync_mode
        self.store: Dict[object, np.ndarray] = {}
        self.updater = None
        self._lock = threading.Lock()  # single-threaded-executor parity
        self._barrier_ranks = set()
        self._barrier_gen = 0
        self._barrier_cv = threading.Condition()
        self._merge: Dict[object, list] = {}
        self._stop = threading.Event()
        # liveness: rank -> monotonic time of last heartbeat (reference:
        # ps::Postoffice node tracking behind GetDeadNodes,
        # kvstore_dist.h:151-160)
        self._heartbeats: Dict[int, float] = {}
        server_self = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                try:
                    while True:
                        msg = _recv_msg(self.request)
                        try:
                            reply = server_self._dispatch(msg)
                        except Exception as e:  # keep serving; tell the client
                            reply = ("err", "%s: %s" % (type(e).__name__, e))
                        _send_msg(self.request, reply)
                        if msg[0] == "stop":
                            break
                except (ConnectionError, OSError):
                    pass

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = Server((host, port), Handler)
        self.addr = self._server.server_address

    # -- message dispatch --------------------------------------------------
    def _dispatch(self, msg):
        cmd = msg[0]
        if cmd == "init":
            _, key, arr = msg
            with self._lock:
                self.store.setdefault(key, np.array(arr))
            return ("ok",)
        if cmd == "push":
            key, arr = msg[1], msg[2]
            rank = msg[3] if len(msg) > 3 else 0
            with self._lock:
                if self.sync_mode:
                    # per-worker rounds: a fast worker's next-iteration push
                    # must not count toward the current round
                    # (kvstore_dist_server.h:164-179 merges one push per
                    # worker before the update fires)
                    rounds = self._merge.setdefault(key, [])
                    placed = False
                    for rnd in rounds:
                        if rank not in rnd:
                            rnd[rank] = np.asarray(arr)
                            placed = True
                            break
                    if not placed:
                        rounds.append({rank: np.asarray(arr)})
                    if rounds and len(rounds[0]) >= self.num_workers:
                        merged = np.sum(list(rounds.pop(0).values()), axis=0)
                        self._apply(key, merged)
                else:
                    self._apply(key, np.asarray(arr))
            return ("ok",)
        if cmd == "pull":
            _, key = msg
            with self._lock:
                if key not in self.store:
                    return ("err", "uninitialized key %r" % (key,))
                return ("ok", self.store[key])
        if cmd == "set_optimizer":
            is_recovery = bool(msg[2]) if len(msg) > 2 else False
            optimizer = pickle.loads(msg[1])
            with self._lock:
                # a rejoining rank 0 re-ships the optimizer it launched
                # with; installing it fresh would reset live momentum
                # state mid-training — keep the installed updater
                if not (is_recovery and self.updater is not None):
                    self.updater = opt.get_updater(optimizer)
            return ("ok",)
        if cmd == "heartbeat":
            rank = int(msg[1])
            with self._lock:
                self._heartbeats[rank] = time.monotonic()
            return ("ok",)
        if cmd == "dead_nodes":
            timeout_s = float(msg[1]) if len(msg) > 1 else 60.0
            return ("ok", self._dead_nodes(timeout_s))
        if cmd == "barrier":
            rank = int(msg[1]) if len(msg) > 1 else 0
            is_recovery = bool(msg[2]) if len(msg) > 2 else False
            timeout = float(os.environ.get("MXNET_KVSTORE_BARRIER_TIMEOUT",
                                           "600"))
            hb_timeout = float(os.environ.get(
                "MXNET_KVSTORE_DEAD_TIMEOUT", "60"))
            deadline = time.monotonic() + timeout
            with self._barrier_cv:
                # rejoin semantics (reference kvstore_dist.h:35-38): a
                # recovered worker skips a barrier only when the job has
                # passed startup (a generation completed) AND no peers
                # are currently parked at one — if they are, it must
                # join and release them (they count num_workers arrivals
                # and would otherwise wedge until the timeout). Arrivals
                # are tracked per RANK so a worker that crashed after
                # arriving cannot double-count on rejoin.
                if (is_recovery and self._barrier_gen > 0
                        and not self._barrier_ranks):
                    return ("ok",)
                gen = self._barrier_gen
                self._barrier_ranks.add(rank)
                if len(self._barrier_ranks) >= self.num_workers:
                    self._barrier_ranks = set()
                    self._barrier_gen += 1
                    self._barrier_cv.notify_all()
                    return ("ok",)
                # wake periodically: a dead peer (stale heartbeat) releases
                # the barrier with an error instead of hanging the job until
                # the full timeout (reference: GetDeadNodes lets callers
                # observe the failure; a dead worker otherwise wedges the
                # server's merge-until-NumWorkers forever)
                while True:
                    released = self._barrier_cv.wait_for(
                        lambda: self._barrier_gen != gen,
                        timeout=min(1.0, max(deadline - time.monotonic(),
                                             0.01)))
                    if released:
                        return ("ok",)
                    dead = self._dead_nodes(hb_timeout)
                    if dead:
                        if self._barrier_gen == gen:
                            self._barrier_ranks.discard(rank)
                        return ("err", "barrier aborted: dead workers %s"
                                % dead)
                    if time.monotonic() >= deadline:
                        if self._barrier_gen == gen:
                            self._barrier_ranks.discard(rank)
                        return ("err",
                                "barrier timed out after %.0fs" % timeout)
        if cmd == "stop":
            self._stop.set()
            threading.Thread(target=self._server.shutdown,
                             daemon=True).start()
            return ("ok",)
        return ("err", "unknown command %r" % (cmd,))

    def _dead_nodes(self, timeout_s):
        """Ranks whose last heartbeat is older than ``timeout_s`` (only
        ranks that have ever heartbeated are tracked — a worker that never
        connected is the launcher's problem, as in the reference)."""
        now = time.monotonic()
        with self._lock:
            return sorted(r for r, t in self._heartbeats.items()
                          if now - t > timeout_s)

    def _apply(self, key, grad):
        """Run the updater (reference DataHandle: updater_(key, recved,
        &stored)); without one, accumulate like the reference default."""
        if key not in self.store:
            self.store[key] = np.array(grad)
            return
        if self.updater is None:
            self.store[key] = self.store[key] + grad
            return
        weight = nd.array(self.store[key])
        self.updater(key, nd.array(grad), weight)
        self.store[key] = weight.asnumpy()

    # -- lifecycle ---------------------------------------------------------
    def serve_forever(self):
        self._server.serve_forever(poll_interval=0.05)

    def start_background(self):
        t = threading.Thread(target=self.serve_forever, daemon=True)
        t.start()
        return t

    def stop(self):
        self._stop.set()
        self._server.shutdown()
        self._server.server_close()


class ServerClient:
    """Worker-side connection to a KVStoreServer (the ps::KVWorker role)."""

    def __init__(self, host, port):
        self._addr = (host, port)
        self._sock = socket.create_connection((host, port), timeout=120)
        self._lock = threading.Lock()
        self._hb_stop = None

    def start_heartbeat(self, rank, interval=5.0):
        """Publish liveness for ``rank`` every ``interval`` seconds on a
        daemon thread (ps-lite node heartbeats; feeds the server's
        dead-node tracking).  Uses its OWN connection: the main RPC socket
        can sit inside a long blocking barrier() round trip, and a worker
        waiting at a barrier must not go heartbeat-silent (that would make
        the dead-peer barrier release see live stragglers as dead)."""
        if self._hb_stop is not None:
            return
        self._hb_stop = threading.Event()
        stop = self._hb_stop
        addr = self._addr
        self.heartbeat(rank)  # immediate first beat on the main socket

        def loop():
            try:
                sock = socket.create_connection(addr, timeout=30)
            except OSError:
                return
            try:
                while not stop.wait(interval):
                    _send_msg(sock, ("heartbeat", rank))
                    reply = _recv_msg(sock)
                    if reply[0] != "ok":
                        return
            except Exception:
                return  # connection gone: the server will see us dead
            finally:
                try:
                    sock.close()
                except OSError:
                    pass

        threading.Thread(target=loop, daemon=True).start()

    def heartbeat(self, rank):
        self._rpc("heartbeat", rank)

    def dead_nodes(self, timeout_s=60.0):
        return self._rpc("dead_nodes", timeout_s)

    def _rpc(self, *msg):
        with self._lock:
            _send_msg(self._sock, msg)
            reply = _recv_msg(self._sock)
        if reply[0] != "ok":
            from .base import MXNetError

            raise MXNetError("kvstore server error: %s" % (reply[1],))
        return reply[1] if len(reply) > 1 else None

    def init(self, key, arr):
        self._rpc("init", key, np.asarray(arr))

    def push(self, key, arr, rank=0):
        self._rpc("push", key, np.asarray(arr), rank)

    def pull(self, key):
        return self._rpc("pull", key)

    def set_optimizer(self, optimizer, is_recovery=False):
        self._rpc("set_optimizer",
                  pickle.dumps(optimizer, pickle.HIGHEST_PROTOCOL),
                  int(is_recovery))

    def barrier(self, rank=0, is_recovery=False):
        self._rpc("barrier", rank, int(is_recovery))

    def stop_server(self):
        self._rpc("stop")

    def close(self):
        self._sock.close()


def start_server(host="127.0.0.1", port=0, num_workers=1, sync_mode=False):
    """Start a server in this process (background thread); returns it."""
    srv = KVStoreServer(host, port, num_workers, sync_mode)
    srv.start_background()
    return srv


def _init_kvstore_server_module():
    """Reference bootstrap (python/mxnet/kvstore_server.py:11-58): processes
    launched with DMLC_ROLE=server run the serving loop then exit."""
    role = os.environ.get("DMLC_ROLE", "")
    if role != "server":
        return
    # Address resolution (clients derive the matching list in
    # DistAsyncKVStore): DMLC_SERVER_URIS ("h1:p1,h2:p2", the ssh
    # launcher's authoritative assignment) wins; otherwise server i
    # listens on DMLC_PS_ROOT_URI : root_port + i.  Big arrays are
    # range-split across the fleet (reference kvstore_dist.h:264-302).
    server_id = int(os.environ.get("DMLC_SERVER_ID", "0"))
    uris = os.environ.get("DMLC_SERVER_URIS")
    if uris:
        entry = uris.split(",")[server_id]
        host, _, p = entry.rpartition(":")
        port = int(p)
    else:
        host = os.environ.get("DMLC_PS_ROOT_URI", "127.0.0.1")
        port = int(os.environ.get("DMLC_PS_ROOT_PORT", "9091")) + server_id
    num_workers = int(os.environ.get("DMLC_NUM_WORKER", "1"))
    sync = os.environ.get("MXNET_KVSTORE_SYNC", "0") == "1"
    srv = KVStoreServer(host, port, num_workers, sync_mode=sync)
    srv.serve_forever()
    raise SystemExit(0)


_init_kvstore_server_module()

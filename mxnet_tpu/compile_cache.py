"""Persistent XLA compilation cache + AOT executable bundles.

ROADMAP item 5: every process used to pay full XLA compilation on
startup — a fresh serving replica warmed every bucket through the
compiler, an elastic fresh-rank joiner recompiled the fused step its
peers were already running, and a hot-swap shadow replica recompiled
before it could flip.  This module makes the compiled executable itself
a durable, content-addressed artifact (the TVM compile-artifact-reuse
idea, arXiv:1802.04799, applied at the XLA executable layer):

* every lowered program the executor stack builds (fused train step,
  forward, forward+backward — and therefore every serving bucket) is
  keyed by a **content fingerprint**: the batch signature of its
  arguments (the StepMonitor recompile detector's machinery), a hash of
  the symbol graph, the static trace knobs (mixed-precision dtype,
  remat, ctx-group placement, grad_req partition, optimizer family and
  hypers), and the stable sharding fingerprint (mesh axes/devices +
  PartitionSpecs);
* on miss the program is lowered and compiled exactly as before, then
  the executable is serialized (``jax.experimental.serialize_executable``)
  into an atomic, CRC-checked cache entry (same tmp+fsync+rename
  discipline as checkpoints);
* on hit the executable deserializes in milliseconds and **no XLA
  compilation happens at all**.

Environment compatibility (jax/jaxlib version, backend, device
kind/count, process count) is recorded in every entry and checked at
load: a mismatched entry is a miss (invalidation), never a crash.  Cache
I/O is a ``faults`` dotted op (``compile_cache.load`` /
``compile_cache.store``) so chaos tests can prove a corrupt or torn
entry degrades to a plain recompile.  Telemetry:
``mxtpu_compile_cache_hits_total`` / ``_misses_total`` /
``_stores_total`` / ``_errors_total`` plus compile-ms vs deserialize-ms
histograms.

AOT bundles (``checkpoint.save_aot_bundle``) re-pack the live entries a
serving process is running into a directory next to the params, with a
warmup manifest — a new replica attaches the bundle as a read-only
cache overlay and its whole warmup is deserialize-only.

Enable with ``MXNET_COMPILE_CACHE_DIR=/path`` (empty default = off: the
executor stack behaves exactly as before).
"""
from __future__ import annotations

import json
import os
import pickle
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from .artifact_store import EntryStore, digest_of
from .base import MXNetError, env, register_env

__all__ = [
    "enabled", "cache_dir", "env_fingerprint", "stats", "reset_stats",
    "maybe_cached", "CachedFunction", "attach_bundle", "detach_bundles",
    "save_bundle", "read_manifest", "ls_entries", "verify_entry", "prune",
    "entry_meta", "MANIFEST_NAME", "ENTRY_SUFFIX",
]

register_env("MXNET_COMPILE_CACHE_DIR", "", str,
             "Directory for the persistent framework-level compilation "
             "cache (serialized XLA executables, content-fingerprint "
             "keyed). Empty disables the cache entirely.")
register_env("MXNET_COMPILE_CACHE_MAX_MB", 2048, int,
             "Size budget for the compile-cache directory; after a store "
             "the oldest entries (by mtime) are pruned until under "
             "budget. <= 0 disables pruning.")
register_env("MXNET_COMPILE_CACHE_STRICT", 0, int,
             "1 makes cache load/store failures raise instead of "
             "degrading to a plain recompile (debugging aid; production "
             "keeps 0: a broken cache must never break the job).")
register_env("MXNET_COMPILE_CACHE_MIN_MS", 0.0, float,
             "Only compilations that took at least this many ms are "
             "stored (0 stores everything). Skips serializing trivial "
             "programs whose recompile is cheaper than the disk entry.")

_MAGIC = b"MXTPUCC1"
_SCHEMA = 1
ENTRY_SUFFIX = ".mxc"
MANIFEST_NAME = "manifest.json"

# on-disk grammar + admin shared with the autotune TuningDB via
# artifact_store (one implementation, two artifact families)
_STORE = EntryStore(_MAGIC, ENTRY_SUFFIX, "compile-cache", "compile_cache")

_lock = threading.Lock()
# process-wide loaded-executable cache: a hot-swap shadow replica in the
# same process inherits the outgoing replica's executables without even
# touching the disk.  key digest -> (callable, meta)
_mem: Dict[str, Tuple[Any, dict]] = {}
# read-only overlay directories (attached AOT bundles), searched after
# the primary cache dir
_bundles: List[str] = []
_env_fp_cache: Optional[dict] = None


def enabled() -> bool:
    return bool(env("MXNET_COMPILE_CACHE_DIR", "", str))


def active() -> bool:
    """True when executables may come out of (or go into) the cache:
    the on-disk cache is enabled or an AOT bundle overlay is attached.
    The executor stack uses this to build cache-eligible programs
    without buffer donation — XLA's executable deserializer has been
    observed to mis-bind donated (input-output aliased) arguments that
    share a shape, so persisted executables must not rely on it."""
    return enabled() or bool(_bundles)


def cache_dir() -> str:
    return env("MXNET_COMPILE_CACHE_DIR", "", str)


def _strict() -> bool:
    return bool(env("MXNET_COMPILE_CACHE_STRICT", 0, int))


# ---------------------------------------------------------------------------
# telemetry instruments (global registry; cheap even with telemetry off —
# these fire once per executable build, never per step)
# ---------------------------------------------------------------------------

_instruments = None


def _metrics():
    global _instruments
    if _instruments is None:
        from . import telemetry as tm

        reg = tm.registry()
        _instruments = {
            "hits": reg.counter(
                "mxtpu_compile_cache_hits_total",
                "Executable builds satisfied by deserializing a cache "
                "entry (no XLA compilation)."),
            "misses": reg.counter(
                "mxtpu_compile_cache_misses_total",
                "Executable builds that had to run the XLA compiler."),
            "stores": reg.counter(
                "mxtpu_compile_cache_stores_total",
                "Cache entries written."),
            "errors": reg.counter(
                "mxtpu_compile_cache_errors_total",
                "Cache load/store failures degraded to a recompile "
                "(corrupt entry, torn write, injected fault)."),
            "compile_ms": reg.histogram(
                "mxtpu_compile_ms",
                "XLA compile time per cache-miss executable build (ms).",
                start=1.0, factor=4.0, count=12),
            "deserialize_ms": reg.histogram(
                "mxtpu_compile_cache_deserialize_ms",
                "Executable deserialize time per cache hit (ms).",
                start=0.25, factor=4.0, count=12),
        }
    return _instruments


def _log_event(kind, **fields):
    try:
        from . import telemetry as tm

        tm.log_event(kind, **fields)
    except Exception:
        pass


def stats() -> dict:
    """Compact counters for BENCH / capture records."""
    m = _metrics()
    return {
        "dir": cache_dir() or None,
        "hits": m["hits"].value,
        "misses": m["misses"].value,
        "stores": m["stores"].value,
        "errors": m["errors"].value,
        "compile_ms": round(m["compile_ms"].sum, 1),
        "deserialize_ms": round(m["deserialize_ms"].sum, 1),
    }


def reset_stats() -> None:
    """Test hook: drop instrument handles (a telemetry registry reset
    leaves stale handles otherwise) and the in-memory executable cache."""
    global _instruments
    with _lock:
        _instruments = None
        _mem.clear()
        del _bundles[:]


# ---------------------------------------------------------------------------
# fingerprinting
# ---------------------------------------------------------------------------

def env_fingerprint() -> dict:
    """The compatibility envelope an executable is only valid inside:
    jax/jaxlib versions, backend platform, device kind and count, process
    count.  Recorded in every entry and checked at load — any mismatch
    invalidates (a miss, never a crash)."""
    global _env_fp_cache
    if _env_fp_cache is None:
        import jax
        import jaxlib

        devs = jax.devices()
        _env_fp_cache = {
            "schema": _SCHEMA,
            "jax": jax.__version__,
            "jaxlib": jaxlib.__version__,
            "platform": jax.default_backend(),
            "device_kind": devs[0].device_kind if devs else "none",
            "device_count": len(devs),
            "process_count": jax.process_count(),
        }
    return dict(_env_fp_cache)


def _signature(args) -> dict:
    """The batch-signature half of the key: (shape, dtype) per leaf plus
    the pytree structure (which pins argument names and None slots)."""
    import jax

    leaves, treedef = jax.tree_util.tree_flatten(args)
    import numpy as np

    sig = []
    for leaf in leaves:
        shape = tuple(getattr(leaf, "shape", ()))
        dtype = str(getattr(leaf, "dtype", np.asarray(leaf).dtype))
        sig.append([list(shape), dtype])
    return {"tree": str(treedef), "leaves": sig}


_digest = digest_of


# ---------------------------------------------------------------------------
# entry file format:  MAGIC | u64 meta_len | meta json | pickle(payload)
# with a CRC32 sidecar — the shared artifact_store grammar
# ---------------------------------------------------------------------------

def _entry_path(d: str, digest: str) -> str:
    return _STORE.entry_path(d, digest)


def entry_meta(path: str) -> dict:
    """Parse just the json header of an entry (no unpickling)."""
    return _STORE.entry_meta(path)


def _write_entry(d: str, digest: str, meta: dict, payload_bytes: bytes,
                 op: str = "compile_cache.store") -> str:
    return _STORE.write_entry(d, digest, meta, payload_bytes, op=op)


def _read_payload(path: str) -> Tuple[dict, bytes]:
    return _STORE.read_payload(path)


def _env_compatible(meta: dict) -> bool:
    return meta.get("env") == env_fingerprint()


# ---------------------------------------------------------------------------
# load / store
# ---------------------------------------------------------------------------

def _read_dirs() -> List[str]:
    d = cache_dir()
    out = [d] if d else []
    with _lock:
        out.extend(_bundles)
    return out


def _load(digest: str):
    """-> (callable, meta) or None.  Every failure mode — missing file,
    CRC mismatch, torn header, unpicklable payload, injected fault —
    degrades to None (a miss) with a structured telemetry event."""
    with _lock:
        hit = _mem.get(digest)
    if hit is not None:
        return hit
    from . import faults
    from .filesystem import verify_crc_sidecar

    for d in _read_dirs():
        path = _entry_path(d, digest)
        if not os.path.exists(path):
            continue
        try:
            faults.fire("compile_cache.load")
            ok = verify_crc_sidecar(path)
            if ok is False:
                raise MXNetError("CRC mismatch")
            meta, payload = _read_payload(path)
            if not _env_compatible(meta):
                _log_event("compile_cache_invalidate", path=path,
                           entry_env=meta.get("env"),
                           current_env=env_fingerprint())
                continue  # stale-version entry: a miss, not an error
            from jax.experimental import serialize_executable as se

            t0 = time.perf_counter()
            loaded = se.deserialize_and_load(*pickle.loads(payload))
            ms = (time.perf_counter() - t0) * 1e3
            _metrics()["deserialize_ms"].observe(ms)
            with _lock:
                _mem[digest] = (loaded, meta)
            _log_event("compile_cache_hit", digest=digest, path=path,
                       deserialize_ms=round(ms, 3))
            return loaded, meta
        except Exception as exc:
            _metrics()["errors"].inc()
            _log_event("compile_cache_corrupt", path=path,
                       error=repr(exc)[:300])
            if _strict():
                raise
            continue
    return None


def _store(digest: str, compiled, meta: dict, compile_ms: float) -> Optional[str]:
    d = cache_dir()
    if not d:
        return None
    min_ms = env("MXNET_COMPILE_CACHE_MIN_MS", 0.0, float)
    if compile_ms < min_ms:
        return None
    try:
        from jax.experimental import serialize_executable as se

        payload = pickle.dumps(se.serialize(compiled))
        path = _write_entry(d, digest, meta, payload)
        _metrics()["stores"].inc()
        _log_event("compile_cache_store", digest=digest, path=path,
                   bytes=len(payload), compile_ms=round(compile_ms, 1))
        budget = env("MXNET_COMPILE_CACHE_MAX_MB", 2048, int)
        if budget > 0:
            prune(d, budget)
        return path
    except Exception as exc:
        _metrics()["errors"].inc()
        _log_event("compile_cache_store_failed", digest=digest,
                   error=repr(exc)[:300])
        if _strict():
            raise
        return None


# ---------------------------------------------------------------------------
# the executor-facing wrapper
# ---------------------------------------------------------------------------

class CachedFunction:
    """Lazy cache-aware stand-in for a ``jax.jit`` callable.

    The first call under each argument signature fingerprints the
    concrete arguments, consults the cache (memory, then the cache dir,
    then attached bundles), and either deserializes the executable
    (hit: no XLA compilation) or AOT-compiles via ``lower().compile()``
    and stores the result.  Subsequent calls with the same signature go
    straight to the loaded executable; a NEW signature re-primes — the
    same retrace-on-shape-change contract as plain ``jax.jit``.  Any
    cache malfunction falls back to the wrapped jit callable, which
    behaves exactly as if the cache never existed.
    """

    __slots__ = ("_fn", "_kind", "_static_key", "_executor", "_by_sig",
                 "records", "digest", "meta", "cost_info", "cache_state")

    def __init__(self, fn, kind: str, static_key, executor):
        self._fn = fn
        self._kind = kind
        self._static_key = static_key
        self._executor = executor
        self._by_sig: Dict[Any, Any] = {}
        # one record per primed signature (bundle export reads these):
        # {"digest", "meta", "compiled" (live Compiled on miss else None)}
        self.records: List[dict] = []
        # most-recent prime, for the executor/introspection wiring
        self.digest = None
        self.meta = None
        self.cost_info = None
        self.cache_state = None  # "hit" | "miss" | "bypass"

    # delegation keeps telemetry.lower_and_analyze / perf_probe working
    # against the introspection hook unchanged
    def lower(self, *args, **kwargs):
        return self._fn.lower(*args, **kwargs)

    @staticmethod
    def _quick_sig(args):
        """Hashable per-call signature — the dispatch key.  Cheap
        (no hashing/serialization): treedef + leaf shapes/dtypes."""
        import jax

        leaves, treedef = jax.tree_util.tree_flatten(args)
        return (treedef, tuple(
            (tuple(getattr(l, "shape", ())), str(getattr(l, "dtype", "")))
            for l in leaves))

    def __call__(self, *args):
        fn = self._by_sig.get(self._quick_sig(args))
        if fn is None:
            fn = self._prime(args)
        return fn(*args)

    def _key_parts(self, args) -> dict:
        ex = self._executor
        plan = ex._plan
        parts = {
            "schema": _SCHEMA,
            "kind": self._kind,
            "static": repr(self._static_key),
            "graph": plan.fingerprint(),
            "compute_dtype": str(ex._compute_dtype),
            "cast_exclude": sorted(ex._cast_exclude),
            "remat": int(env("MXNET_BACKWARD_DO_MIRROR", 0, int) or 0),
            "group2ctx": sorted(
                (g, str(c)) for g, c in ex._group2ctx.items()),
            "sig": _signature(args),
        }
        # tuned and untuned executables must never collide: when the
        # autotuner is active its DB-state fingerprint joins the key (a
        # different set of winners is a different program)
        try:
            from . import autotune as _at

            at_fp = _at.cache_fingerprint()
        except Exception:
            at_fp = None
        if at_fp is not None:
            parts["autotune"] = at_fp
        if ex._shard_mesh is not None:
            from .sharding.mesh import mesh_fingerprint

            parts["shard"] = {
                "mesh": mesh_fingerprint(ex._shard_mesh),
                "specs": sorted((k, str(v))
                                for k, v in ex._shard_specs.items()),
            }
        return parts

    def _register(self, sig, fn, state, digest=None, meta=None,
                  compiled=None):
        self._by_sig[sig] = fn
        self.cache_state = state
        self.digest = digest
        self.meta = meta
        self.cost_info = (meta or {}).get("cost") or None
        if digest is not None:
            self.records.append(
                {"digest": digest, "meta": meta, "compiled": compiled})
        return fn

    def _prime(self, args):
        sig = self._quick_sig(args)
        digest = None
        try:
            parts = self._key_parts(args)
            digest = _digest(parts)
            hit = _load(digest)
        except Exception as exc:
            if _strict():
                raise
            _metrics()["errors"].inc()
            _log_event("compile_cache_key_failed", kind=self._kind,
                       error=repr(exc)[:300])
            hit = None
            if digest is None:
                # can't even fingerprint: bypass the cache entirely
                return self._register(sig, self._fn, "bypass")
        if hit is not None:
            loaded, meta = hit
            _metrics()["hits"].inc()
            return self._register(sig, loaded, "hit", digest, meta)
        # miss: compile exactly as the plain jit path would, then store
        _metrics()["misses"].inc()
        try:
            t0 = time.perf_counter()
            compiled = self._fn.lower(*args).compile()
            compile_ms = (time.perf_counter() - t0) * 1e3
        except Exception:
            # AOT lowering unsupported for this program: run the plain
            # jit callable (compiles internally, uncached)
            return self._register(sig, self._fn, "bypass")
        _metrics()["compile_ms"].observe(compile_ms)
        cost = _cost_of(compiled)
        meta = self._build_meta(digest, compile_ms, cost)
        with _lock:
            _mem[digest] = (compiled, meta)
        _store(digest, compiled, meta, compile_ms)
        return self._register(sig, compiled, "miss", digest, meta, compiled)

    def _build_meta(self, digest, compile_ms, cost) -> dict:
        ex = self._executor
        mesh_axes = None
        if ex._shard_mesh is not None:
            mesh = ex._shard_mesh
            mesh_axes = {str(n): int(mesh.shape[n]) for n in mesh.axis_names}
        return {
            "digest": digest,
            "kind": self._kind,
            "env": env_fingerprint(),
            "mesh_axes": mesh_axes,
            "created": round(time.time(), 3),
            "compile_ms": round(compile_ms, 1),
            "cost": cost,
        }


def _cost_of(compiled) -> Optional[dict]:
    from .hlo_analysis import cost_analysis

    return cost_analysis(compiled)


def maybe_cached(fn, kind: str, static_key, executor):
    """Executor hook: wrap a jit callable in a :class:`CachedFunction`
    when the cache is enabled, else return it untouched (the default —
    zero behavior change with no cache dir configured)."""
    if not enabled() and not _bundles:
        return fn
    return CachedFunction(fn, kind, static_key, executor)


# ---------------------------------------------------------------------------
# AOT bundles — a read-only cache overlay saved beside a checkpoint
# ---------------------------------------------------------------------------

def save_bundle(path: str, entries, warmup: Optional[dict] = None) -> str:
    """Write an AOT executable bundle: one cache entry per compiled
    program in ``entries`` (:class:`CachedFunction` wrappers, typically
    every bucket of a serving replica) plus ``manifest.json`` recording
    the warmup recipe and the environment fingerprint.  Entries whose
    executable came from the cache are copied from their source entry
    file; fresh compiles are serialized directly."""
    os.makedirs(path, exist_ok=True)
    from .filesystem import atomic_write

    manifest = {
        "schema": _SCHEMA,
        "env": env_fingerprint(),
        "created": round(time.time(), 3),
        "warmup": warmup or {},
        "entries": [],
    }
    seen = set()
    for wrapper in entries:
        for rec in getattr(wrapper, "records", []) or []:
            digest, meta = rec["digest"], rec["meta"] or {}
            if digest in seen:
                continue
            if rec.get("compiled") is not None:
                from jax.experimental import serialize_executable as se

                payload = pickle.dumps(se.serialize(rec["compiled"]))
            else:
                # executable was itself deserialized: copy its source entry
                src = None
                for d in _read_dirs():
                    p = _entry_path(d, digest)
                    if os.path.exists(p):
                        src = p
                        break
                if src is None:
                    continue
                _, payload = _read_payload(src)
            _write_entry(path, digest, meta, payload)
            seen.add(digest)
            manifest["entries"].append({
                "digest": digest,
                "kind": meta.get("kind"),
                "mesh_axes": meta.get("mesh_axes"),
                "cost": meta.get("cost"),
            })
    # the tuning DB rides along: a restored replica is tuned-by-
    # construction, with zero re-tuning (best-effort — a bundle without
    # tuning entries is still a valid bundle)
    try:
        from . import autotune as _at

        n = _at.export_to_bundle(path)
        if n:
            manifest["autotune_entries"] = n
    except Exception:
        pass
    atomic_write(os.path.join(path, MANIFEST_NAME),
                 lambda f: f.write(json.dumps(manifest, indent=1,
                                              default=str).encode()),
                 checksum=True, op="compile_cache.store")
    _log_event("compile_cache_bundle_saved", path=path,
               entries=len(manifest["entries"]))
    return path


def read_manifest(path: str) -> dict:
    with open(os.path.join(path, MANIFEST_NAME)) as f:
        return json.load(f)


def attach_bundle(path: str, mesh=None) -> dict:
    """Attach an AOT bundle directory as a read-only cache overlay.

    Refuses LOUDLY (raises :class:`MXNetError`) when the bundle was
    built for a different device topology or — when ``mesh`` is given —
    under different mesh axes: silently serving the wrong executable
    layout is exactly the failure this check exists to stop.  A stale
    jax/jaxlib version is a softer failure: the bundle attaches but
    every entry invalidates at load (plain recompile) with a structured
    event."""
    manifest = read_manifest(path)
    cur = env_fingerprint()
    ent_env = manifest.get("env") or {}
    for k in ("platform", "device_kind", "device_count", "process_count"):
        if ent_env.get(k) != cur.get(k):
            raise MXNetError(
                "AOT bundle %s was built for %s=%r but this process has "
                "%r — refusing the mismatched restore (rebuild the bundle "
                "on this topology or serve without it)"
                % (path, k, ent_env.get(k), cur.get(k)))
    if mesh is not None:
        want = {str(n): int(mesh.shape[n]) for n in mesh.axis_names}
        for e in manifest.get("entries", []):
            axes = e.get("mesh_axes")
            if axes and axes != want:
                raise MXNetError(
                    "AOT bundle entry %s records mesh axes %s but the "
                    "target mesh is %s — refusing the mismatched restore"
                    % (e.get("digest"), axes, want))
    with _lock:
        if path not in _bundles:
            _bundles.append(path)
    try:
        from . import autotune as _at

        _at.attach_bundle_overlay(path)
    except Exception:
        pass
    _log_event("compile_cache_bundle_attached", path=path,
               entries=len(manifest.get("entries", [])))
    return manifest


def detach_bundles() -> None:
    with _lock:
        del _bundles[:]


# ---------------------------------------------------------------------------
# admin: ls / verify / prune  (shared with tools/compile_cache_admin.py)
# ---------------------------------------------------------------------------

def ls_entries(d: str) -> List[dict]:
    """[{digest, path, bytes, mtime, kind, compile_ms, env_ok}] for every
    entry in ``d`` (unreadable headers report kind='corrupt')."""
    return _STORE.ls_entries(
        d, meta_fields=lambda meta: {"kind": meta.get("kind"),
                                     "compile_ms": meta.get("compile_ms"),
                                     "env_ok": _env_compatible(meta)})


def verify_entry(path: str) -> Tuple[bool, str]:
    """(ok, detail): CRC sidecar + header + payload unpickle check —
    everything short of loading onto devices."""
    ok, detail = _STORE.verify_entry(
        path, payload_check=lambda meta, payload: pickle.loads(payload),
        env_ok=_env_compatible)
    if detail == "ok (stale env: invalidates on load)":
        detail = "ok (stale env: recompiles on load)"
    return ok, detail


def prune(d: str, budget_mb: int) -> List[str]:
    """Delete oldest-mtime entries (and their sidecars) until the
    directory is under ``budget_mb``.  Returns the removed paths."""
    removed = _STORE.prune(d, budget_mb)
    if removed:
        _log_event("compile_cache_pruned", dir=d, removed=len(removed))
    return removed

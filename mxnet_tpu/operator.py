"""Custom-operator subsystem — user-defined ops in Python.

TPU-native redesign of the reference's custom-op machinery
(/root/reference/src/operator/custom/custom.cc and
/root/reference/python/mxnet/operator.py:396-576): the reference calls back
from the C++ engine into Python through C function pointers run with
``ExecType::kAsync``; here the callback rides ``jax.pure_callback`` inside
the jitted graph, and the user-supplied backward is wired in with
``jax.custom_vjp`` (replacing the synthesized ``_backward_Custom`` node).

The host round-trip breaks XLA fusion at the custom-op boundary — same
fundamental cost as the reference's engine→Python hop; documented so users
keep custom ops off the hot path or port them to Pallas.

Also provides the legacy ``PythonOp``/``NDArrayOp`` classes
(reference python/mxnet/operator.py:19-226, registered there as the
``_Native``/``_NDArray`` ops): thin adapters over the same Custom path.
"""
from __future__ import annotations

import itertools
import threading
from typing import Any, Dict, List

import numpy as np

from .base import MXNetError

__all__ = ["CustomOp", "CustomOpProp", "register", "get_prop_class",
           "PythonOp", "NDArrayOp", "NumpyOp"]


class CustomOp(object):
    """Base class for user operators. Subclass and implement
    ``forward``/``backward``; use ``assign`` to honour the write request.

    Deviation from the reference: ``backward`` receives ``in_data``/
    ``out_data`` explicitly (saved as vjp residuals), and one operator
    instance may be shared by executors with identical input shapes — do
    NOT stash per-batch state on ``self`` in ``forward`` for use in
    ``backward``; recompute from the arrays that are passed in."""

    def forward(self, is_train, req, in_data, out_data, aux):
        raise NotImplementedError()

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        raise NotImplementedError()

    def assign(self, dst, req, src):
        """Write ``src`` into ``dst`` per request type (reference
        python/mxnet/operator.py:433-440)."""
        if req == "null":
            return
        elif req in ("write", "inplace"):
            dst[:] = src
        elif req == "add":
            dst[:] = dst + src


class CustomOpProp(object):
    """Metadata provider for a custom op (shapes/types/arg lists/state).

    ``need_top_grad``: True when the op needs the gradient from the layer
    above (ordinary op); False for loss layers that are their own gradient
    source (reference python/mxnet/operator.py:442-453)."""

    def __init__(self, need_top_grad=True):
        self.need_top_grad_ = bool(need_top_grad)

    def infer_shape(self, in_shape):
        return in_shape, [in_shape[0]] * len(self.list_outputs()), []

    def infer_type(self, in_type):
        return (in_type, [in_type[0]] * len(self.list_outputs()),
                [in_type[0]] * len(self.list_auxiliary_states()))

    def list_arguments(self):
        return ["data"]

    def list_outputs(self):
        return ["output"]

    def list_auxiliary_states(self):
        return []

    def need_top_grad(self):
        return self.need_top_grad_

    def declare_backward_dependency(self, out_grad, in_data, out_data):
        deps = []
        if self.need_top_grad_:
            deps.extend(out_grad)
        deps.extend(in_data)
        deps.extend(out_data)
        return deps

    def create_operator(self, ctx, in_shapes, in_dtypes):
        raise NotImplementedError()


_prop_registry: Dict[str, type] = {}
_registry_lock = threading.Lock()


def register(reg_name):
    """Decorator: register a ``CustomOpProp`` subclass under ``reg_name`` so
    ``mx.sym.Custom(..., op_type=reg_name)`` / ``mx.nd.Custom`` find it
    (reference python/mxnet/operator.py:576)."""

    def do_register(prop_cls):
        with _registry_lock:
            _prop_registry[reg_name] = prop_cls
            # re-registration under the same name (notebook workflows) must
            # not keep serving cached props of the old class
            for key in [k for k in _prop_cache if k[0] == reg_name]:
                del _prop_cache[key]
        return prop_cls

    return do_register


def get_prop_class(reg_name: str) -> type:
    try:
        return _prop_registry[reg_name]
    except KeyError:
        raise KeyError(
            "Custom op type %r is not registered; call "
            "mx.operator.register(%r) on a CustomOpProp subclass first"
            % (reg_name, reg_name))


# ---------------------------------------------------------------------------
# Bridging into the op registry / jitted graph
# ---------------------------------------------------------------------------

_RESERVED_ATTRS = ("ctx", "name", "op_type")


class _LRU(dict):
    """Tiny bounded cache — bucketing workloads create one entry per shape;
    unbounded growth would pin every CustomOp instance forever."""

    def __init__(self, maxsize=256):
        super(_LRU, self).__init__()
        self._maxsize = maxsize

    def __setitem__(self, key, value):
        if key not in self and len(self) >= self._maxsize:
            del self[next(iter(self))]
        super(_LRU, self).__setitem__(key, value)


_prop_cache: Dict[Any, CustomOpProp] = {}
_op_cache: Dict[Any, CustomOp] = _LRU()
# downstream caches key on this serial, not id(prop): after re-registration
# CPython may recycle a freed prop's address, which would nondeterministically
# serve a stale CustomOp built from the old class
_prop_serial_counter = itertools.count()


def _prop_key(prop) -> int:
    return getattr(prop, "_mx_prop_serial", id(prop))


def _user_kwargs(attrs: Dict[str, Any]) -> Dict[str, str]:
    return {k: v for k, v in attrs.items()
            if k not in _RESERVED_ATTRS and not k.startswith("__")}


def _get_prop(attrs: Dict[str, Any]) -> CustomOpProp:
    op_type = attrs["op_type"]
    kwargs = _user_kwargs(attrs)
    key = (op_type, tuple(sorted(kwargs.items())))
    prop = _prop_cache.get(key)
    if prop is None:
        prop = get_prop_class(op_type)(**kwargs)
        prop._mx_prop_serial = next(_prop_serial_counter)
        _prop_cache[key] = prop
    return prop


def _get_operator(prop: CustomOpProp, in_shapes, in_dtypes) -> CustomOp:
    key = (_prop_key(prop), tuple(map(tuple, in_shapes)),
           tuple(str(d) for d in in_dtypes))
    op = _op_cache.get(key)
    if op is None:
        from .context import cpu

        op = prop.create_operator(cpu(), [list(s) for s in in_shapes],
                                  list(in_dtypes))
        _op_cache[key] = op
    return op


def _to_ndarrays(np_arrays):
    """Wrap host numpy arrays as CPU NDArrays for the user callback (the
    reference hands engine TBlobs to Python as NDArrays)."""
    from .context import cpu
    from .ndarray import array

    return [array(a, ctx=cpu(), dtype=a.dtype) for a in np_arrays]


def _normalize_shapes(prop, in_shapes):
    """Run prop.infer_shape; tolerate the 2-tuple (no-aux) return form."""
    res = prop.infer_shape([list(s) for s in in_shapes])
    if len(res) == 2:
        ishapes, oshapes = res
        ashapes = []
    else:
        ishapes, oshapes, ashapes = res
    return ([tuple(s) for s in ishapes], [tuple(s) for s in oshapes],
            [tuple(s) for s in ashapes])


def _out_struct(prop, main, aux):
    import jax

    in_shapes = [tuple(t.shape) for t in main]
    in_dtypes = [np.dtype(t.dtype) for t in main] or [np.dtype(np.float32)]
    oshapes, odtypes = _out_spec(prop, in_shapes, in_dtypes)
    out_struct = tuple(jax.ShapeDtypeStruct(s, d)
                       for s, d in zip(oshapes, odtypes))
    aux_struct = tuple(jax.ShapeDtypeStruct(tuple(t.shape), np.dtype(t.dtype))
                       for t in aux)
    return out_struct, aux_struct


_out_spec_cache: Dict[Any, Any] = _LRU()


def _out_spec(prop, in_shapes, in_dtypes):
    """(out_shapes, out_dtypes) per (prop, shapes, dtypes) — computed once,
    not per training step."""
    key = (_prop_key(prop), tuple(map(tuple, in_shapes)),
           tuple(str(d) for d in in_dtypes))
    spec = _out_spec_cache.get(key)
    if spec is None:
        _, oshapes, _ = _normalize_shapes(prop, in_shapes)
        try:
            odts = [np.dtype(d) for d in prop.infer_type(list(in_dtypes))[1]]
        except NotImplementedError:
            odts = [np.dtype(in_dtypes[0])] * len(oshapes)
        spec = (oshapes, odts)
        _out_spec_cache[key] = spec
    return spec


def _host_forward(prop, is_train, main_np, aux_np):
    main_np = [np.asarray(a) for a in main_np]
    aux_np = [np.asarray(a) for a in aux_np]
    op = _get_operator(prop, [a.shape for a in main_np],
                       [a.dtype for a in main_np])
    in_nd = _to_ndarrays(main_np)
    aux_nd = _to_ndarrays(aux_np)
    oshapes, odts = _out_spec(prop, [a.shape for a in main_np],
                              [a.dtype for a in main_np])
    out_nd = _to_ndarrays([np.zeros(s, d) for s, d in zip(oshapes, odts)])
    req = ["write"] * len(out_nd)
    op.forward(bool(is_train), req, in_nd, out_nd, aux_nd)
    outs = tuple(o.asnumpy() for o in out_nd)
    auxs = tuple(a.asnumpy() for a in aux_nd)
    return outs + auxs


def _host_backward(prop, out_grad_np, main_np, out_np, aux_np):
    main_np = [np.asarray(a) for a in main_np]
    out_grad_np = [np.asarray(a) for a in out_grad_np]
    out_np = [np.asarray(a) for a in out_np]
    aux_np = [np.asarray(a) for a in aux_np]
    op = _get_operator(prop, [a.shape for a in main_np],
                       [a.dtype for a in main_np])
    in_nd = _to_ndarrays(main_np)
    og_nd = _to_ndarrays(out_grad_np)
    out_nd = _to_ndarrays(out_np)
    aux_nd = _to_ndarrays(aux_np)
    ig_nd = _to_ndarrays([np.zeros(a.shape, a.dtype) for a in main_np])
    req = ["write"] * len(ig_nd)
    op.backward(req, og_nd, in_nd, out_nd, ig_nd, aux_nd)
    return tuple(g.asnumpy() for g in ig_nd)


_host_cb_supported = None


def host_callbacks_supported() -> bool:
    """Whether the active JAX backend can run host callbacks inside jit
    (some tunneled TPU platforms reject host send/recv).  Probed once with a
    trivial pure_callback compile; Executor uses this to fall back to
    unjitted execution for graphs containing Custom/_Native/_NDArray ops."""
    global _host_cb_supported
    if _host_cb_supported is None:
        import jax

        try:
            spec = jax.ShapeDtypeStruct((), np.dtype(np.float32))
            out = jax.jit(lambda: jax.pure_callback(
                lambda: np.float32(1.0), spec))()
            _host_cb_supported = float(out) == 1.0
        except jax.errors.ConcretizationTypeError:
            # probed from inside an active trace — cannot tell; leave the
            # capability unknown and let the caller proceed optimistically
            return True
        except Exception:
            _host_cb_supported = False
    return _host_cb_supported


def _custom_call_eager(prop, is_train, main, aux):
    """Imperative path: direct host execution with no callback machinery —
    works on every platform (the reference's kAsync engine op calling into
    Python, custom-inl.h, without an engine)."""
    import jax.numpy as jnp

    main_np = [np.asarray(t) for t in main]
    aux_np = [np.asarray(t) for t in aux]
    res = _host_forward(prop, is_train, main_np, aux_np)
    return tuple(jnp.asarray(r) for r in res)


def _custom_call(prop, is_train, main, aux):
    """The jit-traceable core: pure_callback forward wrapped in custom_vjp
    whose backward pure_callbacks into the user's ``backward``."""
    import jax

    main = tuple(main)
    aux = tuple(aux)
    out_struct, aux_struct = _out_struct(prop, main, aux)
    n_out = len(out_struct)

    def fwd_cb(*arrs):
        m = arrs[:len(main)]
        a = arrs[len(main):]
        return _host_forward(prop, is_train, m, a)

    @jax.custom_vjp
    def run(main_t, aux_t):
        res = jax.pure_callback(fwd_cb, out_struct + aux_struct,
                                *main_t, *aux_t, vmap_method="sequential")
        return tuple(res[:n_out]), tuple(res[n_out:])

    def run_fwd(main_t, aux_t):
        outs, aux_new = run(main_t, aux_t)
        return (outs, aux_new), (main_t, outs, aux_new)

    def run_bwd(residual, cotangent):
        main_t, outs, aux_new = residual
        out_cot, _aux_cot = cotangent

        def bwd_cb(*arrs):
            og = arrs[:n_out]
            m = arrs[n_out:n_out + len(main_t)]
            o = arrs[n_out + len(main_t):2 * n_out + len(main_t)]
            a = arrs[2 * n_out + len(main_t):]
            return _host_backward(prop, og, m, o, a)

        in_struct = tuple(
            jax.ShapeDtypeStruct(t.shape, t.dtype) for t in main_t)
        grads = jax.pure_callback(bwd_cb, in_struct, *out_cot, *main_t,
                                  *outs, *aux_new, vmap_method="sequential")
        zero_aux = tuple(jax.numpy.zeros(t.shape, t.dtype) for t in aux_new)
        return (tuple(grads), zero_aux)

    run.defvjp(run_fwd, run_bwd)
    outs, aux_new = run(main, aux)
    return outs, aux_new


def _custom_kernel(opctx, attrs, *tensors):
    """Registry kernel for the ``Custom`` op."""
    import jax

    prop = _get_prop(attrs)
    n_args = len(prop.list_arguments())
    main = tensors[:n_args]
    aux = tensors[n_args:]
    if not any(isinstance(t, jax.core.Tracer) for t in tensors):
        # imperative mx.nd.Custom (or NaiveEngine executor): run on host
        # directly — no pure_callback, so platforms without host send/recv
        # support still work
        return _custom_call_eager(prop, opctx.is_train, main, aux)
    if _host_cb_supported is False:  # known-unsupported (probed eagerly)
        raise MXNetError(
            "This JAX backend does not support host callbacks inside jit, "
            "so Custom ops cannot run in a compiled graph here. Run the "
            "executor in NaiveEngine mode (MXNET_ENGINE_TYPE=NaiveEngine) "
            "or on a backend with host-callback support; Executors detect "
            "this automatically for graphs containing Custom ops.")
    outs, aux_new = _custom_call(prop, opctx.is_train, main, aux)
    return tuple(outs) + tuple(aux_new)


def _custom_infer_shape(attrs, in_shapes):
    if any(s is None for s in in_shapes):
        raise ValueError("Custom op needs all input shapes")
    prop = _get_prop(attrs)
    return _normalize_shapes(prop, in_shapes)


def _register_legacy_callback_stubs():
    """``_Native``/``_NDArray`` nodes carry serialized C function POINTERS
    in the reference's JSON (python/mxnet/operator.py:19-226 pack ctypes
    addresses into the ``info`` attr) — not portable to any other process,
    in the reference either.  Register the names so such graphs LOAD and
    introspect; executing one raises with the porting path."""
    from .base import MXNetError
    from .ops.registry import register as reg_op

    def _make(name):
        @reg_op(name, inputs=("data",), allow_extra_attrs=True,
                hint=name.strip("_").lower())
        def _stub(opctx, attrs, *arrays):
            raise MXNetError(
                "%s carries process-local callback pointers and cannot "
                "execute from a serialized graph; re-create the op with "
                "PythonOp/NDArrayOp.get_symbol or mx.operator.register "
                "(Custom)" % name)

    _make("_Native")
    _make("_NDArray")


_register_legacy_callback_stubs()


def _register_custom_op():
    from .ops.param import Param
    from .ops.registry import register as reg_op

    reg_op(
        "Custom",
        inputs=lambda attrs: list(_get_prop(attrs).list_arguments()),
        num_outputs=lambda attrs: len(_get_prop(attrs).list_outputs()),
        aux=lambda attrs: list(_get_prop(attrs).list_auxiliary_states()),
        params={"op_type": Param(str, required=True,
                                 doc="registered CustomOpProp name")},
        allow_extra_attrs=True,
        infer_shape=_custom_infer_shape,
        output_names=lambda attrs: list(_get_prop(attrs).list_outputs()),
        hint="custom",
    )(_custom_kernel)


# ---------------------------------------------------------------------------
# Legacy PythonOp / NDArrayOp (reference ``_Native`` / ``_NDArray`` ops)
# ---------------------------------------------------------------------------

class PythonOp(object):
    """Base for the legacy numpy-callback op (reference
    python/mxnet/operator.py:19-120, op name ``_Native``). ``get_symbol``
    registers an adapter prop and returns a Custom symbol."""

    _legacy_counter = [0]

    def __init__(self, need_top_grad=True):
        self.info_ = None
        self.need_top_grad_ = bool(need_top_grad)

    # user API (numpy in/out, in-place writes into out arrays)
    def forward(self, in_data, out_data):
        out_data[0][:] = in_data[0]

    def backward(self, out_grad, in_data, out_data, in_grad):
        raise NotImplementedError()

    def infer_shape(self, in_shape):
        return in_shape, [in_shape[0]]

    def list_arguments(self):
        return ["data"]

    def list_outputs(self):
        return ["output"]

    def need_top_grad(self):
        return self.need_top_grad_

    def _adapter_prop(self):
        legacy = self

        class _LegacyOp(CustomOp):
            def forward(self, is_train, req, in_data, out_data, aux):
                ins = [np.array(a.asnumpy()) for a in in_data]
                outs = [np.array(a.asnumpy()) for a in out_data]
                legacy.forward(in_data=ins, out_data=outs)
                for dst, src in zip(out_data, outs):
                    self.assign(dst, "write", src)

            def backward(self, req, out_grad, in_data, out_data, in_grad,
                         aux):
                ogs = [np.array(a.asnumpy()) for a in out_grad]
                ins = [np.array(a.asnumpy()) for a in in_data]
                outs = [np.array(a.asnumpy()) for a in out_data]
                igs = [np.array(a.asnumpy()) for a in in_grad]
                legacy.backward(out_grad=ogs, in_data=ins, out_data=outs,
                                in_grad=igs)
                for dst, src in zip(in_grad, igs):
                    self.assign(dst, "write", src)

        class _LegacyProp(CustomOpProp):
            def __init__(self):
                super(_LegacyProp, self).__init__(
                    need_top_grad=legacy.need_top_grad())

            def list_arguments(self):
                return legacy.list_arguments()

            def list_outputs(self):
                return legacy.list_outputs()

            def infer_shape(self, in_shape):
                res = legacy.infer_shape(in_shape)
                return res if len(res) == 3 else (res[0], res[1], [])

            def create_operator(self, ctx, in_shapes, in_dtypes):
                return _LegacyOp()

        return _LegacyProp

    def get_symbol(self, *args, **kwargs):
        from . import symbol

        PythonOp._legacy_counter[0] += 1
        reg_name = "_legacy_python_op_%d" % PythonOp._legacy_counter[0]
        register(reg_name)(self._adapter_prop())
        kwargs["op_type"] = reg_name
        return symbol.Custom(*args, **kwargs)


class NDArrayOp(PythonOp):
    """Legacy NDArray-callback op (reference python/mxnet/operator.py:122-226,
    op name ``_NDArray``): forward/backward receive NDArrays."""

    def forward(self, in_data, out_data):
        out_data[0][:] = in_data[0]

    def declare_backward_dependency(self, out_grad, in_data, out_data):
        deps = []
        if self.need_top_grad_:
            deps.extend(out_grad)
        deps.extend(in_data)
        deps.extend(out_data)
        return deps

    def _adapter_prop(self):
        legacy = self

        class _LegacyOp(CustomOp):
            def forward(self, is_train, req, in_data, out_data, aux):
                legacy.forward(in_data=in_data, out_data=out_data)

            def backward(self, req, out_grad, in_data, out_data, in_grad,
                         aux):
                legacy.backward(out_grad=out_grad, in_data=in_data,
                                out_data=out_data, in_grad=in_grad)

        class _LegacyProp(CustomOpProp):
            def __init__(self):
                super(_LegacyProp, self).__init__(
                    need_top_grad=legacy.need_top_grad())

            def list_arguments(self):
                return legacy.list_arguments()

            def list_outputs(self):
                return legacy.list_outputs()

            def infer_shape(self, in_shape):
                res = legacy.infer_shape(in_shape)
                return res if len(res) == 3 else (res[0], res[1], [])

            def declare_backward_dependency(self, out_grad, in_data,
                                            out_data):
                return legacy.declare_backward_dependency(
                    out_grad, in_data, out_data)

            def create_operator(self, ctx, in_shapes, in_dtypes):
                return _LegacyOp()

        return _LegacyProp


#: reference alias — numpy-based op
NumpyOp = PythonOp

_register_custom_op()

# refresh the generated op surfaces (symbol/ndarray codegen ran at their
# import time, before Custom existed in the registry)
from . import ndarray as _nd_mod  # noqa: E402
from . import symbol as _sym_mod  # noqa: E402

_nd_mod._init_ops()
_sym_mod._init_symbol_module()

"""Model-level helpers: checkpointing and the kvstore update paths.

Parity: /root/reference/python/mxnet/model.py (BatchEndParam :25,
_create_kvstore :40-77, _update_params[_on_kvstore] :88-116,
save_checkpoint :319, load_checkpoint :349).  The legacy FeedForward API is
provided for porting convenience and delegates to Module.
"""
from __future__ import annotations

import collections
import logging
import os
from typing import Dict, Optional, Tuple

from . import ndarray as nd
from . import symbol as sym
from .base import MXNetError
from . import kvstore as kvs

__all__ = ["BatchEndParam", "save_checkpoint", "load_checkpoint",
           "load_checkpoint_state", "FeedForward"]

BatchEndParam = collections.namedtuple(
    "BatchEndParams", ["epoch", "nbatch", "eval_metric", "locals"])


def _create_kvstore(kvstore, num_device, arg_params):
    """Resolve a kvstore spec to (kv, update_on_kvstore) (reference
    model.py:40-77)."""
    update_on_kvstore = True
    if kvstore is None:
        kv = None
    elif isinstance(kvstore, kvs.KVStore):
        kv = kvstore
    elif isinstance(kvstore, str):
        if num_device == 1 and "dist" not in kvstore:
            # a single in-step device group needs no kvstore round-trip
            kv = None
        else:
            kv = kvs.create(kvstore)
            if kvstore == "local":
                max_size = max(int(__import__("numpy").prod(p.shape))
                               for p in arg_params.values()) if arg_params else 0
                if max_size < 1024 * 1024 * 16:
                    update_on_kvstore = False
    else:
        raise TypeError("kvstore must be KVStore, str or None")
    if kv is None:
        update_on_kvstore = False
    return kv, update_on_kvstore


def _initialize_kvstore(kvstore, param_arrays, arg_params, param_names,
                        update_on_kvstore, skip_indices=()):
    """``skip_indices``: params routed elsewhere (row_sparse slots ride
    the sparse plane's sharded tables — initializing them here would ship
    a dense copy of a table that must never leave the servers)."""
    skip = frozenset(skip_indices)
    for idx, param_on_devs in enumerate(param_arrays):
        if idx in skip:
            continue
        kvstore.init(idx, arg_params[param_names[idx]])
        if update_on_kvstore:
            kvstore.pull(idx, param_on_devs, priority=-idx)


def _update_params_on_kvstore(param_arrays, grad_arrays, kvstore,
                              param_order=None, defer_wait=False):
    """Centralized update: push grads, pull weights (reference model.py:88).

    All pushes are issued FIRST, in ``param_order`` (backward order — the
    order gradients become available), each with ``priority=-index`` so an
    async kvstore services front-layer keys first; pulls follow in forward
    order.  On an async store nothing here blocks — with ``defer_wait``
    the caller overlaps communication with the next batch's host-side
    prep and waits later (Module._wait_async_comm); otherwise a final
    ``wait_all`` restores the synchronous contract.  On a plain kvstore
    push/pull complete inline and ``wait`` is the no-op base method, so
    behavior is unchanged."""
    n = len(param_arrays)
    if param_order is None:
        param_order = range(n - 1, -1, -1)

    def has_grad(index):
        g = grad_arrays[index]
        return not (g is None or (isinstance(g, list) and g[0] is None))

    for index in param_order:
        if has_grad(index):
            kvstore.push(index, grad_arrays[index], priority=-index)
    for index in range(n):
        if has_grad(index):
            kvstore.pull(index, param_arrays[index], priority=-index)
    if not defer_wait:
        kvstore.wait_all()


def _update_params(param_arrays, grad_arrays, updater, num_device,
                   kvstore=None):
    """Replicated-updater path (reference model.py:99-116)."""
    for index, pair in enumerate(zip(param_arrays, grad_arrays)):
        arg_list, grad_list = pair
        if grad_list is None or (isinstance(grad_list, list) and
                                 grad_list[0] is None):
            continue
        if not isinstance(arg_list, list):
            arg_list, grad_list = [arg_list], [grad_list]
        if kvstore:
            kvstore.push(index, grad_list, priority=-index)
            kvstore.pull(index, grad_list, priority=-index)
            # async store: the pulled-back grads feed the local updater
            # next — wait this key out before reading
            kvstore.wait(index)
        for k, p in enumerate(zip(arg_list, grad_list)):
            w, g = p
            updater(index * num_device + k, g, w)


def save_checkpoint(prefix, epoch, symbol, arg_params, aux_params,
                    max_to_keep=None, extra_state=None,
                    mark_last_good=False):
    """Write prefix-symbol.json + prefix-%04d.params (reference
    model.py:319-349; format per ndarray.cc:633-714).

    Both files land atomically (tmp + fsync + ``os.replace``) and the
    params file carries a CRC32 sidecar, so a crash mid-save can neither
    tear the newest checkpoint nor shadow the previous good one, and
    :func:`find_latest_checkpoint` can reject corrupted survivors.

    Alongside the params a ``prefix-%04d.state`` sidecar captures the
    framework PRNG stream (``mx.random.get_state()``) merged with any
    caller ``extra_state`` (e.g. data-iterator position from
    ``DataIter.state_dict()``), closing the deterministic-replay gap: a
    resume that restores the sidecar replays the exact stochastic
    schedule and batch sequence the original run would have seen.

    ``max_to_keep`` prunes the retention ring down to the newest N
    epochs after the new one lands (the ``last_good``-marked epoch is
    never pruned); ``mark_last_good`` stamps this epoch as the rollback
    target :func:`find_latest_checkpoint` prefers."""
    import pickle

    from . import random as _random
    from .filesystem import atomic_write

    if symbol is not None:
        symbol.save("%s-symbol.json" % prefix)
    save_dict = {("arg:%s" % k): v for k, v in arg_params.items()}
    save_dict.update({("aux:%s" % k): v for k, v in aux_params.items()})
    param_name = "%s-%04d.params" % (prefix, epoch)
    nd.save(param_name, save_dict, checksum=True, op="ckpt.write")
    state = {"rng": _random.get_state()}
    if extra_state:
        state.update(extra_state)
    blob = pickle.dumps(state, protocol=pickle.HIGHEST_PROTOCOL)
    atomic_write("%s-%04d.state" % (prefix, epoch),
                 lambda f: f.write(blob), checksum=False, op="ckpt.state")
    if mark_last_good:
        _mark_last_good(prefix, epoch)
    if max_to_keep is not None:
        _prune_checkpoints(prefix, int(max_to_keep))
    logging.info("Saved checkpoint to \"%s\"", param_name)


def _last_good_path(prefix):
    return "%s-last-good" % prefix


def _mark_last_good(prefix, epoch):
    """Atomically stamp ``epoch`` as the rollback target for ``prefix``."""
    from .filesystem import atomic_write

    atomic_write(_last_good_path(prefix),
                 lambda f: f.write(("%04d\n" % epoch).encode("ascii")),
                 checksum=False, op="ckpt.state")


def _read_last_good(prefix):
    """Epoch stamped by :func:`_mark_last_good`, or None."""
    try:
        with open(_last_good_path(prefix), "r") as f:
            return int(f.read().strip())
    except (OSError, ValueError):
        return None


def _prune_checkpoints(prefix, max_to_keep):
    """Delete all but the newest ``max_to_keep`` epochs of ``prefix``
    (params + CRC + state sidecars).  The ``last_good``-marked epoch is
    exempt — pruning must never delete the rollback target."""
    import glob
    import re

    if max_to_keep < 1:
        return
    keep_always = _read_last_good(prefix)
    epochs = []
    for path in glob.glob("%s-[0-9][0-9][0-9][0-9].params" % prefix):
        m = re.search(r"-(\d{4})\.params$", path)
        if m:
            epochs.append(int(m.group(1)))
    for ep in sorted(epochs, reverse=True)[max_to_keep:]:
        if ep == keep_always:
            continue
        for suffix in (".params", ".params.crc32", ".state"):
            try:
                os.remove("%s-%04d%s" % (prefix, ep, suffix))
            except OSError:
                pass


def load_checkpoint_state(prefix, epoch, restore_rng=False):
    """Read the ``.state`` sidecar written by :func:`save_checkpoint`
    (None for a pre-sidecar checkpoint).  ``restore_rng`` feeds the
    captured PRNG stream straight back into ``mx.random`` so the resumed
    run continues the original stochastic schedule bit-exactly."""
    import pickle

    from . import random as _random

    try:
        with open("%s-%04d.state" % (prefix, epoch), "rb") as f:
            state = pickle.load(f)
    except OSError:
        return None
    if restore_rng and "rng" in state:
        _random.set_state(state["rng"])
    return state


def _checkpoint_ok(path):
    """Is ``path`` a loadable .params file?  CRC sidecar verdict when one
    exists; otherwise (pre-sidecar artifact, or a torn temp another writer
    left behind) a cheap container-magic sniff."""
    import struct

    from .filesystem import verify_crc_sidecar

    verdict = verify_crc_sidecar(path)
    if verdict is not None:
        return verdict
    try:
        with open(path, "rb") as f:
            head = f.read(8)
        return (len(head) == 8 and
                struct.unpack("<Q", head)[0] == nd._MAGIC)
    except OSError:
        return False


def find_latest_checkpoint(prefix, prefer_last_good=True):
    """Newest saved epoch for ``prefix`` (prefix-%04d.params), or None.

    The discovery half of checkpoint-based fault tolerance: a relaunched
    worker resumes from here instead of a hand-passed --load-epoch
    (reference mechanism: example/image-classification/common/fit.py
    --load-epoch; the launcher's --auto-resume mode relies on this).
    Partial or corrupt files (CRC sidecar mismatch, bad container magic)
    are skipped, so a crash during save rolls resume back to the newest
    INTACT epoch instead of wedging every relaunch on a torn file.

    When the training guardian has stamped a ``last_good`` marker
    (``prefix-last-good``), that epoch wins over anything newer: epochs
    past the marker may carry numerically-poisoned parameters the
    guardian was rolling away from when the process died.  Pass
    ``prefer_last_good=False`` for the raw newest-intact scan."""
    import glob
    import re

    if prefer_last_good:
        marked = _read_last_good(prefix)
        if marked is not None and \
                _checkpoint_ok("%s-%04d.params" % (prefix, marked)):
            return marked
    best = None
    for path in glob.glob("%s-[0-9][0-9][0-9][0-9].params" % prefix):
        m = re.search(r"-(\d{4})\.params$", path)
        if not m:
            continue
        if not _checkpoint_ok(path):
            logging.warning("skipping corrupt checkpoint %s", path)
            continue
        ep = int(m.group(1))
        best = ep if best is None else max(best, ep)
    return best


def load_checkpoint(prefix, epoch):
    """Load (symbol, arg_params, aux_params) from a checkpoint (reference
    model.py:349)."""
    symbol = sym.load("%s-symbol.json" % prefix)
    save_dict = nd.load("%s-%04d.params" % (prefix, epoch))
    arg_params = {}
    aux_params = {}
    for k, v in save_dict.items():
        tp, _, name = k.partition(":")
        if tp == "arg":
            arg_params[name] = v
        if tp == "aux":
            aux_params[name] = v
    return (symbol, arg_params, aux_params)


class FeedForward:
    """Legacy training API (reference model.py FeedForward) — a thin adapter
    over mx.mod.Module kept so reference scripts port unchanged."""

    def __init__(self, symbol, ctx=None, num_epoch=None, epoch_size=None,
                 optimizer="sgd", initializer=None, numpy_batch_size=128,
                 arg_params=None, aux_params=None, allow_extra_params=False,
                 begin_epoch=0, **kwargs):
        from .initializer import Uniform

        self.symbol = symbol
        self.ctx = ctx
        self.num_epoch = num_epoch
        self.epoch_size = epoch_size
        self.optimizer = optimizer
        self.initializer = initializer if initializer is not None else Uniform(0.01)
        self.numpy_batch_size = numpy_batch_size
        self.arg_params = arg_params
        self.aux_params = aux_params
        self.allow_extra_params = allow_extra_params
        self.begin_epoch = begin_epoch
        self.kwargs = kwargs.copy()
        self._module = None

    def _get_module(self, data, label_name="softmax_label"):
        from .module import Module
        from .io import DataDesc

        data_names = [d[0] for d in data.provide_data]
        label_names = [l[0] for l in data.provide_label] or [label_name]
        mod = Module(self.symbol, data_names=data_names,
                     label_names=label_names, context=self.ctx)
        return mod

    def fit(self, X, y=None, eval_data=None, eval_metric="acc",
            epoch_end_callback=None, batch_end_callback=None, kvstore="local",
            logger=None, work_load_list=None, monitor=None,
            eval_end_callback=None, eval_batch_end_callback=None):
        train_data = self._as_iter(X, y)
        mod = self._get_module(train_data)
        optimizer_params = dict(self.kwargs)
        optimizer_params.setdefault("learning_rate", 0.01)
        mod.fit(train_data, eval_data=eval_data, eval_metric=eval_metric,
                epoch_end_callback=epoch_end_callback,
                batch_end_callback=batch_end_callback, kvstore=kvstore,
                optimizer=self.optimizer, optimizer_params=optimizer_params,
                initializer=self.initializer, arg_params=self.arg_params,
                aux_params=self.aux_params, begin_epoch=self.begin_epoch,
                num_epoch=self.num_epoch, monitor=monitor)
        self._module = mod
        self.arg_params, self.aux_params = mod.get_params()
        return self

    def predict(self, X, num_batch=None):
        data = self._as_iter(X, None)
        if self._module is None:
            mod = self._get_module(data)
            mod.bind(data_shapes=data.provide_data, for_training=False)
            mod.set_params(self.arg_params or {}, self.aux_params or {},
                           allow_missing=False)
            self._module = mod
        outs = self._module.predict(data, num_batch=num_batch)
        return outs.asnumpy() if hasattr(outs, "asnumpy") else outs

    def score(self, X, y=None, eval_metric="acc"):
        data = self._as_iter(X, y)
        if self._module is None:  # e.g. right after FeedForward.load
            mod = self._get_module(data)
            mod.bind(data_shapes=data.provide_data,
                     label_shapes=data.provide_label, for_training=False)
            mod.set_params(self.arg_params or {}, self.aux_params or {},
                           allow_missing=False)
            self._module = mod
        res = self._module.score(data, eval_metric)
        return res[0][1]

    def _as_iter(self, X, y):
        from .io import DataIter, NDArrayIter

        if isinstance(X, DataIter):
            return X
        return NDArrayIter(X, y, self.numpy_batch_size, shuffle=False)

    def save(self, prefix, epoch=None):
        if epoch is None:
            epoch = self.num_epoch
        save_checkpoint(prefix, epoch, self.symbol, self.arg_params or {},
                        self.aux_params or {})

    @staticmethod
    def load(prefix, epoch, ctx=None, **kwargs):
        symbol, arg_params, aux_params = load_checkpoint(prefix, epoch)
        return FeedForward(symbol, ctx=ctx, arg_params=arg_params,
                           aux_params=aux_params, begin_epoch=epoch, **kwargs)

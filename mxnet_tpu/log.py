"""Logging utilities (reference python/mxnet/log.py: colored, leveled
logger factory)."""
from __future__ import annotations

import logging
import sys

CRITICAL = logging.CRITICAL
ERROR = logging.ERROR
WARNING = logging.WARNING
INFO = logging.INFO
DEBUG = logging.DEBUG
NOTSET = logging.NOTSET


class _Formatter(logging.Formatter):
    """Level-colored single-line format (TTY only)."""

    def __init__(self, color=None):
        super().__init__(datefmt="%m%d %H:%M:%S")
        self._color = sys.stderr.isatty() if color is None else color

    def format(self, record):
        base = "%(asctime)s %(levelname).1s %(name)s %(message)s"
        if self._color:
            if record.levelno >= logging.WARNING:
                base = "\x1b[31m" + base + "\x1b[0m"
            elif record.levelno >= logging.INFO:
                base = "\x1b[32m" + base + "\x1b[0m"
        self._style._fmt = base
        return super().format(record)


def get_logger(name=None, filename=None, filemode=None, level=WARNING):
    """Configured logger (reference log.py getLogger): colored stream
    handler, or a plain file handler when ``filename`` is given."""
    logger = logging.getLogger(name)
    if getattr(logger, "_init_done", False):
        logger.setLevel(level)
        return logger
    logger._init_done = True
    if filename:
        hdlr = logging.FileHandler(filename, filemode or "a")
        hdlr.setFormatter(logging.Formatter(
            "%(asctime)s %(levelname).1s %(name)s %(message)s",
            datefmt="%m%d %H:%M:%S"))
    else:
        hdlr = logging.StreamHandler()
        hdlr.setFormatter(_Formatter())
    logger.addHandler(hdlr)
    logger.setLevel(level)
    return logger


getLogger = get_logger  # reference spelling

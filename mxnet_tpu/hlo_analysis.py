"""Shared XLA cost-analysis and HLO-audit helpers.

One home for the flops/bytes-accessed introspection that used to be
copy-pasted across ``telemetry.step_monitor``, ``compile_cache``,
``tools/perf_probe.py`` and ``tools/layout_probe.py`` — and that the
autotuner now uses as its cheap objective: lower a candidate program,
read XLA's own cost analysis, and score it with a roofline model
("A Learned Performance Model for TPUs", arxiv 2008.01040, argues the
compiled program's numbers are the ones that matter).  Everything here
runs on CPU with no chip — lowering is shape-only.
"""
from __future__ import annotations

import collections
import re
from typing import Optional

from .base import env, register_env

__all__ = ["peak_flops", "hbm_bytes_per_s", "cost_analysis",
           "lower_and_analyze", "roofline_ms", "hlo_op_counts",
           "bn_fusion_analysis"]

register_env("MXNET_TELEMETRY_HBM_GBS", 0.0, float,
             "HBM bandwidth (GB/s) for the roofline bytes term; "
             "0 uses the TPU v5e figure (819 GB/s).")

# TPU v5e: 197 bf16 TFLOP/s, 819 GB/s HBM — the chip every PERF.md
# number was measured on; both overridable for other parts
_V5E_PEAK_FLOPS = 197e12
_V5E_HBM_BYTES_S = 819e9


def peak_flops() -> float:
    """MFU denominator: MXNET_TELEMETRY_PEAK_FLOPS override, else the
    TPU v5e bf16 peak used by bench.py/perf_probe (197 TFLOP/s)."""
    v = env("MXNET_TELEMETRY_PEAK_FLOPS", 0.0, float)
    return float(v) if v else _V5E_PEAK_FLOPS


def hbm_bytes_per_s() -> float:
    """Roofline bytes denominator: MXNET_TELEMETRY_HBM_GBS override,
    else TPU v5e HBM bandwidth (819 GB/s)."""
    v = env("MXNET_TELEMETRY_HBM_GBS", 0.0, float)
    return float(v) * 1e9 if v else _V5E_HBM_BYTES_S


def cost_analysis(compiled) -> Optional[dict]:
    """XLA's cost analysis of a compiled executable as
    ``{"flops", "bytes_accessed"}``, or None when the backend doesn't
    report one."""
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, list):
            ca = ca[0]
        return {"flops": ca.get("flops"),
                "bytes_accessed": ca.get("bytes accessed")}
    except Exception:
        return None


def lower_and_analyze(fn, abstract):
    """Lower+compile a jitted program at abstract args and read XLA cost
    analysis.  Returns (compiled, {"flops", "bytes_accessed"}); compiled
    is None when the program can't be lowered (naive engine)."""
    if fn is None or not hasattr(fn, "lower"):
        return None, None
    lowered = fn.lower(*abstract)
    compiled = lowered.compile()
    return compiled, cost_analysis(compiled)


def roofline_ms(info) -> Optional[float]:
    """Roofline lower-bound runtime (ms) of a cost-analysis dict: the
    slower of the compute term (flops/peak) and the memory term
    (bytes/HBM-bandwidth).  The autotuner's CPU-side objective — exact
    runtimes are wrong off-chip, but the RANKING across candidates of
    the same program tracks the roofline."""
    if not info:
        return None
    flops = float(info.get("flops") or 0.0)
    nbytes = float(info.get("bytes_accessed") or 0.0)
    if flops <= 0 and nbytes <= 0:
        return None
    return max(flops / peak_flops(), nbytes / hbm_bytes_per_s()) * 1e3


def hlo_op_counts(hlo_text, interesting=None) -> dict:
    """Histogram of HLO opcodes in a compiled ``as_text()`` dump,
    optionally filtered to an opcode whitelist."""
    ops = collections.Counter(
        re.findall(r"^\s*[%\w.-]+ = [\w\[\]<>{}, ]*?(\w+)\(", hlo_text,
                   re.M))
    if interesting is None:
        return dict(ops)
    return {k: v for k, v in ops.most_common() if k in interesting}


def bn_fusion_analysis(hlo_text) -> dict:
    """Does BN's scale/shift ride the conv epilogue? (VERDICT r4 ask.)

    Classifies every convolution by actual dataflow, not substring
    presence: a conv counts as epilogue-fused only when its RESULT name
    is an operand of a multiply/add/subtract inside the same non-entry
    fusion computation (the BN affine transform then costs no extra HBM
    round trip). Convs in the ENTRY computation are bare by definition —
    entry-level instructions are separate kernels even when an
    elementwise op consumes them there (worth ~2 MFU points per PERF.md's
    control-minus-BN-stats data if that is where BN's scale/shift run)."""
    # computations: optional ENTRY prefix, then 'name (...) -> ... {'.
    # The '%' name sigil is optional THROUGHOUT: modern compiled.as_text()
    # dumps omit it ('convolution.3 = f32[...] convolution(arg.1, ...)'),
    # classic dumps keep it — names are normalized sigil-less.
    blocks = re.findall(r"^(ENTRY\s+)?%?[\w.-]+ [^\n]*\{\n(.*?)^\s*\}",
                        hlo_text, re.M | re.S)
    fused = fused_plain = bare = 0
    for entry_prefix, body in blocks:
        conv_names = [m.group(1).lstrip("%") for m in re.finditer(
            r"(%?[\w.-]+)\s*=\s*\S+\s+convolution\(", body)]
        if not conv_names:
            continue
        if entry_prefix:
            bare += len(conv_names)
            continue
        ew_operands = set()
        for m in re.finditer(
                r"=\s*\S+\s+(?:multiply|add|subtract)\(([^)]*)\)", body):
            ew_operands.update(
                t.lstrip("%")
                for t in re.findall(r"%?[\w][\w.-]*", m.group(1)))
        for c in conv_names:
            if c in ew_operands:
                fused += 1
            else:
                fused_plain += 1
    return {"convs_total": fused + fused_plain + bare,
            "convs_fused_with_elementwise_epilogue": fused,
            "convs_fused_plain": fused_plain,
            "convs_bare_in_entry": bare}

"""Sharded / async checkpointing — the TPU-native half of the checkpoint
story (SURVEY §5.4).

The reference persists ``prefix-symbol.json`` + a dmlc stream of named
arrays (``.params``, /root/reference/src/ndarray/ndarray.cc:633-714); this
framework keeps that format bit-compatible (``mx.nd.save/load``) for
interchange.  This module adds the TPU-era equivalent on top: an
orbax-backed checkpoint keyed by the SAME name->array dicts, which

  * writes each device shard from the process that owns it (multi-host
    global-mesh training checkpoints without gathering to one host),
  * restores with the arrays' shardings preserved,
  * round-trips the symbol JSON next to the weights.

API mirrors ``mx.model.save_checkpoint``/``load_checkpoint``.
"""
from __future__ import annotations

import json
import os
from typing import Dict, Optional, Tuple

from .base import MXNetError

__all__ = ["save_sharded_checkpoint", "load_sharded_checkpoint",
           "load_sharded_checkpoint_state", "load_partition_specs",
           "aot_bundle_path", "save_aot_bundle", "attach_aot_bundle"]

# written (by process 0) only after every process's shards have landed; a
# directory without it is a crash-torn save.  Orbax's own commit marker
# (commit_success.txt) is honored too, for checkpoints written before this
# guard existed.
_COMPLETE_MARKER = "mxnet_complete"

# per-parameter PartitionSpec metadata saved next to the weights, so a
# tensor-parallel layout restores onto a fresh mesh (same axis names)
# without gathering anything to one host first
_SPEC_FILE = "partition_specs.json"

# framework PRNG stream (mx.random.get_state()) pickled next to the
# weights — restoring it is half of bit-deterministic resume
_STATE_FILE = "extra_state.pkl"


def _is_complete(path):
    return (os.path.exists(os.path.join(path, _COMPLETE_MARKER))
            or os.path.exists(os.path.join(path, "commit_success.txt")))


def _to_tree(arg_params, aux_params):
    from . import ndarray as nd

    def unwrap(d):
        return {k: (v._data if isinstance(v, nd.NDArray) else v)
                for k, v in (d or {}).items()}

    return {"arg": unwrap(arg_params), "aux": unwrap(aux_params)}


def _spec_to_json(spec):
    return [list(e) if isinstance(e, (tuple, list)) else
            (None if e is None else str(e)) for e in tuple(spec)]


def _spec_from_json(entries):
    from jax.sharding import PartitionSpec

    return PartitionSpec(*[tuple(e) if isinstance(e, list) else e
                           for e in entries])


def _derive_specs(tree, overrides=None):
    """{"arg"/"aux": {name: json-spec}} from the arrays' NamedShardings
    (non-named / single-device shardings record as replicated)."""
    from jax.sharding import NamedSharding

    overrides = overrides or {}
    out = {}
    for grp, sub in tree.items():
        g = {}
        for name, x in sub.items():
            if name in overrides:
                g[name] = _spec_to_json(overrides[name])
                continue
            sharding = getattr(x, "sharding", None)
            g[name] = _spec_to_json(sharding.spec) \
                if isinstance(sharding, NamedSharding) else []
        out[grp] = g
    return out


def save_sharded_checkpoint(prefix, epoch, symbol, arg_params, aux_params,
                            partition_specs=None):
    """Write ``prefix-symbol.json`` + ``prefix-<epoch>.orbax/`` (a sharded
    orbax tree).  In multi-process jobs every process must call this
    collectively; each writes only its addressable shards.

    Each parameter's PartitionSpec (read off its NamedSharding, or from
    ``partition_specs`` = {name: PartitionSpec} overrides) is saved as
    ``partition_specs.json`` inside the directory, so the layout restores
    onto a fresh mesh via ``load_sharded_checkpoint(..., mesh=...)``."""
    import jax
    import orbax.checkpoint as ocp

    if symbol is not None and jax.process_index() == 0:
        # one writer: N processes saving collectively must not race on the
        # shared symbol file
        symbol.save("%s-symbol.json" % prefix)
    path = os.path.abspath("%s-%04d.orbax" % (prefix, epoch))
    tree = _to_tree(arg_params, aux_params)
    ckpt = ocp.PyTreeCheckpointer()
    ckpt.save(path, tree, force=True)
    if jax.process_index() == 0:
        from .filesystem import atomic_write

        specs = _derive_specs(tree, partition_specs)
        atomic_write(os.path.join(path, _SPEC_FILE),
                     lambda f: f.write(
                         json.dumps(specs, indent=1).encode()),
                     op="ckpt.write")
        # PRNG stream state rides inside the directory (same
        # deterministic-replay contract as model.save_checkpoint's
        # .state sidecar), landing before the marker like the specs
        import pickle

        from . import random as _random

        blob = pickle.dumps({"rng": _random.get_state()},
                            protocol=pickle.HIGHEST_PROTOCOL)
        atomic_write(os.path.join(path, _STATE_FILE),
                     lambda f: f.write(blob), op="ckpt.state")
        # the spec file lands BEFORE the marker: a complete checkpoint
        # always has its layout metadata
        atomic_write(os.path.join(path, _COMPLETE_MARKER),
                     lambda f: f.write(b"ok\n"), op="ckpt.write")
    return path


def load_sharded_checkpoint_state(prefix, epoch, restore_rng=False):
    """The extra-state dict saved inside a sharded checkpoint (PRNG
    stream), or None for pre-state checkpoints.  ``restore_rng`` feeds
    the stream back into ``mx.random``."""
    import pickle

    from . import random as _random

    path = os.path.abspath("%s-%04d.orbax" % (prefix, epoch))
    try:
        with open(os.path.join(path, _STATE_FILE), "rb") as f:
            state = pickle.load(f)
    except OSError:
        return None
    if restore_rng and "rng" in state:
        _random.set_state(state["rng"])
    return state


def load_partition_specs(prefix, epoch):
    """{"arg"/"aux": {name: PartitionSpec}} saved with the checkpoint, or
    None for checkpoints written before spec metadata existed."""
    path = os.path.abspath("%s-%04d.orbax" % (prefix, epoch))
    spec_path = os.path.join(path, _SPEC_FILE)
    if not os.path.exists(spec_path):
        return None
    with open(spec_path) as f:
        raw = json.load(f)
    return {grp: {k: _spec_from_json(v) for k, v in sub.items()}
            for grp, sub in raw.items()}


def load_sharded_checkpoint(prefix, epoch, shardings=None, mesh=None):
    """-> (symbol_or_None, arg_params, aux_params) as NDArray dicts.

    ``shardings``: optional ``{"arg"/"aux": {name: jax.sharding}}`` tree to
    restore arrays directly onto a mesh (multi-host restore).

    ``mesh``: rebuild the shardings from the checkpoint's own
    ``partition_specs.json`` against this mesh — a tensor-parallel layout
    restores shard-for-shard onto a fresh job (same axis names, possibly
    different process topology) with no full-tensor gathers anywhere.
    """
    from . import ndarray as nd
    from . import symbol as sym

    import orbax.checkpoint as ocp

    path = os.path.abspath("%s-%04d.orbax" % (prefix, epoch))
    if not os.path.isdir(path):
        raise MXNetError("no sharded checkpoint at %s" % path)
    if not _is_complete(path):
        raise MXNetError(
            "sharded checkpoint %s is incomplete (no completion marker): "
            "the saving job likely crashed mid-write — fall back to an "
            "earlier epoch" % path)
    if mesh is not None and shardings is None:
        from jax.sharding import NamedSharding, PartitionSpec

        saved = load_partition_specs(prefix, epoch)
        if saved is None:
            raise MXNetError(
                "checkpoint %s has no partition-spec metadata; pass "
                "explicit shardings= to restore onto a mesh" % path)
        known = set(mesh.axis_names)
        for grp, sub in saved.items():
            for name, spec in sub.items():
                used = {ax for e in tuple(spec) if e is not None
                        for ax in (e if isinstance(e, tuple) else (e,))}
                if not used <= known:
                    raise MXNetError(
                        "checkpoint spec for %s/%s uses mesh axes %s absent "
                        "from the target mesh %s"
                        % (grp, name, sorted(used - known),
                           tuple(mesh.axis_names)))
        shardings = {grp: {k: NamedSharding(mesh, spec)
                           for k, spec in sub.items()}
                     for grp, sub in saved.items()}
    ckpt = ocp.PyTreeCheckpointer()
    if shardings is not None:
        # pass shardings INTO orbax so each process reads only the shards
        # it owns (no full-tree materialization per host)
        meta = ckpt.metadata(path)
        tree_meta = getattr(meta, "item_metadata", meta)
        restore_args = {
            grp: {k: (ocp.ArrayRestoreArgs(
                          sharding=shardings.get(grp, {}).get(k))
                      if shardings.get(grp, {}).get(k) is not None
                      else ocp.RestoreArgs())
                  for k in sub}
            for grp, sub in tree_meta.items()}
        tree = ckpt.restore(path, restore_args=restore_args)
    else:
        tree = ckpt.restore(path)
    symbol = None
    sym_path = "%s-symbol.json" % prefix
    if os.path.exists(sym_path):
        symbol = sym.load(sym_path)
    arg = {k: nd.NDArray(_as_jax(v)) for k, v in tree.get("arg", {}).items()}
    aux = {k: nd.NDArray(_as_jax(v)) for k, v in tree.get("aux", {}).items()}
    return symbol, arg, aux


def _as_jax(v):
    import jax.numpy as jnp

    return v if hasattr(v, "devices") else jnp.asarray(v)


# ---------------------------------------------------------------------------
# AOT executable bundles — the compiled half of a checkpoint.  Params say
# WHAT the model computes; the bundle carries the compiled HOW (serialized
# XLA executables + a warmup manifest), so a fresh replica restored from
# this prefix is serving in seconds instead of sitting in the compiler.
# ---------------------------------------------------------------------------

def aot_bundle_path(prefix, epoch):
    """``prefix-NNNN.aot/`` next to the params — same naming family as
    ``prefix-NNNN.params`` / ``prefix-NNNN.orbax``."""
    return os.path.abspath("%s-%04d.aot" % (prefix, epoch))


def save_aot_bundle(prefix, epoch, entries, warmup=None):
    """Write the AOT executable bundle beside a checkpoint.

    ``entries``: primed ``compile_cache.CachedFunction`` wrappers —
    typically ``BucketedPredictor.compiled_entries()`` over every serving
    replica, so the bundle holds one executable per warmed bucket.
    ``warmup``: a manifest dict (input shapes, buckets, dtype) recording
    how to re-drive the same warmup.  Returns the bundle path."""
    from . import compile_cache

    return compile_cache.save_bundle(aot_bundle_path(prefix, epoch),
                                     entries, warmup=warmup)


def attach_aot_bundle(prefix, epoch, mesh=None):
    """Attach ``prefix-NNNN.aot/`` as a read-only compile-cache overlay;
    returns the manifest (or None when no bundle exists).  Raises
    :class:`MXNetError` when the bundle was built for a different device
    topology or mesh — a mismatched executable restore must fail loudly,
    not serve a wrong layout."""
    from . import compile_cache, faults

    # chaos seam: checkpoint.aot.attach:ioerr=1 simulates a torn/unreadable
    # bundle mid-fault-in (the platform leak-path drill)
    faults.fire("checkpoint.aot.attach")
    path = aot_bundle_path(prefix, epoch)
    if not os.path.exists(os.path.join(path, compile_cache.MANIFEST_NAME)):
        return None
    return compile_cache.attach_bundle(path, mesh=mesh)

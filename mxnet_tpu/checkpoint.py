"""Sharded / async checkpointing — the TPU-native half of the checkpoint
story (SURVEY §5.4).

The reference persists ``prefix-symbol.json`` + a dmlc stream of named
arrays (``.params``, /root/reference/src/ndarray/ndarray.cc:633-714); this
framework keeps that format bit-compatible (``mx.nd.save/load``) for
interchange.  This module adds the TPU-era equivalent on top: an
orbax-backed checkpoint keyed by the SAME name->array dicts, which

  * writes each device shard from the process that owns it (multi-host
    global-mesh training checkpoints without gathering to one host),
  * restores with the arrays' shardings preserved,
  * round-trips the symbol JSON next to the weights.

API mirrors ``mx.model.save_checkpoint``/``load_checkpoint``.
"""
from __future__ import annotations

import json
import os
from typing import Dict, Optional, Tuple

from .base import MXNetError

__all__ = ["save_sharded_checkpoint", "load_sharded_checkpoint"]

# written (by process 0) only after every process's shards have landed; a
# directory without it is a crash-torn save.  Orbax's own commit marker
# (commit_success.txt) is honored too, for checkpoints written before this
# guard existed.
_COMPLETE_MARKER = "mxnet_complete"


def _is_complete(path):
    return (os.path.exists(os.path.join(path, _COMPLETE_MARKER))
            or os.path.exists(os.path.join(path, "commit_success.txt")))


def _to_tree(arg_params, aux_params):
    from . import ndarray as nd

    def unwrap(d):
        return {k: (v._data if isinstance(v, nd.NDArray) else v)
                for k, v in (d or {}).items()}

    return {"arg": unwrap(arg_params), "aux": unwrap(aux_params)}


def save_sharded_checkpoint(prefix, epoch, symbol, arg_params, aux_params):
    """Write ``prefix-symbol.json`` + ``prefix-<epoch>.orbax/`` (a sharded
    orbax tree).  In multi-process jobs every process must call this
    collectively; each writes only its addressable shards."""
    import jax
    import orbax.checkpoint as ocp

    if symbol is not None and jax.process_index() == 0:
        # one writer: N processes saving collectively must not race on the
        # shared symbol file
        symbol.save("%s-symbol.json" % prefix)
    path = os.path.abspath("%s-%04d.orbax" % (prefix, epoch))
    tree = _to_tree(arg_params, aux_params)
    ckpt = ocp.PyTreeCheckpointer()
    ckpt.save(path, tree, force=True)
    if jax.process_index() == 0:
        from .filesystem import atomic_write

        atomic_write(os.path.join(path, _COMPLETE_MARKER),
                     lambda f: f.write(b"ok\n"), op="ckpt.write")
    return path


def load_sharded_checkpoint(prefix, epoch, shardings=None):
    """-> (symbol_or_None, arg_params, aux_params) as NDArray dicts.

    ``shardings``: optional ``{"arg"/"aux": {name: jax.sharding}}`` tree to
    restore arrays directly onto a mesh (multi-host restore).
    """
    from . import ndarray as nd
    from . import symbol as sym

    import orbax.checkpoint as ocp

    path = os.path.abspath("%s-%04d.orbax" % (prefix, epoch))
    if not os.path.isdir(path):
        raise MXNetError("no sharded checkpoint at %s" % path)
    if not _is_complete(path):
        raise MXNetError(
            "sharded checkpoint %s is incomplete (no completion marker): "
            "the saving job likely crashed mid-write — fall back to an "
            "earlier epoch" % path)
    ckpt = ocp.PyTreeCheckpointer()
    if shardings is not None:
        # pass shardings INTO orbax so each process reads only the shards
        # it owns (no full-tree materialization per host)
        meta = ckpt.metadata(path)
        tree_meta = getattr(meta, "item_metadata", meta)
        restore_args = {
            grp: {k: (ocp.ArrayRestoreArgs(
                          sharding=shardings.get(grp, {}).get(k))
                      if shardings.get(grp, {}).get(k) is not None
                      else ocp.RestoreArgs())
                  for k in sub}
            for grp, sub in tree_meta.items()}
        tree = ckpt.restore(path, restore_args=restore_args)
    else:
        tree = ckpt.restore(path)
    symbol = None
    sym_path = "%s-symbol.json" % prefix
    if os.path.exists(sym_path):
        symbol = sym.load(sym_path)
    arg = {k: nd.NDArray(_as_jax(v)) for k, v in tree.get("arg", {}).items()}
    aux = {k: nd.NDArray(_as_jax(v)) for k, v in tree.get("aux", {}).items()}
    return symbol, arg, aux


def _as_jax(v):
    import jax.numpy as jnp

    return v if hasattr(v, "devices") else jnp.asarray(v)

"""Python-side backing for the C prediction ABI (src/c_predict_api.cc).

The C library embeds (or joins) a CPython interpreter and drives this shim
with primitive types only — strings, bytes, ints — so the C side stays a
thin marshalling layer.  Handles are integers into a registry, mirroring
the reference's opaque ``PredictorHandle`` over C++ objects
(/root/reference/src/c_predict_api.cc:41-280).
"""
from __future__ import annotations

import threading

import numpy as np

class _HandleRegistry:
    """Integer-handle table — the opaque-handle pattern all C-ABI objects
    share (predictors, NDArrays)."""

    def __init__(self):
        self._items = {}
        self._next = 1
        self._lock = threading.Lock()

    def put(self, obj):
        with self._lock:
            hid = self._next
            self._next += 1
            self._items[hid] = obj
        return hid

    def get(self, hid, kind):
        obj = self._items.get(hid)
        if obj is None:
            raise KeyError("invalid %s handle %d" % (kind, hid))
        return obj

    def pop(self, hid):
        with self._lock:
            self._items.pop(hid, None)

    def replace(self, hid, obj):
        with self._lock:
            if hid not in self._items:
                raise KeyError("invalid handle %d" % hid)
            self._items[hid] = obj


_predictors = _HandleRegistry()


def _ctx_from_dev(dev_type, dev_id=0):
    """Reference dev_type codes (include/mxnet/base.h): 1=cpu, 2=gpu."""
    from . import context as ctx_mod

    return ctx_mod.Context("gpu" if dev_type == 2 else "cpu", dev_id)


def create(symbol_json, params_bytes, input_keys, input_shapes, dev_type):
    """-> integer handle.  ``params_bytes``: a .params file image;
    ``input_shapes``: list of tuples aligned with ``input_keys``."""
    import io as _io

    from . import Predictor
    from . import context as ctx_mod
    from . import ndarray as nd
    from .ndarray import _load_stream

    params = _load_stream(_io.BytesIO(params_bytes)) if params_bytes else {}
    if not isinstance(params, dict):
        from .base import MXNetError

        raise MXNetError(
            "params blob has no names (list container); save checkpoints "
            "as a name->array dict")
    ctx = _ctx_from_dev(dev_type)
    shapes = {k: tuple(int(d) for d in s)
              for k, s in zip(input_keys, input_shapes)}
    pred = Predictor(symbol_json, params, shapes, ctx=ctx)
    return _predictors.put(pred)


def _get(hid):
    return _predictors.get(hid, "predictor")


def set_input(hid, key, data_bytes, shape):
    """``shape`` is the flat element count from the C caller (MXPredSetInput
    passes data as a flat float buffer); reshape to the bound input."""
    pred = _get(hid)
    want = pred._input_shapes[key]
    arr = np.frombuffer(data_bytes, np.float32).reshape(want)
    pred.set_input(key, arr)


def forward(hid):
    pred = _get(hid)
    pred._exec.forward(is_train=False)


def num_outputs(hid):
    return len(_get(hid).get_outputs())


def get_output_shape(hid, index):
    return tuple(int(d) for d in _get(hid).get_output(index).shape)


def get_output(hid, index):
    out = _get(hid).get_output(index).asnumpy().astype(np.float32)
    return out.tobytes()


def reshape(hid, input_keys, input_shapes):
    """New handle bound to new shapes, sharing weights (MXPredReshape)."""
    pred = _get(hid)
    new = pred.reshape({k: tuple(int(d) for d in s)
                        for k, s in zip(input_keys, input_shapes)})
    return _predictors.put(new)


def free(hid):
    _predictors.pop(hid)


# ---------------------------------------------------------------------------
# Core NDArray / op C API backing (src/c_api.cc — the reference's
# c_api.cc NDArray CRUD + MXImperativeInvoke + MXListAllOpNames subset).
# Same integer-handle registry pattern as the predictor above.
# ---------------------------------------------------------------------------

_ndarrays = _HandleRegistry()


def _nd_put(arr):
    return _ndarrays.put(arr)


def _nd_get(hid):
    return _ndarrays.get(hid, "NDArray")


def nd_create(shape, dev_type, dev_id, dtype_flag):
    from . import ndarray as nd
    from .ndarray import _FLAG_TYPE

    return _nd_put(nd.zeros(tuple(int(d) for d in shape),
                            ctx=_ctx_from_dev(dev_type, dev_id),
                            dtype=_FLAG_TYPE[dtype_flag]))


def nd_free(hid):
    _ndarrays.pop(hid)


def nd_shape(hid):
    return tuple(int(d) for d in _nd_get(hid).shape)


def nd_dtype(hid):
    from .ndarray import _TYPE_FLAG

    return _TYPE_FLAG[str(np.dtype(_nd_get(hid).dtype))]


def nd_copy_from(hid, data_bytes):
    arr = _nd_get(hid)
    src = np.frombuffer(data_bytes, dtype=arr.dtype).reshape(arr.shape)
    arr[:] = src


def nd_copy_to(hid):
    return _nd_get(hid).asnumpy().tobytes()


def nd_wait_all():
    from . import ndarray as nd

    nd.waitall()


def nd_save(fname, hids, keys):
    from . import ndarray as nd

    arrs = [_nd_get(h) for h in hids]
    nd.save(fname, dict(zip(keys, arrs)) if keys else arrs)


def nd_load(fname):
    """-> (handles, names); names empty for list containers."""
    from . import ndarray as nd

    data = nd.load(fname)
    if isinstance(data, dict):
        names = list(data.keys())
        return [_nd_put(data[k]) for k in names], names
    return [_nd_put(a) for a in data], []


def list_op_names():
    from .ops import list_ops

    return sorted(list_ops())


def op_input_names(op_name):
    """Declared input order for one op (MXTPUListOpInputs — the
    reference exposes this via MXSymbolGetAtomicSymbolInfo's arg
    descriptions)."""
    from .ops.registry import get_op

    return list(get_op(op_name).input_names({}))


def nd_invoke(op_name, in_hids, keys, vals):
    """MXImperativeInvoke: attrs arrive as strings; the op's declarative
    Param specs parse them (the reference's attr_parser contract)."""
    from .ndarray import NDArray, _invoke

    inputs = [_nd_get(h) for h in in_hids]
    kwargs = dict(zip(keys, vals))
    res = _invoke(op_name, tuple(inputs), kwargs)
    outs = res if isinstance(res, (list, tuple)) else [res]
    for o in outs:
        if not isinstance(o, NDArray):  # _invoke's contract; keep loud
            raise TypeError("op %s returned a non-NDArray output" % op_name)
    return [_nd_put(o) for o in outs]


# ---------------------------------------------------------------------------
# Symbol / Executor C API backing (src/c_api.cc — the reference's
# c_api_symbolic.cc:54-545 + c_api_executor.cc:11-157 surfaces).  A C
# consumer can now build a graph from JSON, infer shapes, bind NDArrays,
# and run forward/backward with no Python-side setup.
# ---------------------------------------------------------------------------

_symbols = _HandleRegistry()
_executors = _HandleRegistry()

# reference OpReqType codes (include/mxnet/op_attr_types.h): kNullOp=0,
# kWriteTo=1, kWriteInplace=2, kAddTo=3
_GRAD_REQ_CODE = {0: "null", 1: "write", 2: "write", 3: "add"}


def sym_from_json(json_str):
    from . import symbol

    return _symbols.put(symbol.load_json(json_str))


def sym_from_file(fname):
    from . import symbol

    return _symbols.put(symbol.load(fname))


def _sym_get(hid):
    obj = _symbols.get(hid, "Symbol")
    if isinstance(obj, _PendingAtomic):
        raise ValueError(
            "symbol handle %d is an uncomposed atomic symbol (%s); call "
            "MXTPUSymbolCompose to wire its inputs first" % (hid, obj.op))
    return obj


def sym_tojson(hid):
    return _sym_get(hid).tojson()


def sym_list_arguments(hid):
    return list(_sym_get(hid).list_arguments())


def sym_list_outputs(hid):
    return list(_sym_get(hid).list_outputs())


def sym_list_aux(hid):
    return list(_sym_get(hid).list_auxiliary_states())


def sym_free(hid):
    _symbols.pop(hid)


def sym_infer_shape(hid, keys, shapes):
    """-> (arg_shapes, out_shapes, aux_shapes) as lists of int tuples, or
    (None, None, None) when the provided shapes underdetermine the graph
    (the reference's ``complete`` flag)."""
    sym = _sym_get(hid)
    kwargs = {k: tuple(int(d) for d in s) for k, s in zip(keys, shapes)}
    arg, out, aux = sym.infer_shape_partial(**kwargs)
    if (arg is None or out is None or aux is None
            or any(s is None for s in arg + out + aux)):
        return None, None, None
    return ([tuple(map(int, s)) for s in arg],
            [tuple(map(int, s)) for s in out],
            [tuple(map(int, s)) for s in aux])


def exec_bind(sym_hid, dev_type, dev_id, arg_hids, grad_hids,
              grad_req_codes, aux_hids):
    """Bind in ``list_arguments`` order (the reference MXExecutorBind
    contract).  ``grad_hids`` entries of 0 mean no gradient buffer for
    that argument; gradients are written IN PLACE into the caller's
    NDArray handles by exec_backward."""
    sym = _sym_get(sym_hid)
    ctx = _ctx_from_dev(dev_type, dev_id)
    names = sym.list_arguments()
    args = [_nd_get(h) for h in arg_hids]
    args_grad = {}
    grad_req = {}
    for name, ghid, code in zip(names, grad_hids, grad_req_codes):
        req = _GRAD_REQ_CODE.get(int(code), "null")
        grad_req[name] = req if ghid else "null"
        if ghid and req != "null":
            args_grad[name] = _nd_get(ghid)
    aux = [_nd_get(h) for h in aux_hids]
    ex = sym.bind(ctx, args, args_grad=args_grad or None,
                  grad_req=grad_req, aux_states=aux or None)
    return _executors.put(ex)


def _exec_get(hid):
    return _executors.get(hid, "Executor")


def exec_forward(hid, is_train):
    _exec_get(hid).forward(is_train=bool(is_train))


def exec_backward(hid, head_hids):
    ex = _exec_get(hid)
    if head_hids:
        ex.backward(out_grads=[_nd_get(h) for h in head_hids])
    else:
        ex.backward()


def exec_outputs(hid):
    """-> fresh NDArray registry handles for the executor outputs."""
    return [_nd_put(o) for o in _exec_get(hid).outputs]


def exec_free(hid):
    _executors.pop(hid)


# ---------------------------------------------------------------------------
# DataIter C API backing (src/c_api.cc — the reference's
# c_api.cc:446-543 MXListDataIters/MXDataIterCreateIter/Next/GetData/
# GetLabel/GetPadNum/BeforeFirst/Free).  String attrs are parsed with
# literal_eval (the reference's param-spec string parsing), so a C
# consumer writes batch_size="8", data_shape="(3, 64, 64)".
# ---------------------------------------------------------------------------

_dataiters = _HandleRegistry()

# iterators creatable from string params alone (file-backed; the
# array-backed NDArrayIter needs live buffers and stays Python-only,
# matching the reference where it is a Python-side class too)
_C_ITER_NAMES = ("MNISTIter", "CSVIter", "ImageRecordIter",
                 "ImageDetRecordIter")


def iter_list():
    return list(_C_ITER_NAMES)


# params that are strings by contract: a shard file named '123' must not
# become the int 123 (the reference parses against typed param specs)
_STR_ATTRS = frozenset((
    "data_csv", "label_csv", "image", "label", "path_imgrec",
    "path_imglist", "path_imgidx", "path_root", "mean_img", "data_name",
    "label_name"))


def _parse_attr(k, v):
    import ast

    if k in _STR_ATTRS:
        return v
    try:
        return ast.literal_eval(v)
    except (ValueError, SyntaxError):
        return v


def iter_create(name, keys, vals):
    from . import image as image_mod
    from . import io as io_mod

    if name not in _C_ITER_NAMES:
        raise ValueError("unknown data iterator %r (have: %s)"
                         % (name, ", ".join(_C_ITER_NAMES)))
    cls = getattr(io_mod, name, None) or getattr(image_mod, name)
    kwargs = {k: _parse_attr(k, v) for k, v in zip(keys, vals)}
    return _dataiters.put({"iter": cls(**kwargs), "batch": None})


def _iter_get(hid):
    return _dataiters.get(hid, "DataIter")


def iter_next(hid):
    rec = _iter_get(hid)
    try:
        rec["batch"] = rec["iter"].next()
        return 1
    except StopIteration:
        rec["batch"] = None
        return 0


def iter_before_first(hid):
    rec = _iter_get(hid)
    rec["batch"] = None
    rec["iter"].reset()


def _iter_batch(hid):
    batch = _iter_get(hid)["batch"]
    if batch is None:
        raise RuntimeError("no current batch: call DataIterNext first")
    return batch


def iter_get_data(hid):
    return _nd_put(_iter_batch(hid).data[0])


def iter_get_label(hid):
    return _nd_put(_iter_batch(hid).label[0])


def iter_get_pad(hid):
    return int(_iter_batch(hid).pad or 0)


def iter_free(hid):
    _dataiters.pop(hid)


# ---------------------------------------------------------------------------
# KVStore C API backing (src/c_api.cc — the reference's c_api.cc:544-700
# MXKVStoreCreate/Init/Push/Pull/GetType/GetRank/GetGroupSize/Barrier).
# The C updater callback (MXKVStoreSetUpdater) is not exposed: on this
# framework the updater is the server-side optimizer (set via Python or
# the launcher), and the local kvstore's default is summing — matching
# how Module drives it.
# ---------------------------------------------------------------------------

_kvstores = _HandleRegistry()


def kv_create(kv_type):
    from . import kvstore

    return _kvstores.put(kvstore.create(kv_type))


def _kv_get(hid):
    return _kvstores.get(hid, "KVStore")


def kv_free(hid):
    try:
        kv = _kv_get(hid)
    except KeyError:
        return
    if hasattr(kv, "close"):
        try:
            kv.close()
        except Exception:
            pass
    _kvstores.pop(hid)


def kv_init(hid, keys, nd_hids):
    kv = _kv_get(hid)
    kv.init(list(keys), [_nd_get(h) for h in nd_hids])


def kv_push(hid, keys, nd_hids):
    kv = _kv_get(hid)
    kv.push(list(keys), [_nd_get(h) for h in nd_hids])


def kv_pull(hid, keys, nd_hids):
    """Pull INTO the caller's existing NDArray handles (reference
    MXKVStorePull semantics: out buffers are caller-provided)."""
    kv = _kv_get(hid)
    kv.pull(list(keys), out=[_nd_get(h) for h in nd_hids])


def kv_type(hid):
    return _kv_get(hid).type


def kv_rank(hid):
    return int(_kv_get(hid).rank)


def kv_group_size(hid):
    return int(_kv_get(hid).num_workers)


def kv_barrier(hid):
    _kv_get(hid)._barrier()


# ---------------------------------------------------------------------------
# Round-5 breadth: C-side graph building (reference c_api_symbolic.cc
# MXSymbolCreateVariable/CreateAtomicSymbol/Compose), NDArray views
# (c_api.cc MXNDArraySlice/Reshape/GetContext, CopyFromTo), executor
# reshape, version/seed.
# ---------------------------------------------------------------------------


def sym_variable(name):
    from . import symbol

    return _symbols.put(symbol.Variable(name))


class _PendingAtomic:
    """CreateAtomicSymbol's result before Compose wires its inputs —
    mirrors the reference's uncomposed nnvm node."""

    def __init__(self, op, attrs):
        self.op = op
        self.attrs = attrs


def sym_atomic(op_name, keys, vals):
    return _symbols.put(_PendingAtomic(op_name, dict(zip(keys, vals))))


def sym_compose(hid, name, keys, arg_hids):
    """Wire inputs into a symbol IN PLACE (the reference composes the
    same handle). Atomic handles become real symbols by calling the op;
    already-real symbols (e.g. loaded from JSON) have their free
    variables substituted via Symbol.compose. keys empty -> positional
    (atomic: the op's input order; real: list_arguments order)."""
    from . import symbol

    target = _symbols.get(hid, "Symbol")
    args = [_sym_get(h) for h in arg_hids]
    if isinstance(target, _PendingAtomic):
        op = getattr(symbol, target.op, None)
        if op is None:
            raise ValueError("unknown operator %r" % target.op)
        attrs = dict(target.attrs)
        if name:
            attrs.setdefault("name", name)
        if keys:
            composed = op(**dict(zip(keys, args)), **attrs)
        else:
            composed = op(*args, **attrs)
    else:
        # delegate to Symbol.__call__ so the positional mapping (and its
        # arity validation) lives in exactly one place
        composed = (target(**dict(zip(keys, args))) if keys
                    else target(*args))
    _symbols.replace(hid, composed)


def nd_slice(hid, begin, end):
    arr = _nd_get(hid)
    begin, end = int(begin), int(end)
    if not 0 <= begin <= end <= arr.shape[0]:
        # the reference MXNDArraySlice CHECKs the range; numpy's silent
        # clamping would hand a C caller a wrong-sized array
        raise ValueError("invalid slice [%d, %d) for axis-0 extent %d"
                         % (begin, end, arr.shape[0]))
    return _nd_put(arr[begin:end])


def nd_reshape(hid, dims):
    return _nd_put(_nd_get(hid).reshape(tuple(int(d) for d in dims)))


def nd_context(hid):
    ctx = _nd_get(hid).context
    return int(ctx.device_typeid), int(ctx.device_id)


def nd_copyfromto(src_hid, dst_hid):
    _nd_get(src_hid).copyto(_nd_get(dst_hid))


def exec_reshape(hid, keys, shapes):
    ex = _executors.get(hid, "Executor")
    new = ex.reshape(**{k: tuple(int(d) for d in s)
                        for k, s in zip(keys, shapes)})
    return _executors.put(new)


def random_seed(seed):
    from . import random as rnd

    rnd.seed(int(seed))


def version():
    from . import __version__

    return str(__version__)

"""Python-side backing for the C prediction ABI (src/c_predict_api.cc).

The C library embeds (or joins) a CPython interpreter and drives this shim
with primitive types only — strings, bytes, ints — so the C side stays a
thin marshalling layer.  Handles are integers into a registry, mirroring
the reference's opaque ``PredictorHandle`` over C++ objects
(/root/reference/src/c_predict_api.cc:41-280).
"""
from __future__ import annotations

import threading

import numpy as np

_registry = {}
_next_id = [1]
_lock = threading.Lock()


def create(symbol_json, params_bytes, input_keys, input_shapes, dev_type):
    """-> integer handle.  ``params_bytes``: a .params file image;
    ``input_shapes``: list of tuples aligned with ``input_keys``."""
    import io as _io

    from . import Predictor
    from . import context as ctx_mod
    from . import ndarray as nd
    from .ndarray import _load_stream

    params = _load_stream(_io.BytesIO(params_bytes)) if params_bytes else {}
    if not isinstance(params, dict):
        from .base import MXNetError

        raise MXNetError(
            "params blob has no names (list container); save checkpoints "
            "as a name->array dict")
    # reference dev_type codes (include/mxnet/base.h): 1=cpu, 2=gpu
    ctx = ctx_mod.Context("gpu" if dev_type == 2 else "cpu")
    shapes = {k: tuple(int(d) for d in s)
              for k, s in zip(input_keys, input_shapes)}
    pred = Predictor(symbol_json, params, shapes, ctx=ctx)
    with _lock:
        hid = _next_id[0]
        _next_id[0] += 1
        _registry[hid] = pred
    return hid


def _get(hid):
    pred = _registry.get(hid)
    if pred is None:
        raise KeyError("invalid predictor handle %d" % hid)
    return pred


def set_input(hid, key, data_bytes, shape):
    """``shape`` is the flat element count from the C caller (MXPredSetInput
    passes data as a flat float buffer); reshape to the bound input."""
    pred = _get(hid)
    want = pred._input_shapes[key]
    arr = np.frombuffer(data_bytes, np.float32).reshape(want)
    pred.set_input(key, arr)


def forward(hid):
    pred = _get(hid)
    pred._exec.forward(is_train=False)


def num_outputs(hid):
    return len(_get(hid).get_outputs())


def get_output_shape(hid, index):
    return tuple(int(d) for d in _get(hid).get_output(index).shape)


def get_output(hid, index):
    out = _get(hid).get_output(index).asnumpy().astype(np.float32)
    return out.tobytes()


def reshape(hid, input_keys, input_shapes):
    """New handle bound to new shapes, sharing weights (MXPredReshape)."""
    pred = _get(hid)
    new = pred.reshape({k: tuple(int(d) for d in s)
                        for k, s in zip(input_keys, input_shapes)})
    with _lock:
        hid2 = _next_id[0]
        _next_id[0] += 1
        _registry[hid2] = new
    return hid2


def free(hid):
    with _lock:
        _registry.pop(hid, None)

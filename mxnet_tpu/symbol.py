"""Symbol — the symbolic graph layer.

TPU-native redesign of the reference's nnvm-based Symbol
(python/mxnet/symbol.py + nnvm graph IR, /root/reference
src/c_api/c_api_symbolic.cc).  A Symbol is a list of output entries of an
immutable DAG of ``_Node``s.  Instead of nnvm passes, the graph lowers to a
pure JAX function (see executor.py) — autodiff, memory planning, fusion and
placement are XLA's job (SURVEY.md §7 architecture mapping).

Kept API surface: composition with auto-created parameter variables and
NameManager naming, ``infer_shape``/``infer_shape_partial`` with parameter
shape filling (reference InferShape pass semantics), ``infer_type``,
``list_arguments/outputs/auxiliary_states``, ``Group``, slicing, attr
scoping (``__ctx_group__`` etc. via AttrScope), JSON save/load compatible
with the reference's graph JSON (nodes/"op": "null" variables/arg_nodes/
heads), and ``bind``/``simple_bind``.
"""
from __future__ import annotations

import json
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

import builtins as _builtins

from .attribute import AttrScope
from .base import MXNetError
from .name import NameManager
from .ops import OpContext, get_op, registered_ops
from .ops.param import attrs_to_strs

__all__ = ["Symbol", "Variable", "var", "Group", "load", "load_json",
           "pow", "maximum", "minimum", "ones", "zeros", "arange"]


class _Node:
    __slots__ = ("op", "name", "attrs", "inputs", "attr_dict", "_aux_names")

    def __init__(self, op, name: str, attrs: Dict[str, Any],
                 inputs: List[Tuple["_Node", int]], attr_dict: Dict[str, str]):
        self.op = op  # None for variables
        self.name = name
        self.attrs = attrs
        self.inputs = inputs
        self.attr_dict = dict(attr_dict or {})
        self._aux_names = None

    @property
    def is_variable(self) -> bool:
        return self.op is None

    def num_outputs(self) -> int:
        return 1 if self.op is None else self.op.num_outputs(self.attrs)

    def aux_names(self) -> List[str]:
        if self.op is None or not self.op.aux:
            return []
        if self._aux_names is None:
            self._aux_names = ["%s_%s" % (self.name, a)
                               for a in self.op.aux_names(self.attrs)]
        return self._aux_names


def _topo_sort(heads: Sequence[Tuple[_Node, int]]) -> List[_Node]:
    order: List[_Node] = []
    seen = set()

    def visit(node: _Node):
        if id(node) in seen:
            return
        seen.add(id(node))
        for parent, _ in node.inputs:
            visit(parent)
        order.append(node)

    for node, _ in heads:
        visit(node)
    return order



def _op_param_strs(node) -> Dict[str, str]:
    """Node attrs filtered to the op's declared params, stringified —
    the ONE filter debug_str / attr_dict / JSON save all share."""
    return {k: v for k, v in attrs_to_strs(node.attrs).items()
            if k in node.op.params}


class Symbol:
    __slots__ = ("_outputs",)

    def __init__(self, outputs: List[Tuple[_Node, int]]):
        self._outputs = list(outputs)

    # ------------------------------------------------------------------
    # introspection
    # ------------------------------------------------------------------
    @property
    def name(self) -> Optional[str]:
        if len(self._outputs) == 1:
            return self._outputs[0][0].name
        return None

    def _nodes(self) -> List[_Node]:
        return _topo_sort(self._outputs)

    def list_arguments(self) -> List[str]:
        return [n.name for n in self._nodes() if n.is_variable]

    def list_outputs(self) -> List[str]:
        names = []
        for node, idx in self._outputs:
            if node.is_variable:
                names.append(node.name)
            else:
                names.append(node.op.output_names(node.attrs, node.name)[idx])
        return names

    def list_auxiliary_states(self) -> List[str]:
        out = []
        for n in self._nodes():
            out.extend(n.aux_names())
        return out

    def compose(self, **kwargs) -> "Symbol":
        """Substitute free variable inputs by name with other symbols'
        outputs, rebuilding the node DAG — the graph-level half of the
        reference's MXSymbolCompose (c_api_symbolic.cc:200-260; nnvm
        composes atomic symbols the same way). Unknown names raise."""
        args = set(self.list_arguments())
        unknown = set(kwargs) - args
        if unknown:
            raise ValueError(
                "compose: %s are not free arguments of this symbol "
                "(free: %s)" % (sorted(unknown), sorted(args)))
        sub = {k: v._outputs[0] for k, v in kwargs.items()}
        memo: Dict[int, _Node] = {}

        def sub_input(inp):
            node, idx = inp
            if node.is_variable and node.name in sub:
                return sub[node.name]
            return (rebuild(node), idx)

        def rebuild(node):
            got = memo.get(id(node))
            if got is not None:
                return got
            if node.is_variable:
                memo[id(node)] = node
                return node
            new = _Node(node.op, node.name, node.attrs,
                        [sub_input(i) for i in node.inputs],
                        node.attr_dict)
            memo[id(node)] = new
            return new

        return Symbol([sub_input(o) for o in self._outputs])

    def __call__(self, *args, **kwargs) -> "Symbol":
        """Compose on inputs — ``x(y, z)`` / ``x(data=y)`` (reference
        symbol.py:212-230). Positional args map to ``list_arguments``
        order; mixing positional and keyword raises like the reference.
        Returns a NEW symbol (this one is untouched — symbols here are
        immutable, so copy-then-mutate collapses to just compose)."""
        kwargs.pop("name", None)  # accepted for API parity; composition
        # here rewires a DAG whose nodes keep their own names
        if args and kwargs:
            raise TypeError(
                "compose only accepts input Symbols either as positional "
                "or keyword arguments, not both")
        if args:
            free = self.list_arguments()
            if len(args) > len(free):
                raise TypeError(
                    "compose got %d positional inputs for %d free "
                    "arguments %s" % (len(args), len(free), free))
            kwargs = dict(zip(free, args))
        return self.compose(**kwargs)

    def debug_str(self) -> str:
        """Readable graph dump (reference symbol.py debug_str —> nnvm
        PrintGraphIR): one line per node with op, name, and inputs."""
        lines = []
        for n in self._nodes():
            if n.is_variable:
                lines.append("Variable:%s" % n.name)
            else:
                ins = ", ".join("%s[%d]" % (p.name, i) for p, i in n.inputs)
                attrs = ", ".join(
                    "%s=%s" % kv
                    for kv in sorted(_op_param_strs(n).items()))
                lines.append("Op:%s, Name=%s%s%s" % (
                    n.op.name, n.name,
                    ("\n  Inputs: %s" % ins) if ins else "",
                    ("\n  Attrs: %s" % attrs) if attrs else ""))
        lines.append("Outputs: %s" % ", ".join(self.list_outputs()))
        return "\n".join(lines)

    def get_internals(self) -> "Symbol":
        entries = []
        for n in self._nodes():
            for i in range(n.num_outputs()):
                entries.append((n, i))
        return Symbol(entries)

    def get_children(self) -> Optional["Symbol"]:
        node = self._outputs[0][0]
        if not node.inputs:
            return None
        return Symbol(list(node.inputs))

    def __getitem__(self, index):
        if isinstance(index, str):
            names = self.list_outputs()
            if index not in names:
                raise ValueError("Cannot find output %s" % index)
            index = names.index(index)
        if isinstance(index, _builtins.slice):
            return Symbol(self._outputs[index])
        return Symbol([self._outputs[index]])

    def __len__(self):
        return len(self._outputs)

    def __iter__(self):
        for i in range(len(self._outputs)):
            yield self[i]

    def __repr__(self):
        name = self.name
        return "<Symbol %s>" % (name if name else "Grouped")

    # ------------------------------------------------------------------
    # attributes
    # ------------------------------------------------------------------
    def attr(self, key: str) -> Optional[str]:
        node = self._outputs[0][0]
        return node.attr_dict.get(key)

    def list_attr(self, recursive=False) -> Dict[str, str]:
        if recursive:
            out = {}
            for n in self._nodes():
                for k, v in n.attr_dict.items():
                    out["%s_%s" % (n.name, k)] = v
            return out
        return dict(self._outputs[0][0].attr_dict)

    def attr_dict(self) -> Dict[str, Dict[str, str]]:
        out = {}
        for n in self._nodes():
            d = dict(n.attr_dict)
            if n.op is not None:
                d.update(attrs_to_strs({k: v for k, v in n.attrs.items()
                                        if k in n.op.params}))
            if d:
                out[n.name] = d
        return out

    def _set_attr(self, **kwargs):
        self._outputs[0][0].attr_dict.update(kwargs)

    # ------------------------------------------------------------------
    # arithmetic
    # ------------------------------------------------------------------
    def _binary(self, other, op_name, scalar_op, reverse=False):
        if isinstance(other, Symbol):
            a, b = (other, self) if reverse else (self, other)
            return _create(op_name, [a, b], {})
        if np.isscalar(other):
            return _create(scalar_op, [self], {"scalar": float(other)})
        raise TypeError("unsupported operand type %s" % type(other))

    def __add__(self, o):
        return self._binary(o, "_Plus", "_PlusScalar")

    __radd__ = __add__

    def __sub__(self, o):
        return self._binary(o, "_Minus", "_MinusScalar")

    def __rsub__(self, o):
        return self._binary(o, "_Minus", "_RMinusScalar", reverse=True)

    def __mul__(self, o):
        return self._binary(o, "_Mul", "_MulScalar")

    __rmul__ = __mul__

    def __truediv__(self, o):
        return self._binary(o, "_Div", "_DivScalar")

    def __rtruediv__(self, o):
        return self._binary(o, "_Div", "_RDivScalar", reverse=True)

    __div__ = __truediv__
    __rdiv__ = __rtruediv__

    def __pow__(self, o):
        return self._binary(o, "_Power", "_PowerScalar")

    def __neg__(self):
        return _create("negative", [self], {})

    def __eq__(self, o):
        if isinstance(o, (Symbol, int, float)):
            return self._binary(o, "_equal", "_equal_scalar")
        return NotImplemented

    def __ne__(self, o):
        if isinstance(o, (Symbol, int, float)):
            return self._binary(o, "_not_equal", "_not_equal_scalar")
        return NotImplemented

    def __gt__(self, o):
        return self._binary(o, "_greater", "_greater_scalar")

    def __ge__(self, o):
        return self._binary(o, "_greater_equal", "_greater_equal_scalar")

    def __lt__(self, o):
        return self._binary(o, "_lesser", "_lesser_scalar")

    def __le__(self, o):
        return self._binary(o, "_lesser_equal", "_lesser_equal_scalar")

    def __hash__(self):
        return id(self)

    def __copy__(self):
        return Symbol(list(self._outputs))

    def __deepcopy__(self, memo):
        return load_json(self.tojson())

    # ------------------------------------------------------------------
    # shape / type inference
    # ------------------------------------------------------------------
    def infer_shape(self, *args, **kwargs):
        arg_shapes, out_shapes, aux_shapes = self._infer_shape_impl(args, kwargs)
        if arg_shapes is not None and any(s is None for s in arg_shapes + out_shapes):
            return None, None, None
        return arg_shapes, out_shapes, aux_shapes

    def infer_shape_partial(self, *args, **kwargs):
        return self._infer_shape_impl(args, kwargs)

    def _infer_shape_impl(self, args, kwargs):
        arg_names = self.list_arguments()
        known: Dict[str, Tuple[int, ...]] = {}
        if args:
            for name, shape in zip(arg_names, args):
                if shape is not None:
                    known[name] = tuple(shape)
        for k, v in kwargs.items():
            if v is not None:
                known[k] = tuple(v)
        entry_shapes, aux_shapes = _forward_infer(
            self, {k: (tuple(v), None) for k, v in known.items()})
        arg_out = []
        for n in self._nodes():
            if n.is_variable:
                st = entry_shapes.get((id(n), 0))
                arg_out.append(st[0] if st else None)
        out_out = []
        for node, idx in self._outputs:
            st = entry_shapes.get((id(node), idx))
            out_out.append(st[0] if st else None)
        aux_out = []
        for n in self._nodes():
            for aname in n.aux_names():
                aux_out.append(aux_shapes.get(aname))
        return arg_out, out_out, aux_out

    def infer_type(self, *args, **kwargs):
        arg_names = self.list_arguments()
        known: Dict[str, Any] = {}
        if args:
            for name, t in zip(arg_names, args):
                if t is not None:
                    known[name] = np.dtype(t)
        for k, v in kwargs.items():
            if v is not None:
                known[k] = np.dtype(v)
        # types propagate through the same machinery, carried next to shapes
        shapes_needed = {k: (None, v) for k, v in known.items()}
        entry_info, _aux = _forward_infer(self, shapes_needed, types_only=True)
        arg_out = [None] * len(arg_names)
        for i, n in enumerate(n for n in self._nodes() if n.is_variable):
            st = entry_info.get((id(n), 0))
            arg_out[i] = st[1] if st else None
        default = np.dtype(np.float32)
        arg_out = [t if t is not None else default for t in arg_out]
        out_out = []
        for node, idx in self._outputs:
            st = entry_info.get((id(node), idx))
            out_out.append(st[1] if st and st[1] is not None else default)
        # aux dtype: ops may pin it (BatchNorm moving stats stay float32 like
        # the reference); otherwise it follows the node's first input dtype.
        aux_out = []
        for n in self._nodes():
            if not n.aux_names():
                continue
            if n.op.aux_dtype is not None:
                adt = np.dtype(n.op.aux_dtype)
            else:
                adt = default
                if n.inputs:
                    st = entry_info.get((id(n.inputs[0][0]), n.inputs[0][1]))
                    if st and st[1] is not None:
                        adt = st[1]
            aux_out.extend([adt] * len(n.aux_names()))
        return arg_out, out_out, aux_out

    # ------------------------------------------------------------------
    # save / load (reference graph JSON format)
    # ------------------------------------------------------------------
    def tojson(self) -> str:
        nodes = self._nodes()
        node_index = {id(n): i for i, n in enumerate(nodes)}
        jnodes = []
        arg_nodes = []
        for i, n in enumerate(nodes):
            if n.is_variable:
                arg_nodes.append(i)
                jnodes.append({"op": "null", "name": n.name,
                               "attr": dict(n.attr_dict), "inputs": []})
            else:
                attr = attrs_to_strs({
                    k: v for k, v in n.attrs.items()
                    if (n.op.params and k in n.op.params) or
                    (n.op.allow_extra_attrs and not k.startswith("__") and
                     k not in ("ctx", "name") and v is not None)})
                attr.update(n.attr_dict)
                jnodes.append({
                    "op": n.op.name, "name": n.name, "attr": attr,
                    "inputs": [[node_index[id(p)], int(idx), 0]
                               for p, idx in n.inputs]})
        heads = [[node_index[id(node)], int(idx), 0] for node, idx in self._outputs]
        return json.dumps({"nodes": jnodes, "arg_nodes": arg_nodes,
                           "node_row_ptr": list(range(len(nodes) + 1)),
                           "heads": heads,
                           "attrs": {"mxnet_version": ["int", 901]}}, indent=2)

    def save(self, fname: str) -> None:
        from .filesystem import atomic_write

        payload = self.tojson().encode("utf-8")
        atomic_write(fname, lambda f: f.write(payload), op="symbol.write")

    # ------------------------------------------------------------------
    # binding
    # ------------------------------------------------------------------
    def bind(self, ctx, args, args_grad=None, grad_req="write", aux_states=None,
             group2ctx=None, shared_exec=None):
        from .executor import Executor

        return Executor(self, ctx, args, args_grad, grad_req, aux_states,
                        group2ctx=group2ctx, shared_exec=shared_exec)

    def simple_bind(self, ctx, grad_req="write", type_dict=None, group2ctx=None,
                    shared_exec=None, **kwargs):
        from . import ndarray as nd
        from .executor import Executor

        arg_shapes, _, aux_shapes = self.infer_shape(**kwargs)
        if arg_shapes is None:
            raise ValueError("Cannot infer shapes: provide input shapes")
        type_dict = type_dict or {}
        arg_types, _, aux_types = self.infer_type(**{
            k: v for k, v in type_dict.items()})
        args = [nd.zeros(s, ctx, dtype=t) for s, t in zip(arg_shapes, arg_types)]
        aux = [nd.zeros(s, ctx, dtype=t) for s, t in zip(aux_shapes, aux_types)]
        grad_req_dict = grad_req if isinstance(grad_req, dict) else {}
        args_grad = {}
        for name, s, t in zip(self.list_arguments(), arg_shapes, arg_types):
            req = grad_req_dict.get(name, grad_req) if grad_req_dict else grad_req
            if req != "null":
                args_grad[name] = nd.zeros(s, ctx, dtype=t)
        return Executor(self, ctx, args, args_grad or None, grad_req, aux,
                        group2ctx=group2ctx, shared_exec=shared_exec)

    # evaluation sugar
    def eval(self, ctx=None, **kwargs):
        from .context import current_context

        ctx = ctx or current_context()
        ex = self.bind(ctx, kwargs)
        return ex.forward()

    # grad of outputs wrt wrt-args as a new executor-level helper
    def grad(self, wrt: Sequence[str]) -> "Symbol":
        raise MXNetError(
            "Symbol.grad is not supported: gradients come from Executor.backward "
            "(JAX autodiff), matching deprecated status in the reference")


# ---------------------------------------------------------------------------
# forward inference over the graph (shapes + dtypes)
# ---------------------------------------------------------------------------


def _forward_infer(sym: Symbol, known: Dict[str, Tuple], types_only=False):
    """Propagate (shape, dtype) through the graph.  ``known`` maps variable
    name -> (shape or None, dtype or None).  Per-op infer_shape functions may
    fill unknown *input* shapes (parameter shape deduction, mirroring the
    reference's bidirectional InferShape pass)."""
    import jax

    nodes = _topo_sort(sym._outputs)
    info: Dict[Tuple[int, int], Tuple] = {}
    aux_shapes: Dict[str, Tuple] = {}

    for n in nodes:
        if n.is_variable:
            shape, dtype = known.get(n.name, (None, None))
            if shape is None:
                sattr = n.attr_dict.get("__shape__")
                if sattr:
                    import ast

                    shape = tuple(ast.literal_eval(sattr))
            if dtype is None:
                dattr = n.attr_dict.get("__dtype__")
                if dattr:
                    dtype = np.dtype(dattr)
            info[(id(n), 0)] = (shape, dtype)

    # iterate to convergence like the reference InferShape pass; deep chains
    # of parameter-shape deduction need more than a fixed handful of sweeps.
    changed = True
    passes = 0
    max_passes = _builtins.max(10, 2 * len(nodes))
    while changed and passes < max_passes:
        changed = False
        passes += 1
        for n in nodes:
            if n.is_variable:
                continue
            in_entries = [(id(p), idx) for p, idx in n.inputs]
            in_infos = [info.get(e, (None, None)) for e in in_entries]
            in_shapes = [s for s, _ in in_infos]
            in_dtypes = [t for _, t in in_infos]
            nout = n.num_outputs()
            have_all_out = all(
                info.get((id(n), i), (None, None))[0] is not None
                for i in range(nout)) if not types_only else all(
                info.get((id(n), i), (None, None))[1] is not None
                for i in range(nout))
            # 1) per-op shape inference (may fill parameter shapes)
            if n.op.infer_shape is not None and not types_only:
                try:
                    new_in, out_shapes, aux = n.op.infer_shape(n.attrs, in_shapes)
                except Exception:
                    new_in, out_shapes, aux = in_shapes, [None] * nout, []
                for e, old, new in zip(in_entries, in_shapes, new_in):
                    if new is not None and old is None:
                        old_info = info.get(e, (None, None))
                        info[e] = (tuple(new), old_info[1])
                        changed = True
                for i, s in enumerate(out_shapes):
                    if s is not None:
                        old_info = info.get((id(n), i), (None, None))
                        if old_info[0] is None:
                            info[(id(n), i)] = (tuple(s), old_info[1])
                            changed = True
                for aname, ashape in zip(n.aux_names(), aux):
                    if ashape is not None and aname not in aux_shapes:
                        aux_shapes[aname] = tuple(ashape)
                        changed = True
            # 2) full eval_shape when every input is fully known
            in_infos = [info.get(e, (None, None)) for e in in_entries]
            full = all(s is not None for s, _ in in_infos)
            if full and not have_all_out:
                structs = [
                    jax.ShapeDtypeStruct(s, t if t is not None else np.float32)
                    for s, t in in_infos]
                n_aux = len(n.op.aux_names(n.attrs))
                if n_aux:
                    known_aux = [aux_shapes.get(a) for a in n.aux_names()]
                    if any(a is None for a in known_aux):
                        continue
                    structs += [jax.ShapeDtypeStruct(s, np.float32)
                                for s in known_aux]
                try:
                    outs = _abstract_apply(n.op, n.attrs, structs)
                except Exception:
                    continue
                for i in range(nout):
                    cur = info.get((id(n), i), (None, None))
                    new = (tuple(outs[i].shape), np.dtype(outs[i].dtype))
                    if cur[0] is None or cur[1] is None:
                        info[(id(n), i)] = new
                        changed = True
            # 3) dtype-only propagation (works without shapes, reference
            # InferType pass semantics: same-dtype rule + dtype attrs)
            in_infos = [info.get(e, (None, None)) for e in in_entries]
            need_dtype = any(
                info.get((id(n), i), (None, None))[1] is None for i in range(nout))
            if need_dtype:
                dt = None
                if "dtype" in n.attrs and n.attrs.get("dtype") and \
                        isinstance(n.attrs.get("dtype"), str):
                    from .ops.param import _np_dtype

                    try:
                        dt = np.dtype(_np_dtype(n.attrs["dtype"]))
                    except TypeError:
                        dt = None
                if dt is None:
                    in_dts = [t for _, t in in_infos if t is not None]
                    if in_dts and all(t is not None for _, t in in_infos):
                        dt = np.result_type(*in_dts)
                    elif not in_entries:
                        dt = np.dtype(np.float32)
                if dt is not None:
                    for i in range(nout):
                        s, t = info.get((id(n), i), (None, None))
                        if t is None:
                            info[(id(n), i)] = (s, dt)
                            changed = True
            # back-propagate dtypes to unknown-dtype variable inputs
            out_dt = info.get((id(n), 0), (None, None))[1]
            if out_dt is not None:
                for (p, pidx), e in zip(n.inputs, in_entries):
                    s, t = info.get(e, (None, None))
                    if t is None and p.is_variable:
                        info[e] = (s, out_dt)
                        changed = True
    return info, aux_shapes


def _abstract_apply(op, attrs, structs):
    import jax

    n_aux = len(op.aux_names(attrs))

    def fn(*arrs):
        main = arrs[: len(arrs) - n_aux] if n_aux else arrs
        aux = arrs[len(arrs) - n_aux:] if n_aux else ()
        opctx = OpContext(is_train=False, rng=jax.random.PRNGKey(0))
        outs, _ = op.apply(opctx, attrs, main, aux)
        return outs

    return jax.eval_shape(fn, *structs)


# ---------------------------------------------------------------------------
# symbol creation
# ---------------------------------------------------------------------------


def _create(op_name: str, sym_args: List[Symbol], kwargs: Dict[str, Any],
            name: Optional[str] = None, attr: Optional[Dict[str, str]] = None):
    op = get_op(op_name)
    sym_kwargs = {}
    attrs = {}
    for k, v in kwargs.items():
        if isinstance(v, Symbol):
            sym_kwargs[k] = v
        else:
            attrs[k] = v
    if op.key_var_num_args and op.key_var_num_args not in attrs and sym_args:
        attrs[op.key_var_num_args] = len(sym_args)
    parsed = op.parse_attrs(attrs)
    name = NameManager.current().get(name, op.hint)
    input_names = op.input_names(parsed)
    slots: Dict[str, Symbol] = {}
    for iname, s in zip(input_names, sym_args):
        slots[iname] = s
    for k, v in sym_kwargs.items():
        if k not in input_names:
            raise MXNetError("unknown input %s for op %s" % (k, op_name))
        slots[k] = v
    entries: List[Tuple[_Node, int]] = []
    for iname in input_names:
        s = slots.get(iname)
        if s is None:
            # auto-create parameter variable (reference composition semantics)
            vnode = _Node(None, "%s_%s" % (name, iname), {},
                          [], AttrScope.current().get(None))
            entries.append((vnode, 0))
        else:
            if len(s._outputs) != 1:
                raise MXNetError(
                    "Cannot use grouped symbol as input %s of %s" % (iname, op_name))
            entries.append(s._outputs[0])
    attr_dict = AttrScope.current().get(attr)
    node = _Node(op, name, parsed, entries, attr_dict)
    return Symbol([(node, i) for i in range(op.num_outputs(parsed))])


def _make_symbol_function(op_name: str, op):
    def fn(*args, **kwargs):
        name = kwargs.pop("name", None)
        attr = kwargs.pop("attr", None)
        sym_args = [a for a in args if isinstance(a, Symbol)]
        return _create(op_name, sym_args, kwargs, name=name, attr=attr)

    fn.__name__ = op_name
    fn.__doc__ = op.doc or "Auto-generated symbol function for op %s" % op_name
    return fn


def Variable(name: str, attr=None, shape=None, dtype=None, init=None, **kwargs) -> Symbol:
    """Create a named variable (placeholder) symbol."""
    if not isinstance(name, str):
        raise TypeError("Expect a string for variable name")
    attr = AttrScope.current().get(attr)
    if shape is not None:
        attr["__shape__"] = str(tuple(shape))
    if dtype is not None:
        attr["__dtype__"] = np.dtype(dtype).name
    if init is not None:
        attr["__init__"] = init if isinstance(init, str) else init.dumps()
    for k, v in kwargs.items():
        attr["__%s__" % k] = str(v)
    node = _Node(None, name, {}, [], attr)
    return Symbol([(node, 0)])


var = Variable


def Group(symbols: Sequence[Symbol]) -> Symbol:
    entries = []
    for s in symbols:
        entries.extend(s._outputs)
    return Symbol(entries)


def load(fname: str) -> Symbol:
    with open(fname) as f:
        return load_json(f.read())


#: scope attrs that belong to the graph, not to any op's parameter struct
#: (the reference AttrScope's sanctioned keys, python/mxnet/attribute.py);
#: consulted only for allow_extra_attrs ops — a declared op param of the
#: same name always stays a param
_GRAPH_LEVEL_ATTRS = frozenset({
    "ctx_group", "lr_mult", "wd_mult", "force_mirroring", "mirror_stage"})


# per-parameter multiplier keys the pre-NNVM format hid in op attrs
# (reference kHiddenKeys, c_api_symbolic.cc:20-22)
_LEGACY_HIDDEN_KEYS = ("ctx_group", "lr_mult", "wd_mult",
                       "force_mirroring", "mirror_stage")


def load_json(json_str: str) -> Symbol:
    data = json.loads(json_str)
    jnodes = data["nodes"]
    nodes: List[_Node] = []
    for jn in jnodes:
        attr = dict(jn.get("attr", jn.get("attrs", {})) or {})
        # legacy (pre-NNVM) graphs keep op params in a separate "param"
        # dict of strings — fold them in, the upgrade pass the reference
        # runs in src/nnvm/legacy_json_util.cc
        attr.update(jn.get("param", {}) or {})
        if jn["op"] == "null":
            nodes.append(_Node(None, jn["name"], {}, [], attr))
        else:
            op = get_op(jn["op"])
            # Keys the op declares are parameters; everything else (user attrs
            # set via AttrScope, e.g. lr_mult, or dunder graph attrs) passes
            # through as node attributes instead of raising — matches the
            # reference, where node attrs and op params share one string map.
            # Graph-level scope attrs must never reach an allow_extra_attrs
            # op (Custom) as constructor kwargs — a checkpoint of a Custom
            # node built under AttrScope(ctx_group=...) would fail to load.
            def _is_param(k):
                if k.startswith("__"):
                    return False
                if k in op.params:  # declared params always win (e.g. the
                    return True     # grad_scale of SoftmaxOutput)
                return op.allow_extra_attrs and k not in _GRAPH_LEVEL_ATTRS

            param_attrs = {k: v for k, v in attr.items() if _is_param(k)}
            graph_attrs = {k: v for k, v in attr.items() if not _is_param(k)}
            parsed = op.parse_attrs(param_attrs)
            inputs = [(nodes[i[0]], i[1]) for i in jn["inputs"]]
            if "param" in jn:
                # legacy upgrade, part 2 (legacy_json_util.cc:60-84):
                # "{input}_{key}" attrs (e.g. weight_lr_mult) push down onto
                # the named variable input as "__{key}__"
                in_names = op.input_names(parsed)
                for k in list(graph_attrs):
                    for hk in _LEGACY_HIDDEN_KEYS:
                        if k.endswith("_" + hk) and len(k) > len(hk) + 1:
                            prefix = k[: -len(hk) - 1]
                            if prefix in in_names:
                                tgt = inputs[in_names.index(prefix)][0]
                                if tgt.op is None:
                                    tgt.attr_dict["__%s__" % hk] = \
                                        graph_attrs.pop(k)
                            break
            nodes.append(_Node(op, jn["name"], parsed, inputs, graph_attrs))
    heads = [(nodes[h[0]], h[1] if len(h) > 1 else 0) for h in data["heads"]]
    return Symbol(heads)


# convenience creators mirroring mx.sym.zeros/ones/arange
def zeros(shape, dtype="float32", **kwargs):
    return _create("_zeros", [], {"shape": shape, "dtype": dtype, **kwargs})


def ones(shape, dtype="float32", **kwargs):
    return _create("_ones", [], {"shape": shape, "dtype": dtype, **kwargs})


def arange(start, stop=None, step=1.0, repeat=1, name=None, dtype="float32"):
    return _create("_arange", [], {"start": start, "stop": stop, "step": step,
                                   "repeat": repeat, "dtype": dtype}, name=name)


def pow(base, exp):
    if isinstance(base, Symbol) and isinstance(exp, Symbol):
        return _create("_Power", [base, exp], {})
    if isinstance(base, Symbol):
        return base.__pow__(exp)
    raise TypeError("pow expects Symbol base")


def maximum(lhs, rhs):
    if isinstance(lhs, Symbol) and isinstance(rhs, Symbol):
        return _create("_Maximum", [lhs, rhs], {})
    s = lhs if isinstance(lhs, Symbol) else rhs
    other = rhs if s is lhs else lhs
    return _create("_MaximumScalar", [s], {"scalar": float(other)})


def minimum(lhs, rhs):
    if isinstance(lhs, Symbol) and isinstance(rhs, Symbol):
        return _create("_Minimum", [lhs, rhs], {})
    s = lhs if isinstance(lhs, Symbol) else rhs
    other = rhs if s is lhs else lhs
    return _create("_MinimumScalar", [s], {"scalar": float(other)})


def _init_symbol_module():
    g = globals()
    for name, op in registered_ops().items():
        if name in g:
            continue
        g[name] = _make_symbol_function(name, op)


_init_symbol_module()

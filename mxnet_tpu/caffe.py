"""Caffe model import — prototxt + caffemodel → (Symbol, params).

Plugin/tooling parity: the reference ships ``plugin/caffe`` (runtime
operator bridge into an installed Caffe) and ``tools/caffe_converter``
(protobuf-compiled offline converter, ``convert_symbol.py`` /
``convert_model.py``). A TPU framework gains nothing from embedding the
Caffe *runtime*; what migrating users actually need is the model
FORMAT, so this module implements the converter natively:

* ``.prototxt`` is protobuf text format — parsed with a ~60-line
  recursive reader (no protobuf dependency);
* ``.caffemodel`` is protobuf wire format — decoded with a minimal
  varint/length-delimited field walker against the public BVLC field
  numbers (NetParameter.layer=100 / V1 layers=2; BlobProto data=5,
  shape=7). Only names + blobs are read from the binary; layer
  topology/attributes come from the prototxt.

Layer coverage matches the reference converter's supported set
(reference convert_symbol.py:60-180): Input/Data, Convolution,
Deconvolution, InnerProduct, Pooling, ReLU, PReLU, Sigmoid, TanH,
Dropout, LRN, BatchNorm+Scale (merged into one mx BatchNorm), Concat,
Eltwise, Flatten, Reshape, Split, Softmax(WithLoss).

    sym, arg_params, aux_params = mx.caffe.convert(
        "deploy.prototxt", "weights.caffemodel")
"""
from __future__ import annotations

import logging
import re
from typing import Dict, List, Tuple

import numpy as np

# ---------------------------------------------------------------------------
# protobuf text format (.prototxt)
# ---------------------------------------------------------------------------

_TOKEN = re.compile(r"""
    (?P<comment>\#[^\n]*)
  | (?P<open>[A-Za-z_][A-Za-z0-9_]*\s*:?\s*\{)   # `f {` and legal `f: {`
  | (?P<kv>[A-Za-z_][A-Za-z0-9_]*\s*:\s*(?:"(?:[^"\\]|\\.)*"|[^\s{}]+))
  | (?P<close>\})
""", re.VERBOSE)


def parse_prototxt(text: str) -> Dict:
    """Text-format protobuf → dict; repeated fields become lists."""
    root: Dict = {}
    stack: List[Dict] = [root]
    for m in _TOKEN.finditer(text):
        if m.lastgroup == "comment":
            continue
        if m.lastgroup == "open":
            name = m.group().rstrip("{").strip().rstrip(":").strip()
            child: Dict = {}
            _append(stack[-1], name, child)
            stack.append(child)
        elif m.lastgroup == "close":
            stack.pop()
        else:
            key, _, raw = m.group().partition(":")
            _append(stack[-1], key.strip(), _scalar(raw.strip()))
    return root


def _append(d, key, value):
    if key in d:
        if not isinstance(d[key], list):
            d[key] = [d[key]]
        d[key].append(value)
    else:
        d[key] = value


def _scalar(raw):
    if raw.startswith('"'):
        return raw[1:-1]
    low = raw.lower()
    if low in ("true", "false"):
        return low == "true"
    try:
        return int(raw)
    except ValueError:
        pass
    try:
        return float(raw)
    except ValueError:
        return raw


def _aslist(v):
    if v is None:
        return []
    return v if isinstance(v, list) else [v]


# ---------------------------------------------------------------------------
# protobuf wire format (.caffemodel) — names + blobs only
# ---------------------------------------------------------------------------


def _walk_fields(buf: memoryview):
    """Yield (field_number, wire_type, value) over one message body."""
    i, n = 0, len(buf)
    while i < n:
        key, i = _varint(buf, i)
        field, wt = key >> 3, key & 7
        if wt == 0:
            v, i = _varint(buf, i)
        elif wt == 1:
            v, i = bytes(buf[i:i + 8]), i + 8
        elif wt == 2:
            ln, i = _varint(buf, i)
            v, i = buf[i:i + ln], i + ln
        elif wt == 5:
            v, i = bytes(buf[i:i + 4]), i + 4
        else:
            raise ValueError("unsupported wire type %d" % wt)
        yield field, wt, v


def _varint(buf, i):
    out = shift = 0
    while True:
        b = buf[i]
        i += 1
        out |= (b & 0x7F) << shift
        if not b & 0x80:
            return out, i
        shift += 7


def _floats(wt, v, acc):
    """BlobProto.data (field 5): packed (wt=2) or repeated scalar (wt=5)."""
    if wt == 2:
        acc.append(np.frombuffer(bytes(v), "<f4"))
    else:
        acc.append(np.frombuffer(v, "<f4"))


def _parse_blob(body) -> np.ndarray:
    data, shape, legacy = [], [], {}
    for field, wt, v in _walk_fields(body):
        if field == 5:
            _floats(wt, v, data)
        elif field == 7 and wt == 2:  # BlobShape { repeated int64 dim=1 }
            for f2, wt2, v2 in _walk_fields(v):
                if f2 == 1:
                    if wt2 == 2:  # packed
                        j = 0
                        while j < len(v2):
                            d, j = _varint(v2, j)
                            shape.append(d)
                    else:
                        shape.append(v2)
        elif field in (1, 2, 3, 4) and wt == 0:  # legacy num/channels/h/w
            legacy[field] = v
    arr = (np.concatenate(data) if data
           else np.zeros(0, "f"))
    if not shape and legacy:
        # legacy 4D num/channels/height/width kept as-is; the layer-aware
        # conversion (convert_model) squeezes where the layer type says
        # so — stripping leading 1s here would corrupt e.g. a
        # num_output=1 convolution weight
        shape = [legacy.get(k, 1) for k in (1, 2, 3, 4)]
    return arr.reshape(shape) if shape else arr


def parse_caffemodel(data: bytes) -> Dict[str, List[np.ndarray]]:
    """{layer_name: [blob arrays]} from a NetParameter binary. Handles
    both layer (field 100, V2) and layers (field 2, V1)."""
    out: Dict[str, List[np.ndarray]] = {}
    for field, wt, v in _walk_fields(memoryview(data)):
        if field not in (100, 2) or wt != 2:
            continue
        name, blobs = None, []
        # V2 LayerParameter: name=1, blobs=7; V1: name=4, blobs=6
        name_f, blob_f = (1, 7) if field == 100 else (4, 6)
        for f2, wt2, v2 in _walk_fields(v):
            if f2 == name_f and wt2 == 2:
                name = bytes(v2).decode()
            elif f2 == blob_f and wt2 == 2:
                blobs.append(_parse_blob(v2))
        if name is not None:
            out[name] = blobs
    return out


# ---------------------------------------------------------------------------
# symbol conversion
# ---------------------------------------------------------------------------


# V1 prototxt `layers { type: CONVOLUTION }` enum names → V2 strings
# (protobuf text format carries enum NAMES; the old numeric wire values
# never appear in text)
_V1_TYPES = {
    "CONVOLUTION": "Convolution", "DECONVOLUTION": "Deconvolution",
    "INNER_PRODUCT": "InnerProduct", "POOLING": "Pooling",
    "RELU": "ReLU", "PRELU": "PReLU", "SIGMOID": "Sigmoid",
    "TANH": "TanH", "DROPOUT": "Dropout", "LRN": "LRN",
    "CONCAT": "Concat", "ELTWISE": "Eltwise", "FLATTEN": "Flatten",
    "RESHAPE": "Reshape", "SPLIT": "Split", "SOFTMAX": "Softmax",
    "SOFTMAX_LOSS": "SoftmaxWithLoss", "DATA": "Data",
    "ACCURACY": "Accuracy", "BN": "BatchNorm", "SCALE": "Scale",
}


def _norm_type(ltype):
    return _V1_TYPES.get(ltype, ltype) if isinstance(ltype, str) else ltype


def _kernel_pair(p, stem, default=0):
    """Caffe params are either isotropic (kernel_size) or _h/_w pairs."""
    iso = p.get("%s_size" % stem if stem == "kernel" else stem)
    if iso is not None:
        iso = _aslist(iso)[0]
        return (iso, iso)
    return (p.get("%s_h" % stem, default), p.get("%s_w" % stem, default))


def convert_symbol(prototxt_text: str):
    """prototxt → (mx Symbol, input_name). Reference parity:
    tools/caffe_converter/convert_symbol.py."""
    from . import symbol as sym_mod

    net = parse_prototxt(prototxt_text)
    layers = _aslist(net.get("layer")) or _aslist(net.get("layers"))
    tops: Dict[str, object] = {}
    input_name = None

    def get(bottom):
        if bottom not in tops:
            raise ValueError("unknown bottom %r" % bottom)
        return tops[bottom]

    # legacy top-level input declaration
    for iname in _aslist(net.get("input")):
        v = sym_mod.Variable(iname)
        tops[iname] = v
        input_name = input_name or iname

    last = None
    for layer in layers:
        ltype = _norm_type(layer.get("type"))
        name = layer.get("name")
        louts = _aslist(layer.get("top"))
        if ltype in ("Input", "Data", "HDF5Data", "ImageData"):
            v = sym_mod.Variable(louts[0] if louts else name)
            tops[louts[0] if louts else name] = v
            input_name = input_name or (louts[0] if louts else name)
            continue
        out = _emit_layer(sym_mod, layer, get, layers)
        if out is _SKIP:
            continue
        for t in (louts or [name]):
            tops[t] = out
        last = out

    if last is None:
        raise ValueError("prototxt contains no convertible layers")
    return last, input_name


_SKIP = object()


def _emit_layer(sym_mod, layer, get, layers):
    """One caffe layer → one mx symbol expression (the ONE mapping both
    convert_symbol and CaffeOp use). ``get(bottom)`` resolves inputs;
    returns _SKIP for non-compute layers."""
    ltype = _norm_type(layer.get("type"))
    name = layer.get("name")
    bots = _aslist(layer.get("bottom"))
    if ltype in ("SoftmaxWithLoss", "Softmax"):
        out = sym_mod.SoftmaxOutput(get(bots[0]), name=name)
    elif ltype in ("Convolution", "Deconvolution"):
        p = layer.get("convolution_param", {})
        kh, kw = _kernel_pair(p, "kernel")
        sh, sw = _kernel_pair(p, "stride", 1) if (
            "stride" in p or "stride_h" in p) else (1, 1)
        ph, pw = _kernel_pair(p, "pad", 0) if (
            "pad" in p or "pad_h" in p) else (0, 0)
        op = (sym_mod.Convolution if ltype == "Convolution"
              else sym_mod.Deconvolution)
        out = op(get(bots[0]), name=name,
                 num_filter=p["num_output"],
                 kernel=(kh, kw), stride=(sh or 1, sw or 1),
                 pad=(ph, pw),
                 num_group=p.get("group", 1),
                 no_bias=not p.get("bias_term", True))
    elif ltype in ("InnerProduct",):
        p = layer.get("inner_product_param", {})
        out = sym_mod.FullyConnected(
            sym_mod.Flatten(get(bots[0])), name=name,
            num_hidden=p["num_output"],
            no_bias=not p.get("bias_term", True))
    elif ltype in ("Pooling",):
        p = layer.get("pooling_param", {})
        kh, kw = _kernel_pair(p, "kernel")
        sh, sw = _kernel_pair(p, "stride", 1)
        ph, pw = _kernel_pair(p, "pad", 0)
        pool = {0: "max", 1: "avg", "MAX": "max",
                "AVE": "avg"}.get(p.get("pool", 0), "max")
        if p.get("global_pooling"):
            out = sym_mod.Pooling(get(bots[0]), name=name,
                                  kernel=(1, 1), global_pool=True,
                                  pool_type=pool)
        else:
            # Caffe pools with ceil-mode window placement
            out = sym_mod.Pooling(
                get(bots[0]), name=name, kernel=(kh, kw),
                stride=(sh or 1, sw or 1), pad=(ph, pw),
                pool_type=pool,
                pooling_convention="full")
    elif ltype in ("ReLU",):
        out = sym_mod.Activation(get(bots[0]), name=name,
                                 act_type="relu")
    elif ltype == "PReLU":
        out = sym_mod.LeakyReLU(get(bots[0]), name=name,
                                act_type="prelu")
    elif ltype in ("Sigmoid",):
        out = sym_mod.Activation(get(bots[0]), name=name,
                                 act_type="sigmoid")
    elif ltype in ("TanH",):
        out = sym_mod.Activation(get(bots[0]), name=name,
                                 act_type="tanh")
    elif ltype in ("Dropout",):
        p = layer.get("dropout_param", {})
        out = sym_mod.Dropout(get(bots[0]), name=name,
                              p=p.get("dropout_ratio", 0.5))
    elif ltype in ("LRN",):
        p = layer.get("lrn_param", {})
        out = sym_mod.LRN(get(bots[0]), name=name,
                          alpha=p.get("alpha", 1e-4),
                          beta=p.get("beta", 0.75),
                          knorm=p.get("k", 1.0),
                          nsize=p.get("local_size", 5))
    elif ltype == "BatchNorm":
        p = layer.get("batch_norm_param", {})
        # fix_gamma=False: a following Scale layer's gamma/beta fold
        # into this op's arg params (without Scale the defaults
        # gamma=1/beta=0 reproduce bare caffe BatchNorm)
        out = sym_mod.BatchNorm(get(bots[0]), name=name,
                                eps=p.get("eps", 1e-5),
                                use_global_stats=True,
                                fix_gamma=False)
    elif ltype == "Scale":
        # Caffe pairs BatchNorm (normalize) + Scale (gamma/beta);
        # mx BatchNorm holds all four — the Scale layer merges into
        # its bottom BatchNorm (reference convert_symbol.py does the
        # same): symbol-side it is identity, param-side
        # convert_model folds the blobs in. A standalone Scale has
        # no BatchNorm to fold into — refuse rather than silently
        # dropping the scaling math.
        if _bn_producer(layers, bots[0]) is None:
            raise NotImplementedError(
                "standalone Scale layer %r (bottom %r is not a "
                "BatchNorm output) is not supported" % (name, bots[0]))
        out = get(bots[0])
    elif ltype in ("Concat",):
        p = layer.get("concat_param", {})
        out = sym_mod.Concat(*[get(b) for b in bots], name=name,
                             dim=p.get("axis", 1))
    elif ltype == "Eltwise":
        p = layer.get("eltwise_param", {})
        op = p.get("operation", "SUM")
        ins = [get(b) for b in bots]  # caffe allows N bottoms
        out = ins[0]
        for rhs in ins[1:]:
            if op in ("SUM", 1):
                out = out + rhs
            elif op in ("PROD", 0):
                out = out * rhs
            else:
                out = sym_mod.maximum(out, rhs)
    elif ltype in ("Flatten",):
        out = sym_mod.Flatten(get(bots[0]), name=name)
    elif ltype == "Reshape":
        p = layer.get("reshape_param", {})
        dims = tuple(_aslist(p.get("shape", {}).get("dim", [])))
        out = sym_mod.Reshape(get(bots[0]), name=name, shape=dims)
    elif ltype in ("Split",):
        out = get(bots[0])
    elif ltype in ("Accuracy", "SoftmaxWithLossWeight"):
        return _SKIP
    else:
        raise NotImplementedError(
            "caffe layer type %r (%s) not supported" % (ltype, name))
    return out


def convert_model(prototxt_text: str, caffemodel_bytes: bytes):
    """→ (symbol, arg_params, aux_params), mx-native layouts. Reference
    parity: tools/caffe_converter/convert_model.py (incl. BatchNorm +
    Scale blob merging)."""
    from . import ndarray as nd

    sym, _ = convert_symbol(prototxt_text)
    blobs = parse_caffemodel(caffemodel_bytes)
    net = parse_prototxt(prototxt_text)
    layers = _aslist(net.get("layer")) or _aslist(net.get("layers"))
    by_name = {la.get("name"): la for la in layers}
    arg_params, aux_params = {}, {}
    # the reference converter swaps channels 0/2 of the FIRST convolution's
    # weight when it consumes 3/4-channel input (convert_model.py:68-71):
    # Caffe pipelines feed BGR (OpenCV), mx pipelines RGB
    first_conv = next((la.get("name") for la in layers
                       if _norm_type(la.get("type")) == "Convolution"), None)

    for name, lblobs in blobs.items():
        if name not in by_name:
            # train-vs-deploy mismatch (loss-only or renamed layers):
            # emitting params for them breaks bind/load of the converted
            # symbol, so skip the blobs like the reference prototxt-driven
            # converter implicitly does
            logging.warning(
                "caffe.convert_model: layer %r has blobs in the caffemodel "
                "but is absent from the deploy prototxt; skipping", name)
            continue
        layer = by_name[name]
        ltype = _norm_type(layer.get("type"))
        if not lblobs:
            continue
        if ltype == "BatchNorm":
            mean, var = lblobs[0], lblobs[1]
            scale = lblobs[2].reshape(()) if len(lblobs) > 2 else 1.0
            f = (1.0 / float(scale)) if float(np.asarray(scale)) else 0.0
            aux_params[name + "_moving_mean"] = nd.array(mean.ravel() * f)
            aux_params[name + "_moving_var"] = nd.array(var.ravel() * f)
            # gamma/beta defaults until a Scale layer overrides
            arg_params.setdefault(
                name + "_gamma", nd.array(np.ones_like(mean.ravel())))
            arg_params.setdefault(
                name + "_beta", nd.array(np.zeros_like(mean.ravel())))
        elif ltype == "Scale":
            bn = _aslist(layer.get("bottom"))[0]
            bn_layer = _bn_producer(layers, bn)
            if bn_layer is None:  # convert_symbol refuses these too
                raise NotImplementedError(
                    "standalone Scale layer %r is not supported" % name)
            arg_params[bn_layer + "_gamma"] = nd.array(lblobs[0].ravel())
            if len(lblobs) > 1:
                arg_params[bn_layer + "_beta"] = nd.array(
                    lblobs[1].ravel())
        elif ltype == "PReLU":
            arg_params[name + "_gamma"] = nd.array(lblobs[0].ravel())
        elif ltype == "InnerProduct":
            # V1 legacy blobs arrive (1, 1, out, in); V2 (out, in) —
            # the matrix is the last two dims either way
            W = lblobs[0]
            arg_params[name + "_weight"] = nd.array(
                W.reshape(W.shape[-2], W.shape[-1]))
            if len(lblobs) > 1:
                arg_params[name + "_bias"] = nd.array(lblobs[1].ravel())
        else:
            # conv [out,in,kh,kw] layout matches mx
            wmat = lblobs[0]
            if name == first_conv and wmat.ndim == 4 \
                    and wmat.shape[1] in (3, 4):
                wmat = wmat.copy()
                wmat[:, [0, 2]] = wmat[:, [2, 0]]  # BGR -> RGB
            arg_params[name + "_weight"] = nd.array(wmat)
            if len(lblobs) > 1:
                arg_params[name + "_bias"] = nd.array(lblobs[1].ravel())
    return sym, arg_params, aux_params


def _bn_producer(layers, top):
    """Name of the BatchNorm layer producing ``top`` (None if the
    producer is not a BatchNorm — a standalone Scale, refused)."""
    for la in layers:
        if top in _aslist(la.get("top")) and \
                _norm_type(la.get("type")) == "BatchNorm":
            return la.get("name")
    return None


def convert_mean(binaryproto: bytes) -> np.ndarray:
    """Mean-file BlobProto → (C, H, W) array (reference
    convert_mean.py). Accepts the raw bytes of a .binaryproto file.
    Real mean files carry legacy num/channels/height/width dims with
    num=1 — squeezed to match the reference tool's output shape."""
    arr = _parse_blob(memoryview(binaryproto))
    if arr.ndim == 4 and arr.shape[0] == 1:
        arr = arr[0]
    return arr


_CAFFEOP_SEQ = 0


def CaffeOp(data, prototxt: str, name=None):
    """Single-layer runtime sugar — the reference plugin's CaffeOp
    (``plugin/caffe/caffe_operator.cc``) embedded a Caffe layer spec in
    the graph and ran Caffe's kernel; here the same prototxt snippet is
    mapped onto the native op registry at graph-build time:

        net = mx.caffe.CaffeOp(net, 'layer { name: "c1" '
                               'type: "Convolution" convolution_param '
                               '{ num_output: 8 kernel_size: 3 } }')

    The snippet must contain exactly one layer; bottom/top wiring is
    implied by ``data``."""
    cfg = parse_prototxt(prototxt)
    layers = _aslist(cfg.get("layer")) or _aslist(cfg.get("layers"))
    if not layers and cfg.get("type"):
        layers = [cfg]  # bare `name: ... type: ...` body
    if len(layers) != 1:
        raise ValueError("CaffeOp needs exactly one layer in the "
                         "prototxt snippet (got %d)" % len(layers))
    layer = dict(layers[0])
    if name is not None:
        layer["name"] = name
    if "name" not in layer:
        # unique per call — two unnamed parametric layers must not
        # silently share '<name>_weight' params
        global _CAFFEOP_SEQ
        _CAFFEOP_SEQ += 1
        layer["name"] = "caffeop%d" % _CAFFEOP_SEQ
    layer["bottom"] = "_caffeop_in"
    layer["top"] = layer["name"]
    from . import symbol as sym_mod

    out = _emit_layer(sym_mod, layer, lambda bottom: data, [layer])
    if out is _SKIP:
        raise ValueError("layer type %r emits no computation"
                         % layer.get("type"))
    return out


def convert(prototxt_path: str, caffemodel_path: str):
    """File-path front end (CLI: tools/caffe_converter.py)."""
    from .filesystem import open_uri

    with open_uri(prototxt_path, "r") as f:
        text = f.read()
    with open_uri(caffemodel_path, "rb") as f:
        data = f.read()
    return convert_model(text, data)


# -- test/tooling support: a wire-format WRITER so tests can fabricate
# caffemodel binaries without Caffe or protobuf installed ----------------


def _enc_varint(v):
    out = bytearray()
    while True:
        b = v & 0x7F
        v >>= 7
        out.append(b | (0x80 if v else 0))
        if not v:
            return bytes(out)


def _enc_field(field, wt, payload):
    return _enc_varint(field << 3 | wt) + payload


def encode_blob(arr: np.ndarray) -> bytes:
    arr = np.asarray(arr, "<f4")
    shape = b"".join(_enc_field(1, 0, _enc_varint(d)) for d in arr.shape)
    body = _enc_field(7, 2, _enc_varint(len(shape)) + shape)
    data = arr.ravel().tobytes()
    body += _enc_field(5, 2, _enc_varint(len(data)) + data)
    return body


def encode_caffemodel(layer_blobs: Dict[str, List[np.ndarray]]) -> bytes:
    """NetParameter binary (V2 layer field) for tests/fixtures."""
    out = b""
    for name, blobs in layer_blobs.items():
        nm = name.encode()
        body = _enc_field(1, 2, _enc_varint(len(nm)) + nm)
        for b in blobs:
            enc = encode_blob(b)
            body += _enc_field(7, 2, _enc_varint(len(enc)) + enc)
        out += _enc_field(100, 2, _enc_varint(len(body)) + body)
    return out

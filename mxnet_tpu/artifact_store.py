"""Shared atomic entry-store helpers for content-addressed artifact
caches.

One on-disk grammar for every persistent artifact family the framework
keeps beside a job (serialized XLA executables in ``compile_cache``,
tuning winners in ``autotune``):

    MAGIC | u64 meta_len | meta json | payload bytes

written atomically (tmp+fsync+rename, the checkpoint discipline) with a
CRC32 sidecar, read back with CRC + header verification, and
listed/verified/pruned by one admin implementation.  Each family
parameterizes an :class:`EntryStore` with its own magic, filename
suffix, and fault-injection op prefix — the families share THIS code
instead of copy-pasting the format.
"""
from __future__ import annotations

import hashlib
import json
import os
from typing import Callable, List, Optional, Tuple

from .base import MXNetError

__all__ = ["EntryStore", "digest_of"]


def digest_of(parts: dict) -> str:
    """Canonical content fingerprint: sha256 over the sorted-key JSON of
    ``parts``, truncated to 32 hex chars (the entry filename stem)."""
    blob = json.dumps(parts, sort_keys=True, default=str).encode()
    return hashlib.sha256(blob).hexdigest()[:32]


class EntryStore:
    """Format + admin surface for one artifact family.

    Parameters
    ----------
    magic : bytes
        File magic; a mismatch is a loud "not a <label> entry" error.
    suffix : str
        Entry filename suffix (e.g. ``".mxc"``).
    label : str
        Human name used in error messages.
    op_prefix : str
        Dotted-op prefix for the ``faults`` layer: stores fire
        ``<op_prefix>.store`` through ``filesystem.atomic_write``.
    """

    def __init__(self, magic: bytes, suffix: str, label: str,
                 op_prefix: str):
        self.magic = magic
        self.suffix = suffix
        self.label = label
        self.op_prefix = op_prefix

    # -- paths / headers --------------------------------------------------
    def entry_path(self, d: str, digest: str) -> str:
        return os.path.join(d, digest + self.suffix)

    def entry_meta(self, path: str) -> dict:
        """Parse just the json header of an entry (payload untouched)."""
        with open(path, "rb") as f:
            magic = f.read(len(self.magic))
            if magic != self.magic:
                raise MXNetError("%s is not a %s entry"
                                 % (path, self.label))
            mlen = int.from_bytes(f.read(8), "little")
            if mlen <= 0 or mlen > (1 << 24):
                raise MXNetError("%s has an implausible meta header" % path)
            return json.loads(f.read(mlen).decode())

    # -- write / read -----------------------------------------------------
    def write_entry(self, d: str, digest: str, meta: dict,
                    payload_bytes: bytes, op: Optional[str] = None) -> str:
        from .filesystem import atomic_write

        os.makedirs(d, exist_ok=True)
        meta_blob = json.dumps(meta, sort_keys=True, default=str).encode()
        path = self.entry_path(d, digest)

        def writer(f):
            f.write(self.magic)
            f.write(len(meta_blob).to_bytes(8, "little"))
            f.write(meta_blob)
            f.write(payload_bytes)

        # atomic_write fires the fault layer under the family's dotted op
        # and lands the CRC sidecar after the data — identical discipline
        # to checkpoints
        atomic_write(path, writer, checksum=True,
                     op=op or (self.op_prefix + ".store"))
        return path

    def read_payload(self, path: str) -> Tuple[dict, bytes]:
        with open(path, "rb") as f:
            blob = f.read()
        if blob[:len(self.magic)] != self.magic:
            raise MXNetError("%s is not a %s entry" % (path, self.label))
        off = len(self.magic)
        mlen = int.from_bytes(blob[off:off + 8], "little")
        off += 8
        if mlen <= 0 or off + mlen > len(blob):
            raise MXNetError("%s has a torn meta header" % path)
        meta = json.loads(blob[off:off + mlen].decode())
        return meta, blob[off + mlen:]

    # -- admin: ls / verify / prune --------------------------------------
    def ls_entries(self, d: str,
                   meta_fields: Optional[Callable[[dict], dict]] = None
                   ) -> List[dict]:
        """[{digest, path, bytes, mtime, **meta_fields(meta)}] for every
        entry in ``d`` (unreadable headers report kind='corrupt')."""
        out = []
        if not os.path.isdir(d):
            return out
        for name in sorted(os.listdir(d)):
            if not name.endswith(self.suffix):
                continue
            path = os.path.join(d, name)
            st = os.stat(path)
            rec = {"digest": name[:-len(self.suffix)], "path": path,
                   "bytes": st.st_size, "mtime": st.st_mtime}
            try:
                meta = self.entry_meta(path)
                rec.update(meta_fields(meta) if meta_fields else meta)
            except Exception as exc:
                rec.update(kind="corrupt", error=repr(exc)[:120])
            out.append(rec)
        return out

    def verify_entry(self, path: str,
                     payload_check: Optional[Callable] = None,
                     env_ok: Optional[Callable[[dict], bool]] = None
                     ) -> Tuple[bool, str]:
        """(ok, detail): CRC sidecar + header + payload check —
        everything short of actually using the entry.  ``payload_check``
        (meta, payload) may raise to flag an unreadable payload;
        ``env_ok(meta)`` False downgrades the detail (still ok: a
        stale-env entry invalidates at load, it is not corrupt)."""
        from .filesystem import verify_crc_sidecar

        crc = verify_crc_sidecar(path)
        if crc is False:
            return False, "crc mismatch"
        try:
            meta, payload = self.read_payload(path)
            if payload_check is not None:
                payload_check(meta, payload)
        except Exception as exc:
            return False, "unreadable: %r" % (exc,)
        if env_ok is not None and not env_ok(meta):
            return True, "ok (stale env: invalidates on load)"
        return True, "ok"

    def prune(self, d: str, budget_mb: int) -> List[str]:
        """Delete oldest-mtime entries (and their sidecars) until the
        directory is under ``budget_mb``.  Returns the removed paths."""
        entries = self.ls_entries(d)
        total = sum(e["bytes"] for e in entries)
        budget = budget_mb * (1 << 20)
        removed = []
        for e in sorted(entries, key=lambda e: e["mtime"]):
            if total <= budget:
                break
            for p in (e["path"], e["path"] + ".crc32"):
                try:
                    os.unlink(p)
                except OSError:
                    pass
            removed.append(e["path"])
            total -= e["bytes"]
        return removed

"""FaultPlan — the deterministic decision engine behind ``mxnet_tpu.faults``.

A plan is a list of rules, each binding an *operation glob* to a fault
kind.  Instrumented code names every I/O site with a dotted operation
string (``kv.client.send``, ``ckpt.write``, ...) and calls
:func:`mxnet_tpu.faults.fire` there; the plan decides — reproducibly,
from a seed — whether that particular call fails, stalls, or kills the
process.

Spec grammar (one string, env-var friendly)::

    spec    := rule (";" rule)*
    rule    := op_glob ":" action ("," action)*
    action  := kind "=" rate ["@" param]
             | kind ["@" param]          # rate-less shorthand, rate = 1

* ``op_glob`` — fnmatch pattern over operation names (``kv.client.*``).
* ``kind`` — one of ``drop`` (raise :class:`InjectedConnectionError`),
  ``ioerr`` (raise :class:`InjectedIOError`), ``delay`` (sleep),
  ``partial`` (torn file write — consumed by
  :func:`mxnet_tpu.filesystem.atomic_write`), ``kill``
  (``os._exit(137)``, a hard crash no ``finally`` can intercept),
  ``nan``/``bitflip`` (tensor corruption — consumed by
  :meth:`FaultPlan.corrupt` at instrumented tensor sites like
  ``guardian.grad``).
* ``rate`` — probability in [0, 1] drawn from the rule's own seeded
  stream, so unrelated rules never perturb each other's decisions.
* ``param`` — kind-specific: delay duration (``10ms``/``0.25s``/bare
  seconds), partial-write fraction kept, bitflip bit index, or — for
  any kind — ``#N`` to fire exactly on the N-th matching call
  (deterministic count trigger; rate is ignored).

Examples::

    kv.client.*:drop=0.3                 # 30% of worker wire ops drop
    kv.client.recv:drop=1@#2             # drop exactly the 2nd ACK read
    ckpt.write:partial=1@0.5             # every save tears at 50%
    kv.server.recv:kill=1@#40;*:delay=0.05@5ms
    guardian.grad:bitflip@#1             # flip a bit in the 1st guarded
                                         # gradient (rate-less shorthand)

Determinism contract: each rule owns a ``random.Random`` seeded from
``(seed, rule_index)`` and a call counter, so the decision for the N-th
call matching a rule depends only on (spec, seed, N) — not on wall time,
thread scheduling of *other* operations, or process layout.  The same
seed therefore replays the same faults (``tools/chaos_run.py``).
"""
from __future__ import annotations

import fnmatch
import os
import random
import threading
from typing import Dict, List, Optional, Tuple

__all__ = ["FaultPlan", "Rule", "InjectedConnectionError", "InjectedIOError",
           "parse_spec"]

_KINDS = ("drop", "ioerr", "delay", "partial", "kill", "nan", "bitflip")

# kinds that are inert in fire() and polled by the instrumented tensor
# site via FaultPlan.corrupt (the 'partial' pattern, but for arrays)
_CORRUPT_KINDS = ("nan", "bitflip")


class InjectedConnectionError(ConnectionResetError):
    """A connection drop injected by the active fault plan.

    Subclasses :class:`ConnectionResetError` so the code under test takes
    exactly the path a real peer reset would take."""


class InjectedIOError(OSError):
    """A file-I/O failure injected by the active fault plan."""


class Rule:
    __slots__ = ("op", "kind", "rate", "param", "nth")

    def __init__(self, op: str, kind: str, rate: float,
                 param: Optional[float] = None, nth: Optional[int] = None):
        if kind not in _KINDS:
            raise ValueError("unknown fault kind %r (one of %s)"
                             % (kind, "/".join(_KINDS)))
        self.op = op
        self.kind = kind
        self.rate = float(rate)
        self.param = param
        self.nth = nth  # exact call index trigger ('#N'), 1-based

    def __repr__(self):
        extra = "@#%d" % self.nth if self.nth is not None else (
            "@%g" % self.param if self.param is not None else "")
        return "%s:%s=%g%s" % (self.op, self.kind, self.rate, extra)


def _parse_param(kind: str, raw: str) -> Tuple[Optional[float], Optional[int]]:
    """-> (param, nth).  '#N' is the deterministic count trigger; delay
    params accept ms/s suffixes and normalize to seconds."""
    if raw.startswith("#"):
        return None, int(raw[1:])
    if kind == "delay":
        if raw.endswith("ms"):
            return float(raw[:-2]) / 1e3, None
        if raw.endswith("s"):
            return float(raw[:-1]), None
        return float(raw), None
    return float(raw), None


def parse_spec(spec: str) -> List[Rule]:
    rules: List[Rule] = []
    for chunk in spec.split(";"):
        chunk = chunk.strip()
        if not chunk:
            continue
        op, sep, actions = chunk.partition(":")
        if not sep:
            raise ValueError(
                "bad fault rule %r: expected 'op_glob:kind=rate[@param]'"
                % chunk)
        for action in actions.split(","):
            action = action.strip()
            kind, sep, rest = action.partition("=")
            if not sep:
                # rate-less shorthand 'kind[@param]' — rate defaults to 1
                # (reads naturally with '#N' count triggers:
                # 'guardian.grad:bitflip@#1')
                kind, _, param_s = action.partition("@")
                rate_s = "1"
            else:
                rate_s, _, param_s = rest.partition("@")
            param, nth = _parse_param(kind.strip(), param_s) if param_s \
                else (None, None)
            rules.append(Rule(op.strip(), kind.strip(), float(rate_s),
                              param, nth))
    return rules


class FaultPlan:
    """Seeded fault schedule over operation names (see module docstring).

    Thread-safe: rule streams/counters are guarded by one lock; the
    decision for the N-th call matching a rule is a pure function of
    (spec, seed, N).
    """

    def __init__(self, spec, seed: int = 0):
        if isinstance(spec, str):
            self.spec = spec
            self.rules = parse_spec(spec)
        else:  # pre-built rule list
            self.rules = list(spec)
            self.spec = ";".join(repr(r) for r in self.rules)
        self.seed = int(seed)
        self._lock = threading.Lock()
        # per-rule independent streams: interleaved calls to other ops
        # must not shift this rule's decision sequence
        self._rngs = [random.Random((self.seed + 1) * 1000003 + i)
                      for i in range(len(self.rules))]
        self._counts = [0] * len(self.rules)
        self.events: List[Tuple[str, str, int]] = []  # (op, kind, call_no)

    def __repr__(self):
        return "FaultPlan(seed=%d, %r)" % (self.seed, self.spec)

    # -- decisions ---------------------------------------------------------
    def _decide(self, op: str):
        """-> list of (Rule, call_no) that fire for this call of ``op``."""
        fired = []
        with self._lock:
            for i, rule in enumerate(self.rules):
                if not fnmatch.fnmatchcase(op, rule.op):
                    continue
                self._counts[i] += 1
                n = self._counts[i]
                if rule.nth is not None:
                    hit = (n == rule.nth)
                else:
                    # always draw, even at rate 0/1: the stream position
                    # stays aligned with the call count
                    hit = self._rngs[i].random() < rule.rate
                if hit:
                    self.events.append((op, rule.kind, n))
                    fired.append((rule, n))
        return fired

    @staticmethod
    def _note_injected(op, kind, call_no):
        """Telemetry counter + event per injected fault (no-op when off)."""
        from .. import telemetry as _tm

        if not _tm.enabled():
            return
        _tm.labeled_counter("mxtpu_faults_injected_total", "kind",
                            "Faults injected by the active plan.").inc(kind)
        _tm.log_event("fault_injected", op=op, fault=kind, call_no=call_no)

    def fire(self, op: str) -> None:
        """Evaluate all rules for one operation; may sleep, raise, or kill
        the process.  ``partial`` rules never fire here — they are polled
        by the file writer via :meth:`partial_fraction`."""
        import time

        for rule, n in self._decide(op):
            self._note_injected(op, rule.kind, n)
            if rule.kind == "delay":
                time.sleep(rule.param if rule.param is not None else 0.01)
            elif rule.kind == "drop":
                raise InjectedConnectionError(
                    "injected connection drop at %s (call #%d, seed %d)"
                    % (op, n, self.seed))
            elif rule.kind == "ioerr":
                raise InjectedIOError(
                    "injected I/O error at %s (call #%d, seed %d)"
                    % (op, n, self.seed))
            elif rule.kind == "kill":
                try:
                    # flight recorder: leave postmortem evidence of the
                    # victim's last spans/events before the hard exit
                    from .. import telemetry as _tm

                    _tm.flight_recorder.dump("fault-kill:%s" % op)
                except Exception:
                    pass
                os._exit(137)
            # 'partial' and the corrupt kinds intentionally inert in
            # fire() — polled by their instrumented sites instead

    def partial_fraction(self, op: str) -> Optional[float]:
        """Fraction of the file to keep for a torn write at ``op``, or
        None when no ``partial`` rule fires on this call."""
        frac = None
        with self._lock:
            for i, rule in enumerate(self.rules):
                if rule.kind != "partial" or \
                        not fnmatch.fnmatchcase(op, rule.op):
                    continue
                self._counts[i] += 1
                n = self._counts[i]
                if rule.nth is not None:
                    hit = (n == rule.nth)
                else:
                    hit = self._rngs[i].random() < rule.rate
                if hit:
                    self.events.append((op, rule.kind, n))
                    frac = rule.param if rule.param is not None else 0.5
                    hit_no = n
        if frac is not None:
            self._note_injected(op, "partial", hit_no)
        return frac

    def targets_corruption(self, op: str) -> bool:
        """True when any ``nan``/``bitflip`` rule's glob matches ``op``.
        A pure predicate — counters and RNG streams are not advanced —
        so callers can branch (e.g. the Module keeps gradients
        host-visible for injection) without perturbing the schedule."""
        return any(r.kind in _CORRUPT_KINDS and
                   fnmatch.fnmatchcase(op, r.op) for r in self.rules)

    def corrupt(self, op: str, array):
        """Tensor-corruption poll for instrumented sites: returns
        ``array`` untouched when no ``nan``/``bitflip`` rule fires on
        this call, else a corrupted **copy**.

        The victim element is picked from the rule's own seeded stream,
        so which element is hit depends only on (spec, seed, call_no) —
        the determinism contract the chaos scenarios replay against.

        * ``nan`` — the picked element becomes NaN (for float dtypes;
          integer arrays get their maximum value).
        * ``bitflip`` — one bit of the picked element flips.  By default
          the most-significant *exponent* bit (the canonical worst-case
          SDC: the value scales by ~2**128 or collapses toward zero);
          ``@B`` picks an explicit bit index instead.
        """
        hits = []
        with self._lock:
            for i, rule in enumerate(self.rules):
                if rule.kind not in _CORRUPT_KINDS or \
                        not fnmatch.fnmatchcase(op, rule.op):
                    continue
                self._counts[i] += 1
                n = self._counts[i]
                if rule.nth is not None:
                    hit = (n == rule.nth)
                else:
                    hit = self._rngs[i].random() < rule.rate
                if hit:
                    self.events.append((op, rule.kind, n))
                    # element pick drawn under the lock from the rule's
                    # stream: stays deterministic per (spec, seed, N)
                    hits.append((rule, n, self._rngs[i].randrange(2 ** 31)))
        if not hits:
            return array
        import numpy as np

        out = np.array(array, copy=True)
        flat = out.reshape(-1).view()
        for rule, n, pick in hits:
            self._note_injected(op, rule.kind, n)
            idx = pick % max(1, flat.size)
            if rule.kind == "nan":
                if np.issubdtype(out.dtype, np.floating):
                    flat[idx] = np.nan
                else:
                    flat[idx] = np.iinfo(out.dtype).max
            else:  # bitflip
                itemsize = out.dtype.itemsize
                bits = itemsize * 8
                if rule.param is not None and rule.nth is None:
                    bit = int(rule.param) % bits
                elif np.issubdtype(out.dtype, np.floating) and bits >= 16:
                    bit = bits - 2  # MSB of the exponent field
                else:
                    bit = bits - 1
                u = np.dtype("uint%d" % bits)
                word = flat[idx:idx + 1].view(u)
                word ^= u.type(1) << u.type(bit)
        return out

"""mxnet_tpu.faults — deterministic, seed-driven fault injection.

The robustness layer the distributed stack is hardened against
(docs/how_to/fault_tolerance.md).  Socket and file I/O sites across the
kvstore transport (``kvstore_server.py``), checkpoint writer
(``filesystem.atomic_write``), dist heartbeats, and the elastic
membership evictor (``kv.server.evict``) name themselves with dotted
operation strings and call :func:`fire` before touching the real
resource; an installed :class:`FaultPlan` then injects connection drops,
delays, torn writes, or process kills on a reproducible schedule.

Three ways to activate a plan:

* **In-process** (tests)::

      with mx.faults.inject("kv.client.*:drop=0.3", seed=7):
          train()

* **Whole process via env** — the contract ``tools/chaos_run.py`` and
  chaos tests use for subprocess workers/servers::

      MXNET_FAULTS_SPEC="kv.client.*:drop=0.3" MXNET_FAULTS_SEED=7 \\
          python train.py

* **Explicit**: ``mx.faults.install(FaultPlan(spec, seed))`` /
  ``mx.faults.uninstall()``.

When no plan is installed every hook is a single global-is-None check —
the production hot path pays nothing.
"""
from __future__ import annotations

import contextlib
import os
import threading
from typing import Optional

from .plan import (FaultPlan, InjectedConnectionError, InjectedIOError, Rule,
                   parse_spec)

__all__ = ["FaultPlan", "Rule", "InjectedConnectionError", "InjectedIOError",
           "parse_spec", "install", "uninstall", "active", "fire",
           "partial_fraction", "corrupt", "targets_corruption", "inject",
           "install_from_env"]

_plan: Optional[FaultPlan] = None
_lock = threading.Lock()


def install(plan: FaultPlan) -> FaultPlan:
    """Make ``plan`` the process-global active plan (replacing any)."""
    global _plan
    with _lock:
        _plan = plan
    return plan


def uninstall() -> None:
    global _plan
    with _lock:
        _plan = None


def active() -> Optional[FaultPlan]:
    return _plan


def fire(op: str) -> None:
    """Injection point: no-op without an active plan, else the plan may
    sleep, raise, or kill here.  Called from instrumented I/O sites."""
    p = _plan
    if p is not None:
        p.fire(op)


def partial_fraction(op: str) -> Optional[float]:
    """Torn-write poll for file writers (see FaultPlan.partial_fraction)."""
    p = _plan
    if p is None:
        return None
    return p.partial_fraction(op)


def corrupt(op: str, array):
    """Tensor-corruption poll for array sites (``guardian.grad``, ...):
    returns ``array`` untouched without an active plan, else whatever
    :meth:`FaultPlan.corrupt` decides (a corrupted copy when a
    ``nan``/``bitflip`` rule fires on this call)."""
    p = _plan
    if p is None:
        return array
    return p.corrupt(op, array)


def targets_corruption(op: str) -> bool:
    """True when the active plan has a corruption rule aimed at ``op``
    (pure predicate — no counters advance)."""
    p = _plan
    return p is not None and p.targets_corruption(op)


@contextlib.contextmanager
def inject(spec: str, seed: int = 0):
    """Scoped installation for tests: installs a fresh plan, yields it,
    restores whatever was active before."""
    prev = _plan
    plan = FaultPlan(spec, seed)
    install(plan)
    try:
        yield plan
    finally:
        with _lock:
            globals()["_plan"] = prev


def install_from_env() -> Optional[FaultPlan]:
    """Activate from ``MXNET_FAULTS_SPEC`` / ``MXNET_FAULTS_SEED`` (the
    subprocess contract).  No-op when the spec var is unset/empty or a
    plan is already installed explicitly."""
    spec = os.environ.get("MXNET_FAULTS_SPEC", "")
    if not spec or _plan is not None:
        return _plan
    seed = int(os.environ.get("MXNET_FAULTS_SEED", "0"))
    return install(FaultPlan(spec, seed))


# env activation happens at import: a worker launched with the env vars
# set is fault-injected from its very first wire op
install_from_env()

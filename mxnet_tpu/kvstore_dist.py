"""``dist_sync`` / ``dist_device_sync`` — multi-host synchronous data
parallelism over ``jax.distributed``.

TPU-native redesign of the reference's parameter-server sync path
(/root/reference/src/kvstore/kvstore_dist.h:28-318 worker client,
kvstore_dist_server.h:136-200 per-key accumulation until ``NumWorkers()``
pushes arrive).  There is no server here: every worker participates in a
collective sum (XLA collectives over the ``jax.distributed`` coordination
service — ICI/DCN on real pods), after which each worker applies the same
deterministic update to its replica.  That reproduces the server's sync-sum
semantics — pushed values for one key are summed across all workers before
the optimizer sees them — without a host round-trip.

Worker bring-up follows the reference's env-var contract
(/root/reference/tools/launch.py + dmlc tracker): ``DMLC_NUM_WORKER``,
``DMLC_WORKER_ID``, ``DMLC_PS_ROOT_URI``/``DMLC_PS_ROOT_PORT`` name the
coordinator (the scheduler's analogue).  ``tools/launch.py`` in this repo
sets them for local multi-process runs.

Create the kvstore before running device computations: JAX's distributed
runtime must initialize before the backends are first used.
"""
from __future__ import annotations

import logging
import os
from typing import Dict, Optional

from .base import MXNetError
from .kvstore import KVStore, _key_list, _val_list
from .ndarray import NDArray
from . import ndarray as nd

__all__ = ["DistSyncKVStore", "ensure_distributed_initialized"]

_initialized = False


def ensure_distributed_initialized():
    """Bring up ``jax.distributed`` from the DMLC env-var contract (no-op for
    single-worker runs or when already connected)."""
    global _initialized
    if _initialized:
        return
    num_workers = int(os.environ.get("DMLC_NUM_WORKER", "1"))
    if num_workers <= 1:
        _initialized = True
        return
    import jax

    addr = os.environ.get("DMLC_PS_ROOT_URI", "127.0.0.1")
    port = os.environ.get("DMLC_PS_ROOT_PORT", "9360")
    worker_id = int(os.environ.get("DMLC_WORKER_ID", "0"))
    try:
        jax.distributed.initialize(
            coordinator_address="%s:%s" % (addr, port),
            num_processes=num_workers, process_id=worker_id)
    except RuntimeError as e:
        if "already" in str(e).lower():
            logging.debug("jax.distributed already initialized: %s", e)
        else:
            raise MXNetError(
                "dist_sync bring-up failed (create the kvstore before any "
                "device computation; coordinator %s:%s): %s"
                % (addr, port, e))
    _initialized = True


class DistSyncKVStore(KVStore):
    """Synchronous multi-worker store: ``push`` sums values across ALL
    workers (collective allreduce), then the updater — installed identically
    on every worker by ``set_optimizer`` — applies the same update to each
    replica.  Equivalent to the reference server's merge-until-NumWorkers
    then update (kvstore_dist_server.h:164-200), minus the server."""

    def __init__(self, kv_type="dist_sync"):
        ensure_distributed_initialized()
        super().__init__(kv_type)
        self._start_heartbeat()

    # -- collective helpers ------------------------------------------------
    _cmesh = None
    _sum_fn = None

    def _collective_mesh(self):
        """1-axis mesh with ONE device per worker process — the lane the
        eager push()'s allreduce rides (a compiled XLA collective over
        ICI/DCN, not a host gather loop).  The fused Module path does not
        come through here at all: its psum is compiled into the train step
        over the full global mesh (module/executor_group.py)."""
        if DistSyncKVStore._cmesh is None:
            import jax
            import numpy as np
            from jax.sharding import Mesh

            per_proc = {}
            for d in jax.devices():
                per_proc.setdefault(d.process_index, d)
            devs = [per_proc[p] for p in sorted(per_proc)]
            DistSyncKVStore._cmesh = Mesh(np.asarray(devs), ("workers",))
        return DistSyncKVStore._cmesh

    def _allreduce_sum(self, arr):
        """Sum an array across worker processes as ONE compiled collective
        (device-side; replaces the reference's ZPush/server-merge round trip,
        kvstore_dist.h:211-228)."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        if jax.process_count() == 1:
            return arr
        mesh = self._collective_mesh()
        me = jax.process_index()
        local_dev = next(d for d in mesh.devices.flat
                         if d.process_index == me)
        v = jax.device_put(arr, local_dev)[None]
        sharding = NamedSharding(mesh, P("workers"))
        global_shape = (jax.process_count(),) + tuple(arr.shape)
        stacked = jax.make_array_from_single_device_arrays(
            global_shape, sharding, [v])
        if DistSyncKVStore._sum_fn is None:
            DistSyncKVStore._sum_fn = jax.jit(
                lambda a: a.sum(axis=0),
                out_shardings=NamedSharding(mesh, P()))
        out = DistSyncKVStore._sum_fn(stacked)
        return out.addressable_shards[0].data

    def _broadcast0(self, arr):
        import jax

        if jax.process_count() == 1:
            return arr
        from jax.experimental import multihost_utils

        return multihost_utils.broadcast_one_to_all(arr)

    # -- data plane --------------------------------------------------------
    def init(self, key, value):
        """Rank-0's value wins and is broadcast so every worker starts from
        identical parameters (the reference inits only from rank 0,
        kvstore_dist.h:64-82)."""
        keys, _ = _key_list(key)
        vals = _val_list(value, len(keys))
        for k, v in zip(keys, vals):
            if k in self._store:
                raise MXNetError("duplicate init of key %s" % str(k))
            src = v[0] if isinstance(v[0], NDArray) else nd.array(v[0])
            self._store[k] = NDArray(self._broadcast0(src._data), src.context)

    def push(self, key, value, priority=0):
        keys, _ = _key_list(key)
        vals = _val_list(value, len(keys))
        for k, vlist in zip(keys, vals):
            if k not in self._store:
                raise MXNetError("push to uninitialized key %s" % str(k))
            acc = vlist[0]._data
            for v in vlist[1:]:  # local device-group sum first
                acc = acc + v._data
            merged = NDArray(self._allreduce_sum(acc), vlist[0].context)
            if self._updater is not None:
                self._updater(k, merged, self._store[k])
            else:
                self._store[k]._set(merged._data)

    # -- control plane -----------------------------------------------------
    def _barrier(self):
        import jax

        if jax.process_count() > 1:
            from jax.experimental import multihost_utils

            multihost_utils.sync_global_devices("kvstore_barrier")

    # -- liveness ------------------------------------------------------
    _hb_thread = None

    @staticmethod
    def _coord_client():
        """The jax.distributed coordination-service client (the scheduler's
        key-value store — the Postoffice analogue), or None."""
        try:
            from jax._src import distributed

            return distributed.global_state.client
        except Exception:
            return None

    def _start_heartbeat(self):
        """Publish this worker's liveness into the coordination service so
        peers can count dead nodes (reference: ps-lite node heartbeats
        behind GetDeadNodes, kvstore_dist.h:151-160)."""
        import threading
        import time

        if DistSyncKVStore._hb_thread is not None:
            return
        client = self._coord_client()
        if client is None:
            return
        rank = self.rank
        interval = float(os.environ.get("MXNET_KVSTORE_HEARTBEAT_INTERVAL",
                                        "5"))
        seq = [0]

        def beat_once():
            from . import faults

            # publish a SEQUENCE NUMBER, not a wall-clock timestamp: hosts'
            # clocks skew, but a stale-vs-advancing counter is judged
            # entirely against the READER's monotonic clock
            faults.fire("kv.dist.heartbeat")
            seq[0] += 1
            client.key_value_set("mxtpu_hb/%d" % rank, str(seq[0]),
                                 allow_overwrite=True)

        def loop(stop):
            from . import faults

            while not stop.wait(interval):
                try:
                    beat_once()
                except (faults.InjectedConnectionError,
                        faults.InjectedIOError):
                    continue  # injected transient: miss this beat, go stale
                except Exception:
                    return

        try:
            beat_once()
        except TypeError:
            # older client signature without allow_overwrite: unsupported —
            # disable heartbeats
            return
        except Exception:
            return
        stop = threading.Event()
        t = threading.Thread(target=loop, args=(stop,), daemon=True)
        t.start()
        DistSyncKVStore._hb_thread = (t, stop)

    _hb_seen: Dict[int, tuple] = {}

    def _read_hb(self, client, r):
        try:
            return client.key_value_try_get("mxtpu_hb/%d" % r)
        except AttributeError:
            try:
                return client.blocking_key_value_get("mxtpu_hb/%d" % r, 1000)
            except Exception:
                return None
        except Exception:
            return None

    def get_num_dead_node(self, node_id=0, timeout=None):
        """Count workers whose heartbeat counter has stopped advancing for
        ``timeout`` seconds of the CALLER's monotonic clock (no cross-host
        wall-clock comparison, so clock skew cannot fabricate or mask
        deaths).  The first observation of a rank establishes its baseline,
        so detection needs two calls at least ``timeout`` apart — collectives
        on this runtime additionally fail fast on lost peers.  Reference:
        kvstore_dist.h:151-160.  ``timeout=None`` takes the shared
        ``MXNET_KVSTORE_HEARTBEAT_TIMEOUT`` default so every liveness
        consumer agrees on who is dead."""
        import time

        import jax

        if timeout is None:
            from .kvstore_server import _hb_timeout_default

            timeout = _hb_timeout_default()

        if jax.process_count() == 1:
            return 0
        client = self._coord_client()
        if client is None:
            return 0
        dead = 0
        now = time.monotonic()
        for r in range(self.num_workers):
            if r == self.rank:
                continue
            raw = self._read_hb(client, r)
            if raw is None:
                continue  # never heartbeated: not tracked (launcher's job)
            prev = DistSyncKVStore._hb_seen.get(r)
            if prev is None or prev[0] != raw:
                DistSyncKVStore._hb_seen[r] = (raw, now)
                continue
            if now - prev[1] > timeout:
                dead += 1
        return dead

"""``dist_sync`` / ``dist_device_sync`` — multi-host synchronous data
parallelism over ``jax.distributed``.

TPU-native redesign of the reference's parameter-server sync path
(/root/reference/src/kvstore/kvstore_dist.h:28-318 worker client,
kvstore_dist_server.h:136-200 per-key accumulation until ``NumWorkers()``
pushes arrive).  There is no server here: every worker participates in a
collective sum (XLA collectives over the ``jax.distributed`` coordination
service — ICI/DCN on real pods), after which each worker applies the same
deterministic update to its replica.  That reproduces the server's sync-sum
semantics — pushed values for one key are summed across all workers before
the optimizer sees them — without a host round-trip.

Worker bring-up follows the reference's env-var contract
(/root/reference/tools/launch.py + dmlc tracker): ``DMLC_NUM_WORKER``,
``DMLC_WORKER_ID``, ``DMLC_PS_ROOT_URI``/``DMLC_PS_ROOT_PORT`` name the
coordinator (the scheduler's analogue).  ``tools/launch.py`` in this repo
sets them for local multi-process runs.

Create the kvstore before running device computations: JAX's distributed
runtime must initialize before the backends are first used.
"""
from __future__ import annotations

import logging
import os
from typing import Dict, Optional

from .base import MXNetError
from .kvstore import KVStore, _key_list, _val_list
from .ndarray import NDArray
from . import ndarray as nd

__all__ = ["DistSyncKVStore", "ensure_distributed_initialized"]

_initialized = False


def ensure_distributed_initialized():
    """Bring up ``jax.distributed`` from the DMLC env-var contract (no-op for
    single-worker runs or when already connected)."""
    global _initialized
    if _initialized:
        return
    num_workers = int(os.environ.get("DMLC_NUM_WORKER", "1"))
    if num_workers <= 1:
        _initialized = True
        return
    import jax

    addr = os.environ.get("DMLC_PS_ROOT_URI", "127.0.0.1")
    port = os.environ.get("DMLC_PS_ROOT_PORT", "9360")
    worker_id = int(os.environ.get("DMLC_WORKER_ID", "0"))
    try:
        jax.distributed.initialize(
            coordinator_address="%s:%s" % (addr, port),
            num_processes=num_workers, process_id=worker_id)
    except RuntimeError as e:
        if "already" in str(e).lower():
            logging.debug("jax.distributed already initialized: %s", e)
        else:
            raise MXNetError(
                "dist_sync bring-up failed (create the kvstore before any "
                "device computation; coordinator %s:%s): %s"
                % (addr, port, e))
    _initialized = True


class DistSyncKVStore(KVStore):
    """Synchronous multi-worker store: ``push`` sums values across ALL
    workers (collective allreduce), then the updater — installed identically
    on every worker by ``set_optimizer`` — applies the same update to each
    replica.  Equivalent to the reference server's merge-until-NumWorkers
    then update (kvstore_dist_server.h:164-200), minus the server."""

    def __init__(self, kv_type="dist_sync"):
        ensure_distributed_initialized()
        super().__init__(kv_type)

    # -- collective helpers ------------------------------------------------
    _cmesh = None
    _sum_fn = None

    def _collective_mesh(self):
        """1-axis mesh with ONE device per worker process — the lane the
        eager push()'s allreduce rides (a compiled XLA collective over
        ICI/DCN, not a host gather loop).  The fused Module path does not
        come through here at all: its psum is compiled into the train step
        over the full global mesh (module/executor_group.py)."""
        if DistSyncKVStore._cmesh is None:
            import jax
            import numpy as np
            from jax.sharding import Mesh

            per_proc = {}
            for d in jax.devices():
                per_proc.setdefault(d.process_index, d)
            devs = [per_proc[p] for p in sorted(per_proc)]
            DistSyncKVStore._cmesh = Mesh(np.asarray(devs), ("workers",))
        return DistSyncKVStore._cmesh

    def _allreduce_sum(self, arr):
        """Sum an array across worker processes as ONE compiled collective
        (device-side; replaces the reference's ZPush/server-merge round trip,
        kvstore_dist.h:211-228)."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        if jax.process_count() == 1:
            return arr
        mesh = self._collective_mesh()
        me = jax.process_index()
        local_dev = next(d for d in mesh.devices.flat
                         if d.process_index == me)
        v = jax.device_put(arr, local_dev)[None]
        sharding = NamedSharding(mesh, P("workers"))
        global_shape = (jax.process_count(),) + tuple(arr.shape)
        stacked = jax.make_array_from_single_device_arrays(
            global_shape, sharding, [v])
        if DistSyncKVStore._sum_fn is None:
            DistSyncKVStore._sum_fn = jax.jit(
                lambda a: a.sum(axis=0),
                out_shardings=NamedSharding(mesh, P()))
        out = DistSyncKVStore._sum_fn(stacked)
        return out.addressable_shards[0].data

    def _broadcast0(self, arr):
        import jax

        if jax.process_count() == 1:
            return arr
        from jax.experimental import multihost_utils

        return multihost_utils.broadcast_one_to_all(arr)

    # -- data plane --------------------------------------------------------
    def init(self, key, value):
        """Rank-0's value wins and is broadcast so every worker starts from
        identical parameters (the reference inits only from rank 0,
        kvstore_dist.h:64-82)."""
        keys, _ = _key_list(key)
        vals = _val_list(value, len(keys))
        for k, v in zip(keys, vals):
            if k in self._store:
                raise MXNetError("duplicate init of key %s" % str(k))
            src = v[0] if isinstance(v[0], NDArray) else nd.array(v[0])
            self._store[k] = NDArray(self._broadcast0(src._data), src.context)

    def push(self, key, value, priority=0):
        keys, _ = _key_list(key)
        vals = _val_list(value, len(keys))
        for k, vlist in zip(keys, vals):
            if k not in self._store:
                raise MXNetError("push to uninitialized key %s" % str(k))
            acc = vlist[0]._data
            for v in vlist[1:]:  # local device-group sum first
                acc = acc + v._data
            merged = NDArray(self._allreduce_sum(acc), vlist[0].context)
            if self._updater is not None:
                self._updater(k, merged, self._store[k])
            else:
                self._store[k]._set(merged._data)

    # -- control plane -----------------------------------------------------
    def _barrier(self):
        import jax

        if jax.process_count() > 1:
            from jax.experimental import multihost_utils

            multihost_utils.sync_global_devices("kvstore_barrier")

    def get_num_dead_node(self, node_id=0, timeout=60):
        """The jax.distributed runtime fails fast on lost peers (the
        coordination service aborts collectives), so a reachable store
        implies zero dead nodes — the reference polls ps-lite instead
        (kvstore_dist.h:151-160)."""
        return 0

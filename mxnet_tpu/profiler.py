"""Profiler (parity: /root/reference/python/mxnet/profiler.py:10-38 over
src/engine/profiler.{h,cc}).

The reference stamps per-op micros and dumps Chrome trace JSON
(profiler.h:88-109).  Here the heavy lifting is ``jax.profiler`` (XPlane →
TensorBoard/perfetto, the richer superset of a chrome trace); this module
keeps the reference's API shape and ALSO emits a minimal chrome-trace JSON
of python-level step events so ``dump_profile`` output remains loadable in
chrome://tracing.
"""
from __future__ import annotations

import json
import os
import time
import threading
from typing import List, Optional

from .base import env, register_env

__all__ = ["profiler_set_config", "profiler_set_state", "dump_profile",
           "pause", "resume", "Frame", "trace_tid"]

_state = {"mode": "symbolic", "filename": "profile.json", "running": False,
          "events": [], "tnames": {}, "jax_trace_dir": None,
          "lock": threading.Lock()}

# Synthetic per-thread track ids.  ``threading.get_ident()`` values are
# recycled by the OS the moment a thread exits, so in a long trace a fresh
# worker (serving batcher, router pool, HTTP handler) can inherit a dead
# comm-engine worker's ident and silently rename its track in the merged
# thread_name metadata.  Handing every thread a monotonically increasing id
# on first use keeps exactly one track per actual thread for the lifetime
# of the process.
_tid_local = threading.local()
_tid_next = [1]


def trace_tid() -> int:
    """This thread's stable trace-track id (never reused across threads)."""
    tid = getattr(_tid_local, "tid", None)
    if tid is None:
        with _state["lock"]:
            tid = _tid_next[0]
            _tid_next[0] += 1
        _tid_local.tid = tid
    return tid

# external span sink installed by mxnet_tpu.telemetry.tracer: when set,
# Frame/record_event deliver each event (plus the recording thread's name)
# there too, so telemetry captures spans without the profiler run state
_sink = None


def _set_sink(fn):
    global _sink
    _sink = fn


def _snapshot_events():
    """Consistent copy of (events, thread-name map) for trace mergers."""
    with _state["lock"]:
        return list(_state["events"]), dict(_state["tnames"])


def profiler_set_config(mode="symbolic", filename="profile.json"):
    """Set profiler config (reference profiler.py:10): mode in
    {'symbolic', 'all'}; filename receives the chrome trace on dump."""
    if mode not in ("symbolic", "all"):
        raise ValueError("profiler mode must be 'symbolic' or 'all'")
    _state["mode"] = mode
    _state["filename"] = filename


def profiler_set_state(state="stop"):
    """Start/stop profiling (reference profiler.py:22).  'run' also starts a
    jax.profiler trace capturing device (TPU) activity."""
    if state not in ("run", "stop"):
        raise ValueError("profiler state must be 'run' or 'stop'")
    import jax

    if state == "run" and not _state["running"]:
        # mutate under the lock: a Frame closing on another thread must
        # never append into the buffer being replaced
        with _state["lock"]:
            _state["running"] = True
            _state["events"] = []
            _state["tnames"] = {}
        trace_dir = os.path.splitext(_state["filename"])[0] + "_xplane"
        try:
            jax.profiler.start_trace(trace_dir)
            _state["jax_trace_dir"] = trace_dir
        except Exception:
            _state["jax_trace_dir"] = None
    elif state == "stop" and _state["running"]:
        with _state["lock"]:
            _state["running"] = False
        if _state["jax_trace_dir"]:
            try:
                jax.profiler.stop_trace()
            except Exception:
                pass


def pause():
    with _state["lock"]:
        _state["running"] = False


def resume():
    with _state["lock"]:
        _state["running"] = True


class Frame:
    """Context manager recording one named span into the chrome trace (the
    python-level analogue of OprExecStat, profiler.h:20-42)."""

    def __init__(self, name, category="python", args=None):
        self.name = name
        self.category = category
        # optional chrome-trace args payload (e.g. the distributed trace
        # id a kvstore RPC envelope carried); read at exit so callers may
        # attach fields while the span is open
        self.args = args

    def __enter__(self):
        self._t0 = time.perf_counter_ns() // 1000
        return self

    def __exit__(self, *exc):
        sink = _sink
        if _state["running"] or sink is not None:
            t1 = time.perf_counter_ns() // 1000
            # per-thread id: spans from worker threads (comm engine,
            # serving batcher, kvstore handlers) land on their own tracks
            tid = trace_tid()
            ev = {"name": self.name, "cat": self.category, "ph": "X",
                  "ts": self._t0, "dur": t1 - self._t0, "pid": 0, "tid": tid}
            if self.args:
                ev["args"] = dict(self.args)
            tname = threading.current_thread().name
            if _state["running"]:
                with _state["lock"]:
                    _state["events"].append(ev)
                    _state["tnames"][tid] = tname
            if sink is not None:
                sink(ev, tname)


def record_event(name, t0_us, dur_us, category="op"):
    sink = _sink
    if _state["running"] or sink is not None:
        tid = trace_tid()
        ev = {"name": name, "cat": category, "ph": "X", "ts": t0_us,
              "dur": dur_us, "pid": 0, "tid": tid}
        tname = threading.current_thread().name
        if _state["running"]:
            with _state["lock"]:
                _state["events"].append(ev)
                _state["tnames"][tid] = tname
        if sink is not None:
            sink(ev, tname)


def dump_profile():
    """Write the chrome trace file (reference profiler.py:34 → DumpProfile,
    profiler.h:88).  Safe to call mid-run: pending events are flushed
    under ``_state["lock"]`` whether or not ``profiler_set_state("stop")``
    ever ran."""
    with _state["lock"]:
        payload = {"traceEvents": list(_state["events"]),
                   "displayTimeUnit": "ms"}
    with open(_state["filename"], "w") as f:
        json.dump(payload, f)
    return _state["filename"]


register_env("MXNET_PROFILER_AUTOSTART", 0, int, "Start profiler at import.")
if env("MXNET_PROFILER_AUTOSTART", 0, int):
    profiler_set_state("run")

"""Device-mesh construction and sharding helpers.

Replaces the reference's device bookkeeping (Context lists handed to
DataParallelExecutorGroup, kvstore device groups — src/kvstore/comm.h:61-360)
with one named mesh: axes are *roles* ('data', 'model', 'pipe', 'seq',
'expert'), and placement is expressed as PartitionSpecs over those roles
rather than explicit copies.
"""
from __future__ import annotations

import threading
from typing import Dict, Optional, Sequence

import numpy as np

#: canonical axis order — data-parallel outermost (maps to the slower/outer
#: ICI dimensions last in the mesh tuple so model/seq collectives ride the
#: fastest links; jax device order within a host is contiguous)
CANONICAL_AXES = ("data", "pipe", "expert", "model", "seq")


class MeshConfig:
    """Declarative mesh spec: axis name → size. Size -1 means 'absorb the
    remaining devices' (at most one axis may be -1)."""

    def __init__(self, **axes: int):
        self.axes = dict(axes)

    def resolve(self, n_devices: int) -> Dict[str, int]:
        axes = dict(self.axes)
        unknown = [k for k, v in axes.items() if v == -1]
        if len(unknown) > 1:
            raise ValueError("at most one mesh axis may be -1")
        known = int(np.prod([v for v in axes.values() if v != -1])) or 1
        if unknown:
            if n_devices % known:
                raise ValueError(
                    "cannot infer axis %r: %d devices not divisible by %d"
                    % (unknown[0], n_devices, known))
            axes[unknown[0]] = n_devices // known
        total = int(np.prod(list(axes.values()))) if axes else 1
        if total != n_devices:
            raise ValueError(
                "mesh %r uses %d devices but %d are available"
                % (axes, total, n_devices))
        return axes


def make_mesh(axes: Optional[Dict[str, int]] = None, devices=None):
    """Build a named Mesh. ``axes`` maps axis name → size (-1 = remaining);
    default is a pure data-parallel mesh over all devices."""
    import jax
    from jax.sharding import Mesh

    if devices is None:
        devices = jax.devices()
    devices = list(devices)
    if not axes:
        axes = {"data": len(devices)}
    resolved = MeshConfig(**axes).resolve(len(devices))
    # order axes canonically so collectives on inner axes stay intra-group
    names = sorted(resolved, key=lambda a: (
        CANONICAL_AXES.index(a) if a in CANONICAL_AXES else len(CANONICAL_AXES)))
    shape = [resolved[a] for a in names]
    arr = np.array(devices).reshape(shape)
    return Mesh(arr, tuple(names))


def data_parallel_mesh(n: Optional[int] = None):
    import jax

    devs = jax.devices()
    if n is not None:
        devs = devs[:n]
    return make_mesh({"data": len(devs)}, devs)


def shard(x, mesh, spec):
    """Place ``x`` on ``mesh`` with PartitionSpec ``spec`` (tuple of axis
    names / None, or an existing PartitionSpec)."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec

    if not isinstance(spec, PartitionSpec):
        spec = PartitionSpec(*spec)
    return jax.device_put(x, NamedSharding(mesh, spec))


def replicate(x, mesh):
    from jax.sharding import PartitionSpec

    return shard(x, mesh, PartitionSpec())


_local = threading.local()


def current_mesh():
    """The ambient mesh installed by ``set_current_mesh`` (None if unset)."""
    return getattr(_local, "mesh", None)


class set_current_mesh:
    """Context manager installing an ambient mesh, so higher layers
    (executor sharding, kvstore facade) can pick it up without plumbing."""

    def __init__(self, mesh):
        self.mesh = mesh
        self._prev = None

    def __enter__(self):
        self._prev = getattr(_local, "mesh", None)
        _local.mesh = self.mesh
        return self.mesh

    def __exit__(self, *exc):
        _local.mesh = self._prev
        return False

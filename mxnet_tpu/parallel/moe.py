"""Expert parallelism — a mixture-of-experts FFN with the expert
dimension sharded over a mesh axis (beyond-reference capability; the
2017 reference has no conditional computation at all).

Exact einsum-dispatch formulation (no capacity dropping): every token's
top-k expert outputs are combined with renormalized gate weights. Experts
live sharded — each device holds E/n expert FFNs and computes them for
the full token stream; the weighted combine is a ``psum`` over the expert
axis, which XLA lowers to an ICI all-reduce. This is the
communication-light exact scheme (tokens replicated, experts sharded);
capacity-based all-to-all dispatch is a drop-in change of the inner
function when token counts outgrow replication.
"""
from __future__ import annotations

import functools


def _gate_combine(x, gate_w, top_k):
    """combine[b, s, E]: renormalized top-k gate weight of each expert for
    each token — the single routing implementation shared by the sharded
    path and the dense oracle."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    logits = jnp.einsum("bsd,de->bse", x, gate_w)
    weights, assign = lax.top_k(jax.nn.softmax(logits, axis=-1), top_k)
    weights = weights / jnp.sum(weights, axis=-1, keepdims=True)
    return jnp.sum(
        jax.nn.one_hot(assign, gate_w.shape[-1], dtype=x.dtype)
        * weights[..., None], axis=2)


def moe_ffn_reference(x, gate_w, w1, w2, top_k=1, act=None):
    """Dense single-device oracle. x: [b, s, d]; gate_w: [d, E];
    w1: [E, d, h]; w2: [E, h, d]."""
    import jax
    import jax.numpy as jnp

    act = act or jax.nn.gelu
    combine = _gate_combine(x, gate_w, top_k)
    hidden = act(jnp.einsum("bsd,edh->besh", x, w1))
    out = jnp.einsum("besh,ehd->besd", hidden, w2)
    return jnp.einsum("bse,besd->bsd", combine, out)


def _moe_inner(x, gate_w, w1, w2, *, axis, top_k, act):
    import jax
    import jax.numpy as jnp
    from jax import lax

    e_local = w1.shape[0]
    idx = lax.axis_index(axis)
    # routing is computed from the replicated gate everywhere (identical
    # on all shards; avoids a broadcast)
    combine = _gate_combine(x, gate_w, top_k)             # [b, s, E]
    local = lax.dynamic_slice_in_dim(combine, idx * e_local, e_local,
                                     axis=2)              # [b, s, E/n]
    hidden = act(jnp.einsum("bsd,edh->besh", x, w1))
    out = jnp.einsum("besh,ehd->besd", hidden, w2)
    partial = jnp.einsum("bse,besd->bsd", local, out)
    return lax.psum(partial, axis)


def moe_ffn(x, gate_w, w1, w2, mesh, axis: str = "expert", top_k: int = 1,
            act=None):
    """Expert-parallel MoE FFN. ``w1``/``w2`` are sharded on their expert
    dimension over ``axis`` of ``mesh``; ``x``/``gate_w`` replicated.
    Exact — matches ``moe_ffn_reference`` to float tolerance."""
    import jax
    from jax.sharding import PartitionSpec as P

    from ._compat import shard_map

    act = act or jax.nn.gelu
    n = mesh.shape[axis]
    if gate_w.shape[-1] != w1.shape[0]:
        raise ValueError(
            "gate has %d experts but w1 has %d"
            % (gate_w.shape[-1], w1.shape[0]))
    if w1.shape[0] % n:
        raise ValueError(
            "experts (%d) must be divisible by mesh axis %r size %d"
            % (w1.shape[0], axis, n))
    inner = functools.partial(_moe_inner, axis=axis, top_k=top_k, act=act)
    fn = shard_map(
        inner, mesh=mesh,
        in_specs=(P(), P(), P(axis), P(axis)),
        out_specs=P())
    return fn(x, gate_w, w1, w2)

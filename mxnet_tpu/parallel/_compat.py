"""jax-version shims for the parallel subpackage.

Newer jax promotes ``shard_map`` to the top level, renames its
replication checker ``check_rep`` -> ``check_vma``, and types
manual-mode values with varying-axis annotations (``lax.pcast``).
jax < 0.5 has none of these; map onto what exists so the same SPMD
code traces on both.
"""


def shard_map(f, *, mesh, in_specs, out_specs, check_vma=None):
    try:
        from jax import shard_map as _sm
    except ImportError:
        from jax.experimental.shard_map import shard_map as _sm

        # old shard_map's rep checker predates varying-axis types and
        # rejects mixed-rep scan carries the new checker accepts; the
        # pcast annotations that would fix them don't exist here
        return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                   check_rep=False)
    kw = {} if check_vma is None else {"check_vma": check_vma}
    return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kw)


def pcast_varying(x, axes):
    from jax import lax

    if hasattr(lax, "pcast"):
        return lax.pcast(x, axes, to="varying")
    return x  # no varying-axis types on old jax; nothing to annotate

"""Collective-permute pipeline parallelism over a 'pipe' mesh axis.

TPU-native form of the reference's manual model parallelism: where the
reference pins LSTM layers to GPUs and splices ``_CrossDeviceCopy`` nodes at
the boundaries (src/executor/graph_executor.cc:230-320,
example/model-parallel-lstm/lstm.py:142-205), here every device holds one
stage's parameters (stacked and sharded over 'pipe') and microbatch
activations stream stage-to-stage with ``lax.ppermute`` — the GPipe schedule
compiled into one SPMD program.
"""
from __future__ import annotations

import functools


def _pipeline_inner(params, xs, *, axis, n_stages, n_micro, stage_fn):
    import jax.numpy as jnp
    from jax import lax

    stage = lax.axis_index(axis)
    # local params arrive with a leading stage axis of length 1
    local_params = _tree_squeeze(params)
    n_steps = n_micro + n_stages - 1
    micro_shape = xs.shape[1:]
    # initial carries must be typed varying over the pipe axis (shard_map
    # VMA typing — the loop outputs depend on stage-varying params)
    from ._compat import pcast_varying

    state0 = pcast_varying(jnp.zeros(micro_shape, xs.dtype), (axis,))
    out0 = pcast_varying(jnp.zeros((n_micro,) + micro_shape, xs.dtype),
                         (axis,))
    fwd_perm = [(j, j + 1) for j in range(n_stages - 1)]

    def step(carry, t):
        state, outs = carry
        feed = xs[jnp.minimum(t, n_micro - 1)]
        inp = jnp.where(stage == 0, feed, state)
        out = stage_fn(local_params, inp)
        # last stage: record finished microbatch t-(n_stages-1)
        done_idx = t - (n_stages - 1)
        record = jnp.logical_and(stage == n_stages - 1, done_idx >= 0)
        idx = jnp.maximum(done_idx, 0)
        outs = jnp.where(
            record,
            outs.at[idx].set(out),
            outs)
        state = lax.ppermute(out, axis, fwd_perm)
        return (state, outs), None

    (_, outs), _ = lax.scan(step, (state0, out0), jnp.arange(n_steps))
    # outputs live only on the last stage; zero elsewhere then psum to
    # replicate them across the pipe axis
    outs = jnp.where(stage == n_stages - 1, outs, jnp.zeros_like(outs))
    return lax.psum(outs, axis)


def _tree_squeeze(params):
    import jax

    return jax.tree_util.tree_map(lambda p: p[0], params)


def pipeline_spmd(stage_fn, stage_params, x, mesh, axis: str = "pipe",
                  n_microbatches: int = None):
    """Run ``n_stages`` homogeneous stages as a pipeline over ``axis``.

    ``stage_fn(params_i, act) -> act`` must preserve the activation shape.
    ``stage_params``: pytree whose leaves have leading dim n_stages (sharded
    over ``axis``). ``x``: [batch, ...] global input; split into
    ``n_microbatches`` along batch. Returns [batch, ...] outputs (replicated
    over ``axis``)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from ._compat import shard_map

    n_stages = mesh.shape[axis]
    if n_microbatches is None:
        n_microbatches = n_stages
    batch = x.shape[0]
    if batch % n_microbatches:
        raise ValueError("batch %d not divisible into %d microbatches"
                         % (batch, n_microbatches))
    xs = jnp.reshape(x, (n_microbatches, batch // n_microbatches) + x.shape[1:])

    param_specs = jax.tree_util.tree_map(
        lambda _: P(axis), stage_params)
    inner = functools.partial(_pipeline_inner, axis=axis, n_stages=n_stages,
                              n_micro=n_microbatches, stage_fn=stage_fn)
    fn = shard_map(inner, mesh=mesh,
                   in_specs=(param_specs, P()), out_specs=P())
    outs = fn(stage_params, xs)
    return jnp.reshape(outs, (batch,) + x.shape[1:])

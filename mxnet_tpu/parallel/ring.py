"""Sequence-parallel attention over the device mesh — the long-context
engine (new capability vs the reference, which only had bucketing for long
sequences; SURVEY.md §5.7).

Three schemes, all exact (not approximations of softmax attention):

* ``ring_attention`` — K/V blocks rotate around the mesh ring with
  ``lax.ppermute`` while each device's Q block accumulates the softmax
  online (the numerically-stable m/l running max/denominator recurrence).
  Communication overlaps compute; memory per device is O(seq/n).
* ``ring_flash_attention`` — same ring, but the per-block compute is the
  Pallas flash kernel (ops/attention.py) forward AND backward, with a
  custom ring-level vjp (dk/dv ride the ring with their blocks). The
  end-to-end long-context training path: VMEM-streamed blocks locally,
  O(seq/n) HBM per device globally.
* ``ulysses_attention`` — ``lax.all_to_all`` reshards from sequence-sharded
  to head-sharded, runs dense local attention, then reshards back. Cheaper
  at moderate sequence lengths when heads >= mesh axis size.

Tensor convention: [batch, seq, heads, head_dim], sequence sharded on
``axis`` (default 'seq').
"""
from __future__ import annotations

import functools
from typing import Optional

import numpy as np

_NEG = -1e30


def local_attention(q, k, v, causal: bool = False,
                    scale: Optional[float] = None):
    """Dense single-device softmax attention — the oracle and the inner
    kernel for ulysses. [b, s, h, d] in/out."""
    import jax.numpy as jnp

    if scale is None:
        scale = 1.0 / np.sqrt(q.shape[-1])
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    if causal:
        sq, sk = q.shape[1], k.shape[1]
        mask = jnp.arange(sq)[:, None] >= jnp.arange(sk)[None, :]
        s = jnp.where(mask[None, None], s, _NEG)
    p = jnp.exp(s - s.max(-1, keepdims=True))
    p = p / p.sum(-1, keepdims=True)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v).astype(q.dtype)


def _ring_inner(q, k, v, *, axis, vary_axes, n_shards, causal, scale):
    import jax.numpy as jnp
    from jax import lax

    idx = lax.axis_index(axis)
    b, sq, h, d = q.shape
    sk = k.shape[1]
    q_pos = idx * sq + jnp.arange(sq)
    perm = [(j, (j + 1) % n_shards) for j in range(n_shards)]

    # initial accumulators must carry the same varying-axis type as the
    # loop outputs (shard_map VMA typing)
    from ._compat import pcast_varying

    def _vary(x):
        return pcast_varying(x, vary_axes)

    o0 = _vary(jnp.zeros((b, sq, h, d), jnp.float32))
    m0 = _vary(jnp.full((b, h, sq), _NEG, jnp.float32))
    l0 = _vary(jnp.zeros((b, h, sq), jnp.float32))

    def step(carry, t):
        o, m, l, k_blk, v_blk = carry
        # after t right-rotations this device holds block (idx - t) mod n
        k_idx = jnp.mod(idx - t, n_shards)
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k_blk).astype(jnp.float32)
        s = s * scale
        if causal:
            k_pos = k_idx * sk + jnp.arange(sk)
            mask = q_pos[:, None] >= k_pos[None, :]
            s = jnp.where(mask[None, None], s, _NEG)
        m_new = jnp.maximum(m, s.max(-1))
        p = jnp.exp(s - m_new[..., None])
        corr = jnp.exp(m - m_new)  # [b, h, q]
        l = l * corr + p.sum(-1)
        o = (o * corr.transpose(0, 2, 1)[..., None] +
             jnp.einsum("bhqk,bkhd->bqhd", p,
                        v_blk.astype(jnp.float32)))
        k_blk = lax.ppermute(k_blk, axis, perm)
        v_blk = lax.ppermute(v_blk, axis, perm)
        return (o, m_new, l, k_blk, v_blk), None

    (o, m, l, _, _), _ = lax.scan(
        step, (o0, m0, l0, k, v), jnp.arange(n_shards))
    out = o / l.transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


def ring_attention(q, k, v, mesh, axis: str = "seq",
                   batch_axis: Optional[str] = None, causal: bool = False,
                   scale: Optional[float] = None):
    """Exact attention with the sequence dimension sharded over ``axis`` of
    ``mesh``; K/V ride the ring via ppermute (ICI neighbours on TPU).

    q, k, v: [batch, seq, heads, head_dim] global arrays (sequence may be
    sharded on ``axis``; batch optionally on ``batch_axis``)."""
    import jax
    from jax.sharding import PartitionSpec as P

    from ._compat import shard_map

    if scale is None:
        scale = 1.0 / np.sqrt(q.shape[-1])
    n_shards = mesh.shape[axis]
    spec = P(batch_axis, axis, None, None)
    vary_axes = (axis,) + ((batch_axis,) if batch_axis else ())
    inner = functools.partial(_ring_inner, axis=axis, vary_axes=vary_axes,
                              n_shards=n_shards, causal=causal, scale=scale)
    fn = shard_map(inner, mesh=mesh, in_specs=(spec, spec, spec),
                   out_specs=spec)
    return fn(q, k, v)


def _merge_blocks(o_a, lse_a, o_b, lse_b):
    """Numerically-stable merge of two flash partial results.
    o: [b, sq, h, d] f32 (normalized), lse: [b*h, sq] f32."""
    import jax.numpy as jnp

    lse_new = jnp.logaddexp(lse_a, lse_b)
    b, sq, h, d = o_a.shape

    def w(lse):
        return jnp.exp(lse - lse_new).reshape(b, h, sq) \
            .transpose(0, 2, 1)[..., None]

    return o_a * w(lse_a) + o_b * w(lse_b), lse_new


def _ring_flash_fwd(q, k, v, *, axis, vary_axes, n_shards, causal, scale,
                    block_q, block_k, interpret):
    import jax.numpy as jnp
    from jax import lax

    from ..ops.attention import _flash_forward

    idx = lax.axis_index(axis)
    b, sq, h, d = q.shape
    perm = [(j, (j + 1) % n_shards) for j in range(n_shards)]

    from ._compat import pcast_varying

    def _vary(x):
        return pcast_varying(x, vary_axes)

    o0 = _vary(jnp.zeros((b, sq, h, d), jnp.float32))
    lse0 = _vary(jnp.full((b * h, sq), _NEG, jnp.float32))

    def step(carry, t):
        o, lse, k_blk, v_blk = carry
        k_idx = jnp.mod(idx - t, n_shards)

        def blk_diag(_):
            return _flash_forward(q, k_blk, v_blk, True, scale, block_q,
                                  block_k, interpret)

        def blk_full(_):
            return _flash_forward(q, k_blk, v_blk, False, scale, block_q,
                                  block_k, interpret)

        def blk_skip(_):
            # constants must carry the same varying-axis type as the other
            # switch branches (check_vma on TPU rejects a mismatch)
            return (_vary(jnp.zeros((b, sq, h, d), q.dtype)),
                    _vary(jnp.full((b * h, sq), _NEG, jnp.float32)))

        if causal:
            branch = jnp.where(k_idx == idx, 0,
                               jnp.where(k_idx < idx, 1, 2))
            o_b, lse_b = lax.switch(branch, [blk_diag, blk_full, blk_skip],
                                    None)
        else:
            o_b, lse_b = blk_full(None)
        o, lse = _merge_blocks(o, lse, o_b.astype(jnp.float32), lse_b)
        k_blk = lax.ppermute(k_blk, axis, perm)
        v_blk = lax.ppermute(v_blk, axis, perm)
        return (o, lse, k_blk, v_blk), None

    (o, lse, _, _), _ = lax.scan(step, (o0, lse0, k, v),
                                 jnp.arange(n_shards))
    return o.astype(q.dtype), lse


def _ring_flash_bwd(q, k, v, o, lse, do, *, axis, vary_axes, n_shards,
                    causal, scale, block_q, block_k, interpret):
    import jax.numpy as jnp
    from jax import lax

    from ..ops.attention import _flash_backward, _flash_bwd_precompute

    idx = lax.axis_index(axis)
    b, sq, h, d = q.shape
    perm = [(j, (j + 1) % n_shards) for j in range(n_shards)]

    from ._compat import pcast_varying

    def _vary(x):
        return pcast_varying(x, vary_axes)

    dq0 = _vary(jnp.zeros((b, sq, h, d), jnp.float32))
    dkv0 = _vary(jnp.zeros((b, sq, h, d), jnp.float32))
    # q/dO layouts, lse and delta do not change across ring steps —
    # compute once, not per rotated block
    pre = _flash_bwd_precompute(q, o, lse, do)

    def step(carry, t):
        dq, k_blk, v_blk, dk_blk, dv_blk = carry
        k_idx = jnp.mod(idx - t, n_shards)

        def go_diag(_):
            return _flash_backward(q, k_blk, v_blk, o, lse, do, True,
                                   scale, block_q, block_k, interpret,
                                   pre=pre)

        def go_full(_):
            return _flash_backward(q, k_blk, v_blk, o, lse, do, False,
                                   scale, block_q, block_k, interpret,
                                   pre=pre)

        def go_skip(_):
            # zeros_like tracks the compute branches' shape AND dtype
            # (dq/dk/dv come back in q/k/v dtype; lax.switch requires
            # identical branch signatures for mixed-precision q vs k/v).
            # No _vary: zeros_like inherits the operand's varying type,
            # and pcast varying->varying is rejected.
            return (jnp.zeros_like(q), jnp.zeros_like(k),
                    jnp.zeros_like(v))

        if causal:
            branch = jnp.where(k_idx == idx, 0,
                               jnp.where(k_idx < idx, 1, 2))
            dq_c, dk_c, dv_c = lax.switch(
                branch, [go_diag, go_full, go_skip], None)
        else:
            dq_c, dk_c, dv_c = go_full(None)
        dq = dq + dq_c.astype(jnp.float32)
        dk_blk = dk_blk + dk_c.astype(jnp.float32)
        dv_blk = dv_blk + dv_c.astype(jnp.float32)
        # dk/dv travel WITH their k/v block: after the full cycle each
        # block's gradient is home with every device's contribution
        k_blk = lax.ppermute(k_blk, axis, perm)
        v_blk = lax.ppermute(v_blk, axis, perm)
        dk_blk = lax.ppermute(dk_blk, axis, perm)
        dv_blk = lax.ppermute(dv_blk, axis, perm)
        return (dq, k_blk, v_blk, dk_blk, dv_blk), None

    (dq, _, _, dk, dv), _ = lax.scan(
        step, (dq0, k, v, dkv0, dkv0), jnp.arange(n_shards))
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


def ring_flash_attention(q, k, v, mesh, axis: str = "seq",
                         batch_axis: Optional[str] = None,
                         causal: bool = False, scale: Optional[float] = None,
                         block_q: int = 512, block_k: int = 512):
    """Ring attention whose per-block compute is the Pallas flash kernel
    (fwd AND bwd): sequence sharded over ``axis``, K/V (and their
    gradients, on the backward ring) rotating via ppermute, per-block
    partials merged by logsumexp. Exact; O(seq/n) memory per device with
    VMEM-streamed blocks — the long-context training path end to end."""
    import jax
    from jax.sharding import PartitionSpec as P

    from ._compat import shard_map

    if scale is None:
        scale = 1.0 / np.sqrt(q.shape[-1])
    n_shards = mesh.shape[axis]
    interpret = jax.default_backend() != "tpu"
    spec = P(batch_axis, axis, None, None)
    vary_axes = (axis,) + ((batch_axis,) if batch_axis else ())
    kw = dict(axis=axis, vary_axes=vary_axes, n_shards=n_shards,
              causal=causal, scale=scale, block_q=block_q, block_k=block_k,
              interpret=interpret)

    @jax.custom_vjp
    def rf(q, k, v):
        o, _ = _ring_flash_fwd(q, k, v, **kw)
        return o

    def fwd(q, k, v):
        o, lse = _ring_flash_fwd(q, k, v, **kw)
        return o, (q, k, v, o, lse)

    def bwd(res, g):
        return _ring_flash_bwd(*res, g, **kw)

    rf.defvjp(fwd, bwd)
    check_vma = jax.default_backend() == "tpu"
    fn = shard_map(rf, mesh=mesh, in_specs=(spec, spec, spec),
                   out_specs=spec, check_vma=check_vma)
    return fn(q, k, v)


def _ulysses_inner(q, k, v, *, axis, n_shards, causal, scale, attn_fn):
    from jax import lax

    # [b, s/n, h, d] -> [b, s, h/n, d]: gather sequence, scatter heads
    def fwd(x):
        return lax.all_to_all(x, axis, split_axis=2, concat_axis=1,
                              tiled=True)

    def bwd(x):
        return lax.all_to_all(x, axis, split_axis=1, concat_axis=2,
                              tiled=True)

    out = attn_fn(fwd(q), fwd(k), fwd(v), causal=causal, scale=scale)
    return bwd(out)


def ulysses_attention(q, k, v, mesh, axis: str = "seq",
                      batch_axis: Optional[str] = None, causal: bool = False,
                      scale: Optional[float] = None, attn_fn=None):
    """All-to-all sequence parallelism: heads are sharded during attention,
    sequence is sharded elsewhere. Requires heads % mesh.shape[axis] == 0."""
    import jax
    from jax.sharding import PartitionSpec as P

    from ._compat import shard_map

    n_shards = mesh.shape[axis]
    if q.shape[2] % n_shards:
        raise ValueError(
            "ulysses needs heads (%d) divisible by mesh axis %r size %d"
            % (q.shape[2], axis, n_shards))
    if scale is None:
        scale = 1.0 / np.sqrt(q.shape[-1])
    if attn_fn is None:
        attn_fn = local_attention
    spec = P(batch_axis, axis, None, None)
    inner = functools.partial(_ulysses_inner, axis=axis, n_shards=n_shards,
                              causal=causal, scale=scale, attn_fn=attn_fn)
    # pallas interpret-mode (non-TPU) dynamic_slice inside shard_map trips
    # the varying-axis checker (jax 0.9); keep the checker on for TPU
    check_vma = jax.default_backend() == "tpu"
    fn = shard_map(inner, mesh=mesh, in_specs=(spec, spec, spec),
                   out_specs=spec, check_vma=check_vma)
    return fn(q, k, v)

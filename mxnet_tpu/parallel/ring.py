"""Sequence-parallel attention over the device mesh — the long-context
engine (new capability vs the reference, which only had bucketing for long
sequences; SURVEY.md §5.7).

Two schemes, both exact (not approximations of softmax attention):

* ``ring_attention`` — K/V blocks rotate around the mesh ring with
  ``lax.ppermute`` while each device's Q block accumulates the softmax
  online (the numerically-stable m/l running max/denominator recurrence).
  Communication overlaps compute; memory per device is O(seq/n).
* ``ulysses_attention`` — ``lax.all_to_all`` reshards from sequence-sharded
  to head-sharded, runs dense local attention, then reshards back. Cheaper
  at moderate sequence lengths when heads >= mesh axis size.

Tensor convention: [batch, seq, heads, head_dim], sequence sharded on
``axis`` (default 'seq').
"""
from __future__ import annotations

import functools
from typing import Optional

import numpy as np

_NEG = -1e30


def local_attention(q, k, v, causal: bool = False,
                    scale: Optional[float] = None):
    """Dense single-device softmax attention — the oracle and the inner
    kernel for ulysses. [b, s, h, d] in/out."""
    import jax.numpy as jnp

    if scale is None:
        scale = 1.0 / np.sqrt(q.shape[-1])
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    if causal:
        sq, sk = q.shape[1], k.shape[1]
        mask = jnp.arange(sq)[:, None] >= jnp.arange(sk)[None, :]
        s = jnp.where(mask[None, None], s, _NEG)
    p = jnp.exp(s - s.max(-1, keepdims=True))
    p = p / p.sum(-1, keepdims=True)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v).astype(q.dtype)


def _ring_inner(q, k, v, *, axis, vary_axes, n_shards, causal, scale):
    import jax.numpy as jnp
    from jax import lax

    idx = lax.axis_index(axis)
    b, sq, h, d = q.shape
    sk = k.shape[1]
    q_pos = idx * sq + jnp.arange(sq)
    perm = [(j, (j + 1) % n_shards) for j in range(n_shards)]

    # initial accumulators must carry the same varying-axis type as the
    # loop outputs (shard_map VMA typing)
    def _vary(x):
        return lax.pcast(x, vary_axes, to="varying")

    o0 = _vary(jnp.zeros((b, sq, h, d), jnp.float32))
    m0 = _vary(jnp.full((b, h, sq), _NEG, jnp.float32))
    l0 = _vary(jnp.zeros((b, h, sq), jnp.float32))

    def step(carry, t):
        o, m, l, k_blk, v_blk = carry
        # after t right-rotations this device holds block (idx - t) mod n
        k_idx = jnp.mod(idx - t, n_shards)
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k_blk).astype(jnp.float32)
        s = s * scale
        if causal:
            k_pos = k_idx * sk + jnp.arange(sk)
            mask = q_pos[:, None] >= k_pos[None, :]
            s = jnp.where(mask[None, None], s, _NEG)
        m_new = jnp.maximum(m, s.max(-1))
        p = jnp.exp(s - m_new[..., None])
        corr = jnp.exp(m - m_new)  # [b, h, q]
        l = l * corr + p.sum(-1)
        o = (o * corr.transpose(0, 2, 1)[..., None] +
             jnp.einsum("bhqk,bkhd->bqhd", p,
                        v_blk.astype(jnp.float32)))
        k_blk = lax.ppermute(k_blk, axis, perm)
        v_blk = lax.ppermute(v_blk, axis, perm)
        return (o, m_new, l, k_blk, v_blk), None

    (o, m, l, _, _), _ = lax.scan(
        step, (o0, m0, l0, k, v), jnp.arange(n_shards))
    out = o / l.transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


def ring_attention(q, k, v, mesh, axis: str = "seq",
                   batch_axis: Optional[str] = None, causal: bool = False,
                   scale: Optional[float] = None):
    """Exact attention with the sequence dimension sharded over ``axis`` of
    ``mesh``; K/V ride the ring via ppermute (ICI neighbours on TPU).

    q, k, v: [batch, seq, heads, head_dim] global arrays (sequence may be
    sharded on ``axis``; batch optionally on ``batch_axis``)."""
    import jax
    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    if scale is None:
        scale = 1.0 / np.sqrt(q.shape[-1])
    n_shards = mesh.shape[axis]
    spec = P(batch_axis, axis, None, None)
    vary_axes = (axis,) + ((batch_axis,) if batch_axis else ())
    inner = functools.partial(_ring_inner, axis=axis, vary_axes=vary_axes,
                              n_shards=n_shards, causal=causal, scale=scale)
    fn = shard_map(inner, mesh=mesh, in_specs=(spec, spec, spec),
                   out_specs=spec)
    return fn(q, k, v)


def _ulysses_inner(q, k, v, *, axis, n_shards, causal, scale, attn_fn):
    from jax import lax

    # [b, s/n, h, d] -> [b, s, h/n, d]: gather sequence, scatter heads
    def fwd(x):
        return lax.all_to_all(x, axis, split_axis=2, concat_axis=1,
                              tiled=True)

    def bwd(x):
        return lax.all_to_all(x, axis, split_axis=1, concat_axis=2,
                              tiled=True)

    out = attn_fn(fwd(q), fwd(k), fwd(v), causal=causal, scale=scale)
    return bwd(out)


def ulysses_attention(q, k, v, mesh, axis: str = "seq",
                      batch_axis: Optional[str] = None, causal: bool = False,
                      scale: Optional[float] = None, attn_fn=None):
    """All-to-all sequence parallelism: heads are sharded during attention,
    sequence is sharded elsewhere. Requires heads % mesh.shape[axis] == 0."""
    import jax
    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    n_shards = mesh.shape[axis]
    if q.shape[2] % n_shards:
        raise ValueError(
            "ulysses needs heads (%d) divisible by mesh axis %r size %d"
            % (q.shape[2], axis, n_shards))
    if scale is None:
        scale = 1.0 / np.sqrt(q.shape[-1])
    if attn_fn is None:
        attn_fn = local_attention
    spec = P(batch_axis, axis, None, None)
    inner = functools.partial(_ulysses_inner, axis=axis, n_shards=n_shards,
                              causal=causal, scale=scale, attn_fn=attn_fn)
    # pallas interpret-mode (non-TPU) dynamic_slice inside shard_map trips
    # the varying-axis checker (jax 0.9); keep the checker on for TPU
    check_vma = jax.default_backend() == "tpu"
    fn = shard_map(inner, mesh=mesh, in_specs=(spec, spec, spec),
                   out_specs=spec, check_vma=check_vma)
    return fn(q, k, v)

"""mxnet_tpu.parallel — SPMD parallelism over TPU device meshes.

This package is the TPU-native replacement for the reference's entire
distribution plane (src/kvstore/comm.h, kvstore_dist.h, ps-lite, the
DataParallelExecutorGroup scatter/gather in
python/mxnet/module/executor_group.py:77-236, and the manual
model-parallel-lstm layer placement in example/model-parallel-lstm/): instead
of explicit push/pull and cross-device copies, parameters and activations
carry sharding annotations over a named `jax.sharding.Mesh` and XLA compiles
the collectives (psum/all_gather/reduce_scatter/ppermute) into the step
function, riding ICI within a slice and DCN across slices.

New-capability set beyond the reference (SURVEY.md §5.7, §7 step 8):

* ``ring_attention`` — exact blockwise attention with keys/values rotating
  around the mesh ring (ppermute), sequence-parallel long-context training.
* ``ring_flash_attention`` — the same ring with the Pallas flash kernel as
  the per-block compute, fwd and bwd (dk/dv ride the ring home).
* ``ulysses_attention`` — all-to-all sequence parallelism (shard heads during
  attention, sequence elsewhere).
* ``pipeline_spmd`` — collective-permute pipeline over stacked homogeneous
  stages (the TPU-native form of the reference's model-parallel LSTM
  placement, example/model-parallel-lstm/lstm.py:142-205).
* ``moe_ffn`` — expert parallelism: mixture-of-experts FFN with experts
  sharded over a mesh axis, exact einsum dispatch, psum combine.
"""
from .mesh import (MeshConfig, make_mesh, data_parallel_mesh, shard, replicate,
                   current_mesh, set_current_mesh)
from .ring import (ring_attention, ring_flash_attention,
                   ulysses_attention, local_attention)
from .moe import moe_ffn, moe_ffn_reference
from .pipeline import pipeline_spmd

__all__ = [
    "MeshConfig", "make_mesh", "data_parallel_mesh", "shard", "replicate",
    "current_mesh", "set_current_mesh",
    "ring_attention", "ring_flash_attention", "ulysses_attention",
    "local_attention", "moe_ffn", "moe_ffn_reference",
    "pipeline_spmd",
]

"""``mx.th`` — call (Py)Torch functions on NDArrays.

Parity: the reference bridges Torch7 tensor math into MXNet
(/root/reference/python/mxnet/torch.py over plugin/torch/): ``mx.th.foo``
runs a torch function on MXNet arrays.  Here the bridge targets PyTorch
(CPU build, baked into this image): NDArrays convert to torch tensors
(zero-copy through numpy where dtypes allow), the torch callable runs
eagerly on host, and results wrap back as NDArrays.  This is an escape
hatch for host-side math — it does not trace into jitted graphs (use the
op registry / ``register_pallas_op`` for that), matching the reference's
"runs outside the engine's typed path" caveat for its torch bridge.
"""
from __future__ import annotations

import sys
import types

import numpy as np

from .base import MXNetError

__all__ = ["apply", "is_available"]


def is_available() -> bool:
    try:
        import torch  # noqa: F401

        return True
    except Exception:
        return False


def _to_torch(v):
    import torch

    from . import ndarray as nd

    if isinstance(v, nd.NDArray):
        # asnumpy() can be a zero-copy view of the immutable JAX buffer:
        # torch in-place ops on it would corrupt the array behind JAX's
        # back, so hand torch its own writable copy
        return torch.from_numpy(np.array(v.asnumpy()))
    if isinstance(v, np.ndarray):
        arr = np.ascontiguousarray(v)
        if not arr.flags.writeable:
            arr = arr.copy()
        return torch.from_numpy(arr)
    if isinstance(v, (list, tuple)):
        return type(v)(_to_torch(x) for x in v)
    return v


def _from_torch(v):
    import torch

    from . import ndarray as nd

    if isinstance(v, torch.Tensor):
        return nd.array(v.detach().cpu().numpy())
    if isinstance(v, (list, tuple)):
        return type(v)(_from_torch(x) for x in v)
    return v


def apply(fn, *args, **kwargs):
    """Run ``fn`` (a torch callable or dotted name like ``"fft.rfft"``)
    on NDArray/numpy arguments; NDArrays come back out."""
    import torch

    if isinstance(fn, str):
        obj = torch
        for part in fn.split("."):
            obj = getattr(obj, part)
        fn = obj
    out = fn(*[_to_torch(a) for a in args],
             **{k: _to_torch(v) for k, v in kwargs.items()})
    return _from_torch(out)


class _TorchModule(types.ModuleType):
    """Attribute access forwards to torch: ``mx.th.exp(x)``,
    ``mx.th.linalg.svd(m)`` — the reference's generated mx.th surface."""

    def __getattr__(self, name):
        if name.startswith("__"):
            raise AttributeError(name)
        try:
            import torch
        except Exception:
            raise MXNetError(
                "mx.th requires torch, which is unavailable in this "
                "environment")
        target = getattr(torch, name)
        if isinstance(target, types.ModuleType):
            sub = _TorchNamespace(target)
            return sub
        if callable(target):
            return lambda *a, **kw: apply(target, *a, **kw)
        return target


class _TorchNamespace:
    def __init__(self, mod):
        self._mod = mod

    def __getattr__(self, name):
        target = getattr(self._mod, name)
        if isinstance(target, types.ModuleType):
            return _TorchNamespace(target)
        if callable(target):
            return lambda *a, **kw: apply(target, *a, **kw)
        return target


sys.modules[__name__].__class__ = _TorchModule

"""Random number handling (parity: python/mxnet/random.py + the RNG resource
ResourceRandom in /root/reference/src/resource.cc:144).

A single seeded JAX PRNG stream is split per stochastic op call — the
functional TPU replacement for per-device cuRAND generators.  ``seed()``
reseeds the stream exactly like ``mx.random.seed``.
"""
from __future__ import annotations

import threading

import numpy as np

__all__ = ["seed", "next_key", "uniform", "normal", "randint", "current_seed",
           "get_state", "set_state"]

_state = threading.local()


def _ensure():
    if not hasattr(_state, "key"):
        import jax

        _state.seed = 0
        _state.key = jax.random.PRNGKey(0)
    return _state


def seed(seed_state: int) -> None:
    """Seed the global random stream (parity: mx.random.seed; reference also
    reseeds numpy-side augmenters, so we touch np.random too)."""
    import jax

    st = _ensure()
    st.seed = int(seed_state)
    st.key = jax.random.PRNGKey(int(seed_state))
    np.random.seed(int(seed_state) % (2 ** 32))


def current_seed() -> int:
    return _ensure().seed


def get_state() -> dict:
    """Snapshot the framework PRNG stream as plain host data (the key as
    a numpy array), so checkpoints can carry it — the missing half of
    deterministic resume: params alone replay a different stochastic
    schedule."""
    st = _ensure()
    return {"seed": st.seed, "key": np.asarray(st.key).copy()}


def set_state(state: dict) -> None:
    """Restore a :func:`get_state` snapshot: the next :func:`next_key`
    split continues bit-identically from the captured stream position."""
    import jax.numpy as jnp

    st = _ensure()
    st.seed = int(state["seed"])
    st.key = jnp.asarray(np.asarray(state["key"]))


def next_key():
    """Split one fresh key off the global stream."""
    import jax

    st = _ensure()
    st.key, sub = jax.random.split(st.key)
    return sub


def uniform(low=0.0, high=1.0, shape=(1,), ctx=None, out=None, dtype="float32"):
    from . import ndarray as nd

    return nd.uniform(low=low, high=high, shape=shape, ctx=ctx, out=out, dtype=dtype)


def normal(loc=0.0, scale=1.0, shape=(1,), ctx=None, out=None, dtype="float32"):
    from . import ndarray as nd

    return nd.normal(loc=loc, scale=scale, shape=shape, ctx=ctx, out=out, dtype=dtype)


def randint(low, high, shape=(1,), ctx=None, out=None, dtype="int32"):
    import jax

    from . import ndarray as nd

    key = next_key()
    data = jax.random.randint(key, shape, low, high)
    arr = nd.array(np.asarray(data), ctx=ctx, dtype=dtype)
    if out is not None:
        out[:] = arr
        return out
    return arr

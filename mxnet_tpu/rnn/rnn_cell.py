"""Symbolic RNN cell library — capability parity with the reference
python/mxnet/rnn/rnn_cell.py:87-900 (RNN/LSTM/GRU/Fused/Sequential/
Bidirectional/Dropout/Zoneout/Residual cells + unroll), redesigned for the
TPU build:

* ``FusedRNNCell`` lowers to the single fused ``RNN`` op (a ``lax.scan``
  whose per-step work is one MXU matmul — see ops/rnn_op.py) instead of
  cuDNN, and is the fast path for training.
* ``unroll`` with ``begin_state=None`` synthesizes zero states with the
  ``_rnn_state_zeros`` op tied to the input symbol, so no shape-0
  placeholder inference is needed (XLA static shapes).
* Gate orders match the fused op: LSTM [i, f, g, o]; GRU [r, z, n] with the
  linear-before-reset recurrence, so ``FusedRNNCell.unfuse()`` is exact.
"""
from __future__ import annotations

import numpy as np

from .. import symbol
from ..base import MXNetError
from ..name import NameManager

__all__ = ["RNNParams", "BaseRNNCell", "RNNCell", "LSTMCell", "GRUCell",
           "FusedRNNCell", "SequentialRNNCell", "BidirectionalCell",
           "DropoutCell", "ModifierCell", "ZoneoutCell", "ResidualCell"]


class RNNParams(object):
    """Container for cell parameter symbols, shared between cells via the
    ``params`` constructor argument (reference rnn_cell.py:57-85)."""

    def __init__(self, prefix=""):
        self._prefix = prefix
        self._params = {}

    def get(self, name, **kwargs):
        name = self._prefix + name
        if name not in self._params:
            self._params[name] = symbol.Variable(name, **kwargs)
        return self._params[name]


class BaseRNNCell(object):
    """Abstract RNN cell (reference rnn_cell.py:87-306)."""

    def __init__(self, prefix="", params=None):
        if params is None:
            params = RNNParams(prefix)
            self._own_params = True
        else:
            self._own_params = False
        self._prefix = prefix
        self._params = params
        self._modified = False
        self.reset()

    def reset(self):
        self._init_counter = -1
        self._counter = -1

    def __call__(self, inputs, states):
        raise NotImplementedError

    @property
    def params(self):
        self._own_params = False
        return self._params

    @property
    def state_info(self):
        """List of dicts {'shape': tuple (0 = batch), '__layout__': str}."""
        raise NotImplementedError

    @property
    def state_shape(self):
        return [info["shape"] for info in self.state_info]

    @property
    def _gate_names(self):
        return ()

    def begin_state(self, func=None, **kwargs):
        """Initial states.  ``func=None`` (default) creates Variable symbols
        (bindable inputs, shapes deduced from the graph); pass
        ``func=mx.sym.zeros`` with a ``batch_size`` kwarg for inline zeros,
        or any symbol-returning callable as in the reference."""
        assert not self._modified, \
            "After applying modifier cells the base cell cannot be called " \
            "directly. Call the modifier cell instead."
        batch_size = kwargs.pop("batch_size", 0)
        states = []
        for info in self.state_info:
            self._init_counter += 1
            name = "%sbegin_state_%d" % (self._prefix, self._init_counter)
            shape = tuple(batch_size if s == 0 else s
                          for s in info["shape"])
            if func is None:
                states.append(symbol.Variable(name))
            elif func is symbol.Variable:
                states.append(func(name, **kwargs))
            else:
                states.append(func(shape=shape, name=name, **kwargs))
        return states

    def _zeros_states(self, data, batch_axis):
        """States-of-zeros whose batch dim follows ``data`` (used by unroll
        when begin_state is None)."""
        states = []
        for info in self.state_info:
            self._init_counter += 1
            states.append(symbol._create(
                "_rnn_state_zeros", [data],
                {"shape": info["shape"], "batch_axis": batch_axis},
                name="%sbegin_state_%d" % (self._prefix, self._init_counter)))
        return states

    # -- weight (un)packing ------------------------------------------------
    def unpack_weights(self, args):
        """Split fused gate weights into per-gate entries (reference
        rnn_cell.py:186-214)."""
        args = dict(args)
        if not self._gate_names:
            return args
        h = self._num_hidden
        for group_name in ("i2h", "h2h"):
            for t in ("weight", "bias"):
                name = "%s%s_%s" % (self._prefix, group_name, t)
                if name not in args:
                    continue
                arr = args.pop(name).asnumpy() if hasattr(args.get(name), "asnumpy") \
                    else args.pop(name)
                arr = np.asarray(arr)
                for j, gate in enumerate(self._gate_names):
                    wname = "%s%s%s_%s" % (self._prefix, group_name, gate, t)
                    args[wname] = arr[j * h:(j + 1) * h].copy()
        return args

    def pack_weights(self, args):
        args = dict(args)
        if not self._gate_names:
            return args
        for group_name in ("i2h", "h2h"):
            for t in ("weight", "bias"):
                parts = []
                ok = True
                for gate in self._gate_names:
                    wname = "%s%s%s_%s" % (self._prefix, group_name, gate, t)
                    if wname not in args:
                        ok = False
                        break
                    parts.append(np.asarray(
                        args[wname].asnumpy() if hasattr(args[wname], "asnumpy")
                        else args[wname]))
                if not ok:
                    continue
                for gate in self._gate_names:
                    del args["%s%s%s_%s" % (self._prefix, group_name, gate, t)]
                args["%s%s_%s" % (self._prefix, group_name, t)] = \
                    np.concatenate(parts, axis=0)
        return args

    # -- unrolling ---------------------------------------------------------
    def unroll(self, length, inputs=None, begin_state=None, input_prefix="",
               layout="NTC", merge_outputs=None):
        """Unroll the cell for ``length`` steps (reference rnn_cell.py:245).

        inputs: a single Symbol with layout NTC/TNC, a list of per-step
        Symbols (each (N, C)), or None (creates t%d_data Variables)."""
        self.reset()
        inputs, ref, batch_axis = _normalize_sequence(
            length, inputs, input_prefix, layout)
        if begin_state is None:
            begin_state = self._zeros_states(ref, batch_axis)
        states = begin_state
        outputs = []
        for i in range(length):
            output, states = self(inputs[i], states)
            outputs.append(output)
        if merge_outputs:
            outputs = [symbol.expand_dims(o, axis=1) for o in outputs]
            outputs = symbol.Concat(*outputs, dim=1)
        return outputs, states


def _normalize_sequence(length, inputs, input_prefix, layout):
    """-> (list of per-step symbols, reference symbol, batch_axis)."""
    if inputs is None:
        inputs = [symbol.Variable("%st%d_data" % (input_prefix, i))
                  for i in range(length)]
        return inputs, inputs[0], 0
    if isinstance(inputs, symbol.Symbol):
        t_axis = layout.find("T")
        batch_axis = layout.find("N")
        ref = inputs
        if length == 1:
            steps = [symbol.Reshape(
                symbol.slice_axis(inputs, axis=t_axis, begin=0, end=1),
                shape=(0, -1))]
        else:
            steps = list(symbol.SliceChannel(
                inputs, num_outputs=length, axis=t_axis, squeeze_axis=True))
        # per-step batch axis after squeezing T
        return steps, ref, 0 if batch_axis > t_axis else batch_axis
    return list(inputs), inputs[0], 0


class RNNCell(BaseRNNCell):
    """Elman RNN cell: h' = act(W x + b_i + U h + b_h) (reference
    rnn_cell.py:308-355)."""

    def __init__(self, num_hidden, activation="tanh", prefix="rnn_",
                 params=None):
        super().__init__(prefix=prefix, params=params)
        self._num_hidden = num_hidden
        self._activation = activation
        self._iW = self.params.get("i2h_weight")
        self._iB = self.params.get("i2h_bias")
        self._hW = self.params.get("h2h_weight")
        self._hB = self.params.get("h2h_bias")

    @property
    def state_info(self):
        return [{"shape": (0, self._num_hidden), "__layout__": "NC"}]

    @property
    def _gate_names(self):
        return ("",)

    def __call__(self, inputs, states):
        self._counter += 1
        name = "%st%d_" % (self._prefix, self._counter)
        i2h = symbol.FullyConnected(data=inputs, weight=self._iW,
                                    bias=self._iB,
                                    num_hidden=self._num_hidden,
                                    name="%si2h" % name)
        h2h = symbol.FullyConnected(data=states[0], weight=self._hW,
                                    bias=self._hB,
                                    num_hidden=self._num_hidden,
                                    name="%sh2h" % name)
        output = symbol.Activation(i2h + h2h, act_type=self._activation,
                                   name="%sout" % name)
        return output, [output]


class LSTMCell(BaseRNNCell):
    """LSTM cell, gate order [i, f, g, o] matching the fused RNN op
    (reference rnn_cell.py:356-417)."""

    def __init__(self, num_hidden, prefix="lstm_", params=None,
                 forget_bias=1.0):
        super().__init__(prefix=prefix, params=params)
        self._num_hidden = num_hidden
        self._iW = self.params.get("i2h_weight")
        self._hW = self.params.get("h2h_weight")
        from ..initializer import LSTMBias

        self._iB = self.params.get(
            "i2h_bias", init=LSTMBias(forget_bias=forget_bias))
        self._hB = self.params.get("h2h_bias")

    @property
    def state_info(self):
        return [{"shape": (0, self._num_hidden), "__layout__": "NC"},
                {"shape": (0, self._num_hidden), "__layout__": "NC"}]

    @property
    def _gate_names(self):
        return ("_i", "_f", "_c", "_o")

    def __call__(self, inputs, states):
        self._counter += 1
        name = "%st%d_" % (self._prefix, self._counter)
        i2h = symbol.FullyConnected(data=inputs, weight=self._iW,
                                    bias=self._iB,
                                    num_hidden=self._num_hidden * 4,
                                    name="%si2h" % name)
        h2h = symbol.FullyConnected(data=states[0], weight=self._hW,
                                    bias=self._hB,
                                    num_hidden=self._num_hidden * 4,
                                    name="%sh2h" % name)
        gates = i2h + h2h
        slices = symbol.SliceChannel(gates, num_outputs=4, axis=1,
                                     name="%sslice" % name)
        in_gate = symbol.Activation(slices[0], act_type="sigmoid")
        forget_gate = symbol.Activation(slices[1], act_type="sigmoid")
        in_trans = symbol.Activation(slices[2], act_type="tanh")
        out_gate = symbol.Activation(slices[3], act_type="sigmoid")
        next_c = forget_gate * states[1] + in_gate * in_trans
        next_h = out_gate * symbol.Activation(next_c, act_type="tanh",
                                              name="%sstate" % name)
        return next_h, [next_h, next_c]


class GRUCell(BaseRNNCell):
    """GRU cell, gate order [r, z, n], linear-before-reset recurrence
    matching the fused RNN op (reference rnn_cell.py:418-485)."""

    def __init__(self, num_hidden, prefix="gru_", params=None):
        super().__init__(prefix=prefix, params=params)
        self._num_hidden = num_hidden
        self._iW = self.params.get("i2h_weight")
        self._iB = self.params.get("i2h_bias")
        self._hW = self.params.get("h2h_weight")
        self._hB = self.params.get("h2h_bias")

    @property
    def state_info(self):
        return [{"shape": (0, self._num_hidden), "__layout__": "NC"}]

    @property
    def _gate_names(self):
        return ("_r", "_z", "_o")

    def __call__(self, inputs, states):
        self._counter += 1
        name = "%st%d_" % (self._prefix, self._counter)
        prev_h = states[0]
        i2h = symbol.FullyConnected(data=inputs, weight=self._iW,
                                    bias=self._iB,
                                    num_hidden=self._num_hidden * 3,
                                    name="%si2h" % name)
        h2h = symbol.FullyConnected(data=prev_h, weight=self._hW,
                                    bias=self._hB,
                                    num_hidden=self._num_hidden * 3,
                                    name="%sh2h" % name)
        i2h_r, i2h_z, i2h_n = symbol.SliceChannel(
            i2h, num_outputs=3, axis=1, name="%si2h_slice" % name)
        h2h_r, h2h_z, h2h_n = symbol.SliceChannel(
            h2h, num_outputs=3, axis=1, name="%sh2h_slice" % name)
        reset = symbol.Activation(i2h_r + h2h_r, act_type="sigmoid")
        update = symbol.Activation(i2h_z + h2h_z, act_type="sigmoid")
        next_hbar = symbol.Activation(i2h_n + reset * h2h_n, act_type="tanh")
        ones = update * 0.0 + 1.0
        next_h = (ones - update) * next_hbar + update * prev_h
        return next_h, [next_h]


class FusedRNNCell(BaseRNNCell):
    """Fused multi-layer RNN lowered to the single ``RNN`` op — the
    reference's cuDNN FusedRNNCell (rnn_cell.py:486-672) re-targeted to the
    lax.scan kernel in ops/rnn_op.py."""

    def __init__(self, num_hidden, num_layers=1, mode="lstm",
                 bidirectional=False, dropout=0.0, get_next_state=False,
                 forget_bias=1.0, prefix=None, params=None):
        if prefix is None:
            prefix = "%s_" % mode
        super().__init__(prefix=prefix, params=params)
        self._num_hidden = num_hidden
        self._num_layers = num_layers
        self._mode = mode
        self._bidirectional = bidirectional
        self._dropout = dropout
        self._get_next_state = get_next_state
        self._forget_bias = forget_bias
        self._parameter = self.params.get("parameters")

    @property
    def _num_gates(self):
        return {"rnn_relu": 1, "rnn_tanh": 1, "lstm": 4, "gru": 3}[self._mode]

    @property
    def _dir(self):
        return 2 if self._bidirectional else 1

    @property
    def state_info(self):
        n = self._num_layers * self._dir
        infos = [{"shape": (n, 0, self._num_hidden), "__layout__": "LNC"}]
        if self._mode == "lstm":
            infos.append({"shape": (n, 0, self._num_hidden),
                          "__layout__": "LNC"})
        return infos

    @property
    def _gate_names(self):
        return {"rnn_relu": ("",), "rnn_tanh": ("",),
                "lstm": ("_i", "_f", "_c", "_o"),
                "gru": ("_r", "_z", "_o")}[self._mode]

    def _input_size_from(self, total):
        """Solve the packed-vector length for the layer-0 input size."""
        g, h = self._num_gates, self._num_hidden
        d = self._dir
        rest = 2 * d * g * h * self._num_layers  # all biases
        for layer in range(1, self._num_layers):
            rest += d * (g * h * (h * d) + g * h * h)
        rest += d * g * h * h  # layer-0 h2h
        i = (total - rest) // (d * g * h)
        if i <= 0 or rest + d * g * h * i != total:
            raise MXNetError("packed RNN parameter length %d inconsistent "
                             "with cell config" % total)
        return i

    def unpack_weights(self, args):
        from ..ops.rnn_op import rnn_unpack_layout

        args = dict(args)
        name = self._parameter.name
        arr = args.pop(name)
        arr = np.asarray(arr.asnumpy() if hasattr(arr, "asnumpy") else arr)
        input_size = self._input_size_from(arr.size)
        layout = rnn_unpack_layout(input_size, self._num_hidden,
                                   self._num_layers, self._mode,
                                   self._bidirectional)
        for layer, direction, kind, off, shape in layout:
            n = int(np.prod(shape))
            block = arr[off:off + n].reshape(shape)
            dir_s = ["l", "r"][direction]
            # whole fused gate blocks, named to match the unfuse()d cells
            args["%s%s%d_%s" % (self._prefix, dir_s, layer, kind)] = \
                block.copy()
        return args

    def pack_weights(self, args):
        from ..ops.rnn_op import rnn_unpack_layout, rnn_param_size

        args = dict(args)
        h = self._num_hidden
        # deduce input size from the layer-0 i2h weight
        probe = "%sl0_i2h_weight" % self._prefix
        input_size = np.asarray(
            args[probe].asnumpy() if hasattr(args[probe], "asnumpy")
            else args[probe]).shape[1]
        total = rnn_param_size(input_size, h, self._num_layers, self._mode,
                               self._bidirectional)
        layout = rnn_unpack_layout(input_size, h, self._num_layers,
                                   self._mode, self._bidirectional)
        out = np.zeros(total, np.float32)
        for layer, direction, kind, off, shape in layout:
            dir_s = ["l", "r"][direction]
            pname = "%s%s%d_%s" % (self._prefix, dir_s, layer, kind)
            block = np.asarray(
                args.pop(pname).asnumpy()
                if hasattr(args.get(pname), "asnumpy") else args.pop(pname))
            out[off:off + block.size] = block.reshape(-1)
        args[self._parameter.name] = out
        return args

    def __call__(self, inputs, states):
        raise NotImplementedError(
            "FusedRNNCell cannot be stepped; use unroll")

    def unroll(self, length, inputs=None, begin_state=None, input_prefix="",
               layout="NTC", merge_outputs=None):
        self.reset()
        if inputs is None:
            inputs = [symbol.Variable("%st%d_data" % (input_prefix, i))
                      for i in range(length)]
        if isinstance(inputs, (list, tuple)):
            inputs = symbol.Concat(
                *[symbol.expand_dims(i, axis=0) for i in inputs], dim=0)
            tnc = inputs
            batch_axis = 1
        else:
            if layout == "NTC":
                tnc = symbol.SwapAxis(inputs, dim1=0, dim2=1)
            elif layout == "TNC":
                tnc = inputs
            else:
                raise MXNetError("unsupported layout %s" % layout)
            batch_axis = 1
        if begin_state is None:
            begin_state = self._zeros_states(tnc, batch_axis)
        states = list(begin_state)
        kwargs = {}
        if self._mode == "lstm":
            kwargs["state_cell"] = states[1]
        rnn = symbol._create(
            "RNN",
            [tnc, self._parameter, states[0]] +
            ([states[1]] if self._mode == "lstm" else []),
            {"state_size": self._num_hidden,
             "num_layers": self._num_layers,
             "bidirectional": self._bidirectional,
             "mode": self._mode, "p": self._dropout,
             "state_outputs": self._get_next_state},
            name="%srnn" % self._prefix)
        if self._get_next_state:
            outputs = rnn[0]
            if self._mode == "lstm":
                final = [rnn[1], rnn[2]]
            else:
                final = [rnn[1]]
        else:
            outputs = rnn if not isinstance(rnn, list) else rnn[0]
            final = []
        if layout == "NTC":
            outputs = symbol.SwapAxis(outputs, dim1=0, dim2=1)
        if merge_outputs is False:
            t_axis = 0 if layout == "TNC" else 1
            outputs = list(symbol.SliceChannel(
                outputs, num_outputs=length, axis=t_axis, squeeze_axis=True))
        return outputs, final

    def unfuse(self):
        """Equivalent SequentialRNNCell of unfused cells (exact: gate order
        and GRU recurrence match the fused kernel)."""
        stack = SequentialRNNCell()
        get_cell = {
            "rnn_relu": lambda p: RNNCell(self._num_hidden,
                                          activation="relu", prefix=p),
            "rnn_tanh": lambda p: RNNCell(self._num_hidden,
                                          activation="tanh", prefix=p),
            "lstm": lambda p: LSTMCell(self._num_hidden, prefix=p,
                                       forget_bias=self._forget_bias),
            "gru": lambda p: GRUCell(self._num_hidden, prefix=p),
        }[self._mode]
        for i in range(self._num_layers):
            if self._bidirectional:
                stack.add(BidirectionalCell(
                    get_cell("%sl%d_" % (self._prefix, i)),
                    get_cell("%sr%d_" % (self._prefix, i)),
                    output_prefix="%sbi_%d_" % (self._prefix, i)))
            else:
                stack.add(get_cell("%sl%d_" % (self._prefix, i)))
            if self._dropout > 0 and i != self._num_layers - 1:
                stack.add(DropoutCell(
                    self._dropout, prefix="%s_dropout%d_" % (self._prefix, i)))
        return stack


class SequentialRNNCell(BaseRNNCell):
    """Stack of cells applied in order (reference rnn_cell.py:673-748)."""

    def __init__(self, params=None):
        super().__init__(prefix="", params=params)
        self._cells = []
        self._override_cell_params = params is not None

    def add(self, cell):
        self._cells.append(cell)
        if self._override_cell_params:
            cell.params._params.update(self.params._params)
            self.params._params.update(cell.params._params)

    @property
    def state_info(self):
        return sum([c.state_info for c in self._cells], [])

    def begin_state(self, **kwargs):
        assert not self._modified
        return sum([c.begin_state(**kwargs) for c in self._cells], [])

    def _zeros_states(self, data, batch_axis):
        return sum([c._zeros_states(data, batch_axis)
                    for c in self._cells], [])

    def unpack_weights(self, args):
        for cell in self._cells:
            args = cell.unpack_weights(args)
        return args

    def pack_weights(self, args):
        for cell in self._cells:
            args = cell.pack_weights(args)
        return args

    def __call__(self, inputs, states):
        self._counter += 1
        next_states = []
        p = 0
        for cell in self._cells:
            n = len(cell.state_info)
            cell_states = states[p:p + n]
            p += n
            inputs, cell_states = cell(inputs, cell_states)
            next_states.extend(cell_states)
        return inputs, next_states

    def unroll(self, length, inputs=None, begin_state=None, input_prefix="",
               layout="NTC", merge_outputs=None):
        self.reset()
        # per-cell unroll so FusedRNNCell members stay fused
        num_cells = len(self._cells)
        if begin_state is not None:
            p = 0
            cell_begin = []
            for cell in self._cells:
                n = len(cell.state_info)
                cell_begin.append(begin_state[p:p + n])
                p += n
        else:
            cell_begin = [None] * num_cells
        states = []
        for i, cell in enumerate(self._cells):
            merge = merge_outputs if i == num_cells - 1 else True
            inputs, cell_states = cell.unroll(
                length, inputs=inputs, begin_state=cell_begin[i],
                input_prefix=input_prefix, layout=layout,
                merge_outputs=merge)
            layout = "NTC" if merge else layout
            states.extend(cell_states)
        return inputs, states


class DropoutCell(BaseRNNCell):
    """Applies dropout to the input (reference rnn_cell.py:749-782)."""

    def __init__(self, dropout, prefix="dropout_", params=None):
        super().__init__(prefix=prefix, params=params)
        self._dropout = dropout

    @property
    def state_info(self):
        return []

    def __call__(self, inputs, states):
        if self._dropout > 0:
            inputs = symbol.Dropout(data=inputs, p=self._dropout)
        return inputs, states


class ModifierCell(BaseRNNCell):
    """Base for cells wrapping another cell (reference rnn_cell.py:783-824)."""

    def __init__(self, base_cell):
        super().__init__()
        base_cell._modified = True
        self.base_cell = base_cell

    @property
    def params(self):
        self._own_params = False
        return self.base_cell.params

    @property
    def state_info(self):
        return self.base_cell.state_info

    def begin_state(self, func=None, **kwargs):
        assert not self._modified
        self.base_cell._modified = False
        begin = self.base_cell.begin_state(func=func, **kwargs)
        self.base_cell._modified = True
        return begin

    def _zeros_states(self, data, batch_axis):
        self.base_cell._modified = False
        out = self.base_cell._zeros_states(data, batch_axis)
        self.base_cell._modified = True
        return out

    def unpack_weights(self, args):
        return self.base_cell.unpack_weights(args)

    def pack_weights(self, args):
        return self.base_cell.pack_weights(args)

    def __call__(self, inputs, states):
        raise NotImplementedError


class ZoneoutCell(ModifierCell):
    """Zoneout regularization (Krueger et al.): randomly preserves previous
    state values (reference rnn_cell.py:825-866)."""

    def __init__(self, base_cell, zoneout_outputs=0.0, zoneout_states=0.0):
        assert not isinstance(base_cell, FusedRNNCell), \
            "FusedRNNCell does not support zoneout; unfuse() first"
        super().__init__(base_cell)
        self.zoneout_outputs = zoneout_outputs
        self.zoneout_states = zoneout_states
        self.prev_output = None

    def reset(self):
        super().reset()
        self.prev_output = None

    def __call__(self, inputs, states):
        cell = self.base_cell
        p_outputs, p_states = self.zoneout_outputs, self.zoneout_states
        next_output, next_states = cell(inputs, states)

        def mask(p, like):
            return symbol.Dropout(data=like * 0.0 + 1.0, p=p)

        prev_output = self.prev_output if self.prev_output is not None \
            else next_output * 0.0
        if p_outputs != 0.0:
            output = mask(p_outputs, next_output) * \
                (next_output - prev_output) + prev_output
        else:
            output = next_output
        if p_states != 0.0:
            states = [mask(p_states, ns) * (ns - s) + s
                      for ns, s in zip(next_states, states)]
        else:
            states = next_states
        self.prev_output = output
        return output, states


class ResidualCell(ModifierCell):
    """Adds the input to the output (He et al.): o' = cell(x) + x."""

    def __call__(self, inputs, states):
        output, states = self.base_cell(inputs, states)
        return output + inputs, states


class BidirectionalCell(BaseRNNCell):
    """Runs l_cell forward and r_cell backward over the sequence; only
    supports unroll (reference rnn_cell.py:867-960)."""

    def __init__(self, l_cell, r_cell, params=None, output_prefix="bi_"):
        super().__init__(prefix="", params=params)
        self._output_prefix = output_prefix
        self._cells = [l_cell, r_cell]

    def unpack_weights(self, args):
        for cell in self._cells:
            args = cell.unpack_weights(args)
        return args

    def pack_weights(self, args):
        for cell in self._cells:
            args = cell.pack_weights(args)
        return args

    def __call__(self, inputs, states):
        raise NotImplementedError(
            "Bidirectional cannot be stepped; use unroll")

    @property
    def state_info(self):
        return sum([c.state_info for c in self._cells], [])

    def begin_state(self, **kwargs):
        assert not self._modified
        return sum([c.begin_state(**kwargs) for c in self._cells], [])

    def _zeros_states(self, data, batch_axis):
        return sum([c._zeros_states(data, batch_axis)
                    for c in self._cells], [])

    def unroll(self, length, inputs=None, begin_state=None, input_prefix="",
               layout="NTC", merge_outputs=None):
        self.reset()
        inputs, ref, batch_axis = _normalize_sequence(
            length, inputs, input_prefix, layout)
        if begin_state is None:
            begin_state = self._zeros_states(ref, batch_axis)
        l_cell, r_cell = self._cells
        n_l = len(l_cell.state_info)
        l_outputs, l_states = l_cell.unroll(
            length, inputs=inputs, begin_state=begin_state[:n_l],
            layout="NTC", merge_outputs=False)
        r_outputs, r_states = r_cell.unroll(
            length, inputs=list(reversed(inputs)),
            begin_state=begin_state[n_l:], layout="NTC",
            merge_outputs=False)
        outputs = [
            symbol.Concat(l_o, r_o, dim=1,
                          name="%st%d" % (self._output_prefix, i))
            for i, (l_o, r_o) in enumerate(
                zip(l_outputs, reversed(r_outputs)))]
        if merge_outputs:
            outputs = [symbol.expand_dims(o, axis=1) for o in outputs]
            outputs = symbol.Concat(*outputs, dim=1)
        return outputs, l_states + r_states



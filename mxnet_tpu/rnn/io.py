"""Bucketed sequence iterators — reference python/mxnet/rnn/io.py:61
(BucketSentenceIter).  Pads each sentence to its bucket length, groups into
per-bucket batches, and emits batches tagged with ``bucket_key`` so
BucketingModule jit-compiles one step function per bucket (the TPU analogue
of the reference's shared-memory per-bucket executors)."""
from __future__ import annotations

import random

import numpy as np

from .. import ndarray as nd
from ..io import DataBatch, DataDesc, DataIter

__all__ = ["BucketSentenceIter"]


class BucketSentenceIter(DataIter):
    """Iterator over sentences (lists of int ids) bucketed by length.

    Parameters mirror the reference: sentences, batch_size, buckets
    (default: auto from the length histogram), invalid_label (padding id),
    data_name/label_name, layout 'NT'.  Label is data shifted one step left
    (next-token prediction)."""

    def __init__(self, sentences, batch_size, buckets=None, invalid_label=-1,
                 data_name="data", label_name="softmax_label", dtype="float32",
                 layout="NT"):
        super().__init__(batch_size)
        if not buckets:
            counts = np.bincount([len(s) for s in sentences])
            buckets = [i for i, n in enumerate(counts)
                       if n >= batch_size]
            if not buckets:
                buckets = [max(len(s) for s in sentences)]
        buckets.sort()
        self.data = [[] for _ in buckets]
        ndiscard = 0
        for sent in sentences:
            buck = np.searchsorted(buckets, len(sent))
            if buck == len(buckets):
                ndiscard += 1
                continue
            buff = np.full((buckets[buck],), invalid_label, dtype=dtype)
            buff[:len(sent)] = sent
            self.data[buck].append(buff)
        self.data = [np.asarray(x, dtype=dtype) for x in self.data]
        self.batch_size = batch_size
        self.buckets = buckets
        self.data_name = data_name
        self.label_name = label_name
        self.dtype = dtype
        self.invalid_label = invalid_label
        self.layout = layout
        self.default_bucket_key = max(buckets)
        self.major_axis = layout.find("N")

        self.idx = []
        for i, buck in enumerate(self.data):
            self.idx.extend([(i, j) for j in
                             range(0, len(buck) - batch_size + 1, batch_size)])
        self.curr_idx = 0
        self.nddata = []
        self.ndlabel = []
        self.reset()

    @property
    def provide_data(self):
        if self.major_axis == 0:
            shape = (self.batch_size, self.default_bucket_key)
        else:
            shape = (self.default_bucket_key, self.batch_size)
        return [DataDesc(self.data_name, shape)]

    @property
    def provide_label(self):
        return [DataDesc(self.label_name, self.provide_data[0].shape)]

    def reset(self):
        self.curr_idx = 0
        random.shuffle(self.idx)
        for buck in self.data:
            np.random.shuffle(buck)
        self.nddata = []
        self.ndlabel = []
        for buck in self.data:
            label = np.empty_like(buck)
            label[:, :-1] = buck[:, 1:]
            label[:, -1] = self.invalid_label
            self.nddata.append(nd.array(buck, dtype=self.dtype))
            self.ndlabel.append(nd.array(label, dtype=self.dtype))

    def next(self):
        if self.curr_idx == len(self.idx):
            raise StopIteration
        i, j = self.idx[self.curr_idx]
        self.curr_idx += 1
        if self.major_axis == 0:
            data = self.nddata[i][j:j + self.batch_size]
            label = self.ndlabel[i][j:j + self.batch_size]
        else:
            data = self.nddata[i][j:j + self.batch_size].T
            label = self.ndlabel[i][j:j + self.batch_size].T
        return DataBatch(
            data=[data], label=[label], pad=0,
            bucket_key=self.buckets[i],
            provide_data=[DataDesc(self.data_name, data.shape)],
            provide_label=[DataDesc(self.label_name, label.shape)])

"""RNN cells and bucketed sequence IO (reference python/mxnet/rnn/)."""
from .rnn_cell import (BaseRNNCell, BidirectionalCell, DropoutCell,
                       FusedRNNCell, GRUCell, LSTMCell, ModifierCell,
                       ResidualCell, RNNCell, RNNParams, SequentialRNNCell,
                       ZoneoutCell)
from .io import BucketSentenceIter

__all__ = ["RNNParams", "BaseRNNCell", "RNNCell", "LSTMCell", "GRUCell",
           "FusedRNNCell", "SequentialRNNCell", "BidirectionalCell",
           "DropoutCell", "ModifierCell", "ZoneoutCell", "ResidualCell",
           "BucketSentenceIter"]

"""Extra imperative-op documents (reference
python/mxnet/ndarray_doc.py). The reference's import-time codegen merges
``NDArrayDoc`` subclass docstrings into generated functions; here op
docstrings come from the registry's declarative ``Param`` docs, and this
registry exists so downstream code subclassing ``NDArrayDoc`` keeps
working — docs registered here are appended at access time via
``get_extra_doc``."""
from __future__ import annotations

_EXTRA = {}


class NDArrayDoc:
    """Subclass as ``class <op>(NDArrayDoc): '<extra doc>'`` (the
    reference pattern); the docstring is recorded for the op name."""

    def __init_subclass__(cls):
        if cls.__doc__:
            _EXTRA[cls.__name__] = cls.__doc__


def get_extra_doc(op_name):
    return _EXTRA.get(op_name, "")

"""mxnet_tpu — a TPU-native deep-learning framework with the API surface of
Apache MXNet 0.9 (reference: /root/reference), built on JAX/XLA.

Import layout mirrors /root/reference/python/mxnet/__init__.py so reference
user scripts port by changing only the import line.
"""
import os as _os

# Honour JAX_PLATFORMS even where the runtime image pins jax_platforms
# (e.g. "axon,cpu") at a layer that ignores the env var; must run before
# the first backend initialization.
if _os.environ.get("JAX_PLATFORMS"):
    import jax as _jax

    try:
        _jax.config.update("jax_platforms", _os.environ["JAX_PLATFORMS"])
    except Exception:  # backend already initialized — leave it be
        pass

# Persistent XLA compilation cache: big fused-step programs (ResNet-50
# fwd+bwd+update is ~30 min of XLA time on a 1-core host) survive process
# restarts. MXNET_COMPILE_CACHE= (empty) disables; JAX_COMPILATION_CACHE_DIR
# still wins if the user set it. Enabled when the PRIMARY (first-listed)
# platform is TPU-shaped: XLA:CPU AOT cache entries embed host machine
# features and can SIGILL on reload, so a cpu-primary config must not cache
# (a cpu *fallback* entry is fine — it only compiles if the primary backend
# failed to load at all). Unset JAX_PLATFORMS → off: the backend is unknown
# until init and this image always pins the var.
_plats = [p.strip() for p in
          _os.environ.get("JAX_PLATFORMS", "").lower().split(",") if p.strip()]
if ("JAX_COMPILATION_CACHE_DIR" not in _os.environ and _plats
        and _plats[0] not in ("cpu", "cuda", "gpu", "rocm")):
    _cache_dir = _os.environ.get(
        "MXNET_COMPILE_CACHE",
        _os.path.join(_os.path.dirname(_os.path.abspath(__file__)),
                      _os.pardir, ".jax_cache"))
    if _cache_dir:
        import jax as _jax

        try:
            _jax.config.update("jax_compilation_cache_dir",
                               _os.path.abspath(_cache_dir))
            _jax.config.update("jax_persistent_cache_min_compile_time_secs",
                               2.0)
        except Exception:
            pass

from . import base
from .base import MXNetError
# telemetry must land before the layers it instruments (callback, faults,
# kvstore, comm_engine, module, io, serving) so their module-level lazy
# handles resolve against a fully initialised registry
from . import telemetry
from .context import Context, cpu, gpu, tpu, current_context, num_gpus, num_tpus
from .attribute import AttrScope
from .name import NameManager, Prefix
from . import random
from . import random as rnd
from . import ndarray
from . import ndarray as nd
from .ndarray import NDArray
from . import symbol
from . import symbol as sym
from .symbol import Symbol
from . import executor
from .executor import Executor
from . import filesystem
from . import io
from . import recordio
from . import initializer
from . import initializer as init
from . import optimizer
from . import lr_scheduler
from . import metric
from . import callback
from . import faults
from . import guardian
from . import kvstore
from . import kvstore as kv
# server-role bootstrap: under DMLC_ROLE=server this serves and exits
# (reference python/mxnet/kvstore_server.py:58 _init_kvstore_server_module)
from . import kvstore_server
from . import comm_engine
# row-sparse values + the sharded-embedding-table plane; already loaded
# (minus its lazy layers) by kvstore_server's row_merge import
from . import sparse
from . import sharding
from . import model
from . import module
from . import module as mod
from . import rnn
from . import operator
from . import parallel
from . import monitor
from . import monitor as mon
from . import visualization
from . import visualization as viz
from . import profiler
from . import image
from . import models
from . import contrib
from .predictor import Predictor, load_exported
from . import serving
from . import generation
from .ops import register_pallas_op, Param
from . import rtc
from . import torch as th
from . import caffe
from . import checkpoint
from . import notebook
from . import log
from . import misc
from . import libinfo
from .libinfo import __version__
from . import executor_manager

from . import base
from .base import MXNetError
from .context import Context, cpu, gpu, tpu, current_context, num_gpus, num_tpus
from .attribute import AttrScope
from .name import NameManager, Prefix
from . import random
from . import ndarray
from . import ndarray as nd
from .ndarray import NDArray
from . import symbol
from . import symbol as sym
from .symbol import Symbol
from . import executor
from .executor import Executor

"""mxnet_tpu — a TPU-native deep-learning framework with the API surface of
Apache MXNet 0.9 (reference: /root/reference), built on JAX/XLA.

Import layout mirrors /root/reference/python/mxnet/__init__.py so reference
user scripts port by changing only the import line.
"""
import os as _os

# Honour JAX_PLATFORMS even where the runtime image pins jax_platforms
# (e.g. "axon,cpu") at a layer that ignores the env var; must run before
# the first backend initialization.
if _os.environ.get("JAX_PLATFORMS"):
    import jax as _jax

    try:
        _jax.config.update("jax_platforms", _os.environ["JAX_PLATFORMS"])
    except Exception:  # backend already initialized — leave it be
        pass

from . import base
from .base import MXNetError
from .context import Context, cpu, gpu, tpu, current_context, num_gpus, num_tpus
from .attribute import AttrScope
from .name import NameManager, Prefix
from . import random
from . import random as rnd
from . import ndarray
from . import ndarray as nd
from .ndarray import NDArray
from . import symbol
from . import symbol as sym
from .symbol import Symbol
from . import executor
from .executor import Executor
from . import io
from . import recordio
from . import initializer
from . import initializer as init
from . import optimizer
from . import lr_scheduler
from . import metric
from . import callback
from . import kvstore
from . import kvstore as kv
# server-role bootstrap: under DMLC_ROLE=server this serves and exits
# (reference python/mxnet/kvstore_server.py:58 _init_kvstore_server_module)
from . import kvstore_server
from . import model
from . import module
from . import module as mod
from . import rnn
from . import operator
from . import parallel
from . import monitor
from . import monitor as mon
from . import visualization
from . import visualization as viz
from . import profiler
from . import image
from . import models
from . import contrib
from .predictor import Predictor, load_exported
from .ops import register_pallas_op, Param
from . import rtc
from . import torch as th
from . import checkpoint
from . import notebook
from . import log
from . import misc
from . import libinfo
from .libinfo import __version__
from . import executor_manager

"""comm_engine — dependency-scheduled asynchronous kvstore communication.

The reference's signature perf feature is its async engine: ``Push``
returns immediately, per-variable ordering is tracked by the engine, and
``WaitForVar``/``WaitForAll`` are the only sync points
(/root/reference/src/engine/threaded_engine.h).  The kvstore rides that
engine, so a ``push``/``pull`` with ``priority=`` set overlaps backward
compute and the next batch's host-side prep.  Our port executed fully
synchronously; this module restores the contract at the kvstore layer:

* :class:`CommEngine` — a small dependency tracker: every operation names
  the keys it touches; ops on the same key run in FIFO submission order,
  ops on disjoint keys run concurrently on a worker pool
  (``MXNET_KVSTORE_ASYNC_THREADS``), and among *ready* ops the highest
  ``priority`` wins (Module passes ``priority=-index``, so front-layer
  pulls — the ones gating the next forward — jump the queue).
* :class:`AsyncKVStore` — wraps any KVStore flavor and turns push/pull
  into engine submissions.  Completion is observed through an explicit
  ``wait(keys)`` / ``wait_all()`` barrier or *implicitly* when a
  pulled-into NDArray is read (``asnumpy``/``wait_to_read`` — the
  reference's WaitToRead contract, installed as a read guard in
  ``ndarray.py``).
* Gradient coalescing — keys whose payload is under
  ``MXNET_KVSTORE_BUCKET_BYTES`` are packed into fused bucket messages
  when the wrapped store speaks the batched wire protocol
  (``push_multi``/``pull_multi``, kvstore.py); the same
  small-transfer amortization FusionStitching applies to tiny GPU
  kernels, applied to the DCN/ps transport.

Bit-compatibility: per-key FIFO makes the per-key update sequence
identical to the synchronous path, so async training reaches bit-identical
weights (tests/test_comm_engine.py equivalence test).  The wrapper stays
on top of PR 2's crash-tolerant transport — buckets travel under ONE
idempotency token, so exactly-once replay covers the whole bucket.
"""
from __future__ import annotations

import heapq
import os
import threading
import time
from collections import deque
from typing import Dict, List, Optional

import numpy as np

from .base import MXNetError, register_env
from .ndarray import NDArray
from . import ndarray as _nd_mod
from . import profiler as _prof
from .kvstore import KVStore, _key_list, _val_list
from .telemetry import tracer as _tracer

__all__ = ["CommEngine", "AsyncKVStore", "CommMetrics", "make_async",
           "maybe_async"]

register_env("MXNET_KVSTORE_ASYNC", 1, int,
             "Wrap the Module kvstore update path in the async comm "
             "engine (0 restores the fully synchronous push/pull loop).")
register_env("MXNET_KVSTORE_ASYNC_THREADS", 2, int,
             "Worker threads in the kvstore comm engine.")
register_env("MXNET_KVSTORE_BUCKET_BYTES", 65536, int,
             "Coalesce pushes/pulls of keys under this many bytes into "
             "fused bucket RPCs (0 disables bucketing).")


# ---------------------------------------------------------------------------
# metrics (the serving-style counter idiom, serving/metrics.py)
# ---------------------------------------------------------------------------
class CommMetrics:
    """Comm-plane counters on the shared telemetry registry.

    Storage is a per-store :class:`telemetry.Registry` (``mxtpu_comm_*``
    series, registered as a collector so they appear in the global
    Prometheus render); ``snapshot()`` keeps the original dict-returning
    API as a view over it, so ``kv.comm_stats()`` callers see the same
    keys as before."""

    _COUNTERS = ("pushes", "pulls", "bytes_pushed", "bytes_pulled",
                 "bucket_flushes", "bucket_keys", "wait_calls")

    def __init__(self):
        from . import telemetry as _tm

        self._reg = _tm.Registry()
        self._c = {k: self._reg.counter("mxtpu_comm_%s" % k)
                   for k in self._COUNTERS}
        self._c["wait_ms_total"] = self._reg.counter(
            "mxtpu_comm_wait_ms_total",
            "Total time blocked in engine waits (ms).")
        self._fill_sum = self._reg.counter(
            "mxtpu_comm_bucket_fill_ratio_sum",
            "Sum of per-flush bucket fill ratios (÷ flushes = avg fill).")
        self._wait_hist = self._reg.histogram(
            "mxtpu_comm_wait_ms_hist",
            "Per-call engine wait time (ms).",
            start=0.05, factor=4.0, count=10)
        _tm.register_collector(self)

    def add(self, name, n=1):
        self._c[name].inc(n)

    def note_bucket(self, nkeys, nbytes, capacity):
        self._c["bucket_flushes"].inc()
        self._c["bucket_keys"].inc(nkeys)
        if capacity > 0:
            self._fill_sum.inc(min(1.0, nbytes / float(capacity)))

    def note_wait(self, seconds):
        ms = seconds * 1e3
        self._c["wait_calls"].inc()
        self._c["wait_ms_total"].inc(ms)
        self._wait_hist.observe(ms)

    def add_live_gauge(self, name, fn, doc=""):
        """Register a callback gauge (queue depth, inflight RPCs) read at
        render/snapshot time."""
        self._reg.gauge("mxtpu_comm_%s" % name, doc, fn=fn)

    def render_prometheus(self):
        return self._reg.render_prometheus()

    def snapshot(self):
        d = {k: c.value for k, c in self._c.items()}
        flushes = d["bucket_flushes"]
        d["bucket_fill_ratio"] = (self._fill_sum.value / flushes
                                  if flushes else 0.0)
        d["avg_wait_ms"] = (d["wait_ms_total"] / d["wait_calls"]
                            if d["wait_calls"] else 0.0)
        return d


# ---------------------------------------------------------------------------
# the dependency-tracking dispatcher
# ---------------------------------------------------------------------------
class _Op:
    __slots__ = ("fn", "keys", "priority", "seq", "label", "nleft",
                 "event", "exc", "cleanup", "flow_id")

    def __init__(self, fn, keys, priority, seq, label, cleanup):
        self.fn = fn
        self.keys = keys          # unique, in submission order
        self.priority = priority
        self.seq = seq
        self.label = label
        self.cleanup = cleanup
        self.nleft = 0            # chains where a predecessor still runs
        self.event = threading.Event()
        self.exc = None
        self.flow_id = None       # trace flow linking submit -> execute


class CommEngine:
    """Per-key FIFO chains + a priority heap over the ready set + a worker
    pool: the reference ThreadedEngine's Push/WaitForVar contract scoped
    to kvstore traffic.  An op is *ready* when it is at the head of every
    key chain it participates in; among ready ops the highest ``priority``
    (FIFO within a priority, by submission seq) runs first."""

    def __init__(self, num_threads=None, name="kvcomm"):
        if num_threads is None:
            num_threads = int(os.environ.get(
                "MXNET_KVSTORE_ASYNC_THREADS", "2"))
        self.num_threads = max(1, int(num_threads))
        self._lock = threading.Lock()
        self._ready_cv = threading.Condition(self._lock)
        self._idle_cv = threading.Condition(self._lock)
        self._chains: Dict[object, deque] = {}
        self._ready: List[tuple] = []   # heap of (-priority, seq, op)
        self._seq = 0
        self._outstanding = 0
        self.peak_outstanding = 0
        self._failures: List[_Op] = []
        self._stop = False
        self._threads = [
            threading.Thread(target=self._worker, daemon=True,
                             name="%s-%d" % (name, i))
            for i in range(self.num_threads)]
        for t in self._threads:
            t.start()

    # -- submission --------------------------------------------------------
    def submit(self, fn, keys, priority=0, label=None, cleanup=None) -> _Op:
        """Enqueue ``fn`` touching ``keys``; returns the op handle (its
        ``event`` is set on completion, ``exc`` carries a failure)."""
        ukeys = list(dict.fromkeys(keys))
        with self._lock:
            if self._stop:
                raise MXNetError("CommEngine is shut down")
            self._seq += 1
            op = _Op(fn, ukeys, priority, self._seq, label, cleanup)
            for k in ukeys:
                chain = self._chains.setdefault(k, deque())
                chain.append(op)
                if len(chain) > 1:
                    op.nleft += 1
            self._outstanding += 1
            if self._outstanding > self.peak_outstanding:
                self.peak_outstanding = self._outstanding
            if op.nleft == 0:
                heapq.heappush(self._ready, (-op.priority, op.seq, op))
                self._ready_cv.notify()
        if _tracer.active():
            # flow arrow from the submitting thread to the worker-thread
            # span executing the op (the finish lands in _worker)
            op.flow_id = "comm-%d-%d" % (os.getpid(), op.seq)
            _tracer.flow_event(op.label or "comm.op", "s", op.flow_id)
        return op

    def outstanding(self):
        with self._lock:
            return self._outstanding

    # -- worker ------------------------------------------------------------
    def _worker(self):
        while True:
            with self._lock:
                while not self._ready and not self._stop:
                    self._ready_cv.wait()
                if self._stop and not self._ready:
                    return
                _, _, op = heapq.heappop(self._ready)
            try:
                with _prof.Frame(op.label or "comm.op", "comm"):
                    if op.flow_id is not None:
                        _tracer.flow_event(op.label or "comm.op", "f",
                                           op.flow_id)
                    op.fn()
            except BaseException as e:  # recorded, raised at the barrier
                op.exc = e
            if op.cleanup is not None:
                try:
                    op.cleanup(op)
                except Exception:
                    pass
            with self._lock:
                for k in op.keys:
                    chain = self._chains[k]
                    chain.popleft()  # == op: it was the head everywhere
                    if not chain:
                        del self._chains[k]
                    else:
                        nxt = chain[0]
                        nxt.nleft -= 1
                        if nxt.nleft == 0:
                            heapq.heappush(self._ready,
                                           (-nxt.priority, nxt.seq, nxt))
                            self._ready_cv.notify()
                self._outstanding -= 1
                if op.exc is not None:
                    self._failures.append(op)
                op.event.set()
                if self._outstanding == 0:
                    self._idle_cv.notify_all()

    # -- barriers ----------------------------------------------------------
    def wait(self, keys):
        """Block until every submitted op touching ``keys`` completed
        (the engine's WaitForVar)."""
        tails = []
        with self._lock:
            for k in keys:
                chain = self._chains.get(k)
                if chain:
                    tails.append(chain[-1])
        for op in tails:
            op.event.wait()
        self.raise_failures()

    def wait_all(self, timeout=None):
        """Block until the engine drains (WaitForAll), then surface the
        first recorded failure.  With ``timeout`` (seconds) the wait is
        bounded: returns False if ops were still outstanding when it
        expired (nothing is cancelled), True when drained."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._idle_cv:
            while self._outstanding:
                if deadline is None:
                    self._idle_cv.wait()
                    continue
                left = deadline - time.monotonic()
                if left <= 0:
                    return False
                self._idle_cv.wait(left)
        self.raise_failures()
        return True

    def raise_failures(self):
        with self._lock:
            if not self._failures:
                return
            failed, self._failures = self._failures[:], []
        first = failed[0]
        raise MXNetError(
            "async kvstore op %r failed: %s: %s%s"
            % (first.label or "comm.op", type(first.exc).__name__, first.exc,
               (" (+%d more failures)" % (len(failed) - 1))
               if len(failed) > 1 else "")) from first.exc

    def shutdown(self):
        with self._lock:
            self._stop = True
            self._ready_cv.notify_all()
        for t in self._threads:
            t.join(timeout=5)


# ---------------------------------------------------------------------------
# implicit completion: the NDArray read guard (WaitToRead contract)
# ---------------------------------------------------------------------------
class _ReadTicket:
    """Marks NDArrays an in-flight (or still-buffered) pull writes into."""

    __slots__ = ("owner", "ids", "op")

    def __init__(self, owner, ids):
        self.owner = owner
        self.ids = ids
        self.op = None


_READS: Dict[int, _ReadTicket] = {}
_READS_LOCK = threading.Lock()


def _read_guard(arr):
    ticket = _READS.get(id(arr))
    if ticket is not None:
        ticket.owner._resolve_ticket(ticket)


def _install_read_guard():
    if _nd_mod._async_read_guard is None:
        _nd_mod._async_read_guard = _read_guard


def _register_ticket(ticket):
    with _READS_LOCK:
        for aid in ticket.ids:
            _READS[aid] = ticket


def _drop_ticket(ticket):
    with _READS_LOCK:
        for aid in ticket.ids:
            if _READS.get(aid) is ticket:
                del _READS[aid]


# ---------------------------------------------------------------------------
# the async wrapper
# ---------------------------------------------------------------------------
class _PendingEntry:
    __slots__ = ("key", "vals", "outs", "priority", "nbytes", "ticket")

    def __init__(self, key, vals=None, outs=None, priority=0, nbytes=0,
                 ticket=None):
        self.key = key
        self.vals = vals
        self.outs = outs
        self.priority = priority
        self.nbytes = nbytes
        self.ticket = ticket


def _est_bytes(arr):
    size = 1
    for d in arr.shape:
        size *= int(d)
    return size * np.dtype(arr.dtype).itemsize


class AsyncKVStore(KVStore):
    """Non-blocking facade over any KVStore flavor: ``push``/``pull``
    return immediately (engine submissions with per-key FIFO + priority),
    ``wait``/``wait_all`` are the explicit barriers, and reading a
    pulled-into NDArray blocks implicitly.  Control-plane calls (init,
    set_optimizer, barrier, optimizer-state IO) drain the engine first,
    so PR 2's recovery/idempotency semantics are untouched.

    Keys whose payload is under ``bucket_bytes`` coalesce into fused
    multi-key RPCs when the wrapped store implements
    ``push_multi``/``pull_multi`` (dist_async does); ``bucket_bytes=0``
    disables coalescing."""

    def __init__(self, kv, num_threads=None, bucket_bytes=None):
        if isinstance(kv, AsyncKVStore):
            raise MXNetError("kvstore is already async")
        self._kv = kv
        self._type = kv.type
        if num_threads is None and "_sync" in kv.type:
            # collective push path: cross-host collective issue order must
            # be identical on every worker — one thread keeps it serial
            num_threads = 1
        self._engine = CommEngine(num_threads)
        if bucket_bytes is None:
            bucket_bytes = int(os.environ.get(
                "MXNET_KVSTORE_BUCKET_BYTES", "65536"))
        can_bucket = hasattr(kv, "push_multi") and hasattr(kv, "pull_multi")
        self._bucket_bytes = int(bucket_bytes) if can_bucket else 0
        self._buf_lock = threading.RLock()
        self._push_buf: List[_PendingEntry] = []
        self._push_keys = set()
        self._push_bytes = 0
        self._pull_buf: List[_PendingEntry] = []
        self._pull_keys = set()
        self._pull_bytes = 0
        self.metrics = CommMetrics()
        # live gauges: sampled at Prometheus-render/snapshot time
        import weakref as _weakref

        eng = self._engine
        self.metrics.add_live_gauge(
            "queue_depth", eng.outstanding,
            "Ops queued or running in the comm engine.")
        self.metrics.add_live_gauge(
            "queue_peak", lambda e=eng: e.peak_outstanding,
            "High-watermark of engine queue depth.")
        _wself = _weakref.ref(self)

        def _inflight():
            s = _wself()
            clients = getattr(s._kv, "_clients", None) if s else None
            return sum(len(getattr(c, "_inflight", ()))
                       for c in clients) if clients else 0

        self.metrics.add_live_gauge(
            "inflight_rpcs", _inflight,
            "Pipelined RPCs awaiting replies across transport clients.")
        _install_read_guard()

    # -- identity ----------------------------------------------------------
    @property
    def inner(self):
        return self._kv

    @property
    def rank(self):
        return self._kv.rank

    @property
    def num_workers(self):
        return self._kv.num_workers

    def __getattr__(self, name):
        # anything not overridden (e.g. dist internals tests poke, or
        # flavor-specific extras) falls through to the wrapped store
        return getattr(self.__dict__["_kv"], name)

    # -- bucketing ---------------------------------------------------------
    def _flush_pushes_locked(self):
        if not self._push_buf:
            return
        entries, self._push_buf = self._push_buf, []
        self._push_keys = set()
        nbytes, self._push_bytes = self._push_bytes, 0
        keys = [e.key for e in entries]
        pri = max(e.priority for e in entries)
        if len(entries) == 1:
            e = entries[0]
            fn = (lambda kv=self._kv, e=e:
                  kv.push(e.key, e.vals, priority=e.priority))
            label = "comm.push"
        else:
            pairs = [(e.key, e.vals) for e in entries]
            fn = lambda kv=self._kv, pairs=pairs: kv.push_multi(pairs)
            label = "comm.push_bucket"
            self.metrics.note_bucket(len(entries), nbytes,
                                     self._bucket_bytes)
        self._engine.submit(fn, keys, pri, label=label)

    def _flush_pulls_locked(self):
        if not self._pull_buf:
            return
        entries, self._pull_buf = self._pull_buf, []
        self._pull_keys = set()
        nbytes, self._pull_bytes = self._pull_bytes, 0
        keys = [e.key for e in entries]
        pri = max(e.priority for e in entries)
        tickets = [e.ticket for e in entries if e.ticket is not None]

        def cleanup(op, tickets=tickets):
            for t in tickets:
                _drop_ticket(t)

        if len(entries) == 1:
            e = entries[0]
            fn = (lambda kv=self._kv, e=e:
                  kv.pull(e.key, e.outs, priority=e.priority))
            label = "comm.pull"
        else:
            pairs = [(e.key, e.outs) for e in entries]
            fn = lambda kv=self._kv, pairs=pairs: kv.pull_multi(pairs)
            label = "comm.pull_bucket"
            self.metrics.note_bucket(len(entries), nbytes,
                                     self._bucket_bytes)
        op = self._engine.submit(fn, keys, pri, label=label,
                                 cleanup=cleanup)
        for t in tickets:
            t.op = op

    def _flush_locked(self):
        self._flush_pushes_locked()
        self._flush_pulls_locked()

    def flush(self):
        """Submit any coalescing buffers to the engine (non-blocking)."""
        with self._buf_lock:
            self._flush_locked()

    def _resolve_ticket(self, ticket):
        """Read-guard path: an NDArray a pending pull targets is being
        read — flush the pull if still buffered, then wait it out."""
        if ticket.op is None:
            with self._buf_lock:
                if ticket.op is None:
                    self._flush_pulls_locked()
        op = ticket.op
        if op is None:
            return
        if not op.event.is_set():
            t0 = time.perf_counter()
            op.event.wait()
            self.metrics.note_wait(time.perf_counter() - t0)
        if op.exc is not None:
            self._engine.raise_failures()

    # -- data plane --------------------------------------------------------
    def push(self, key, value, priority=0):
        keys, _ = _key_list(key)
        vals = _val_list(value, len(keys))
        for k, vlist in zip(keys, vals):
            # snapshot now: jax arrays are immutable, so holding the
            # current buffers makes the deferred execution race-free even
            # when the caller rebinds the gradient NDArrays next batch
            snap = [v if isinstance(v, NDArray) else
                    NDArray(np.asarray(v)) for v in vlist]
            snap = [NDArray(v._data, v.context) for v in snap]
            nbytes = _est_bytes(snap[0])
            self.metrics.add("pushes")
            self.metrics.add("bytes_pushed", nbytes)
            with self._buf_lock:
                if k in self._pull_keys:
                    self._flush_pulls_locked()  # keep per-key FIFO
                if 0 < nbytes <= self._bucket_bytes:
                    self._push_buf.append(
                        _PendingEntry(k, vals=snap, priority=priority,
                                      nbytes=nbytes))
                    self._push_keys.add(k)
                    self._push_bytes += nbytes
                    if self._push_bytes >= self._bucket_bytes:
                        self._flush_pushes_locked()
                else:
                    if k in self._push_keys:
                        self._flush_pushes_locked()
                    self._engine.submit(
                        lambda kv=self._kv, k=k, snap=snap, p=priority:
                        kv.push(k, snap, priority=p),
                        [k], priority, label="comm.push")

    def pull(self, key, out=None, priority=0):
        keys, _ = _key_list(key)
        outs = _val_list(out, len(keys))
        for k, olist in zip(keys, outs):
            nbytes = _est_bytes(olist[0])
            self.metrics.add("pulls")
            self.metrics.add("bytes_pulled", nbytes)
            ticket = _ReadTicket(self, [id(o) for o in olist])
            _register_ticket(ticket)
            with self._buf_lock:
                if k in self._push_keys:
                    self._flush_pushes_locked()  # pull observes the push
                if k in self._pull_keys:
                    self._flush_pulls_locked()
                if 0 < nbytes <= self._bucket_bytes:
                    self._pull_buf.append(
                        _PendingEntry(k, outs=olist, priority=priority,
                                      nbytes=nbytes, ticket=ticket))
                    self._pull_keys.add(k)
                    self._pull_bytes += nbytes
                    if self._pull_bytes >= self._bucket_bytes:
                        self._flush_pulls_locked()
                else:
                    op = self._engine.submit(
                        lambda kv=self._kv, k=k, olist=olist, p=priority:
                        kv.pull(k, olist, priority=p),
                        [k], priority, label="comm.pull",
                        cleanup=lambda op, t=ticket: _drop_ticket(t))
                    ticket.op = op

    # -- barriers ----------------------------------------------------------
    def wait(self, keys=None):
        """Block until ops touching ``keys`` (or everything, when None)
        completed — the engine's WaitForVar/WaitForAll surface."""
        if keys is None:
            return self.wait_all()
        keys, _ = _key_list(keys)
        self.flush()
        t0 = time.perf_counter()
        self._engine.wait(keys)
        self.metrics.note_wait(time.perf_counter() - t0)

    def wait_all(self, timeout=None):
        self.flush()
        t0 = time.perf_counter()
        done = self._engine.wait_all(timeout)
        self.metrics.note_wait(time.perf_counter() - t0)
        return done

    def drain(self, timeout=None):
        """Preemption drain (docs/how_to/fault_tolerance.md §elasticity):
        flush the coalescing buffers and wait — bounded by ``timeout``
        seconds — for every in-flight op, swallowing op failures: a
        worker about to ``leave`` must get its final grads out if it
        can, not die on a push error mid-teardown.  Returns True when
        the engine drained."""
        try:
            return bool(self.wait_all(timeout))
        except MXNetError:
            return True  # drained; pending failures surfaced and dropped

    def sparse_plane(self):
        """Build (once) the row-sparse parameter plane bound to this
        engine: sparse pushes ride the same per-key FIFO chains as dense
        traffic, so they pipeline with compute and a pull always observes
        the pushes submitted before it (docs/how_to/sparse.md)."""
        plane = self.__dict__.get("_sparse_plane")
        if plane is None:
            from .sparse.plane import SparseParamPlane

            plane = SparseParamPlane(self)
            self.__dict__["_sparse_plane"] = plane
        return plane

    # -- control plane (drain first: ordering + recovery semantics) --------
    def init(self, key, value):
        self.wait_all()
        self._kv.init(key, value)

    def set_optimizer(self, optimizer):
        self.wait_all()
        self._kv.set_optimizer(optimizer)

    def _set_updater(self, updater):
        self.wait_all()
        self._kv._set_updater(updater)

    def _barrier(self):
        self.wait_all()
        self._kv._barrier()

    def _send_command_to_servers(self, head, body):
        self.wait_all()
        self._kv._send_command_to_servers(head, body)

    def save_optimizer_states(self, fname):
        self.wait_all()
        self._kv.save_optimizer_states(fname)

    def load_optimizer_states(self, fname):
        self.wait_all()
        self._kv.load_optimizer_states(fname)

    def get_num_dead_node(self, node_id=0, timeout=None):
        return self._kv.get_num_dead_node(node_id, timeout) \
            if hasattr(self._kv, "get_num_dead_node") else 0

    # -- observability -----------------------------------------------------
    def comm_stats(self):
        """Snapshot of the comm counters + live gauges: engine queue
        depth/peak and (dist flavors) transport in-flight requests."""
        d = self.metrics.snapshot()
        d["queue_depth"] = self._engine.outstanding()
        d["queue_peak"] = self._engine.peak_outstanding
        clients = getattr(self._kv, "_clients", None)
        if clients:
            d["inflight_requests"] = sum(
                len(getattr(c, "_inflight", ())) for c in clients)
            d["inflight_peak"] = max(
                getattr(c, "max_inflight", 0) for c in clients)
        return d

    # -- lifecycle ---------------------------------------------------------
    def close(self):
        try:
            self.wait_all()
        except MXNetError:
            pass  # teardown: pending failures already surfaced or moot
        self._engine.shutdown()
        if hasattr(self._kv, "close"):
            self._kv.close()

    def __del__(self):
        try:
            self._engine.shutdown()
        except Exception:
            pass


def make_async(kv, num_threads=None, bucket_bytes=None) -> AsyncKVStore:
    """Wrap ``kv`` in the comm engine; a no-op on an already-async store."""
    if isinstance(kv, AsyncKVStore):
        return kv
    return AsyncKVStore(kv, num_threads=num_threads,
                        bucket_bytes=bucket_bytes)


def maybe_async(kv):
    """Module's policy hook: wrap unless ``MXNET_KVSTORE_ASYNC=0``."""
    if os.environ.get("MXNET_KVSTORE_ASYNC", "1") == "0":
        return kv
    if kv is None or isinstance(kv, AsyncKVStore):
        return kv
    return AsyncKVStore(kv)

"""ModelManager — actuates placement plans over real InferenceServers.

The planner decides *what should be resident*; the manager makes it so:

* **fault_in** — build a warm :class:`InferenceServer` via
  ``from_checkpoint(attach_aot=True)`` (the AOT bundle beside the
  checkpoint makes every bucket warm by deserialization — zero
  cold-bucket runs), register it in the shared replica registry with
  ``{"model", "tenant"}`` meta so model-scoped routers adopt it, and
  start its heartbeat.
* **page_out** — save the server's AOT bundle (executables + tuning
  entries travel with the checkpoint; the NEXT fault-in warms from it),
  deregister, then ``stop()`` — which releases the device-resident
  params and executables (satellite fix: a paged-out model must not pin
  device memory; ``mxtpu_platform_resident_bytes`` proves it fell).
* **migrate** — fault the model in at its new device, then page the old
  copy out: capacity never dips mid-migration.
* **replan** — one planner pass + actuation, page-outs first (freeing
  the bytes the fault-ins then claim), with a minimum-residency
  anti-thrash guard so diurnal demand wiggle cannot flap a model in and
  out every tick.

Every actuation is a ``faults`` dotted op (``platform.fault_in`` /
``platform.page_out`` / ``platform.migrate``) and counts in the
model-labeled platform telemetry.
"""
from __future__ import annotations

import math
import threading
import time
from typing import Dict, Optional

from .. import faults
from .. import telemetry as _telemetry
from ..base import MXNetError, env, register_env
from ..serving.registry import ReplicaRegistry, start_heartbeater
from ..serving.server import InferenceServer
from .planner import DevicePool, PlacementPlanner
from .spec import ModelSpec

__all__ = ["ModelManager", "PlatformMetrics"]

register_env("MXNET_PLATFORM_REPLAN_MS", 2000.0, float,
             "Background placement-replan period of a started "
             "ModelManager (0 disables the loop; replan() stays "
             "callable).")
register_env("MXNET_PLATFORM_DEMAND_HALFLIFE_S", 30.0, float,
             "Half-life of the per-model demand EWMA the placement "
             "planner scores against — shorter chases diurnal load "
             "faster, longer resists thrash.")
register_env("MXNET_PLATFORM_MIN_RESIDENT_S", 5.0, float,
             "Anti-thrash guard: a model faulted in more recently than "
             "this is not paged out by a replan (explicit page_out() "
             "calls are not gated).")


class PlatformMetrics:
    """Model-labeled platform telemetry (a registry collector)."""

    def __init__(self):
        reg = self._registry = _telemetry.Registry()
        self.fault_ins = reg.labeled_counter(
            "mxtpu_platform_fault_ins_total", "model")
        self.page_outs = reg.labeled_counter(
            "mxtpu_platform_page_outs_total", "model")
        self.migrations = reg.labeled_counter(
            "mxtpu_platform_migrations_total", "model")
        self.plans = reg.counter("mxtpu_platform_plans_total")
        self.g_resident = reg.gauge("mxtpu_platform_resident_models")
        self.g_registered = reg.gauge("mxtpu_platform_registered_models")
        self.g_resident_bytes = reg.gauge("mxtpu_platform_resident_bytes")
        _telemetry.register_collector(self)

    def render_prometheus(self):
        return self._registry.render_prometheus()


class ModelManager:
    """Owns the model catalog, the demand signal, and the live servers.

    Parameters
    ----------
    pool : DevicePool
        The memory budget placements pack against.
    registry : ReplicaRegistry, optional
        Shared replica live-set; created (in-process) when absent.
        Every faulted-in server registers here with model/tenant meta.
    planner : PlacementPlanner, optional
        Defaults to a fresh planner over ``pool``.
    """

    def __init__(self, pool: DevicePool, registry=None,
                 planner: Optional[PlacementPlanner] = None):
        self.pool = pool
        self.registry = ReplicaRegistry() if registry is None else registry
        self.planner = PlacementPlanner(pool) if planner is None else planner
        self.metrics = PlatformMetrics()
        self._lock = threading.RLock()
        self._specs: Dict[str, ModelSpec] = {}
        self._servers: Dict[str, InferenceServer] = {}
        self._beat_stops: Dict[str, object] = {}
        self._placement: Dict[str, int] = {}
        self._resident_since: Dict[str, float] = {}
        self._demand: Dict[str, float] = {}
        self._demand_t: Dict[str, float] = {}
        self._fault_in_ms: Dict[str, float] = {}
        self._replica_seq = 0
        self._halflife_s = env("MXNET_PLATFORM_DEMAND_HALFLIFE_S", 30.0,
                               float)
        self._min_resident_s = env("MXNET_PLATFORM_MIN_RESIDENT_S", 5.0,
                                   float)
        self._loop_stop = threading.Event()
        self._loop_thread = None
        self._closed = False

    # -- catalog -----------------------------------------------------------
    def register_model(self, spec: ModelSpec):
        with self._lock:
            if spec.name in self._specs:
                raise MXNetError("model %r already registered" % spec.name)
            self._specs[spec.name] = spec
            self._demand.setdefault(spec.name, 0.0)
        self.metrics.g_registered.set(len(self._specs))
        _telemetry.log_event("platform_register", model=spec.name,
                             tenant=spec.tenant, slo=spec.slo)
        return spec

    def spec(self, name: str) -> ModelSpec:
        with self._lock:
            spec = self._specs.get(name)
        if spec is None:
            raise MXNetError("unknown model %r (registered: %s)"
                             % (name, sorted(self._specs)))
        return spec

    def models(self):
        with self._lock:
            return sorted(self._specs)

    # -- demand signal -----------------------------------------------------
    def record_demand(self, name: str, n: float = 1.0):
        """Fold ``n`` requests into the model's demand EWMA (decayed by
        the configured half-life since the last observation)."""
        now = time.monotonic()
        with self._lock:
            cur = self._decayed_demand_locked(name, now)
            self._demand[name] = cur + float(n)
            self._demand_t[name] = now

    def _decayed_demand_locked(self, name, now):
        last = self._demand_t.get(name)
        cur = self._demand.get(name, 0.0)
        if last is None or self._halflife_s <= 0:
            return cur
        return cur * math.pow(0.5, (now - last) / self._halflife_s)

    def demand(self) -> Dict[str, float]:
        now = time.monotonic()
        with self._lock:
            return {n: self._decayed_demand_locked(n, now)
                    for n in self._specs}

    # -- actuation ---------------------------------------------------------
    def _next_replica_name(self, model):
        self._replica_seq += 1
        return "%s/r%d" % (model, self._replica_seq)

    def fault_in(self, name: str, device: Optional[int] = None):
        """Materialize one model as a live warm replica; returns the
        server.  Idempotent for already-resident models."""
        spec = self.spec(name)
        with self._lock:
            if name in self._servers:
                return self._servers[name]
        faults.fire("platform.fault_in")
        t0 = time.monotonic()
        kwargs = dict(spec.server_kwargs)
        if spec.generator_spec is not None:
            kwargs.setdefault("generator_spec", dict(spec.generator_spec))
        server = InferenceServer.from_checkpoint(
            spec.prefix, spec.epoch, spec.input_shapes, attach_aot=True,
            **kwargs)
        self._observe_exec_bytes(spec, server)
        rep_name = None
        with self._lock:
            if name in self._servers:  # raced another fault_in
                srv = self._servers[name]
            else:
                rep_name = self._next_replica_name(name)
                self._servers[name] = server
                self._placement[name] = 0 if device is None else int(device)
                self._resident_since[name] = time.monotonic()
                srv = server
        if rep_name is None:
            server.stop(drain=False)
            return srv
        self._beat_stops[name] = start_heartbeater(
            self.registry, rep_name, server,
            meta={"model": name, "tenant": spec.tenant})
        dt_ms = (time.monotonic() - t0) * 1e3
        self._fault_in_ms[name] = dt_ms
        self.metrics.fault_ins.inc(name)
        self._update_gauges()
        _telemetry.log_event("platform_fault_in", model=name,
                             device=self._placement[name],
                             ms=round(dt_ms, 1),
                             cold_runs=server.cold_bucket_runs())
        return server

    def page_out(self, name: str):
        """Demote one model to its on-disk AOT bundle and release its
        device memory.  No-op for non-resident models."""
        with self._lock:
            server = self._servers.pop(name, None)
            stop_beat = self._beat_stops.pop(name, None)
            self._placement.pop(name, None)
            self._resident_since.pop(name, None)
        if server is None:
            return
        faults.fire("platform.page_out")
        spec = self.spec(name)
        # bundle BEFORE stop: compiled_entries() is empty once the
        # predictors are released
        try:
            if server.compiled_entries():
                server.save_aot_bundle(spec.prefix, spec.epoch)
        except Exception:
            pass  # bundle refresh is best-effort; next fault-in still
            # warms from the previous bundle (or compiles)
        if stop_beat is not None:
            stop_beat()
        server.stop(drain=True)
        self.metrics.page_outs.inc(name)
        self._update_gauges()
        _telemetry.log_event("platform_page_out", model=name,
                             resident_bytes=server.resident_bytes())

    def migrate(self, name: str, device: int):
        """Move a resident model to another device (fault-in first, so
        capacity never dips)."""
        faults.fire("platform.migrate")
        with self._lock:
            if name not in self._servers:
                return self.fault_in(name, device)
        self.page_out(name)
        server = self.fault_in(name, device)
        self.metrics.migrations.inc(name)
        return server

    def replan(self):
        """One planner pass + actuation; returns the plan."""
        with self._lock:
            specs = dict(self._specs)
            current = dict(self._placement)
            since = dict(self._resident_since)
        plan = self.planner.plan(specs, self.demand(), current)
        self.metrics.plans.inc()
        now = time.monotonic()
        for act in plan.actions:
            model = act["model"]
            if act["op"] == "page_out":
                if now - since.get(model, 0.0) < self._min_resident_s:
                    continue  # anti-thrash: too fresh to evict
                self.page_out(model)
            elif act["op"] == "fault_in":
                self.fault_in(model, act["device"])
            elif act["op"] == "migrate":
                self.migrate(model, act["dst"])
        return plan

    # -- observability -----------------------------------------------------
    def _observe_exec_bytes(self, spec, server):
        """Refine the spec's executable-footprint estimate from the live
        server's XLA cost analysis (when the compile cache primed it).
        ``bytes_accessed`` counts the param reads too; those bytes are
        already in ``param_footprint``, so only the excess over the
        server's resident param bytes counts as executable overhead."""
        try:
            total = 0
            for entry in server.compiled_entries():
                info = getattr(entry, "cost_info", None)
                if info and info.get("bytes_accessed"):
                    total += int(info["bytes_accessed"])
            if total:
                spec.observe_exec_bytes(
                    max(0, total - server.resident_bytes()))
        except Exception:
            pass

    def resident_bytes(self) -> int:
        """Device bytes pinned by resident models right now — the value
        behind ``mxtpu_platform_resident_bytes``.  Falls after
        ``page_out`` (the released server reports 0)."""
        with self._lock:
            servers = list(self._servers.values())
        return sum(s.resident_bytes() for s in servers)

    def _update_gauges(self):
        with self._lock:
            n = len(self._servers)
        self.metrics.g_resident.set(n)
        self.metrics.g_resident_bytes.set(self.resident_bytes())

    def server_for(self, name: str) -> Optional[InferenceServer]:
        with self._lock:
            return self._servers.get(name)

    def placement(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._placement)

    def fault_in_latency_ms(self, name: str) -> Optional[float]:
        return self._fault_in_ms.get(name)

    def describe(self) -> dict:
        with self._lock:
            resident = sorted(self._servers)
            placement = dict(self._placement)
        return {
            "models": {n: self.spec(n).describe() for n in self.models()},
            "resident": resident,
            "placement": placement,
            "paged": sorted(set(self.models()) - set(resident)),
            "demand": {n: round(v, 2) for n, v in self.demand().items()},
            "resident_bytes": self.resident_bytes(),
            "pool": self.pool.describe(),
        }

    # -- lifecycle ---------------------------------------------------------
    def start(self, replan_ms: Optional[float] = None):
        """Start the background replan loop (no-op when the period
        resolves to 0)."""
        period_ms = env("MXNET_PLATFORM_REPLAN_MS", 2000.0, float) \
            if replan_ms is None else float(replan_ms)
        if period_ms <= 0 or self._loop_thread is not None:
            return self
        period_s = period_ms / 1e3

        def loop():
            while not self._loop_stop.wait(period_s):
                try:
                    self.replan()
                except Exception:
                    pass  # one bad tick must not kill the planner

        self._loop_thread = threading.Thread(
            target=loop, name="mxtpu-platform-replan", daemon=True)
        self._loop_thread.start()
        return self

    def close(self):
        """Stop the loop and page out every resident model."""
        if self._closed:
            return
        self._closed = True
        self._loop_stop.set()
        if self._loop_thread is not None:
            self._loop_thread.join(timeout=5)
            self._loop_thread = None
        for name in list(self._servers):
            try:
                self.page_out(name)
            except Exception:
                pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

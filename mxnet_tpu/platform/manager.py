"""ModelManager — actuates placement plans over real InferenceServers.

The planner decides *what should be resident*; the manager makes it so:

* **fault_in** — build a warm :class:`InferenceServer` via
  ``from_checkpoint(attach_aot=True)`` (the AOT bundle beside the
  checkpoint makes every bucket warm by deserialization — zero
  cold-bucket runs), register it in the shared replica registry with
  ``{"model", "tenant", "device", "replica"}`` meta so model-scoped
  routers adopt it and the health plane can group it into a failure
  domain, and start its heartbeat.  Exactly one fault-in builds at a
  time per model (the **fault-in window**): concurrent callers wait,
  and the front door 503s arrivals with a Retry-After derived from the
  fault-in ETA.  A fault-in that fails midway (torn AOT bundle,
  injected warmup IOError) unwinds completely — ``resident_bytes()``
  returns to its pre-attempt value.
* **page_out** — save the server's AOT bundle (executables + tuning
  entries travel with the checkpoint; the NEXT fault-in warms from it),
  deregister, then ``stop()`` — which releases the device-resident
  params and executables.  ``graceful=True`` is the SLO-aware
  preemption path: quiesce arrivals (readiness off + deregister), drain
  the batcher, hand live generate streams to a surviving replica via
  the router's mid-stream failover, and only then release memory —
  transcripts stay bit-identical.
* **migrate** — page one replica out at its old device, fault it in at
  the new one.
* **replan** — one planner pass + actuation under a monotonic **plan
  generation** stamped on every platform telemetry event, with a
  minimum-residency anti-thrash guard.
* **degradation ladder** — on a failure-domain death (health-plane
  callback): reap the dead replicas, re-plan over surviving capacity
  (rung 1: warm re-faults onto surviving domains), engage brownout when
  not everything fits (rung 2: only higher-SLO classes admitted), and
  gracefully page out the lowest-score models (rung 3).

Every actuation is a ``faults`` dotted op (``platform.fault_in`` /
``platform.page_out`` / ``platform.migrate``) and counts in the
model-labeled platform telemetry.
"""
from __future__ import annotations

import math
import threading
import time
from typing import Dict, Optional

from .. import faults
from .. import telemetry as _telemetry
from ..base import MXNetError, env, register_env
from ..serving.registry import ReplicaRegistry, start_heartbeater
from ..serving.server import InferenceServer
from .planner import DevicePool, PlacementPlanner
from .spec import ModelSpec

__all__ = ["ModelManager", "PlatformMetrics", "FaultInProgressError"]

register_env("MXNET_PLATFORM_REPLAN_MS", 2000.0, float,
             "Background placement-replan period of a started "
             "ModelManager (0 disables the loop; replan() stays "
             "callable).")
register_env("MXNET_PLATFORM_DEMAND_HALFLIFE_S", 30.0, float,
             "Half-life of the per-model demand EWMA the placement "
             "planner scores against — shorter chases diurnal load "
             "faster, longer resists thrash.")
register_env("MXNET_PLATFORM_MIN_RESIDENT_S", 5.0, float,
             "Anti-thrash guard: a model faulted in more recently than "
             "this is not paged out by a replan (explicit page_out() "
             "calls are not gated).")
register_env("MXNET_PLATFORM_FAULTIN_ETA_MS", 2000.0, float,
             "Fault-in ETA estimate used for Retry-After on 503s during "
             "a model's fault-in window, until a measured fault-in "
             "latency replaces it.")
register_env("MXNET_PLATFORM_DRAIN_MS", 5000.0, float,
             "Graceful page-out drain budget: how long a preempted "
             "replica may spend flushing its batcher queue before its "
             "generate streams are handed off and memory is released.")


class FaultInProgressError(MXNetError):
    """A request arrived during its model's fault-in window — HTTP 503 +
    Retry-After (the fault-in ETA), not a terminal error: the model is
    coming up, retry shortly."""

    def __init__(self, msg, retry_after=1.0):
        super().__init__(msg)
        self.retry_after = retry_after


class PlatformMetrics:
    """Model-labeled platform telemetry (a registry collector)."""

    def __init__(self):
        reg = self._registry = _telemetry.Registry()
        self.fault_ins = reg.labeled_counter(
            "mxtpu_platform_fault_ins_total", "model")
        self.fault_in_fails = reg.labeled_counter(
            "mxtpu_platform_fault_in_failures_total", "model")
        self.page_outs = reg.labeled_counter(
            "mxtpu_platform_page_outs_total", "model")
        self.migrations = reg.labeled_counter(
            "mxtpu_platform_migrations_total", "model")
        self.reaps = reg.labeled_counter(
            "mxtpu_platform_replica_reaps_total", "model")
        self.plans = reg.counter("mxtpu_platform_plans_total")
        self.brownouts = reg.counter("mxtpu_platform_brownouts_total")
        self.g_plan_gen = reg.gauge("mxtpu_platform_plan_generation")
        self.g_resident = reg.gauge("mxtpu_platform_resident_models")
        self.g_registered = reg.gauge("mxtpu_platform_registered_models")
        self.g_resident_bytes = reg.gauge("mxtpu_platform_resident_bytes")
        _telemetry.register_collector(self)

    def render_prometheus(self):
        return self._registry.render_prometheus()


class ModelManager:
    """Owns the model catalog, the demand signal, and the live servers.

    Parameters
    ----------
    pool : DevicePool
        The memory budget placements pack against (its
        ``devices_per_host`` defines the failure domains).
    registry : ReplicaRegistry, optional
        Shared replica live-set; created (in-process) when absent.
        Every faulted-in server registers here with model/tenant/device
        meta.
    planner : PlacementPlanner, optional
        Defaults to a fresh planner over ``pool``.
    """

    def __init__(self, pool: DevicePool, registry=None,
                 planner: Optional[PlacementPlanner] = None):
        self.pool = pool
        self.registry = ReplicaRegistry() if registry is None else registry
        self.planner = PlacementPlanner(pool) if planner is None else planner
        self.metrics = PlatformMetrics()
        self._lock = threading.RLock()
        self._specs: Dict[str, ModelSpec] = {}
        # all replica-scoped state is name -> {replica_index: value}
        self._servers: Dict[str, Dict[int, InferenceServer]] = {}
        self._beat_stops: Dict[str, Dict[int, tuple]] = {}  # (reg_name, stop)
        self._placement: Dict[str, Dict[int, int]] = {}
        self._resident_since: Dict[str, float] = {}
        self._demand: Dict[str, float] = {}
        self._demand_t: Dict[str, float] = {}
        self._fault_in_ms: Dict[str, float] = {}
        self._faulting: Dict[str, dict] = {}  # open fault-in windows
        self._replica_seq = 0
        self._plan_gen = 0
        self._health = None
        self._quotas = None
        self._halflife_s = env("MXNET_PLATFORM_DEMAND_HALFLIFE_S", 30.0,
                               float)
        self._min_resident_s = env("MXNET_PLATFORM_MIN_RESIDENT_S", 5.0,
                                   float)
        self._loop_stop = threading.Event()
        self._loop_thread = None
        self._closed = False

    # -- catalog -----------------------------------------------------------
    def register_model(self, spec: ModelSpec):
        with self._lock:
            if spec.name in self._specs:
                raise MXNetError("model %r already registered" % spec.name)
            self._specs[spec.name] = spec
            self._demand.setdefault(spec.name, 0.0)
        self.metrics.g_registered.set(len(self._specs))
        _telemetry.log_event("platform_register", model=spec.name,
                             tenant=spec.tenant, slo=spec.slo,
                             replicas=spec.replicas)
        return spec

    def spec(self, name: str) -> ModelSpec:
        with self._lock:
            spec = self._specs.get(name)
        if spec is None:
            raise MXNetError("unknown model %r (registered: %s)"
                             % (name, sorted(self._specs)))
        return spec

    def models(self):
        with self._lock:
            return sorted(self._specs)

    # -- resilience wiring -------------------------------------------------
    def attach_health(self, health):
        """Wire a :class:`~.healthplane.HealthPlane` to this manager:
        its domain transitions drive the degradation ladder, and replans
        exclude dead capacity.  Returns the health plane."""
        with self._lock:
            self._health = health
        health._on_change = self._on_domain_health
        return health

    def bind_quotas(self, quotas):
        """Give the manager the admission gate to brown out on capacity
        loss (the front door calls this with its TenantQuotas)."""
        with self._lock:
            self._quotas = quotas
        return quotas

    def plan_generation(self) -> int:
        """Monotonic plan generation — bumped on every replan and every
        health transition, stamped on all platform telemetry events."""
        with self._lock:
            return self._plan_gen

    def _bump_gen_locked(self) -> int:
        self._plan_gen += 1
        self.metrics.g_plan_gen.set(self._plan_gen)
        return self._plan_gen

    # -- demand signal -----------------------------------------------------
    def record_demand(self, name: str, n: float = 1.0):
        """Fold ``n`` requests into the model's demand EWMA (decayed by
        the configured half-life since the last observation)."""
        now = time.monotonic()
        with self._lock:
            cur = self._decayed_demand_locked(name, now)
            self._demand[name] = cur + float(n)
            self._demand_t[name] = now

    def _decayed_demand_locked(self, name, now):
        last = self._demand_t.get(name)
        cur = self._demand.get(name, 0.0)
        if last is None or self._halflife_s <= 0:
            return cur
        return cur * math.pow(0.5, (now - last) / self._halflife_s)

    def demand(self) -> Dict[str, float]:
        now = time.monotonic()
        with self._lock:
            return {n: self._decayed_demand_locked(n, now)
                    for n in self._specs}

    # -- actuation ---------------------------------------------------------
    def _next_replica_name(self, model):
        self._replica_seq += 1
        return "%s/r%d" % (model, self._replica_seq)

    def _fault_in_eta_s_locked(self, name) -> float:
        ms = self._fault_in_ms.get(name)
        if ms is None:
            ms = env("MXNET_PLATFORM_FAULTIN_ETA_MS", 2000.0, float)
        return max(ms / 1e3, 1e-3)

    def fault_in_window(self, name: str) -> Optional[float]:
        """Remaining fault-in ETA in seconds while ``name`` has an open
        fault-in window, else None — the front door's Retry-After for
        503s during the window."""
        with self._lock:
            win = self._faulting.get(name)
            if win is None:
                return None
            elapsed = time.monotonic() - win["t0"]
            return max(win["eta_s"] - elapsed, 0.05)

    def fault_in(self, name: str, device: Optional[int] = None,
                 replica: int = 0):
        """Materialize one replica of a model as a live warm server;
        returns the server.  Idempotent for already-resident replicas.
        Exactly one build runs per model at a time: concurrent callers
        wait on the fault-in window (and become the next owner if the
        build fails).  A failed build leaks nothing — the partially
        allocated server unwinds and ``resident_bytes()`` is unchanged."""
        spec = self.spec(name)
        replica = int(replica)
        while True:
            with self._lock:
                srv = self._servers.get(name, {}).get(replica)
                if srv is not None:
                    return srv
                win = self._faulting.get(name)
                if win is None:
                    win = {"t0": time.monotonic(),
                           "eta_s": self._fault_in_eta_s_locked(name),
                           "event": threading.Event()}
                    self._faulting[name] = win
                    break
            # another thread owns this model's fault-in: wait it out,
            # then re-check (its failure makes us the next owner)
            win["event"].wait(timeout=win["eta_s"] * 4 + 30.0)
        t0 = time.monotonic()
        try:
            faults.fire("platform.fault_in")
            kwargs = dict(spec.server_kwargs)
            if spec.generator_spec is not None:
                kwargs.setdefault("generator_spec",
                                  dict(spec.generator_spec))
            server = InferenceServer.from_checkpoint(
                spec.prefix, spec.epoch, spec.input_shapes, attach_aot=True,
                **kwargs)
        except BaseException as exc:
            with self._lock:
                self._faulting.pop(name, None)
                gen = self._plan_gen
            win["event"].set()
            self.metrics.fault_in_fails.inc(name)
            self._update_gauges()
            _telemetry.log_event("platform_fault_in_failed", model=name,
                                 replica=replica, gen=gen,
                                 error=repr(exc))
            raise
        self._observe_exec_bytes(spec, server)
        # bundle-on-first-build: a cold build writes its AOT bundle
        # immediately, not just at graceful page-out — a replica reaped
        # with its host saves nothing, and the degradation ladder's
        # re-fault onto survivors must still come back warm
        try:
            if server.cold_bucket_runs() > 0 and server.compiled_entries():
                server.save_aot_bundle(spec.prefix, spec.epoch)
        except Exception:
            pass
        rep_name = None
        if device is None:
            # demand-paged arrivals carry no device: place on surviving
            # capacity, never on a host the health plane has declared
            # dead (the ladder's explicit replan may still move it)
            alive = self._health.alive_devices() if self._health else None
            dev = int(alive[0]) if alive else 0
        else:
            dev = int(device)
        with self._lock:
            reps = self._servers.setdefault(name, {})
            if replica in reps:  # raced another fault_in
                srv = reps[replica]
            else:
                rep_name = self._next_replica_name(name)
                reps[replica] = server
                self._placement.setdefault(name, {})[replica] = dev
                self._resident_since[name] = time.monotonic()
                srv = server
            self._faulting.pop(name, None)
            gen = self._plan_gen
        win["event"].set()
        if rep_name is None:
            server.stop(drain=False)
            return srv
        stop = start_heartbeater(
            self.registry, rep_name, server,
            meta={"model": name, "tenant": spec.tenant, "device": dev,
                  "replica": replica})
        with self._lock:
            self._beat_stops.setdefault(name, {})[replica] = \
                (rep_name, stop)
        dt_ms = (time.monotonic() - t0) * 1e3
        self._fault_in_ms[name] = dt_ms
        self.metrics.fault_ins.inc(name)
        self._update_gauges()
        _telemetry.log_event("platform_fault_in", model=name,
                             replica=replica, device=dev, gen=gen,
                             ms=round(dt_ms, 1),
                             cold_runs=server.cold_bucket_runs())
        return server

    def page_out(self, name: str, replica: Optional[int] = None,
                 graceful: bool = False):
        """Demote replicas of a model to the on-disk AOT bundle and
        release their device memory (``replica=None`` pages out every
        replica).  No-op for non-resident models.

        ``graceful=True`` is SLO-aware preemption: readiness drops and
        the replica deregisters FIRST (routers stop dispatching here),
        the batcher drains (bounded by ``MXNET_PLATFORM_DRAIN_MS``), the
        AOT bundle refreshes, live generate streams hand off to a
        surviving replica via the router's mid-stream failover, and only
        then is device memory released — transcripts stay
        bit-identical."""
        with self._lock:
            reps = self._servers.get(name, {})
            idxs = (sorted(reps) if replica is None
                    else [int(replica)] if int(replica) in reps else [])
            popped = []
            for i in idxs:
                popped.append((i, reps.pop(i),
                               self._beat_stops.get(name, {}).pop(i, None)))
                self._placement.get(name, {}).pop(i, None)
            if not self._servers.get(name):
                self._servers.pop(name, None)
                self._beat_stops.pop(name, None)
                self._placement.pop(name, None)
                self._resident_since.pop(name, None)
            gen = self._plan_gen
        if not popped:
            return
        faults.fire("platform.page_out")
        spec = self.spec(name)
        for i, server, beat in popped:
            self._page_out_one(name, spec, i, server, beat, graceful, gen)
        self.metrics.page_outs.inc(name)
        self._update_gauges()

    def _page_out_one(self, name, spec, idx, server, beat, graceful, gen):
        handed = 0
        if graceful:
            try:
                server.begin_drain()
            except Exception:
                pass
            if beat is not None:
                beat[1]()  # deregister: routers drop it on next sync
                beat = None
            try:
                server.wait_idle(
                    env("MXNET_PLATFORM_DRAIN_MS", 5000.0, float) / 1e3)
            except Exception:
                pass
        # bundle BEFORE stop: compiled_entries() is empty once the
        # predictors are released
        try:
            if server.compiled_entries():
                server.save_aot_bundle(spec.prefix, spec.epoch)
        except Exception:
            pass  # bundle refresh is best-effort; next fault-in still
            # warms from the previous bundle (or compiles)
        if beat is not None:
            beat[1]()
        if graceful:
            try:
                # live generate streams fail over mid-stream to a
                # surviving replica BEFORE the memory goes away
                handed = server.handoff_streams()
            except Exception:
                pass
            server.stop(drain=False)
        else:
            server.stop(drain=True)
        _telemetry.log_event("platform_page_out", model=name, replica=idx,
                             gen=gen, graceful=bool(graceful),
                             streams_handed_off=handed,
                             resident_bytes=server.resident_bytes())

    def migrate(self, name: str, device: int, replica: int = 0):
        """Move one replica to another device."""
        faults.fire("platform.migrate")
        with self._lock:
            resident = int(replica) in self._servers.get(name, {})
        if not resident:
            return self.fault_in(name, device, replica=replica)
        self.page_out(name, replica=replica, graceful=True)
        server = self.fault_in(name, device, replica=replica)
        self.metrics.migrations.inc(name)
        return server

    def replan(self, force: bool = False, graceful: bool = True):
        """One planner pass + actuation; returns the plan.  ``force``
        bypasses the anti-thrash guard and keeps actuating past
        individual action failures (the degradation-ladder mode)."""
        with self._lock:
            specs = dict(self._specs)
            current_replicas = {n: dict(v)
                                for n, v in self._placement.items() if v}
            current = {n: v[min(v)]
                       for n, v in current_replicas.items()}
        alive = (self._health.alive_devices()
                 if self._health is not None else None)
        plan = self.planner.plan(specs, self.demand(), current,
                                 alive_devices=alive,
                                 current_replicas=current_replicas)
        self.metrics.plans.inc()
        self._actuate(plan, force=force, graceful=graceful)
        return plan

    def _actuate(self, plan, force=False, graceful=True):
        with self._lock:
            gen = self._bump_gen_locked()
            since = dict(self._resident_since)
        _telemetry.log_event("platform_plan_actuate", gen=gen,
                             actions=len(plan.actions),
                             paged=len(plan.paged))
        now = time.monotonic()
        for act in plan.actions:
            model = act["model"]
            rep = act.get("replica", 0)
            try:
                if act["op"] == "page_out":
                    if not force and now - since.get(model, 0.0) \
                            < self._min_resident_s:
                        continue  # anti-thrash: too fresh to evict
                    self.page_out(model, replica=rep, graceful=graceful)
                elif act["op"] == "fault_in":
                    self.fault_in(model, act["device"], replica=rep)
                elif act["op"] == "migrate":
                    self.migrate(model, act["dst"], replica=rep)
            except Exception:
                if not force:
                    raise
                # ladder actuation keeps going: one failed action must
                # not strand the rest of the recovery
        return plan

    # -- degradation ladder ------------------------------------------------
    def _on_domain_health(self, domain, alive):
        """Health-plane transition callback: walk the degradation ladder
        on a domain death; replan + lift brownout on recovery."""
        with self._lock:
            gen = self._bump_gen_locked()
        _telemetry.log_event("platform_domain_transition", domain=domain,
                             alive=bool(alive), gen=gen)
        if alive:
            if self._quotas is not None and self._health is not None \
                    and not self._health.dead_domains():
                self._quotas.clear_brownout(gen=gen)
            try:
                self.replan(force=True)
            except Exception:
                pass
            return
        self._reap_domain(domain, gen)
        with self._lock:
            specs = dict(self._specs)
            current_replicas = {n: dict(v)
                                for n, v in self._placement.items() if v}
            current = {n: v[min(v)]
                       for n, v in current_replicas.items()}
        alive_devs = (self._health.alive_devices()
                      if self._health is not None else None)
        try:
            plan = self.planner.plan(specs, self.demand(), current,
                                     alive_devices=alive_devs,
                                     current_replicas=current_replicas)
        except Exception:
            return
        self.metrics.plans.inc()
        # rung 2 first: while the shuffle below runs, the door already
        # sheds the SLO classes that lost their seats — only ranks above
        # the best paged model's class stay admitted
        if self._quotas is not None:
            if plan.paged:
                ranks = [specs[n].slo_rank() for n in plan.paged
                         if n in specs]
                floor = max(0, min(ranks) - 1) if ranks else 0
                self._quotas.set_brownout(floor, gen=gen)
                self.metrics.brownouts.inc()
            elif self._health is not None \
                    and not self._health.dead_domains():
                self._quotas.clear_brownout(gen=gen)
        # rung 1 (warm re-faults onto survivors) + rung 3 (graceful
        # page-out of the lowest-score models) in one actuation
        self._actuate(plan, force=True, graceful=True)

    def _reap_domain(self, domain, gen):
        """Drop every replica placed in a dead domain: its host is gone,
        so there is no drain — stop the heartbeat thread, reap the
        registry corpse so routers converge before the TTL, release
        whatever the in-process simulation still holds."""
        dead = []
        with self._lock:
            for name in list(self._placement):
                reps = self._placement[name]
                for i in [i for i, d in reps.items()
                          if self.pool.domain_of(d) == domain]:
                    dev = reps.pop(i)
                    server = self._servers.get(name, {}).pop(i, None)
                    beat = self._beat_stops.get(name, {}).pop(i, None)
                    dead.append((name, i, dev, server, beat))
                if not self._servers.get(name):
                    self._servers.pop(name, None)
                    self._beat_stops.pop(name, None)
                    self._placement.pop(name, None)
                    self._resident_since.pop(name, None)
        for name, i, dev, server, beat in dead:
            if beat is not None:
                try:
                    beat[1](deregister=False)  # dead hosts don't leave
                except Exception:
                    pass
                try:
                    self.registry.deregister(beat[0])
                except Exception:
                    pass
            if server is not None:
                try:
                    server.stop(drain=False)
                except Exception:
                    pass
            self.metrics.reaps.inc(name)
            _telemetry.log_event("platform_replica_reap", model=name,
                                 replica=i, device=dev, domain=domain,
                                 gen=gen)
        self._update_gauges()

    def kill_replica(self, name: str, replica: int = 0) -> bool:
        """Chaos hook: simulate host death for one replica.  Its server
        dies hard (streams fail mid-flight, memory gone) and its
        heartbeats STOP without deregistering — exactly a kill -9'd
        host.  Control-plane state still lists the replica as placed:
        only the health plane's probe (registry TTL eviction) discovers
        the loss and triggers the degradation ladder."""
        replica = int(replica)
        with self._lock:
            server = self._servers.get(name, {}).get(replica)
            beat = self._beat_stops.get(name, {}).get(replica)
        if beat is not None:
            try:
                beat[1](deregister=False)
            except Exception:
                pass
            with self._lock:
                # the heartbeater is dead, but the registry NAME must
                # stay on file: the ladder's reap deregisters the corpse
                # by that name so routers converge before the TTL would
                if replica in self._beat_stops.get(name, {}):
                    self._beat_stops[name][replica] = (
                        beat[0], lambda **kw: None)
        if server is not None:
            server.stop(drain=False)
        _telemetry.log_event("platform_replica_kill", model=name,
                             replica=replica)
        return server is not None

    # -- observability -----------------------------------------------------
    def _observe_exec_bytes(self, spec, server):
        """Refine the spec's executable-footprint estimate from the live
        server's XLA cost analysis (when the compile cache primed it).
        ``bytes_accessed`` counts the param reads too; those bytes are
        already in ``param_footprint``, so only the excess over the
        server's resident param bytes counts as executable overhead."""
        try:
            total = 0
            for entry in server.compiled_entries():
                info = getattr(entry, "cost_info", None)
                if info and info.get("bytes_accessed"):
                    total += int(info["bytes_accessed"])
            if total:
                spec.observe_exec_bytes(
                    max(0, total - server.resident_bytes()))
        except Exception:
            pass

    def resident_bytes(self) -> int:
        """Device bytes pinned by resident models right now — the value
        behind ``mxtpu_platform_resident_bytes``.  Falls after
        ``page_out`` (the released server reports 0)."""
        with self._lock:
            servers = [s for reps in self._servers.values()
                       for s in reps.values()]
        return sum(s.resident_bytes() for s in servers)

    def _update_gauges(self):
        with self._lock:
            n = sum(1 for reps in self._servers.values() if reps)
        self.metrics.g_resident.set(n)
        self.metrics.g_resident_bytes.set(self.resident_bytes())

    def server_for(self, name: str) -> Optional[InferenceServer]:
        """The first live replica server of a model (None when paged
        out).  Prefers a replica that is not stopped — during a host
        loss the killed replica's corpse must not shadow its surviving
        peer."""
        with self._lock:
            reps = self._servers.get(name)
            if not reps:
                return None
            for i in sorted(reps):
                if reps[i].ready_state() != "stopped":
                    return reps[i]
            return reps[min(reps)]

    def placement(self) -> Dict[str, int]:
        """Primary (lowest-index) replica's device per resident model —
        the legacy single-replica view; :meth:`replica_placement` has
        the full map."""
        with self._lock:
            return {n: v[min(v)]
                    for n, v in self._placement.items() if v}

    def replica_placement(self) -> Dict[str, Dict[int, int]]:
        with self._lock:
            return {n: dict(v) for n, v in self._placement.items() if v}

    def fault_in_latency_ms(self, name: str) -> Optional[float]:
        return self._fault_in_ms.get(name)

    def describe(self) -> dict:
        with self._lock:
            resident = sorted(n for n, v in self._servers.items() if v)
            placement = {n: v[min(v)]
                         for n, v in self._placement.items() if v}
            replica_placement = {n: dict(v)
                                 for n, v in self._placement.items() if v}
            gen = self._plan_gen
        out = {
            "models": {n: self.spec(n).describe() for n in self.models()},
            "resident": resident,
            "placement": placement,
            "replica_placement": replica_placement,
            "paged": sorted(set(self.models()) - set(resident)),
            "demand": {n: round(v, 2) for n, v in self.demand().items()},
            "resident_bytes": self.resident_bytes(),
            "plan_generation": gen,
            "pool": self.pool.describe(),
        }
        if self._health is not None:
            out["health"] = self._health.describe()
        return out

    # -- lifecycle ---------------------------------------------------------
    def start(self, replan_ms: Optional[float] = None):
        """Start the background replan loop (no-op when the period
        resolves to 0)."""
        period_ms = env("MXNET_PLATFORM_REPLAN_MS", 2000.0, float) \
            if replan_ms is None else float(replan_ms)
        if period_ms <= 0 or self._loop_thread is not None:
            return self
        period_s = period_ms / 1e3

        def loop():
            while not self._loop_stop.wait(period_s):
                try:
                    self.replan()
                except Exception:
                    pass  # one bad tick must not kill the planner

        self._loop_thread = threading.Thread(
            target=loop, name="mxtpu-platform-replan", daemon=True)
        self._loop_thread.start()
        return self

    def close(self):
        """Stop the loop and page out every resident model."""
        if self._closed:
            return
        self._closed = True
        self._loop_stop.set()
        if self._loop_thread is not None:
            self._loop_thread.join(timeout=5)
            self._loop_thread = None
        for name in list(self._servers):
            try:
                self.page_out(name)
            except Exception:
                pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

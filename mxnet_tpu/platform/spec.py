"""ModelSpec — one registered model and its estimated device footprint.

The planner never loads a model to decide where it fits: placement runs
off *estimates* that are cheap to compute from what is already on disk
(the checkpoint's param file size), what the spec declares (a generator
spec implies a paged KV pool of known geometry), and what past runs
measured (a live server's compile-cache cost analysis refines the
executable-overhead guess — the tune-once idea: measurements travel
with the model, later placements inherit them).
"""
from __future__ import annotations

import os
from typing import Dict, Optional, Sequence

import numpy as np

from ..base import MXNetError, env, register_env

__all__ = ["ModelSpec", "SLO_RANK"]

register_env("MXNET_PLATFORM_EXEC_OVERHEAD", 0.25, float,
             "Executable-footprint estimate as a fraction of a model's "
             "param bytes, used by the placement planner until a live "
             "run's XLA cost analysis refines it.")

# placement priority by SLO class: interactive models evict last,
# batch models evict first, generators sit between (their KV pool makes
# fault-in costlier than a pure classifier's)
SLO_RANK = {"interactive": 0, "generate": 1, "batch": 2}


class ModelSpec:
    """One model the platform may serve.

    Parameters
    ----------
    name : str
        Platform-unique model name (the routing key in request paths).
    prefix, epoch : str, int
        ``save_checkpoint`` prefix/epoch this model loads from; an AOT
        bundle beside it (``prefix-NNNN.aot/``) makes fault-in warm.
    input_shapes : dict
        ``{input: shape}`` including the batch axis, as for
        :class:`~mxnet_tpu.serving.server.InferenceServer`.
    tenant : str
        Owning tenant (quota accounting + telemetry label).
    slo : str
        SLO class: ``interactive`` / ``batch`` / ``generate``.
    weight : float
        Fair-share weight for this model's tenant traffic.
    generator_spec : dict, optional
        DecodeEngine kwargs for generate-capable models; implies a
        KV-pool footprint.
    param_bytes : int, optional
        Explicit param footprint; default derives from the checkpoint
        file size on disk.
    server_kwargs : dict, optional
        Extra ``InferenceServer.from_checkpoint`` kwargs (buckets,
        max_queue, ...).
    replicas : int
        Desired replica count (default 1).  The planner spreads a
        model's replicas across failure domains, so losing one host
        degrades capacity instead of availability; each replica costs
        one full footprint.
    """

    __slots__ = ("name", "prefix", "epoch", "input_shapes", "tenant",
                 "slo", "weight", "generator_spec", "server_kwargs",
                 "replicas", "_param_bytes", "_measured_exec_bytes")

    def __init__(self, name: str, prefix: str, epoch: int,
                 input_shapes: Dict[str, Sequence[int]],
                 tenant: str = "default", slo: str = "interactive",
                 weight: float = 1.0,
                 generator_spec: Optional[dict] = None,
                 param_bytes: Optional[int] = None,
                 server_kwargs: Optional[dict] = None,
                 replicas: int = 1):
        if not name or "/" in name:
            raise MXNetError("model name must be non-empty and slash-free, "
                             "got %r" % (name,))
        if slo not in SLO_RANK:
            raise MXNetError("unknown SLO class %r (one of %s)"
                             % (slo, sorted(SLO_RANK)))
        if int(replicas) < 1:
            raise MXNetError("replicas must be >= 1, got %r" % (replicas,))
        self.name = name
        self.prefix = prefix
        self.epoch = int(epoch)
        self.input_shapes = {k: tuple(v) for k, v in input_shapes.items()}
        self.tenant = tenant
        self.slo = slo
        self.weight = float(weight)
        self.generator_spec = dict(generator_spec) if generator_spec else None
        self.server_kwargs = dict(server_kwargs) if server_kwargs else {}
        self.replicas = int(replicas)
        self._param_bytes = None if param_bytes is None else int(param_bytes)
        self._measured_exec_bytes = None

    # -- footprint ---------------------------------------------------------
    def param_footprint(self) -> int:
        """Param bytes: explicit > checkpoint file size > 0 (a spec whose
        checkpoint is not on disk yet still registers; the planner just
        sees it as weightless until it materializes)."""
        if self._param_bytes is not None:
            return self._param_bytes
        path = "%s-%04d.params" % (self.prefix, self.epoch)
        try:
            return os.path.getsize(path)
        except OSError:
            return 0

    def kv_footprint(self) -> int:
        """Paged-KV-pool bytes a generate-capable model pins: K and V
        pages across layers at the spec's (or default) pool geometry."""
        gs = self.generator_spec
        if not gs:
            return 0
        num_layers = int(gs.get("num_layers", 4))
        num_heads = int(gs.get("num_heads", 8))
        hidden = int(gs.get("hidden", 512))
        head_dim = hidden // num_heads
        page_size = int(gs.get("page_size")
                        or env("MXNET_GEN_PAGE_SIZE", 16, int))
        num_pages = int(gs.get("num_pages")
                        or env("MXNET_GEN_NUM_PAGES", 128, int))
        dtype_size = np.dtype(gs.get("dtype", np.float32)).itemsize
        return (2 * num_layers * num_pages * page_size
                * num_heads * head_dim * dtype_size)

    def exec_footprint(self) -> int:
        """Executable bytes: the live-run measurement when one exists,
        else the ``MXNET_PLATFORM_EXEC_OVERHEAD`` fraction of params."""
        if self._measured_exec_bytes is not None:
            return self._measured_exec_bytes
        frac = env("MXNET_PLATFORM_EXEC_OVERHEAD", 0.25, float)
        return int(self.param_footprint() * frac)

    def observe_exec_bytes(self, nbytes: int):
        """Refine the executable estimate from a live server's cost
        analysis (``CachedFunction.cost_info['bytes_accessed']``)."""
        self._measured_exec_bytes = int(nbytes)

    def footprint(self) -> dict:
        p, k, e = (self.param_footprint(), self.kv_footprint(),
                   self.exec_footprint())
        return {"param_bytes": p, "kv_bytes": k, "exec_bytes": e,
                "total": p + k + e}

    def slo_rank(self) -> int:
        return SLO_RANK[self.slo]

    def describe(self) -> dict:
        d = self.footprint()
        d.update(name=self.name, tenant=self.tenant, slo=self.slo,
                 weight=self.weight, prefix=self.prefix, epoch=self.epoch,
                 generate=self.generator_spec is not None,
                 replicas=self.replicas)
        return d

    def __repr__(self):
        return ("ModelSpec(%r, tenant=%r, slo=%r, total_bytes=%d)"
                % (self.name, self.tenant, self.slo,
                   self.footprint()["total"]))

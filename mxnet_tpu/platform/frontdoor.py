"""FrontDoor — the multi-model, multi-tenant request path.

One front door serves every registered model: requests name their model
in the URL path (``POST /v1/<model>/predict``) or the ``X-MXNet-Model``
header, and their tenant in ``X-Tenant``.  Per model, the front door
keeps a model-scoped :class:`~mxnet_tpu.serving.router.Router` view
over the manager's ONE shared replica registry (the satellite fix:
registration meta carries the model label, so N routers filter one
table instead of needing a registry each).  Admission runs through
:class:`~mxnet_tpu.platform.quotas.TenantQuotas` BEFORE the router —
a flooding tenant is 429d at the door, its neighbours never queue
behind it — and every admitted request feeds the manager's demand
EWMA, which is what earns a paged-out model its fault-in.

A request for a paged-out model blocks on the fault-in (warm via the
AOT bundle, so the stall is a bundle deserialize, not a compile) and
then routes normally — demand paging, model edition.  Only the FIRST
such request pays that stall: while the model's fault-in window is open,
later arrivals are rejected with 503 + ``Retry-After`` set to the
remaining fault-in ETA (:class:`~.manager.FaultInProgressError`), and
the queued-or-rejected decision is logged with the plan generation.
When the degradation ladder has engaged brownout,
:class:`~.quotas.BrownoutError` maps to the same 503 + ``Retry-After``
family.
"""
from __future__ import annotations

import json
from typing import Dict, Optional

from .. import telemetry as _telemetry
from ..base import MXNetError
from ..serving.batcher import (DeadlineExceededError, QueueFullError,
                               ServerClosedError)
from ..serving.router import (NoReplicaAvailableError, Router,
                              RouterOverloadError)
from .manager import FaultInProgressError, ModelManager
from .quotas import BrownoutError, TenantQuotaExceededError, TenantQuotas
from .spec import SLO_RANK

__all__ = ["FrontDoor"]


class FrontDoor:
    """Quota-gated, model-routed entry point over a :class:`ModelManager`.

    Parameters
    ----------
    manager : ModelManager
        Owns the catalog, placement, and the shared replica registry.
    quotas : TenantQuotas, optional
        Defaults to a fresh gate whose pressure signal is the max
        pressure across this front door's live routers.
    slo_classes : dict, optional
        Passed through to each per-model router.
    registry_sync_ms : float
        Per-model router registry sync period; kept tight (50ms) so a
        fault-in becomes routable fast, and forced synchronously after
        every fault-in anyway.
    """

    def __init__(self, manager: ModelManager,
                 quotas: Optional[TenantQuotas] = None,
                 slo_classes: Optional[dict] = None,
                 registry_sync_ms: float = 50.0):
        self.manager = manager
        self.quotas = TenantQuotas(pressure_fn=self._pressure) \
            if quotas is None else quotas
        # the degradation ladder browns this gate out on capacity loss
        manager.bind_quotas(self.quotas)
        self._slo_classes = slo_classes
        self._sync_ms = float(registry_sync_ms)
        self._routers: Dict[str, Router] = {}
        self._httpd = None
        self._http_thread = None
        self._closed = False

    # -- routing -----------------------------------------------------------
    def router_for(self, model: str) -> Router:
        """The model-scoped router view, created on first use."""
        r = self._routers.get(model)
        if r is None:
            self.manager.spec(model)  # raises for unknown models
            r = self._routers.get(model)
            if r is None:
                r = Router(registry=self.manager.registry, model=model,
                           slo_classes=self._slo_classes,
                           registry_sync_ms=self._sync_ms)
                self._routers[model] = r
        return r

    def _pressure(self) -> float:
        """Fleet pressure signal for the quota gate: worst live router.
        Routers with no replicas — or only draining corpses mid-reap —
        report pressure 1.0; a model mid-fault-in (or mid-host-loss)
        must not trip fair-share shedding, so only routers with a
        dispatchable replica count."""
        worst = 0.0
        for r in list(self._routers.values()):
            if any(not rep.draining for rep in r.replicas()):
                worst = max(worst, r.pressure())
        return worst

    def _resolve_slo(self, model: str, slo: Optional[str]) -> str:
        """An explicit per-request ``slo`` wins; omitted, the request is
        admitted as the MODEL's registered SLO class — a batch model's
        tenant must not dodge a brownout by leaving the field blank."""
        if slo:
            return slo
        try:
            return self.manager.spec(model).slo
        except Exception:
            return "interactive"

    def _admit(self, model: str, tenant: str,
               slo: str = "interactive") -> Router:
        if self._closed:
            raise ServerClosedError("front door is closed")
        self.quotas.admit(tenant, slo_rank=SLO_RANK.get(slo, 2))
        self.manager.record_demand(model)
        router = self.router_for(model)
        if self.manager.server_for(model) is None:
            gen = self.manager.plan_generation()
            eta = self.manager.fault_in_window(model)
            if eta is not None:
                # another request already owns the fault-in: shed with
                # the remaining ETA instead of piling threads up behind
                # the build
                _telemetry.log_event(
                    "platform_faultin_wait", model=model, tenant=tenant,
                    decision="rejected", retry_after=round(eta, 3),
                    gen=gen)
                raise FaultInProgressError(
                    "model %r is faulting in (plan gen %d); retry in "
                    "%.2fs" % (model, gen, eta), retry_after=eta)
            # demand paging: fault the model in (warm, via its AOT
            # bundle) and make it routable before dispatching
            _telemetry.log_event("platform_faultin_wait", model=model,
                                 tenant=tenant, decision="queued",
                                 gen=gen)
            self.manager.fault_in(model)
            router.sync_registry()
        elif not any(not r.draining and r.ready()
                     for r in router.replicas()):
            # the model is resident (e.g. a replan faulted it in) but
            # this router's 50ms background sync has not caught up yet —
            # a corpse handle awaiting removal does not count as caught
            # up, or a post-host-loss re-fault stays unroutable for a
            # full sync period
            router.sync_registry()
        return router

    def submit(self, model: str, tenant: str = "default",
               slo: Optional[str] = None,
               deadline_ms: Optional[float] = None, **inputs):
        """Admit + route one request; returns the router future.  Raises
        :class:`TenantQuotaExceededError` (tenant over quota / fair
        share) or :class:`RouterOverloadError` (fleet shed) — both the
        429 family — synchronously.  ``slo=None`` admits as the model's
        registered SLO class."""
        slo = self._resolve_slo(model, slo)
        router = self._admit(model, tenant, slo=slo)
        return router.submit(slo=slo, deadline_ms=deadline_ms, **inputs)

    def predict(self, model: str, tenant: str = "default",
                slo: Optional[str] = None,
                deadline_ms: Optional[float] = None, **inputs):
        return self.submit(model, tenant=tenant, slo=slo,
                           deadline_ms=deadline_ms, **inputs).result()

    def generate(self, model: str, prompt, max_new_tokens=None,
                 tenant: str = "default", slo: Optional[str] = None,
                 deadline_ms: Optional[float] = None):
        slo = self._resolve_slo(model, slo)
        router = self._admit(model, tenant, slo=slo)
        return router.generate(prompt, max_new_tokens, slo=slo,
                               deadline_ms=deadline_ms)

    def describe(self) -> dict:
        d = self.manager.describe()
        d["tenants"] = self.quotas.snapshot()
        d["routers"] = {m: [rep["name"] for rep in r.describe()]
                        for m, r in self._routers.items()}
        return d

    # -- lifecycle ---------------------------------------------------------
    def close(self):
        if self._closed:
            return
        self._closed = True
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
            if self._http_thread is not None:
                self._http_thread.join(timeout=5)
                self._http_thread = None
        for r in self._routers.values():
            r.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # -- HTTP --------------------------------------------------------------
    def serve_http(self, host: str = "127.0.0.1", port: int = 0):
        """Stdlib HTTP face; returns the bound ``(host, port)``.

        * ``POST /v1/<model>/predict`` — body as the router's
          ``/predict`` (``inputs`` / ``slo`` / ``deadline_ms``); model
          from the path, or ``X-MXNet-Model`` on bare ``/predict``;
          tenant from ``X-Tenant`` (default ``default``).  429 +
          ``Retry-After`` when THIS tenant is over quota or the class
          was shed, 503 when no replica, 504 past deadline.
        * ``POST /v1/<model>/generate`` — NDJSON token stream, same
          admission rules.
        * ``GET /models`` — catalog, placement, demand, tenant stats.
        * ``GET /metrics`` — process-wide Prometheus text (platform
          gauges included).
        * ``GET /healthz`` — 200 until ``close``.
        """
        import threading
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        door = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def _reply(self, code, body, ctype="application/json",
                       headers=()):
                data = body if isinstance(body, bytes) else body.encode()
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                for k, v in headers:
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(data)

            def _route(self):
                """(model, verb) from ``/v1/<model>/<verb>`` or the
                bare ``/<verb>`` + ``X-MXNet-Model`` header."""
                parts = [p for p in self.path.split("/") if p]
                if len(parts) == 3 and parts[0] == "v1":
                    return parts[1], parts[2]
                if len(parts) == 1:
                    return self.headers.get("X-MXNet-Model"), parts[0]
                return None, None

            def do_GET(self):
                if self.path == "/models":
                    self._reply(200, json.dumps(door.describe()))
                elif self.path == "/metrics":
                    self._reply(200, _telemetry.render_prometheus(),
                                ctype="text/plain; version=0.0.4")
                elif self.path == "/healthz":
                    if door._closed:
                        self._reply(503, json.dumps({"status": "closed"}))
                    else:
                        self._reply(200, "ok", ctype="text/plain")
                else:
                    self._reply(404, json.dumps({"error": "not found"}))

            def do_POST(self):
                model, verb = self._route()
                if verb not in ("predict", "generate") or not model:
                    self._reply(404, json.dumps(
                        {"error": "POST /v1/<model>/predict|generate"}))
                    return
                tenant = self.headers.get("X-Tenant") or "default"
                try:
                    n = int(self.headers.get("Content-Length", "0"))
                    req = json.loads(self.rfile.read(n) or b"{}")
                    if verb == "generate":
                        self._generate(model, tenant, req)
                        return
                    fut = door.submit(
                        model, tenant=tenant,
                        slo=req.get("slo"),
                        deadline_ms=req.get("deadline_ms"),
                        **req.get("inputs", {}))
                    import numpy as np

                    outs = fut.result()
                    self._reply(200, json.dumps(
                        {"outputs": [np.asarray(o).tolist()
                                     for o in outs]}))
                except (TenantQuotaExceededError,
                        RouterOverloadError) as exc:
                    self._reply(429, json.dumps({"error": str(exc)}),
                                headers=(("Retry-After", "%g"
                                          % exc.retry_after),))
                except (FaultInProgressError, BrownoutError) as exc:
                    # the platform is coming up / running degraded:
                    # retryable, with an honest ETA
                    self._reply(503, json.dumps({"error": str(exc)}),
                                headers=(("Retry-After", "%g"
                                          % exc.retry_after),))
                except DeadlineExceededError as exc:
                    self._reply(504, json.dumps({"error": str(exc)}))
                except (NoReplicaAvailableError, ServerClosedError,
                        QueueFullError) as exc:
                    self._reply(503, json.dumps({"error": str(exc)}))
                except (MXNetError, ValueError, TypeError, KeyError,
                        OSError, json.JSONDecodeError) as exc:
                    self._reply(400, json.dumps({"error": repr(exc)}))

            def _generate(self, model, tenant, req):
                it = door.generate(
                    model, req.get("prompt", []),
                    req.get("max_new_tokens"), tenant=tenant,
                    slo=req.get("slo"),
                    deadline_ms=req.get("deadline_ms"))
                self.send_response(200)
                self.send_header("Content-Type", "application/x-ndjson")
                self.end_headers()
                self.close_connection = True
                n = 0
                try:
                    for tok in it:
                        self.wfile.write(
                            (json.dumps({"token": int(tok)}) + "\n")
                            .encode())
                        self.wfile.flush()
                        n += 1
                    self.wfile.write((json.dumps(
                        {"done": True, "n": n}) + "\n").encode())
                    self.wfile.flush()
                except BrokenPipeError:
                    it.close()
                except BaseException as exc:
                    try:
                        self.wfile.write((json.dumps(
                            {"error": repr(exc)}) + "\n").encode())
                        self.wfile.flush()
                    except OSError:
                        pass

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._http_thread = threading.Thread(
            target=self._httpd.serve_forever, name="mxtpu-frontdoor-http",
            daemon=True)
        self._http_thread.start()
        return self._httpd.server_address

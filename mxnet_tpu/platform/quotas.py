"""TenantQuotas — per-tenant admission control with weighted fairness.

The router's shed machinery is *global*: past the pressure threshold,
every sheddable request gets a 429.  That is the wrong failure isolation
for a multi-tenant platform — one tenant's flood must 429 THAT tenant
while its neighbours keep their SLOs.  Two mechanisms compose here:

* **Token-bucket rate limits** — a hard per-tenant requests/s ceiling
  (``MXNET_PLATFORM_TENANT_RATE`` / per-tenant overrides) with a burst
  allowance.  Exceeding it rejects with a computed ``Retry-After``
  (time until the bucket refills one token), independent of fleet load.
* **Weighted fair sharing under pressure** — when the fleet's measured
  queue pressure crosses the shed threshold, each tenant is entitled to
  a ``weight``-proportional share of the *observed aggregate* request
  rate; tenants running above their entitlement are shed first.  A
  tenant inside its share is never shed by a neighbour's overload —
  that is the cross-tenant isolation property the chaos tenant-storm
  scenario asserts.

Both paths raise :class:`TenantQuotaExceededError`, which the front
door maps to HTTP 429 + ``Retry-After`` exactly like the router's
:class:`~mxnet_tpu.serving.router.RouterOverloadError`.

A third, platform-driven gate is **brownout**: when the degradation
ladder sheds capacity (a failure domain died and not every model fits
the survivors), the manager calls :meth:`TenantQuotas.set_brownout` with
the highest SLO rank still admitted.  Requests of lower-priority
classes raise :class:`BrownoutError` — the 503 + ``Retry-After``
family, distinct from the tenant's own 429s: the *platform* is degraded,
not the tenant misbehaving.  One recovery or successful re-plan clears
it via :meth:`TenantQuotas.clear_brownout`.
"""
from __future__ import annotations

import threading
import time
from typing import Dict, Optional

from .. import telemetry as _telemetry
from ..base import MXNetError, env, register_env

__all__ = ["TenantQuotas", "TenantQuotaExceededError", "BrownoutError"]

register_env("MXNET_PLATFORM_TENANT_RATE", 0.0, float,
             "Default per-tenant admission rate limit in requests/s "
             "(token bucket); 0 disables the hard ceiling and leaves "
             "only pressure-driven fair-share shedding.")
register_env("MXNET_PLATFORM_TENANT_BURST", 32.0, float,
             "Token-bucket burst allowance (requests) a tenant may spend "
             "above its steady rate before hard-limit 429s begin.")
register_env("MXNET_PLATFORM_FAIR_PRESSURE", 0.75, float,
             "Fleet queue-pressure fraction beyond which per-tenant "
             "weighted fair-share shedding engages (tenants above their "
             "share are 429d; tenants inside it are never shed).")
register_env("MXNET_PLATFORM_BROWNOUT_RETRY_S", 2.0, float,
             "Retry-After the brownout gate attaches to 503s for SLO "
             "classes shed while the platform runs degraded on a "
             "partial device pool.")

_EWMA_ALPHA = 0.2


class TenantQuotaExceededError(MXNetError):
    """Per-tenant admission rejection — HTTP 429 + Retry-After for ONE
    tenant, not the fleet."""

    def __init__(self, msg, retry_after=1.0):
        super().__init__(msg)
        self.retry_after = retry_after


class BrownoutError(MXNetError):
    """Platform-degraded admission rejection (a failure domain is down
    and this request's SLO class is below the brownout floor) — HTTP 503
    + Retry-After.  Distinct from :class:`TenantQuotaExceededError`: the
    platform is shedding, not the tenant flooding."""

    def __init__(self, msg, retry_after=2.0):
        super().__init__(msg)
        self.retry_after = retry_after


class _Tenant:
    __slots__ = ("rate", "burst", "weight", "tokens", "last_refill",
                 "ewma_rps", "last_seen", "admitted", "shed", "browned")

    def __init__(self, rate, burst, weight):
        self.rate = rate
        self.burst = burst
        self.weight = weight
        self.tokens = burst
        self.last_refill = time.monotonic()
        self.ewma_rps = 0.0
        self.last_seen = self.last_refill
        self.admitted = 0
        self.shed = 0
        self.browned = 0


class TenantQuotas:
    """Admission gate shared by every front door over one fleet."""

    def __init__(self, pressure_fn=None,
                 fair_pressure: Optional[float] = None):
        self._lock = threading.Lock()
        self._tenants: Dict[str, _Tenant] = {}
        self._pressure_fn = pressure_fn
        self._fair_pressure = (
            env("MXNET_PLATFORM_FAIR_PRESSURE", 0.75, float)
            if fair_pressure is None else float(fair_pressure))
        self._default_rate = env("MXNET_PLATFORM_TENANT_RATE", 0.0, float)
        self._default_burst = env("MXNET_PLATFORM_TENANT_BURST", 32.0, float)
        self._brownout = None  # (max_admitted_rank, plan_gen, retry_after)

    def set_quota(self, tenant: str, rate: Optional[float] = None,
                  burst: Optional[float] = None, weight: float = 1.0):
        """Pin one tenant's rate ceiling / burst / fair-share weight
        (None keeps the env default)."""
        with self._lock:
            t = self._tenant_locked(tenant)
            if rate is not None:
                t.rate = float(rate)
            if burst is not None:
                t.burst = float(burst)
                t.tokens = min(t.tokens, t.burst)
            t.weight = float(weight)

    def _tenant_locked(self, tenant) -> _Tenant:
        t = self._tenants.get(tenant)
        if t is None:
            t = self._tenants[tenant] = _Tenant(
                self._default_rate, self._default_burst, 1.0)
        return t

    def _observe_locked(self, t, now):
        # request-rate EWMA from inter-arrival gaps: 1/gap is the
        # instantaneous rate; the EWMA smooths it into the fair-share
        # comparison signal
        gap = now - t.last_seen
        t.last_seen = now
        if gap > 0:
            inst = min(1.0 / gap, 1e6)
            t.ewma_rps = (inst if t.ewma_rps == 0.0 else
                          _EWMA_ALPHA * inst
                          + (1 - _EWMA_ALPHA) * t.ewma_rps)

    # -- brownout (degradation-ladder rung 2) ------------------------------
    def set_brownout(self, max_rank: int, gen: int = 0,
                     retry_after: Optional[float] = None):
        """Engage brownout: only requests whose SLO rank is <=
        ``max_rank`` are admitted (rank 0 = interactive; see
        ``spec.SLO_RANK``).  ``gen`` is the plan generation that caused
        it, stamped on shed events."""
        retry = (env("MXNET_PLATFORM_BROWNOUT_RETRY_S", 2.0, float)
                 if retry_after is None else float(retry_after))
        with self._lock:
            prev = self._brownout
            self._brownout = (int(max_rank), int(gen), retry)
        if prev is None or prev[:2] != (int(max_rank), int(gen)):
            _telemetry.log_event("platform_brownout", engaged=True,
                                 max_rank=int(max_rank), gen=int(gen))

    def clear_brownout(self, gen: int = 0):
        with self._lock:
            prev = self._brownout
            self._brownout = None
        if prev is not None:
            _telemetry.log_event("platform_brownout", engaged=False,
                                 gen=int(gen))

    def brownout(self):
        """The active ``(max_rank, gen, retry_after)`` or None."""
        with self._lock:
            return self._brownout

    def admit(self, tenant: str = "default", slo_rank=None):
        """Admit one request for ``tenant`` or raise
        :class:`TenantQuotaExceededError` (over quota / fair share — the
        tenant's fault, 429) or :class:`BrownoutError` (platform
        degraded and ``slo_rank`` is below the brownout floor — 503).
        Never raises for tenants inside both their rate ceiling and
        their fair share while the platform is whole.  ``slo_rank`` None
        bypasses the brownout gate (legacy callers)."""
        now = time.monotonic()
        with self._lock:
            t = self._tenant_locked(tenant)
            self._observe_locked(t, now)
            b = self._brownout
            if b is not None and slo_rank is not None \
                    and int(slo_rank) > b[0]:
                t.browned += 1
                _telemetry.log_event(
                    "platform_quota_shed", tenant=tenant,
                    reason="brownout", slo_rank=int(slo_rank),
                    max_rank=b[0], gen=b[1])
                raise BrownoutError(
                    "platform degraded (plan gen %d): SLO rank %d not "
                    "admitted during brownout (floor %d)"
                    % (b[1], int(slo_rank), b[0]), retry_after=b[2])
            # hard ceiling first: refill, then spend
            if t.rate > 0:
                t.tokens = min(t.burst,
                               t.tokens + (now - t.last_refill) * t.rate)
                t.last_refill = now
                if t.tokens < 1.0:
                    t.shed += 1
                    retry = max((1.0 - t.tokens) / t.rate, 1e-3)
                    _telemetry.log_event("platform_quota_shed",
                                         tenant=tenant, reason="rate",
                                         rps=round(t.ewma_rps, 1))
                    raise TenantQuotaExceededError(
                        "tenant %r over its %.1f req/s quota"
                        % (tenant, t.rate), retry_after=retry)
                t.tokens -= 1.0
            # fair share second: only under fleet pressure, only for
            # tenants running above their weight-proportional slice
            pressure = self._pressure_fn() if self._pressure_fn else 0.0
            if pressure >= self._fair_pressure:
                total_w = sum(x.weight for x in self._tenants.values())
                total_rps = sum(x.ewma_rps for x in self._tenants.values())
                share = total_rps * (t.weight / total_w) if total_w else 0.0
                if total_rps > 0 and t.ewma_rps > share * 1.25:
                    t.shed += 1
                    _telemetry.log_event(
                        "platform_quota_shed", tenant=tenant, reason="fair",
                        rps=round(t.ewma_rps, 1), share=round(share, 1),
                        pressure=round(pressure, 3))
                    raise TenantQuotaExceededError(
                        "tenant %r over fair share (%.1f > %.1f req/s) at "
                        "%.0f%% pressure"
                        % (tenant, t.ewma_rps, share, pressure * 100),
                        retry_after=0.5)
            t.admitted += 1

    def snapshot(self) -> dict:
        with self._lock:
            return {name: {"admitted": t.admitted, "shed": t.shed,
                           "browned": t.browned,
                           "rate": t.rate, "weight": t.weight,
                           "ewma_rps": round(t.ewma_rps, 2)}
                    for name, t in self._tenants.items()}

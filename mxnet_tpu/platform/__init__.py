"""Multi-tenant model platform over the serving fleet.

The serving stack below this package (router, autoscaler, registry)
assumes ONE model per fleet.  The platform turns that fleet into a
shared pool serving many models for many tenants:

* :class:`ModelSpec` — one registered model: checkpoint prefix, input
  shapes, tenant, SLO class, and an estimated device **footprint**
  (param bytes via ``sharding.param_bytes`` / checkpoint size, KV-pool
  bytes for generator specs, executable overhead refined from
  ``hlo_analysis`` cost analysis once the model has run).
* :class:`PlacementPlanner` — bin-packs registered models onto a
  :class:`DevicePool` by footprint, demand, and SLO class; emits a
  :class:`PlacementPlan` plus the page-out / fault-in / migrate actions
  that reconcile the current placement to it.
* :class:`ModelManager` — actuates plans: hot models live as
  :class:`~mxnet_tpu.serving.server.InferenceServer` replicas
  (registered with ``model``/``tenant`` meta so per-model routers can
  filter one shared registry); cold models are paged out to AOT bundles
  and faulted back in warm via ``from_checkpoint(attach_aot=True)``
  with zero cold-bucket runs.
* :class:`TenantQuotas` — per-tenant admission control: token-bucket
  rate limits plus weighted fair sharing under pressure, so one
  tenant's flood sheds THAT tenant (429 + Retry-After), never its
  neighbours.
* :class:`FrontDoor` — the multi-model request path: model name in the
  URL path or ``X-MXNet-Model`` header, tenant in ``X-Tenant``, routed
  through per-model router views over one replica registry.

Every planner decision is a ``mxnet_tpu.faults`` dotted op
(``platform.plan`` / ``platform.page_out`` / ``platform.fault_in`` /
``platform.migrate``), so the chaos harness drives placement churn
deterministically.
"""
from .spec import ModelSpec
from .planner import DevicePool, PlacementPlan, PlacementPlanner
from .quotas import TenantQuotaExceededError, TenantQuotas
from .manager import ModelManager, PlatformMetrics
from .frontdoor import FrontDoor

__all__ = [
    "ModelSpec", "DevicePool", "PlacementPlan", "PlacementPlanner",
    "TenantQuotas", "TenantQuotaExceededError", "ModelManager",
    "PlatformMetrics", "FrontDoor",
]

"""Multi-tenant model platform over the serving fleet.

The serving stack below this package (router, autoscaler, registry)
assumes ONE model per fleet.  The platform turns that fleet into a
shared pool serving many models for many tenants:

* :class:`ModelSpec` — one registered model: checkpoint prefix, input
  shapes, tenant, SLO class, and an estimated device **footprint**
  (param bytes via ``sharding.param_bytes`` / checkpoint size, KV-pool
  bytes for generator specs, executable overhead refined from
  ``hlo_analysis`` cost analysis once the model has run).
* :class:`PlacementPlanner` — bin-packs registered models onto a
  :class:`DevicePool` by footprint, demand, and SLO class; emits a
  :class:`PlacementPlan` plus the page-out / fault-in / migrate actions
  that reconcile the current placement to it.
* :class:`ModelManager` — actuates plans: hot models live as
  :class:`~mxnet_tpu.serving.server.InferenceServer` replicas
  (registered with ``model``/``tenant`` meta so per-model routers can
  filter one shared registry); cold models are paged out to AOT bundles
  and faulted back in warm via ``from_checkpoint(attach_aot=True)``
  with zero cold-bucket runs.
* :class:`TenantQuotas` — per-tenant admission control: token-bucket
  rate limits plus weighted fair sharing under pressure, so one
  tenant's flood sheds THAT tenant (429 + Retry-After), never its
  neighbours.
* :class:`HealthPlane` — failure-domain liveness: the pool's devices
  group into host-sized domains probed via registry heartbeats and
  injectable faults; K consecutive misses flip a domain dead, which
  drives the manager's **degradation ladder** — reap dead replicas,
  re-fault evicted models warm onto survivors, brown out lower SLO
  classes (:class:`BrownoutError`, 503 + Retry-After) when not
  everything fits, and gracefully page out the lowest-score models
  (drained, streams handed off mid-generate, transcripts
  bit-identical).
* :class:`FrontDoor` — the multi-model request path: model name in the
  URL path or ``X-MXNet-Model`` header, tenant in ``X-Tenant``, routed
  through per-model router views over one replica registry.  Arrivals
  during a model's fault-in window get 503 + Retry-After with the
  fault-in ETA (:class:`FaultInProgressError`).

Every planner decision is a ``mxnet_tpu.faults`` dotted op
(``platform.plan`` / ``platform.page_out`` / ``platform.fault_in`` /
``platform.migrate`` / ``platform.health.domain.<d>``), so the chaos
harness drives placement churn and host loss deterministically.
"""
from .spec import ModelSpec
from .planner import DevicePool, PlacementPlan, PlacementPlanner
from .quotas import BrownoutError, TenantQuotaExceededError, TenantQuotas
from .healthplane import HealthPlane
from .manager import FaultInProgressError, ModelManager, PlatformMetrics
from .frontdoor import FrontDoor

__all__ = [
    "ModelSpec", "DevicePool", "PlacementPlan", "PlacementPlanner",
    "TenantQuotas", "TenantQuotaExceededError", "BrownoutError",
    "HealthPlane", "ModelManager", "PlatformMetrics",
    "FaultInProgressError", "FrontDoor",
]

"""PlacementPlanner — bin-pack models onto the device pool by demand.

The planner answers one question every replan tick: *which models
deserve to be resident right now, and where?*  Inputs are the
registered :class:`~mxnet_tpu.platform.spec.ModelSpec` footprints, a
demand estimate per model (the manager's request-rate EWMA), and the
current placement.  Output is a :class:`PlacementPlan` plus the action
list (page-out / fault-in / migrate) that reconciles reality to it.

The packing itself is first-fit-decreasing — the classic bin-packing
heuristic: score models by ``demand x weight`` (SLO rank breaks ties:
interactive beats generate beats batch), walk them best-first, place
each on the device with the most free bytes that still fits it.
Models that fit nowhere are planned *paged* — they live as AOT bundles
on disk until demand earns them a slot.  Sticky placement: a model
already resident on a device that still fits stays there (a replan must
not churn placements for equal-score shuffles — migrations cost warm
fault-ins).
"""
from __future__ import annotations

import threading
from typing import Dict, List, Optional

from .. import faults
from .. import telemetry as _telemetry
from ..base import MXNetError, env, register_env

__all__ = ["DevicePool", "PlacementPlan", "PlacementPlanner"]

register_env("MXNET_PLATFORM_DEVICE_BYTES", 16 << 30, int,
             "Per-device memory budget (bytes) the placement planner "
             "packs model footprints against when the pool does not "
             "declare one explicitly.")


class DevicePool:
    """The memory budget the planner packs against: N devices of B
    bytes.  Defaults to the visible JAX device count and the
    ``MXNET_PLATFORM_DEVICE_BYTES`` budget — tests pass tiny explicit
    pools to simulate '10 models, room for 4'."""

    def __init__(self, num_devices: Optional[int] = None,
                 bytes_per_device: Optional[int] = None):
        if num_devices is None:
            import jax

            num_devices = len(jax.devices())
        self.num_devices = int(num_devices)
        if self.num_devices < 1:
            raise MXNetError("device pool needs >= 1 device")
        self.bytes_per_device = (
            env("MXNET_PLATFORM_DEVICE_BYTES", 16 << 30, int)
            if bytes_per_device is None else int(bytes_per_device))

    def total_bytes(self) -> int:
        return self.num_devices * self.bytes_per_device

    def describe(self) -> dict:
        return {"num_devices": self.num_devices,
                "bytes_per_device": self.bytes_per_device}


class PlacementPlan:
    """One planner output: ``resident`` maps model name -> device id,
    ``paged`` lists the models living as bundles, ``actions`` is the
    reconciliation the manager actuates (in order: page-outs free the
    memory the fault-ins then claim)."""

    __slots__ = ("resident", "paged", "actions", "free_bytes")

    def __init__(self, resident: Dict[str, int], paged: List[str],
                 actions: List[dict], free_bytes: Dict[int, int]):
        self.resident = resident
        self.paged = paged
        self.actions = actions
        self.free_bytes = free_bytes

    def describe(self) -> dict:
        return {"resident": dict(self.resident), "paged": list(self.paged),
                "actions": [dict(a) for a in self.actions],
                "free_bytes": dict(self.free_bytes)}


class PlacementPlanner:
    """First-fit-decreasing packer with sticky placement."""

    def __init__(self, pool: DevicePool):
        self.pool = pool
        self._lock = threading.Lock()

    def plan(self, specs: Dict[str, object], demand: Dict[str, float],
             current: Optional[Dict[str, int]] = None) -> PlacementPlan:
        """Pack ``specs`` (name -> ModelSpec) onto the pool.

        ``demand`` is requests/s per model (missing == 0); ``current``
        is the live placement (name -> device) used both for stickiness
        and to derive the page-out/fault-in/migrate action diff.
        """
        faults.fire("platform.plan")
        current = dict(current or {})
        with self._lock:
            order = sorted(
                specs.values(),
                key=lambda s: (-(demand.get(s.name, 0.0) * s.weight),
                               s.slo_rank(), s.name))
            free = {d: self.pool.bytes_per_device
                    for d in range(self.pool.num_devices)}
            resident: Dict[str, int] = {}
            paged: List[str] = []
            for spec in order:
                need = spec.footprint()["total"]
                if need > self.pool.bytes_per_device:
                    raise MXNetError(
                        "model %r (%d bytes) cannot fit any device "
                        "(%d bytes)" % (spec.name, need,
                                        self.pool.bytes_per_device))
                # sticky: keep the current device while it still fits
                dev = current.get(spec.name)
                if dev is not None and dev in free and free[dev] >= need:
                    free[dev] -= need
                    resident[spec.name] = dev
                    continue
                # first fit on the most-free device (best-fit-decreasing
                # by free space keeps large contiguous headroom)
                cand = max(free, key=lambda d: (free[d], -d))
                if free[cand] >= need:
                    free[cand] -= need
                    resident[spec.name] = cand
                else:
                    paged.append(spec.name)

        actions = []
        for name in sorted(current):
            if name not in resident:
                actions.append({"op": "page_out", "model": name,
                                "device": current[name]})
        for name, dev in sorted(resident.items()):
            old = current.get(name)
            if old is None:
                actions.append({"op": "fault_in", "model": name,
                                "device": dev})
            elif old != dev:
                actions.append({"op": "migrate", "model": name,
                                "src": old, "dst": dev})
        plan = PlacementPlan(resident, paged, actions, free)
        _telemetry.log_event(
            "platform_plan", resident=len(resident), paged=len(paged),
            actions=len(actions))
        return plan

"""PlacementPlanner — bin-pack models onto the device pool by demand.

The planner answers one question every replan tick: *which models
deserve to be resident right now, and where?*  Inputs are the
registered :class:`~mxnet_tpu.platform.spec.ModelSpec` footprints, a
demand estimate per model (the manager's request-rate EWMA), and the
current placement.  Output is a :class:`PlacementPlan` plus the action
list (page-out / fault-in / migrate) that reconciles reality to it.

The packing itself is first-fit-decreasing — the classic bin-packing
heuristic: score models by ``demand x weight`` (SLO rank breaks ties:
interactive beats generate beats batch), walk them best-first, place
each on the device with the most free bytes that still fits it.
Models that fit nowhere are planned *paged* — they live as AOT bundles
on disk until demand earns them a slot.  Sticky placement: a model
already resident on a device that still fits stays there (a replan must
not churn placements for equal-score shuffles — migrations cost warm
fault-ins).  Packing runs in two passes — a first copy of every model,
then the extra replicas — so capacity pressure (a dead host) sheds
redundancy before it sheds any model's availability.
"""
from __future__ import annotations

import threading
from typing import Dict, List, Optional

from .. import faults
from .. import telemetry as _telemetry
from ..base import MXNetError, env, register_env

__all__ = ["DevicePool", "PlacementPlan", "PlacementPlanner"]

register_env("MXNET_PLATFORM_DEVICE_BYTES", 16 << 30, int,
             "Per-device memory budget (bytes) the placement planner "
             "packs model footprints against when the pool does not "
             "declare one explicitly.")
register_env("MXNET_PLATFORM_DEVICES_PER_HOST", 0, int,
             "Devices per failure domain (host) for the placement "
             "planner's replica spreading and the health plane's "
             "domain grouping; 0 means all devices share one host.")


class DevicePool:
    """The memory budget the planner packs against: N devices of B
    bytes, grouped into failure domains of ``devices_per_host`` devices
    (host = domain: device ``d`` lives in domain ``d //
    devices_per_host``).  Defaults to the visible JAX device count, the
    ``MXNET_PLATFORM_DEVICE_BYTES`` budget, and one domain holding
    everything — tests pass tiny explicit pools to simulate '10 models,
    room for 4' or '2 hosts x 2 devices'."""

    def __init__(self, num_devices: Optional[int] = None,
                 bytes_per_device: Optional[int] = None,
                 devices_per_host: Optional[int] = None):
        if num_devices is None:
            import jax

            num_devices = len(jax.devices())
        self.num_devices = int(num_devices)
        if self.num_devices < 1:
            raise MXNetError("device pool needs >= 1 device")
        self.bytes_per_device = (
            env("MXNET_PLATFORM_DEVICE_BYTES", 16 << 30, int)
            if bytes_per_device is None else int(bytes_per_device))
        if devices_per_host is None:
            devices_per_host = env("MXNET_PLATFORM_DEVICES_PER_HOST", 0,
                                   int) or self.num_devices
        self.devices_per_host = int(devices_per_host)
        if self.devices_per_host < 1:
            raise MXNetError("devices_per_host must be >= 1")

    def total_bytes(self) -> int:
        return self.num_devices * self.bytes_per_device

    def domain_of(self, device: int) -> int:
        """The failure domain (host index) a device belongs to."""
        return int(device) // self.devices_per_host

    @property
    def num_domains(self) -> int:
        return (self.num_devices + self.devices_per_host - 1) \
            // self.devices_per_host

    def devices_in(self, domain: int) -> List[int]:
        lo = int(domain) * self.devices_per_host
        return list(range(lo, min(lo + self.devices_per_host,
                                  self.num_devices)))

    def describe(self) -> dict:
        return {"num_devices": self.num_devices,
                "bytes_per_device": self.bytes_per_device,
                "devices_per_host": self.devices_per_host,
                "num_domains": self.num_domains}


class PlacementPlan:
    """One planner output: ``resident`` maps model name -> primary
    device id, ``paged`` lists the models living as bundles, ``actions``
    is the reconciliation the manager actuates (in order: page-outs free
    the memory the fault-ins then claim).  ``replica_devices`` is the
    full per-replica placement (``name -> {replica_index: device}``);
    for single-replica models it is just ``{0: resident[name]}``."""

    __slots__ = ("resident", "paged", "actions", "free_bytes",
                 "replica_devices")

    def __init__(self, resident: Dict[str, int], paged: List[str],
                 actions: List[dict], free_bytes: Dict[int, int],
                 replica_devices: Optional[Dict[str, Dict[int, int]]] = None):
        self.resident = resident
        self.paged = paged
        self.actions = actions
        self.free_bytes = free_bytes
        self.replica_devices = ({n: {0: d} for n, d in resident.items()}
                                if replica_devices is None
                                else replica_devices)

    def describe(self) -> dict:
        return {"resident": dict(self.resident), "paged": list(self.paged),
                "actions": [dict(a) for a in self.actions],
                "free_bytes": dict(self.free_bytes),
                "replica_devices": {n: dict(v) for n, v
                                    in self.replica_devices.items()}}


class PlacementPlanner:
    """First-fit-decreasing packer with sticky placement."""

    def __init__(self, pool: DevicePool):
        self.pool = pool
        self._lock = threading.Lock()

    def plan(self, specs: Dict[str, object], demand: Dict[str, float],
             current: Optional[Dict[str, int]] = None,
             alive_devices=None,
             current_replicas: Optional[Dict[str, Dict[int, int]]] = None
             ) -> PlacementPlan:
        """Pack ``specs`` (name -> ModelSpec) onto the pool.

        ``demand`` is requests/s per model (missing == 0); ``current``
        is the live placement (name -> primary device) used both for
        stickiness and to derive the page-out/fault-in/migrate action
        diff.  ``alive_devices`` (from the health plane) restricts
        packing to surviving capacity — dead devices hold nothing, and
        replicas stuck on them migrate.  ``current_replicas`` is the
        full per-replica placement for multi-replica models (``name ->
        {replica_index: device}``); replicas of one model spread across
        failure domains when capacity allows.
        """
        faults.fire("platform.plan")
        current = dict(current or {})
        olds_by_model: Dict[str, Dict[int, int]] = {
            n: dict(v) for n, v in (current_replicas or {}).items()}
        for name, dev in current.items():
            olds_by_model.setdefault(name, {0: dev})
        with self._lock:
            order = sorted(
                specs.values(),
                key=lambda s: (-(demand.get(s.name, 0.0) * s.weight),
                               s.slo_rank(), s.name))
            devices = (range(self.pool.num_devices) if alive_devices is None
                       else sorted({int(d) for d in alive_devices
                                    if 0 <= int(d) < self.pool.num_devices}))
            free = {d: self.pool.bytes_per_device for d in devices}
            resident: Dict[str, int] = {}
            replica_devices: Dict[str, Dict[int, int]] = {}
            paged: List[str] = []
            jobs = []
            for spec in order:
                need = spec.footprint()["total"]
                if need > self.pool.bytes_per_device:
                    raise MXNetError(
                        "model %r (%d bytes) cannot fit any device "
                        "(%d bytes)" % (spec.name, need,
                                        self.pool.bytes_per_device))
                olds = olds_by_model.get(spec.name, {})
                # surviving replica indices first: after a host loss the
                # live copy keeps its seat and the dead index becomes
                # the expendable extra
                idxs = sorted(range(getattr(spec, "replicas", 1)),
                              key=lambda i: (i not in olds, i))
                jobs.append((spec, need, olds, idxs,
                             {}))  # type: ignore[var-annotated]
            # two passes: a first copy of every model, then the extra
            # replicas — under capacity pressure a model must lose
            # redundancy before any other model loses availability
            for lo, hi in ((0, 1), (1, None)):
                for spec, need, olds, idxs, placed in jobs:
                    for i in idxs[lo:hi]:
                        # sticky: keep the current device while it still
                        # fits (and is alive — dead devices are not in
                        # free)
                        dev = olds.get(i)
                        if dev is not None and dev in free and \
                                free[dev] >= need:
                            free[dev] -= need
                            placed[i] = dev
                            continue
                        if not free:
                            continue
                        # best fit on the most-free device, preferring a
                        # failure domain this model does not occupy yet —
                        # losing one host must degrade capacity, not
                        # availability
                        used_doms = {self.pool.domain_of(d)
                                     for d in placed.values()}
                        cand = max(free, key=lambda d: (
                            self.pool.domain_of(d) not in used_doms,
                            free[d], -d))
                        if free[cand] >= need:
                            free[cand] -= need
                            placed[i] = cand
            for spec, _need, _olds, _idxs, placed in jobs:
                if placed:
                    resident[spec.name] = placed[min(placed)]
                    replica_devices[spec.name] = placed
                else:
                    paged.append(spec.name)

        actions = []
        for name in sorted(olds_by_model):
            if name in resident:
                continue
            olds = olds_by_model[name]
            multi = len(olds) > 1
            for i in sorted(olds):
                act = {"op": "page_out", "model": name, "device": olds[i]}
                if multi or i != 0:
                    act["replica"] = i
                actions.append(act)
        for name in sorted(resident):
            placed = replica_devices[name]
            olds = olds_by_model.get(name, {})
            spec = specs.get(name)
            multi = max(len(placed), len(olds),
                        getattr(spec, "replicas", 1) if spec else 1) > 1
            for i in sorted(set(olds) | set(placed)):
                old, new = olds.get(i), placed.get(i)
                act = None
                if old is None and new is not None:
                    act = {"op": "fault_in", "model": name, "device": new}
                elif new is None and old is not None:
                    act = {"op": "page_out", "model": name, "device": old}
                elif old != new:
                    act = {"op": "migrate", "model": name, "src": old,
                           "dst": new}
                if act is not None:
                    if multi or i != 0:
                        act["replica"] = i
                    actions.append(act)
        plan = PlacementPlan(resident, paged, actions, free,
                             replica_devices)
        _telemetry.log_event(
            "platform_plan", resident=len(resident), paged=len(paged),
            actions=len(actions), alive=len(free))
        return plan

"""HealthPlane — failure-domain liveness for the platform.

The placement planner packs devices as if they live forever; real hosts
do not.  This module groups the :class:`~.planner.DevicePool`'s devices
into **failure domains** (host = domain, ``DevicePool.devices_per_host``
devices each) and tracks one alive/dead bit per domain from two
signals:

* **Registry heartbeats** — a dead host does not deregister, its
  heartbeats just stop.  Replicas register with a ``device`` meta label;
  a domain that *had* live replicas and now shows none (TTL-evicted from
  the :class:`~mxnet_tpu.serving.registry.ReplicaRegistry`) counts a
  probe miss.
* **Injectable faults** — every probe fires one dotted op per domain
  (``platform.health.domain.<d>``); an injected error IS a probe miss
  for that domain, so chaos specs kill a host deterministically
  (``platform.health.domain.0:ioerr=1.0`` under ``MXNET_FAULTS_SEED``).

Debounce mirrors the router's probe contract: ``MXNET_PLATFORM_HEALTH_FAILS``
consecutive misses flip a domain down, ONE success flips it back up — a
slow heartbeat under load must not trigger the degradation ladder.
Every transition is a structured telemetry event and a callback into the
:class:`~.manager.ModelManager`, which reacts by reaping dead replicas,
re-planning over the surviving capacity, and walking the degradation
ladder.
"""
from __future__ import annotations

import threading
from typing import Dict, List, Optional

from .. import faults
from .. import telemetry as _telemetry
from ..base import env, register_env

__all__ = ["HealthPlane"]

register_env("MXNET_PLATFORM_HEALTH_FAILS", 3, int,
             "Consecutive health-probe misses before the platform marks "
             "a failure domain (host) dead; recovery takes one success.")
register_env("MXNET_PLATFORM_HEALTH_PROBE_MS", 500.0, float,
             "Background health-probe period of a started HealthPlane "
             "(0 disables the loop; probe() stays callable).")


class HealthPlane:
    """Per-failure-domain liveness over a :class:`~.planner.DevicePool`.

    Parameters
    ----------
    pool : DevicePool
        Supplies the device -> domain grouping.
    registry : ReplicaRegistry, optional
        Heartbeat source; without one only the faults hooks and explicit
        ``mark_down``/``mark_up`` drive transitions.
    probe_fails : int, optional
        Debounce threshold; default ``MXNET_PLATFORM_HEALTH_FAILS``.
    on_change : callable, optional
        ``on_change(domain, alive)`` fired (outside the lock) on every
        transition — the manager's degradation-ladder entry point.
    """

    def __init__(self, pool, registry=None,
                 probe_fails: Optional[int] = None, on_change=None):
        self.pool = pool
        self.registry = registry
        self._k = max(1, env("MXNET_PLATFORM_HEALTH_FAILS", 3, int)
                      if probe_fails is None else int(probe_fails))
        self._on_change = on_change
        self._lock = threading.Lock()
        self._alive: Dict[int, bool] = {
            d: True for d in range(pool.num_domains)}
        self._misses: Dict[int, int] = {
            d: 0 for d in range(pool.num_domains)}
        # domains that have ever shown live registry replicas: only those
        # can miss on an empty heartbeat view (a domain nothing was ever
        # placed on is not dead, just idle)
        self._expected = set()
        self._loop_stop = threading.Event()
        self._loop_thread = None

    # -- probing -----------------------------------------------------------
    def probe(self) -> List[tuple]:
        """One liveness sweep; returns the ``(domain, alive)``
        transitions it caused (empty when nothing changed)."""
        faults.fire("platform.health.probe")
        present = set()
        if self.registry is not None:
            meta = self.registry.live().get("meta", {})
            for rec in meta.values():
                dev = rec.get("device")
                if dev is not None:
                    present.add(self.pool.domain_of(int(dev)))
        transitions = []
        with self._lock:
            for dom in range(self.pool.num_domains):
                ok = True
                try:
                    faults.fire("platform.health.domain.%d" % dom)
                except Exception:
                    ok = False
                if ok and self.registry is not None:
                    if dom in present:
                        self._expected.add(dom)
                    elif dom in self._expected:
                        # had replicas, heartbeats stopped: TTL eviction
                        # emptied the domain without a deregister — the
                        # dead-host signature
                        ok = False
                if ok:
                    self._misses[dom] = 0
                    if not self._alive[dom] and \
                            (self.registry is None or dom in present):
                        # recovery needs positive evidence when a
                        # registry is attached: a reaped domain is empty
                        # AND dead until a replica heartbeats from it
                        # again (or mark_up re-admits it explicitly)
                        self._alive[dom] = True
                        transitions.append((dom, True))
                else:
                    self._misses[dom] += 1
                    if self._alive[dom] and self._misses[dom] >= self._k:
                        self._alive[dom] = False
                        self._expected.discard(dom)
                        transitions.append((dom, False))
        for dom, up in transitions:
            self._announce(dom, up)
        return transitions

    def _announce(self, dom, up):
        _telemetry.log_event("platform_domain_health", domain=dom,
                             alive=up,
                             devices=self.pool.devices_in(dom))
        if self._on_change is not None:
            try:
                self._on_change(dom, up)
            except Exception:
                pass  # a ladder failure must not kill the prober

    def mark_down(self, domain: int):
        """Explicit administrative/chaos transition (no debounce)."""
        with self._lock:
            changed = self._alive.get(domain, True)
            self._alive[domain] = False
            self._misses[domain] = self._k
            self._expected.discard(domain)
        if changed:
            self._announce(domain, False)

    def mark_up(self, domain: int):
        with self._lock:
            changed = not self._alive.get(domain, True)
            self._alive[domain] = True
            self._misses[domain] = 0
        if changed:
            self._announce(domain, True)

    # -- queries -----------------------------------------------------------
    def is_alive(self, device: int) -> bool:
        with self._lock:
            return self._alive.get(self.pool.domain_of(device), True)

    def alive_domains(self) -> List[int]:
        with self._lock:
            return sorted(d for d, ok in self._alive.items() if ok)

    def dead_domains(self) -> List[int]:
        with self._lock:
            return sorted(d for d, ok in self._alive.items() if not ok)

    def alive_devices(self) -> List[int]:
        with self._lock:
            return [d for d in range(self.pool.num_devices)
                    if self._alive.get(self.pool.domain_of(d), True)]

    def describe(self) -> dict:
        with self._lock:
            return {"domains": {d: {"alive": ok,
                                    "misses": self._misses.get(d, 0),
                                    "devices": self.pool.devices_in(d)}
                                for d, ok in sorted(self._alive.items())},
                    "probe_fails": self._k}

    # -- lifecycle ---------------------------------------------------------
    def start(self, probe_ms: Optional[float] = None):
        """Start the background probe loop (no-op when the period
        resolves to 0)."""
        period_ms = env("MXNET_PLATFORM_HEALTH_PROBE_MS", 500.0, float) \
            if probe_ms is None else float(probe_ms)
        if period_ms <= 0 or self._loop_thread is not None:
            return self
        period_s = period_ms / 1e3

        def loop():
            while not self._loop_stop.wait(period_s):
                try:
                    self.probe()
                except Exception:
                    pass  # one bad sweep must not kill the prober

        self._loop_thread = threading.Thread(
            target=loop, name="mxtpu-platform-health", daemon=True)
        self._loop_thread.start()
        return self

    def close(self):
        self._loop_stop.set()
        if self._loop_thread is not None:
            self._loop_thread.join(timeout=5)
            self._loop_thread = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

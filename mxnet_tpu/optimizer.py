"""Optimizers — weight update rules.

TPU-native counterpart of /root/reference/python/mxnet/optimizer.py:279-669.
Same registry + class surface (SGD/DCASGD/NAG/SGLD/ccSGD/Adam/AdaGrad/
RMSProp/AdaDelta/Test + Updater/get_updater); the update rules delegate to
the fused update *ops* (ops/optimizer_ops.py — one XLA kernel per update,
like the reference's fused CUDA kernels) where one exists, and to jnp
expressions otherwise.  States are NDArrays so the kvstore updater path and
Module.update share one implementation.
"""
from __future__ import annotations

import logging
import math
import pickle
from typing import Dict, Optional

import numpy as np

from .ndarray import NDArray
from . import guardian as _guardian
from . import ndarray as nd


def zeros(shape, ctx=None, dtype=None, like=None):
    """Zeros for optimizer state.  When ``like`` (the weight) is given the
    state inherits its sharding, so momentum/variance buffers live on the
    same mesh as replicated parameters instead of a single device."""
    if like is not None:
        import jax.numpy as jnp

        return NDArray(jnp.zeros_like(like._data), like.context)
    from .ndarray import zeros as _nd_zeros

    return _nd_zeros(shape, ctx, dtype=dtype)

__all__ = ["Optimizer", "SGD", "DCASGD", "NAG", "SGLD", "ccSGD", "Adam",
           "AdaGrad", "RMSProp", "AdaDelta", "Test", "Updater", "get_updater",
           "create", "register"]


class Optimizer:
    """Base optimizer (reference optimizer.py:10-270): owns lr/wd multipliers,
    per-index update counts, gradient rescale/clip, and the state dict."""

    opt_registry: Dict[str, type] = {}

    @staticmethod
    def register(klass):
        assert isinstance(klass, type)
        name = klass.__name__.lower()
        if name in Optimizer.opt_registry:
            logging.warning("New optimizer %s is overriding existing one", name)
        Optimizer.opt_registry[name] = klass
        return klass

    @staticmethod
    def create_optimizer(name, **kwargs):
        if name.lower() in Optimizer.opt_registry:
            return Optimizer.opt_registry[name.lower()](**kwargs)
        raise ValueError("Cannot find optimizer %s" % name)

    def __init__(self, rescale_grad=1.0, param_idx2name=None, wd=0.0,
                 clip_gradient=None, learning_rate=0.01, lr_scheduler=None,
                 sym=None, begin_num_update=0):
        self.rescale_grad = rescale_grad
        self.lr = learning_rate
        self.lr_scheduler = lr_scheduler
        if lr_scheduler is not None:
            self.lr_scheduler.base_lr = learning_rate
        self.wd = wd
        self.lr_mult = {}
        self.wd_mult = {}
        self.begin_num_update = begin_num_update
        self.num_update = begin_num_update
        self._index_update_count: Dict[int, int] = {}
        self.clip_gradient = clip_gradient
        if param_idx2name is None:
            param_idx2name = {}
        assert isinstance(param_idx2name, dict), \
            "param_idx2name should be a dict of param indexes to names."
        self.idx2name = param_idx2name.copy()
        self.sym = sym
        self.set_lr_mult({})
        self.set_wd_mult({})

    def __getstate__(self):
        """Drop the symbol when pickling: it is consulted only at
        construction (set_lr_mult/set_wd_mult read its attrs into plain
        dicts, kept) and routinely holds unpicklable op closures — an
        optimizer shipped to kvstore servers or journaled into a snapshot
        must not drag the whole graph along."""
        state = self.__dict__.copy()
        state["sym"] = None
        return state

    def create_state(self, index, weight):
        """Create the state NDArray(s) for ``index`` (None if stateless)."""
        return None

    def update(self, index, weight, grad, state):
        raise NotImplementedError("virtual Optimizer.update")

    # -- fused-step support ------------------------------------------------
    #: set True by rules that draw noise inside ``pure_update``
    needs_rng = False

    def pure_update(self, weight, grad, state, lr, wd, t, rng=None):
        """Traceable functional form of :meth:`update` for the fused train
        step (Executor.fused_step): given jax arrays, return
        ``(new_weight, new_state)`` with no side effects.  ``lr``/``wd`` are
        traced scalars with per-param multipliers already applied; ``t`` is
        the traced update count (bias correction); ``rng`` a PRNG key when
        :attr:`needs_rng`.  Optimizers that don't implement it fall back to
        the eager per-key path.  Counterpart of the reference's fused update
        kernels (src/operator/optimizer_op.cc:18-73) running *inside* the
        jitted step instead of as separate engine pushes."""
        raise NotImplementedError

    @classmethod
    def has_pure_update(cls):
        return cls.pure_update is not Optimizer.pure_update

    def _pure_grad(self, weight, grad, wd=None):
        """Shared rescale/clip/wd preamble in traced form."""
        import jax.numpy as jnp

        g = grad.astype(weight.dtype) if grad.dtype != weight.dtype else grad
        g = g * self.rescale_grad
        if self.clip_gradient is not None and self.clip_gradient > 0:
            g = jnp.clip(g, -self.clip_gradient, self.clip_gradient)
        if wd is not None:
            g = g + wd * weight
        return g

    # -- multipliers -------------------------------------------------------
    def set_lr_scale(self, args_lrscale):  # deprecated reference surface
        raise DeprecationWarning("Use set_lr_mult instead.")

    def set_lr_mult(self, args_lr_mult):
        self.lr_mult = {}
        if self.sym is not None:
            attr = self.sym.attr_dict()
            for name in self.sym.list_arguments():
                if name in attr and "__lr_mult__" in attr[name]:
                    self.lr_mult[name] = float(attr[name]["__lr_mult__"])
                elif name in attr and "lr_mult" in attr[name]:
                    self.lr_mult[name] = float(attr[name]["lr_mult"])
        self.lr_mult.update(args_lr_mult)

    def set_wd_mult(self, args_wd_mult):
        """No-wd default for biases/gammas/betas, like the reference
        (optimizer.py set_wd_mult: params not ending in _weight/_gamma get
        wd_mult 0)."""
        self.wd_mult = {}
        for n in self.idx2name.values():
            if not (n.endswith("_weight") or n.endswith("_gamma")):
                self.wd_mult[n] = 0.0
        if self.sym is not None:
            attr = self.sym.attr_dict()
            for name in self.sym.list_arguments():
                if name in attr and "__wd_mult__" in attr[name]:
                    self.wd_mult[name] = float(attr[name]["__wd_mult__"])
                elif name in attr and "wd_mult" in attr[name]:
                    self.wd_mult[name] = float(attr[name]["wd_mult"])
        self.wd_mult.update(args_wd_mult)

    # -- bookkeeping -------------------------------------------------------
    def _update_count(self, index):
        if index not in self._index_update_count:
            self._index_update_count[index] = self.begin_num_update
        self._index_update_count[index] += 1
        self.num_update = max(self._index_update_count[index], self.num_update)

    def _get_lr(self, index):
        if self.lr_scheduler is not None:
            lr = self.lr_scheduler(self.num_update)
        else:
            lr = self.lr
        if index in self.lr_mult:
            lr *= self.lr_mult[index]
        elif index in self.idx2name:
            lr *= self.lr_mult.get(self.idx2name[index], 1.0)
        if _guardian._governor is not None:
            # guardian re-warm ramp after an anomaly burst; a plain
            # None-check when no ramp is live
            lr *= _guardian.current_lr_mult()
        return lr

    def _get_wd(self, index):
        wd = self.wd
        if index in self.wd_mult:
            wd *= self.wd_mult[index]
        elif index in self.idx2name:
            wd *= self.wd_mult.get(self.idx2name[index], 1.0)
        return wd


# convenience wrapper for Optimizer.create_optimizer
create = Optimizer.create_optimizer
register = Optimizer.register


def _clip(g, bound):
    import jax.numpy as jnp

    if bound is not None and bound > 0:
        return jnp.clip(g, -bound, bound)
    return g


@register
class SGD(Optimizer):
    """SGD with momentum and weight decay (reference optimizer.py:279),
    delegating to the fused sgd_update/sgd_mom_update ops."""

    def __init__(self, momentum=0.0, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return None
        return zeros(None, like=weight)

    def update(self, index, weight, grad, state):
        assert isinstance(weight, NDArray) and isinstance(grad, NDArray)
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        kwargs = dict(lr=lr, wd=wd, rescale_grad=self.rescale_grad,
                      clip_gradient=self.clip_gradient
                      if self.clip_gradient is not None else -1.0)
        if state is not None:
            nd.sgd_mom_update(weight, grad, state, out=weight,
                              momentum=self.momentum, **kwargs)
        else:
            nd.sgd_update(weight, grad, out=weight, **kwargs)

    def pure_update(self, weight, grad, state, lr, wd, t, rng=None):
        g = self._pure_grad(weight, grad, wd)
        if state is None:
            return weight - lr * g, None
        new_mom = self.momentum * state - lr * g
        return weight + new_mom, new_mom


@register
class DCASGD(Optimizer):
    """Delay-compensated async SGD (reference optimizer.py:325)."""

    def __init__(self, momentum=0.0, lamda=0.04, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum
        self.weight_previous = {}
        self.lamda = lamda

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return (None, weight.copy())
        return (zeros(None, like=weight),
                weight.copy())

    def update(self, index, weight, grad, state):
        import jax.numpy as jnp

        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        g = grad._data * self.rescale_grad
        g = _clip(g, self.clip_gradient)
        mom, previous_weight = state
        comp = g + wd * weight._data + self.lamda * g * g * (
            weight._data - previous_weight._data)
        if mom is not None:
            new_mom = self.momentum * mom._data - lr * comp
            mom._set(new_mom)
            delta = new_mom
        else:
            delta = -lr * comp
        previous_weight._set(weight._data)
        weight._set(weight._data + delta)

    def pure_update(self, weight, grad, state, lr, wd, t, rng=None):
        g = self._pure_grad(weight, grad)
        mom, prev = state
        comp = g + wd * weight + self.lamda * g * g * (weight - prev)
        if mom is not None:
            new_mom = self.momentum * mom - lr * comp
            delta = new_mom
        else:
            new_mom = None
            delta = -lr * comp
        return weight + delta, (new_mom, weight)


@register
class NAG(SGD):
    """Nesterov accelerated SGD (reference optimizer.py:380)."""

    def update(self, index, weight, grad, state):
        import jax.numpy as jnp

        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        g = grad._data * self.rescale_grad
        g = _clip(g, self.clip_gradient)
        if state is not None:
            mom = state._data * self.momentum
            gw = g + wd * weight._data
            mom = mom + gw
            gw = gw + self.momentum * mom
            state._set(mom)
            weight._set(weight._data - lr * gw)
        else:
            assert self.momentum == 0.0
            weight._set(weight._data - lr * (g + wd * weight._data))

    def pure_update(self, weight, grad, state, lr, wd, t, rng=None):
        g = self._pure_grad(weight, grad)
        gw = g + wd * weight
        if state is None:
            return weight - lr * gw, None
        new_mom = self.momentum * state + gw
        return weight - lr * (gw + self.momentum * new_mom), new_mom


@register
class SGLD(Optimizer):
    """Stochastic gradient Langevin dynamics (reference optimizer.py:416):
    gradient step + N(0, sqrt(lr)) noise for posterior sampling."""

    def update(self, index, weight, grad, state):
        from . import random as _random
        import jax

        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        g = grad._data * self.rescale_grad
        g = _clip(g, self.clip_gradient)
        noise = jax.random.normal(_random.next_key(), weight.shape,
                                  dtype=weight._data.dtype) * math.sqrt(lr)
        weight._set(weight._data - lr / 2 * (g + wd * weight._data) + noise)

    needs_rng = True

    def pure_update(self, weight, grad, state, lr, wd, t, rng=None):
        import jax
        import jax.numpy as jnp

        g = self._pure_grad(weight, grad, wd)
        noise = jax.random.normal(rng, weight.shape,
                                  dtype=weight.dtype) * jnp.sqrt(lr)
        return weight - lr / 2 * g + noise, None


@register
class ccSGD(SGD):
    """Same update as SGD; kept for API parity (reference's C++-side SGD,
    optimizer.py:445)."""


@register
class Adam(Optimizer):
    """Adam (reference optimizer.py:451) via the fused adam_update op, with
    the reference's bias-correction folded into the effective lr."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1 = beta1
        self.beta2 = beta2
        self.epsilon = epsilon

    def create_state(self, index, weight):
        return (zeros(None, like=weight),   # mean
                zeros(None, like=weight))   # var

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        t = self._index_update_count[index]
        coef1 = 1.0 - self.beta1 ** t
        coef2 = 1.0 - self.beta2 ** t
        lr *= math.sqrt(coef2) / coef1
        mean, var = state
        nd.adam_update(weight, grad, mean, var, out=weight, lr=lr, wd=wd,
                       beta1=self.beta1, beta2=self.beta2,
                       epsilon=self.epsilon, rescale_grad=self.rescale_grad,
                       clip_gradient=self.clip_gradient
                       if self.clip_gradient is not None else -1.0)

    def pure_update(self, weight, grad, state, lr, wd, t, rng=None):
        import jax.numpy as jnp

        g = self._pure_grad(weight, grad, wd)
        tf = t.astype(jnp.float32) if hasattr(t, "astype") else float(t)
        lr_t = lr * jnp.sqrt(1.0 - self.beta2 ** tf) / (1.0 - self.beta1 ** tf)
        mean, var = state
        new_mean = self.beta1 * mean + (1.0 - self.beta1) * g
        new_var = self.beta2 * var + (1.0 - self.beta2) * jnp.square(g)
        w = weight - lr_t * new_mean / (jnp.sqrt(new_var) + self.epsilon)
        return w, (new_mean, new_var)


@register
class AdaGrad(Optimizer):
    """AdaGrad (reference optimizer.py:499)."""

    def __init__(self, eps=1e-7, **kwargs):
        super().__init__(**kwargs)
        self.float_stable_eps = eps

    def create_state(self, index, weight):
        return zeros(None, like=weight)

    def update(self, index, weight, grad, state):
        import jax.numpy as jnp

        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        g = grad._data * self.rescale_grad
        g = _clip(g, self.clip_gradient)
        history = state._data + jnp.square(g)
        state._set(history)
        weight._set(weight._data - lr * (
            g / jnp.sqrt(history + self.float_stable_eps)
            + wd * weight._data))

    def pure_update(self, weight, grad, state, lr, wd, t, rng=None):
        import jax.numpy as jnp

        g = self._pure_grad(weight, grad)
        history = state + jnp.square(g)
        w = weight - lr * (g / jnp.sqrt(history + self.float_stable_eps)
                           + wd * weight)
        return w, history


@register
class RMSProp(Optimizer):
    """RMSProp (reference optimizer.py:536): Tieleman's variant by default,
    Graves' centered variant when ``centered=True``; delegates to the fused
    rmsprop_update / rmspropalex_update ops."""

    def __init__(self, learning_rate=0.001, gamma1=0.9, gamma2=0.9,
                 epsilon=1e-8, centered=False, clip_weights=None, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.gamma1 = gamma1
        self.gamma2 = gamma2
        self.centered = centered
        self.epsilon = epsilon
        self.clip_weights = clip_weights

    def create_state(self, index, weight):
        if self.centered:
            return (zeros(None, like=weight),  # n
                    zeros(None, like=weight),  # g
                    zeros(None, like=weight))  # delta
        return (zeros(None, like=weight),)     # n

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        kwargs = dict(lr=lr, wd=wd, rescale_grad=self.rescale_grad,
                      gamma1=self.gamma1, epsilon=self.epsilon,
                      clip_gradient=self.clip_gradient
                      if self.clip_gradient is not None else -1.0,
                      clip_weights=self.clip_weights
                      if self.clip_weights is not None else -1.0)
        if not self.centered:
            (n,) = state
            nd.rmsprop_update(weight, grad, n, out=weight, **kwargs)
        else:
            n, g, delta = state
            nd.rmspropalex_update(weight, grad, n, g, delta, out=weight,
                                  gamma2=self.gamma2, **kwargs)

    def pure_update(self, weight, grad, state, lr, wd, t, rng=None):
        import jax.numpy as jnp

        g = self._pure_grad(weight, grad, wd)
        if not self.centered:
            (n,) = state
            new_n = (1.0 - self.gamma1) * jnp.square(g) + self.gamma1 * n
            w = weight - lr * g / jnp.sqrt(new_n + self.epsilon)
            new_state = (new_n,)
        else:
            n, gs, delta = state
            new_n = (1.0 - self.gamma1) * jnp.square(g) + self.gamma1 * n
            new_g = (1.0 - self.gamma1) * g + self.gamma1 * gs
            new_delta = self.gamma2 * delta - lr * g / jnp.sqrt(
                new_n - jnp.square(new_g) + self.epsilon)
            w = weight + new_delta
            new_state = (new_n, new_g, new_delta)
        if self.clip_weights is not None and self.clip_weights > 0:
            w = jnp.clip(w, -self.clip_weights, self.clip_weights)
        return w, new_state


@register
class AdaDelta(Optimizer):
    """AdaDelta (reference optimizer.py:605)."""

    def __init__(self, rho=0.90, epsilon=1e-5, **kwargs):
        super().__init__(**kwargs)
        self.rho = rho
        self.epsilon = epsilon

    def create_state(self, index, weight):
        return (zeros(None, like=weight),  # E[g^2]
                zeros(None, like=weight))  # E[dx^2]

    def update(self, index, weight, grad, state):
        import jax.numpy as jnp

        self._update_count(index)
        wd = self._get_wd(index)
        g = grad._data * self.rescale_grad
        g = _clip(g, self.clip_gradient)
        acc_g, acc_delta = state
        new_acc_g = self.rho * acc_g._data + (1.0 - self.rho) * jnp.square(g)
        delta = (jnp.sqrt(acc_delta._data + self.epsilon)
                 / jnp.sqrt(new_acc_g + self.epsilon)) * g
        new_acc_delta = self.rho * acc_delta._data + \
            (1.0 - self.rho) * jnp.square(delta)
        acc_g._set(new_acc_g)
        acc_delta._set(new_acc_delta)
        weight._set(weight._data - (delta + wd * weight._data))

    def pure_update(self, weight, grad, state, lr, wd, t, rng=None):
        import jax.numpy as jnp

        g = self._pure_grad(weight, grad)
        acc_g, acc_delta = state
        new_acc_g = self.rho * acc_g + (1.0 - self.rho) * jnp.square(g)
        delta = (jnp.sqrt(acc_delta + self.epsilon)
                 / jnp.sqrt(new_acc_g + self.epsilon)) * g
        new_acc_delta = self.rho * acc_delta + (1.0 - self.rho) * jnp.square(delta)
        return weight - (delta + wd * weight), (new_acc_g, new_acc_delta)


@register
class Test(Optimizer):
    """Trivial test optimizer: weight += grad * rescale (reference
    optimizer.py:653)."""

    def create_state(self, index, weight):
        return zeros(None, like=weight)

    def update(self, index, weight, grad, state):
        weight._set(weight._data + grad._data * self.rescale_grad)
        state._set(weight._data)

    def pure_update(self, weight, grad, state, lr, wd, t, rng=None):
        w = weight + grad.astype(weight.dtype) * self.rescale_grad
        return w, w


class Updater:
    """Closure applying an optimizer on (index, grad, weight) — what runs on
    the kvstore (reference optimizer.py:669 get_updater)."""

    def __init__(self, optimizer: Optimizer):
        self.optimizer = optimizer
        self.states: Dict[int, object] = {}

    def __call__(self, index, grad, weight):
        if index not in self.states:
            self.states[index] = self.optimizer.create_state(index, weight)
        self.optimizer.update(index, weight, grad, self.states[index])

    def set_states(self, states):
        self.states = pickle.loads(states)

    def get_states(self):
        return pickle.dumps(self.states)


def get_updater(optimizer: Optimizer) -> Updater:
    return Updater(optimizer)

"""URI-scheme filesystem registry for the data-IO layer.

Reference parity: dmlc-core ``InputSplit::Create`` resolves data URIs by
scheme — plain paths and ``file://`` read the local filesystem, while
``hdfs://`` / ``s3://`` are compiled in behind ``USE_HDFS`` / ``USE_S3``
(reference ``make/config.mk:136-144``; every RecordIO iterator goes
through it, e.g. ``src/io/iter_image_det_recordio.cc:45``). The
TPU-native equivalent is a runtime registry instead of a build flag:
local IO is built in, and remote schemes are GATED — the image installs
no cloud clients, so ``hdfs://``/``s3://`` raise with instructions until
the user registers an opener backed by whatever client their
environment provides (fsspec, boto3, pyarrow.fs, a FUSE mount, ...).

    import mxnet_tpu as mx
    mx.filesystem.register_scheme("s3", my_s3_opener)
    it = mx.io.ImageRecordIter(path_imgrec="s3://bucket/train.rec", ...)

An opener is ``fn(uri, mode) -> file-like`` (binary modes get bytes;
``mode`` is the ``open()``-style string). All RecordIO-based readers and
writers (MXRecordIO, MXIndexedRecordIO, the record iterators, im2rec)
resolve through ``open_uri``.
"""
from __future__ import annotations

from typing import Callable, Dict

_SCHEMES: Dict[str, Callable] = {}

# schemes the reference ships build-gated support for; named in the
# error message so migrating users know the knob moved from compile
# time to run time
_KNOWN_REMOTE = ("hdfs", "s3")


def scheme_of(uri: str) -> str:
    """The URI's scheme, '' for plain local paths. A Windows drive
    letter ('C:/...') is not a scheme."""
    head, sep, _ = uri.partition("://")
    if not sep or len(head) <= 1:
        return ""
    return head.lower()


def local_path(uri: str):
    """The local filesystem path for ''/file:// URIs, else None. The ONE
    place local-vs-remote resolution lives — callers that need an
    existence check use this rather than re-deriving the rule."""
    scheme = scheme_of(uri)
    if scheme == "":
        return uri
    if scheme == "file":
        return uri[7:]
    return None


def register_scheme(scheme: str, opener: Callable) -> None:
    """Register ``opener(uri, mode) -> file-like`` for ``scheme``.
    Re-registering replaces (last wins); ``None`` unregisters."""
    scheme = scheme.lower().rstrip(":")
    if scheme in ("", "file") or len(scheme) == 1:
        # '' / 'file' are built-in local; single letters are treated as
        # Windows drive prefixes by scheme_of — an opener registered
        # under any of these would never be dispatched
        raise ValueError(
            "scheme %r cannot be registered: ''/file are built-in local "
            "and single-letter schemes collide with drive letters"
            % scheme)
    if opener is None:
        _SCHEMES.pop(scheme, None)
    else:
        _SCHEMES[scheme] = opener


def open_uri(uri: str, mode: str = "rb"):
    """Open ``uri`` through the scheme registry (local files built in)."""
    lp = local_path(uri)
    if lp is not None:
        return open(lp, mode)
    scheme = scheme_of(uri)
    opener = _SCHEMES.get(scheme)
    if opener is None:
        hint = (" (the reference gates %s:// behind USE_%s at build "
                "time, make/config.mk:136-144; here it is a runtime "
                "hook)" % (scheme, scheme.upper())
                if scheme in _KNOWN_REMOTE else "")
        raise IOError(
            "no filesystem registered for scheme %r (uri %r). Register "
            "one backed by your environment's client, e.g.\n"
            "    mx.filesystem.register_scheme(%r, "
            "lambda uri, mode: fsspec.open(uri, mode).open())%s"
            % (scheme, uri, scheme, hint))
    return opener(uri, mode)

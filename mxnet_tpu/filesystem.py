"""URI-scheme filesystem registry for the data-IO layer.

Reference parity: dmlc-core ``InputSplit::Create`` resolves data URIs by
scheme — plain paths and ``file://`` read the local filesystem, while
``hdfs://`` / ``s3://`` are compiled in behind ``USE_HDFS`` / ``USE_S3``
(reference ``make/config.mk:136-144``; every RecordIO iterator goes
through it, e.g. ``src/io/iter_image_det_recordio.cc:45``). The
TPU-native equivalent is a runtime registry instead of a build flag:
local IO is built in, and remote schemes are GATED — the image installs
no cloud clients, so ``hdfs://``/``s3://`` raise with instructions until
the user registers an opener backed by whatever client their
environment provides (fsspec, boto3, pyarrow.fs, a FUSE mount, ...).

    import mxnet_tpu as mx
    mx.filesystem.register_scheme("s3", my_s3_opener)
    it = mx.io.ImageRecordIter(path_imgrec="s3://bucket/train.rec", ...)

An opener is ``fn(uri, mode) -> file-like`` (binary modes get bytes;
``mode`` is the ``open()``-style string). All RecordIO-based readers and
writers (MXRecordIO, MXIndexedRecordIO, the record iterators, im2rec)
resolve through ``open_uri``.
"""
from __future__ import annotations

import os
import zlib
from typing import Callable, Dict, Optional

_SCHEMES: Dict[str, Callable] = {}

# schemes the reference ships build-gated support for; named in the
# error message so migrating users know the knob moved from compile
# time to run time
_KNOWN_REMOTE = ("hdfs", "s3")


def scheme_of(uri: str) -> str:
    """The URI's scheme, '' for plain local paths. A Windows drive
    letter ('C:/...') is not a scheme."""
    head, sep, _ = uri.partition("://")
    if not sep or len(head) <= 1:
        return ""
    return head.lower()


def local_path(uri: str):
    """The local filesystem path for ''/file:// URIs, else None. The ONE
    place local-vs-remote resolution lives — callers that need an
    existence check use this rather than re-deriving the rule."""
    scheme = scheme_of(uri)
    if scheme == "":
        return uri
    if scheme == "file":
        return uri[7:]
    return None


def register_scheme(scheme: str, opener: Callable) -> None:
    """Register ``opener(uri, mode) -> file-like`` for ``scheme``.
    Re-registering replaces (last wins); ``None`` unregisters."""
    scheme = scheme.lower().rstrip(":")
    if scheme in ("", "file") or len(scheme) == 1:
        # '' / 'file' are built-in local; single letters are treated as
        # Windows drive prefixes by scheme_of — an opener registered
        # under any of these would never be dispatched
        raise ValueError(
            "scheme %r cannot be registered: ''/file are built-in local "
            "and single-letter schemes collide with drive letters"
            % scheme)
    if opener is None:
        _SCHEMES.pop(scheme, None)
    else:
        _SCHEMES[scheme] = opener


def open_uri(uri: str, mode: str = "rb"):
    """Open ``uri`` through the scheme registry (local files built in)."""
    lp = local_path(uri)
    if lp is not None:
        return open(lp, mode)
    scheme = scheme_of(uri)
    opener = _SCHEMES.get(scheme)
    if opener is None:
        hint = (" (the reference gates %s:// behind USE_%s at build "
                "time, make/config.mk:136-144; here it is a runtime "
                "hook)" % (scheme, scheme.upper())
                if scheme in _KNOWN_REMOTE else "")
        raise IOError(
            "no filesystem registered for scheme %r (uri %r). Register "
            "one backed by your environment's client, e.g.\n"
            "    mx.filesystem.register_scheme(%r, "
            "lambda uri, mode: fsspec.open(uri, mode).open())%s"
            % (scheme, uri, scheme, hint))
    return opener(uri, mode)


# ---------------------------------------------------------------------------
# Durable local writes — the crash-consistency primitives the checkpoint
# and kvstore-snapshot writers sit on (docs/how_to/fault_tolerance.md).
# The reference writes .params with a bare fopen/fwrite
# (ndarray.cc:633-714): a crash mid-save leaves a torn file that LOOKS
# like the newest checkpoint.  Here every durable artifact goes through
# tmp + fsync + os.replace (readers only ever see old-complete or
# new-complete bytes) and carries a CRC32 sidecar so silent corruption
# (torn writes from OTHER writers, bit rot, partial copies) is detected
# at discovery time instead of mid-restore.
# ---------------------------------------------------------------------------

_CRC_SUFFIX = ".crc32"
_CRC_CHUNK = 1 << 20


def file_crc32(path: str) -> int:
    """Streaming CRC32 of a file's bytes (constant memory)."""
    crc = 0
    with open(path, "rb") as f:
        while True:
            chunk = f.read(_CRC_CHUNK)
            if not chunk:
                return crc
            crc = zlib.crc32(chunk, crc)


def crc_sidecar_path(path: str) -> str:
    return path + _CRC_SUFFIX


def write_crc_sidecar(path: str) -> str:
    """Record ``crc32 size`` of ``path`` in an (atomically written)
    sidecar; returns the sidecar path."""
    line = "%08x %d\n" % (file_crc32(path), os.path.getsize(path))
    side = crc_sidecar_path(path)
    atomic_write(side, lambda f: f.write(line.encode("ascii")),
                 checksum=False, op="crc.sidecar")
    return side


def verify_crc_sidecar(path: str) -> Optional[bool]:
    """True/False when a sidecar exists and the file matches/mismatches;
    None when there is no sidecar to judge by (pre-sidecar artifact)."""
    side = crc_sidecar_path(path)
    if not os.path.exists(side):
        return None
    try:
        with open(side, "r") as f:
            crc_s, size_s = f.read().split()
        if not os.path.exists(path):
            return False
        if os.path.getsize(path) != int(size_s):
            return False
        return file_crc32(path) == int(crc_s, 16)
    except (OSError, ValueError):
        return False


def atomic_write(path: str, writer: Callable, checksum: bool = False,
                 op: str = "file.write") -> str:
    """Crash-safe replace of ``path``: ``writer(f)`` fills a same-dir temp
    file, which is fsync'd and ``os.replace``'d over the target — readers
    never observe a partial file.  With ``checksum`` a CRC32 sidecar is
    written after the data lands.  ``op`` names this site to the fault
    layer: an active plan's ``partial`` rule tears the TEMP file and
    raises (simulating power loss mid-write) — the target is untouched,
    which is exactly the guarantee under test.
    """
    from . import faults

    faults.fire(op)
    d = os.path.dirname(os.path.abspath(path)) or "."
    tmp = "%s.tmp.%d" % (path, os.getpid())
    try:
        with open(tmp, "wb") as f:
            writer(f)
            frac = faults.partial_fraction(op)
            if frac is not None:
                # torn write: keep a prefix, make it durable, then die the
                # way a crashed writer would (before the replace)
                f.flush()
                f.truncate(max(0, int(f.tell() * frac)))
                f.flush()
                os.fsync(f.fileno())
                raise faults.InjectedIOError(
                    "injected torn write at %s (%s)" % (op, path))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        # fsync the directory so the rename itself survives power loss
        try:
            dfd = os.open(d, os.O_RDONLY)
            try:
                os.fsync(dfd)
            finally:
                os.close(dfd)
        except OSError:
            pass  # platform without dir fsync: best effort
    except faults.InjectedIOError:
        raise  # leave the torn temp behind, as a real crash would
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    if checksum:
        write_crc_sidecar(path)
    return path

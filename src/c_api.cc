// Core C ABI implementation — NDArray CRUD + serialization + op invoke
// over the embedded interpreter (see include/mxtpu/c_api.h and the design
// note at the top of c_predict_api.cc).  Python side:
// mxnet_tpu/capi_shim.py (nd_* functions).
#include "capi_common.h"

#include "mxtpu/c_api.h"

#include <cstdarg>
#include <cstdint>
#include <cstring>
#include <string>
#include <vector>

using mx_uint = uint32_t;
using mxtpu_capi::GIL;
using mxtpu_capi::ensure_python;
using mxtpu_capi::call_shim;
using mxtpu_capi::set_error;
using mxtpu_capi::set_error_from_python;

namespace {

// NDArray handles are heap longs carrying the shim registry id.
struct NDHandle {
  long long hid;
};

// Per-thread backing for returned arrays (reference c_api uses
// thread-local return stores the same way).
thread_local std::vector<mx_uint> t_shape;
thread_local std::vector<std::string> t_names_store;
thread_local std::vector<const char*> t_names;

}  // namespace

extern "C" {

int MXTPUNDArrayCreate(const mx_uint* shape, mx_uint ndim, int dev_type,
                       int dev_id, int dtype_flag, void** out) {
  ensure_python();
  GIL gil;
  PyObject* shp = PyTuple_New(ndim);
  for (mx_uint i = 0; i < ndim; ++i) {
    PyTuple_SET_ITEM(shp, i, PyLong_FromUnsignedLong(shape[i]));
  }
  PyObject* res =
      call_shim("nd_create", "(Oiii)", shp, dev_type, dev_id, dtype_flag);
  Py_DECREF(shp);
  if (!res) return -1;
  auto* h = new NDHandle{PyLong_AsLongLong(res)};
  Py_DECREF(res);
  *out = h;
  return 0;
}

int MXTPUNDArrayFree(void* handle) {
  auto* h = static_cast<NDHandle*>(handle);
  if (!h) return 0;
  {
    GIL gil;
    PyObject* res = call_shim("nd_free", "(L)", h->hid);
    if (res) Py_DECREF(res);
    else PyErr_Clear();
  }
  delete h;
  return 0;
}

int MXTPUNDArrayGetShape(void* handle, mx_uint* out_ndim,
                         const mx_uint** out_data) {
  auto* h = static_cast<NDHandle*>(handle);
  GIL gil;
  PyObject* res = call_shim("nd_shape", "(L)", h->hid);
  if (!res) return -1;
  Py_ssize_t n = PyTuple_Size(res);
  t_shape.resize(n);
  for (Py_ssize_t i = 0; i < n; ++i) {
    t_shape[i] = static_cast<mx_uint>(
        PyLong_AsUnsignedLong(PyTuple_GET_ITEM(res, i)));
  }
  Py_DECREF(res);
  *out_ndim = static_cast<mx_uint>(n);
  *out_data = t_shape.data();
  return 0;
}

int MXTPUNDArrayGetDType(void* handle, int* out_dtype) {
  auto* h = static_cast<NDHandle*>(handle);
  GIL gil;
  PyObject* res = call_shim("nd_dtype", "(L)", h->hid);
  if (!res) return -1;
  *out_dtype = static_cast<int>(PyLong_AsLong(res));
  Py_DECREF(res);
  return 0;
}

int MXTPUNDArraySyncCopyFromCPU(void* handle, const void* data,
                                size_t nbytes) {
  auto* h = static_cast<NDHandle*>(handle);
  GIL gil;
  PyObject* res = call_shim("nd_copy_from", "(Ly#)", h->hid,
                            static_cast<const char*>(data),
                            static_cast<Py_ssize_t>(nbytes));
  if (!res) return -1;
  Py_DECREF(res);
  return 0;
}

int MXTPUNDArraySyncCopyToCPU(void* handle, void* data, size_t nbytes) {
  auto* h = static_cast<NDHandle*>(handle);
  GIL gil;
  PyObject* res = call_shim("nd_copy_to", "(L)", h->hid);
  if (!res) return -1;
  char* buf = nullptr;
  Py_ssize_t len = 0;
  if (PyBytes_AsStringAndSize(res, &buf, &len) != 0) {
    Py_DECREF(res);
    set_error_from_python();
    return -1;
  }
  if (static_cast<size_t>(len) != nbytes) {
    Py_DECREF(res);
    set_error("copy size mismatch: array has " + std::to_string(len) +
              " bytes, caller asked for " + std::to_string(nbytes));
    return -1;
  }
  std::memcpy(data, buf, len);
  Py_DECREF(res);
  return 0;
}

int MXTPUNDArrayWaitAll(void) {
  ensure_python();
  GIL gil;
  PyObject* res = call_shim("nd_wait_all", "()");
  if (!res) return -1;
  Py_DECREF(res);
  return 0;
}

int MXTPUNDArraySave(const char* fname, mx_uint num_args, void** args,
                     const char** keys) {
  GIL gil;
  PyObject* hids = PyList_New(num_args);
  PyObject* names = keys ? PyList_New(num_args) : PyList_New(0);
  for (mx_uint i = 0; i < num_args; ++i) {
    PyList_SET_ITEM(hids, i, PyLong_FromLongLong(
        static_cast<NDHandle*>(args[i])->hid));
    if (keys) PyList_SET_ITEM(names, i, PyUnicode_FromString(keys[i]));
  }
  PyObject* res = call_shim("nd_save", "(sOO)", fname, hids, names);
  Py_DECREF(hids);
  Py_DECREF(names);
  if (!res) return -1;
  Py_DECREF(res);
  return 0;
}

int MXTPUNDArrayLoad(const char* fname, mx_uint* out_size, void*** out_arr,
                     mx_uint* out_name_size, const char*** out_names) {
  ensure_python();
  GIL gil;
  PyObject* res = call_shim("nd_load", "(s)", fname);
  if (!res) return -1;
  PyObject* hids = PyTuple_GET_ITEM(res, 0);
  PyObject* names = PyTuple_GET_ITEM(res, 1);
  Py_ssize_t n = PyList_Size(hids);
  if (n < 0) {
    PyErr_Clear();
    Py_DECREF(res);
    set_error("MXTPUNDArrayLoad: shim returned a non-list");
    return -1;
  }
  // fresh malloc'd array per call: the handles inside are caller-owned
  // already, so the array that is their only copy must not be a shared
  // thread-local that the next Load/Invoke silently overwrites
  // (n+1 so a zero-entry load never trips the malloc(0)-may-be-NULL case)
  void** arr = static_cast<void**>(malloc((n + 1) * sizeof(void*)));
  if (!arr) {
    Py_DECREF(res);
    set_error("MXTPUNDArrayLoad: allocation failed");
    return -1;
  }
  for (Py_ssize_t i = 0; i < n; ++i) {
    arr[i] = new NDHandle{PyLong_AsLongLong(PyList_GET_ITEM(hids, i))};
  }
  Py_ssize_t nn = PyList_Size(names);
  t_names_store.resize(nn);
  t_names.resize(nn);
  for (Py_ssize_t i = 0; i < nn; ++i) {
    t_names_store[i] = PyUnicode_AsUTF8(PyList_GET_ITEM(names, i));
    t_names[i] = t_names_store[i].c_str();
  }
  Py_DECREF(res);
  *out_size = static_cast<mx_uint>(n);
  *out_arr = arr;
  *out_name_size = static_cast<mx_uint>(nn);
  *out_names = t_names.data();
  return 0;
}

int MXTPUListAllOpNames(mx_uint* out_size, const char*** out_array) {
  ensure_python();
  GIL gil;
  PyObject* res = call_shim("list_op_names", "()");
  if (!res) return -1;
  Py_ssize_t n = PyList_Size(res);
  t_names_store.resize(n);
  t_names.resize(n);
  for (Py_ssize_t i = 0; i < n; ++i) {
    t_names_store[i] = PyUnicode_AsUTF8(PyList_GET_ITEM(res, i));
    t_names[i] = t_names_store[i].c_str();
  }
  Py_DECREF(res);
  *out_size = static_cast<mx_uint>(n);
  *out_array = t_names.data();
  return 0;
}

int MXTPUImperativeInvoke(const char* op_name, int num_inputs, void** inputs,
                          int* num_outputs, void*** outputs, int num_params,
                          const char** param_keys, const char** param_vals) {
  ensure_python();
  GIL gil;
  PyObject* in = PyList_New(num_inputs);
  for (int i = 0; i < num_inputs; ++i) {
    PyList_SET_ITEM(in, i, PyLong_FromLongLong(
        static_cast<NDHandle*>(inputs[i])->hid));
  }
  PyObject* keys = PyList_New(num_params);
  PyObject* vals = PyList_New(num_params);
  for (int i = 0; i < num_params; ++i) {
    PyList_SET_ITEM(keys, i, PyUnicode_FromString(param_keys[i]));
    PyList_SET_ITEM(vals, i, PyUnicode_FromString(param_vals[i]));
  }
  PyObject* res = call_shim("nd_invoke", "(sOOO)", op_name, in, keys, vals);
  Py_DECREF(in);
  Py_DECREF(keys);
  Py_DECREF(vals);
  if (!res) return -1;
  Py_ssize_t n = PyList_Size(res);
  if (n < 0) {
    PyErr_Clear();
    Py_DECREF(res);
    set_error("MXTPUImperativeInvoke: shim returned a non-list");
    return -1;
  }
  void** arr = static_cast<void**>(malloc((n + 1) * sizeof(void*)));
  if (!arr) {
    Py_DECREF(res);
    set_error("MXTPUImperativeInvoke: allocation failed");
    return -1;
  }
  for (Py_ssize_t i = 0; i < n; ++i) {
    arr[i] = new NDHandle{PyLong_AsLongLong(PyList_GET_ITEM(res, i))};
  }
  Py_DECREF(res);
  *num_outputs = static_cast<int>(n);
  *outputs = arr;
  return 0;
}

int MXTPUFreeHandleArray(void** arr) {
  free(arr);
  return 0;
}

}  // extern "C"

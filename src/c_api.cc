// Core C ABI implementation — NDArray CRUD + serialization + op invoke
// over the embedded interpreter (see include/mxtpu/c_api.h and the design
// note at the top of c_predict_api.cc).  Python side:
// mxnet_tpu/capi_shim.py (nd_* functions).
#include "capi_common.h"

#include "mxtpu/c_api.h"

#include <cstdarg>
#include <cstdint>
#include <cstring>
#include <string>
#include <vector>

using mx_uint = uint32_t;
using mxtpu_capi::GIL;
using mxtpu_capi::ensure_python;
using mxtpu_capi::call_shim;
using mxtpu_capi::set_error;
using mxtpu_capi::set_error_from_python;

namespace {

// Opaque handles are heap longs carrying the shim registry id (one
// registry per object kind in capi_shim.py).
struct NDHandle {
  long long hid;
};
struct SymHandle {
  long long hid;
};
struct ExecHandle {
  long long hid;
};
struct IterHandle {
  long long hid;
};
struct KVHandle {
  long long hid;
};

// Per-thread backing for returned arrays (reference c_api uses
// thread-local return stores the same way).
thread_local std::vector<mx_uint> t_shape;
thread_local std::vector<std::string> t_names_store;
thread_local std::vector<const char*> t_names;
thread_local std::string t_json;

// Marshal a shim-returned list of strings into the shared thread-local
// name table (library-owned, valid until the next call — header
// contract).  Consumes the reference to `res`.
int fill_name_table(PyObject* res, mx_uint* out_size,
                    const char*** out_array) {
  Py_ssize_t n = PyList_Size(res);
  if (n < 0) {
    PyErr_Clear();
    Py_DECREF(res);
    mxtpu_capi::set_error("shim returned a non-list name table");
    return -1;
  }
  t_names_store.resize(n);
  t_names.resize(n);
  for (Py_ssize_t i = 0; i < n; ++i) {
    t_names_store[i] = PyUnicode_AsUTF8(PyList_GET_ITEM(res, i));
    t_names[i] = t_names_store[i].c_str();
  }
  Py_DECREF(res);
  *out_size = static_cast<mx_uint>(n);
  *out_array = t_names.data();
  return 0;
}

}  // namespace

extern "C" {

int MXTPUNDArrayCreate(const mx_uint* shape, mx_uint ndim, int dev_type,
                       int dev_id, int dtype_flag, void** out) {
  ensure_python();
  GIL gil;
  PyObject* shp = PyTuple_New(ndim);
  for (mx_uint i = 0; i < ndim; ++i) {
    PyTuple_SET_ITEM(shp, i, PyLong_FromUnsignedLong(shape[i]));
  }
  PyObject* res =
      call_shim("nd_create", "(Oiii)", shp, dev_type, dev_id, dtype_flag);
  Py_DECREF(shp);
  if (!res) return -1;
  auto* h = new NDHandle{PyLong_AsLongLong(res)};
  Py_DECREF(res);
  *out = h;
  return 0;
}

int MXTPUNDArrayFree(void* handle) {
  auto* h = static_cast<NDHandle*>(handle);
  if (!h) return 0;
  {
    GIL gil;
    PyObject* res = call_shim("nd_free", "(L)", h->hid);
    if (res) Py_DECREF(res);
    else PyErr_Clear();
  }
  delete h;
  return 0;
}

int MXTPUNDArrayGetShape(void* handle, mx_uint* out_ndim,
                         const mx_uint** out_data) {
  auto* h = static_cast<NDHandle*>(handle);
  GIL gil;
  PyObject* res = call_shim("nd_shape", "(L)", h->hid);
  if (!res) return -1;
  Py_ssize_t n = PyTuple_Size(res);
  t_shape.resize(n);
  for (Py_ssize_t i = 0; i < n; ++i) {
    t_shape[i] = static_cast<mx_uint>(
        PyLong_AsUnsignedLong(PyTuple_GET_ITEM(res, i)));
  }
  Py_DECREF(res);
  *out_ndim = static_cast<mx_uint>(n);
  *out_data = t_shape.data();
  return 0;
}

int MXTPUNDArrayGetDType(void* handle, int* out_dtype) {
  auto* h = static_cast<NDHandle*>(handle);
  GIL gil;
  PyObject* res = call_shim("nd_dtype", "(L)", h->hid);
  if (!res) return -1;
  *out_dtype = static_cast<int>(PyLong_AsLong(res));
  Py_DECREF(res);
  return 0;
}

int MXTPUNDArraySyncCopyFromCPU(void* handle, const void* data,
                                size_t nbytes) {
  auto* h = static_cast<NDHandle*>(handle);
  GIL gil;
  PyObject* res = call_shim("nd_copy_from", "(Ly#)", h->hid,
                            static_cast<const char*>(data),
                            static_cast<Py_ssize_t>(nbytes));
  if (!res) return -1;
  Py_DECREF(res);
  return 0;
}

int MXTPUNDArraySyncCopyToCPU(void* handle, void* data, size_t nbytes) {
  auto* h = static_cast<NDHandle*>(handle);
  GIL gil;
  PyObject* res = call_shim("nd_copy_to", "(L)", h->hid);
  if (!res) return -1;
  char* buf = nullptr;
  Py_ssize_t len = 0;
  if (PyBytes_AsStringAndSize(res, &buf, &len) != 0) {
    Py_DECREF(res);
    set_error_from_python();
    return -1;
  }
  if (static_cast<size_t>(len) != nbytes) {
    Py_DECREF(res);
    set_error("copy size mismatch: array has " + std::to_string(len) +
              " bytes, caller asked for " + std::to_string(nbytes));
    return -1;
  }
  std::memcpy(data, buf, len);
  Py_DECREF(res);
  return 0;
}

int MXTPUNDArrayWaitAll(void) {
  ensure_python();
  GIL gil;
  PyObject* res = call_shim("nd_wait_all", "()");
  if (!res) return -1;
  Py_DECREF(res);
  return 0;
}

int MXTPUNDArraySave(const char* fname, mx_uint num_args, void** args,
                     const char** keys) {
  GIL gil;
  PyObject* hids = PyList_New(num_args);
  PyObject* names = keys ? PyList_New(num_args) : PyList_New(0);
  for (mx_uint i = 0; i < num_args; ++i) {
    PyList_SET_ITEM(hids, i, PyLong_FromLongLong(
        static_cast<NDHandle*>(args[i])->hid));
    if (keys) PyList_SET_ITEM(names, i, PyUnicode_FromString(keys[i]));
  }
  PyObject* res = call_shim("nd_save", "(sOO)", fname, hids, names);
  Py_DECREF(hids);
  Py_DECREF(names);
  if (!res) return -1;
  Py_DECREF(res);
  return 0;
}

int MXTPUNDArrayLoad(const char* fname, mx_uint* out_size, void*** out_arr,
                     mx_uint* out_name_size, const char*** out_names) {
  ensure_python();
  GIL gil;
  PyObject* res = call_shim("nd_load", "(s)", fname);
  if (!res) return -1;
  PyObject* hids = PyTuple_GET_ITEM(res, 0);
  PyObject* names = PyTuple_GET_ITEM(res, 1);
  Py_ssize_t n = PyList_Size(hids);
  if (n < 0) {
    PyErr_Clear();
    Py_DECREF(res);
    set_error("MXTPUNDArrayLoad: shim returned a non-list");
    return -1;
  }
  // fresh malloc'd array per call: the handles inside are caller-owned
  // already, so the array that is their only copy must not be a shared
  // thread-local that the next Load/Invoke silently overwrites
  // (n+1 so a zero-entry load never trips the malloc(0)-may-be-NULL case)
  void** arr = static_cast<void**>(malloc((n + 1) * sizeof(void*)));
  if (!arr) {
    Py_DECREF(res);
    set_error("MXTPUNDArrayLoad: allocation failed");
    return -1;
  }
  for (Py_ssize_t i = 0; i < n; ++i) {
    arr[i] = new NDHandle{PyLong_AsLongLong(PyList_GET_ITEM(hids, i))};
  }
  Py_ssize_t nn = PyList_Size(names);
  t_names_store.resize(nn);
  t_names.resize(nn);
  for (Py_ssize_t i = 0; i < nn; ++i) {
    t_names_store[i] = PyUnicode_AsUTF8(PyList_GET_ITEM(names, i));
    t_names[i] = t_names_store[i].c_str();
  }
  Py_DECREF(res);
  *out_size = static_cast<mx_uint>(n);
  *out_arr = arr;
  *out_name_size = static_cast<mx_uint>(nn);
  *out_names = t_names.data();
  return 0;
}

int MXTPUListAllOpNames(mx_uint* out_size, const char*** out_array) {
  ensure_python();
  GIL gil;
  PyObject* res = call_shim("list_op_names", "()");
  if (!res) return -1;
  return fill_name_table(res, out_size, out_array);
}

int MXTPUListOpInputs(const char* op_name, mx_uint* out_size,
                      const char*** out_array) {
  ensure_python();
  GIL gil;
  PyObject* res = call_shim("op_input_names", "(s)", op_name);
  if (!res) return -1;
  return fill_name_table(res, out_size, out_array);
}

int MXTPUImperativeInvoke(const char* op_name, int num_inputs, void** inputs,
                          int* num_outputs, void*** outputs, int num_params,
                          const char** param_keys, const char** param_vals) {
  ensure_python();
  GIL gil;
  PyObject* in = PyList_New(num_inputs);
  for (int i = 0; i < num_inputs; ++i) {
    PyList_SET_ITEM(in, i, PyLong_FromLongLong(
        static_cast<NDHandle*>(inputs[i])->hid));
  }
  PyObject* keys = PyList_New(num_params);
  PyObject* vals = PyList_New(num_params);
  for (int i = 0; i < num_params; ++i) {
    PyList_SET_ITEM(keys, i, PyUnicode_FromString(param_keys[i]));
    PyList_SET_ITEM(vals, i, PyUnicode_FromString(param_vals[i]));
  }
  PyObject* res = call_shim("nd_invoke", "(sOOO)", op_name, in, keys, vals);
  Py_DECREF(in);
  Py_DECREF(keys);
  Py_DECREF(vals);
  if (!res) return -1;
  Py_ssize_t n = PyList_Size(res);
  if (n < 0) {
    PyErr_Clear();
    Py_DECREF(res);
    set_error("MXTPUImperativeInvoke: shim returned a non-list");
    return -1;
  }
  void** arr = static_cast<void**>(malloc((n + 1) * sizeof(void*)));
  if (!arr) {
    Py_DECREF(res);
    set_error("MXTPUImperativeInvoke: allocation failed");
    return -1;
  }
  for (Py_ssize_t i = 0; i < n; ++i) {
    arr[i] = new NDHandle{PyLong_AsLongLong(PyList_GET_ITEM(res, i))};
  }
  Py_DECREF(res);
  *num_outputs = static_cast<int>(n);
  *outputs = arr;
  return 0;
}

int MXTPUFreeHandleArray(void** arr) {
  free(arr);
  return 0;
}

/* ------------------------------------------------------------------ */
/* KVStore surface (shim: kv_* functions in capi_shim.py;
 * reference c_api.cc:544-700)                                         */

int MXTPUKVStoreCreate(const char* type, void** out) {
  ensure_python();
  GIL gil;
  PyObject* res = call_shim("kv_create", "(s)", type);
  if (!res) return -1;
  *out = new KVHandle{PyLong_AsLongLong(res)};
  Py_DECREF(res);
  return 0;
}

int MXTPUKVStoreFree(void* handle) {
  auto* h = static_cast<KVHandle*>(handle);
  if (!h) return 0;
  {
    GIL gil;
    PyObject* res = call_shim("kv_free", "(L)", h->hid);
    if (res) Py_DECREF(res);
    else PyErr_Clear();
  }
  delete h;
  return 0;
}

namespace {
int kv_keyed_call(void* handle, const char* fn, mx_uint num,
                  const int* keys, void** vals) {
  GIL gil;
  PyObject* pkeys = PyList_New(num);
  PyObject* pvals = PyList_New(num);
  for (mx_uint i = 0; i < num; ++i) {
    PyList_SET_ITEM(pkeys, i, PyLong_FromLong(keys[i]));
    PyList_SET_ITEM(pvals, i, PyLong_FromLongLong(
        static_cast<NDHandle*>(vals[i])->hid));
  }
  PyObject* res = call_shim(fn, "(LOO)",
                            static_cast<KVHandle*>(handle)->hid, pkeys,
                            pvals);
  Py_DECREF(pkeys);
  Py_DECREF(pvals);
  if (!res) return -1;
  Py_DECREF(res);
  return 0;
}
}  // namespace

int MXTPUKVStoreInit(void* handle, mx_uint num, const int* keys,
                     void** vals) {
  return kv_keyed_call(handle, "kv_init", num, keys, vals);
}

int MXTPUKVStorePush(void* handle, mx_uint num, const int* keys,
                     void** vals) {
  return kv_keyed_call(handle, "kv_push", num, keys, vals);
}

/* Pull fills the CALLER's NDArray handles in place. */
int MXTPUKVStorePull(void* handle, mx_uint num, const int* keys,
                     void** vals) {
  return kv_keyed_call(handle, "kv_pull", num, keys, vals);
}

int MXTPUKVStoreGetType(void* handle, const char** out_type) {
  GIL gil;
  PyObject* res = call_shim("kv_type", "(L)",
                            static_cast<KVHandle*>(handle)->hid);
  if (!res) return -1;
  t_json = PyUnicode_AsUTF8(res);
  Py_DECREF(res);
  *out_type = t_json.c_str();
  return 0;
}

int MXTPUKVStoreGetRank(void* handle, int* out) {
  GIL gil;
  PyObject* res = call_shim("kv_rank", "(L)",
                            static_cast<KVHandle*>(handle)->hid);
  if (!res) return -1;
  *out = static_cast<int>(PyLong_AsLong(res));
  Py_DECREF(res);
  return 0;
}

int MXTPUKVStoreGetGroupSize(void* handle, int* out) {
  GIL gil;
  PyObject* res = call_shim("kv_group_size", "(L)",
                            static_cast<KVHandle*>(handle)->hid);
  if (!res) return -1;
  *out = static_cast<int>(PyLong_AsLong(res));
  Py_DECREF(res);
  return 0;
}

int MXTPUKVStoreBarrier(void* handle) {
  GIL gil;
  PyObject* res = call_shim("kv_barrier", "(L)",
                            static_cast<KVHandle*>(handle)->hid);
  if (!res) return -1;
  Py_DECREF(res);
  return 0;
}

/* ------------------------------------------------------------------ */
/* DataIter surface (shim: iter_* functions in capi_shim.py;
 * reference c_api.cc:446-543)                                         */

int MXTPUListDataIters(mx_uint* out_size, const char*** out_array) {
  ensure_python();
  GIL gil;
  PyObject* res = call_shim("iter_list", "()");
  if (!res) return -1;
  return fill_name_table(res, out_size, out_array);
}

int MXTPUDataIterCreate(const char* name, mx_uint num_params,
                        const char** keys, const char** vals, void** out) {
  ensure_python();
  GIL gil;
  PyObject* pkeys = PyList_New(num_params);
  PyObject* pvals = PyList_New(num_params);
  for (mx_uint i = 0; i < num_params; ++i) {
    PyList_SET_ITEM(pkeys, i, PyUnicode_FromString(keys[i]));
    PyList_SET_ITEM(pvals, i, PyUnicode_FromString(vals[i]));
  }
  PyObject* res = call_shim("iter_create", "(sOO)", name, pkeys, pvals);
  Py_DECREF(pkeys);
  Py_DECREF(pvals);
  if (!res) return -1;
  *out = new IterHandle{PyLong_AsLongLong(res)};
  Py_DECREF(res);
  return 0;
}

int MXTPUDataIterNext(void* handle, int* out) {
  GIL gil;
  PyObject* res = call_shim("iter_next", "(L)",
                            static_cast<IterHandle*>(handle)->hid);
  if (!res) return -1;
  *out = static_cast<int>(PyLong_AsLong(res));
  Py_DECREF(res);
  return 0;
}

int MXTPUDataIterBeforeFirst(void* handle) {
  GIL gil;
  PyObject* res = call_shim("iter_before_first", "(L)",
                            static_cast<IterHandle*>(handle)->hid);
  if (!res) return -1;
  Py_DECREF(res);
  return 0;
}

namespace {
int iter_fetch_nd(void* handle, const char* fn, void** out) {
  GIL gil;
  PyObject* res =
      call_shim(fn, "(L)", static_cast<IterHandle*>(handle)->hid);
  if (!res) return -1;
  *out = new NDHandle{PyLong_AsLongLong(res)};
  Py_DECREF(res);
  return 0;
}
}  // namespace

/* The returned NDArrayHandle is caller-owned (MXTPUNDArrayFree). */
int MXTPUDataIterGetData(void* handle, void** out) {
  return iter_fetch_nd(handle, "iter_get_data", out);
}

int MXTPUDataIterGetLabel(void* handle, void** out) {
  return iter_fetch_nd(handle, "iter_get_label", out);
}

int MXTPUDataIterGetPadNum(void* handle, int* out) {
  GIL gil;
  PyObject* res = call_shim("iter_get_pad", "(L)",
                            static_cast<IterHandle*>(handle)->hid);
  if (!res) return -1;
  *out = static_cast<int>(PyLong_AsLong(res));
  Py_DECREF(res);
  return 0;
}

int MXTPUDataIterFree(void* handle) {
  auto* h = static_cast<IterHandle*>(handle);
  if (!h) return 0;
  {
    GIL gil;
    PyObject* res = call_shim("iter_free", "(L)", h->hid);
    if (res) Py_DECREF(res);
    else PyErr_Clear();
  }
  delete h;
  return 0;
}

/* ------------------------------------------------------------------ */
/* Symbol surface (shim: sym_* functions in capi_shim.py)              */

int MXTPUSymbolCreateFromJSON(const char* json, void** out) {
  ensure_python();
  GIL gil;
  PyObject* res = call_shim("sym_from_json", "(s)", json);
  if (!res) return -1;
  *out = new SymHandle{PyLong_AsLongLong(res)};
  Py_DECREF(res);
  return 0;
}

int MXTPUSymbolCreateFromFile(const char* fname, void** out) {
  ensure_python();
  GIL gil;
  PyObject* res = call_shim("sym_from_file", "(s)", fname);
  if (!res) return -1;
  *out = new SymHandle{PyLong_AsLongLong(res)};
  Py_DECREF(res);
  return 0;
}

int MXTPUSymbolSaveToJSON(void* sym, const char** out_json) {
  GIL gil;
  PyObject* res =
      call_shim("sym_tojson", "(L)", static_cast<SymHandle*>(sym)->hid);
  if (!res) return -1;
  t_json = PyUnicode_AsUTF8(res);
  Py_DECREF(res);
  *out_json = t_json.c_str();
  return 0;
}

int MXTPUSymbolListArguments(void* sym, mx_uint* out_size,
                             const char*** out_array) {
  GIL gil;
  PyObject* res = call_shim("sym_list_arguments", "(L)",
                            static_cast<SymHandle*>(sym)->hid);
  if (!res) return -1;
  return fill_name_table(res, out_size, out_array);
}

int MXTPUSymbolListOutputs(void* sym, mx_uint* out_size,
                           const char*** out_array) {
  GIL gil;
  PyObject* res = call_shim("sym_list_outputs", "(L)",
                            static_cast<SymHandle*>(sym)->hid);
  if (!res) return -1;
  return fill_name_table(res, out_size, out_array);
}

int MXTPUSymbolListAuxiliaryStates(void* sym, mx_uint* out_size,
                                   const char*** out_array) {
  GIL gil;
  PyObject* res = call_shim("sym_list_aux", "(L)",
                            static_cast<SymHandle*>(sym)->hid);
  if (!res) return -1;
  return fill_name_table(res, out_size, out_array);
}

int MXTPUSymbolFree(void* sym) {
  auto* h = static_cast<SymHandle*>(sym);
  if (!h) return 0;
  {
    GIL gil;
    PyObject* res = call_shim("sym_free", "(L)", h->hid);
    if (res) Py_DECREF(res);
    else PyErr_Clear();
  }
  delete h;
  return 0;
}

namespace {
// One category of inferred shapes (args / outputs / aux), marshalled from
// a shim list-of-tuples into stable thread-local storage.
struct ShapeSet {
  std::vector<std::vector<mx_uint>> store;
  std::vector<mx_uint> ndims;
  std::vector<const mx_uint*> ptrs;

  void fill(PyObject* shapes) {
    Py_ssize_t n = PyList_Size(shapes);
    store.resize(n);
    ndims.resize(n);
    ptrs.resize(n);
    for (Py_ssize_t i = 0; i < n; ++i) {
      PyObject* tup = PyList_GET_ITEM(shapes, i);
      Py_ssize_t nd = PyTuple_Size(tup);
      store[i].resize(nd);
      for (Py_ssize_t j = 0; j < nd; ++j) {
        store[i][j] = static_cast<mx_uint>(
            PyLong_AsUnsignedLong(PyTuple_GET_ITEM(tup, j)));
      }
      ndims[i] = static_cast<mx_uint>(nd);
      ptrs[i] = store[i].data();
    }
  }
};

thread_local ShapeSet t_arg_shapes, t_out_shapes, t_aux_shapes;
}  // namespace

int MXTPUSymbolInferShape(void* sym, mx_uint num_args, const char** keys,
                          const mx_uint* arg_ind_ptr,
                          const mx_uint* arg_shape_data,
                          mx_uint* in_shape_size,
                          const mx_uint** in_shape_ndim,
                          const mx_uint*** in_shape_data,
                          mx_uint* out_shape_size,
                          const mx_uint** out_shape_ndim,
                          const mx_uint*** out_shape_data,
                          mx_uint* aux_shape_size,
                          const mx_uint** aux_shape_ndim,
                          const mx_uint*** aux_shape_data, int* complete) {
  GIL gil;
  PyObject* pkeys = PyList_New(num_args);
  PyObject* pshapes = PyList_New(num_args);
  for (mx_uint i = 0; i < num_args; ++i) {
    PyList_SET_ITEM(pkeys, i, PyUnicode_FromString(keys[i]));
    mx_uint lo = arg_ind_ptr[i], hi = arg_ind_ptr[i + 1];
    PyObject* shp = PyTuple_New(hi - lo);
    for (mx_uint j = lo; j < hi; ++j) {
      PyTuple_SET_ITEM(shp, j - lo,
                       PyLong_FromUnsignedLong(arg_shape_data[j]));
    }
    PyList_SET_ITEM(pshapes, i, shp);
  }
  PyObject* res = call_shim("sym_infer_shape", "(LOO)",
                            static_cast<SymHandle*>(sym)->hid, pkeys,
                            pshapes);
  Py_DECREF(pkeys);
  Py_DECREF(pshapes);
  if (!res) return -1;
  PyObject* args_l = PyTuple_GET_ITEM(res, 0);
  if (args_l == Py_None) {  // underdetermined: the reference's !complete
    Py_DECREF(res);
    *complete = 0;
    *in_shape_size = *out_shape_size = *aux_shape_size = 0;
    *in_shape_ndim = *out_shape_ndim = *aux_shape_ndim = nullptr;
    *in_shape_data = *out_shape_data = *aux_shape_data = nullptr;
    return 0;
  }
  t_arg_shapes.fill(args_l);
  t_out_shapes.fill(PyTuple_GET_ITEM(res, 1));
  t_aux_shapes.fill(PyTuple_GET_ITEM(res, 2));
  Py_DECREF(res);
  *complete = 1;
  *in_shape_size = static_cast<mx_uint>(t_arg_shapes.ndims.size());
  *in_shape_ndim = t_arg_shapes.ndims.data();
  *in_shape_data = t_arg_shapes.ptrs.data();
  *out_shape_size = static_cast<mx_uint>(t_out_shapes.ndims.size());
  *out_shape_ndim = t_out_shapes.ndims.data();
  *out_shape_data = t_out_shapes.ptrs.data();
  *aux_shape_size = static_cast<mx_uint>(t_aux_shapes.ndims.size());
  *aux_shape_ndim = t_aux_shapes.ndims.data();
  *aux_shape_data = t_aux_shapes.ptrs.data();
  return 0;
}

/* ------------------------------------------------------------------ */
/* Executor surface (shim: exec_* functions in capi_shim.py)           */

int MXTPUExecutorBind(void* sym, int dev_type, int dev_id, mx_uint num_args,
                      void** arg_handles, void** grad_handles,
                      const mx_uint* grad_req_types, mx_uint num_aux,
                      void** aux_handles, void** out) {
  GIL gil;
  PyObject* pargs = PyList_New(num_args);
  PyObject* pgrads = PyList_New(num_args);
  PyObject* preqs = PyList_New(num_args);
  for (mx_uint i = 0; i < num_args; ++i) {
    PyList_SET_ITEM(pargs, i, PyLong_FromLongLong(
        static_cast<NDHandle*>(arg_handles[i])->hid));
    void* g = grad_handles ? grad_handles[i] : nullptr;
    PyList_SET_ITEM(pgrads, i, PyLong_FromLongLong(
        g ? static_cast<NDHandle*>(g)->hid : 0));
    PyList_SET_ITEM(preqs, i, PyLong_FromUnsignedLong(
        grad_req_types ? grad_req_types[i] : 0));
  }
  PyObject* paux = PyList_New(num_aux);
  for (mx_uint i = 0; i < num_aux; ++i) {
    PyList_SET_ITEM(paux, i, PyLong_FromLongLong(
        static_cast<NDHandle*>(aux_handles[i])->hid));
  }
  PyObject* res = call_shim("exec_bind", "(LiiOOOO)",
                            static_cast<SymHandle*>(sym)->hid, dev_type,
                            dev_id, pargs, pgrads, preqs, paux);
  Py_DECREF(pargs);
  Py_DECREF(pgrads);
  Py_DECREF(preqs);
  Py_DECREF(paux);
  if (!res) return -1;
  *out = new ExecHandle{PyLong_AsLongLong(res)};
  Py_DECREF(res);
  return 0;
}

int MXTPUExecutorForward(void* handle, int is_train) {
  GIL gil;
  PyObject* res = call_shim("exec_forward", "(Li)",
                            static_cast<ExecHandle*>(handle)->hid, is_train);
  if (!res) return -1;
  Py_DECREF(res);
  return 0;
}

int MXTPUExecutorBackward(void* handle, mx_uint num_heads,
                          void** head_grads) {
  GIL gil;
  PyObject* pheads = PyList_New(head_grads ? num_heads : 0);
  if (head_grads) {
    for (mx_uint i = 0; i < num_heads; ++i) {
      PyList_SET_ITEM(pheads, i, PyLong_FromLongLong(
          static_cast<NDHandle*>(head_grads[i])->hid));
    }
  }
  PyObject* res = call_shim("exec_backward", "(LO)",
                            static_cast<ExecHandle*>(handle)->hid, pheads);
  Py_DECREF(pheads);
  if (!res) return -1;
  Py_DECREF(res);
  return 0;
}

int MXTPUExecutorOutputs(void* handle, mx_uint* out_size, void*** out) {
  GIL gil;
  PyObject* res = call_shim("exec_outputs", "(L)",
                            static_cast<ExecHandle*>(handle)->hid);
  if (!res) return -1;
  Py_ssize_t n = PyList_Size(res);
  if (n < 0) {
    PyErr_Clear();
    Py_DECREF(res);
    set_error("MXTPUExecutorOutputs: shim returned a non-list");
    return -1;
  }
  void** arr = static_cast<void**>(malloc((n + 1) * sizeof(void*)));
  if (!arr) {
    Py_DECREF(res);
    set_error("MXTPUExecutorOutputs: allocation failed");
    return -1;
  }
  for (Py_ssize_t i = 0; i < n; ++i) {
    arr[i] = new NDHandle{PyLong_AsLongLong(PyList_GET_ITEM(res, i))};
  }
  Py_DECREF(res);
  *out_size = static_cast<mx_uint>(n);
  *out = arr;
  return 0;
}

int MXTPUExecutorFree(void* handle) {
  auto* h = static_cast<ExecHandle*>(handle);
  if (!h) return 0;
  {
    GIL gil;
    PyObject* res = call_shim("exec_free", "(L)", h->hid);
    if (res) Py_DECREF(res);
    else PyErr_Clear();
  }
  delete h;
  return 0;
}

/* ------------------------------------------------------------------ */
/* Round-5 breadth: C-side graph building, NDArray views, executor     */
/* reshape, version/seed (reference c_api_symbolic.cc:54-220,          */
/* c_api.cc MXNDArraySlice/Reshape/GetContext, MXExecutorReshape,      */
/* MXGetVersion, MXRandomSeed).                                        */

int MXTPUSymbolCreateVariable(const char* name, void** out) {
  ensure_python();
  GIL gil;
  PyObject* res = call_shim("sym_variable", "(s)", name);
  if (!res) return -1;
  *out = new SymHandle{PyLong_AsLongLong(res)};
  Py_DECREF(res);
  return 0;
}

int MXTPUSymbolCreateAtomicSymbol(const char* op_name, mx_uint num_param,
                                  const char** keys, const char** vals,
                                  void** out) {
  ensure_python();
  GIL gil;
  PyObject* pkeys = PyList_New(num_param);
  PyObject* pvals = PyList_New(num_param);
  for (mx_uint i = 0; i < num_param; ++i) {
    PyList_SET_ITEM(pkeys, i, PyUnicode_FromString(keys[i]));
    PyList_SET_ITEM(pvals, i, PyUnicode_FromString(vals[i]));
  }
  PyObject* res = call_shim("sym_atomic", "(sOO)", op_name, pkeys, pvals);
  Py_DECREF(pkeys);
  Py_DECREF(pvals);
  if (!res) return -1;
  *out = new SymHandle{PyLong_AsLongLong(res)};
  Py_DECREF(res);
  return 0;
}

int MXTPUSymbolCompose(void* sym, const char* name, mx_uint num_args,
                       const char** keys, void** args) {
  GIL gil;
  PyObject* pkeys = PyList_New(keys ? num_args : 0);
  PyObject* phids = PyList_New(num_args);
  for (mx_uint i = 0; i < num_args; ++i) {
    if (keys) PyList_SET_ITEM(pkeys, i, PyUnicode_FromString(keys[i]));
    PyList_SET_ITEM(phids, i, PyLong_FromLongLong(
        static_cast<SymHandle*>(args[i])->hid));
  }
  PyObject* res = call_shim("sym_compose", "(LsOO)",
                            static_cast<SymHandle*>(sym)->hid,
                            name ? name : "", pkeys, phids);
  Py_DECREF(pkeys);
  Py_DECREF(phids);
  if (!res) return -1;
  Py_DECREF(res);
  return 0;
}

int MXTPUNDArraySlice(void* handle, mx_uint begin, mx_uint end, void** out) {
  GIL gil;
  PyObject* res = call_shim("nd_slice", "(LII)",
                            static_cast<NDHandle*>(handle)->hid,
                            begin, end);
  if (!res) return -1;
  *out = new NDHandle{PyLong_AsLongLong(res)};
  Py_DECREF(res);
  return 0;
}

int MXTPUNDArrayReshape(void* handle, int ndim, const int* dims, void** out) {
  GIL gil;
  PyObject* pdims = PyList_New(ndim);
  for (int i = 0; i < ndim; ++i) {
    PyList_SET_ITEM(pdims, i, PyLong_FromLong(dims[i]));
  }
  PyObject* res = call_shim("nd_reshape", "(LO)",
                            static_cast<NDHandle*>(handle)->hid, pdims);
  Py_DECREF(pdims);
  if (!res) return -1;
  *out = new NDHandle{PyLong_AsLongLong(res)};
  Py_DECREF(res);
  return 0;
}

int MXTPUNDArrayGetContext(void* handle, int* out_dev_type,
                           int* out_dev_id) {
  GIL gil;
  PyObject* res = call_shim("nd_context", "(L)",
                            static_cast<NDHandle*>(handle)->hid);
  if (!res) return -1;
  *out_dev_type = static_cast<int>(
      PyLong_AsLong(PyTuple_GET_ITEM(res, 0)));
  *out_dev_id = static_cast<int>(
      PyLong_AsLong(PyTuple_GET_ITEM(res, 1)));
  Py_DECREF(res);
  return 0;
}

int MXTPUNDArrayCopyFromTo(void* src, void* dst) {
  GIL gil;
  PyObject* res = call_shim("nd_copyfromto", "(LL)",
                            static_cast<NDHandle*>(src)->hid,
                            static_cast<NDHandle*>(dst)->hid);
  if (!res) return -1;
  Py_DECREF(res);
  return 0;
}

int MXTPUExecutorReshape(void* handle, mx_uint num_args, const char** keys,
                         const mx_uint* arg_ndims,
                         const mx_uint** arg_shapes, void** out) {
  GIL gil;
  PyObject* pkeys = PyList_New(num_args);
  PyObject* pshapes = PyList_New(num_args);
  for (mx_uint i = 0; i < num_args; ++i) {
    PyList_SET_ITEM(pkeys, i, PyUnicode_FromString(keys[i]));
    PyObject* shp = PyTuple_New(arg_ndims[i]);
    for (mx_uint j = 0; j < arg_ndims[i]; ++j) {
      PyTuple_SET_ITEM(shp, j, PyLong_FromUnsignedLong(arg_shapes[i][j]));
    }
    PyList_SET_ITEM(pshapes, i, shp);
  }
  PyObject* res = call_shim("exec_reshape", "(LOO)",
                            static_cast<ExecHandle*>(handle)->hid,
                            pkeys, pshapes);
  Py_DECREF(pkeys);
  Py_DECREF(pshapes);
  if (!res) return -1;
  *out = new ExecHandle{PyLong_AsLongLong(res)};
  Py_DECREF(res);
  return 0;
}

int MXTPUGetVersion(const char** out) {
  ensure_python();
  GIL gil;
  PyObject* res = call_shim("version", "()");
  if (!res) return -1;
  t_json = PyUnicode_AsUTF8(res);
  Py_DECREF(res);
  *out = t_json.c_str();
  return 0;
}

int MXTPURandomSeed(int seed) {
  ensure_python();
  GIL gil;
  PyObject* res = call_shim("random_seed", "(i)", seed);
  if (!res) return -1;
  Py_DECREF(res);
  return 0;
}

}  // extern "C"

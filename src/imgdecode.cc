// Native JPEG batch decoder — the TPU-side answer to the reference's
// OpenMP decode threads (/root/reference/src/io/iter_image_recordio.cc:140-160
// decodes chunks in parallel with OpenCV).  Python/PIL decode holds the GIL
// and tops out around ~300 img/s at 224^2; this decodes a whole batch on a
// C++ thread pool via libjpeg, GIL-free, scaling with cores.
//
// C ABI (consumed by mxnet_tpu/native.py via ctypes):
//   mxtpu_decode_jpeg_batch_alloc(bufs, lens, n, outs, ws, hs, nthreads)
// allocates and fills RGB HWC 8-bit buffers (freed via mxtpu_free_many).

#include <atomic>
#include <csetjmp>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <thread>
#include <vector>

#include <jpeglib.h>

namespace {

struct ErrMgr {
  jpeg_error_mgr pub;
  jmp_buf jump;
};

void on_error(j_common_ptr cinfo) {
  ErrMgr* err = reinterpret_cast<ErrMgr*>(cinfo->err);
  longjmp(err->jump, 1);
}

void silent_output(j_common_ptr) {}

// Decode one JPEG into out (RGB HWC, preallocated w*h*3). Returns 0 on ok.
int decode_one(const uint8_t* buf, size_t len, uint8_t* out, int want_w,
               int want_h) {
  jpeg_decompress_struct cinfo;
  ErrMgr jerr;
  cinfo.err = jpeg_std_error(&jerr.pub);
  jerr.pub.error_exit = on_error;
  jerr.pub.output_message = silent_output;
  if (setjmp(jerr.jump)) {
    jpeg_destroy_decompress(&cinfo);
    return -1;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_mem_src(&cinfo, const_cast<uint8_t*>(buf),
               static_cast<unsigned long>(len));
  jpeg_read_header(&cinfo, TRUE);
  cinfo.out_color_space = JCS_RGB;
  jpeg_start_decompress(&cinfo);
  if (static_cast<int>(cinfo.output_width) != want_w ||
      static_cast<int>(cinfo.output_height) != want_h ||
      cinfo.output_components != 3) {
    jpeg_abort_decompress(&cinfo);
    jpeg_destroy_decompress(&cinfo);
    return -2;
  }
  const size_t stride = static_cast<size_t>(want_w) * 3;
  while (cinfo.output_scanline < cinfo.output_height) {
    JSAMPROW row = out + static_cast<size_t>(cinfo.output_scanline) * stride;
    jpeg_read_scanlines(&cinfo, &row, 1);
  }
  jpeg_finish_decompress(&cinfo);
  jpeg_destroy_decompress(&cinfo);
  return 0;
}

}  // namespace

extern "C" {

// Peek dimensions without a full decode. Returns 0 on success.
int mxtpu_jpeg_dims(const uint8_t* buf, size_t len, int* w, int* h) {
  jpeg_decompress_struct cinfo;
  ErrMgr jerr;
  cinfo.err = jpeg_std_error(&jerr.pub);
  jerr.pub.error_exit = on_error;
  jerr.pub.output_message = silent_output;
  if (setjmp(jerr.jump)) {
    jpeg_destroy_decompress(&cinfo);
    return -1;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_mem_src(&cinfo, const_cast<uint8_t*>(buf),
               static_cast<unsigned long>(len));
  jpeg_read_header(&cinfo, TRUE);
  *w = static_cast<int>(cinfo.image_width);
  *h = static_cast<int>(cinfo.image_height);
  jpeg_destroy_decompress(&cinfo);
  return 0;
}

// One-call variant: header parse + allocation + decode all happen on the
// C++ thread pool (one GIL release for the whole batch).  outs[i] receives
// a malloc'd RGB HWC buffer (caller frees via mxtpu_free_many) and
// ws/hs[i] its dims; failed entries get outs[i]=NULL, ws/hs=0.
int mxtpu_decode_jpeg_batch_alloc(const uint8_t** bufs, const size_t* lens,
                                  int n, uint8_t** outs, int* ws, int* hs,
                                  int nthreads) {
  if (nthreads < 1) nthreads = 1;
  if (nthreads > n) nthreads = n;
  std::atomic<int> next(0), ok(0);
  auto worker = [&]() {
    for (;;) {
      int i = next.fetch_add(1);
      if (i >= n) return;
      outs[i] = nullptr;
      ws[i] = hs[i] = 0;
      int w = 0, h = 0;
      if (mxtpu_jpeg_dims(bufs[i], lens[i], &w, &h) != 0 || w <= 0 ||
          h <= 0) {
        continue;
      }
      uint8_t* out = static_cast<uint8_t*>(
          malloc(static_cast<size_t>(w) * h * 3));
      if (!out) continue;
      if (decode_one(bufs[i], lens[i], out, w, h) != 0) {
        free(out);
        continue;
      }
      outs[i] = out;
      ws[i] = w;
      hs[i] = h;
      ok.fetch_add(1);
    }
  };
  std::vector<std::thread> pool;
  pool.reserve(nthreads);
  for (int t = 0; t < nthreads; ++t) pool.emplace_back(worker);
  for (auto& th : pool) th.join();
  return ok.load();
}

void mxtpu_free_many(uint8_t** ptrs, int n) {
  for (int i = 0; i < n; ++i) {
    if (ptrs[i]) free(ptrs[i]);
    ptrs[i] = nullptr;
  }
}

}  // extern "C"

// Shared plumbing for the embeddable C ABI translation units
// (c_predict_api.cc, c_api.cc): thread-local error string, interpreter
// bring-up, GIL RAII, and the cached mxnet_tpu.capi_shim module.
#ifndef MXTPU_SRC_CAPI_COMMON_H_
#define MXTPU_SRC_CAPI_COMMON_H_

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <string>

namespace mxtpu_capi {

// Last error for this thread; read back via MXTPUGetLastError().
extern thread_local std::string g_last_error;

void set_error(const std::string& msg);

// Fetch the current Python exception into the error string.
void set_error_from_python();

// Initialize CPython if this process has no interpreter yet (standalone C
// embedder); a no-op when loaded into an existing Python process.
void ensure_python();

struct GIL {
  PyGILState_STATE state;
  GIL() { state = PyGILState_Ensure(); }
  ~GIL() { PyGILState_Release(state); }
};

// The mxnet_tpu.capi_shim module (borrowed ref, cached; GIL held).
PyObject* shim();

// Call a capi_shim function with Py_BuildValue-style args (fmt must build
// a tuple, e.g. "(Lsi)").  Returns a new ref, or nullptr with the error
// already captured into g_last_error.  GIL must be held.
PyObject* call_shim(const char* fn, const char* fmt, ...);

}  // namespace mxtpu_capi

#endif  // MXTPU_SRC_CAPI_COMMON_H_

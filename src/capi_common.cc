// Shared C-ABI plumbing — see capi_common.h.
#include "capi_common.h"

#include <cstdarg>
#include <mutex>

namespace mxtpu_capi {

thread_local std::string g_last_error;

void set_error(const std::string& msg) { g_last_error = msg; }

void set_error_from_python() {
  PyObject *type = nullptr, *value = nullptr, *tb = nullptr;
  PyErr_Fetch(&type, &value, &tb);
  std::string msg = "python error";
  if (value) {
    if (PyObject* s = PyObject_Str(value)) {
      if (const char* c = PyUnicode_AsUTF8(s)) msg = c;
      Py_DECREF(s);
    }
  }
  Py_XDECREF(type);
  Py_XDECREF(value);
  Py_XDECREF(tb);
  set_error(msg);
}

namespace {
std::once_flag g_init_once;
}

void ensure_python() {
  std::call_once(g_init_once, []() {
    if (!Py_IsInitialized()) {
      Py_InitializeEx(0);
      // release the GIL acquired by Py_Initialize so PyGILState_Ensure
      // works uniformly from any thread
      PyEval_SaveThread();
    }
  });
}

PyObject* shim() {
  static PyObject* mod = nullptr;  // accessed under the GIL only
  if (!mod) {
    mod = PyImport_ImportModule("mxnet_tpu.capi_shim");
  }
  return mod;
}

PyObject* call_shim(const char* fn, const char* fmt, ...) {
  PyObject* mod = shim();
  if (!mod) {
    set_error_from_python();
    return nullptr;
  }
  va_list va;
  va_start(va, fmt);
  PyObject* callable = PyObject_GetAttrString(mod, fn);
  PyObject* args = Py_VaBuildValue(fmt, va);
  va_end(va);
  PyObject* res = nullptr;
  if (callable && args) res = PyObject_CallObject(callable, args);
  Py_XDECREF(args);
  Py_XDECREF(callable);
  if (!res) set_error_from_python();
  return res;
}

}  // namespace mxtpu_capi

extern "C" const char* MXTPUGetLastError(void) {
  return mxtpu_capi::g_last_error.c_str();
}

// C prediction ABI — implementation.
//
// Reference parity: /root/reference/src/c_api/c_predict_api.cc:41-280 and
// c_api_error.cc (thread-local error string).  Design deviation, on
// purpose: the reference's C layer sits ABOVE its C++ executor; here the
// executor/compiler stack IS the Python/JAX runtime, so this layer embeds
// (or joins) a CPython interpreter and marshals primitives into
// mxnet_tpu.capi_shim.  The C surface stays flat and binding-friendly —
// what made the reference's R/Scala/JS frontends possible.
//
// Works both as a standalone embedder (C program links libmxtpu_capi.so,
// we Py_Initialize) and inside an existing Python process (ctypes dlopen,
// we just take the GIL).

#include "capi_common.h"

#include "mxtpu/c_predict_api.h"

#include <cstdint>
#include <cstring>
#include <string>
#include <vector>

using mx_uint = uint32_t;
using mxtpu_capi::GIL;
using mxtpu_capi::call_shim;
using mxtpu_capi::ensure_python;
using mxtpu_capi::set_error;
using mxtpu_capi::set_error_from_python;
using mxtpu_capi::shim;

namespace {

struct Predictor {
  long long hid = 0;
  std::vector<mx_uint> last_shape;  // backing for GetOutputShape
};

// shapes from the CSR arrays -> python list of tuples
PyObject* shapes_to_py(mx_uint n, const mx_uint* indptr, const mx_uint* data) {
  PyObject* list = PyList_New(n);
  for (mx_uint i = 0; i < n; ++i) {
    mx_uint lo = indptr[i], hi = indptr[i + 1];
    PyObject* tup = PyTuple_New(hi - lo);
    for (mx_uint j = lo; j < hi; ++j) {
      PyTuple_SET_ITEM(tup, j - lo, PyLong_FromUnsignedLong(data[j]));
    }
    PyList_SET_ITEM(list, i, tup);
  }
  return list;
}

PyObject* keys_to_py(mx_uint n, const char** keys) {
  PyObject* list = PyList_New(n);
  for (mx_uint i = 0; i < n; ++i) {
    PyList_SET_ITEM(list, i, PyUnicode_FromString(keys[i]));
  }
  return list;
}

}  // namespace

extern "C" {

int MXTPUPredCreate(const char* symbol_json, const void* param_bytes,
                    int param_size, int dev_type, int dev_id,
                    mx_uint num_input_nodes, const char** input_keys,
                    const mx_uint* input_shape_indptr,
                    const mx_uint* input_shape_data, void** out) {
  (void)dev_id;
  ensure_python();
  GIL gil;
  PyObject* keys = keys_to_py(num_input_nodes, input_keys);
  PyObject* shapes =
      shapes_to_py(num_input_nodes, input_shape_indptr, input_shape_data);
  PyObject* res = call_shim(
      "create", "(sy#OOi)", symbol_json,
      static_cast<const char*>(param_bytes),
      static_cast<Py_ssize_t>(param_size), keys, shapes, dev_type);
  Py_DECREF(keys);
  Py_DECREF(shapes);
  if (!res) return -1;
  auto* p = new Predictor();
  p->hid = PyLong_AsLongLong(res);
  Py_DECREF(res);
  *out = p;
  return 0;
}

int MXTPUPredSetInput(void* handle, const char* key, const float* data,
                      mx_uint size) {
  auto* p = static_cast<Predictor*>(handle);
  GIL gil;
  PyObject* res = call_shim(
      "set_input", "(Lsy#(k))", p->hid, key,
      reinterpret_cast<const char*>(data),
      static_cast<Py_ssize_t>(size * sizeof(float)),
      static_cast<unsigned long>(size));
  if (!res) return -1;
  Py_DECREF(res);
  return 0;
}

int MXTPUPredForward(void* handle) {
  auto* p = static_cast<Predictor*>(handle);
  GIL gil;
  PyObject* res = call_shim("forward", "(L)", p->hid);
  if (!res) return -1;
  Py_DECREF(res);
  return 0;
}

int MXTPUPredGetOutputShape(void* handle, mx_uint index, mx_uint** shape_data,
                            mx_uint* shape_ndim) {
  auto* p = static_cast<Predictor*>(handle);
  GIL gil;
  PyObject* res = call_shim("get_output_shape", "(Lk)", p->hid,
                            static_cast<unsigned long>(index));
  if (!res) return -1;
  Py_ssize_t n = PyTuple_Size(res);
  p->last_shape.resize(n);
  for (Py_ssize_t i = 0; i < n; ++i) {
    p->last_shape[i] = static_cast<mx_uint>(
        PyLong_AsUnsignedLong(PyTuple_GET_ITEM(res, i)));
  }
  Py_DECREF(res);
  *shape_data = p->last_shape.data();
  *shape_ndim = static_cast<mx_uint>(n);
  return 0;
}

int MXTPUPredGetOutput(void* handle, mx_uint index, float* data,
                       mx_uint size) {
  auto* p = static_cast<Predictor*>(handle);
  GIL gil;
  PyObject* res = call_shim("get_output", "(Lk)", p->hid,
                            static_cast<unsigned long>(index));
  if (!res) return -1;
  char* buf = nullptr;
  Py_ssize_t len = 0;
  if (PyBytes_AsStringAndSize(res, &buf, &len) != 0) {
    Py_DECREF(res);
    set_error_from_python();
    return -1;
  }
  if (static_cast<size_t>(len) != size * sizeof(float)) {
    Py_DECREF(res);
    set_error("output size mismatch: have " + std::to_string(len / 4) +
              " floats, caller asked for " + std::to_string(size));
    return -1;
  }
  std::memcpy(data, buf, len);
  Py_DECREF(res);
  return 0;
}

int MXTPUPredReshape(mx_uint num_input_nodes, const char** input_keys,
                     const mx_uint* input_shape_indptr,
                     const mx_uint* input_shape_data, void* handle,
                     void** out) {
  auto* p = static_cast<Predictor*>(handle);
  GIL gil;
  PyObject* keys = keys_to_py(num_input_nodes, input_keys);
  PyObject* shapes =
      shapes_to_py(num_input_nodes, input_shape_indptr, input_shape_data);
  PyObject* res = call_shim("reshape", "(LOO)", p->hid, keys, shapes);
  Py_DECREF(keys);
  Py_DECREF(shapes);
  if (!res) return -1;
  auto* p2 = new Predictor();
  p2->hid = PyLong_AsLongLong(res);
  Py_DECREF(res);
  *out = p2;
  return 0;
}

int MXTPUPredFree(void* handle) {
  auto* p = static_cast<Predictor*>(handle);
  if (!p) return 0;
  {
    GIL gil;
    // deliberately NOT call_shim: a failed free is ignored and must not
    // clobber the thread-local error a caller may be inspecting
    PyObject* res = PyObject_CallMethod(shim(), "free", "L", p->hid);
    if (res) Py_DECREF(res);
    else PyErr_Clear();
  }
  delete p;
  return 0;
}

}  // extern "C"

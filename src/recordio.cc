// Native RecordIO reader/writer + threaded prefetcher for mxnet_tpu.
//
// TPU-native equivalent of the reference's dmlc-core RecordIO framing
// (consumed at /root/reference/src/io/ — iter_image_recordio.cc reads
// dmlc::InputSplit chunks; iter_prefetcher.h:28-129 double-buffers with
// dmlc::ThreadedIter).  Same on-disk format as python recordio.py
// (magic 0xced7230a, little-endian u32 magic+lrec, 4-byte payload pad), so
// files are interchangeable between the C++ and Python paths and with the
// reference's packs.
//
// Exposed as a flat C ABI consumed via ctypes (no pybind11 in this image).
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <condition_variable>
#include <deque>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

namespace {

constexpr uint32_t kMagic = 0xced7230a;
constexpr uint32_t kLFlagBits = 29;
constexpr uint32_t kLengthMask = (1u << kLFlagBits) - 1;

struct Reader {
  FILE* fp = nullptr;
};

struct Writer {
  FILE* fp = nullptr;
};

// one decoded record
struct Record {
  std::vector<uint8_t> data;
  int64_t offset = -1;  // byte offset of the record header in the file
};

// Bounded-queue threaded prefetcher (dmlc::ThreadedIter semantics: one
// producer thread reads ahead of the consumer; consumer pops in order).
struct Prefetcher {
  FILE* fp = nullptr;
  std::thread producer;
  std::mutex mu;
  std::condition_variable not_empty, not_full;
  std::deque<Record> queue;
  size_t capacity = 8;
  bool eof = false;
  bool stop = false;
  std::string error;
};

// Reads one logical record, reassembling dmlc-core multi-part continuations
// (continue-flag 1=first/2=middle/3=last; the separator magic consumed by
// the writer's split is restored between parts).
bool read_record(FILE* fp, Record* out, std::string* err) {
  out->data.clear();
  bool expect_more = false;
  for (;;) {
    uint32_t head[2];
    int64_t off =
#ifdef _WIN32
        _ftelli64(fp);
#else
        ftello(fp);
#endif
    size_t n = fread(head, 1, sizeof(head), fp);
    if (n == 0) {
      if (expect_more) *err = "truncated multi-part record";
      return false;  // clean EOF (or truncation error set above)
    }
    if (n < sizeof(head)) {
      *err = "truncated record header";
      return false;
    }
    if (head[0] != kMagic) {
      *err = "invalid RecordIO magic";
      return false;
    }
    uint32_t lrec = head[1];
    uint32_t length = lrec & kLengthMask;
    uint32_t cflag = lrec >> kLFlagBits;
    if (!expect_more) {
      if (cflag == 2 || cflag == 3) {
        *err = "unexpected continuation record";
        return false;
      }
      out->offset = off;
    } else {
      if (cflag != 2 && cflag != 3) {
        *err = "unterminated multi-part record";
        return false;
      }
      const uint8_t* m = reinterpret_cast<const uint8_t*>(&kMagic);
      out->data.insert(out->data.end(), m, m + 4);
    }
    size_t old = out->data.size();
    out->data.resize(old + length);
    if (length && fread(out->data.data() + old, 1, length, fp) < length) {
      *err = "truncated record payload";
      return false;
    }
    uint32_t pad = (4 - (length % 4)) % 4;
    if (pad) fseek(fp, pad, SEEK_CUR);
    if (cflag == 0 || cflag == 3) return true;
    expect_more = true;
  }
}

void producer_loop(Prefetcher* p) {
  for (;;) {
    Record rec;
    std::string err;
    bool ok = read_record(p->fp, &rec, &err);
    std::unique_lock<std::mutex> lk(p->mu);
    if (!ok) {
      p->eof = true;
      p->error = err;
      p->not_empty.notify_all();
      return;
    }
    p->not_full.wait(lk, [p] { return p->queue.size() < p->capacity || p->stop; });
    if (p->stop) return;
    p->queue.push_back(std::move(rec));
    p->not_empty.notify_one();
  }
}

}  // namespace

extern "C" {

// ---- sequential reader ----------------------------------------------------
void* rio_reader_open(const char* path) {
  FILE* fp = fopen(path, "rb");
  if (!fp) return nullptr;
  auto* r = new Reader();
  r->fp = fp;
  return r;
}

// Returns payload length, 0 on EOF, -1 on error.  Caller frees *out with
// rio_free.  *offset receives the record's byte offset.
int64_t rio_read(void* handle, uint8_t** out, int64_t* offset) {
  auto* r = static_cast<Reader*>(handle);
  Record rec;
  std::string err;
  if (!read_record(r->fp, &rec, &err)) {
    return err.empty() ? 0 : -1;
  }
  *out = static_cast<uint8_t*>(malloc(rec.data.empty() ? 1 : rec.data.size()));
  memcpy(*out, rec.data.data(), rec.data.size());
  if (offset) *offset = rec.offset;
  return static_cast<int64_t>(rec.data.size());
}

int64_t rio_read_at(void* handle, int64_t pos, uint8_t** out) {
  auto* r = static_cast<Reader*>(handle);
#ifdef _WIN32
  _fseeki64(r->fp, pos, SEEK_SET);
#else
  fseeko(r->fp, pos, SEEK_SET);
#endif
  return rio_read(handle, out, nullptr);
}

void rio_reader_reset(void* handle) {
  auto* r = static_cast<Reader*>(handle);
  fseek(r->fp, 0, SEEK_SET);
}

void rio_reader_close(void* handle) {
  auto* r = static_cast<Reader*>(handle);
  if (r->fp) fclose(r->fp);
  delete r;
}

// ---- writer ---------------------------------------------------------------
void* rio_writer_open(const char* path) {
  FILE* fp = fopen(path, "wb");
  if (!fp) return nullptr;
  auto* w = new Writer();
  w->fp = fp;
  return w;
}

namespace {
bool write_part(FILE* fp, uint32_t cflag, const uint8_t* buf, size_t len) {
  uint32_t head[2] = {kMagic,
                      (cflag << kLFlagBits) | static_cast<uint32_t>(len)};
  if (fwrite(head, 1, sizeof(head), fp) < sizeof(head)) return false;
  if (len && fwrite(buf, 1, len, fp) < len) return false;
  uint32_t pad = (4 - (len % 4)) % 4;
  static const uint8_t zeros[4] = {0, 0, 0, 0};
  if (pad && fwrite(zeros, 1, pad, fp) < pad) return false;
  return true;
}
}  // namespace

// Writes one logical record, splitting at 4-byte-aligned occurrences of the
// magic word in the payload (dmlc-core multi-part framing; the magic is
// consumed as the part separator and restored by read_record).
// Returns the byte offset the record was written at, or -1 on error.
int64_t rio_write(void* handle, const uint8_t* buf, int64_t len) {
  auto* w = static_cast<Writer*>(handle);
  if (len < 0 || static_cast<uint64_t>(len) > kLengthMask) return -1;
  int64_t off =
#ifdef _WIN32
      _ftelli64(w->fp);
#else
      ftello(w->fp);
#endif
  size_t size = static_cast<size_t>(len);
  std::vector<size_t> splits;
  for (size_t i = 0; i + 4 <= size; i += 4) {
    if (memcmp(buf + i, &kMagic, 4) == 0) splits.push_back(i);
  }
  if (splits.empty()) {
    if (!write_part(w->fp, 0, buf, size)) return -1;
    return off;
  }
  size_t begin = 0;
  for (size_t n = 0; n < splits.size(); ++n) {
    if (!write_part(w->fp, n == 0 ? 1 : 2, buf + begin, splits[n] - begin))
      return -1;
    begin = splits[n] + 4;
  }
  if (!write_part(w->fp, 3, buf + begin, size - begin)) return -1;
  return off;
}

void rio_writer_close(void* handle) {
  auto* w = static_cast<Writer*>(handle);
  if (w->fp) fclose(w->fp);
  delete w;
}

// ---- threaded prefetcher --------------------------------------------------
void* rio_prefetch_open(const char* path, int capacity) {
  FILE* fp = fopen(path, "rb");
  if (!fp) return nullptr;
  auto* p = new Prefetcher();
  p->fp = fp;
  p->capacity = capacity > 0 ? static_cast<size_t>(capacity) : 8;
  p->producer = std::thread(producer_loop, p);
  return p;
}

// Pops the next prefetched record: returns length, 0 on EOF, -1 on error.
int64_t rio_prefetch_next(void* handle, uint8_t** out) {
  auto* p = static_cast<Prefetcher*>(handle);
  std::unique_lock<std::mutex> lk(p->mu);
  p->not_empty.wait(lk, [p] { return !p->queue.empty() || p->eof; });
  if (p->queue.empty()) {
    return p->error.empty() ? 0 : -1;
  }
  Record rec = std::move(p->queue.front());
  p->queue.pop_front();
  p->not_full.notify_one();
  lk.unlock();
  *out = static_cast<uint8_t*>(malloc(rec.data.empty() ? 1 : rec.data.size()));
  memcpy(*out, rec.data.data(), rec.data.size());
  return static_cast<int64_t>(rec.data.size());
}

void rio_prefetch_close(void* handle) {
  auto* p = static_cast<Prefetcher*>(handle);
  {
    std::lock_guard<std::mutex> lk(p->mu);
    p->stop = true;
  }
  p->not_full.notify_all();
  if (p->producer.joinable()) p->producer.join();
  if (p->fp) fclose(p->fp);
  delete p;
}

void rio_free(uint8_t* buf) { free(buf); }

// sanity/version probe for the ctypes loader
int64_t rio_abi_version() { return 2; }  // 2: + imgdecode.cc jpeg batch API

}  // extern "C"

/* Standalone C consumer of the SYMBOL/EXECUTOR ABI — unlike demo.c (which
 * uses the fixed-function predict API), this builds the graph from JSON,
 * infers shapes, binds NDArrays and runs the executor: the full
 * MXSymbolCreateFromJSON -> MXExecutorBind -> MXExecutorForward flow a
 * language binding would use (reference: c_api_symbolic.cc:54-545,
 * c_api_executor.cc:11-157).  The process starts with NO Python;
 * libmxtpu_capi.so embeds the interpreter.
 *
 * Usage: demo_symbol <prefix> <epoch> <batch> <dim>
 * Reads <prefix>-symbol.json + <prefix>-<epoch 04d>.params, feeds a
 * deterministic batch, prints the first output row as CSV.
 */
#include <stdio.h>
#include <stdlib.h>
#include <string.h>

#include "mxtpu/c_api.h"

#define CHECK(rc)                                                     \
  do {                                                                \
    if ((rc) != 0) {                                                  \
      fprintf(stderr, "error: %s\n", MXTPUGetLastError());            \
      return 1;                                                       \
    }                                                                 \
  } while (0)

int main(int argc, char** argv) {
  if (argc < 5) {
    fprintf(stderr, "usage: %s prefix epoch batch dim\n", argv[0]);
    return 2;
  }
  const char* prefix = argv[1];
  int epoch = atoi(argv[2]);
  mx_uint batch = (mx_uint)atoi(argv[3]);
  mx_uint dim = (mx_uint)atoi(argv[4]);
  char path[512];

  /* graph from the -symbol.json file */
  snprintf(path, sizeof path, "%s-symbol.json", prefix);
  SymbolHandle sym = NULL;
  CHECK(MXTPUSymbolCreateFromFile(path, &sym));

  mx_uint n_args = 0;
  const char** arg_names_tl = NULL;
  CHECK(MXTPUSymbolListArguments(sym, &n_args, &arg_names_tl));
  /* copy out: name tables are thread-local, next call invalidates them */
  char** arg_names = (char**)malloc(n_args * sizeof(char*));
  for (mx_uint i = 0; i < n_args; ++i) arg_names[i] = strdup(arg_names_tl[i]);

  /* shapes for every argument from the input shape alone */
  const char* keys[2] = {"data", "softmax_label"};
  mx_uint indptr[3] = {0, 2, 3};
  mx_uint sdata[3] = {batch, dim, batch};
  mx_uint in_size, out_size_s, aux_size;
  const mx_uint *in_ndim, *out_ndim, *aux_ndim;
  const mx_uint **in_data, **out_data, **aux_data;
  int complete = 0;
  CHECK(MXTPUSymbolInferShape(sym, 2, keys, indptr, sdata, &in_size,
                              &in_ndim, &in_data, &out_size_s, &out_ndim,
                              &out_data, &aux_size, &aux_ndim, &aux_data,
                              &complete));
  if (!complete || in_size != n_args) {
    fprintf(stderr, "shape inference incomplete\n");
    return 1;
  }
  /* copy the arg shapes out of thread-local storage before further calls */
  mx_uint* arg_ndim = (mx_uint*)malloc(n_args * sizeof(mx_uint));
  mx_uint** arg_shape = (mx_uint**)malloc(n_args * sizeof(mx_uint*));
  for (mx_uint i = 0; i < n_args; ++i) {
    arg_ndim[i] = in_ndim[i];
    arg_shape[i] = (mx_uint*)malloc(in_ndim[i] * sizeof(mx_uint));
    memcpy(arg_shape[i], in_data[i], in_ndim[i] * sizeof(mx_uint));
  }

  /* weights from the checkpoint (keys are "arg:<name>" / "aux:<name>") */
  snprintf(path, sizeof path, "%s-%04d.params", prefix, epoch);
  mx_uint n_loaded = 0, n_names = 0;
  NDArrayHandle* loaded = NULL;
  const char** loaded_names_tl = NULL;
  CHECK(MXTPUNDArrayLoad(path, &n_loaded, &loaded, &n_names,
                         &loaded_names_tl));
  char** loaded_names = (char**)malloc(n_names * sizeof(char*));
  for (mx_uint i = 0; i < n_names; ++i)
    loaded_names[i] = strdup(loaded_names_tl[i]);

  /* one NDArray per argument: checkpoint weight if named, zeros for the
   * data/label inputs */
  NDArrayHandle* args = (NDArrayHandle*)calloc(n_args, sizeof(NDArrayHandle));
  int* from_ckpt = (int*)calloc(n_args, sizeof(int));
  for (mx_uint i = 0; i < n_args; ++i) {
    for (mx_uint j = 0; j < n_loaded; ++j) {
      const char* nm = loaded_names[j];
      if (strncmp(nm, "arg:", 4) == 0 && strcmp(nm + 4, arg_names[i]) == 0) {
        args[i] = loaded[j];
        from_ckpt[i] = 1;
        break;
      }
    }
    if (!args[i]) {
      CHECK(MXTPUNDArrayCreate(arg_shape[i], arg_ndim[i], 1, 0, 0,
                               &args[i]));
    }
  }

  /* deterministic input batch, same pattern as demo.c */
  size_t n_in = (size_t)batch * dim;
  float* x = (float*)malloc(n_in * sizeof(float));
  /* (int) before the subtraction: i is unsigned, (i%7)-3 would wrap */
  for (size_t i = 0; i < n_in; ++i)
    x[i] = ((float)(int)(i % 7) - 3.0f) * 0.25f;
  for (mx_uint i = 0; i < n_args; ++i) {
    if (strcmp(arg_names[i], "data") == 0) {
      CHECK(MXTPUNDArraySyncCopyFromCPU(args[i], x, n_in * sizeof(float)));
    }
  }

  /* bind (no gradients — inference) and run */
  ExecutorHandle ex = NULL;
  CHECK(MXTPUExecutorBind(sym, 1, 0, n_args, args, NULL, NULL, 0, NULL,
                          &ex));
  CHECK(MXTPUExecutorForward(ex, 0));

  mx_uint n_out = 0;
  NDArrayHandle* outs = NULL;
  CHECK(MXTPUExecutorOutputs(ex, &n_out, &outs));
  mx_uint ndim = 0;
  const mx_uint* oshape = NULL;
  CHECK(MXTPUNDArrayGetShape(outs[0], &ndim, &oshape));
  mx_uint cols = ndim >= 2 ? oshape[1] : 1;
  size_t total = 1;
  for (mx_uint i = 0; i < ndim; ++i) total *= oshape[i];
  float* out = (float*)malloc(total * sizeof(float));
  CHECK(MXTPUNDArraySyncCopyToCPU(outs[0], out, total * sizeof(float)));
  for (mx_uint j = 0; j < cols; ++j) {
    printf(j ? ",%g" : "%g", out[j]);
  }
  printf("\n");

  for (mx_uint i = 0; i < n_out; ++i) MXTPUNDArrayFree(outs[i]);
  MXTPUFreeHandleArray(outs);
  MXTPUExecutorFree(ex);
  /* every loaded handle is freed exactly once (some are also in args) */
  for (mx_uint i = 0; i < n_args; ++i) {
    if (!from_ckpt[i]) MXTPUNDArrayFree(args[i]);
  }
  for (mx_uint j = 0; j < n_loaded; ++j) MXTPUNDArrayFree(loaded[j]);
  MXTPUFreeHandleArray(loaded);
  MXTPUSymbolFree(sym);
  return 0;
}

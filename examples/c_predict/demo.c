/* Standalone C consumer of the prediction ABI — proves the embedding path
 * (this process starts with NO Python interpreter; libmxtpu_capi.so brings
 * one up).  Reference analogue: the image-classification predict example
 * built on c_predict_api.h.
 *
 * Usage: demo <prefix> <epoch> <n_inputs> <input_dim>
 * Reads <prefix>-symbol.json and <prefix>-<epoch 04d>.params, feeds a
 * deterministic batch, prints the first output row as CSV.
 */
#include <stdio.h>
#include <stdlib.h>
#include <string.h>

#include "mxtpu/c_predict_api.h"

static char* read_file(const char* path, long* size) {
  FILE* f = fopen(path, "rb");
  if (!f) { fprintf(stderr, "cannot open %s\n", path); exit(2); }
  fseek(f, 0, SEEK_END);
  *size = ftell(f);
  fseek(f, 0, SEEK_SET);
  char* buf = (char*)malloc(*size + 1);
  if (fread(buf, 1, *size, f) != (size_t)*size) { exit(2); }
  buf[*size] = 0;
  fclose(f);
  return buf;
}

int main(int argc, char** argv) {
  if (argc < 5) {
    fprintf(stderr, "usage: %s prefix epoch batch dim\n", argv[0]);
    return 2;
  }
  const char* prefix = argv[1];
  int epoch = atoi(argv[2]);
  mx_uint batch = (mx_uint)atoi(argv[3]);
  mx_uint dim = (mx_uint)atoi(argv[4]);

  char path[512];
  long sym_size, param_size;
  snprintf(path, sizeof path, "%s-symbol.json", prefix);
  char* sym_json = read_file(path, &sym_size);
  snprintf(path, sizeof path, "%s-%04d.params", prefix, epoch);
  char* params = read_file(path, &param_size);

  const char* keys[2] = {"data", "softmax_label"};
  mx_uint indptr[3] = {0, 2, 3};
  mx_uint shapes[3] = {batch, dim, batch};
  PredictorHandle h = NULL;
  if (MXTPUPredCreate(sym_json, params, (int)param_size, 1, 0, 2, keys,
                      indptr, shapes, &h) != 0) {
    fprintf(stderr, "create failed: %s\n", MXTPUGetLastError());
    return 1;
  }

  float* data = (float*)malloc(sizeof(float) * batch * dim);
  for (mx_uint i = 0; i < batch * dim; ++i) {
    /* (int) before the subtraction: i is unsigned, (i%7)-3 would wrap */
    data[i] = ((float)(int)(i % 7) - 3.0f) / 3.0f;
  }
  if (MXTPUPredSetInput(h, "data", data, batch * dim) != 0 ||
      MXTPUPredForward(h) != 0) {
    fprintf(stderr, "forward failed: %s\n", MXTPUGetLastError());
    return 1;
  }
  mx_uint* oshape;
  mx_uint ondim;
  if (MXTPUPredGetOutputShape(h, 0, &oshape, &ondim) != 0) return 1;
  mx_uint total = 1;
  for (mx_uint i = 0; i < ondim; ++i) total *= oshape[i];
  float* out = (float*)malloc(sizeof(float) * total);
  if (MXTPUPredGetOutput(h, 0, out, total) != 0) {
    fprintf(stderr, "get output failed: %s\n", MXTPUGetLastError());
    return 1;
  }
  mx_uint row = ondim > 1 ? oshape[ondim - 1] : total;
  for (mx_uint i = 0; i < row; ++i) {
    printf(i ? ",%g" : "%g", out[i]);
  }
  printf("\n");
  MXTPUPredFree(h);
  free(out);
  free(data);
  free(sym_json);
  free(params);
  return 0;
}

#!/usr/bin/env python
"""Model-parallel multi-layer LSTM (reference:
example/model-parallel-lstm/lstm.py:142-205 — BASELINE config #5).

Each LSTM layer is pinned to a device via ``group2ctx`` (the reference's
``AttrScope(ctx_group=...)`` + PlaceDevice pass); activations cross device
boundaries through compiled transfers (our jax.device_put = the reference's
``_CrossDeviceCopy`` nodes). Trains a next-token model on a synthetic
corpus; perplexity must fall.

For mesh-style pipelining of homogeneous stacks see
``mxnet_tpu.parallel.pipeline_spmd`` — the TPU-native successor to this
placement scheme."""
from __future__ import annotations

import argparse
import logging
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import mxnet_tpu as mx  # noqa: E402
from examples.rnn.lstm_bucketing import synthetic_corpus  # noqa: E402


def build_symbol(seq_len, num_layers, num_hidden, num_embed, vocab_size):
    """Unrolled stacked LSTM with one ctx group per layer."""
    data = mx.sym.Variable("data")
    label = mx.sym.Variable("softmax_label")
    with mx.AttrScope(ctx_group="embed"):
        hidden = mx.sym.Embedding(data=data, input_dim=vocab_size,
                                  output_dim=num_embed, name="embed")
    for i in range(num_layers):
        with mx.AttrScope(ctx_group="layer%d" % i):
            cell = mx.rnn.LSTMCell(num_hidden=num_hidden,
                                   prefix="lstm_l%d_" % i)
            outputs, _ = cell.unroll(seq_len, inputs=hidden,
                                     merge_outputs=True)
            hidden = outputs
    with mx.AttrScope(ctx_group="decode"):
        pred = mx.sym.Reshape(hidden, shape=(-1, num_hidden))
        pred = mx.sym.FullyConnected(data=pred, num_hidden=vocab_size,
                                     name="pred")
        label_r = mx.sym.Reshape(label, shape=(-1,))
        sm = mx.sym.SoftmaxOutput(data=pred, label=label_r, name="softmax")
    return sm


def main():
    ap = argparse.ArgumentParser(description="model-parallel lstm")
    ap.add_argument("--num-layers", type=int, default=2)
    ap.add_argument("--num-hidden", type=int, default=64)
    ap.add_argument("--num-embed", type=int, default=64)
    ap.add_argument("--batch-size", type=int, default=16)
    ap.add_argument("--seq-len", type=int, default=16)
    ap.add_argument("--num-epochs", type=int, default=3)
    ap.add_argument("--lr", type=float, default=0.05)
    args = ap.parse_args()
    logging.basicConfig(level=logging.INFO,
                        format="%(asctime)-15s %(message)s")

    import jax

    n_dev = len(jax.devices())
    group2ctx = {"embed": mx.Context("cpu", 0), "decode":
                 mx.Context("cpu", max(0, n_dev - 1))}
    for i in range(args.num_layers):
        group2ctx["layer%d" % i] = mx.Context("cpu", (i + 1) % n_dev)
    logging.info("placement: %s", group2ctx)

    vocab_size = 64
    sents = [s[:args.seq_len] for s in synthetic_corpus(vocab_size, 800)
             if len(s) >= args.seq_len]
    data = np.array(sents, np.float32)
    x, y = data[:, :-1], data[:, 1:]

    net = build_symbol(args.seq_len - 1, args.num_layers, args.num_hidden,
                       args.num_embed, vocab_size)
    exe = net.simple_bind(
        mx.cpu(), data=(args.batch_size, args.seq_len - 1),
        softmax_label=(args.batch_size * (args.seq_len - 1),),
        grad_req="write", group2ctx=group2ctx)
    rng = np.random.RandomState(0)
    for name, arr in exe.arg_dict.items():
        if name in ("data", "softmax_label"):
            continue
        arr[:] = rng.uniform(-0.08, 0.08, arr.shape).astype(np.float32)

    n_batches = len(x) // args.batch_size
    for epoch in range(args.num_epochs):
        tot_nll, tot_tok = 0.0, 0
        for b in range(n_batches):
            sl = slice(b * args.batch_size, (b + 1) * args.batch_size)
            exe.arg_dict["data"][:] = x[sl]
            exe.arg_dict["softmax_label"][:] = y[sl].reshape(-1)
            probs = exe.forward(is_train=True)[0].asnumpy()
            exe.backward()
            for name, grad in exe.grad_dict.items():
                if name in ("data", "softmax_label") or grad is None:
                    continue
                mx.nd.sgd_update(exe.arg_dict[name], grad,
                                 out=exe.arg_dict[name], lr=args.lr)
            lab = y[sl].reshape(-1).astype(int)
            picked = probs[np.arange(len(lab)), lab]
            tot_nll -= np.log(np.maximum(picked, 1e-10)).sum()
            tot_tok += len(lab)
        ppl = np.exp(tot_nll / tot_tok)
        logging.info("Epoch[%d] Train-Perplexity=%.3f", epoch, ppl)
    print('{"metric": "final_perplexity", "value": %.3f}' % ppl)


if __name__ == "__main__":
    main()

"""LSTM + CTC sequence labeling (reference: example/warpctc/lstm_ocr.py,
the warp-ctc plugin's showcase — captcha OCR there; a generated
frame-stream task here so the example runs without image assets).

Task: each sample is a digit string rendered as a stream of noisy frames
(each symbol held for a random number of frames, blanks between); the
model reads the frames with an LSTM and is trained with the ``WarpCTC``
loss (blank=0) to emit the digit string. Greedy CTC decoding (collapse
repeats, drop blanks) measures sequence accuracy.

Usage: python lstm_ocr.py [--num-epochs 10]
"""
from __future__ import annotations

import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import mxnet_tpu as mx  # noqa: E402


def make_dataset(n_samples, seq_len, label_len, n_classes, feat_dim,
                 seed=0):
    """Frames: a fixed random template per symbol + noise; labels padded
    with 0 (the CTC blank)."""
    rng = np.random.RandomState(seed)
    templates = rng.randn(n_classes + 1, feat_dim).astype(np.float32)
    X = np.zeros((n_samples, seq_len, feat_dim), np.float32)
    Y = np.zeros((n_samples, label_len), np.float32)
    for i in range(n_samples):
        n_sym = rng.randint(1, label_len + 1)
        syms = rng.randint(1, n_classes + 1, size=n_sym)
        Y[i, :n_sym] = syms
        t = 0
        for s_ in syms:
            hold = rng.randint(2, 4)
            for _ in range(hold):
                if t >= seq_len:
                    break
                X[i, t] = templates[s_] + rng.randn(feat_dim) * 0.3
                t += 1
            if t < seq_len and rng.rand() < 0.5:
                X[i, t] = templates[0] + rng.randn(feat_dim) * 0.3  # blank
                t += 1
    return X, Y


def build_net(seq_len, label_len, num_hidden, n_classes):
    data = mx.sym.Variable("data")          # (N, T, F)
    label = mx.sym.Variable("label")        # (N, L)
    cell = mx.rnn.LSTMCell(num_hidden=num_hidden, prefix="lstm_")
    outputs, _ = cell.unroll(seq_len, inputs=data, layout="NTC",
                             merge_outputs=True)       # (N, T, H)
    tm = mx.sym.transpose(outputs, axes=(1, 0, 2))     # (T, N, H) time-major
    pred = mx.sym.Reshape(tm, shape=(-1, num_hidden))  # (T*N, H)
    pred = mx.sym.FullyConnected(pred, num_hidden=n_classes + 1,
                                 name="pred")          # (T*N, P)
    return mx.sym.WarpCTC(data=pred, label=label, label_length=label_len,
                          input_length=seq_len)


def ctc_greedy_decode(probs, seq_len, n_batch):
    """probs: (T*N, P) time-major softmax -> list of decoded label lists
    (collapse repeats, drop blanks)."""
    path = probs.reshape(seq_len, n_batch, -1).argmax(-1)  # (T, N)
    out = []
    for n in range(n_batch):
        prev, dec = -1, []
        for t in range(seq_len):
            c = int(path[t, n])
            if c != prev and c != 0:
                dec.append(c)
            prev = c
        out.append(dec)
    return out


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--num-epochs", type=int, default=10)
    ap.add_argument("--batch-size", type=int, default=16)
    ap.add_argument("--seq-len", type=int, default=20)
    ap.add_argument("--label-len", type=int, default=4)
    ap.add_argument("--num-classes", type=int, default=10)
    ap.add_argument("--feat-dim", type=int, default=16)
    ap.add_argument("--num-hidden", type=int, default=64)
    ap.add_argument("--num-samples", type=int, default=480)
    ap.add_argument("--lr", type=float, default=1e-2)
    args = ap.parse_args(argv)

    X, Y = make_dataset(args.num_samples, args.seq_len, args.label_len,
                        args.num_classes, args.feat_dim)
    it = mx.io.NDArrayIter(X, Y, batch_size=args.batch_size,
                           shuffle=True, label_name="label")
    net = build_net(args.seq_len, args.label_len, args.num_hidden,
                    args.num_classes)
    mod = mx.mod.Module(net, data_names=("data",), label_names=("label",))

    def ctc_acc(labels, preds):
        """Greedy-decode sequence accuracy (the reference example's custom
        metric shape: feval over (labels, softmax))."""
        n = labels.shape[0]
        decoded = ctc_greedy_decode(preds, args.seq_len, n)
        hits = sum(int(decoded[i] ==
                       [int(v) for v in labels[i] if v != 0])
                   for i in range(n))
        return hits / float(n)

    mod.fit(it, num_epoch=args.num_epochs, optimizer="adam",
            optimizer_params={"learning_rate": args.lr},
            eval_metric=mx.metric.np(ctc_acc, allow_extra_outputs=True),
            initializer=mx.init.Xavier(factor_type="in", magnitude=2.34),
            batch_end_callback=mx.callback.Speedometer(args.batch_size, 10))

    # sequence accuracy via greedy CTC decode
    it.reset()
    correct = total = 0
    for batch in it:
        mod.forward(batch, is_train=False)
        probs = mod.get_outputs()[0].asnumpy()
        decoded = ctc_greedy_decode(probs, args.seq_len, args.batch_size)
        labels = batch.label[0].asnumpy()
        for n in range(args.batch_size):
            want = [int(v) for v in labels[n] if v != 0]
            correct += int(decoded[n] == want)
            total += 1
    acc = correct / total
    print({"metric": "ctc_sequence_accuracy", "value": round(acc, 4)})
    return acc


if __name__ == "__main__":
    main()

"""Train a decoder-only transformer LM through the Module path.

The transformer-family counterpart of train_imagenet.py: real data from a
token .txt corpus (whitespace tokenization) or --benchmark mode with
synthetic tokens, optimized via the fused train step, attention through
the Pallas flash kernels. Beyond-reference model family (the 2017
reference's sequence example is example/rnn/lstm_bucketing.py).

Usage:
  python train_lm.py --benchmark 1 --seq-len 2048 --hidden 1024
  python train_lm.py --data-train corpus.txt --num-epochs 5
"""
import argparse
import logging
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np

import mxnet_tpu as mx


def add_args(parser):
    parser.add_argument("--data-train", type=str, default=None)
    parser.add_argument("--vocab-size", type=int, default=32000)
    parser.add_argument("--num-layers", type=int, default=4)
    parser.add_argument("--num-heads", type=int, default=8)
    parser.add_argument("--hidden", type=int, default=512)
    parser.add_argument("--seq-len", type=int, default=512)
    parser.add_argument("--batch-size", type=int, default=8)
    parser.add_argument("--lr", type=float, default=3e-4)
    parser.add_argument("--optimizer", default="adam")
    parser.add_argument("--num-epochs", type=int, default=3)
    parser.add_argument("--dtype", default="float32",
                        choices=["float32", "bfloat16"])
    parser.add_argument("--benchmark", type=int, default=0)
    parser.add_argument("--num-steps", type=int, default=20)
    parser.add_argument("--warmup", type=int, default=3)
    parser.add_argument("--kv-store", default="local")
    parser.add_argument("--disp-batches", type=int, default=10)
    return parser


def _corpus_iter(path, vocab_size, seq_len, batch_size):
    """Whitespace-token corpus -> (b, s) windows, next-token labels."""
    with open(path) as f:
        toks = f.read().split()
    vocab = {}
    ids = np.array([vocab.setdefault(t, len(vocab) % vocab_size)
                    for t in toks], np.float32)
    n = (len(ids) - 1) // seq_len
    X = ids[:n * seq_len].reshape(n, seq_len)
    Y = ids[1:n * seq_len + 1].reshape(n, seq_len)
    return mx.io.NDArrayIter(X, Y, batch_size=batch_size, shuffle=True,
                             label_name="softmax_label")


def _synth_iter(vocab_size, seq_len, batch_size, batches):
    rng = np.random.RandomState(0)
    X = rng.randint(0, vocab_size,
                    size=(batches * batch_size, seq_len)).astype(np.float32)
    Y = (X + 1) % vocab_size
    return mx.io.NDArrayIter(X, Y, batch_size=batch_size,
                             label_name="softmax_label")


def benchmark(args, net):
    """Synthetic-token steady-state throughput via the fused Module step."""
    it = _synth_iter(args.vocab_size, args.seq_len, args.batch_size, 1)
    mod = mx.mod.Module(net, label_names=("softmax_label",),
                        compute_dtype=args.dtype)
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label,
             for_training=True)
    mod.init_params(initializer=mx.init.Xavier(factor_type="in",
                                               magnitude=2.34))
    mod.init_optimizer(kvstore=args.kv_store, optimizer=args.optimizer,
                       optimizer_params={"learning_rate": args.lr})
    batch = it.next()

    def sync():
        name = mod._exec_group.param_names[-1]
        return mod._exec_group.execs[0].arg_dict[name].asnumpy()

    for _ in range(args.warmup):
        mod.forward_backward(batch)
        mod.update()
    sync()
    t0 = time.time()
    for _ in range(args.num_steps):
        mod.forward_backward(batch)
        mod.update()
    sync()
    dt = time.time() - t0
    toks = args.batch_size * args.seq_len * args.num_steps
    b, s, h, nh, l = (args.batch_size, args.seq_len, args.hidden,
                      args.num_heads, args.num_layers)
    v = args.vocab_size
    # 6ND matmul flops (N = block params + untied lm_head; the input
    # embedding is a gather, not a matmul — counting it would inflate
    # MFU) + the causal attention term, fwd+bwd
    n_params = l * 12 * h * h + v * h
    flops = 6.0 * n_params * toks + l * args.num_steps * \
        (0.5 * 4 * b * nh * s * s * (h // nh)) * 3
    return {"tokens_per_sec": toks / dt, "step_time_ms": dt * 1e3 /
            args.num_steps, "model_tflops": flops / dt / 1e12}


def main():
    args = add_args(argparse.ArgumentParser()).parse_args()
    logging.basicConfig(level=logging.INFO)
    net = mx.models.get_transformer_lm(
        vocab_size=args.vocab_size, num_layers=args.num_layers,
        num_heads=args.num_heads, hidden=args.hidden, seq_len=args.seq_len)
    if args.benchmark:
        stats = benchmark(args, net)
        print({k: round(v, 2) for k, v in stats.items()})
        return
    if args.data_train is None:
        raise SystemExit("--data-train or --benchmark 1 required")
    it = _corpus_iter(args.data_train, args.vocab_size, args.seq_len,
                      args.batch_size)
    mod = mx.mod.Module(net, label_names=("softmax_label",),
                        compute_dtype=args.dtype)
    mod.fit(it, num_epoch=args.num_epochs, optimizer=args.optimizer,
            optimizer_params={"learning_rate": args.lr},
            eval_metric=mx.metric.Perplexity(ignore_label=None),
            batch_end_callback=mx.callback.Speedometer(
                args.batch_size, args.disp_batches))


if __name__ == "__main__":
    main()

"""Long-context LM training: sequence parallelism over a device mesh.

The long-context counterpart of train_lm.py. Activations are sharded
along the SEQUENCE axis of a ('data', 'seq') mesh; attention is
``ring_flash_attention`` (K/V and their gradients ride the ring via
ppermute, per-block compute is the Pallas flash kernel), so per-device
memory is O(seq/n_seq) and context length is bounded by the pod's HBM,
not one chip's. Everything else (matmuls, layernorm, losses) is
position-local, so XLA partitions it along the same axis with no extra
communication beyond the psum for data-parallel gradients.

This is the capability the 2017 reference could not express at all
(its longest-sequence story was bucketing, SURVEY.md §5.7).

Usage (8 virtual devices):
  XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
    python train_lm_longctx.py --seq-len 1024 --seq-shards 4 --steps 5
"""
import argparse
import functools
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np


def build_params(rng, vocab, hidden, heads, layers, seq_len):
    import jax.numpy as jnp

    def glorot(*shape):
        scale = np.sqrt(2.0 / (shape[0] + shape[-1]))
        return jnp.asarray(rng.randn(*shape).astype(np.float32) * scale)

    params = {"embed": glorot(vocab, hidden),
              "pos": glorot(seq_len, hidden) * 0.1,
              "ln_f": {"g": jnp.ones(hidden), "b": jnp.zeros(hidden)},
              "head": glorot(hidden, vocab), "layers": []}
    for _ in range(layers):
        params["layers"].append({
            "ln1": {"g": jnp.ones(hidden), "b": jnp.zeros(hidden)},
            "qkv": glorot(hidden, 3 * hidden),
            "proj": glorot(hidden, hidden),
            "ln2": {"g": jnp.ones(hidden), "b": jnp.zeros(hidden)},
            "fc1": glorot(hidden, 4 * hidden),
            "fc2": glorot(4 * hidden, hidden)})
    return params


def make_step(mesh, heads, block, lr):
    import jax
    import jax.numpy as jnp

    from mxnet_tpu.parallel.ring import ring_flash_attention

    def ln(x, p):
        m = x.mean(-1, keepdims=True)
        v = ((x - m) ** 2).mean(-1, keepdims=True)
        return (x - m) * jax.lax.rsqrt(v + 1e-5) * p["g"] + p["b"]

    def forward(params, tokens):
        b, s = tokens.shape
        h = params["embed"][tokens] + params["pos"][None, :s]
        for lp in params["layers"]:
            a = ln(h, lp["ln1"])
            qkv = a @ lp["qkv"]
            q, k, v = jnp.split(qkv, 3, axis=-1)
            d = q.shape[-1] // heads
            split = lambda t: t.reshape(b, s, heads, d)
            att = ring_flash_attention(split(q), split(k), split(v), mesh,
                                       axis="seq", batch_axis="data",
                                       causal=True,
                                       block_q=block, block_k=block)
            h = h + att.reshape(b, s, -1) @ lp["proj"]
            a = ln(h, lp["ln2"])
            h = h + jax.nn.gelu(a @ lp["fc1"]) @ lp["fc2"]
        return ln(h, params["ln_f"]) @ params["head"]

    def loss_fn(params, tokens, labels):
        logits = forward(params, tokens)
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        nll = -jnp.take_along_axis(logp, labels[..., None], axis=-1)
        return nll.mean()

    @functools.partial(jax.jit, donate_argnums=(0,))
    def step(params, tokens, labels):
        loss, grads = jax.value_and_grad(loss_fn)(params, tokens, labels)
        new = jax.tree.map(lambda p, g: p - lr * g.astype(p.dtype),
                           params, grads)
        return new, loss

    return step


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--vocab-size", type=int, default=256)
    ap.add_argument("--hidden", type=int, default=128)
    ap.add_argument("--heads", type=int, default=4)
    ap.add_argument("--layers", type=int, default=2)
    ap.add_argument("--seq-len", type=int, default=1024)
    ap.add_argument("--batch", type=int, default=2)
    ap.add_argument("--seq-shards", type=int, default=4)
    ap.add_argument("--block", type=int, default=128)
    ap.add_argument("--steps", type=int, default=5)
    ap.add_argument("--lr", type=float, default=0.05)
    args = ap.parse_args(argv)

    if os.environ.get("JAX_PLATFORMS", "").strip() == "cpu":
        # some images pin jax_platforms to a tunneled accelerator over the
        # env var; honor an explicit cpu request via the config
        import jax

        jax.config.update("jax_platforms", "cpu")
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    devs = jax.devices()
    n_seq = args.seq_shards
    if len(devs) < n_seq:
        raise SystemExit(
            "need %d devices for --seq-shards %d, found %d (set XLA_FLAGS="
            "--xla_force_host_platform_device_count=%d for a virtual mesh)"
            % (n_seq, n_seq, len(devs), n_seq))
    n_data = len(devs) // n_seq
    mesh = Mesh(np.array(devs[:n_data * n_seq]).reshape(n_data, n_seq),
                ("data", "seq"))
    rng = np.random.RandomState(0)
    params = build_params(rng, args.vocab_size, args.hidden, args.heads,
                          args.layers, args.seq_len)
    # deterministic task (+1 mod vocab) so the loss visibly falls
    X = rng.randint(0, args.vocab_size,
                    size=(args.batch * n_data, args.seq_len))
    Y = (X + 1) % args.vocab_size
    data_sh = NamedSharding(mesh, P("data", "seq"))
    tokens = jax.device_put(jnp.asarray(X, jnp.int32), data_sh)
    labels = jax.device_put(jnp.asarray(Y, jnp.int32), data_sh)
    params = jax.device_put(params, NamedSharding(mesh, P()))

    step = make_step(mesh, args.heads, args.block, args.lr)
    losses = []
    t0 = time.time()
    for i in range(args.steps):
        params, loss = step(params, tokens, labels)
        losses.append(float(loss))
        print("step %d loss %.4f" % (i, losses[-1]), flush=True)
    dt = time.time() - t0
    toks = args.batch * n_data * args.seq_len * args.steps
    print("tokens/s %.1f  first->last loss %.4f -> %.4f"
          % (toks / dt, losses[0], losses[-1]))
    return losses


if __name__ == "__main__":
    main()

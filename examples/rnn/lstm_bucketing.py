#!/usr/bin/env python
"""LSTM language model with bucketing (reference:
example/rnn/lstm_bucketing.py — BASELINE config #3, PTB).

Reads PTB-format text from --data-dir when present; otherwise generates a
synthetic Markov-chain corpus so the example runs without downloads.
Perplexity must fall epoch over epoch."""
from __future__ import annotations

import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import mxnet_tpu as mx  # noqa: E402


def tokenize_text(fname, vocab=None, invalid_label=-1, start_label=0):
    with open(fname) as f:
        lines = f.readlines()
    sentences = [line.split() for line in lines]
    if vocab is None:
        vocab = {}
    out = []
    for s in sentences:
        ids = []
        for w in s:
            if w not in vocab:
                vocab[w] = len(vocab) + start_label
            ids.append(vocab[w])
        if ids:
            out.append(ids)
    return out, vocab


def synthetic_corpus(vocab_size=64, n_sentences=1500, seed=0):
    """Markov chain with a sparse transition matrix — learnable structure."""
    rng = np.random.RandomState(seed)
    trans = np.zeros((vocab_size, vocab_size))
    for i in range(vocab_size):
        nxt = rng.choice(vocab_size, size=4, replace=False)
        trans[i, nxt] = rng.dirichlet(np.ones(4))
    sents = []
    for _ in range(n_sentences):
        length = rng.randint(8, 33)
        s = [rng.randint(vocab_size)]
        for _ in range(length - 1):
            s.append(rng.choice(vocab_size, p=trans[s[-1]]))
        sents.append(s)
    return sents


def main():
    ap = argparse.ArgumentParser(description="lstm bucketing LM")
    ap.add_argument("--data-dir", type=str, default="ptb_data")
    ap.add_argument("--num-layers", type=int, default=2)
    ap.add_argument("--num-hidden", type=int, default=64)
    ap.add_argument("--num-embed", type=int, default=64)
    ap.add_argument("--batch-size", type=int, default=16)
    ap.add_argument("--num-epochs", type=int, default=5)
    ap.add_argument("--lr", type=float, default=0.05)
    ap.add_argument("--kv-store", type=str, default="local")
    ap.add_argument("--disp-batches", type=int, default=20)
    args = ap.parse_args()

    train_path = os.path.join(args.data_dir, "ptb.train.txt")
    if os.path.exists(train_path):
        train_sent, vocab = tokenize_text(train_path, start_label=1)
        val_sent, _ = tokenize_text(
            os.path.join(args.data_dir, "ptb.valid.txt"), vocab=vocab,
            start_label=1)
        vocab_size = len(vocab) + 1
    else:
        vocab_size = 64
        sents = synthetic_corpus(vocab_size)
        train_sent, val_sent = sents[150:], sents[:150]

    buckets = [8, 16, 24, 32]
    train = mx.rnn.BucketSentenceIter(train_sent, args.batch_size,
                                      buckets=buckets, invalid_label=0)
    val = mx.rnn.BucketSentenceIter(val_sent, args.batch_size,
                                    buckets=buckets, invalid_label=0)

    stack = mx.rnn.SequentialRNNCell()
    for i in range(args.num_layers):
        stack.add(mx.rnn.LSTMCell(num_hidden=args.num_hidden,
                                  prefix="lstm_l%d_" % i))

    def sym_gen(seq_len):
        data = mx.sym.Variable("data")
        label = mx.sym.Variable("softmax_label")
        embed = mx.sym.Embedding(data=data, input_dim=vocab_size,
                                 output_dim=args.num_embed, name="embed")
        stack.reset()
        outputs, states = stack.unroll(seq_len, inputs=embed,
                                       merge_outputs=True)
        pred = mx.sym.Reshape(outputs, shape=(-1, args.num_hidden))
        pred = mx.sym.FullyConnected(data=pred, num_hidden=vocab_size,
                                     name="pred")
        label = mx.sym.Reshape(label, shape=(-1,))
        pred = mx.sym.SoftmaxOutput(data=pred, label=label, name="softmax")
        return pred, ("data",), ("softmax_label",)

    mod = mx.mod.BucketingModule(sym_gen,
                                 default_bucket_key=train.default_bucket_key,
                                 context=mx.current_context())
    import logging

    logging.basicConfig(level=logging.INFO,
                        format="%(asctime)-15s %(message)s")
    mod.fit(train, eval_data=val,
            eval_metric=mx.metric.Perplexity(ignore_label=0),
            kvstore=args.kv_store, optimizer="sgd",
            optimizer_params={"learning_rate": args.lr, "momentum": 0.0,
                              "wd": 1e-5},
            initializer=mx.init.Xavier(factor_type="in", magnitude=2.34),
            num_epoch=args.num_epochs,
            batch_end_callback=mx.callback.Speedometer(
                args.batch_size, args.disp_batches, auto_reset=False))


if __name__ == "__main__":
    main()

#!/usr/bin/env python
"""Inference throughput benchmark (reference:
example/image-classification/benchmark_score.py): forward-only img/s for
the model zoo across batch sizes."""
from __future__ import annotations

import argparse
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import mxnet_tpu as mx  # noqa: E402


def score(network, batch_size, image_shape, num_classes, num_batches=20):
    if network.startswith("resnet"):
        num_layers = int(network.split("-")[1]) if "-" in network else 50
        sym = mx.models.get_resnet(num_classes=num_classes,
                                   num_layers=num_layers,
                                   image_shape=image_shape)
    elif network == "alexnet":
        sym = mx.models.get_alexnet(num_classes=num_classes)
    elif network in ("inception-v3", "inception_v3"):
        sym = mx.models.get_inception_v3(num_classes=num_classes)
    elif network.startswith("inception"):
        sym = mx.models.get_inception_bn(num_classes=num_classes)
    elif network == "lenet":
        sym = mx.models.get_lenet(num_classes=num_classes)
    else:
        raise ValueError(network)
    data_shape = (batch_size,) + tuple(image_shape)
    exe = sym.simple_bind(mx.current_context(), data=data_shape,
                          grad_req="null")
    rng = np.random.RandomState(0)
    for name, arr in exe.arg_dict.items():
        if name in ("data", "softmax_label"):
            continue
        arr[:] = rng.uniform(-0.05, 0.05, arr.shape).astype(np.float32)
    exe.arg_dict["data"][:] = rng.uniform(0, 1, data_shape).astype(np.float32)
    # warmup (compile)
    out = exe.forward(is_train=False)[0]
    out.wait_to_read()
    t0 = time.time()
    for _ in range(num_batches):
        out = exe.forward(is_train=False)[0]
    out.wait_to_read()
    dt = time.time() - t0
    return num_batches * batch_size / dt


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--networks", default="lenet,resnet-18,alexnet")
    ap.add_argument("--image-shape", default="3,224,224")
    ap.add_argument("--num-classes", type=int, default=1000)
    ap.add_argument("--batch-sizes", default="1,32")
    args = ap.parse_args()
    shape = tuple(int(x) for x in args.image_shape.split(","))
    for net in args.networks.split(","):
        ishape = (1, 28, 28) if net == "lenet" else shape
        ncls = 10 if net == "lenet" else args.num_classes
        for bs in (int(b) for b in args.batch_sizes.split(",")):
            ips = score(net, bs, ishape, ncls)
            print("network: %-12s batch: %-3d  %.1f img/s" % (net, bs, ips))


if __name__ == "__main__":
    main()

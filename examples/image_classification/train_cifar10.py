#!/usr/bin/env python
"""Train ResNet on CIFAR-10 RecordIO packs (reference:
example/image-classification/train_cifar10.py). Falls back to --benchmark
synthetic mode without --data-train."""
from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import mxnet_tpu as mx  # noqa: E402
from examples.image_classification.common import fit  # noqa: E402
from examples.image_classification.train_imagenet import (  # noqa: E402
    get_network, get_rec_iter)


def main():
    parser = argparse.ArgumentParser(description="train cifar10")
    fit.add_fit_args(parser)
    parser.add_argument("--data-train", type=str, default=None)
    parser.add_argument("--data-val", type=str, default=None)
    parser.add_argument("--data-nthreads", type=int, default=4)
    parser.set_defaults(network="resnet-18", num_classes=10,
                        image_shape="3,32,32", num_examples=50000,
                        lr=0.05, lr_step_epochs="200,250", batch_size=128)
    args = parser.parse_args()
    if not args.data_train:
        args.benchmark = 1
    net = get_network(args)
    fit.fit(args, net, get_rec_iter)


if __name__ == "__main__":
    main()

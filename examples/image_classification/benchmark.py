"""Training-throughput sweep across networks and batch sizes.

Capability parity with the reference's sweep harness
(/root/reference/example/image-classification/benchmark.py), redesigned
for the mesh world: instead of re-invoking train_imagenet.py over ssh for
each gpu count, each config runs the fused Module train step in-process
(synthetic data, the same path as ``train_imagenet.py --benchmark 1``)
and the result is one JSON line per config.

Usage:
  python benchmark.py --networks resnet-50:256:224 alexnet:512:224 \
      [--dtype bfloat16] [--num-steps 30]
"""
import argparse
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

from examples.image_classification.common import fit  # noqa: E402
from examples.image_classification.train_imagenet import get_network  # noqa: E402


def parse_args():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--networks", nargs="+",
                   default=["resnet-50:256:224", "inception-bn:256:224",
                            "alexnet:512:224"],
                   help="configs as network:batch_size:image_size")
    p.add_argument("--num-classes", type=int, default=1000)
    p.add_argument("--dtype", default="bfloat16",
                   choices=["float32", "bfloat16"])
    p.add_argument("--num-steps", type=int, default=30)
    p.add_argument("--warmup", type=int, default=5)
    p.add_argument("--kv-store", default="local")
    return p.parse_args()


def run_config(spec, cli):
    name, batch, size = spec.split(":")
    parser = argparse.ArgumentParser()
    fit.add_fit_args(parser)
    args = parser.parse_args([
        "--network", name, "--num-classes", str(cli.num_classes),
        "--image-shape", "3,%s,%s" % (size, size),
        "--batch-size", batch, "--dtype", cli.dtype,
        "--kv-store", cli.kv_store, "--benchmark", "1"])
    net = get_network(args)
    stats = fit.benchmark(args, net, num_steps=cli.num_steps,
                          warmup=cli.warmup)
    return {"network": name, "batch_size": int(batch),
            "image_size": int(size), "dtype": cli.dtype,
            "img_per_sec": round(stats["img_per_sec"], 2),
            "step_time_ms": round(stats["step_time_ms"], 2)}


def main():
    cli = parse_args()
    for spec in cli.networks:
        # SystemExit included: a malformed numeric field makes the inner
        # argparse sys.exit, which must not abort the remaining sweep
        try:
            print(json.dumps(run_config(spec, cli)), flush=True)
        except (Exception, SystemExit) as e:
            print(json.dumps({"network": spec, "error": str(e)[:300]}),
                  flush=True)


if __name__ == "__main__":
    main()

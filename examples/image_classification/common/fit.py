"""Shared training driver for the image-classification examples
(reference: example/image-classification/common/fit.py — kvstore creation,
checkpoint/resume, LR schedule, Speedometer, --benchmark synthetic mode)."""
from __future__ import annotations

import argparse
import logging
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", "..", ".."))

import mxnet_tpu as mx  # noqa: E402


def add_fit_args(parser: argparse.ArgumentParser):
    parser.add_argument("--network", type=str, default="lenet")
    parser.add_argument("--batch-size", type=int, default=128)
    parser.add_argument("--num-epochs", type=int, default=10)
    parser.add_argument("--lr", type=float, default=0.1)
    parser.add_argument("--lr-factor", type=float, default=0.1)
    parser.add_argument("--lr-step-epochs", type=str, default="")
    parser.add_argument("--momentum", type=float, default=0.9)
    parser.add_argument("--wd", type=float, default=1e-4)
    parser.add_argument("--optimizer", type=str, default="sgd")
    parser.add_argument("--kv-store", type=str, default="local")
    parser.add_argument("--model-prefix", type=str, default=None)
    parser.add_argument("--load-epoch", type=int, default=None)
    parser.add_argument("--disp-batches", type=int, default=20)
    parser.add_argument("--benchmark", type=int, default=0,
                        help="1 = synthetic data, report img/s only")
    parser.add_argument("--test-io", type=int, default=0,
                        help="1 = run the data iterator alone and report "
                             "IO img/s (reference fit.py:106-116)")
    parser.add_argument("--num-examples", type=int, default=60000)
    parser.add_argument("--num-classes", type=int, default=10)
    parser.add_argument("--image-shape", type=str, default="1,28,28")
    parser.add_argument("--dtype", type=str, default="float32")
    return parser


class SyntheticIter(mx.io.DataIter):
    """--benchmark 1 data source (reference fit.py:106-116): random batch
    repeated, no host pipeline in the loop."""

    def __init__(self, data_shape, label_range, batch_size, num_batches=50):
        super().__init__(batch_size)
        rng = np.random.RandomState(0)
        self._data = mx.nd.array(
            rng.uniform(-1, 1, (batch_size,) + data_shape).astype(np.float32))
        self._label = mx.nd.array(
            rng.randint(0, label_range, (batch_size,)).astype(np.float32))
        self.num_batches = num_batches
        self._cur = 0
        self.provide_data = [mx.io.DataDesc("data",
                                            (batch_size,) + data_shape)]
        self.provide_label = [mx.io.DataDesc("softmax_label", (batch_size,))]

    def reset(self):
        self._cur = 0

    def next(self):
        if self._cur >= self.num_batches:
            raise StopIteration
        self._cur += 1
        return mx.io.DataBatch(data=[self._data], label=[self._label], pad=0)


def _lr_scheduler(args, kv, epoch_size):
    if not args.lr_step_epochs:
        return None
    steps = [int(e) for e in args.lr_step_epochs.split(",") if e]
    begin = args.load_epoch or 0
    steps = [epoch_size * (s - begin) for s in steps
             if epoch_size * (s - begin) > 0]
    if not steps:
        return None
    return mx.lr_scheduler.MultiFactorScheduler(step=steps,
                                                factor=args.lr_factor)


def _compute_dtype(args):
    return args.dtype if args.dtype not in ("float32", None) else None


def benchmark(args, network, num_steps=30, warmup=5):
    """--benchmark mode through the REAL Module path (bind / init_optimizer /
    forward_backward / update / update_metric — the same statements
    BaseModule.fit runs), timing steady-state steps with compile excluded.
    Returns a stats dict; reference equivalent: common/fit.py:106-116
    synthetic-data mode."""
    shape = tuple(int(x) for x in args.image_shape.split(","))
    train = SyntheticIter(shape, args.num_classes, args.batch_size,
                          num_batches=num_steps + warmup)
    mod = mx.mod.Module(network, context=mx.current_context(),
                        compute_dtype=_compute_dtype(args))
    mod.bind(data_shapes=train.provide_data,
             label_shapes=train.provide_label, for_training=True)
    mod.init_params(initializer=mx.init.Xavier(factor_type="in",
                                               magnitude=2.34))
    opt_params = {"learning_rate": args.lr, "wd": args.wd,
                  "rescale_grad": 1.0 / args.batch_size}
    if args.optimizer in ("sgd", "nag"):
        opt_params["momentum"] = args.momentum
    mod.init_optimizer(kvstore=args.kv_store, optimizer=args.optimizer,
                       optimizer_params=opt_params)
    metric = mx.metric.Accuracy()
    batch = train.next()

    def step():
        mod.forward_backward(batch)
        mod.update()
        mod.update_metric(metric, batch.label)

    def sync():
        # pull one small param: its value depends on every prior update, so
        # this bounds the whole async chain
        name = mod._exec_group.param_names[-1]
        return mod._exec_group.execs[0].arg_dict[name].asnumpy()

    for _ in range(warmup):
        step()
    sync()
    t0 = time.time()
    for _ in range(num_steps):
        step()
    sync()
    dt = time.time() - t0
    final_param = sync()
    acc = metric.get()[1]
    return {"img_per_sec": args.batch_size * num_steps / dt,
            "step_time_ms": 1000.0 * dt / num_steps,
            "batch_size": args.batch_size, "dtype": args.dtype,
            "accuracy": acc,
            "finite": bool(np.all(np.isfinite(final_param)))}


def fit(args, network, data_loader):
    """args: parsed CLI; network: Symbol; data_loader(args, kv) ->
    (train_iter, val_iter_or_None)."""
    logging.basicConfig(level=logging.INFO,
                        format="%(asctime)-15s %(message)s")
    if args.benchmark:
        stats = benchmark(args, network)
        print('{"metric": "img_per_sec", "value": %.2f}'
              % stats["img_per_sec"])
        return stats
    kv = mx.kvstore.create(args.kv_store)
    train, val = data_loader(args, kv)

    if getattr(args, "test_io", 0):
        # IO-only throughput: drain the train iterator, no compute in the
        # loop (reference common/fit.py:106-116, the --test-io mode used to
        # prove the decode pipeline can feed the chip)
        tic = time.time()
        n = 0
        for epoch in range(args.num_epochs):
            train.reset()
            for batch in train:
                batch.data[0].wait_to_read()
                n += args.batch_size
                if n % (args.batch_size * args.disp_batches) == 0:
                    logging.info("io-test %d samples, %.1f img/s", n,
                                 n / (time.time() - tic))
        dt = time.time() - tic
        stats = {"io_img_per_sec": n / dt, "samples": n}
        print('{"metric": "io_img_per_sec", "value": %.2f}'
              % stats["io_img_per_sec"])
        return stats

    arg_params = aux_params = None
    begin_epoch = 0
    if args.model_prefix and args.load_epoch is not None:
        network, arg_params, aux_params = mx.model.load_checkpoint(
            args.model_prefix, args.load_epoch)
        begin_epoch = args.load_epoch
        logging.info("resumed from %s epoch %d", args.model_prefix,
                     args.load_epoch)

    epoch_size = max(1, args.num_examples // args.batch_size)
    mod = mx.mod.Module(network, context=mx.current_context(),
                        compute_dtype=_compute_dtype(args))
    batch_end = [mx.callback.Speedometer(args.batch_size,
                                         args.disp_batches)]
    epoch_end = []
    if args.model_prefix:
        epoch_end.append(mx.callback.do_checkpoint(args.model_prefix))
    opt_params = {"learning_rate": args.lr, "wd": args.wd}
    if args.optimizer in ("sgd", "nag"):
        opt_params["momentum"] = args.momentum
    sched = _lr_scheduler(args, kv, epoch_size)
    if sched is not None:
        opt_params["lr_scheduler"] = sched

    mod.fit(train, eval_data=val, num_epoch=args.num_epochs,
            begin_epoch=begin_epoch, arg_params=arg_params,
            aux_params=aux_params, optimizer=args.optimizer,
            optimizer_params=opt_params, kvstore=kv,
            eval_metric=mx.metric.Accuracy(),
            batch_end_callback=batch_end, epoch_end_callback=epoch_end,
            initializer=mx.init.Xavier(factor_type="in", magnitude=2.34))
    return mod

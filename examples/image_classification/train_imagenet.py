#!/usr/bin/env python
"""Train ResNet/Inception/AlexNet on ImageNet RecordIO packs (reference:
example/image-classification/train_imagenet.py — BASELINE config #2).

With --benchmark 1 (default when no --data-train) runs on synthetic data
and reports img/s — the reference's fit.py:106-116 mode used for the
headline throughput numbers (docs/how_to/perf.md:130-139)."""
from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import mxnet_tpu as mx  # noqa: E402
from examples.image_classification.common import fit  # noqa: E402


def get_rec_iter(args, kv):
    shape = tuple(int(x) for x in args.image_shape.split(","))
    train = mx.io.ImageRecordIter(
        path_imgrec=args.data_train, data_shape=shape,
        batch_size=args.batch_size, shuffle=True, rand_crop=True,
        rand_mirror=True, mean_r=123.68, mean_g=116.779, mean_b=103.939,
        part_index=kv.rank, num_parts=kv.num_workers,
        preprocess_threads=args.data_nthreads)
    val = None
    if args.data_val:
        val = mx.io.ImageRecordIter(
            path_imgrec=args.data_val, data_shape=shape,
            batch_size=args.batch_size, shuffle=False,
            mean_r=123.68, mean_g=116.779, mean_b=103.939,
            preprocess_threads=args.data_nthreads)
    return train, val


def get_network(args):
    shape = tuple(int(x) for x in args.image_shape.split(","))
    name = args.network
    if name.startswith("resnet"):
        num_layers = int(name[len("resnet-"):]) if "-" in name else 50
        return mx.models.get_resnet(num_classes=args.num_classes,
                                    num_layers=num_layers, image_shape=shape)
    if name == "alexnet":
        return mx.models.get_alexnet(num_classes=args.num_classes)
    if name in ("inception-v3", "inception_v3"):
        return mx.models.get_inception_v3(num_classes=args.num_classes)
    if name.startswith("inception"):
        return mx.models.get_inception_bn(num_classes=args.num_classes)
    if name.startswith("vgg"):
        import re as _re

        m = _re.fullmatch(r"vgg-?(\d+)?", name)
        if m is None:
            raise ValueError("cannot parse vgg depth from %r" % name)
        num_layers = int(m.group(1)) if m.group(1) else 16
        return mx.models.get_vgg(num_classes=args.num_classes,
                                 num_layers=num_layers)
    if name == "googlenet":
        return mx.models.get_googlenet(num_classes=args.num_classes)
    if name == "lenet":
        return mx.models.get_lenet(num_classes=args.num_classes)
    if name == "mlp":
        return mx.models.get_mlp(num_classes=args.num_classes)
    raise ValueError("unknown network %s" % name)


def main():
    parser = argparse.ArgumentParser(description="train imagenet")
    fit.add_fit_args(parser)
    parser.add_argument("--data-train", type=str, default=None)
    parser.add_argument("--data-val", type=str, default=None)
    parser.add_argument("--data-nthreads", type=int, default=4)
    parser.set_defaults(network="resnet-50", num_classes=1000,
                        image_shape="3,224,224", num_examples=1281167,
                        lr=0.1, lr_step_epochs="30,60,80", batch_size=32)
    args = parser.parse_args()
    if not args.data_train:
        args.benchmark = 1
    net = get_network(args)
    fit.fit(args, net, get_rec_iter)


if __name__ == "__main__":
    main()

#!/usr/bin/env python
"""Train LeNet/MLP on MNIST (reference:
example/image-classification/train_mnist.py — BASELINE config #1).

Reads idx-ubyte MNIST files from --data-dir when present; otherwise trains
on a generated MNIST-like synthetic digit set so the example runs in
closed environments (accuracy gate still meaningful: the synthetic digits
are linearly inseparable renderings of 10 template classes + noise)."""
from __future__ import annotations

import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import mxnet_tpu as mx  # noqa: E402
from examples.image_classification.common import fit  # noqa: E402


def synthetic_mnist(n=6000, seed=0):
    """10 random 28x28 class templates + per-sample noise and shifts."""
    rng = np.random.RandomState(seed)
    templates = rng.uniform(0, 1, (10, 28, 28)).astype(np.float32)
    labels = rng.randint(0, 10, n)
    imgs = templates[labels]
    shifts = rng.randint(-2, 3, (n, 2))
    out = np.empty_like(imgs)
    for i in range(n):
        out[i] = np.roll(imgs[i], tuple(shifts[i]), axis=(0, 1))
    out += rng.normal(0, 0.3, out.shape).astype(np.float32)
    return out[:, None], labels.astype(np.float32)


def get_mnist_iter(args, kv):
    data_dir = getattr(args, "data_dir", None) or ""
    train_img = os.path.join(data_dir, "train-images-idx3-ubyte")
    flat = args.network == "mlp"
    if data_dir and os.path.exists(train_img):
        train = mx.io.MNISTIter(
            image=train_img,
            label=os.path.join(data_dir, "train-labels-idx1-ubyte"),
            batch_size=args.batch_size, shuffle=True, flat=flat,
            part_index=kv.rank, num_parts=kv.num_workers)
        val = mx.io.MNISTIter(
            image=os.path.join(data_dir, "t10k-images-idx3-ubyte"),
            label=os.path.join(data_dir, "t10k-labels-idx1-ubyte"),
            batch_size=args.batch_size, shuffle=False, flat=flat)
        return train, val
    x, y = synthetic_mnist(args.num_examples)
    if flat:
        x = x.reshape(len(x), -1)
    n_val = len(x) // 6
    train = mx.io.NDArrayIter(x[n_val:], y[n_val:], args.batch_size,
                              shuffle=True)
    val = mx.io.NDArrayIter(x[:n_val], y[:n_val], args.batch_size)
    return train, val


def main():
    parser = argparse.ArgumentParser(description="train mnist")
    fit.add_fit_args(parser)
    parser.add_argument("--data-dir", type=str, default="mnist_data")
    parser.set_defaults(network="lenet", num_examples=6000, num_epochs=5,
                        lr=0.05, batch_size=64, image_shape="1,28,28")
    args = parser.parse_args()
    if args.network == "mlp":
        net = mx.models.get_mlp(num_classes=args.num_classes)
    else:
        net = mx.models.get_lenet(num_classes=args.num_classes)
    fit.fit(args, net, get_mnist_iter)


if __name__ == "__main__":
    main()

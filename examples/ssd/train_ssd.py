#!/usr/bin/env python
"""Train SSD on RecordIO detection packs or synthetic boxes (reference:
example/ssd/train.py — BASELINE config #4). Without --data-train, trains on
generated single-object images; the cls+loc loss must fall."""
from __future__ import annotations

import argparse
import logging
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import mxnet_tpu as mx  # noqa: E402


def synthetic_detection(n, size=64, num_classes=2, seed=0):
    rng = np.random.RandomState(seed)
    data = np.zeros((n, 3, size, size), np.float32)
    label = np.full((n, 4, 5), -1.0, np.float32)
    for i in range(n):
        s = rng.randint(size // 4, size // 2)
        x0 = rng.randint(0, size - s)
        y0 = rng.randint(0, size - s)
        cls = rng.randint(0, num_classes)
        data[i, cls % 3, y0:y0 + s, x0:x0 + s] = 1.0
        label[i, 0] = [cls, x0 / size, y0 / size, (x0 + s) / size,
                       (y0 + s) / size]
    return data, label


def main():
    ap = argparse.ArgumentParser(description="train ssd")
    ap.add_argument("--batch-size", type=int, default=8)
    ap.add_argument("--num-epochs", type=int, default=5)
    ap.add_argument("--lr", type=float, default=0.2)
    ap.add_argument("--momentum", type=float, default=0.9)
    ap.add_argument("--num-classes", type=int, default=2)
    ap.add_argument("--num-examples", type=int, default=64)
    ap.add_argument("--model-prefix", type=str, default=None)
    ap.add_argument("--data-train", type=str, default=None,
                    help=".rec detection pack (im2rec multi-column list); "
                         "without it, trains on synthetic boxes")
    ap.add_argument("--data-shape", type=int, default=64)
    ap.add_argument("--label-pad-width", type=int, default=8)
    ap.add_argument("--rand-mirror", action="store_true")
    ap.add_argument("--rand-crop", type=float, default=0.0)
    args = ap.parse_args()
    logging.basicConfig(level=logging.INFO,
                        format="%(asctime)-15s %(message)s")

    if args.data_train:
        it = mx.image.ImageDetRecordIter(
            path_imgrec=args.data_train,
            data_shape=(3, args.data_shape, args.data_shape),
            batch_size=args.batch_size,
            label_pad_width=args.label_pad_width,
            rand_mirror=args.rand_mirror, rand_crop=args.rand_crop,
            std_r=255.0, std_g=255.0, std_b=255.0,
            label_name="label")
    else:
        data, label = synthetic_detection(args.num_examples,
                                          num_classes=args.num_classes)
        it = mx.io.NDArrayIter(data=data, label=label,
                               batch_size=args.batch_size,
                               label_name="label")
    net = mx.models.get_ssd_train(num_classes=args.num_classes)
    mod = mx.mod.Module(net, data_names=("data",), label_names=("label",),
                        context=mx.current_context())
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    mod.init_params(mx.init.Xavier())
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": args.lr,
                                         "momentum": args.momentum})
    first = last = None
    for epoch in range(args.num_epochs):
        it.reset()
        tot, nb = 0.0, 0
        for batch in it:
            mod.forward(batch, is_train=True)
            cls_prob, loc_loss, cls_target = (
                o.asnumpy() for o in mod.get_outputs())
            valid = cls_target >= 0
            idx = np.maximum(cls_target.astype(int), 0)
            picked = np.take_along_axis(cls_prob, idx[:, None, :],
                                        axis=1)[:, 0, :]
            ce = -np.log(np.maximum(picked, 1e-8))[valid].mean()
            tot += ce + loc_loss.sum() / max(valid.sum(), 1)
            nb += 1
            mod.backward()
            mod.update()
        avg = tot / nb
        first = first if first is not None else avg
        last = avg
        logging.info("Epoch[%d] cls+loc loss=%.4f", epoch, avg)
    if args.model_prefix:
        mod.save_checkpoint(args.model_prefix, args.num_epochs)
    print('{"metric": "ssd_loss_ratio", "value": %.4f}' % (last / first))


if __name__ == "__main__":
    main()

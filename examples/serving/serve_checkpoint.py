"""End-to-end serving demo: train → checkpoint → batching HTTP service.

Trains a tiny MLP on synthetic data, saves a checkpoint, then serves it
through mx.serving.InferenceServer: concurrent clients hit the HTTP
endpoint, the micro-batcher coalesces them into pre-compiled bucket
batches, and the run finishes by printing the /metrics text (note
batches_total << requests_total).

  python examples/serving/serve_checkpoint.py [--requests 64] [--port 0]
"""
import argparse
import json
import os
import sys
import tempfile
import urllib.request
from concurrent.futures import ThreadPoolExecutor

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))

import numpy as np

import mxnet_tpu as mx

IN_DIM = 16


def train_checkpoint(prefix):
    np.random.seed(0)
    X = np.random.randn(256, IN_DIM).astype(np.float32)
    y = (X[:, 0] + X[:, 1] > 0).astype(np.float32)
    it = mx.io.NDArrayIter(X, y, batch_size=32, shuffle=True)
    net = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(net, num_hidden=32, name="fc1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=2, name="fc2")
    net = mx.sym.SoftmaxOutput(net, name="softmax")
    mod = mx.mod.Module(net, context=mx.cpu())
    mod.fit(it, num_epoch=3, optimizer="sgd",
            optimizer_params={"learning_rate": 0.1})
    mod.save_checkpoint(prefix, 3)
    return X


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--requests", type=int, default=64)
    ap.add_argument("--port", type=int, default=0)
    args = ap.parse_args()

    with tempfile.TemporaryDirectory() as d:
        prefix = os.path.join(d, "mlp")
        X = train_checkpoint(prefix)

        srv = mx.serving.InferenceServer.from_checkpoint(
            prefix, 3, {"data": (16, IN_DIM)}, max_wait_us=5000)
        host, port = srv.serve_http(port=args.port)
        print("serving on http://%s:%d  (buckets=%s)"
              % (host, port, list(srv.buckets)))

        def hit(i):
            body = json.dumps(
                {"inputs": {"data": X[i % len(X)].tolist()}}).encode()
            r = urllib.request.urlopen(urllib.request.Request(
                "http://%s:%d/predict" % (host, port), data=body,
                headers={"Content-Type": "application/json"}), timeout=30)
            return json.loads(r.read())["outputs"][0]

        with ThreadPoolExecutor(max_workers=16) as pool:
            outs = list(pool.map(hit, range(args.requests)))
        probs = np.asarray(outs)
        print("served %d requests, prob sums ~1: %s"
              % (len(outs), np.allclose(probs.sum(axis=1), 1, atol=1e-4)))
        print(urllib.request.urlopen(
            "http://%s:%d/metrics" % (host, port), timeout=10)
            .read().decode())
        srv.stop()


if __name__ == "__main__":
    main()

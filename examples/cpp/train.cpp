// C++-frontend demo: build a graph with Operator/Symbol, bind with
// NDArrays, run forward+backward, take an SGD step imperatively, and
// verify the loss falls — the cpp-package workflow (reference
// cpp-package/example/mlp.cpp) over the mxtpu C ABI.
//
// Build: g++ -O2 -std=c++17 train.cpp -I../../include \
//   -L../../mxnet_tpu -lmxtpu_capi -Wl,-rpath,... (see
//   tests/test_c_api.py::test_cpp_frontend)
#include <cmath>
#include <cstdio>
#include <vector>

#include "mxtpu/cpp_api.hpp"

using namespace mxtpu;

int main() {
  RandomSeed(0);

  // y = relu(x W1^T) W2^T ; L2 loss against a fixed target
  auto x = Symbol::Variable("x");
  auto fc1 = Operator("FullyConnected")
                 .SetParam("num_hidden", 8)
                 .SetParam("no_bias", "True")
                 .SetInput("data", x)
                 .CreateSymbol("fc1");
  auto act = Operator("Activation")
                 .SetParam("act_type", "relu")
                 .SetInput("data", fc1)
                 .CreateSymbol("relu1");
  auto fc2 = Operator("FullyConnected")
                 .SetParam("num_hidden", 1)
                 .SetParam("no_bias", "True")
                 .SetInput("data", act)
                 .CreateSymbol("fc2");
  auto target = Symbol::Variable("target");
  auto loss = Operator("LinearRegressionOutput")
                  .SetInput("data", fc2)
                  .SetInput("label", target)
                  .CreateSymbol("loss");

  auto args = loss.ListArguments();  // x, fc1_weight, fc2_weight, target
  if (args.size() != 4) {
    std::fprintf(stderr, "unexpected args: %zu\n", args.size());
    return 1;
  }

  const int B = 16, D = 4;
  std::vector<float> xs(B * D), ys(B);
  for (int i = 0; i < B; ++i) {
    float s = 0;
    for (int j = 0; j < D; ++j) {
      xs[i * D + j] = 0.1f * ((i * D + j) % 7 - 3);
      s += xs[i * D + j];
    }
    ys[i] = s;  // learn a linear map
  }
  std::vector<float> w1(8 * D), w2(8);
  // int index: (i % 11) - 5 must not underflow unsigned
  for (int i = 0; i < static_cast<int>(w1.size()); ++i)
    w1[i] = 0.05f * ((i % 11) - 5);
  for (int i = 0; i < static_cast<int>(w2.size()); ++i)
    w2[i] = 0.05f * ((i % 7) - 3);

  auto ctx = Context::Cpu();
  std::vector<NDArray> arg_arrays = {
      NDArray::FromData(xs, {B, D}, ctx),
      NDArray::FromData(w1, {8, D}, ctx),
      NDArray::FromData(w2, {1, 8}, ctx),
      NDArray::FromData(ys, {B, 1}, ctx)};
  std::vector<NDArray> grads = {
      NDArray({B, D}, ctx), NDArray({8, D}, ctx), NDArray({1, 8}, ctx),
      NDArray({B, 1}, ctx)};
  std::vector<mx_uint> reqs = {0, 1, 1, 0};  // grads for weights only

  Executor exec(loss, ctx, arg_arrays, grads, reqs);

  auto mse = [&](const std::vector<float>& pred) {
    double e = 0;
    for (int i = 0; i < B; ++i)
      e += (pred[i] - ys[i]) * (pred[i] - ys[i]);
    return e / B;
  };

  double first = -1, last = -1;
  for (int step = 0; step < 80; ++step) {
    exec.Forward(true);
    auto out = exec.Outputs()[0].ToVector();
    double l = mse(out);
    if (step == 0) first = l;
    last = l;
    exec.Backward();
    for (int w = 1; w <= 2; ++w) {  // sgd_update in place, imperatively
      auto upd = Operator("sgd_update")
                     .SetParam("lr", 0.1f)
                     .SetInput("grad", grads[w])   // deliberately out of
                     .SetInput("weight", arg_arrays[w])  // declared order:
                     .Invoke();  // Invoke reorders by MXTPUListOpInputs
      upd[0].CopyTo(arg_arrays[w]);
    }
  }
  std::printf("first=%.5f last=%.5f\n", first, last);
  if (!(last < first * 0.2) || !std::isfinite(last)) {
    std::fprintf(stderr, "loss did not fall: %.5f -> %.5f\n", first, last);
    return 2;
  }
  // the graph round-trips through JSON from C++ too
  auto again = Symbol::FromJSON(loss.ToJSON());
  if (again.ListArguments() != args) {
    std::fprintf(stderr, "JSON round-trip changed arguments\n");
    return 3;
  }
  std::printf("cpp frontend ok (%s)\n", Version().c_str());
  return 0;
}

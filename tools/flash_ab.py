"""A/B the round-5 flash-kernel changes on hardware.

Round 4 measured 47.9 TFLOP/s at (bq=512, bk=1024) BEFORE the exp2 +
dimension_semantics commit; the round-5 checklist measured 12.6 at the
same blocks AFTER it. This sweeps the 2x2 variant grid through the same
run_bench harness to attribute the regression.

Results stream to stdout AND to flash_ab.jsonl under the telemetry
artifact dir (MXNET_TELEMETRY_DUMP_DIR) — never the working tree.

Usage: python tools/flash_ab.py [--seq 8192] [--steps 10]
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from artifact_io import tee_line  # noqa: E402


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--seq", type=int, default=8192)
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--blocks", default="512x1024,1024x512,512x512")
    cli = ap.parse_args()

    from bench_attention import run_bench
    from deadline import deadline

    blocks = [tuple(int(x) for x in bl.split("x"))
              for bl in cli.blocks.split(",")]
    for exp2 in ("1", "0"):
        for dimsem in ("1", "0"):
            os.environ["MXTPU_FLASH_EXP2"] = exp2
            os.environ["MXTPU_FLASH_DIMSEM"] = dimsem
            for bq, bk in blocks:
                try:
                    with deadline(600):
                        r = run_bench(seq=cli.seq, steps=cli.steps,
                                      block_q=bq, block_k=bk)
                    tee_line("flash_ab.jsonl",
                             {"exp2": exp2, "dimsem": dimsem,
                              "bq": bq, "bk": bk, "tflops": r["value"],
                              "step_ms": r["step_ms"], "mfu": r["mfu"]})
                except Exception as e:
                    tee_line("flash_ab.jsonl",
                             {"exp2": exp2, "dimsem": dimsem,
                              "bq": bq, "bk": bk,
                              "error": str(e)[:160]})


if __name__ == "__main__":
    main()

"""Admin CLI for the persistent compile cache (MXNET_COMPILE_CACHE_DIR).

Subcommands (all read the cache dir from --dir or the env var):

  ls      one line per entry: digest, kind, size, age, compile-ms it
          saved, and whether it is loadable in THIS environment.
          Kinds: "fwd" (scoring/bucket executors), "gen-prefill" /
          "gen-step" (DecodeEngine prompt-prefill and per-lane-bucket
          decode-step executables), "corrupt" (failed verify)
  verify  CRC + header + payload check per entry; exit 1 if any fail
  prune   delete oldest entries until the directory fits the size budget
          (--max-mb or MXNET_COMPILE_CACHE_MAX_MB)

Usage:
  python tools/compile_cache_admin.py ls [--dir D] [--json]
  python tools/compile_cache_admin.py verify [--dir D] [--json]
  python tools/compile_cache_admin.py prune [--dir D] [--max-mb N] [--json]
"""
import argparse
import json
import os
import sys
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)


def _dir_from(cli):
    d = cli.dir or os.environ.get("MXNET_COMPILE_CACHE_DIR", "")
    if not d:
        sys.exit("no cache dir: pass --dir or set MXNET_COMPILE_CACHE_DIR")
    return d


def cmd_ls(cli):
    from mxnet_tpu import compile_cache as cc

    entries = cc.ls_entries(_dir_from(cli))
    if cli.json:
        print(json.dumps(entries, default=str))
        return 0
    total = 0
    now = time.time()
    for e in entries:
        total += e["bytes"]
        age = now - e["mtime"]
        print("%s  %-7s %9.1fKB  %6.0fs old  compile %sms  %s"
              % (e["digest"], e.get("kind") or "?", e["bytes"] / 1024.0,
                 age, e.get("compile_ms", "?"),
                 "ok" if e.get("env_ok") else
                 ("CORRUPT" if e.get("kind") == "corrupt" else "stale-env")))
    print("%d entries, %.1f MB" % (len(entries), total / (1 << 20)))
    return 0


def cmd_verify(cli):
    from mxnet_tpu import compile_cache as cc

    d = _dir_from(cli)
    results = []
    bad = 0
    for e in cc.ls_entries(d):
        ok, detail = cc.verify_entry(e["path"])
        bad += 0 if ok else 1
        results.append({"digest": e["digest"], "ok": ok, "detail": detail})
    if cli.json:
        print(json.dumps({"entries": results, "bad": bad}))
    else:
        for r in results:
            print("%s  %s  %s" % (r["digest"],
                                  "ok " if r["ok"] else "BAD", r["detail"]))
        print("%d/%d entries verify clean"
              % (len(results) - bad, len(results)))
    return 1 if bad else 0


def cmd_prune(cli):
    from mxnet_tpu import compile_cache as cc

    d = _dir_from(cli)
    budget = cli.max_mb if cli.max_mb is not None else int(
        os.environ.get("MXNET_COMPILE_CACHE_MAX_MB", "2048"))
    removed = cc.prune(d, budget)
    left = cc.ls_entries(d)
    out = {"removed": len(removed), "kept": len(left),
           "bytes": sum(e["bytes"] for e in left), "budget_mb": budget}
    if cli.json:
        print(json.dumps(out))
    else:
        print("pruned %(removed)d entries; %(kept)d kept "
              "(%(bytes)d bytes, budget %(budget_mb)d MB)" % out)
    return 0


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("cmd", choices=("ls", "verify", "prune"))
    ap.add_argument("--dir", default=None,
                    help="cache dir (default: $MXNET_COMPILE_CACHE_DIR)")
    ap.add_argument("--max-mb", type=int, default=None,
                    help="prune budget (default: $MXNET_COMPILE_CACHE_MAX_MB)")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable output")
    cli = ap.parse_args(argv)
    return {"ls": cmd_ls, "verify": cmd_verify, "prune": cmd_prune}[cli.cmd](cli)


if __name__ == "__main__":
    sys.exit(main())

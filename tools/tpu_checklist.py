"""One-shot TPU hardware validation: runs every chip-dependent check and
prints one JSON line per item (all also run standalone; this exists so a
recovered/fresh chip can be fully validated in one command).

  1. flash-attention fwd+bwd vs dense oracle (bf16, causal + full)
  2. flash kernel train-step throughput at 8k (the PERF.md ladder)
  3. 16k-token causal train step (the long-sequence claim)
  4. ring_flash_attention causal on a 1-device mesh (traces all switch
     branches under the TPU vma checker)
  5. bench.py headline (ResNet-50 Module path + transformer_lm_mfu
     model-level metric) unless --skip-resnet
  6. upstream splash-attention oracle at --seq (the ceiling our kernel
     chases; --skip-oracle to omit)

After the checklist, run ``python tools/perf_probe.py`` separately for
the XLA cost analysis + bn_fusion classification (it builds its own
Module; keeping it out-of-process avoids doubling HBM residency).

Results stream to stdout AND to checklist.jsonl under the telemetry
artifact dir (MXNET_TELEMETRY_DUMP_DIR) — never the working tree.

Usage: python tools/tpu_checklist.py [--skip-resnet] [--skip-oracle]
"""
import argparse
import os
import sys
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)
sys.path.insert(0, os.path.join(ROOT, "tools"))

from artifact_io import tee_line  # noqa: E402


def report(name, **kw):
    tee_line("checklist.jsonl", {"check": name, **kw})


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--skip-resnet", action="store_true")
    ap.add_argument("--skip-oracle", action="store_true")
    ap.add_argument("--seq", type=int, default=8192)
    cli = ap.parse_args()

    import jax
    import jax.numpy as jnp
    import numpy as np

    assert jax.default_backend() == "tpu", \
        "tpu_checklist needs the TPU backend (got %s)" % jax.default_backend()

    from mxnet_tpu.ops.attention import flash_attention
    from mxnet_tpu.parallel.ring import local_attention

    # 1. kernel correctness vs dense oracle
    b, s, h, d = 2, 1024, 4, 128
    q, k, v = (jax.random.normal(jax.random.PRNGKey(i), (b, s, h, d),
                                 jnp.bfloat16) * 0.2 for i in range(3))
    for causal in (False, True):
        o = flash_attention(q, k, v, causal=causal)
        ref = local_attention(q, k, v, causal=causal)
        err = float(jnp.max(jnp.abs(o.astype(jnp.float32)
                                    - ref.astype(jnp.float32))))
        g = jax.grad(lambda q, k, v: jnp.mean(flash_attention(
            q, k, v, causal=causal).astype(jnp.float32) ** 2),
            argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(lambda q, k, v: jnp.mean(local_attention(
            q, k, v, causal=causal).astype(jnp.float32) ** 2),
            argnums=(0, 1, 2))(q, k, v)
        gerr = max(float(jnp.max(jnp.abs(a.astype(jnp.float32)
                                         - b_.astype(jnp.float32))))
                   for a, b_ in zip(g, gr))
        report("flash_vs_oracle", causal=causal, fwd_maxerr=round(err, 5),
               bwd_maxerr=round(gerr, 5), ok=err < 0.02 and gerr < 0.02)

    # 2. throughput ladder at --seq, swept over block shapes (in-process;
    # the chip belongs to this process)
    sys.path.insert(0, os.path.join(ROOT, "tools"))
    from bench_attention import run_bench
    from deadline import deadline

    best = None
    for bq, bk in ((512, 512), (512, 1024), (1024, 512)):
        try:
            with deadline(900):
                r = run_bench(seq=cli.seq, steps=10, block_q=bq, block_k=bk)
            report("flash_train_bench", block_q=bq, block_k=bk, result=r,
                   ok=True)
            if best is None or r["value"] > best["value"]:
                best = dict(r, block_q=bq, block_k=bk)
        except Exception as e:
            report("flash_train_bench", block_q=bq, block_k=bk, ok=False,
                   error=str(e)[:200])
    if best is not None:
        report("flash_train_best", tflops=best["value"], mfu=best["mfu"],
               block_q=best["block_q"], block_k=best["block_k"], ok=True)

    # 2b. the kernel at MODEL shapes (b=4 h=16 — the grid the 51.4%
    # model-level MFU actually runs; the b=1 h=8 ladder starves the
    # parallel bh dimension and under-reports the kernel)
    try:
        with deadline(900):
            rm = run_bench(batch=4, heads=16, seq=4096, steps=10,
                           block_q=best["block_q"] if best else 512,
                           block_k=best["block_k"] if best else 1024)
        report("flash_train_model_shape", result=rm, ok=True)
    except Exception as e:
        report("flash_train_model_shape", ok=False, error=str(e)[:200])

    # 3. 16k-token causal train step on one chip
    s16 = 16384
    q16 = jax.random.normal(jax.random.PRNGKey(0), (1, s16, 8, 128),
                            jnp.bfloat16) * 0.1
    step = jax.jit(jax.grad(lambda q: jnp.mean(flash_attention(
        q, q, q, causal=True).astype(jnp.float32) ** 2)))
    t0 = time.time()
    g16 = step(q16)
    jax.block_until_ready(g16)
    dt16 = time.time() - t0
    fin16 = bool(jnp.isfinite(g16.astype(jnp.float32)).all())
    report("flash_16k_train_step", first_step_s=round(dt16, 1),
           finite=fin16, ok=fin16)

    # 4. ring-flash causal traces under the TPU vma checker (all lax.switch
    # branches are traced even on a 1-device mesh)
    from jax.sharding import Mesh
    from mxnet_tpu.parallel.ring import ring_flash_attention

    mesh = Mesh(np.array(jax.devices()[:1]), ("seq",))
    qr = jax.random.normal(jax.random.PRNGKey(1), (1, 512, 2, 128),
                           jnp.bfloat16) * 0.2
    out = ring_flash_attention(qr, qr, qr, mesh, axis="seq", causal=True,
                               block_q=128, block_k=128)
    refr = local_attention(qr, qr, qr, causal=True)
    rerr = float(jnp.max(jnp.abs(out.astype(jnp.float32)
                                 - refr.astype(jnp.float32))))
    gring = jax.grad(lambda q: jnp.mean(ring_flash_attention(
        q, q, q, mesh, axis="seq", causal=True, block_q=128,
        block_k=128).astype(jnp.float32) ** 2))(qr)
    jax.block_until_ready(gring)
    gfin = bool(jnp.isfinite(gring.astype(jnp.float32)).all())
    report("ring_flash_tpu_vma", fwd_maxerr=round(rerr, 5),
           grad_finite=gfin, ok=(rerr < 0.02 and gfin))

    # 5. headline bench — in-process (same TPU-lock constraint as check 2);
    # bench.main prints its own JSON line
    if not cli.skip_resnet:
        import bench

        argv = sys.argv
        # check 2 already swept the flash bench in this process — skip
        # bench.py's duplicate secondary metric
        sys.argv = ["bench.py", "--skip-attention"]
        try:
            # the ResNet fused step is ~30min of cold XLA compile on a
            # 1-core host (cached in .jax_cache afterwards); 3000s raced
            # the cold compile and aborted AFTER paying for it but BEFORE
            # the cache write
            with deadline(5400):
                rec = bench.main()
            report("resnet50_bench", result=rec,
                   ok=bool(rec) and "error" not in rec)
        except Exception as e:
            report("resnet50_bench", ok=False, error=str(e)[:200])
        finally:
            sys.argv = argv

    # 6. upstream splash attention — the mature TPU kernel as the MFU
    # ceiling reference for our flash numbers at the same shape
    if not cli.skip_oracle:
        from bench_attention import run_oracle_bench

        try:
            with deadline(900):
                orc = run_oracle_bench(seq=cli.seq, steps=5)
            report("splash_oracle", result=orc, ok=True)
        except Exception as e:
            report("splash_oracle", ok=False, error=str(e)[:200])

    # 7. model-level A/B: the transformer-LM train step on the splash
    # backend (the flash-backend number is inside resnet50_bench's
    # record); together with check 6 this closes the kernel-vs-model
    # attribution question in one window
    if not cli.skip_resnet and not cli.skip_oracle:
        import bench

        try:
            # a TimeoutError raised mid-dispatch in an earlier check can
            # leave the backend resolution wedged (observed 2026-07-31:
            # pallas lowered "for CPU" on a TPU-only process after the
            # check-5 alarm fired inside a native compile) — re-assert
            # before attributing a failure to the kernel under test
            if jax.default_backend() != "tpu":
                raise RuntimeError(
                    "backend no longer reports tpu (%s) — wedged by an "
                    "earlier check's timeout; rerun standalone"
                    % jax.default_backend())
            with deadline(1200):
                lm = bench.transformer_lm_bench(attn_impl="splash")
            peak = 197e12
            report("transformer_lm_splash",
                   tokens_per_sec=round(lm["tokens_per_sec"], 1),
                   mfu=round(lm["model_tflops"] * 1e12 / peak, 4), ok=True)
        except Exception as e:
            report("transformer_lm_splash", ok=False, error=str(e)[:200])


if __name__ == "__main__":
    main()

#!/usr/bin/env python
"""parse_log — extract per-epoch metrics/throughput from training logs.

Capability parity with the reference's log parser (used by its CI accuracy
gates, /root/reference/tools/parse_log.py and tests/nightly/test_all.sh:
43-60 which grep final validation accuracy); written for this framework's
log format (base_module.fit epoch lines + callback.Speedometer batch
lines).

Usage:
  python tools/parse_log.py train.log                  # markdown table
  python tools/parse_log.py train.log --format json    # one JSON object
  python tools/parse_log.py train.log --metric accuracy --last
      # print just the final value of one metric (CI gate helper):
      #   python tools/parse_log.py log --metric validation-accuracy \
      #       --last --assert-min 0.99
"""
from __future__ import annotations

import argparse
import json
import re
import sys
from collections import defaultdict

# Epoch[3] Train-accuracy=0.981200  /  Epoch[3] Validation-accuracy=0.97
_EPOCH_METRIC = re.compile(
    r"Epoch\[(\d+)\][^\n]*?\b(Train|Validation)-([\w-]+)=([0-9.eE+-]+)")
# Epoch[3] Time cost=12.345
_EPOCH_TIME = re.compile(r"Epoch\[(\d+)\][^\n]*?Time cost=([0-9.eE+-]+)")
# Epoch[3] Batch [40]  Speed: 1234.56 samples/sec
_SPEED = re.compile(
    r"Epoch\[(\d+)\][^\n]*?Speed: ([0-9.eE+-]+) samples/sec")


def parse(text):
    """-> {epoch: {metric_name: value, ..., "time_cost": s, "speed": avg}}"""
    epochs = defaultdict(dict)
    speeds = defaultdict(list)
    for m in _EPOCH_METRIC.finditer(text):
        epoch, phase, name, value = m.groups()
        key = "%s-%s" % (phase.lower(), name)
        try:
            epochs[int(epoch)][key] = float(value)
        except ValueError:
            continue
    for m in _EPOCH_TIME.finditer(text):
        epochs[int(m.group(1))]["time_cost"] = float(m.group(2))
    for m in _SPEED.finditer(text):
        speeds[int(m.group(1))].append(float(m.group(2)))
    for epoch, vals in speeds.items():
        epochs[epoch]["speed"] = sum(vals) / len(vals)
    return dict(sorted(epochs.items()))


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="parse fit/Speedometer training logs")
    ap.add_argument("logfile", help="path, or - for stdin")
    ap.add_argument("--format", choices=("table", "json"), default="table")
    ap.add_argument("--metric", default=None,
                    help="print one metric's series (e.g. train-accuracy)")
    ap.add_argument("--last", action="store_true",
                    help="with --metric: print only the final value")
    ap.add_argument("--assert-min", type=float, default=None,
                    help="exit 1 unless the (final) metric value >= this "
                         "(the CI accuracy gate)")
    args = ap.parse_args(argv)

    if args.metric is None and (args.assert_min is not None or args.last):
        ap.error("--assert-min/--last require --metric")
    text = sys.stdin.read() if args.logfile == "-" else \
        open(args.logfile).read()
    epochs = parse(text)
    if not epochs:
        print("no epoch records found", file=sys.stderr)
        return 1

    if args.metric:
        series = [(e, v[args.metric]) for e, v in epochs.items()
                  if args.metric in v]
        if not series:
            print("metric %r not found; available: %s"
                  % (args.metric,
                     sorted({k for v in epochs.values() for k in v})),
                  file=sys.stderr)
            return 1
        if args.last:
            print(series[-1][1])
        else:
            for e, v in series:
                print(e, v)
        if args.assert_min is not None and series[-1][1] < args.assert_min:
            print("FAIL: %s=%.6f < %.6f" % (args.metric, series[-1][1],
                                            args.assert_min),
                  file=sys.stderr)
            return 1
        return 0

    if args.format == "json":
        print(json.dumps(epochs))
        return 0
    cols = sorted({k for v in epochs.values() for k in v})
    print("| epoch | " + " | ".join(cols) + " |")
    print("|" + "---|" * (len(cols) + 1))
    for e, v in epochs.items():
        print("| %d | " % e +
              " | ".join("%.6g" % v[c] if c in v else "" for c in cols) +
              " |")
    return 0


if __name__ == "__main__":
    sys.exit(main())

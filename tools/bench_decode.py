"""JPEG decode-scaling microbenchmark for the native IO path.

Measures img/s through mxnet_tpu.native.decode_jpeg_batch (the GIL-free
C++ thread-pool decoder, src/imgdecode.cc) at 224x224 across thread
counts — the feed-the-chip half of the benchmark story (reference:
example/image-classification/README.md:245-268 'Note on CPU decoding
performance').

Prints one JSON line per thread count:
  {"metric": "jpeg_decode_img_per_sec", "nthreads": N, "value": ...}

Used by tests/test_real_data_e2e.py to enforce the per-core decode floor.
"""
import argparse
import io
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def make_jpegs(n=64, size=224, quality=90):
    """Deterministic photographic-ish JPEGs (noise compresses atypically)."""
    import numpy as np
    from PIL import Image

    rng = np.random.RandomState(0)
    bufs = []
    base = rng.randint(0, 255, size=(size, size, 3), dtype=np.uint8)
    for i in range(n):
        # smooth gradients + a shifted noise field: realistic JPEG entropy
        arr = np.roll(base, i * 7, axis=1)
        yy = np.linspace(0, 255, size, dtype=np.uint8)
        arr = (arr // 2 + yy[None, :, None] // 2).astype(np.uint8)
        b = io.BytesIO()
        Image.fromarray(arr).save(b, format="JPEG", quality=quality)
        bufs.append(b.getvalue())
    return bufs


def run(nthreads, n_images=256, size=224, iters=3):
    from mxnet_tpu import native

    bufs = make_jpegs(min(n_images, 64), size=size)
    bufs = (bufs * ((n_images + len(bufs) - 1) // len(bufs)))[:n_images]
    # warm up (thread pool spawn, lazy lib load)
    out = native.decode_jpeg_batch(bufs[:8], nthreads=nthreads)
    if out[0] is None:
        raise RuntimeError("native decoder unavailable (libmxtpu.so)")
    t0 = time.time()
    for _ in range(iters):
        native.decode_jpeg_batch(bufs, nthreads=nthreads)
    dt = time.time() - t0
    return n_images * iters / dt


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--threads", default="1,2,4")
    ap.add_argument("--n-images", type=int, default=256)
    ap.add_argument("--size", type=int, default=224)
    cli = ap.parse_args()
    for nt in (int(t) for t in cli.threads.split(",")):
        rate = run(nt, n_images=cli.n_images, size=cli.size)
        print(json.dumps({"metric": "jpeg_decode_img_per_sec",
                          "nthreads": nt, "size": cli.size,
                          "value": round(rate, 1)}), flush=True)


if __name__ == "__main__":
    main()

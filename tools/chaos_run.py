#!/usr/bin/env python
"""Replay (or sweep) fault-injection seeds against a training command.

A chaos test that fails reports its (spec, seed); this tool reruns the
exact same fault schedule — the FaultPlan decision for the N-th matching
call is a pure function of (spec, seed, N), so the failure reproduces
outside pytest where it can be debugged:

    # replay the failing schedule
    python tools/chaos_run.py --spec "kv.client.*:drop=0.3" --seed 7 -- \\
        python tools/launch.py -n 2 -s 1 python train.py

    # sweep seeds 0..19 hunting for a schedule that breaks the job
    python tools/chaos_run.py --spec "kv.client.*:drop=0.3" --seeds 0:20 -- \\
        python train.py

The spec/seed reach the command (and every child it spawns, e.g. via
tools/launch.py) through MXNET_FAULTS_SPEC / MXNET_FAULTS_SEED, which
mxnet_tpu.faults reads at import.  See docs/how_to/fault_tolerance.md
for the spec grammar.

Built-in scenarios (no command needed) exercise whole-stack robustness
properties end to end:

    # elastic membership churn: kill -> evict -> respawn-join
    python tools/chaos_run.py --scenario membership-churn --seeds 0:5

    # serving front door: replica failure + breaker recovery + hot-swap
    python tools/chaos_run.py --scenario serving-failover --seeds 0:5

``serving-failover`` drives a Router over N in-process InferenceServer
replicas under sustained load while a seeded FaultPlan hard-fails one
replica (the seed picks the victim), then lets it recover, then rolls a
checkpoint hot-swap through the fleet — asserting zero failed client
requests, breaker open -> half-open -> closed, and zero post-warmup
recompiles.

``sdc-rollback`` flips an exponent bit in one gradient tensor of a
seeded fit() (the seed picks which) and requires the training guardian
to detect it, roll back to the last-good ring snapshot, and replay to a
final state bit-identical to an uninjected control run; it also pushes a
NaN-poisoned gradient at a kvstore server and requires a typed NACK with
the stored value untouched.

``membership-churn`` runs N elastic workers against a sync-mode server
with eviction enabled, hard-kills one mid-run under a seeded FaultPlan
(the seed picks both the victim rank and the kill step), waits for the
server to evict it, then joins a fresh rank mid-run and verifies every
survivor lands on the churn-invariant final weight (see
tests/elastic_churn_worker.py).

``host-loss`` runs the multi-model platform on 2 hosts x 2 devices and
kills every replica on one host mid-stream and mid-fault-in (heartbeats
stop without deregistration); the health plane must flip the failure
domain dead and the degradation ladder must re-fault the evicted
interactive model warm, brown out the batch class with honest 503s, and
fail generate streams over mid-token with bit-identical transcripts.

Scenario sweeps print one machine-readable summary JSON object on
stdout — ``{"scenario", "seeds", "ok", "failing_seeds", "runs": [{seed,
ok, per-tenant failure counts, ...}]}`` — mirror it to a file with
``--summary-json PATH``; the exit code stays nonzero on any invariant
breach.
"""
from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys


def _free_port():
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def run_membership_churn(seed, timeout=120.0, workers=3, steps=10,
                         join_step=6):
    """Elastic shrink/grow probe: ``workers`` elastic workers train
    against a sync-mode server with eviction on; a seeded FaultPlan
    hard-kills one mid-run (``os._exit(137)`` — kill -9 semantics, no
    leave RPC), the server evicts it on stale heartbeats and the
    survivors continue on renormalized merge rounds; a fresh rank then
    joins mid-run and the job finishes counting the full live set
    again.  Returns True when the victim died with rc 137, membership
    shrank and grew back, and every survivor landed on the
    churn-invariant final weight."""
    import glob
    import json
    import shutil
    import tempfile
    import time

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if repo not in sys.path:
        sys.path.insert(0, repo)
    from mxnet_tpu.kvstore_server import ServerClient

    port = _free_port()
    victim = seed % workers
    kill_call = 2 + seed % max(1, join_step - 2)  # 1-based fire() count
    spec = "churn.worker.step:kill=1@#%d" % kill_call
    # flight recorder: the hard-killed victim must leave postmortem
    # evidence (its last spans/events) in this run-scoped directory
    telem_dir = tempfile.mkdtemp(prefix="chaos-telemetry-")
    base = dict(os.environ,
                DMLC_PS_ROOT_URI="127.0.0.1",
                DMLC_PS_ROOT_PORT=str(port),
                DMLC_NUM_WORKER=str(workers),
                MXNET_KVSTORE_ELASTIC="1",
                MXNET_KVSTORE_HEARTBEAT_INTERVAL="0.2",
                MXNET_TELEMETRY="1",
                MXNET_TELEMETRY_DIR=telem_dir,
                CHURN_TOTAL_STEPS=str(steps),
                CHURN_JOIN_STEP=str(join_step),
                CHURN_EXPECT_MEMBERS=str(workers),
                CHURN_KILL_RANK=str(victim),
                CHURN_FAULTS_SPEC=spec,
                CHURN_FAULTS_SEED=str(seed))
    # the kill must be rank-gated IN-PROCESS by the worker script: a
    # plain MXNET_FAULTS_SPEC would reach every worker with the same
    # seed and kill the whole fleet
    base.pop("MXNET_FAULTS_SPEC", None)
    base.setdefault("JAX_PLATFORMS", "cpu")
    base["PYTHONPATH"] = repo + (
        os.pathsep + base["PYTHONPATH"] if base.get("PYTHONPATH") else "")
    worker_py = os.path.join(repo, "tests", "elastic_churn_worker.py")
    print("chaos_run: membership-churn seed %d: victim rank %d dies at "
          "step %d/%d (spec %r)" % (seed, victim, kill_call - 1, steps,
                                    spec), file=sys.stderr, flush=True)
    server = subprocess.Popen(
        [sys.executable, "-c", "import mxnet_tpu"],
        env=dict(base, DMLC_ROLE="server", MXNET_KVSTORE_SYNC="1",
                 MXNET_KVSTORE_EVICT_TIMEOUT="1.0"),
        cwd=repo)
    procs = {}
    results = {}
    grown = None
    try:
        for r in range(workers):
            procs[r] = subprocess.Popen(
                [sys.executable, worker_py],
                env=dict(base, DMLC_WORKER_ID=str(r)),
                stdout=subprocess.PIPE, text=True)
        with ServerClient("127.0.0.1", port) as cli:
            deadline = time.monotonic() + timeout

            def wait_members(pred, what):
                while time.monotonic() < deadline:
                    try:
                        m = cli.membership()
                    except Exception:
                        m = None
                    if m is not None and pred(m):
                        return m
                    time.sleep(0.1)
                raise RuntimeError("membership-churn: timed out waiting "
                                   "for %s" % what)

            # kill -> evict: gen counts N joins plus the eviction bump,
            # which tells a late poll apart from "not everyone joined yet"
            wait_members(lambda m: m["gen"] >= workers + 1
                         and len(m["ranks"]) == workers - 1, "eviction")
            # respawn-join: a fresh rank, never the victim's reused
            procs[workers] = subprocess.Popen(
                [sys.executable, worker_py],
                env=dict(base, DMLC_WORKER_ID=str(workers),
                         MXNET_KVSTORE_ELASTIC_JOIN="1"),
                stdout=subprocess.PIPE, text=True)
            grown = wait_members(lambda m: len(m["ranks"]) == workers,
                                 "mid-run join")
            print("chaos_run: membership grew back to %s (gen %d)"
                  % (grown["ranks"], grown["gen"]),
                  file=sys.stderr, flush=True)
            for r, p in procs.items():
                out, _ = p.communicate(
                    timeout=max(1.0, deadline - time.monotonic()))
                line = [l for l in (out or "").splitlines()
                        if l.startswith("{")]
                results[r] = (p.returncode,
                              json.loads(line[-1]) if line else None)
    finally:
        for p in procs.values():
            if p.poll() is None:
                p.kill()
        server.terminate()
        try:
            server.wait(timeout=10)
        except subprocess.TimeoutExpired:
            server.kill()

    ok = True
    rc, _ = results.pop(victim, (None, None))
    if rc != 137:
        print("chaos_run: victim rank %d exited rc %s, expected 137"
              % (victim, rc), file=sys.stderr, flush=True)
        ok = False
    for r, (rc, info) in sorted(results.items()):
        if rc != 0 or info is None or "final" not in info:
            print("chaos_run: worker rank %d failed (rc %s, %s)"
                  % (r, rc, info), file=sys.stderr, flush=True)
            ok = False
            continue
        if not info.get("joiner") and \
                abs(info["final"] - info["target"]) > 1e-4:
            print("chaos_run: rank %d final %.6f != invariant %.6f — "
                  "shrunken rounds were not renormalized"
                  % (r, info["final"], info["target"]),
                  file=sys.stderr, flush=True)
            ok = False
    # flight recorder: the fault-injected kill must have dumped the
    # victim's last spans/events before os._exit(137)
    pm = sorted(glob.glob(os.path.join(
        telem_dir, "postmortem-worker%d-*.json" % victim)))
    if not pm:
        print("chaos_run: no flight-recorder postmortem for victim rank %d "
              "in %s" % (victim, telem_dir), file=sys.stderr, flush=True)
        ok = False
    else:
        with open(pm[-1]) as f:
            post = json.load(f)
        if not post.get("reason", "").startswith("fault-kill:") or \
                not (post.get("spans") or post.get("events")):
            print("chaos_run: victim postmortem %s lacks kill reason or "
                  "span/event evidence" % pm[-1],
                  file=sys.stderr, flush=True)
            ok = False
        else:
            print("chaos_run: victim postmortem ok: %s (%d spans, %d "
                  "events)" % (os.path.basename(pm[-1]),
                               len(post["spans"]), len(post["events"])),
                  file=sys.stderr, flush=True)
    if ok:
        shutil.rmtree(telem_dir, ignore_errors=True)
    else:
        print("chaos_run: telemetry artifacts kept at %s" % telem_dir,
              file=sys.stderr, flush=True)
    return ok


def run_serving_failover(seed, timeout=120.0, replicas=3, load_threads=4):
    """Serving front-door probe, in-process: a Router over ``replicas``
    warmed InferenceServer replicas takes sustained load while a seeded
    FaultPlan hard-fails every call to one victim replica (the seed picks
    the victim), then the fault clears, then a checkpoint hot-swap rolls
    through the fleet — all under load.  Passes when zero client requests
    failed end to end, the victim's breaker opened and re-closed after
    recovery, the swap served the new params, and the warm-then-flip kept
    the recompile counter at zero."""
    import tempfile
    import threading
    import time

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if repo not in sys.path:
        sys.path.insert(0, repo)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import serving

    in_dim, hid = 6, 3
    rng = np.random.RandomState(seed)
    net = mx.sym.FullyConnected(mx.sym.Variable("data"), num_hidden=hid,
                                name="fc")

    def ckpt_params(s):
        r = np.random.RandomState(s)
        return {"fc_weight": mx.nd.array(
                    r.randn(hid, in_dim).astype(np.float32)),
                "fc_bias": mx.nd.array(r.randn(hid).astype(np.float32))}

    victim = "r%d" % (seed % replicas)
    spec = "serving.replica.%s.call:ioerr=1" % victim
    print("chaos_run: serving-failover seed %d: victim %s (spec %r), "
          "%d replicas" % (seed, victim, spec, replicas),
          file=sys.stderr, flush=True)

    tmp = tempfile.mkdtemp(prefix="chaos-serving-")
    prefix = os.path.join(tmp, "m")
    mx.model.save_checkpoint(prefix, 1, net, ckpt_params(seed + 1), {})
    mx.model.save_checkpoint(prefix, 2, net, ckpt_params(seed + 2), {})
    srvs = [serving.InferenceServer.from_checkpoint(
        prefix, 1, {"data": (4, in_dim)}, max_wait_us=1000)
        for _ in range(replicas)]
    router = serving.Router(srvs, seed=seed, retries=2,
                            breaker_threshold=3, breaker_cooldown_ms=100)
    X = rng.randn(8, in_dim).astype(np.float32)
    stop_evt = threading.Event()
    failures = []
    served = [0]

    def load():
        i = 0
        while not stop_evt.is_set():
            try:
                router.predict(data=X[i % len(X)])
                served[0] += 1
            except Exception as exc:
                failures.append(repr(exc))
            i += 1

    deadline = time.monotonic() + timeout
    ok = True
    threads = [threading.Thread(target=load, daemon=True)
               for _ in range(load_threads)]
    try:
        for t in threads:
            t.start()
        # phase 1: hard-fail the victim mid-load until its breaker opens
        mx.faults.install(mx.faults.FaultPlan(spec, seed))
        try:
            while time.monotonic() < deadline:
                snap = router.metrics.snapshot()
                if snap["breaker_transitions"].get("open"):
                    break
                time.sleep(0.05)
            else:
                print("chaos_run: breaker never opened", file=sys.stderr)
                ok = False
        finally:
            mx.faults.uninstall()
        # phase 2: fault cleared — the breaker must walk half-open ->
        # closed on a probe request while the load keeps flowing
        while time.monotonic() < deadline:
            states = {d["name"]: d["state"] for d in router.describe()}
            if states.get(victim) == serving.router.BREAKER_CLOSED:
                break
            time.sleep(0.05)
        else:
            print("chaos_run: breaker never re-closed", file=sys.stderr)
            ok = False
        # phase 3: zero-downtime hot-swap under the same load
        swapped = router.swap(prefix, 2)
        time.sleep(0.2)
        stop_evt.set()
        for t in threads:
            t.join(timeout=max(1.0, deadline - time.monotonic()))
    finally:
        stop_evt.set()
        router.close(stop_backends=True)

    snap = router.metrics.snapshot()
    if failures or snap["failed"]:
        print("chaos_run: %d client requests failed (first: %s)"
              % (len(failures), failures[:3]), file=sys.stderr, flush=True)
        ok = False
    if swapped != replicas:
        print("chaos_run: swap covered %d/%d replicas" % (swapped, replicas),
              file=sys.stderr, flush=True)
        ok = False
    cold = router.cold_bucket_runs()
    if cold:
        print("chaos_run: %d post-warmup recompiles — the swap shadows "
              "were not fully warmed" % cold, file=sys.stderr, flush=True)
        ok = False
    if ok:
        print("chaos_run: served %d requests, 0 failed; breaker %s; "
              "swap ok (0 recompiles)"
              % (served[0], dict(snap["breaker_transitions"])),
              file=sys.stderr, flush=True)
        import shutil
        shutil.rmtree(tmp, ignore_errors=True)
    return ok


def run_flash_crowd(seed, timeout=120.0, max_replicas=3, load_threads=6):
    """Self-healing fleet probe, in-process: a replicated front door
    (two Routers over one ReplicaRegistry) serves diurnal + flash-crowd
    open-loop load over a fleet the Autoscaler grows 1→N and shrinks
    back to 1, spawning every replica warm (AOT bundle + compile cache
    attached), while one router is killed mid-flood and its clients
    fail over to the survivor.  Passes when the fleet scaled out (>= 2
    replicas at peak) and back in (1 at the end), zero client requests
    failed end to end, zero interactive-SLO violations (no sheds, no
    deadline expiries), and every scaled-out replica served its first
    request with ``cold_bucket_runs() == 0``."""
    import shutil
    import tempfile
    import threading
    import time

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if repo not in sys.path:
        sys.path.insert(0, repo)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import serving

    in_dim, hid = 6, 3
    rng = np.random.RandomState(seed)
    net = mx.sym.FullyConnected(mx.sym.Variable("data"), num_hidden=hid,
                                name="fc")
    params = {"fc_weight": mx.nd.array(
                  rng.randn(hid, in_dim).astype(np.float32)),
              "fc_bias": mx.nd.array(rng.randn(hid).astype(np.float32))}

    tmp = tempfile.mkdtemp(prefix="chaos-flashcrowd-")
    prefix = os.path.join(tmp, "m")
    mx.model.save_checkpoint(prefix, 1, net, params, {})
    shapes = {"data": (4, in_dim)}
    server_kw = dict(max_wait_us=1000, max_queue=8)
    cache_key, cache_prev = "MXNET_COMPILE_CACHE_DIR", \
        os.environ.get("MXNET_COMPILE_CACHE_DIR")
    os.environ[cache_key] = os.path.join(tmp, "cache")

    class TrackingProvider(serving.LocalCheckpointProvider):
        """LocalCheckpointProvider remembering every spawn, so the
        cold-start acceptance check covers retired replicas too."""

        spawned = []

        def spawn(self):
            name, server = super().spawn()
            self.spawned.append((name, server))
            return name, server

    registry = serving.ReplicaRegistry(ttl_ms=2000)
    # the seed replica primes the compile cache and ships its AOT
    # bundle, so every scale-out spawn warms deserialize-only
    seed_srv = serving.InferenceServer.from_checkpoint(
        prefix, 1, shapes, attach_aot=False, **server_kw)
    seed_srv.save_aot_bundle(prefix, 1)
    stop_seed_beat = serving.start_heartbeater(registry, "seed0", seed_srv,
                                               interval_ms=200)
    slos = {"interactive": serving.SLOClass("interactive", deadline_ms=5000,
                                            priority=0, sheddable=False),
            "batch": serving.SLOClass("batch", priority=1, sheddable=True)}
    routers = [serving.Router(registry=registry, registry_sync_ms=50,
                              slo_classes=dict(slos), seed=seed + i,
                              retries=3)
               for i in range(2)]
    provider = TrackingProvider(prefix, 1, shapes, registry=registry,
                                attach_aot=True, **server_kw)
    autoscaler = serving.Autoscaler(
        routers[0], provider, min_replicas=1, max_replicas=max_replicas,
        interval_ms=50, out_pressure=0.3, in_pressure=0.05, hysteresis=2,
        cooldown_ms=300, drain_timeout_ms=10000)
    autoscaler.start()

    X = rng.randn(8, in_dim).astype(np.float32)
    alive = [True, True]  # routers[1] is killed mid-flood
    phase = ["low"]
    stop_evt = threading.Event()
    failures = []
    served = [0]
    peak = [1]

    def one_request(tid, i):
        """End-to-end client call: bounded retry over the replicated
        front door (a killed router or a 429/overload answer means
        back off and go to the other one — the documented contract)."""
        deadline = time.monotonic() + 10.0
        last = None
        while time.monotonic() < deadline:
            for k in range(2):
                r = (tid + i + k) % 2
                if not alive[r]:
                    continue
                try:
                    routers[r].predict(slo="interactive", deadline_ms=5000,
                                       data=X[i % len(X)])
                    served[0] += 1
                    return True
                except Exception as exc:
                    last = exc
            time.sleep(0.01)
        failures.append(repr(last))
        return False

    def load(tid):
        i = 0
        while not stop_evt.is_set():
            if phase[0] == "low":
                one_request(tid, i)
                i += 1
                time.sleep(0.05)
            else:  # flood: open-loop burst through the front door
                futs = []
                for _ in range(4):
                    r = 0 if not alive[1] else (tid + i) % 2
                    try:
                        futs.append(routers[r].submit(
                            slo="interactive", deadline_ms=5000,
                            data=X[i % len(X)]))
                    except Exception:
                        one_request(tid, i)
                    i += 1
                for f in futs:
                    try:
                        f.result()
                        served[0] += 1
                    except Exception:
                        one_request(tid, i)

    def active_replicas():
        sig = routers[0].signals()
        return sig["replicas"] - sig["draining"]

    deadline = time.monotonic() + timeout
    ok = True
    threads = [threading.Thread(target=load, args=(t,), daemon=True)
               for t in range(load_threads)]
    try:
        for t in threads:
            t.start()
        time.sleep(0.8)  # diurnal trough: fleet must hold at 1
        print("chaos_run: flash crowd begins (replicas=%d)"
              % active_replicas(), file=sys.stderr, flush=True)
        phase[0] = "flood"
        while time.monotonic() < deadline:
            peak[0] = max(peak[0], active_replicas())
            if peak[0] >= 2:
                break
            time.sleep(0.05)
        if peak[0] < 2:
            print("chaos_run: fleet never scaled out under the flood",
                  file=sys.stderr, flush=True)
            ok = False
        # kill one front door mid-flood: clients must fail over
        alive[1] = False
        routers[1].close()
        print("chaos_run: router 1 killed mid-flood (replicas=%d)"
              % active_replicas(), file=sys.stderr, flush=True)
        t_flood_end = time.monotonic() + 1.0
        while time.monotonic() < min(t_flood_end, deadline):
            peak[0] = max(peak[0], active_replicas())
            time.sleep(0.05)
        phase[0] = "low"
        print("chaos_run: flash crowd over (peak replicas=%d); cooling"
              % peak[0], file=sys.stderr, flush=True)
        while time.monotonic() < deadline:
            if active_replicas() <= 1 and not autoscaler.owned():
                break
            time.sleep(0.1)
        else:
            print("chaos_run: fleet never scaled back in",
                  file=sys.stderr, flush=True)
            ok = False
        stop_evt.set()
        for t in threads:
            t.join(timeout=max(1.0, deadline - time.monotonic()))
    finally:
        stop_evt.set()
        autoscaler.stop(retire_owned=True)
        for r, rt in enumerate(routers):
            if alive[r]:
                rt.close()
        stop_seed_beat()
        seed_srv.stop(drain=True)
        registry.close()
        if cache_prev is None:
            os.environ.pop(cache_key, None)
        else:
            os.environ[cache_key] = cache_prev

    if failures:
        print("chaos_run: %d client requests failed end to end (first: %s)"
              % (len(failures), failures[:3]), file=sys.stderr, flush=True)
        ok = False
    snap = routers[0].metrics.snapshot()
    violations = snap["expired"].get("interactive", 0) + \
        snap["shed"].get("interactive", 0)
    if violations:
        print("chaos_run: %d interactive-SLO violations" % violations,
              file=sys.stderr, flush=True)
        ok = False
    scale_outs = [e for e in autoscaler.events
                  if e["op"] == "scale_out" and e["ok"]]
    scale_ins = [e for e in autoscaler.events
                 if e["op"] == "scale_in" and e["ok"]]
    if not scale_outs or not scale_ins:
        print("chaos_run: missing scale events (out=%d in=%d)"
              % (len(scale_outs), len(scale_ins)),
              file=sys.stderr, flush=True)
        ok = False
    cold = {n: s.cold_bucket_runs() for n, s in TrackingProvider.spawned}
    if any(cold.values()):
        print("chaos_run: scaled-out replicas served cold buckets: %s"
              % cold, file=sys.stderr, flush=True)
        ok = False
    if not TrackingProvider.spawned:
        print("chaos_run: autoscaler never spawned a replica",
              file=sys.stderr, flush=True)
        ok = False
    if ok:
        print("chaos_run: served %d requests, 0 failed, 0 SLO violations; "
              "fleet 1→%d→1 (%d scale-outs, %d scale-ins), %d warm spawns "
              "with 0 cold buckets"
              % (served[0], peak[0], len(scale_outs), len(scale_ins),
                 len(cold)), file=sys.stderr, flush=True)
        shutil.rmtree(tmp, ignore_errors=True)
    else:
        print("chaos_run: artifacts kept at %s" % tmp,
              file=sys.stderr, flush=True)
    return ok


def run_decode_storm(seed, timeout=120.0, replicas=2, load_threads=3,
                     streams_per_thread=6):
    """Generative-serving probe, in-process: a Router streams token
    generations (``Router.generate`` — continuous batching + paged KV on
    every replica) under open-loop load from ``load_threads`` clients
    while one replica is hard-killed mid-storm (the seed picks the
    victim and the kill point).  Streams running on the victim must
    resume on a survivor by re-prefilling prompt + emitted tokens —
    greedy decode is deterministic, so every client transcript must be
    bit-identical to the single-engine reference.  Passes when zero
    streams failed, every transcript matched, TTFT p99 stayed bounded,
    and the survivors' decode loops performed zero post-warmup XLA
    compiles."""
    import threading
    import time

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if repo not in sys.path:
        sys.path.insert(0, repo)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import serving
    from mxnet_tpu.serving.metrics import _percentile

    V, layers, heads, hid, S = 64, 2, 2, 32, 32
    rng = np.random.RandomState(seed)
    net = mx.models.get_transformer_lm(vocab_size=V, num_layers=layers,
                                       num_heads=heads, hidden=hid,
                                       seq_len=S)
    arg_shapes, _, _ = net.infer_shape(data=(1, S), softmax_label=(1, S))
    params = {
        name: mx.nd.array(rng.randn(*shp).astype(np.float32) * 0.05)
        for name, shp in zip(net.list_arguments(), arg_shapes)
        if name not in ("data", "softmax_label")}
    spec = dict(vocab_size=V, num_layers=layers, num_heads=heads,
                hidden=hid, max_seq_len=S, lane_buckets=(1, 2, 4),
                page_size=4, num_pages=48, prefill_len_buckets=(8, 16, 32))

    victim_idx = seed % replicas
    kill_after = 4 + seed % 5  # streams completed before the kill
    print("chaos_run: decode-storm seed %d: victim r%d dies after %d "
          "streams, %d replicas x %d clients"
          % (seed, victim_idx, kill_after, replicas, load_threads),
          file=sys.stderr, flush=True)

    srvs = [serving.InferenceServer(
        net, params, {"data": (4, S), "softmax_label": (4, S)},
        max_wait_us=1000, generator_spec=dict(spec))
        for _ in range(replicas)]
    router = serving.Router(srvs, seed=seed, retries=3)

    # greedy decode is deterministic: one reference engine's transcript
    # is THE correct answer for every (prompt, max_new) the storm sends
    ref_engine = mx.generation.DecodeEngine(params, **spec)
    prompts = []
    for i in range(8):
        plen = 2 + int(rng.randint(0, 10))
        prompts.append(([int(t) for t in rng.randint(0, V, size=plen)],
                        4 + int(rng.randint(0, 8))))
    reference = {i: ref_engine.generate(p, n)
                 for i, (p, n) in enumerate(prompts)}
    ref_engine.stop()

    stop_evt = threading.Event()
    failures = []
    mismatches = []
    ttfts = []
    completed = [0]
    lock = threading.Lock()

    def load(tid):
        i = tid
        while not stop_evt.is_set():
            pi = i % len(prompts)
            prompt, max_new = prompts[pi]
            try:
                t0 = time.monotonic()
                toks = []
                for tok in router.generate(prompt, max_new,
                                           request_id="storm-%d-%d"
                                           % (tid, i)):
                    if not toks:
                        with lock:
                            ttfts.append((time.monotonic() - t0) * 1e3)
                    toks.append(tok)
                if toks != reference[pi]:
                    with lock:
                        mismatches.append((pi, toks, reference[pi]))
                with lock:
                    completed[0] += 1
            except Exception as exc:
                with lock:
                    failures.append(repr(exc))
            i += load_threads

    deadline = time.monotonic() + timeout
    ok = True
    threads = [threading.Thread(target=load, args=(t,), daemon=True)
               for t in range(load_threads)]
    try:
        for t in threads:
            t.start()
        while time.monotonic() < deadline and completed[0] < kill_after:
            time.sleep(0.02)
        print("chaos_run: killing replica r%d mid-storm (%d streams done)"
              % (victim_idx, completed[0]), file=sys.stderr, flush=True)
        srvs[victim_idx].stop(drain=False)
        target = completed[0] + load_threads * streams_per_thread
        while time.monotonic() < deadline and completed[0] < target:
            time.sleep(0.05)
        stop_evt.set()
        for t in threads:
            t.join(timeout=max(1.0, deadline - time.monotonic()))
    finally:
        stop_evt.set()
        router.close(stop_backends=True)

    snap = router.metrics.snapshot()
    if failures:
        print("chaos_run: %d streams failed (first: %s)"
              % (len(failures), failures[:3]), file=sys.stderr, flush=True)
        ok = False
    if mismatches:
        pi, got, want = mismatches[0]
        print("chaos_run: %d transcript mismatches (prompt %d: got %s "
              "want %s) — the resume duplicated or dropped tokens"
              % (len(mismatches), pi, got, want),
              file=sys.stderr, flush=True)
        ok = False
    if completed[0] < kill_after + 1:
        print("chaos_run: storm too short (%d streams) to cover the kill"
              % completed[0], file=sys.stderr, flush=True)
        ok = False
    p99 = _percentile(sorted(ttfts), 0.99) if ttfts else None
    if p99 is None or p99 > 30000.0:
        print("chaos_run: TTFT p99 unbounded (%s ms over %d streams)"
              % (p99, len(ttfts)), file=sys.stderr, flush=True)
        ok = False
    cold = sum(s._generator.cold_decode_runs()
               for i, s in enumerate(srvs) if i != victim_idx)
    if cold:
        print("chaos_run: %d post-warmup decode recompiles on survivors"
              % cold, file=sys.stderr, flush=True)
        ok = False
    if ok:
        print("chaos_run: %d streams completed, 0 failed, 0 mismatches; "
              "%d mid-stream resumes; TTFT p50/p99 %.1f/%.1f ms; 0 cold "
              "decode steps"
              % (completed[0], snap["stream_resumes"],
                 _percentile(sorted(ttfts), 0.50), p99),
              file=sys.stderr, flush=True)
    return ok


def run_prefix_storm(seed, timeout=120.0, replicas=2, load_threads=3,
                     streams_per_thread=5):
    """Prefix-cache/speculation probe, in-process: every client hammers
    prompts sharing one hot system-style prefix against a Router whose
    replicas run the copy-on-write prefix cache AND a draft model,
    while the fault plane fails prefix lookups and draft verifies
    (``generation.prefix.lookup`` / ``generation.draft.verify`` ioerr)
    and one replica is hard-killed mid-storm.  A lookup fault must
    degrade to a cache miss and a verify fault to a plain decode step —
    never to a wrong token: greedy decode is deterministic, so every
    transcript must be bit-identical to an uncached, non-speculative
    reference engine.  Passes when zero streams failed, every
    transcript matched, the cache actually served hits under the fault
    storm, survivors did zero post-warmup compiles, and — after
    shutdown — every replica's pool refcounts returned to zero (no
    leaked shared pages)."""
    import threading
    import time

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if repo not in sys.path:
        sys.path.insert(0, repo)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import faults as mx_faults
    from mxnet_tpu import serving

    V, layers, heads, hid, S = 64, 2, 2, 32, 32
    rng = np.random.RandomState(seed)
    net = mx.models.get_transformer_lm(vocab_size=V, num_layers=layers,
                                       num_heads=heads, hidden=hid,
                                       seq_len=S)
    arg_shapes, _, _ = net.infer_shape(data=(1, S), softmax_label=(1, S))
    params = {
        name: mx.nd.array(rng.randn(*shp).astype(np.float32) * 0.05)
        for name, shp in zip(net.list_arguments(), arg_shapes)
        if name not in ("data", "softmax_label")}
    spec = dict(vocab_size=V, num_layers=layers, num_heads=heads,
                hidden=hid, max_seq_len=S, lane_buckets=(1, 2, 4),
                page_size=4, num_pages=40, prefill_len_buckets=(8, 16, 32))
    gen_spec = dict(spec, prefix_cache_pages=12,
                    draft={"params": params, "num_layers": layers,
                           "num_heads": heads, "hidden": hid, "k": 2})

    victim_idx = seed % replicas
    kill_after = 4 + seed % 5
    print("chaos_run: prefix-storm seed %d: victim r%d dies after %d "
          "streams; prefix lookups and draft verifies fault at 25%%"
          % (seed, victim_idx, kill_after), file=sys.stderr, flush=True)

    # one hot shared prefix, per-prompt unique tails — heavy page
    # sharing plus COW splits the moment the tails diverge
    shared = [int(t) for t in rng.randint(0, V, size=12)]
    prompts = []
    for i in range(8):
        tail = [int(t) for t in rng.randint(0, V, size=int(
            rng.randint(0, 7)))]
        prompts.append((shared + tail, 4 + int(rng.randint(0, 5))))

    # greedy reference: NO cache, NO draft, NO faults — THE transcript
    ref_engine = mx.generation.DecodeEngine(params, **spec)
    reference = {i: ref_engine.generate(p, n)
                 for i, (p, n) in enumerate(prompts)}
    ref_engine.stop()

    srvs = [serving.InferenceServer(
        net, params, {"data": (4, S), "softmax_label": (4, S)},
        max_wait_us=1000, generator_spec=dict(gen_spec))
        for _ in range(replicas)]
    engines = [s._generator for s in srvs]
    router = serving.Router(srvs, seed=seed, retries=3)

    stop_evt = threading.Event()
    failures = []
    mismatches = []
    completed = [0]
    lock = threading.Lock()

    def load(tid):
        i = tid
        while not stop_evt.is_set():
            pi = i % len(prompts)
            prompt, max_new = prompts[pi]
            try:
                toks = list(router.generate(prompt, max_new,
                                            request_id="pstorm-%d-%d"
                                            % (tid, i)))
                if toks != reference[pi]:
                    with lock:
                        mismatches.append((pi, toks, reference[pi]))
                with lock:
                    completed[0] += 1
            except Exception as exc:
                with lock:
                    failures.append(repr(exc))
            i += load_threads

    deadline = time.monotonic() + timeout
    ok = True
    threads = [threading.Thread(target=load, args=(t,), daemon=True)
               for t in range(load_threads)]
    fault_spec = ("generation.prefix.lookup:ioerr=0.25;"
                  "generation.draft.verify:ioerr=0.25")
    try:
        with mx_faults.inject(fault_spec, seed=seed):
            for t in threads:
                t.start()
            while time.monotonic() < deadline and \
                    completed[0] < kill_after:
                time.sleep(0.02)
            print("chaos_run: killing replica r%d mid-storm (%d streams "
                  "done)" % (victim_idx, completed[0]),
                  file=sys.stderr, flush=True)
            srvs[victim_idx].stop(drain=False)
            target = completed[0] + load_threads * streams_per_thread
            while time.monotonic() < deadline and completed[0] < target:
                time.sleep(0.05)
            stop_evt.set()
            for t in threads:
                t.join(timeout=max(1.0, deadline - time.monotonic()))
    finally:
        stop_evt.set()
        router.close(stop_backends=True)

    if failures:
        print("chaos_run: %d streams failed (first: %s)"
              % (len(failures), failures[:3]), file=sys.stderr, flush=True)
        ok = False
    if mismatches:
        pi, got, want = mismatches[0]
        print("chaos_run: %d transcript mismatches (prompt %d: got %s "
              "want %s) — a degraded cache/draft path changed tokens"
              % (len(mismatches), pi, got, want),
              file=sys.stderr, flush=True)
        ok = False
    if completed[0] < kill_after + 1:
        print("chaos_run: storm too short (%d streams) to cover the kill"
              % completed[0], file=sys.stderr, flush=True)
        ok = False
    snaps = [e.pool.snapshot() for e in engines]
    hits = sum(s["prefix_hits"] for s in snaps)
    if not hits:
        print("chaos_run: prefix cache never hit — the storm did not "
              "exercise sharing", file=sys.stderr, flush=True)
        ok = False
    leaked = {i: s["total_refcount"] for i, s in enumerate(snaps)
              if s["total_refcount"]}
    dleaked = {i: e._draft_pool.total_refcount()
               for i, e in enumerate(engines)
               if e._draft_pool is not None
               and e._draft_pool.total_refcount()}
    if leaked or dleaked:
        print("chaos_run: leaked shared pages after shutdown "
              "(target %s draft %s)" % (leaked, dleaked),
              file=sys.stderr, flush=True)
        ok = False
    cold = sum(engines[i].cold_decode_runs()
               for i in range(replicas) if i != victim_idx)
    if cold:
        print("chaos_run: %d post-warmup decode recompiles on survivors"
              % cold, file=sys.stderr, flush=True)
        ok = False
    if ok:
        fb = sum(e.metrics.spec_fallbacks.value for e in engines)
        cow = sum(s["cow_copies"] for s in snaps)
        print("chaos_run: %d streams completed, 0 failed, 0 mismatches; "
              "%d prefix hits, %d COW splits, %d verify-fault fallbacks; "
              "refcounts drained to 0"
              % (completed[0], hits, cow, fb),
              file=sys.stderr, flush=True)
    return ok


def run_sparse_replay(seed, timeout=120.0):
    """Exactly-once probe for the sparse wire: one row-sparse push whose
    ACK the server drops (``kv.server.send:drop=1@#1``).  The client sees
    a dead connection and replays the request under the SAME idempotency
    token; the server's dedup window must recognize it and answer from
    the recorded reply without re-applying.  Passes when the retried run
    applied exactly one row push and its table rows are bit-identical to
    an uninterrupted control run."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if repo not in sys.path:
        sys.path.insert(0, repo)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import numpy as np

    from mxnet_tpu import faults
    from mxnet_tpu.kvstore_server import ServerClient, start_server

    rng = np.random.RandomState(seed)
    ids = np.unique(rng.randint(0, 1000, size=64)).astype(np.int64)
    vals = rng.randn(ids.size, 8).astype(np.float32)
    meta = {"num_rows": 1000, "row_shape": (8,), "init": ("zeros",),
            "dtype": "float32", "num_servers": 1, "server_index": 0}

    def one_run(drop_ack):
        srv = start_server(port=0)
        cli = ServerClient(*srv.addr)
        try:
            cli.init_table("emb", meta)
            if drop_ack:
                # installed only around the push so fire #1 on
                # kv.server.send is exactly the push_rows ACK
                with faults.inject("kv.server.send:drop=1@#1", seed):
                    cli.push_rows("emb", ids, vals)
            else:
                cli.push_rows("emb", ids, vals)
            applied = srv.applied_row_pushes
            rows = cli.pull_rows("emb", ids)
            return applied, rows
        finally:
            try:
                cli.stop_server()
            except Exception:
                pass
            cli.close()

    applied_r, rows_r = one_run(drop_ack=True)
    applied_c, rows_c = one_run(drop_ack=False)
    ok = True
    if applied_r != 1:
        print("chaos_run: sparse-replay applied %d row pushes after the "
              "dropped-ACK retry, expected exactly 1" % applied_r,
              file=sys.stderr, flush=True)
        ok = False
    if applied_c != 1:
        print("chaos_run: control run applied %d row pushes, expected 1"
              % applied_c, file=sys.stderr, flush=True)
        ok = False
    if rows_r.tobytes() != rows_c.tobytes():
        print("chaos_run: sparse-replay table rows diverge from the "
              "uninterrupted control run (replay was not exactly-once)",
              file=sys.stderr, flush=True)
        ok = False
    if ok:
        print("chaos_run: sparse-replay ok: dropped ACK, 1 application, "
              "%d rows bit-identical to control" % ids.size,
              file=sys.stderr, flush=True)
    return ok


def run_sdc_rollback(seed, timeout=120.0):
    """Silent-data-corruption containment, both halves of the guardian:

    Training half: the same seeded 2-epoch fit() runs twice — a control
    run, and a run with ``guardian.grad:bitflip@#N`` installed (the seed
    picks N, i.e. which gradient tensor of which step takes an exponent
    bit-flip).  The guardian must catch the poisoned step (the f32
    grad-norm square-sum overflows to inf), roll back to the last-good
    ring snapshot — params, updater state, framework PRNG, and the
    data-iterator cursor — and replay.  Passes when exactly one rollback
    fired and the final params are bit-identical to the control run.

    Fleet half: a kvstore server takes a clean dense push, then a
    NaN-poisoned push from another rank.  The poisoned push must be
    NACKed (typed NonFiniteGradientError at the client, counted per rank
    in mxtpu_kvsrv_rejected_pushes_total) and the stored value must stay
    bit-identical to the clean-only state — containment, not detection
    after the fact."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if repo not in sys.path:
        sys.path.insert(0, repo)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    env = {"MXNET_FUSED_STEP": "0",     # corruption rewrites host grad
                                        # buffers, which forces the eager
                                        # path — the control run must
                                        # match it for bit-identity
           "MXNET_GUARDIAN": "1",
           "MXNET_GUARDIAN_SKIP_MAX": "0",      # straight to rollback
           "MXNET_GUARDIAN_REWARM_STEPS": "0",
           "MXNET_GUARDIAN_RING": "2",
           "MXNET_GUARDIAN_SNAPSHOT_EVERY": "4"}
    saved = {k: os.environ.get(k) for k in env}
    os.environ.update(env)
    try:
        import numpy as np

        import mxnet_tpu as mx
        from mxnet_tpu import faults, guardian, telemetry
        from mxnet_tpu.kvstore_server import (NonFiniteGradientError,
                                              ServerClient, start_server)

        # the env var only matters at import; in-process (pytest) the
        # module is long imported, so flip the gate directly
        guardian.enable()

        def one_fit(spec):
            guardian.reset_stats()
            if spec:
                faults.install(faults.FaultPlan(spec, seed=seed))
            else:
                faults.uninstall()
            try:
                data = mx.sym.Variable("data")
                net = mx.sym.FullyConnected(data, name="fc1",
                                            num_hidden=16)
                net = mx.sym.Activation(net, name="relu1",
                                        act_type="relu")
                net = mx.sym.FullyConnected(net, name="fc2", num_hidden=4)
                net = mx.sym.SoftmaxOutput(net, name="softmax")
                mod = mx.mod.Module(net, context=mx.cpu())
                mx.random.seed(3)
                np.random.seed(3)
                rng = np.random.RandomState(7)
                x = rng.randn(64, 10).astype(np.float32)
                y = rng.randint(0, 4, (64,)).astype(np.float32)
                it = mx.io.NDArrayIter(x, y, batch_size=8, shuffle=True,
                                       label_name="softmax_label")
                mod.fit(it, num_epoch=2, optimizer="sgd",
                        optimizer_params={"learning_rate": 0.05,
                                          "momentum": 0.9},
                        initializer=mx.init.Xavier(), eval_metric="acc")
                args, _ = mod.get_params()
                return ({k: v.asnumpy() for k, v in args.items()},
                        guardian.stats())
            finally:
                faults.uninstall()

        # 16 steps x 4 gradient tensors -> 64 corruption polls; the seed
        # picks which one flips (any step of either epoch), and #1 — the
        # very first gradient, before the spike detector has any history
        # — is always exercised too (the acceptance-pinned worst case)
        n = 1 + np.random.RandomState(seed).randint(64)
        clean, st_clean = one_fit(None)

        ok = True
        if st_clean["rollbacks"] != 0 or st_clean["anomalies"] != 0:
            print("chaos_run: sdc-rollback control run tripped the "
                  "guardian: %r" % (st_clean,), file=sys.stderr, flush=True)
            ok = False
        for idx in sorted({1, n}):
            inj, st_inj = one_fit("guardian.grad:bitflip@#%d" % idx)
            if st_inj["anomalies"] < 1 or st_inj["rollbacks"] != 1:
                print("chaos_run: sdc-rollback injected run (bitflip@#%d) "
                      "expected 1 rollback, got %r" % (idx, st_inj),
                      file=sys.stderr, flush=True)
                ok = False
            diverged = [k for k in clean
                        if clean[k].tobytes() != inj[k].tobytes()]
            if diverged:
                print("chaos_run: sdc-rollback bitflip@#%d replay diverged "
                      "from control in %s" % (idx, ", ".join(sorted(diverged))),
                      file=sys.stderr, flush=True)
                ok = False
        if ok:
            print("chaos_run: sdc-rollback ok: bitflip@#{1,%d} detected, "
                  "1 rollback each, replays bit-identical to control" % n,
                  file=sys.stderr, flush=True)

        # ---- fleet half: server-side NACK containment
        telemetry.enable(trace=False)
        srv = start_server(port=0)
        cli = ServerClient(*srv.addr)
        try:
            cli.init(0, np.zeros(8, dtype=np.float32))
            good = np.random.RandomState(seed + 1).randn(8) \
                .astype(np.float32)
            cli.push(0, good, rank=0)
            want = cli.pull(0).tobytes()
            # the registry is process-global: under --seeds sweeps the
            # counter carries over from earlier iterations, so assert
            # the delta, not the absolute count
            rej0 = telemetry.registry().snapshot().get(
                "mxtpu_kvsrv_rejected_pushes_total", {}).get("3", 0)
            bad = good.copy()
            bad[int(seed) % 8] = np.nan
            try:
                cli.push(0, bad, rank=3)
                print("chaos_run: sdc-rollback poisoned push was ACKed",
                      file=sys.stderr, flush=True)
                ok = False
            except NonFiniteGradientError:
                pass
            if cli.pull(0).tobytes() != want:
                print("chaos_run: sdc-rollback NACKed push mutated the "
                      "store", file=sys.stderr, flush=True)
                ok = False
            rej = telemetry.registry().snapshot().get(
                "mxtpu_kvsrv_rejected_pushes_total", {})
            if srv.rejected_pushes != 1 or rej.get("3", 0) - rej0 != 1:
                print("chaos_run: sdc-rollback rejected-push accounting "
                      "off: server=%d telemetry=%r"
                      % (srv.rejected_pushes, rej),
                      file=sys.stderr, flush=True)
                ok = False
            elif ok:
                print("chaos_run: sdc-rollback ok: poisoned push NACKed, "
                      "store bit-identical, rank 3 counted",
                      file=sys.stderr, flush=True)
        finally:
            try:
                cli.stop_server()
            except Exception:
                pass
            cli.close()
            telemetry.disable()
        return ok
    finally:
        try:
            from mxnet_tpu import guardian as _g
            _g.disable()
        except Exception:
            pass
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def run_tenant_storm(seed, timeout=120.0, good_threads=2):
    """Multi-tenant platform probe, in-process: a FrontDoor over a
    ModelManager serves three models on a pool with room for two while
    one tenant ('storm') floods its model in a tight loop and its
    neighbours ('good0'/'good1') run steady interactive load.  Mid-storm
    the victim model is paged out, then hard-killed mid-migration (its
    server stopped out from under the router without deregistration) —
    each time, demand paging must fault it back in WARM from its AOT
    bundle.  Passes when the storm tenant was shed at the door (429s
    with Retry-After), the good tenants saw ZERO quota sheds and zero
    end-to-end failures, and every post-storm fault-in served with
    ``cold_bucket_runs() == 0``."""
    import shutil
    import tempfile
    import threading
    import time

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if repo not in sys.path:
        sys.path.insert(0, repo)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu.platform import (DevicePool, FrontDoor, ModelManager,
                                    ModelSpec, TenantQuotaExceededError)

    tmp = tempfile.mkdtemp(prefix="chaos-tenantstorm-")
    envs = {"MXNET_COMPILE_CACHE_DIR": os.path.join(tmp, "cache"),
            "MXNET_PLATFORM_MIN_RESIDENT_S": "0"}
    prev = {k: os.environ.get(k) for k in envs}
    os.environ.update(envs)

    in_dim = 6
    rng = np.random.RandomState(seed)
    specs = []
    for i, (name, tenant) in enumerate((("victim", "storm"),
                                        ("good-a", "good0"),
                                        ("good-b", "good1"))):
        hid = 3 + i  # distinct programs: no cross-model cache riding
        net = mx.sym.FullyConnected(mx.sym.Variable("data"),
                                    num_hidden=hid, name="fc")
        params = {"fc_weight": mx.nd.array(
                      rng.randn(hid, in_dim).astype(np.float32)),
                  "fc_bias": mx.nd.array(rng.randn(hid)
                                         .astype(np.float32))}
        prefix = os.path.join(tmp, name)
        mx.model.save_checkpoint(prefix, 1, net, params, {})
        specs.append(ModelSpec(
            name, prefix, 1, {"data": (1, in_dim)}, tenant=tenant,
            param_bytes=1000,
            server_kwargs={"buckets": (1,), "max_wait_us": 1000,
                           "max_queue": 256}))

    total = specs[0].footprint()["total"]
    pool = DevicePool(num_devices=1,
                      bytes_per_device=int(2 * total * 1.2))
    mgr = ModelManager(pool)
    for s in specs:
        mgr.register_model(s)
    door = FrontDoor(mgr)
    x = np.zeros(in_dim, np.float32)
    stop_evt = threading.Event()
    good_failures = []
    good_served = [0]
    storm_stats = {"admitted": 0, "shed": 0}
    deadline = time.monotonic() + timeout
    ok = True

    def good_load(tid):
        model = ("good-a", "good-b")[tid % 2]
        tenant = ("good0", "good1")[tid % 2]
        while not stop_evt.is_set():
            t_req = time.monotonic() + 10.0
            last = None
            while time.monotonic() < min(t_req, deadline):
                try:
                    door.predict(model, tenant=tenant, deadline_ms=5000,
                                 data=x)
                    good_served[0] += 1
                    last = None
                    break
                except TenantQuotaExceededError as exc:
                    # a neighbour's flood must NEVER shed us — fatal
                    good_failures.append("QUOTA:%r" % exc)
                    return
                except Exception as exc:  # dead replica mid-kill: retry
                    last = exc
                    time.sleep(0.02)
            if last is not None:
                good_failures.append(repr(last))
                return
            time.sleep(0.02)

    def storm_load():
        while not stop_evt.is_set():
            try:
                door.predict("victim", tenant="storm", deadline_ms=5000,
                             data=x)
                storm_stats["admitted"] += 1
            except TenantQuotaExceededError as exc:
                if exc.retry_after <= 0:
                    good_failures.append("storm retry_after <= 0")
                storm_stats["shed"] += 1
            except Exception:
                pass  # storm tenant gets no service guarantees

    threads = [threading.Thread(target=good_load, args=(t,), daemon=True)
               for t in range(good_threads)]
    threads.append(threading.Thread(target=storm_load, daemon=True))
    try:
        # the storm tenant is rate-limited; its neighbours are not
        door.quotas.set_quota("storm", rate=25.0, burst=5.0)
        for name, d in (("victim", 5.0), ("good-a", 4.0)):
            mgr.record_demand(name, d)
        mgr.replan()  # victim + good-a resident; good-b demand-pages in
        for t in threads:
            t.start()
        time.sleep(1.0)

        # chaos 1: the victim model is paged out mid-storm — requests
        # in flight drain, the next one demand-pages it back in warm
        mgr.page_out("victim")
        print("chaos_run: victim paged out mid-storm",
              file=sys.stderr, flush=True)
        time.sleep(1.0)

        # chaos 2: hard-kill mid-migration — the victim's server dies
        # out from under the router (no dereg, no drain), exactly what
        # a preempted device looks like; the platform must recover it
        srv = mgr.server_for("victim")
        if srv is not None:
            srv.stop(drain=False)
        mgr.page_out("victim")  # reconcile the corpse
        print("chaos_run: victim replica hard-killed mid-migration",
              file=sys.stderr, flush=True)
        time.sleep(1.5)
        # in-quota storm traffic must have demand-paged the victim back
        # in — WARM, from the bundle its first page-out wrote
        srv = mgr.server_for("victim")
        victim_cold_runs = None if srv is None else srv.cold_bucket_runs()
        stop_evt.set()
        for t in threads:
            t.join(timeout=max(1.0, deadline - time.monotonic()))
    finally:
        stop_evt.set()
        door.close()
        mgr.close()
        for k, v in prev.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v

    if good_failures:
        print("chaos_run: good-tenant violations: %s"
              % good_failures[:3], file=sys.stderr, flush=True)
        ok = False
    snap = door.quotas.snapshot()
    for tenant in ("good0", "good1"):
        if snap.get(tenant, {}).get("shed", 0):
            print("chaos_run: tenant %s was shed by the storm" % tenant,
                  file=sys.stderr, flush=True)
            ok = False
    if not storm_stats["shed"]:
        print("chaos_run: storm tenant was never shed",
              file=sys.stderr, flush=True)
        ok = False
    if not storm_stats["admitted"]:
        print("chaos_run: storm tenant never got its in-quota share",
              file=sys.stderr, flush=True)
        ok = False
    if victim_cold_runs != 0:
        print("chaos_run: victim's post-kill fault-in was not warm "
              "(cold_bucket_runs=%r)" % (victim_cold_runs,),
              file=sys.stderr, flush=True)
        ok = False
    if good_served[0] < 20:
        print("chaos_run: good tenants served only %d requests"
              % good_served[0], file=sys.stderr, flush=True)
        ok = False
    # every fault-in after the first left/loaded an AOT bundle: the
    # recovery path must have been warm (metrics survive close())
    fault_ins = sum(
        int(float(line.rsplit(None, 1)[1]))
        for line in mgr.metrics.render_prometheus().splitlines()
        if line.startswith("mxtpu_platform_fault_ins_total{"))
    if fault_ins < 3:
        print("chaos_run: expected >= 3 victim fault-ins, saw %d"
              % fault_ins, file=sys.stderr, flush=True)
        ok = False
    if ok:
        print("chaos_run: tenant-storm ok: good tenants served %d with "
              "0 sheds and 0 failures through page-out + hard-kill; "
              "storm admitted %d, shed %d; %d fault-ins"
              % (good_served[0], storm_stats["admitted"],
                 storm_stats["shed"], fault_ins),
              file=sys.stderr, flush=True)
        shutil.rmtree(tmp, ignore_errors=True)
    else:
        print("chaos_run: artifacts kept at %s" % tmp,
              file=sys.stderr, flush=True)
    return ok


def run_host_loss(seed, timeout=120.0, stream_threads=3):
    """Failure-domain survival probe, in-process: a FrontDoor platform
    serves three tenants on 2 hosts x 2 devices — 'chat' (generate SLO,
    2 replicas spread across hosts), 'gold' (interactive), 'bulk'
    (batch) — and every replica on one host is killed mid-stream and
    mid-fault-in (heartbeats stop WITHOUT deregistration: only the
    health plane's probe can discover the loss).  The degradation
    ladder must then (1) reap the corpses and re-fault the evicted
    interactive model WARM onto the survivors, (2) brown out the batch
    class (503 + Retry-After) while capacity is short, (3) keep every
    live chat stream bit-identical to the single-engine reference via
    mid-stream failover.  Passes when chat saw zero failures and zero
    transcript mismatches with >= 1 mid-stream resume, gold saw zero
    hard failures (its fault-in-window 503s carried a positive
    Retry-After) and recovered with zero cold-bucket runs, bulk was
    shed by the brownout, the plan generation advanced, every surviving
    placement sits on an alive device, and resident_bytes drops to
    zero at close.  Returns a summary dict (``ok`` + per-tenant failure
    counts) that main() folds into the machine-readable summary JSON."""
    import shutil
    import tempfile
    import threading
    import time

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if repo not in sys.path:
        sys.path.insert(0, repo)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import faults, telemetry
    from mxnet_tpu.platform import (BrownoutError, DevicePool,
                                    FaultInProgressError, FrontDoor,
                                    HealthPlane, ModelManager, ModelSpec)
    from mxnet_tpu.serving.batcher import ServerClosedError
    from mxnet_tpu.serving.registry import ReplicaRegistry
    from mxnet_tpu.serving.router import NoReplicaAvailableError

    tmp = tempfile.mkdtemp(prefix="chaos-hostloss-")
    envs = {"MXNET_COMPILE_CACHE_DIR": os.path.join(tmp, "cache"),
            "MXNET_PLATFORM_MIN_RESIDENT_S": "0",
            "MXNET_PLATFORM_DRAIN_MS": "2000",
            "MXNET_SERVING_REGISTRY_HEARTBEAT_MS": "25"}
    prev = {k: os.environ.get(k) for k in envs}
    os.environ.update(envs)
    telemetry.enable()

    V, S, in_dim = 32, 16, 4
    rng = np.random.RandomState(seed)
    # prefill buckets must cover prompt + emitted: a mid-stream resume
    # re-prefills the whole transcript so far
    gspec = dict(vocab_size=V, num_layers=1, num_heads=2, hidden=16,
                 max_seq_len=S, lane_buckets=(1, 2), page_size=4,
                 num_pages=16, prefill_len_buckets=(8, 16))
    lm = mx.models.get_transformer_lm(vocab_size=V, num_layers=1,
                                      num_heads=2, hidden=16, seq_len=S)
    arg_shapes, _, _ = lm.infer_shape(data=(1, S), softmax_label=(1, S))
    lm_params = {
        name: mx.nd.array(rng.randn(*shp).astype(np.float32) * 0.05)
        for name, shp in zip(lm.list_arguments(), arg_shapes)
        if name not in ("data", "softmax_label")}
    lm_prefix = os.path.join(tmp, "chat")
    mx.model.save_checkpoint(lm_prefix, 1, lm, lm_params, {})
    fc_prefix = {}
    for name in ("gold", "bulk"):
        net = mx.sym.FullyConnected(mx.sym.Variable("data"),
                                    num_hidden=2, name="fc")
        params = {"fc_weight": mx.nd.array(
                      rng.randn(2, in_dim).astype(np.float32)),
                  "fc_bias": mx.nd.array(rng.randn(2).astype(np.float32))}
        fc_prefix[name] = os.path.join(tmp, name)
        mx.model.save_checkpoint(fc_prefix[name], 1, net, params, {})

    # greedy decode is deterministic: one reference engine's transcript
    # is THE correct answer for every (prompt, max_new) the storm sends
    ref_engine = mx.generation.DecodeEngine(lm_params, **gspec)
    prompts = []
    for i in range(6):
        plen = 2 + int(rng.randint(0, 6))
        prompts.append(([int(t) for t in rng.randint(0, V, size=plen)],
                        4 + int(rng.randint(0, 6))))
    reference = {i: ref_engine.generate(p, n)
                 for i, (p, n) in enumerate(prompts)}
    ref_engine.stop()

    specs = [
        ModelSpec("chat", lm_prefix, 1,
                  {"data": (1, S), "softmax_label": (1, S)},
                  tenant="chat", slo="generate", replicas=2,
                  param_bytes=1000, generator_spec=dict(gspec),
                  server_kwargs={"buckets": (1,), "max_wait_us": 1000}),
        ModelSpec("gold", fc_prefix["gold"], 1, {"data": (1, in_dim)},
                  tenant="gold", slo="interactive", param_bytes=7554,
                  server_kwargs={"buckets": (1,), "max_wait_us": 1000}),
        ModelSpec("bulk", fc_prefix["bulk"], 1, {"data": (1, in_dim)},
                  tenant="bulk", slo="batch", param_bytes=7554,
                  server_kwargs={"buckets": (1,), "max_wait_us": 1000}),
    ]
    totals = {s.name: s.footprint()["total"] for s in specs}
    if len(set(totals.values())) != 1:
        print("chaos_run: footprint mismatch %r" % (totals,),
              file=sys.stderr, flush=True)
        return {"ok": False, "notes": ["footprint mismatch"]}
    # one model-replica per device, exactly — and pin the declared
    # footprints: live cost-analysis refinement would re-scale the toy
    # byte budget mid-run
    orig_observe = ModelSpec.observe_exec_bytes
    ModelSpec.observe_exec_bytes = lambda self, nbytes: None

    pool = DevicePool(num_devices=4,
                      bytes_per_device=totals["chat"] + 1,
                      devices_per_host=2)
    reg = ReplicaRegistry(ttl_ms=400)
    mgr = ModelManager(pool, registry=reg)
    hp = mgr.attach_health(HealthPlane(pool, registry=reg, probe_fails=2))
    for s in specs:
        mgr.register_model(s)
    door = FrontDoor(mgr)
    # delayed fault-ins hold every fault-in window open ~0.4s so the
    # kill provably lands mid-fault-in and the door's 503s are
    # observable from the gold tenant's thread
    faults.install(faults.FaultPlan("platform.fault_in:delay=1@0.4",
                                    seed))

    counts = {"chat_ok": 0, "chat_fail": 0, "mismatch": 0,
              "gold_ok": 0, "gold_fail": 0, "gold_503": 0,
              "bulk_ok": 0, "bulk_shed": 0, "bulk_fail": 0}
    errs = []
    lock = threading.Lock()
    stop_evt = threading.Event()
    deadline = time.monotonic() + timeout
    x = np.zeros(in_dim, np.float32)

    def chat_load(tid):
        i = tid
        while not stop_evt.is_set() and time.monotonic() < deadline:
            pi = i % len(prompts)
            prompt, max_new = prompts[pi]
            try:
                toks = list(door.generate("chat", prompt, max_new,
                                          tenant="chat",
                                          deadline_ms=10_000))
                with lock:
                    if toks != reference[pi]:
                        counts["mismatch"] += 1
                    else:
                        counts["chat_ok"] += 1
            except (ServerClosedError, NoReplicaAvailableError,
                    FaultInProgressError):
                time.sleep(0.02)  # mid-reap race window: retryable
            except Exception as exc:
                with lock:
                    counts["chat_fail"] += 1
                    errs.append("chat: %r" % (exc,))
                time.sleep(0.05)
            i += stream_threads

    def gold_load():
        while not stop_evt.is_set() and time.monotonic() < deadline:
            try:
                door.predict("gold", tenant="gold", deadline_ms=5000,
                             data=x)
                with lock:
                    counts["gold_ok"] += 1
            except (FaultInProgressError, BrownoutError) as exc:
                with lock:
                    counts["gold_503"] += 1
                    if not exc.retry_after > 0:
                        counts["gold_fail"] += 1
                        errs.append("gold: 503 with retry_after=%r"
                                    % (exc.retry_after,))
                time.sleep(min(exc.retry_after, 0.2))
            except (ServerClosedError, NoReplicaAvailableError):
                time.sleep(0.02)  # mid-reap race window: retryable
            except Exception as exc:
                with lock:
                    counts["gold_fail"] += 1
                    errs.append("gold: %r" % (exc,))
            time.sleep(0.01)

    def bulk_load():
        while not stop_evt.is_set() and time.monotonic() < deadline:
            try:
                door.predict("bulk", tenant="bulk", slo="batch",
                             deadline_ms=5000, data=x)
                with lock:
                    counts["bulk_ok"] += 1
            except BrownoutError as exc:
                with lock:
                    counts["bulk_shed"] += 1
                    if not exc.retry_after > 0:
                        counts["bulk_fail"] += 1
                        errs.append("bulk: 503 with retry_after=%r"
                                    % (exc.retry_after,))
                time.sleep(0.05)
            except (FaultInProgressError, ServerClosedError,
                    NoReplicaAvailableError):
                time.sleep(0.02)
            except Exception as exc:
                with lock:
                    counts["bulk_fail"] += 1
                    errs.append("bulk: %r" % (exc,))
            time.sleep(0.02)

    ok = True
    notes = []

    def fail(msg):
        nonlocal ok
        ok = False
        notes.append(msg)
        print("chaos_run: host-loss: %s" % msg, file=sys.stderr,
              flush=True)

    gen1 = resumes = gold_cold = 0
    victim_dom = -1
    # two gold clients: during recovery one gets "queued" (blocks inside
    # the ladder-raced fault-in), the other observes the open window and
    # must get the honest 503 + Retry-After
    threads = ([threading.Thread(target=chat_load, args=(t,), daemon=True)
                for t in range(stream_threads)]
               + [threading.Thread(target=gold_load, daemon=True),
                  threading.Thread(target=gold_load, daemon=True),
                  threading.Thread(target=bulk_load, daemon=True)])
    try:
        for name, d in (("chat", 9.0), ("gold", 5.0), ("bulk", 1.0)):
            mgr.record_demand(name, d)
        mgr.replan()
        placed = mgr.replica_placement()
        doms = {pool.domain_of(d) for d in placed.get("chat", {}).values()}
        if doms != {0, 1}:
            fail("chat replicas not spread across hosts: %r" % (placed,))
        gen0 = mgr.plan_generation()
        # gold's host is the victim: it holds gold plus one chat replica
        victim_dom = pool.domain_of(placed["gold"][0])
        victims = [(n, i) for n, reps in placed.items()
                   for i, d in reps.items()
                   if pool.domain_of(d) == victim_dom]
        kill_after = 2 + seed % 3  # chat streams completed pre-kill
        print("chaos_run: host-loss seed %d: host %d dies (%s) after %d "
              "streams" % (seed, victim_dom,
                           ",".join("%s/r%d" % v for v in victims),
                           kill_after),
              file=sys.stderr, flush=True)
        for t in threads:
            t.start()
        while time.monotonic() < deadline and counts["chat_ok"] < kill_after:
            time.sleep(0.02)
        # "mid-stream" must be literal: hold the kill until the victim
        # chat replica has a generate stream actually in flight
        chat_vic = next(i for n, i in victims if n == "chat")
        vic_srv = mgr._servers["chat"][chat_vic]
        while time.monotonic() < deadline and \
                vic_srv._generator.active_lanes() < 1:
            time.sleep(0.002)
        pre_kill = dict(counts)
        for n, i in victims:
            mgr.kill_replica(n, replica=i)
        # only the probe can discover the loss: corpses TTL out of the
        # registry, K consecutive misses flip the domain, and the
        # ladder runs inline right here
        while time.monotonic() < deadline and \
                victim_dom not in hp.dead_domains():
            hp.probe()
            time.sleep(0.05)
        if victim_dom not in hp.dead_domains():
            fail("health plane never declared host %d dead" % victim_dom)
        while time.monotonic() < deadline and \
                mgr.server_for("gold") is None:
            time.sleep(0.05)
        srv = mgr.server_for("gold")
        if srv is None:
            fail("gold never re-faulted onto a survivor")
        else:
            gold_cold = srv.cold_bucket_runs()
            if gold_cold != 0:
                fail("gold re-fault was cold (cold_bucket_runs=%d)"
                     % gold_cold)
        # run the degraded storm until every class shows its verdict
        settle = time.monotonic() + 8.0
        while time.monotonic() < min(deadline, settle) and not (
                counts["chat_ok"] > pre_kill["chat_ok"] + stream_threads
                and counts["gold_ok"] > pre_kill["gold_ok"]
                and counts["bulk_shed"] > 0):
            time.sleep(0.05)
        stop_evt.set()
        for t in threads:
            t.join(timeout=30)
        if any(t.is_alive() for t in threads):
            fail("load threads failed to stop")

        gen1 = mgr.plan_generation()
        resumes = door.router_for("chat").metrics.snapshot()[
            "stream_resumes"]
        if not gen1 > gen0:
            fail("plan generation did not advance (%d -> %d)"
                 % (gen0, gen1))
        if counts["chat_fail"] or counts["mismatch"]:
            fail("chat streams broke: %d failures, %d mismatches"
                 % (counts["chat_fail"], counts["mismatch"]))
        if counts["chat_ok"] <= pre_kill["chat_ok"] + stream_threads:
            fail("chat barely served post-kill (%d -> %d)"
                 % (pre_kill["chat_ok"], counts["chat_ok"]))
        if resumes < 1:
            fail("no mid-stream resume was exercised")
        if counts["gold_fail"]:
            fail("gold saw %d hard failures" % counts["gold_fail"])
        if counts["gold_ok"] <= pre_kill["gold_ok"]:
            fail("gold never served after the ladder ran")
        if counts["gold_503"] < 1:
            fail("gold never saw the fault-in-window 503")
        if counts["bulk_fail"]:
            fail("bulk saw %d hard failures" % counts["bulk_fail"])
        if counts["bulk_shed"] < 1:
            fail("bulk was never browned out")
        b = door.quotas.brownout()
        if b is None:
            fail("no brownout active after capacity loss")
        if mgr.server_for("bulk") is not None:
            fail("bulk still resident on degraded capacity")
        bad = [(n, d) for n, reps in mgr.replica_placement().items()
               for d in reps.values()
               if pool.domain_of(d) == victim_dom]
        if bad:
            fail("placements still on the dead host: %r" % (bad,))
    finally:
        stop_evt.set()
        faults.uninstall()
        ModelSpec.observe_exec_bytes = orig_observe
        try:
            door.close()
            mgr.close()
        finally:
            hp.close()
            reg.close()
            for k, v in prev.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v
    if mgr.resident_bytes() != 0:
        fail("resident_bytes=%d after close" % mgr.resident_bytes())
    for e in errs[:5]:
        print("chaos_run: host-loss error: %s" % e, file=sys.stderr,
              flush=True)
    if ok:
        print("chaos_run: host-loss ok: %d streams (0 failed, 0 "
              "mismatched, %d resumes), gold served %d with %d honest "
              "503s and a warm re-fault, bulk shed %d, plan gen %d"
              % (counts["chat_ok"], resumes, counts["gold_ok"],
                 counts["gold_503"], counts["bulk_shed"], gen1),
              file=sys.stderr, flush=True)
        shutil.rmtree(tmp, ignore_errors=True)
    else:
        print("chaos_run: artifacts kept at %s" % tmp,
              file=sys.stderr, flush=True)
    return {"ok": ok, "victim_domain": victim_dom,
            "streams": counts["chat_ok"], "stream_resumes": resumes,
            "transcript_mismatches": counts["mismatch"],
            "plan_generation": gen1,
            "tenant_failures": {"chat": counts["chat_fail"],
                                "gold": counts["gold_fail"],
                                "bulk": counts["bulk_fail"]},
            "gold_503s": counts["gold_503"],
            "bulk_shed": counts["bulk_shed"], "notes": notes}


_SCENARIOS = {"membership-churn": run_membership_churn,
              "serving-failover": run_serving_failover,
              "flash-crowd": run_flash_crowd,
              "decode-storm": run_decode_storm,
              "prefix-storm": run_prefix_storm,
              "sparse-replay": run_sparse_replay,
              "sdc-rollback": run_sdc_rollback,
              "tenant-storm": run_tenant_storm,
              "host-loss": run_host_loss}


def main():
    parser = argparse.ArgumentParser(
        description="Run a command under a deterministic fault schedule",
        usage="chaos_run.py (--spec SPEC -- command ... | --scenario NAME) "
              "(--seed N | --seeds A:B) [--timeout S]")
    parser.add_argument("--spec", default=None,
                        help="fault spec, e.g. 'kv.client.*:drop=0.3'")
    parser.add_argument("--scenario", choices=sorted(_SCENARIOS),
                        default=None,
                        help="run a built-in end-to-end scenario instead "
                             "of a command")
    parser.add_argument("--seed", type=int, default=None,
                        help="replay one seed")
    parser.add_argument("--seeds", type=str, default=None, metavar="A:B",
                        help="sweep seeds A..B-1, report pass/fail each")
    parser.add_argument("--timeout", type=float, default=None,
                        help="per-run timeout in seconds")
    parser.add_argument("--summary-json", default=None, metavar="PATH",
                        help="also write the scenario summary JSON to "
                             "this file (it always goes to stdout)")
    parser.add_argument("command", nargs=argparse.REMAINDER)
    args = parser.parse_args()
    command = args.command
    if command and command[0] == "--":
        command = command[1:]
    if (args.seed is None) == (args.seeds is None):
        parser.error("exactly one of --seed / --seeds is required")

    if args.seeds is not None:
        a, _, b = args.seeds.partition(":")
        seeds = range(int(a), int(b))
    else:
        seeds = [args.seed]

    if args.scenario is not None:
        if command or args.spec:
            parser.error("--scenario runs its own processes and builds its "
                         "own rank-gated spec; drop --spec and the command")
        scenario = _SCENARIOS[args.scenario]
        failures = []
        runs = []
        for seed in seeds:
            try:
                res = scenario(seed, timeout=args.timeout or 120.0)
            except Exception as exc:
                print("chaos_run: scenario %s seed %d CRASHED: %r"
                      % (args.scenario, seed, exc),
                      file=sys.stderr, flush=True)
                res = {"ok": False, "error": repr(exc)}
            # scenarios return a bare bool or a summary dict ({"ok":
            # bool, ...extra fields}) folded into the summary JSON
            if isinstance(res, dict):
                ok = bool(res.get("ok"))
                extra = {k: v for k, v in res.items() if k != "ok"}
            else:
                ok, extra = bool(res), {}
            runs.append(dict({"seed": seed, "ok": ok}, **extra))
            print("chaos_run: scenario %s seed %d -> %s"
                  % (args.scenario, seed, "ok" if ok else "FAILED"),
                  file=sys.stderr, flush=True)
            if not ok:
                failures.append(seed)
        # machine-readable verdict: one JSON object on stdout (all the
        # human chatter goes to stderr), optionally mirrored to a file
        summary = {"scenario": args.scenario, "seeds": list(seeds),
                   "ok": not failures, "failing_seeds": failures,
                   "runs": runs}
        line = json.dumps(summary, sort_keys=True, default=str)
        print(line, flush=True)
        if args.summary_json:
            with open(args.summary_json, "w") as fh:
                fh.write(line + "\n")
        if failures:
            print("chaos_run: failing seeds: %s  (replay one with --seed N)"
                  % failures, file=sys.stderr, flush=True)
            sys.exit(1)
        return

    if not command:
        parser.error("no command given (put it after --)")

    # validate the spec before burning any runtime on it
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from mxnet_tpu.faults import parse_spec

    if not args.spec:
        parser.error("--spec is required when running a command")
    parse_spec(args.spec)

    failures = []
    for seed in seeds:
        env = dict(os.environ,
                   MXNET_FAULTS_SPEC=args.spec,
                   MXNET_FAULTS_SEED=str(seed))
        print("chaos_run: seed %d, spec %r" % (seed, args.spec),
              file=sys.stderr, flush=True)
        try:
            rc = subprocess.run(command, env=env,
                                timeout=args.timeout).returncode
        except subprocess.TimeoutExpired:
            rc = -1
            print("chaos_run: seed %d TIMED OUT" % seed,
                  file=sys.stderr, flush=True)
        status = "ok" if rc == 0 else "FAILED rc=%d" % rc
        print("chaos_run: seed %d -> %s" % (seed, status),
              file=sys.stderr, flush=True)
        if rc != 0:
            failures.append(seed)
    if failures:
        print("chaos_run: failing seeds: %s  (replay one with --seed N)"
              % failures, file=sys.stderr, flush=True)
        sys.exit(1)


if __name__ == "__main__":
    main()

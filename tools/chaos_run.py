#!/usr/bin/env python
"""Replay (or sweep) fault-injection seeds against a training command.

A chaos test that fails reports its (spec, seed); this tool reruns the
exact same fault schedule — the FaultPlan decision for the N-th matching
call is a pure function of (spec, seed, N), so the failure reproduces
outside pytest where it can be debugged:

    # replay the failing schedule
    python tools/chaos_run.py --spec "kv.client.*:drop=0.3" --seed 7 -- \\
        python tools/launch.py -n 2 -s 1 python train.py

    # sweep seeds 0..19 hunting for a schedule that breaks the job
    python tools/chaos_run.py --spec "kv.client.*:drop=0.3" --seeds 0:20 -- \\
        python train.py

The spec/seed reach the command (and every child it spawns, e.g. via
tools/launch.py) through MXNET_FAULTS_SPEC / MXNET_FAULTS_SEED, which
mxnet_tpu.faults reads at import.  See docs/how_to/fault_tolerance.md
for the spec grammar.
"""
from __future__ import annotations

import argparse
import os
import subprocess
import sys


def main():
    parser = argparse.ArgumentParser(
        description="Run a command under a deterministic fault schedule",
        usage="chaos_run.py --spec SPEC (--seed N | --seeds A:B) "
              "[--timeout S] -- command ...")
    parser.add_argument("--spec", required=True,
                        help="fault spec, e.g. 'kv.client.*:drop=0.3'")
    parser.add_argument("--seed", type=int, default=None,
                        help="replay one seed")
    parser.add_argument("--seeds", type=str, default=None, metavar="A:B",
                        help="sweep seeds A..B-1, report pass/fail each")
    parser.add_argument("--timeout", type=float, default=None,
                        help="per-run timeout in seconds")
    parser.add_argument("command", nargs=argparse.REMAINDER)
    args = parser.parse_args()
    command = args.command
    if command and command[0] == "--":
        command = command[1:]
    if not command:
        parser.error("no command given (put it after --)")
    if (args.seed is None) == (args.seeds is None):
        parser.error("exactly one of --seed / --seeds is required")

    if args.seeds is not None:
        a, _, b = args.seeds.partition(":")
        seeds = range(int(a), int(b))
    else:
        seeds = [args.seed]

    # validate the spec before burning any runtime on it
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from mxnet_tpu.faults import parse_spec

    parse_spec(args.spec)

    failures = []
    for seed in seeds:
        env = dict(os.environ,
                   MXNET_FAULTS_SPEC=args.spec,
                   MXNET_FAULTS_SEED=str(seed))
        print("chaos_run: seed %d, spec %r" % (seed, args.spec),
              file=sys.stderr, flush=True)
        try:
            rc = subprocess.run(command, env=env,
                                timeout=args.timeout).returncode
        except subprocess.TimeoutExpired:
            rc = -1
            print("chaos_run: seed %d TIMED OUT" % seed,
                  file=sys.stderr, flush=True)
        status = "ok" if rc == 0 else "FAILED rc=%d" % rc
        print("chaos_run: seed %d -> %s" % (seed, status),
              file=sys.stderr, flush=True)
        if rc != 0:
            failures.append(seed)
    if failures:
        print("chaos_run: failing seeds: %s  (replay one with --seed N)"
              % failures, file=sys.stderr, flush=True)
        sys.exit(1)


if __name__ == "__main__":
    main()

"""CLI: Caffe deploy.prototxt + .caffemodel → mx checkpoint.

Reference parity: tools/caffe_converter/run.sh convert_model.py —
``python tools/caffe_converter.py deploy.prototxt net.caffemodel out``
writes ``out-symbol.json`` + ``out-0000.params`` loadable with
``mx.model.load_checkpoint("out", 0)``.
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("prototxt")
    ap.add_argument("caffemodel")
    ap.add_argument("prefix", help="output checkpoint prefix")
    cli = ap.parse_args()

    import mxnet_tpu as mx

    sym, arg_params, aux_params = mx.caffe.convert(cli.prototxt,
                                                   cli.caffemodel)
    mx.model.save_checkpoint(cli.prefix, 0, sym, arg_params, aux_params)
    print("wrote %s-symbol.json and %s-0000.params (%d arg, %d aux)"
          % (cli.prefix, cli.prefix, len(arg_params), len(aux_params)))


if __name__ == "__main__":
    main()

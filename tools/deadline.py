"""Shared SIGALRM deadline for the chip-facing tools.

CAVEAT (load-bearing): SIGALRM raises only when control returns to
Python — a hang INSIDE a native XLA compile/execute call is not
interrupted; the TimeoutError fires as soon as the native call returns.
For a truly wedged native call, wrap the whole tool in coreutils
``timeout`` instead.
"""
import contextlib
import signal


@contextlib.contextmanager
def deadline(seconds):
    def _raise(sig, frm):
        raise TimeoutError("exceeded %ds" % seconds)

    old = signal.signal(signal.SIGALRM, _raise)
    signal.alarm(seconds)
    try:
        yield
    finally:
        signal.alarm(0)
        signal.signal(signal.SIGALRM, old)

"""Cold-start worker: time-to-first-prediction for a serving replica.

One process = one replica lifecycle: build an :class:`InferenceServer`
(a deep-enough MLP that XLA compilation dominates cold start, several
batch buckets so warmup compiles more than one program), then measure
wall time from construction start to the first prediction result.  The
parent (``bench.py`` cold-start phase) runs this twice against one
``MXNET_COMPILE_CACHE_DIR``: the first run compiles and populates the
cache, the second must start warm — hits>0, zero compiles — which is the
PR-10 acceptance measurement.

Prints ONE json line:
  {"ttfp_ms", "warmup_ms", "predict_ms", "out_digest", "cache": {...}}

``out_digest`` hashes the first prediction's bytes so the caller can
assert cache-served outputs are bit-identical to freshly-compiled ones.

Usage: python tools/bench_coldstart.py [--buckets 1,2,4] [--hidden 256]
       (cache dir comes from MXNET_COMPILE_CACHE_DIR; empty = cache off)
"""
import argparse
import hashlib
import json
import os
import sys
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

os.environ.setdefault("JAX_PLATFORMS", "cpu")


def build_symbol(layers, hidden, classes):
    import mxnet_tpu as mx

    net = mx.symbol.Variable("data")
    for i in range(layers):
        net = mx.symbol.FullyConnected(net, name="fc%d" % i,
                                       num_hidden=hidden)
        net = mx.symbol.Activation(net, act_type="relu",
                                   name="relu%d" % i)
    net = mx.symbol.FullyConnected(net, name="head", num_hidden=classes)
    return mx.symbol.SoftmaxOutput(net, name="softmax")


def build_params(layers, feat, hidden, classes, seed=7):
    import numpy as np

    rng = np.random.RandomState(seed)
    params = {}
    d_in = feat
    for i in range(layers):
        params["fc%d_weight" % i] = \
            rng.randn(hidden, d_in).astype(np.float32) * 0.05
        params["fc%d_bias" % i] = np.zeros(hidden, np.float32)
        d_in = hidden
    params["head_weight"] = rng.randn(classes, d_in).astype(np.float32) * 0.05
    params["head_bias"] = np.zeros(classes, np.float32)
    return params


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--layers", type=int, default=8)
    ap.add_argument("--feat", type=int, default=64)
    ap.add_argument("--hidden", type=int, default=256)
    ap.add_argument("--classes", type=int, default=16)
    ap.add_argument("--buckets", default="1,2,4")
    cli = ap.parse_args(argv)

    t0 = time.perf_counter()
    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu import compile_cache
    from mxnet_tpu.serving.server import InferenceServer

    buckets = tuple(int(b) for b in cli.buckets.split(","))
    symbol = build_symbol(cli.layers, cli.hidden, cli.classes)
    params = build_params(cli.layers, cli.feat, cli.hidden, cli.classes)

    # TTFP clock starts at server construction (includes every bucket's
    # warmup — the compile-or-deserialize cost under test)
    t_build = time.perf_counter()
    server = InferenceServer(symbol, params,
                             {"data": (max(buckets), cli.feat)},
                             buckets=buckets, warmup=True, start=True)
    t_warm = time.perf_counter()
    x = np.arange(cli.feat, dtype=np.float32) / cli.feat
    out = server.predict(data=x)[0]
    t_first = time.perf_counter()
    server.stop()

    print(json.dumps({
        "ttfp_ms": round((t_first - t_build) * 1e3, 1),
        "warmup_ms": round((t_warm - t_build) * 1e3, 1),
        "predict_ms": round((t_first - t_warm) * 1e3, 1),
        "import_ms": round((t_build - t0) * 1e3, 1),
        "buckets": list(buckets),
        "out_digest": hashlib.sha256(
            np.ascontiguousarray(out).tobytes()).hexdigest()[:16],
        "cache": compile_cache.stats(),
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""Admin CLI for the persistent tuning DB (MXNET_AUTOTUNE_DIR).

Subcommands (all read the DB dir from --dir, MXNET_AUTOTUNE_DIR, or
the <MXNET_COMPILE_CACHE_DIR>/autotune derivation):

  ls           one line per entry: digest, tunable site, objective,
               score, size, age, and whether the recording environment
               matches this one ("stale-env" entries invalidate on load)
  verify       CRC + header + payload check per entry; exit 1 if any fail
  prune        delete oldest entries until the dir fits the size budget
  show-winner  dump one entry's winner config + tuning provenance
               (candidate scores, objective ladder, tuning wall time)

Usage:
  python tools/autotune_admin.py ls [--dir D] [--json]
  python tools/autotune_admin.py verify [--dir D] [--json]
  python tools/autotune_admin.py prune [--dir D] [--max-mb N] [--json]
  python tools/autotune_admin.py show-winner DIGEST [--dir D]
"""
import argparse
import json
import os
import sys
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)


def _dir_from(cli):
    if cli.dir:
        return cli.dir
    from mxnet_tpu import autotune

    d = autotune.db_dir()
    if not d:
        sys.exit("no tuning-DB dir: pass --dir or set MXNET_AUTOTUNE_DIR "
                 "(or MXNET_COMPILE_CACHE_DIR)")
    return d


def cmd_ls(cli):
    import importlib

    atdb = importlib.import_module("mxnet_tpu.autotune.db")

    entries = atdb.ls_entries(_dir_from(cli))
    if cli.json:
        print(json.dumps(entries, default=str))
        return 0
    total = 0
    now = time.time()
    for e in entries:
        total += e["bytes"]
        age = now - e["mtime"]
        print("%s  %-15s %-14s score %-10s %7.1fKB  %6.0fs old  %s"
              % (e["digest"], e.get("site") or "?",
                 e.get("objective") or "?", e.get("score", "?"),
                 e["bytes"] / 1024.0, age,
                 "ok" if e.get("env_ok") else
                 ("CORRUPT" if e.get("kind") == "corrupt" else "stale-env")))
    print("%d entries, %.1f MB" % (len(entries), total / (1 << 20)))
    return 0


def cmd_verify(cli):
    import importlib

    atdb = importlib.import_module("mxnet_tpu.autotune.db")

    d = _dir_from(cli)
    results = []
    bad = 0
    for e in atdb.ls_entries(d):
        ok, detail = atdb.verify_entry(e["path"])
        bad += 0 if ok else 1
        results.append({"digest": e["digest"], "ok": ok, "detail": detail})
    if cli.json:
        print(json.dumps({"entries": results, "bad": bad}))
    else:
        for r in results:
            print("%s  %s  %s" % (r["digest"],
                                  "ok " if r["ok"] else "BAD", r["detail"]))
        print("%d/%d entries verify clean"
              % (len(results) - bad, len(results)))
    return 1 if bad else 0


def cmd_prune(cli):
    import importlib

    atdb = importlib.import_module("mxnet_tpu.autotune.db")

    d = _dir_from(cli)
    budget = cli.max_mb if cli.max_mb is not None else 64
    removed = atdb.prune(d, budget)
    left = atdb.ls_entries(d)
    out = {"removed": len(removed), "kept": len(left),
           "bytes": sum(e["bytes"] for e in left), "budget_mb": budget}
    if cli.json:
        print(json.dumps(out))
    else:
        print("pruned %(removed)d entries; %(kept)d kept "
              "(%(bytes)d bytes, budget %(budget_mb)d MB)" % out)
    return 0


def cmd_show_winner(cli):
    import importlib

    atdb = importlib.import_module("mxnet_tpu.autotune.db")

    if not cli.digest:
        sys.exit("show-winner needs a DIGEST argument (see ls)")
    d = _dir_from(cli)
    path = os.path.join(d, cli.digest + atdb.ENTRY_SUFFIX)
    if not os.path.exists(path):
        sys.exit("no entry %s in %s" % (cli.digest, d))
    print(json.dumps(atdb.show_winner(path), indent=2, default=str))
    return 0


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("cmd", choices=("ls", "verify", "prune", "show-winner"))
    ap.add_argument("digest", nargs="?", default=None,
                    help="entry digest (show-winner only)")
    ap.add_argument("--dir", default=None,
                    help="tuning-DB dir (default: $MXNET_AUTOTUNE_DIR or "
                         "$MXNET_COMPILE_CACHE_DIR/autotune)")
    ap.add_argument("--max-mb", type=int, default=None,
                    help="prune budget in MB (default 64)")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable output")
    cli = ap.parse_args(argv)
    return {"ls": cmd_ls, "verify": cmd_verify, "prune": cmd_prune,
            "show-winner": cmd_show_winner}[cli.cmd](cli)


if __name__ == "__main__":
    sys.exit(main())

#!/usr/bin/env python
"""Generative-serving benchmark: continuous batching vs naive decode.

Drives a :class:`mxnet_tpu.generation.DecodeEngine` (iteration-level
continuous batching over the paged KV pool) with a mixed-length prompt
workload and reports tokens/s, TTFT and inter-token-latency percentiles,
KV-pool peak pages against the live-token bound, and the post-warmup
compile count (must be zero — the decode loop is shape-static).

The baseline is the naive autoregressive server loop: one request at a
time, each new token produced by re-running the FULL prefix through the
full-length prefill executable (batch=1, no KV reuse) — what serving a
training-graph checkpoint looks like before this subsystem existed.
Continuous batching + paged KV must clear ``--min-speedup`` (default 3x)
over it on this CPU-runnable workload.

Runs on CPU in ~a minute; the last stdout line is the JSON record:

    JAX_PLATFORMS=cpu python tools/bench_generate.py
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu.serving.metrics import _percentile  # noqa: E402


def make_model(vocab, layers, heads, hidden, seq_len, seed=0):
    net = mx.models.get_transformer_lm(vocab_size=vocab, num_layers=layers,
                                       num_heads=heads, hidden=hidden,
                                       seq_len=seq_len)
    arg_shapes, _, _ = net.infer_shape(data=(1, seq_len),
                                       softmax_label=(1, seq_len))
    rng = np.random.RandomState(seed)
    params = {
        name: mx.nd.array(rng.randn(*shp).astype(np.float32) * 0.05)
        for name, shp in zip(net.list_arguments(), arg_shapes)
        if name not in ("data", "softmax_label")}
    return net, params


def make_workload(rng, n, vocab, max_seq,
                  plens=(3, 5, 8, 12, 20, 28), budgets=(6, 10, 16, 24)):
    """Mixed-length open-loop workload: short chat-y prompts next to
    long ones, generation budgets skewed the same way."""
    out = []
    for _ in range(n):
        plen = int(rng.choice(plens))
        max_new = int(rng.choice(budgets))
        max_new = min(max_new, max_seq - plen)
        out.append(([int(t) for t in rng.randint(0, vocab, size=plen)],
                    max_new))
    return out


def bench_engine(params, spec, workload):
    """Continuous batching: submit everything, stream everything."""
    engine = mx.generation.DecodeEngine(params, **spec)
    try:
        t0 = time.monotonic()
        streams = [engine.submit(p, n) for p, n in workload]
        for s in streams:
            s.result(timeout=600)
        wall = time.monotonic() - t0
        total = sum(len(s.tokens) for s in streams)
        ttfts = sorted(s.ttft_ms for s in streams)
        itls = sorted(g for s in streams for g in s.itl_ms)
        return {
            "tokens": total,
            "tokens_per_sec": total / wall,
            "wall_s": wall,
            "ttft_ms_p50": _percentile(ttfts, 0.50),
            "ttft_ms_p99": _percentile(ttfts, 0.99),
            "itl_ms_p50": _percentile(itls, 0.50) if itls else None,
            "itl_ms_p99": _percentile(itls, 0.99) if itls else None,
            "peak_pages": engine.pool.peak_pages,
            "pool_capacity": engine.pool.capacity,
            "cold_decode_runs": engine.cold_decode_runs(),
            "warmed_lane_buckets": sorted(engine.warmed_lane_buckets),
            "outputs": [list(s.tokens) for s in streams],
        }
    finally:
        engine.stop()


def bench_naive(net_unused, params, spec, workload):
    """Naive baseline: sequential, batch=1, full-prefix re-decode —
    every token re-runs the whole padded prompt through one full-length
    prefill executable (compiled once; no KV is carried between steps)."""
    from mxnet_tpu.models.transformer import get_transformer_lm_prefill
    from mxnet_tpu.predictor import Predictor

    S = spec["max_seq_len"]
    sym = get_transformer_lm_prefill(
        spec["vocab_size"], spec["num_layers"], spec["num_heads"],
        spec["hidden"], seq_len=S, max_seq_len=S)
    pred = Predictor(sym, params, {"data": (1, S)})
    buf = np.zeros((1, S), np.float32)

    def logits_at(tokens):
        buf[:] = 0
        buf[0, :len(tokens)] = tokens
        out = pred.forward(data=buf)[0].asnumpy()
        return out[0, len(tokens) - 1]

    # warm the single executable before the clock starts
    logits_at([1])
    t0 = time.monotonic()
    outputs = []
    total = 0
    for prompt, max_new in workload:
        toks = list(prompt)
        gen = []
        for _ in range(max_new):
            nxt = int(np.argmax(logits_at(toks)))
            toks.append(nxt)
            gen.append(nxt)
            total += 1
        outputs.append(gen)
    wall = time.monotonic() - t0
    return {"tokens": total, "tokens_per_sec": total / wall,
            "wall_s": wall, "outputs": outputs}


def run(num_requests=16, vocab=128, layers=2, heads=4, hidden=64,
        max_seq=64, page_size=8, num_pages=96, lanes=8, seed=0,
        min_speedup=3.0):
    rng = np.random.RandomState(seed)
    net, params = make_model(vocab, layers, heads, hidden, max_seq,
                             seed=seed)
    spec = dict(vocab_size=vocab, num_layers=layers, num_heads=heads,
                hidden=hidden, max_seq_len=max_seq,
                lane_buckets=tuple(sorted({1, 2, max(4, lanes // 2),
                                           lanes})),
                page_size=page_size, num_pages=num_pages)
    workload = make_workload(rng, num_requests, vocab, max_seq)

    eng = bench_engine(params, spec, workload)
    naive = bench_naive(net, params, spec, workload)

    # greedy decode is deterministic: both servers must emit the exact
    # same tokens or one of them is broken, not just slow
    parity = eng.pop("outputs") == naive.pop("outputs")

    # live-token bound: the pool may never hold more pages than the
    # `lanes` largest concurrently-decodable requests need at full
    # length — the paged layout's whole point vs dense max_len x batch
    totals = sorted((len(p) + n for p, n in workload), reverse=True)
    pages_for = lambda t: -(-t // page_size)  # noqa: E731
    live_bound = sum(pages_for(t) for t in totals[:lanes])
    dense_pages = lanes * pages_for(max_seq)

    record = {
        "metric": "generate_tokens_per_sec",
        "value": round(eng["tokens_per_sec"], 1),
        "unit": "tokens/s",
        "naive_tokens_per_sec": round(naive["tokens_per_sec"], 1),
        "speedup_vs_naive": round(
            eng["tokens_per_sec"] / naive["tokens_per_sec"], 2),
        "min_speedup": min_speedup,
        "outputs_identical": parity,
        "requests": num_requests,
        "tokens": eng["tokens"],
        "ttft_ms_p50": round(eng["ttft_ms_p50"], 2),
        "ttft_ms_p99": round(eng["ttft_ms_p99"], 2),
        "itl_ms_p50": round(eng["itl_ms_p50"], 2),
        "itl_ms_p99": round(eng["itl_ms_p99"], 2),
        "peak_pages": eng["peak_pages"],
        "live_token_page_bound": live_bound,
        "dense_equivalent_pages": dense_pages,
        "cold_decode_runs": eng["cold_decode_runs"],
        "warmed_lane_buckets": eng["warmed_lane_buckets"],
        "model": {"vocab": vocab, "layers": layers, "heads": heads,
                  "hidden": hidden, "max_seq": max_seq,
                  "page_size": page_size, "lanes": lanes},
    }
    record["ok"] = bool(
        parity and record["speedup_vs_naive"] >= min_speedup
        and eng["cold_decode_runs"] == 0
        and eng["peak_pages"] <= live_bound)
    return record


def _ttft_storm(params, spec, workload, prefix_cache_pages, warm_prompt):
    """Submit the whole workload at once and collect TTFT stats; with a
    prefix cache, one warm-up request (excluded from stats) publishes
    the shared prefix first."""
    engine = mx.generation.DecodeEngine(
        params, prefix_cache_pages=prefix_cache_pages, **spec)
    try:
        if prefix_cache_pages and warm_prompt is not None:
            engine.generate(warm_prompt, max_new_tokens=2, timeout=600)
        streams = [engine.submit(p, n) for p, n in workload]
        for s in streams:
            s.result(timeout=600)
        ttfts = sorted(s.ttft_ms for s in streams)
        return {
            "ttft_ms_p50": _percentile(ttfts, 0.50),
            "ttft_ms_p99": _percentile(ttfts, 0.99),
            "prefill_tokens": sum(s.prefill_tokens for s in streams),
            "cached_prefix_tokens": sum(s.cached_prefix_tokens
                                        for s in streams),
            "ttft_iters": [s.ttft_iters for s in streams],
            "cold_decode_runs": engine.cold_decode_runs(),
            "kv": engine.pool.snapshot(),
            "outputs": [list(s.tokens) for s in streams],
        }
    finally:
        engine.stop()


def run_prefix_reuse(num_requests=16, vocab=128, layers=2, heads=4,
                     hidden=64, max_seq=64, page_size=8, num_pages=96,
                     lanes=8, seed=0, min_ttft_reduction=5.0,
                     shared_frac=0.9):
    """Prefix-caching benchmark: a storm of requests sharing one hot
    system-prompt-style prefix (``shared_frac`` of every prompt), TTFT
    with the prefix cache vs without.  Cached admissions skip prefill
    for the shared pages, so first-token latency collapses."""
    rng = np.random.RandomState(seed)
    _, params = make_model(vocab, layers, heads, hidden, max_seq,
                           seed=seed)
    # prompt length lands on a 16-token boundary so the 90%-shared
    # prefix page-aligns and the unique remainder fits one catch-up
    # forward (the cached path's TTFT is then a single pool roundtrip)
    plen = max(16, (int((max_seq * 3) // 4) // 16) * 16)
    # one prefill length bucket: every storm prompt is plen tokens, so
    # warmup compiles only the graphs the run will actually use
    spec = dict(vocab_size=vocab, num_layers=layers, num_heads=heads,
                hidden=hidden, max_seq_len=max_seq,
                lane_buckets=tuple(sorted({1, 2, max(4, lanes // 2),
                                           lanes})),
                page_size=page_size, num_pages=num_pages,
                prefill_len_buckets=(plen,))
    shared_len = int(round(plen * shared_frac))
    shared = [int(t) for t in rng.randint(0, vocab, size=shared_len)]
    workload = []
    for _ in range(num_requests):
        tail = [int(t) for t in
                rng.randint(0, vocab, size=plen - shared_len)]
        workload.append((shared + tail,
                         min(8, max_seq - plen)))
    uncached = _ttft_storm(params, spec, workload, 0, None)
    cached = _ttft_storm(params, spec, workload,
                         num_pages, shared + [1])
    parity = uncached.pop("outputs") == cached.pop("outputs")
    reduction = (uncached["ttft_ms_p50"] / cached["ttft_ms_p50"]
                 if cached["ttft_ms_p50"] else float("inf"))
    kv = cached.pop("kv")
    uncached.pop("kv")
    record = {
        "metric": "generate_prefix_ttft_reduction",
        "value": round(reduction, 2),
        "unit": "x",
        "min_ttft_reduction": min_ttft_reduction,
        "shared_frac": shared_frac,
        "requests": num_requests,
        "outputs_identical": parity,
        "ttft_ms_p50_uncached": round(uncached["ttft_ms_p50"], 2),
        "ttft_ms_p50_cached": round(cached["ttft_ms_p50"], 2),
        "ttft_ms_p99_uncached": round(uncached["ttft_ms_p99"], 2),
        "ttft_ms_p99_cached": round(cached["ttft_ms_p99"], 2),
        "prefill_tokens_uncached": uncached["prefill_tokens"],
        "prefill_tokens_cached": cached["prefill_tokens"],
        "prefix_hits": kv.get("prefix_hits"),
        "prefix_misses": kv.get("prefix_misses"),
        "cold_decode_runs": (uncached["cold_decode_runs"]
                             + cached["cold_decode_runs"]),
    }
    record["ok"] = bool(
        parity and reduction >= min_ttft_reduction
        and record["cold_decode_runs"] == 0
        and cached["prefill_tokens"] < uncached["prefill_tokens"])
    return record


def _tokens_per_sec(params, spec, workload, draft):
    engine = mx.generation.DecodeEngine(params, draft=draft, **spec)
    try:
        t0 = time.monotonic()
        streams = [engine.submit(p, n) for p, n in workload]
        for s in streams:
            s.result(timeout=600)
        wall = time.monotonic() - t0
        total = sum(len(s.tokens) for s in streams)
        proposed = sum(s.draft_proposed for s in streams)
        accepted = sum(s.draft_accepted for s in streams)
        return {
            "tokens": total,
            "tokens_per_sec": total / wall,
            "wall_s": wall,
            "draft_proposed": proposed,
            "draft_accepted": accepted,
            "acceptance": (accepted / proposed) if proposed else None,
            "cold_decode_runs": engine.cold_decode_runs(),
            "draft_k": engine.spec().get("draft", {}).get("k"),
            "outputs": [list(s.tokens) for s in streams],
        }
    finally:
        engine.stop()


def make_draft(params, layers, draft_layers, damp=0.02):
    """Derive a high-acceptance draft checkpoint from the target: keep
    the first ``draft_layers`` transformer blocks plus the shared
    embedding/head, and (bench-only) dampen the TARGET's deeper blocks
    so the residual stream — which both models share — dominates its
    argmax.  Returns (draft_params, dampened_target_params)."""
    tgt = {}
    drf = {}
    for name, arr in params.items():
        a = arr.asnumpy() if hasattr(arr, "asnumpy") else np.asarray(arr)
        base = name.split(":", 1)[-1]
        lid = None
        if base.startswith("layer"):
            lid = int(base[len("layer"):].split("_")[0])
        if lid is not None and lid >= draft_layers:
            tgt[name] = a * damp
        else:
            tgt[name] = a
            drf[name] = a
    return drf, tgt


def run_draft(num_requests=16, vocab=128, layers=2, heads=4, hidden=64,
              max_seq=64, page_size=8, num_pages=96, lanes=8, seed=0,
              min_speedup=1.3, min_acceptance=0.6, draft_k=None):
    """Speculative-decoding benchmark: tokens/s with a draft model +
    fused verify pass vs the plain one-token-per-step engine, on the
    same workload.  Greedy acceptance is bit-identical by construction,
    so the transcripts must match exactly."""
    rng = np.random.RandomState(seed)
    _, params = make_model(vocab, layers, heads, hidden, max_seq,
                           seed=seed)
    # the draft must be MUCH cheaper per step than the target, not
    # merely cheaper: every proposal pays the draft's full dispatch +
    # pool-roundtrip cost, so a half-depth draft leaves speculation
    # arbitraging almost nothing (real deployments pair ~10x-smaller
    # drafts with their targets for the same reason)
    draft_layers = max(1, layers // 4)
    draft_params, target_params = make_draft(params, layers, draft_layers)
    spec = dict(vocab_size=vocab, num_layers=layers, num_heads=heads,
                hidden=hidden, max_seq_len=max_seq,
                lane_buckets=tuple(sorted({1, 2, max(4, lanes // 2),
                                           lanes})),
                page_size=page_size, num_pages=num_pages)
    # decode-dominated workload: speculation only fires on steady
    # (generating) lanes, so short generation budgets would measure
    # admission/prefill transients instead of the token path — and a
    # sub-second measurement window on a shared box is mostly
    # scheduler noise
    workload = make_workload(rng, num_requests, vocab, max_seq,
                             plens=(3, 5, 8, 12),
                             budgets=(32, 40, 48))
    plain = _tokens_per_sec(target_params, spec, workload, None)
    draft = {"params": draft_params, "num_layers": draft_layers,
             "num_heads": heads, "hidden": hidden,
             "acceptance_hint": 0.8}
    if draft_k is not None:
        draft["k"] = draft_k
    spec_run = _tokens_per_sec(target_params, spec, workload, draft)
    parity = plain.pop("outputs") == spec_run.pop("outputs")
    speedup = spec_run["tokens_per_sec"] / plain["tokens_per_sec"]
    record = {
        "metric": "generate_draft_speedup",
        "value": round(speedup, 2),
        "unit": "x",
        "min_speedup": min_speedup,
        "min_acceptance": min_acceptance,
        "outputs_identical": parity,
        "requests": num_requests,
        "tokens": spec_run["tokens"],
        "tokens_per_sec_plain": round(plain["tokens_per_sec"], 1),
        "tokens_per_sec_draft": round(spec_run["tokens_per_sec"], 1),
        "draft_k": spec_run["draft_k"],
        "draft_layers": draft_layers,
        "draft_proposed": spec_run["draft_proposed"],
        "draft_accepted": spec_run["draft_accepted"],
        "acceptance": (round(spec_run["acceptance"], 3)
                       if spec_run["acceptance"] is not None else None),
        "cold_decode_runs": (plain["cold_decode_runs"]
                             + spec_run["cold_decode_runs"]),
    }
    record["ok"] = bool(
        parity and speedup >= min_speedup
        and (record["acceptance"] or 0) >= min_acceptance
        and record["cold_decode_runs"] == 0)
    return record


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--prefix-reuse", action="store_true",
                    help="benchmark cross-request prefix caching: TTFT "
                         "with vs without the cache on a shared-prefix "
                         "storm")
    ap.add_argument("--draft", action="store_true",
                    help="benchmark speculative decoding: tokens/s with "
                         "a draft model vs the plain engine")
    ap.add_argument("--draft-k", type=int, default=None)
    ap.add_argument("--min-ttft-reduction", type=float, default=5.0)
    ap.add_argument("--min-acceptance", type=float, default=0.6)
    ap.add_argument("--shared-frac", type=float, default=0.9)
    ap.add_argument("--requests", type=int, default=None)
    ap.add_argument("--vocab", type=int, default=128)
    ap.add_argument("--layers", type=int, default=None)
    ap.add_argument("--heads", type=int, default=4)
    ap.add_argument("--hidden", type=int, default=None)
    ap.add_argument("--max-seq", type=int, default=None)
    ap.add_argument("--page-size", type=int, default=8)
    ap.add_argument("--num-pages", type=int, default=None)
    ap.add_argument("--lanes", type=int, default=None)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--min-speedup", type=float, default=3.0)
    args = ap.parse_args(argv)
    # the prefix storm needs prompts long enough that a batched prefill
    # visibly outweighs one catch-up forward: the windowed catch-up is
    # compute-proportional (~same per-token cost as prefill), so the
    # measured reduction is plen/(0.1*plen + fixed-dispatch) — longer
    # prompts amortize the fixed cost toward the 10x compute ratio.
    # (max_seq, num_pages, lanes, hidden, requests) per mode; the draft
    # mode runs a DEEPER target (6 layers vs the 1-layer draft) because
    # speculation's win is exactly the per-step cost gap between the
    # two — a target barely heavier than its draft has nothing to
    # arbitrage
    geo = ((432, 344, 8, 128, 8) if args.prefix_reuse
           else (64, 96, 8, 64, 16))
    max_seq = args.max_seq if args.max_seq is not None else geo[0]
    num_pages = args.num_pages if args.num_pages is not None else geo[1]
    lanes = args.lanes if args.lanes is not None else geo[2]
    hidden = args.hidden if args.hidden is not None else geo[3]
    requests = args.requests if args.requests is not None else geo[4]
    layers = (args.layers if args.layers is not None
              else (6 if args.draft else 2))
    common = dict(num_requests=requests, vocab=args.vocab,
                  layers=layers, heads=args.heads,
                  hidden=hidden, max_seq=max_seq,
                  page_size=args.page_size, num_pages=num_pages,
                  lanes=lanes, seed=args.seed)
    if args.prefix_reuse:
        record = run_prefix_reuse(
            min_ttft_reduction=args.min_ttft_reduction,
            shared_frac=args.shared_frac, **common)
    elif args.draft:
        # the plain-vs-naive gate (3x) is not the spec-vs-plain gate
        # (1.3x): only an explicit --min-speedup overrides the latter
        gate = args.min_speedup if args.min_speedup != 3.0 else 1.3
        record = run_draft(min_speedup=gate,
                           min_acceptance=args.min_acceptance,
                           draft_k=args.draft_k, **common)
    else:
        record = run(min_speedup=args.min_speedup, **common)
    print(json.dumps(record))
    return 0 if record["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())

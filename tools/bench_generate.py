#!/usr/bin/env python
"""Generative-serving benchmark: continuous batching vs naive decode.

Drives a :class:`mxnet_tpu.generation.DecodeEngine` (iteration-level
continuous batching over the paged KV pool) with a mixed-length prompt
workload and reports tokens/s, TTFT and inter-token-latency percentiles,
KV-pool peak pages against the live-token bound, and the post-warmup
compile count (must be zero — the decode loop is shape-static).

The baseline is the naive autoregressive server loop: one request at a
time, each new token produced by re-running the FULL prefix through the
full-length prefill executable (batch=1, no KV reuse) — what serving a
training-graph checkpoint looks like before this subsystem existed.
Continuous batching + paged KV must clear ``--min-speedup`` (default 3x)
over it on this CPU-runnable workload.

Runs on CPU in ~a minute; the last stdout line is the JSON record:

    JAX_PLATFORMS=cpu python tools/bench_generate.py
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu.serving.metrics import _percentile  # noqa: E402


def make_model(vocab, layers, heads, hidden, seq_len, seed=0):
    net = mx.models.get_transformer_lm(vocab_size=vocab, num_layers=layers,
                                       num_heads=heads, hidden=hidden,
                                       seq_len=seq_len)
    arg_shapes, _, _ = net.infer_shape(data=(1, seq_len),
                                       softmax_label=(1, seq_len))
    rng = np.random.RandomState(seed)
    params = {
        name: mx.nd.array(rng.randn(*shp).astype(np.float32) * 0.05)
        for name, shp in zip(net.list_arguments(), arg_shapes)
        if name not in ("data", "softmax_label")}
    return net, params


def make_workload(rng, n, vocab, max_seq):
    """Mixed-length open-loop workload: short chat-y prompts next to
    long ones, generation budgets skewed the same way."""
    out = []
    for _ in range(n):
        plen = int(rng.choice([3, 5, 8, 12, 20, 28]))
        max_new = int(rng.choice([6, 10, 16, 24]))
        max_new = min(max_new, max_seq - plen)
        out.append(([int(t) for t in rng.randint(0, vocab, size=plen)],
                    max_new))
    return out


def bench_engine(params, spec, workload):
    """Continuous batching: submit everything, stream everything."""
    engine = mx.generation.DecodeEngine(params, **spec)
    try:
        t0 = time.monotonic()
        streams = [engine.submit(p, n) for p, n in workload]
        for s in streams:
            s.result(timeout=600)
        wall = time.monotonic() - t0
        total = sum(len(s.tokens) for s in streams)
        ttfts = sorted(s.ttft_ms for s in streams)
        itls = sorted(g for s in streams for g in s.itl_ms)
        return {
            "tokens": total,
            "tokens_per_sec": total / wall,
            "wall_s": wall,
            "ttft_ms_p50": _percentile(ttfts, 0.50),
            "ttft_ms_p99": _percentile(ttfts, 0.99),
            "itl_ms_p50": _percentile(itls, 0.50) if itls else None,
            "itl_ms_p99": _percentile(itls, 0.99) if itls else None,
            "peak_pages": engine.pool.peak_pages,
            "pool_capacity": engine.pool.capacity,
            "cold_decode_runs": engine.cold_decode_runs(),
            "warmed_lane_buckets": sorted(engine.warmed_lane_buckets),
            "outputs": [list(s.tokens) for s in streams],
        }
    finally:
        engine.stop()


def bench_naive(net_unused, params, spec, workload):
    """Naive baseline: sequential, batch=1, full-prefix re-decode —
    every token re-runs the whole padded prompt through one full-length
    prefill executable (compiled once; no KV is carried between steps)."""
    from mxnet_tpu.models.transformer import get_transformer_lm_prefill
    from mxnet_tpu.predictor import Predictor

    S = spec["max_seq_len"]
    sym = get_transformer_lm_prefill(
        spec["vocab_size"], spec["num_layers"], spec["num_heads"],
        spec["hidden"], seq_len=S, max_seq_len=S)
    pred = Predictor(sym, params, {"data": (1, S)})
    buf = np.zeros((1, S), np.float32)

    def logits_at(tokens):
        buf[:] = 0
        buf[0, :len(tokens)] = tokens
        out = pred.forward(data=buf)[0].asnumpy()
        return out[0, len(tokens) - 1]

    # warm the single executable before the clock starts
    logits_at([1])
    t0 = time.monotonic()
    outputs = []
    total = 0
    for prompt, max_new in workload:
        toks = list(prompt)
        gen = []
        for _ in range(max_new):
            nxt = int(np.argmax(logits_at(toks)))
            toks.append(nxt)
            gen.append(nxt)
            total += 1
        outputs.append(gen)
    wall = time.monotonic() - t0
    return {"tokens": total, "tokens_per_sec": total / wall,
            "wall_s": wall, "outputs": outputs}


def run(num_requests=16, vocab=128, layers=2, heads=4, hidden=64,
        max_seq=64, page_size=8, num_pages=96, lanes=8, seed=0,
        min_speedup=3.0):
    rng = np.random.RandomState(seed)
    net, params = make_model(vocab, layers, heads, hidden, max_seq,
                             seed=seed)
    spec = dict(vocab_size=vocab, num_layers=layers, num_heads=heads,
                hidden=hidden, max_seq_len=max_seq,
                lane_buckets=tuple(sorted({1, 2, max(4, lanes // 2),
                                           lanes})),
                page_size=page_size, num_pages=num_pages)
    workload = make_workload(rng, num_requests, vocab, max_seq)

    eng = bench_engine(params, spec, workload)
    naive = bench_naive(net, params, spec, workload)

    # greedy decode is deterministic: both servers must emit the exact
    # same tokens or one of them is broken, not just slow
    parity = eng.pop("outputs") == naive.pop("outputs")

    # live-token bound: the pool may never hold more pages than the
    # `lanes` largest concurrently-decodable requests need at full
    # length — the paged layout's whole point vs dense max_len x batch
    totals = sorted((len(p) + n for p, n in workload), reverse=True)
    pages_for = lambda t: -(-t // page_size)  # noqa: E731
    live_bound = sum(pages_for(t) for t in totals[:lanes])
    dense_pages = lanes * pages_for(max_seq)

    record = {
        "metric": "generate_tokens_per_sec",
        "value": round(eng["tokens_per_sec"], 1),
        "unit": "tokens/s",
        "naive_tokens_per_sec": round(naive["tokens_per_sec"], 1),
        "speedup_vs_naive": round(
            eng["tokens_per_sec"] / naive["tokens_per_sec"], 2),
        "min_speedup": min_speedup,
        "outputs_identical": parity,
        "requests": num_requests,
        "tokens": eng["tokens"],
        "ttft_ms_p50": round(eng["ttft_ms_p50"], 2),
        "ttft_ms_p99": round(eng["ttft_ms_p99"], 2),
        "itl_ms_p50": round(eng["itl_ms_p50"], 2),
        "itl_ms_p99": round(eng["itl_ms_p99"], 2),
        "peak_pages": eng["peak_pages"],
        "live_token_page_bound": live_bound,
        "dense_equivalent_pages": dense_pages,
        "cold_decode_runs": eng["cold_decode_runs"],
        "warmed_lane_buckets": eng["warmed_lane_buckets"],
        "model": {"vocab": vocab, "layers": layers, "heads": heads,
                  "hidden": hidden, "max_seq": max_seq,
                  "page_size": page_size, "lanes": lanes},
    }
    record["ok"] = bool(
        parity and record["speedup_vs_naive"] >= min_speedup
        and eng["cold_decode_runs"] == 0
        and eng["peak_pages"] <= live_bound)
    return record


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--requests", type=int, default=16)
    ap.add_argument("--vocab", type=int, default=128)
    ap.add_argument("--layers", type=int, default=2)
    ap.add_argument("--heads", type=int, default=4)
    ap.add_argument("--hidden", type=int, default=64)
    ap.add_argument("--max-seq", type=int, default=64)
    ap.add_argument("--page-size", type=int, default=8)
    ap.add_argument("--num-pages", type=int, default=96)
    ap.add_argument("--lanes", type=int, default=8)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--min-speedup", type=float, default=3.0)
    args = ap.parse_args(argv)
    record = run(num_requests=args.requests, vocab=args.vocab,
                 layers=args.layers, heads=args.heads, hidden=args.hidden,
                 max_seq=args.max_seq, page_size=args.page_size,
                 num_pages=args.num_pages, lanes=args.lanes,
                 seed=args.seed, min_speedup=args.min_speedup)
    print(json.dumps(record))
    return 0 if record["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())

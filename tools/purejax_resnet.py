"""Ceiling probe: hand-written ResNet-50 train step in pure JAX (no framework),
NHWC bf16 compute, f32 master params, fused BN stats, SGD momentum, one
donated jit.  Establishes what XLA can do on this chip so the framework's
overhead is measurable against it.
"""
import json
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

B = int(sys.argv[1]) if len(sys.argv) > 1 else 256
STEPS = int(sys.argv[2]) if len(sys.argv) > 2 else 20

CFG = [(3, 256, 64), (4, 512, 128), (6, 1024, 256), (3, 2048, 512)]


def conv_p(key, kh, kw, ci, co):
    fan_in = kh * kw * ci
    return jax.random.normal(key, (kh, kw, ci, co), jnp.float32) * np.sqrt(
        2.0 / fan_in)


def init_params(key):
    p = {}
    ks = iter(jax.random.split(key, 200))
    p["stem"] = {"w": conv_p(next(ks), 7, 7, 3, 64),
                 "g": jnp.ones((64,)), "b": jnp.zeros((64,))}
    ci = 64
    for si, (n_units, co, mid) in enumerate(CFG):
        for ui in range(n_units):
            blk = {}
            blk["w1"] = conv_p(next(ks), 1, 1, ci, mid)
            blk["g1"] = jnp.ones((mid,)); blk["b1"] = jnp.zeros((mid,))
            blk["w2"] = conv_p(next(ks), 3, 3, mid, mid)
            blk["g2"] = jnp.ones((mid,)); blk["b2"] = jnp.zeros((mid,))
            blk["w3"] = conv_p(next(ks), 1, 1, mid, co)
            blk["g3"] = jnp.ones((co,)); blk["b3"] = jnp.zeros((co,))
            if ui == 0:
                blk["wsc"] = conv_p(next(ks), 1, 1, ci, co)
                blk["gsc"] = jnp.ones((co,)); blk["bsc"] = jnp.zeros((co,))
            p[f"s{si}u{ui}"] = blk
            ci = co
    p["fc"] = {"w": jax.random.normal(next(ks), (2048, 1000)) * 0.01,
               "b": jnp.zeros((1000,))}
    return p


DN = None


def conv(x, w, stride=1):
    global DN
    return lax.conv_general_dilated(
        x, w.astype(jnp.bfloat16), (stride, stride),
        "SAME" if w.shape[0] > 1 else [(0, 0), (0, 0)],
        dimension_numbers=("NHWC", "HWIO", "NHWC"))


def bn(x, g, b):
    mean = jnp.mean(x, axis=(0, 1, 2), dtype=jnp.float32)
    meansq = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=(0, 1, 2))
    var = jnp.maximum(meansq - jnp.square(mean), 0.0)
    inv = lax.rsqrt(var + 2e-5)
    scale = (g * inv).astype(x.dtype)
    shift = (b - mean * inv * g).astype(x.dtype)
    return x * scale + shift


def block(x, p, stride, proj):
    y = jax.nn.relu(bn(conv(x, p["w1"]), p["g1"], p["b1"]))
    y = jax.nn.relu(bn(conv(y, p["w2"], stride), p["g2"], p["b2"]))
    y = bn(conv(y, p["w3"]), p["g3"], p["b3"])
    sc = bn(conv(x, p["wsc"], stride), p["gsc"], p["bsc"]) if proj else x
    return jax.nn.relu(y + sc)


def forward(params, x):
    x = x.astype(jnp.bfloat16)
    x = jax.nn.relu(bn(conv(x, params["stem"]["w"], 2),
                       params["stem"]["g"], params["stem"]["b"]))
    x = lax.reduce_window(x, np.array(-np.inf, x.dtype), lax.max,
                          (1, 3, 3, 1), (1, 2, 2, 1),
                          [(0, 0), (1, 1), (1, 1), (0, 0)])
    for si, (n_units, co, mid) in enumerate(CFG):
        for ui in range(n_units):
            stride = 2 if (si > 0 and ui == 0) else 1
            x = block(x, params[f"s{si}u{ui}"], stride, ui == 0)
    x = jnp.mean(x, axis=(1, 2), dtype=jnp.float32)
    return x @ params["fc"]["w"] + params["fc"]["b"]


def loss_fn(params, x, y):
    logits = forward(params, x)
    logp = jax.nn.log_softmax(logits)
    return -jnp.mean(jnp.take_along_axis(logp, y[:, None], axis=1))


@jax.jit
def init(key):
    return init_params(key)


def main():
    key = jax.random.PRNGKey(0)
    params = init(key)
    mom = jax.tree_util.tree_map(jnp.zeros_like, params)
    x = jax.random.normal(key, (B, 224, 224, 3), jnp.float32)
    y = jax.random.randint(key, (B,), 0, 1000)

    def step(params, mom, x, y):
        g = jax.grad(loss_fn)(params, x, y)
        new_mom = jax.tree_util.tree_map(lambda m, gg: 0.9 * m + gg, mom, g)
        new_p = jax.tree_util.tree_map(lambda p, m: p - 0.1 * m, params,
                                       new_mom)
        return new_p, new_mom

    jstep = jax.jit(step, donate_argnums=(0, 1))
    params, mom = jstep(params, mom, x, y)
    np.asarray(jax.tree_util.tree_leaves(params)[0])
    # cost analysis
    ab = jax.tree_util.tree_map(
        lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), (params, mom, x, y))
    compiled = jstep.lower(*ab).compile()
    ca = compiled.cost_analysis()
    if isinstance(ca, list):
        ca = ca[0]
    t0 = time.time()
    for _ in range(STEPS):
        params, mom = jstep(params, mom, x, y)
    np.asarray(jax.tree_util.tree_leaves(params)[0])
    dt = (time.time() - t0) / STEPS
    model_flops = 3 * 4.089e9 * B
    print(json.dumps({
        "batch": B, "step_ms": round(dt * 1e3, 2),
        "img_per_sec": round(B / dt, 1),
        "mfu_model": round(model_flops / dt / 197e12, 4),
        "xla_flops": ca.get("flops"),
        "xla_gb": round(ca.get("bytes accessed", 0) / 1e9, 2),
        "mfu_xla": round(ca.get("flops", 0) / dt / 197e12, 4)}))


if __name__ == "__main__":
    main()

"""Summarize a tpu_checklist JSONL run against the round's targets.

Usage: python tools/summarize_checklist.py [TPU_CHECKLIST_r05.jsonl]
Prints a PASS/FAIL table for the BASELINE.md two-track targets plus the
hardware-validation checks, and the flash-vs-splash headroom.
"""
import json
import sys


def main():
    path = sys.argv[1] if len(sys.argv) > 1 else "TPU_CHECKLIST_r05.jsonl"
    rows = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line.startswith("{"):
                try:
                    rows.append(json.loads(line))
                except ValueError:
                    pass
    by = {}
    for r in rows:
        by.setdefault(r.get("check", r.get("metric", "?")), []).append(r)

    def got(name):
        return by.get(name, [{}])[-1]

    print("%-28s %-6s %s" % ("check", "ok", "detail"))
    for name, entries in by.items():
        for e in entries:
            ok = e.get("ok", "-")
            detail = {k: v for k, v in e.items() if k not in ("check", "ok")}
            print("%-28s %-6s %s" % (name, ok, json.dumps(detail)[:110]))

    # every target prints a verdict; a missing row is an explicit
    # MISSING (a wedged run must not look like "nothing was in scope")
    print("\n--- targets (BASELINE.md two-track) ---")
    ms = got("flash_train_model_shape").get("result") or {}
    if ms.get("mfu") is not None:
        print("flash kernel at MODEL shapes (b=4 h=16 s=4k): "
              "%.1f TFLOP/s / %.1f%% MFU" % (ms["value"],
                                             100 * ms["mfu"]))
    else:
        print("flash kernel at MODEL shapes: MISSING")
    best = got("flash_train_best")
    mfu = best.get("mfu")
    print("flash kernel MFU: %s (target >=0.40; r4 best 0.243): %s"
          % (mfu, "MISSING" if mfu is None
             else ("PASS" if mfu >= 0.40 else "below")))
    bench = got("resnet50_bench").get("result") or {}
    v = bench.get("value")
    print("resnet img/s: %s (roofline-parity target >=2400): %s"
          % (v, "MISSING" if v is None
             else ("PASS" if v >= 2400 else "below")))
    lm = bench.get("transformer_lm_mfu")
    src = "checklist"
    if lm is None:
        # fall back to the standalone model-level artifact (builder-run
        # measurements survive a wedged checklist window). The side
        # file's round is derived from the checklist path so a future
        # round's wedged run cannot pass on a stale artifact.
        import os
        import re

        m = re.search(r"(r\d+)", os.path.basename(path))
        side = os.path.join(os.path.dirname(path) or ".",
                            "lm_model_%s.jsonl" % (m.group(1) if m
                                                   else "r05"))
        if os.path.exists(side):
            recs = []
            with open(side) as f:
                for x in f:  # same tolerant parse as the main loader
                    x = x.strip()
                    if x.startswith("{"):
                        try:
                            recs.append(json.loads(x))
                        except ValueError:
                            pass
            flash = [r for r in recs if r.get("attn") == "flash"]
            if flash:
                lm, src = flash[-1].get("mfu"), os.path.basename(side)
    print("transformer_lm_mfu: %s (target >=0.30; attn=%s; src=%s): %s"
          % (lm, bench.get("transformer_lm_attn") or "flash", src,
             "MISSING" if lm is None
             else ("PASS" if lm >= 0.30 else "below")))
    orc = got("splash_oracle").get("result") or {}
    ours, theirs = best.get("tflops"), orc.get("value")
    if ours and theirs:
        print("flash vs splash ceiling: %.1f / %.1f TFLOP/s (%.0f%%)"
              % (ours, theirs, 100.0 * ours / theirs))
    else:
        print("flash vs splash ceiling: MISSING (ours=%s oracle=%s)"
              % (ours, theirs))


if __name__ == "__main__":
    main()

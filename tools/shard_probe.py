"""Sharding probe — make a GSPMD layout inspectable before burning a run.

Builds a bench model, binds it on a named mesh under partition rules, and
reports:

  * the resolved rule table (which regex claimed each parameter);
  * per-parameter sharding + the per-device HBM estimate vs replicated;
  * the post-SPMD HLO collective mix of the fused train step
    (all-reduce / all-gather / reduce-scatter / collective-permute) — the
    compiled truth of what the layout costs in comms.

The last stdout line is a single JSON record (bench.py smoke phase parses
it).  CPU-friendly: run with JAX_PLATFORMS=cpu and
XLA_FLAGS=--xla_force_host_platform_device_count=8 for a simulated mesh.

Usage:
  python tools/shard_probe.py --model transformer --mesh data=-1,model=2 \
      --rules transformer_megatron [--steps 2] [--smoke]
"""
import argparse
import json
import os
import re
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

COLLECTIVES = ("all-reduce", "all-gather", "reduce-scatter",
               "collective-permute")


def build_mlp(batch):
    import mxnet_tpu as mx

    data = mx.sym.Variable("data")
    h = mx.sym.FullyConnected(data, num_hidden=256, name="fc1")
    h = mx.sym.Activation(h, act_type="relu")
    h = mx.sym.FullyConnected(h, num_hidden=10, name="fc2")
    net = mx.sym.SoftmaxOutput(h, name="softmax")
    return net, [("data", (batch, 128))], [("softmax_label", (batch,))]


def build_transformer(batch, seq_len=64, hidden=128, layers=2, heads=4,
                      vocab=512):
    from mxnet_tpu.models.transformer import get_transformer_lm

    net = get_transformer_lm(vocab_size=vocab, num_layers=layers,
                             num_heads=heads, hidden=hidden, seq_len=seq_len,
                             block_q=seq_len, block_k=seq_len)
    return net, [("data", (batch, seq_len))], \
        [("softmax_label", (batch, seq_len))]


def synthetic_batch(mx, data_shapes, label_shapes, vocab=512):
    import numpy as np

    rng = np.random.RandomState(7)
    data = []
    for _, shape in data_shapes:
        data.append(mx.nd.array(
            rng.randint(0, vocab, size=shape).astype(np.float32)))
    label = [mx.nd.array(rng.randint(0, 10, size=s).astype(np.float32))
             for _, s in label_shapes]
    return mx.io.DataBatch(data=data, label=label)


def collective_counts(hlo_text):
    counts = {}
    for op in COLLECTIVES:
        # opcode use sites: "<shape> all-reduce(" (start/done variants of
        # async collectives count toward their base opcode)
        n = len(re.findall(r"\b%s(?:-start)?\(" % re.escape(op), hlo_text))
        if n:
            counts[op] = n
    return counts


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--model", default="transformer",
                    choices=("mlp", "transformer"))
    ap.add_argument("--mesh", default="data=-1,model=2",
                    help="mesh layout, e.g. data=-1,model=2")
    ap.add_argument("--rules", default=None,
                    help="preset name (default: transformer_megatron for "
                         "--model transformer, replicated otherwise)")
    ap.add_argument("--batch-size", type=int, default=8)
    ap.add_argument("--steps", type=int, default=2)
    ap.add_argument("--smoke", action="store_true",
                    help="minimal run for CI: tiny model, 1 step")
    args = ap.parse_args()

    import mxnet_tpu as mx
    from mxnet_tpu import sharding

    if args.rules is None:
        args.rules = ("transformer_megatron" if args.model == "transformer"
                      else "replicated")
    if args.smoke:
        args.steps = 1

    mesh = sharding.build_mesh(args.mesh)
    rules = sharding.as_rules(args.rules)
    if args.model == "mlp":
        net, data_shapes, label_shapes = build_mlp(args.batch_size)
    else:
        net, data_shapes, label_shapes = build_transformer(args.batch_size)

    mod = mx.mod.Module(net, context=mx.current_context())
    mod.bind(data_shapes=data_shapes, label_shapes=label_shapes,
             mesh=mesh, partition_rules=rules)
    mod.init_params(initializer=mx.init.Xavier(magnitude=2.0))
    mod.init_optimizer(kvstore="local", optimizer="sgd",
                       optimizer_params={"learning_rate": 0.01,
                                         "momentum": 0.9})

    group = mod._exec_group
    executor = group.execs[0]
    shapes = {n: tuple(executor.arg_dict[n].shape) for n in group.param_names}
    shapes.update({n: tuple(executor.aux_dict[n].shape)
                   for n in group.aux_names})
    print("== mesh ==")
    print(sharding.mesh_axes(mesh))
    print("\n== rule table ==")
    print(rules.explain_str(shapes))

    print("\n== per-parameter sharding ==")
    params = {n: executor.arg_dict[n] for n in group.param_names}
    params.update({n: executor.aux_dict[n] for n in group.aux_names})
    for name, arr in sorted(params.items()):
        factor = sharding.spec_shard_factor(
            mesh, group._param_specs.get(name)) \
            if group._param_specs.get(name) is not None else 1
        print("%-28s %-16s %d-way  %s" % (
            name, tuple(arr.shape), factor,
            tuple(group._param_specs.get(name, ()))))
    per_dev, repl = sharding.param_bytes(params.values())
    print("\nper-device param bytes: %d (replicated would be %d, %.2fx)"
          % (per_dev, repl, repl / max(per_dev, 1)))

    batch = synthetic_batch(mx, data_shapes, label_shapes)
    t0 = time.perf_counter()
    for _ in range(args.steps):
        mod.forward_backward(batch)
        mod.update()
    for o in mod.get_outputs():
        o.wait_to_read()
    step_ms = (time.perf_counter() - t0) / max(args.steps, 1) * 1e3

    collectives = {}
    fn, abstract = getattr(executor, "_fused_introspect", (None, None))
    if fn is not None and hasattr(fn, "lower"):
        hlo = fn.lower(*abstract).compile().as_text()
        collectives = collective_counts(hlo)
        print("\n== post-SPMD fused-step collectives ==")
        print(collectives or "(none)")

    record = {
        "probe": "shard",
        "model": args.model,
        "mesh": sharding.mesh_axes(mesh),
        "rules": rules.name,
        "params_sharded_bytes": per_dev,
        "params_replicated_bytes": repl,
        "collectives": collectives,
        "avg_step_ms": round(step_ms, 2),
        "steps": args.steps,
    }
    print(json.dumps(record))
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""Artifact routing for the perf tools.

The probes print their records to stdout (that contract stays — bench.py
and humans parse it), but the on-disk copy that used to come from shell
redirection into the repo root (``capture_r05.jsonl`` & friends) now
lands in the telemetry artifacts directory instead: set
``MXNET_TELEMETRY_DUMP_DIR`` to collect a run's artifacts in one place,
otherwise they go under the system tmpdir — never the CWD.
"""
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def artifact_path(name):
    """Absolute path for a named artifact in the telemetry dump dir."""
    from mxnet_tpu import telemetry

    d = telemetry.dump_dir()
    os.makedirs(d, exist_ok=True)
    return os.path.join(d, name)


def tee_line(name, record):
    """Print one JSON record line to stdout AND append it to the named
    artifact file.  The file write is best-effort: a read-only artifact
    dir must never kill a probe mid-run."""
    line = json.dumps(record)
    print(line, flush=True)
    try:
        with open(artifact_path(name), "a") as f:
            f.write(line + "\n")
    except OSError:
        pass
    return line


def write_json(name, record, indent=2):
    """Print a JSON document to stdout AND write it to the named
    artifact file (whole-document tools: perf_probe)."""
    doc = json.dumps(record, indent=indent)
    print(doc)
    try:
        with open(artifact_path(name), "w") as f:
            f.write(doc + "\n")
    except OSError:
        pass
    return doc
